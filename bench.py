#!/usr/bin/env python
"""Headline benchmarks: ResNet-50 inference AND training throughput, one chip.

Reference baselines (BASELINE.md / docs perf.md): ResNet-50 bs=128 fp32 on
1x V100 — inference 1233.15 img/s (perf.md:196, fp16 analogue 2355.04),
training 363.69 img/s (perf.md:254, methodology of
example/image-classification/train_imagenet.py --benchmark). Reproduced
here in bfloat16 (the MXU's native input type).

Prints TWO JSON lines {"metric", "value", "unit", "vs_baseline", ...}:
  1. resnet50_v1_infer_bs128_bfloat16  (hybridized compiled scoring)
  2. resnet50_v1_train_bs128_bfloat16  (ONE fused fwd+loss+bwd+SGD-momentum
     executable via parallel.ShardedTrainer, incl. BN stat writeback;
     extra fields: achieved_tflops + the nominal mfu vs the per-device-kind
     peak table in mxnet_tpu.telemetry.costs — TPU v3..v6e + a CPU
     placeholder, BENCH_PEAK_TFLOPS override — AND mfu_xla, the measured
     ratio whose numerator is the XLA cost_analysis() flops the compile
     service captured for the executable)
Every line also carries compile-service telemetry (mxnet_tpu.compile):
``compile_ms`` (time spent compiling this process), ``cache_hits`` /
``cache_misses`` and ``cache_disk_hits`` — with ``MXNET_TPU_CACHE_DIR``
set, a warm start shows ``compile_ms`` collapsing toward the disk-load
time while ``cache_disk_hits`` absorbs the misses (the cold-vs-warm
comparison the subprocess test in tests/test_compile.py asserts).

``--train`` adds a third line: a small-model CPU training step-time
metric (``*_train_cpu`` in ms/step), so BENCH_r06+ records a training
number even when the TPU tunnel is down.

A serving line is emitted BY DEFAULT (disable with BENCH_SKIP_SERVE=1,
or run just it with ``--serve-only``): sustained requests/s + p50/p99
latency + batch fill ratio from a ``tools/loadgen.py`` closed loop
against an in-process 2-model ``mxnet_tpu.serving`` container
(BENCH_SERVE_SECONDS, default 30), so the serving trajectory is tracked
in BENCH_r06+ alongside img/s. A ``serving_rps_int8_*`` companion line
follows it (same harness in ``--dtype both`` pair mode,
BENCH_SERVE_INT8_SECONDS, default 16): the embedding-lookup fixture
served fp32 AND entropy-calibrated int8 from one warm ladder, recording
the matched-p99 int8-vs-float rps ratio every round (ROADMAP item 4).
A ``serving_fleet_rps_*`` line follows (``loadgen --workers`` through
the ServingFleet router at workers=1 and workers=4;
BENCH_FLEET_WORKERS/_SECONDS): the N-worker rps with ``rps_1worker``
and ``scaling_efficiency`` = rpsN/(N·rps1) — the multi-process scaling
trajectory. A ``serving_fleet_hedged_*`` line follows: a 2-host fleet
with one injected straggler host measured hedging-off vs hedging-on
(value = the p99 cut ratio), plus the prediction-cache hit-path vs
compute-path p50 split (``cache_speedup``);
BENCH_FLEET_HEDGE_SECONDS/_DELAY_S size the drill.
BENCH_SKIP_SERVE=1 skips all four.

Env knobs: BENCH_BATCH (default 128), BENCH_DTYPE (bfloat16|float32),
BENCH_ITERS, BENCH_MODEL, BENCH_SKIP_TRAIN, BENCH_PEAK_TFLOPS (default:
auto-detected from the chip generation — v5e 197, v5p 459, v4 275, ...;
an on-chip measured peak is also reported as measured_peak_tflops);
BENCH_TRAIN_CPU_BATCH/_ITERS size the --train smoke.

Per-family ``kernel_vs_xla_<family>`` lines are emitted BY DEFAULT
(disable with BENCH_SKIP_KERNELS=1, run just them with
``--kernels-only``): the kernel-layer autotuner (opperf --kernels)
timing each Pallas kernel family against its XLA baseline and
refreshing the persisted dispatch table. Off-TPU lines carry
``interpret: true`` — interpreter numerics-health lines, not chip perf.
BENCH_KERNEL_RUNS sizes the timing loop.
"""
import json
import os
import time

import numpy as np

# forward GFLOP/img @224x224 per model (public model FLOP counts)
_FWD_GFLOPS = {"resnet50_v1": 4.09, "resnet50_v2": 4.09,
               "resnet18_v1": 1.82, "resnet101_v1": 7.8,
               "resnet152_v1": 11.5, "vgg16": 15.5, "alexnet": 0.71}


def _compile_fields(line):
    """Fold the compile-service totals into one emitted JSON line: how
    much of this process went to compiling vs cache hits (disk hits =
    the persistent-cache warm-start win)."""
    from mxnet_tpu import compile as _compile

    t = _compile.totals()
    line["compile_ms"] = t["compile_ms"]
    line["cache_hits"] = t["hits"]
    line["cache_misses"] = t["misses"]
    line["cache_disk_hits"] = t["disk_hits"]
    return line


def _mfu_xla_fields(line, site, calls_per_sec, devices=1):
    """Measured-flops MFU: the compile service captured XLA
    ``cost_analysis()`` for `site`'s newest executable
    (mxnet_tpu.telemetry.costs); divided by the per-device-kind peak
    table this is ``mfu_xla`` — the ratio whose numerator is what XLA
    actually scheduled, emitted ALONGSIDE the nominal ``mfu`` so
    BENCH_r06+ records both."""
    from mxnet_tpu.telemetry import costs as _tcosts

    rec = _tcosts.latest(site)
    flops = (rec or {}).get("flops")
    if not flops:
        return line
    line["xla_flops_per_call"] = flops
    mfu = _tcosts.mfu_xla(flops, calls_per_sec, devices=devices,
                          peak=_peak_tflops())
    if mfu is not None:
        line["mfu_xla"] = round(mfu, 5)
    return line


def _gradcomms_fields(line, steps=None):
    """Fold the gradient-comms trajectory into a train line:
    ``sync_ms_mean`` (the step timeline's sync phase over the timed
    steps — the serialized collective tail) and ``overlap_ratio`` (the
    bucket pipeline's 1 - blocked/in-flight; null single-host, where no
    cross-host reduction runs)."""
    from mxnet_tpu.kvstore import buckets as _kvbuckets
    from mxnet_tpu.telemetry import steps as _tsteps

    hist = _tsteps.history(steps)
    syncs = [r["phases"].get("sync", 0.0) for r in hist]
    line["sync_ms_mean"] = round(sum(syncs) / len(syncs), 3) \
        if syncs else None
    line["overlap_ratio"] = _kvbuckets.comm_stats()["overlap_ratio"]
    return line


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(prog="bench",
                                 description="headline benchmarks")
    ap.add_argument("--train", action="store_true",
                    help="also emit the small-model CPU training "
                         "step-time metric (runs on any host)")
    ap.add_argument("--train-only", action="store_true",
                    help="emit ONLY the CPU training metric (skip the "
                         "ResNet benches)")
    ap.add_argument("--serve", action="store_true",
                    help="also emit the serving throughput metric "
                         "(tools/loadgen.py closed loop against a "
                         "2-model container; runs on any host)")
    ap.add_argument("--serve-only", action="store_true",
                    help="emit ONLY the serving metric")
    ap.add_argument("--dataplane-only", action="store_true",
                    help="emit ONLY the host data-plane metric")
    ap.add_argument("--kernels-only", action="store_true",
                    help="emit ONLY the per-family kernel-vs-XLA lines")
    args = ap.parse_args(argv)

    if args.kernels_only:
        bench_kernels()
        return

    if args.serve_only:
        bench_serve()
        bench_serve_int8()
        bench_serve_fleet()
        bench_serve_fleet_hedged()
        return
    if args.dataplane_only:
        bench_dataplane()
        return

    import mxnet_tpu as mx
    from mxnet_tpu.base import probe_backend_or_fallback
    from mxnet_tpu.gluon.model_zoo import vision

    if args.train_only:
        bench_train_cpu()
        return

    # a downed TPU tunnel hangs the first backend touch forever; probe
    # (subprocess, 90s deadline) unless the platform is already pinned.
    # reprobe=True additionally re-tests a CPU pin that an EARLIER run's
    # timeout latched (MXTPU_PLATFORM_FALLBACK marks it), so the first
    # run with the tunnel back up records a real TPU line with no env
    # surgery. BENCH_SKIP_PROBE=1 skips the probe's backend spin-up.
    probe_backend_or_fallback(skip_env="BENCH_SKIP_PROBE", reprobe=True)

    batch = int(os.environ.get("BENCH_BATCH", 128))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    iters = int(os.environ.get("BENCH_ITERS", 20))
    model = os.environ.get("BENCH_MODEL", "resnet50_v1")
    baseline = 1233.15  # ResNet-50 bs=128 fp32 on V100 (perf.md:196)

    ctx = mx.tpu() if mx.num_tpus() > 0 else mx.cpu()
    skip_train = bool(os.environ.get("BENCH_SKIP_TRAIN"))
    if ctx.device_type == "cpu":
        # Fallback/CPU host: a full-size run burns the driver's whole
        # budget producing a number nobody scores. Shrink to a smoke size
        # (still a real compiled forward) and skip the training bench.
        import sys

        batch, iters = min(batch, 8), min(iters, 3)
        skip_train = True
        print(f"cpu platform: smoke size batch={batch} iters={iters}, "
              "train bench skipped", file=sys.stderr, flush=True)
    net = vision.get_model(model, classes=1000)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if dtype != "float32":
        net.cast(dtype)
    net.hybridize(static_alloc=True, static_shape=True)

    x = mx.nd.random.uniform(shape=(batch, 3, 224, 224), ctx=ctx)
    if dtype != "float32":
        x = x.astype(dtype)

    # warmup: trigger deferred init (eager) + compile (first hybrid call)
    net(x).wait_to_read()
    net(x).wait_to_read()

    start = time.perf_counter()
    outs = []
    for _ in range(iters):
        outs.append(net(x))
    outs[-1].wait_to_read()
    elapsed = time.perf_counter() - start
    throughput = batch * iters / elapsed

    line = {
        "metric": f"{model}_infer_bs{batch}_{dtype}",
        "value": round(throughput, 2),
        "unit": "img/s",
        "vs_baseline": round(throughput / baseline, 3),
        # fallback runs must not masquerade as chip numbers in the
        # metric series
        "platform": ctx.device_type,
    }
    fwd_flops = _FWD_GFLOPS.get(model, 0.0) * 1e9
    if fwd_flops:
        # nominal mfu now lands on CPU fallback lines too (the table has
        # an explicit placeholder 'cpu' peak); the platform field keeps
        # fallback ratios out of the chip series
        achieved = throughput * fwd_flops / 1e12
        line["achieved_tflops"] = round(achieved, 1)
        line["mfu"] = round(achieved / _peak_tflops(), 3)
    # hybridized scoring compiles through the 'cachedop' service site
    _mfu_xla_fields(line, "cachedop", iters / elapsed)
    print(json.dumps(_compile_fields(line)), flush=True)

    if not skip_train:
        # training compiles a bigger program; cap its timed loop so the
        # whole bench stays inside the driver's window
        train_iters = int(os.environ.get("BENCH_TRAIN_ITERS",
                                         min(iters, 10)))
        bench_train(ctx, batch, dtype, train_iters, model)
    if args.train:
        bench_train_cpu()
    # the serving line is part of the default metric series (the ROADMAP
    # item-1 trajectory); BENCH_SKIP_SERVE=1 opts out of both it and the
    # int8-vs-float companion line (the ROADMAP item-4 ratio)
    if args.serve or not os.environ.get("BENCH_SKIP_SERVE"):
        bench_serve()
        bench_serve_int8()
        # the fleet line: 1-worker vs N-worker rps through the router
        # (serving_fleet_rps_*, scaling_efficiency) — the PR 15
        # near-linear-scaling trajectory
        bench_serve_fleet()
        # the tail-tolerance line: hedging-on vs hedging-off p99 under
        # an injected straggler + the prediction-cache latency split
        bench_serve_fleet_hedged()
    # the host data-plane line tracks the streaming input pipeline
    # (native fused decode+augment img/s + trainer data_wait);
    # BENCH_SKIP_DATAPLANE=1 opts out
    if not os.environ.get("BENCH_SKIP_DATAPLANE"):
        bench_dataplane()
    # per-family Pallas-kernel-vs-XLA speedup lines (the kernel-layer
    # trajectory); BENCH_SKIP_KERNELS=1 opts out
    if not os.environ.get("BENCH_SKIP_KERNELS"):
        bench_kernels()


def bench_train(ctx, batch, dtype, iters, model):
    """Training throughput: fused fwd+loss+bwd+SGD step (one executable)."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    baseline = 363.69  # ResNet-50 bs=128 fp32 training on V100 (perf.md:254)
    flops_per_img = 3 * _FWD_GFLOPS.get(model, 0.0) * 1e9  # train ~= 3x fwd
    peak_tflops = _peak_tflops()

    mx.random.seed(0)
    net = vision.get_model(model, classes=1000)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if dtype != "float32":
        net.cast(dtype)
    x = mx.nd.random.uniform(shape=(batch, 3, 224, 224), ctx=ctx)
    if dtype != "float32":
        x = x.astype(dtype)
    y = mx.nd.array(np.random.randint(0, 1000, batch).astype(np.float32),
                    ctx=ctx)
    net(x)  # materialize deferred shapes
    trainer = ShardedTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4},
        mesh=DeviceMesh({"dp": 1}),
        # benchmark measures async dispatch throughput; the NaN guard's
        # per-step skip-flag read would serialize host and device
        nan_guard=False)
    trainer.step(x, y).wait_to_read()  # compile
    trainer.step(x, y).wait_to_read()  # warm
    start = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(x, y)
    loss.wait_to_read()
    elapsed = time.perf_counter() - start
    throughput = batch * iters / elapsed
    line = {
        "metric": f"{model}_train_bs{batch}_{dtype}",
        "value": round(throughput, 2),
        "unit": "img/s",
        "vs_baseline": round(throughput / baseline, 3),
        "platform": ctx.device_type,
    }
    if flops_per_img:  # only for models with a known FLOP count
        achieved = throughput * flops_per_img / 1e12
        line["achieved_tflops"] = round(achieved, 1)
        line["mfu"] = round(achieved / peak_tflops, 3)
        measured = _measure_chip_peak()
        if measured:
            line["measured_peak_tflops"] = round(measured, 1)
            line["mfu_vs_measured"] = round(achieved / measured, 3)
    _mfu_xla_fields(line, "trainer", iters * 1.0 / elapsed,
                    devices=trainer.mesh.num_devices)
    _gradcomms_fields(line, steps=iters)
    print(json.dumps(_compile_fields(line)), flush=True)


def bench_train_cpu():
    """CPU training step-time smoke: a small conv net through the SAME
    fused ShardedTrainer step as the chip bench, sized to finish in
    seconds — the training number BENCH_r06+ records when the TPU tunnel
    is down. Emits ms/step (lower is better) plus img/s and the compile
    telemetry; with MXNET_TPU_CACHE_DIR set, warm reruns show the
    persistent cache collapsing compile_ms."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    batch = int(os.environ.get("BENCH_TRAIN_CPU_BATCH", 32))
    iters = int(os.environ.get("BENCH_TRAIN_CPU_ITERS", 20))
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.GlobalAvgPool2D(),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = mx.nd.random.uniform(shape=(batch, 3, 32, 32))
    y = mx.nd.array(np.random.RandomState(0).randint(
        0, 10, batch).astype(np.float32))
    net(x)  # materialize deferred shapes
    trainer = ShardedTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9},
        mesh=DeviceMesh({"dp": 1}), nan_guard=False)
    t0 = time.perf_counter()
    trainer.step(x, y).wait_to_read()  # compile
    compile_s = time.perf_counter() - t0
    trainer.step(x, y).wait_to_read()  # warm
    start = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(x, y)
    loss.wait_to_read()
    elapsed = time.perf_counter() - start
    line = {
        "metric": f"smallconv_train_bs{batch}_float32_cpu",
        "value": round(elapsed / iters * 1e3, 3),
        "unit": "ms/step",
        "img_per_s": round(batch * iters / elapsed, 2),
        "first_step_s": round(compile_s, 3),
        "platform": "cpu",
    }
    _mfu_xla_fields(line, "trainer", iters / elapsed)
    _gradcomms_fields(line, steps=iters)
    # optimizer-phase split from the step telemetry: the fused step runs
    # fwd+bwd+optimizer (incl. the kernel-layer opt_sgd/opt_adam dispatch)
    # as ONE executable, so a healthy line shows the optimizer phase
    # collapsed to ~0 with its cost folded into compute — a regression
    # that re-splits the step shows up here as a nonzero optimizer_ms
    rep = trainer.step_report()
    if rep and rep.get("phases"):
        line["optimizer_ms"] = round(rep["phases"].get("optimizer", 0.0), 3)
        line["compute_ms"] = round(rep["phases"].get("compute", 0.0), 3)
    print(json.dumps(_compile_fields(line)), flush=True)


def bench_serve():
    """Serving throughput: tools/loadgen.py closed loop against an
    in-process 2-model container (mxnet_tpu.serving) — sustained
    requests/s with bounded tail latency, the ROADMAP item-1 acceptance
    number. Pre-traffic warmup compiles every bucket, so
    ``recompiles_during_run`` must be 0 (the compile service served only
    cache hits while the clock ran). Env knobs: BENCH_SERVE_SECONDS
    (default 30), BENCH_SERVE_CONCURRENCY (16), BENCH_SERVE_MODELS (2)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import loadgen

    duration = float(os.environ.get("BENCH_SERVE_SECONDS", 30))
    concurrency = int(os.environ.get("BENCH_SERVE_CONCURRENCY", 16))
    models = int(os.environ.get("BENCH_SERVE_MODELS", 2))
    rep = loadgen.run_inproc(duration=duration, mode="closed",
                             concurrency=concurrency, models=models)
    import jax

    line = {
        "metric": f"serving_rps_{models}model_closed{concurrency}",
        "value": rep["rps"],
        "unit": "req/s",
        "duration_s": rep["duration_s"],
        "p50_ms": rep.get("p50_ms"),
        "p99_ms": rep.get("p99_ms"),
        "batch_fill_ratio": rep.get("batch_fill_ratio"),
        "rejected": rep.get("rejected"),
        "recompiles_during_run": rep.get("recompiles_during_run"),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(_compile_fields(line)), flush=True)


def bench_serve_fleet():
    """Serving-fleet throughput: ``tools/loadgen.py --workers N``
    (closed loop through the router against N ModelServer worker
    processes) at workers=1 and workers=N, emitting ONE line whose
    value is the N-worker rps with ``rps_1worker`` and
    ``scaling_efficiency`` = rpsN / (N * rps1) alongside — the
    near-linear 1→N scaling trajectory BENCH_r06+ tracks. The measured
    number is recorded either way; on a < N-core host the efficiency is
    honest about the floor it ran on (``cores`` rides in the line).
    Env knobs: BENCH_FLEET_WORKERS (default 4), BENCH_FLEET_SECONDS
    (default 10 per census), BENCH_SERVE_CONCURRENCY (16)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import loadgen

    import jax

    workers = int(os.environ.get("BENCH_FLEET_WORKERS", 4))
    duration = float(os.environ.get("BENCH_FLEET_SECONDS", 10))
    concurrency = int(os.environ.get("BENCH_SERVE_CONCURRENCY", 16))
    rep1 = loadgen.run_fleet(workers=1, duration=duration,
                             concurrency=concurrency)
    repn = loadgen.run_fleet(workers=workers, duration=duration,
                             concurrency=concurrency)
    rps1, rpsn = rep1.get("rps") or 0.0, repn.get("rps") or 0.0
    line = {
        "metric": f"serving_fleet_rps_{workers}worker_closed{concurrency}",
        "value": rpsn,
        "unit": "req/s",
        "workers": workers,
        "rps_1worker": rps1,
        "scaling_efficiency": round(rpsn / (workers * rps1), 3)
        if rps1 else None,
        "duration_s": repn.get("duration_s"),
        "p50_ms": repn.get("p50_ms"),
        "p99_ms": repn.get("p99_ms"),
        "router_retries": repn.get("router", {}).get("retries"),
        "rejected": repn.get("rejected"),
        "reconnects": repn.get("reconnects"),
        "connect_ms_mean": repn.get("connect_ms_mean"),
        "cores": os.cpu_count(),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(_compile_fields(line)), flush=True)


def bench_serve_fleet_hedged():
    """Tail-tolerance line: a 2-host fleet (two localhost pseudo-hosts)
    with an injected straggler — one host's workers stall every batch
    via the ``serving.batch`` fault point — driven closed-loop twice,
    hedging OFF then ON (same topology, fresh fleet each). The metric
    value is the p99 cut (p99_unhedged / p99_hedged): the router's
    straggler flags + canary probes + hedged requests should cut the
    injected tail by >=3x. The line also carries the prediction-cache
    split — hit-path vs compute-path p50 from the same loadgen harness
    (hot_key_frac 1.0 vs 0.0) — the "cache in front of the batcher"
    latency ratio. Env knobs: BENCH_FLEET_HEDGE_SECONDS (default 6 per
    side), BENCH_FLEET_HEDGE_DELAY_S (0.25), BENCH_SERVE_CONCURRENCY
    (16). BENCH_SKIP_SERVE=1 opts out with the other serving lines."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import loadgen

    import jax

    duration = float(os.environ.get("BENCH_FLEET_HEDGE_SECONDS", 6))
    concurrency = int(os.environ.get("BENCH_SERVE_CONCURRENCY", 16))
    delay_s = float(os.environ.get("BENCH_FLEET_HEDGE_DELAY_S", 0.25))
    hosts = ["local",
             {"name": "slow", "locality": "local",
              "env": {"MXNET_TPU_FAULTS":
                      f"serving.batch:delay@*:{delay_s}"}}]
    cfg = {"interval": 0.3, "hedge_min_ms": 20.0}
    rep_off = loadgen.run_fleet(workers=2, duration=duration,
                                concurrency=concurrency,
                                hosts=list(hosts),
                                config=dict(cfg, hedge=0))
    rep_on = loadgen.run_fleet(workers=2, duration=duration,
                               concurrency=concurrency,
                               hosts=list(hosts),
                               config=dict(cfg, hedge=1))
    # the cache split: hit-path p50 (every request re-sends ONE hot
    # key) vs compute-path p50 (cache off), same in-process harness
    cache_s = max(2.0, duration / 3)
    rep_cold = loadgen.run_inproc(duration=cache_s, concurrency=4,
                                  models=1)
    rep_hot = loadgen.run_inproc(duration=cache_s, concurrency=4,
                                 models=1, hot_key_frac=1.0)
    p99_on, p99_off = rep_on.get("p99_ms"), rep_off.get("p99_ms")
    hit_p50 = rep_hot.get("p50_ms")
    compute_p50 = rep_cold.get("p50_ms")
    line = {
        "metric":
            f"serving_fleet_hedged_2worker_closed{concurrency}",
        "value": round(p99_off / p99_on, 3)
        if p99_on and p99_off else None,
        "unit": "x_p99_cut",
        "p99_hedged_ms": p99_on,
        "p99_unhedged_ms": p99_off,
        "p50_hedged_ms": rep_on.get("p50_ms"),
        "hedges": rep_on.get("hedges"),
        "stragglers": rep_on.get("stragglers"),
        "errors": (rep_on.get("errors") or 0)
        + (rep_off.get("errors") or 0),
        "straggler_delay_s": delay_s,
        "cache_hit_p50_ms": hit_p50,
        "compute_p50_ms": compute_p50,
        "cache_speedup": round(compute_p50 / hit_p50, 2)
        if hit_p50 and compute_p50 else None,
        "cache_hit_ratio": rep_hot.get("cache_hit_ratio"),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(_compile_fields(line)), flush=True)


def bench_serve_int8():
    """Int8 serving throughput vs float, same loadgen harness: the
    embedding-lookup fixture pair (``tools/loadgen.py --dtype both``)
    driven closed-loop per variant from ONE warm server — the ROADMAP
    item-4 acceptance number. Emits the int8 rps as the metric value
    with the matched-p99 int8-vs-float ratio alongside, so BENCH_r06+
    records the ratio every round. ``recompiles_during_run`` must be 0
    (both ladders compiled/disk-loaded at warmup). Env knobs:
    BENCH_SERVE_INT8_SECONDS (default 16), BENCH_SERVE_CONCURRENCY
    (16), BENCH_PAIR_VOCAB/_EMBED_DIM/_SEQ_LEN size the fixture."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import loadgen

    import jax

    duration = float(os.environ.get("BENCH_SERVE_INT8_SECONDS", 16))
    concurrency = int(os.environ.get("BENCH_SERVE_CONCURRENCY", 16))
    rep = loadgen.run_pair(
        duration=duration, concurrency=concurrency,
        vocab=int(os.environ.get("BENCH_PAIR_VOCAB", 50_000)),
        embed_dim=int(os.environ.get("BENCH_PAIR_EMBED_DIM", 512)),
        seq_len=int(os.environ.get("BENCH_PAIR_SEQ_LEN", 1024)))
    line = {
        "metric": f"serving_rps_int8_emblookup_closed{concurrency}",
        "value": rep.get("rps_int8"),
        "unit": "req/s",
        "rps_float32": rep.get("rps_float32"),
        "ratio_int8_vs_float": rep.get("rps_ratio_int8_vs_float"),
        "p99_int8_ms": rep.get("p99_int8_ms"),
        "p99_float32_ms": rep.get("p99_float32_ms"),
        "matched_p99": rep.get("matched_p99"),
        "calib_mode": rep.get("calib_mode"),
        "bucket_census_int8": rep.get("bucket_census_int8"),
        "recompiles_during_run": rep.get("recompiles_during_run"),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(_compile_fields(line)), flush=True)


def bench_dataplane():
    """Host data-plane metric (the streaming input pipeline of the
    native OMP decode+augment loop): img/s and img/s/core of the fused
    native path vs the bit-compatible Python fallback, per-thread
    scaling — AND the starvation check: a small conv net trained
    through PrefetchingIter(ImageRecordIter) at a batch size that
    starves a record-at-a-time pipeline, reporting the mean/max
    ``data_wait`` step phase (PR 9 gauge; ~0 = the host kept up).
    Env knobs: BENCH_DATAPLANE_IMAGES (192), BENCH_DATAPLANE_STEPS (12),
    BENCH_SKIP_DATAPLANE opts out of the default emission."""
    import sys
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmark"))
    import iter_bench

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.io import ImageRecordIter, PrefetchingIter
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer
    from mxnet_tpu.telemetry import steps as _tsteps

    n_img = int(os.environ.get("BENCH_DATAPLANE_IMAGES", 192))
    threads = os.cpu_count() or 1
    aug = iter_bench.run_augment(num_images=n_img, src_size=96,
                                 batch_size=32, data_shape=(3, 64, 64),
                                 epochs=2, threads=threads)

    # starvation check: feed a compiled train step from the pipeline and
    # read back the per-step data_wait phase the prefetcher recorded
    steps_n = int(os.environ.get("BENCH_DATAPLANE_STEPS", 12))
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.GlobalAvgPool2D(),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, 3, 64, 64)))
    trainer = ShardedTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9},
        mesh=DeviceMesh({"dp": 1}), nan_guard=False)
    with tempfile.TemporaryDirectory() as d:
        rec = iter_bench.build_rec(os.path.join(d, "dp"), n_img, 96)
        it = PrefetchingIter(ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 64, 64), batch_size=32,
            shuffle=True, rand_crop=True, rand_mirror=True,
            color_jitter=0.2, seed=0, preprocess_threads=threads,
            num_parts=1, part_index=0))
        warmup = 2  # first steps pay compile + pipeline spin-up
        hist_before = None
        done = 0
        while done < steps_n + warmup:
            try:
                batch = it.next()
            except StopIteration:
                it.reset()
                continue
            trainer.step(batch.data[0],
                         batch.label[0]).wait_to_read()
            done += 1
            if done == warmup:
                hist_before = len(_tsteps.history())
        waits = [r["phases"].get("data_wait", 0.0)
                 for r in _tsteps.history()[hist_before:]]
    line = {
        "metric": "dataplane_native_augment",
        "value": aug["value"],
        "unit": "img/s",
        "img_s_per_core": aug["img_s_per_core"],
        "python_img_s": aug["python_img_s"],
        "speedup_vs_python": aug["speedup_vs_python"],
        "thread_scaling": aug["thread_scaling"],
        "scaling_1_to_4": aug["scaling_1_to_4"],
        "native_augment": aug["native_augment"],
        "threads": aug["threads"],
        "cores": aug["cores"],
        # the starvation check: mean/max data_wait per step (ms). ~0 =
        # the prefetched native pipeline kept the step fed
        "train_steps": len(waits),
        "train_data_wait_ms_mean":
            round(sum(waits) / len(waits), 3) if waits else None,
        "train_data_wait_ms_max":
            round(max(waits), 3) if waits else None,
    }
    iter_bench._persist(line)
    print(json.dumps(_compile_fields(line)), flush=True)


def bench_kernels():
    """Per-family kernel-vs-XLA speedup lines from the kernel-layer
    autotuner (benchmark/opperf.py bench_kernels): one
    ``kernel_vs_xla_<family>`` JSON line per registry family, recording
    the measured speedup, the winner the dispatch table now routes to,
    and the shape bucket that was timed. Off-TPU the kernel side runs
    in the Pallas INTERPRETER — those lines carry ``interpret: true``
    and a deliberately honest (usually <1x) speedup: they track kernel
    NUMERICS health on CPU hosts, not performance; only
    ``interpret: false`` lines belong in the chip perf series. The run
    also refreshes the persisted dispatch table, so the bench doubles
    as the autotune pass. BENCH_SKIP_KERNELS=1 opts out."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmark"))
    import opperf

    runs = int(os.environ.get("BENCH_KERNEL_RUNS", 5))
    res = opperf.bench_kernels(runs=runs, warmup=2)
    platform = "tpu" if any(not r.get("interpret")
                            for r in res["results"]) else "cpu"
    for r in res["results"]:
        k_ms, x_ms = r.get("kernel_ms"), r.get("xla_ms")
        line = {
            "metric": f"kernel_vs_xla_{r['family']}",
            "value": round(x_ms / k_ms, 3) if k_ms and x_ms else None,
            "unit": "x_speedup",
            "winner": r["winner"],
            "kernel_ms": k_ms,
            "xla_ms": x_ms,
            "bucket": r["bucket"],
            # interpret=true means the Pallas interpreter, NOT a chip
            # kernel — never compare these values against TPU lines
            "interpret": bool(r.get("interpret")),
            "platform": platform,
        }
        if r.get("error"):
            line["error"] = r["error"]
        print(json.dumps(line), flush=True)


def _peak_tflops():
    """The per-device-kind peak table (TPU v3..v6e + CPU placeholder)
    lives in mxnet_tpu.telemetry.costs — BENCH_PEAK_TFLOPS override
    preserved, "0"/unset mean auto-detect from
    ``jax.devices()[0].device_kind``."""
    from mxnet_tpu.telemetry import costs as _tcosts

    return _tcosts.peak_tflops(env="BENCH_PEAK_TFLOPS")


def _measure_chip_peak(n=4096, chain=16):
    """Sustained bf16 matmul TFLOP/s on THIS chip (a tunnel-attached or
    shared chip can sit far below the nominal part spec, so nominal-peak
    MFU alone misleads). Chained inside one executable so dispatch and
    transfer amortize away."""
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax

    try:
        a = jnp.ones((n, n), jnp.bfloat16)

        @jax.jit
        def f(a):
            def body(x, _):
                return (x @ a) * (1.0 / n), None

            out, _ = lax.scan(body, a, None, length=chain)
            return out.sum()

        float(f(a))  # compile + warm
        t0 = time.perf_counter()
        float(f(a))
        t = time.perf_counter() - t0
        return chain * 2 * n ** 3 / t / 1e12
    except Exception:
        return None


if __name__ == "__main__":
    main()
