#!/usr/bin/env python
"""Headline benchmark: ResNet-50 batched inference throughput on one chip.

Reference baseline (BASELINE.md / docs perf.md:196): ResNet-50 bs=128 fp32
inference = 1233.15 img/s on 1x V100 (measured via
example/image-classification/benchmark_score.py). This reproduces that
benchmark's methodology — hybridized (compiled) scoring, batch 128, timed
over repeated batches after warmup — on the TPU chip, in bfloat16 (the MXU's
native input type; the fp16-on-V100 analogue is 2355.04 img/s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env knobs: BENCH_BATCH (default 128), BENCH_DTYPE (bfloat16|float32),
BENCH_ITERS, BENCH_MODEL.
"""
import json
import os
import time

import numpy as np


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    batch = int(os.environ.get("BENCH_BATCH", 128))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    iters = int(os.environ.get("BENCH_ITERS", 20))
    model = os.environ.get("BENCH_MODEL", "resnet50_v1")
    baseline = 1233.15  # ResNet-50 bs=128 fp32 on V100 (perf.md:196)

    ctx = mx.tpu() if mx.num_tpus() > 0 else mx.cpu()
    net = vision.get_model(model, classes=1000)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if dtype != "float32":
        net.cast(dtype)
    net.hybridize(static_alloc=True, static_shape=True)

    x = mx.nd.random.uniform(shape=(batch, 3, 224, 224), ctx=ctx)
    if dtype != "float32":
        x = x.astype(dtype)

    # warmup: trigger deferred init (eager) + compile (first hybrid call)
    net(x).wait_to_read()
    net(x).wait_to_read()

    start = time.perf_counter()
    outs = []
    for _ in range(iters):
        outs.append(net(x))
    outs[-1].wait_to_read()
    elapsed = time.perf_counter() - start
    throughput = batch * iters / elapsed

    print(json.dumps({
        "metric": f"{model}_infer_bs{batch}_{dtype}",
        "value": round(throughput, 2),
        "unit": "img/s",
        "vs_baseline": round(throughput / baseline, 3),
    }))


if __name__ == "__main__":
    main()
