/*
 * mxtpu C ABI — the language-binding surface of the TPU-native framework.
 *
 * Parity target: include/mxnet/c_api.h in the reference (MX* functions,
 * int status returns, thread-local error string via MXGetLastError). The
 * reference's C ABI fronts a C++ runtime; here it fronts the embedded
 * Python/JAX runtime (the compute path is XLA), so a handle is an owned
 * reference to a framework NDArray and every call is GIL-safe — callable
 * from any thread of a C/C++/Rust/Java host.
 *
 * Conventions (same as the reference):
 *   - every function returns 0 on success, -1 on failure
 *   - on failure, MXGetLastError() returns a thread-local message
 *   - hyper-parameters are passed as string key/value pairs; values are
 *     parsed as Python literals ("2", "(1, 2)", "float32")
 *
 * Link with -lmxtpu (built from mxnet_tpu/native/mxtpu_c_api.cc; the
 * library embeds the Python interpreter on first use — set PYTHONPATH so
 * `import mxnet_tpu` resolves, and optionally MXTPU_PLATFORM=cpu|tpu).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;

/* dtype codes (parity: mshadow type codes used across the reference ABI) */
#define MXTPU_DTYPE_FLOAT32 0
#define MXTPU_DTYPE_FLOAT64 1
#define MXTPU_DTYPE_FLOAT16 2
#define MXTPU_DTYPE_UINT8 3
#define MXTPU_DTYPE_INT32 4
#define MXTPU_DTYPE_INT8 5
#define MXTPU_DTYPE_INT64 6
#define MXTPU_DTYPE_BFLOAT16 7

/* runtime ------------------------------------------------------------- */
int MXGetVersion(int *out);
const char *MXGetLastError(void);
/* Drain pending work before host teardown. The embedded interpreter stays
 * alive for the process lifetime (finalizing the JAX runtime mid-process
 * is unsafe); parity: MXNotifyShutdown is likewise a sync/detach
 * notification in the reference, not a teardown. */
int MXNotifyShutdown(void);

/* ndarray ------------------------------------------------------------- */
int MXNDArrayCreate(const int64_t *shape, int ndim, int dtype,
                    NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
/* out_pdata points at thread-local storage valid until the next
 * MXNDArrayGetShape call on this thread */
int MXNDArrayGetShape(NDArrayHandle handle, int *out_ndim,
                      const int64_t **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);
int MXNDArraySize(NDArrayHandle handle, int64_t *out_size);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t nbytes);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t nbytes);
int MXNDArrayWaitAll(void);

/* operators ----------------------------------------------------------- */
/* names array is owned by the library; do not free */
int MXListAllOpNames(int *out_size, const char ***out_array);
/* Invoke a registered op. Outputs are returned in a malloc'd handle array
 * the caller releases with MXHandleArrayFree (each handle additionally
 * needs MXNDArrayFree). */
int MXImperativeInvoke(const char *op_name, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals);
int MXHandleArrayFree(NDArrayHandle *handles);

/* ndarray container IO (parity: MXNDArraySave/Load) ------------------- */
/* keys may be NULL (positional save). Load returns a NULL-terminated
 * malloc'd handle array (free with MXHandleArrayFree after freeing each
 * handle); names point at thread-local storage valid until the next
 * load on this thread. */
int MXNDArraySave(const char *fname, int num_args, NDArrayHandle *handles,
                  const char **keys);
int MXNDArrayLoad(const char *fname, int *out_size,
                  NDArrayHandle **out_handles, int *out_name_size,
                  const char ***out_names);
int MXRandomSeed(int seed);

/* symbol (graph) API (parity: MXSymbolCreateFromJSON & co.) ----------- */
typedef void *SymbolHandle;

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);
/* out_json points at thread-local storage valid until the next
 * string-returning symbol call on this thread */
int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json);
int MXSymbolFree(SymbolHandle handle);
int MXSymbolListArguments(SymbolHandle handle, int *out_size,
                          const char ***out_array);
int MXSymbolListOutputs(SymbolHandle handle, int *out_size,
                        const char ***out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle handle, int *out_size,
                                const char ***out_array);
/* reflected per-op parameter schema as JSON (the dmlc::Parameter arg
 * listing; parity role: MXSymbolGetAtomicSymbolInfo) */
int MXSymbolGetAtomicSymbolInfo(const char *op_name, const char **out_json);

/* predictor (standalone inference; parity: c_predict_api.h) ----------- */

typedef void *PredictorHandle;

/* input_shape_indptr has num_input_nodes+1 entries delimiting each
 * input's dims inside input_shape_data (the reference's CSR layout) */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 int num_input_nodes, const char **input_keys,
                 const int64_t *input_shape_indptr,
                 const int64_t *input_shape_data, PredictorHandle *out);
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const void *data, int64_t nbytes);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, int index, int *out_ndim,
                         const int64_t **out_pdata);
int MXPredGetOutput(PredictorHandle handle, int index, void *data,
                    int64_t nbytes);
int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
