"""Kernel layer (mxnet_tpu/kernels/): registry, dispatch, numerics.

Numeric contracts asserted here (each family's ``tolerance`` field):

* opt_sgd / opt_adam / int8_gemm / twobit_* are **bit-exact vs their XLA
  baseline under jit** — both sides compiled, XLA applies the same FMA
  contraction to both, so ``==`` holds elementwise. (Eager-vs-jit is NOT
  bit-exact — op-by-op eager dispatch skips contraction — so the eager
  comparisons below use a 1-ULP-scale allclose instead.)
* flash_attention / decode_attention reorder the softmax reduction
  (online/blocked), so they carry an rtol=2e-5 float32 contract.

Dispatch semantics: table winner routes, corrupt table loads empty and
falls back to untuned defaults, ``MXNET_TPU_KERNELS=0`` restores the
baseline numerics bit-exactly, Pallas-unavailable latches with one
warning, and bucket keys feed the distcheck pass-4 churn sweep.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kernels
from mxnet_tpu.kernels import table as ktable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAMILIES = ("decode_attention", "flash_attention", "int8_gemm",
            "opt_adam", "opt_sgd", "twobit_compress", "twobit_decompress")


@pytest.fixture
def kernel_cache_dir(tmp_path, monkeypatch):
    """Fresh disk cache for the dispatch table; memory-only afterwards."""
    from mxnet_tpu import compile as C

    d = str(tmp_path / "cache")
    monkeypatch.setenv("MXNET_TPU_CACHE_DIR", d)
    C.configure(cache_dir=d)
    ktable.invalidate()
    yield d
    C.configure(cache_dir=None)
    ktable.invalidate()


def _jit(fn):
    import jax

    return jax.jit(fn)


# ===================================================================== #
# registry census                                                       #
# ===================================================================== #

def test_registry_census():
    assert kernels.families() == sorted(FAMILIES)
    for fam in FAMILIES:
        e = kernels.entry(fam)
        assert callable(e.kernel) and callable(e.xla)
        assert callable(e.bucket) and callable(e.supports)
        assert e.tolerance, f"{fam}: numeric contract undocumented"
    # serving-decode families default to the kernel on TPU
    assert kernels.entry("flash_attention").default_tpu
    assert kernels.entry("decode_attention").default_tpu


# ===================================================================== #
# per-family interpret-mode numerics vs the XLA baseline                #
# ===================================================================== #

@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_vs_xla(causal):
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 128, 64).astype(np.float32))
               for _ in range(3))
    e = kernels.entry("flash_attention")
    out = e.kernel(q, k, v, 0.125, causal=causal, interpret=True)
    ref = e.xla(q, k, v, 0.125, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_decode_attention_kernel_vs_xla():
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    B, H, S, D = 2, 2, 256, 64
    q = jnp.asarray(rng.randn(B, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    # ragged: one row stops mid-block, exercising the position mask AND
    # the whole-block skip
    lengths = jnp.asarray([S, 100], np.int32)
    e = kernels.entry("decode_attention")
    out = e.kernel(q, k, v, lengths, 0.125, interpret=True)
    ref = e.xla(q, k, v, lengths, 0.125)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # positions past `lengths` must not leak into the output: growing
    # the padded tail must not change row 1
    k2 = k.at[1, :, 100:].set(1e4)
    out2 = e.kernel(q, k2, v, lengths, 0.125, interpret=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def _opt_inputs(n=5000, seed=2):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(n).astype(np.float32) * s)
            for s in (1.0, 0.1, 0.01, 0.001)]


def test_opt_sgd_bit_exact_under_jit():
    w, g, mom, _ = _opt_inputs()
    e = kernels.entry("opt_sgd")
    kw = dict(momentum=0.9, wd=1e-4, rescale_grad=0.5, clip_gradient=1.0)
    kfn = _jit(lambda *a: e.kernel(*a, interpret=True, **kw))
    xfn = _jit(lambda *a: e.xla(*a, **kw))
    w_k, m_k = kfn(w, g, mom, 0.05)
    w_x, m_x = xfn(w, g, mom, 0.05)
    assert np.array_equal(np.asarray(w_k), np.asarray(w_x))
    assert np.array_equal(np.asarray(m_k), np.asarray(m_x))
    # ... and the eager op it replaces (1-ULP-scale tolerance: the eager
    # path skips the FMA contraction jit applies to both sides above)
    from mxnet_tpu.ops import optimizer_op as op

    w_e, m_e = op.sgd_mom_update.fn(w, g, mom, lr=0.05, **kw)
    np.testing.assert_allclose(np.asarray(w_k), np.asarray(w_e),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(m_k), np.asarray(m_e),
                               rtol=1e-6, atol=1e-7)


def test_opt_adam_bit_exact_under_jit():
    w, g, mean, var = _opt_inputs(seed=3)
    var = abs(var)
    e = kernels.entry("opt_adam")
    kw = dict(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=1e-4,
              rescale_grad=1.0, clip_gradient=-1.0)
    kfn = _jit(lambda *a: e.kernel(*a, interpret=True, **kw))
    xfn = _jit(lambda *a: e.xla(*a, **kw))
    got = kfn(w, g, mean, var, 0.001)
    want = xfn(w, g, mean, var, 0.001)
    for a, b in zip(got, want):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    from mxnet_tpu.ops import optimizer_op as op

    eager = op.adam_update.fn(w, g, mean, var, lr=0.001, **kw)
    for a, b in zip(got, eager):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("relu,bias", [(False, False), (True, True)])
def test_int8_gemm_bit_exact_under_jit(relu, bias):
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    qx = jnp.asarray(rng.randint(-127, 128, (48, 96)).astype(np.int8))
    w = jnp.asarray(rng.randint(-127, 128, (64, 96)).astype(np.int8))
    scale = jnp.asarray((rng.rand(64) * 0.01 + 1e-4).astype(np.float32))
    b = jnp.asarray(rng.randn(64).astype(np.float32)) if bias else None
    e = kernels.entry("int8_gemm")
    kfn = _jit(lambda *a: e.kernel(*a, bias=b, relu=relu, interpret=True))
    xfn = _jit(lambda *a: e.xla(*a, bias=b, relu=relu))
    out_k = kfn(qx, w, scale)
    out_x = xfn(qx, w, scale)
    assert out_k.shape == (48, 64)
    # the XLA baseline IS the quantization.py fused-op math — bit
    # equality here is the int8-GEMM-vs-fused-ops exactness contract
    assert np.array_equal(np.asarray(out_k), np.asarray(out_x))
    if relu:
        assert float(np.asarray(out_k).min()) >= 0.0


def test_int8_gemm_matches_quantized_fc_op():
    """End to end through the _contrib_quantized_fully_connected op (the
    registry consumer): same answer with kernels enabled and disabled."""
    rng = np.random.RandomState(5)
    x = rng.randn(4, 16).astype(np.float32)
    w = (rng.randn(8, 16) * 0.1).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    absmax = np.abs(w).max(axis=1)
    scale = (absmax / 127.0).astype(np.float32)
    qw = np.clip(np.round(w / scale[:, None]), -127, 127).astype(np.int8)

    def run():
        return mx.nd.invoke(
            "_contrib_quantized_fully_connected", mx.nd.array(x),
            mx.nd.array(qw, dtype="int8"), mx.nd.array(scale),
            mx.nd.array(b), num_hidden=8, min_calib_range=float(x.min()),
            max_calib_range=float(x.max())).asnumpy()

    base = run()
    os.environ["MXNET_TPU_KERNELS"] = "0"
    try:
        off = run()
    finally:
        os.environ.pop("MXNET_TPU_KERNELS", None)
    assert np.array_equal(base, off)
    rel = np.abs(base - (x @ w.T + b)).max() / np.abs(x @ w.T + b).max()
    assert rel < 0.05


def test_twobit_bit_exact_under_jit():
    import jax.numpy as jnp

    rng = np.random.RandomState(6)
    g = jnp.asarray(rng.randn(4096).astype(np.float32))
    res = jnp.asarray(rng.randn(4096).astype(np.float32) * 0.1)
    ce = kernels.entry("twobit_compress")
    de = kernels.entry("twobit_decompress")
    # thr is a STATIC hyperparameter (baked into the kernel body), so it
    # must be closed over, not traced through jit
    ckfn = _jit(lambda a, b: ce.kernel(a, b, 0.5, interpret=True))
    cxfn = _jit(lambda a, b: ce.xla(a, b, 0.5))
    codes_k, res_k = ckfn(g, res)
    codes_x, res_x = cxfn(g, res)
    assert codes_k.dtype == np.int8
    assert np.array_equal(np.asarray(codes_k), np.asarray(codes_x))
    assert np.array_equal(np.asarray(res_k), np.asarray(res_x))
    assert set(np.unique(np.asarray(codes_k))) <= {-1, 0, 1}
    dk = _jit(lambda c: de.kernel(c, 0.5, interpret=True))(codes_k)
    dx = _jit(lambda c: de.xla(c, 0.5))(codes_x)
    assert np.array_equal(np.asarray(dk), np.asarray(dx))


# ===================================================================== #
# dispatch routing                                                      #
# ===================================================================== #

def _flash_args():
    import jax.numpy as jnp

    rng = np.random.RandomState(7)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 128, 64).astype(np.float32))
               for _ in range(3))
    return q, k, v, 0.125


def test_dispatch_env_disabled_restores_baseline_bitexact(monkeypatch):
    q, k, v, scale = _flash_args()
    e = kernels.entry("flash_attention")
    monkeypatch.setenv("MXNET_TPU_KERNELS", "0")
    assert not kernels.enabled()
    assert kernels.choice_for("flash_attention", q, k, v, scale) \
        == ("xla", "env_disabled")
    out = kernels.dispatch("flash_attention", q, k, v, scale)
    # the opt-out IS the baseline: same callable, bit-identical result
    assert np.array_equal(np.asarray(out), np.asarray(e.xla(q, k, v, scale)))


def test_dispatch_untuned_default_and_interpret_forced():
    q, k, v, scale = _flash_args()
    choice, reason = kernels.choice_for("flash_attention", q, k, v, scale)
    if kernels.on_tpu():  # pragma: no cover - CPU CI
        assert (choice, reason) == ("kernel", "untuned_default_tpu")
    else:
        assert (choice, reason) == ("xla", "untuned_default")
    kernels.reset_stats()
    out = kernels.dispatch("flash_attention", q, k, v, scale,
                           interpret=True)
    assert out.shape == q.shape
    st = kernels.dispatch_stats()["flash_attention"]
    assert st["kernel"] == 1
    assert st["reasons"] == {"interpret_forced": 1}


def test_dispatch_unsupported_shape_falls_back():
    import jax.numpy as jnp

    rng = np.random.RandomState(8)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 100, 64).astype(np.float32))
               for _ in range(3))  # 100 % 128 != 0
    assert kernels.choice_for("flash_attention", q, k, v, 0.125) \
        == ("xla", "unsupported_shape")
    out = kernels.dispatch("flash_attention", q, k, v, 0.125,
                           interpret=True)  # still safe: routes to XLA
    assert out.shape == q.shape


def test_dispatch_tuned_table_routes(kernel_cache_dir):
    q, k, v, scale = _flash_args()
    e = kernels.entry("flash_attention")
    bucket = e.bucket(q, k, v, scale)
    ktable.record("flash_attention", bucket, "kernel", 1.0, 2.0)
    assert ktable.save()
    ktable.invalidate()
    assert kernels.choice_for("flash_attention", q, k, v, scale) \
        == ("kernel", "tuned")
    ktable.record("flash_attention", bucket, "xla", 2.0, 1.0)
    assert kernels.choice_for("flash_attention", q, k, v, scale) \
        == ("xla", "tuned")


def test_dispatch_table_corrupt_entry_falls_back(kernel_cache_dir):
    from mxnet_tpu.telemetry import registry as treg

    q, k, v, scale = _flash_args()
    e = kernels.entry("flash_attention")
    bucket = e.bucket(q, k, v, scale)
    ktable.record("flash_attention", bucket, "kernel", 1.0, 2.0)
    path = ktable.save()
    # torn write: flip bytes INSIDE the entries payload so json still
    # parses but the CRC no longer matches
    with open(path, "r", encoding="utf-8") as f:
        raw = f.read()
    with open(path, "w", encoding="utf-8") as f:
        f.write(raw.replace('"winner": "kernel"', '"winner": "xlaaaa"'))
    m = treg.get("mxtpu_kernels_table_corrupt_total")
    before = sum(m.series().values()) if m is not None else 0
    ktable.invalidate()
    t = ktable.load()
    assert t["entries"] == {}  # corrupt loads EMPTY, never raises
    assert ktable.census()["corrupt_seen"]
    assert "CRC" in ktable.census()["corrupt_seen"]
    m = treg.get("mxtpu_kernels_table_corrupt_total")
    assert sum(m.series().values()) == before + 1
    # dispatch falls back to the untuned default, and still answers
    assert kernels.choice_for("flash_attention", q, k, v, scale)[1] \
        in ("untuned_default", "untuned_default_tpu")
    out = kernels.dispatch("flash_attention", q, k, v, scale)
    assert out.shape == q.shape
    # unparseable garbage loads empty too
    with open(path, "wb") as f:
        f.write(b"\x00garbage\xff")
    ktable.invalidate()
    assert ktable.load()["entries"] == {}


def test_pallas_unavailable_latches_once(monkeypatch, caplog):
    import logging

    q, k, v, scale = _flash_args()
    monkeypatch.setattr(kernels, "pallas_available", lambda: False)
    kernels._warned_families.discard("flash_attention")
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.kernels"):
        for _ in range(3):
            out = kernels.dispatch("flash_attention", q, k, v, scale)
    assert out.shape == q.shape
    warns = [r for r in caplog.records if "Pallas unavailable" in r.message]
    assert len(warns) == 1  # latched: one warning, not one per call
    assert "flash_attention" in kernels.fallback_report()["warned_families"]


def test_token_salt_tracks_dispatch_state(monkeypatch, kernel_cache_dir):
    ktable.invalidate()
    base = kernels.token_salt()
    monkeypatch.setenv("MXNET_TPU_KERNELS", "0")
    assert kernels.token_salt() != base  # flipped gate -> new executable
    monkeypatch.delenv("MXNET_TPU_KERNELS")
    assert kernels.token_salt() == base
    q, k, v, scale = _flash_args()
    e = kernels.entry("flash_attention")
    ktable.record("flash_attention", e.bucket(q, k, v, scale), "kernel",
                  1.0, 2.0)
    assert kernels.token_salt() != base  # retuned table -> new identity


# ===================================================================== #
# distcheck pass 4 — dispatch keys must not churn                       #
# ===================================================================== #

def test_dispatch_keys_no_churn():
    from mxnet_tpu.analysis import distcheck

    q, k, v, scale = _flash_args()
    distcheck.reset_cache_stats()
    kernels.reset_stats()
    for _ in range(6):
        kernels.choice_for("flash_attention", q, k, v, scale)
    stats = distcheck.cache_stats()
    site = stats.get(("dispatch", "kernels.flash_attention"))
    assert site is not None, stats
    # a pure bucketing function: ONE legitimate miss, then hits
    assert site["misses"] == 1 and site["hits"] == 5
    assert not [i for i in distcheck.check_churn()
                if "kernels.flash_attention" in i.node]
    distcheck.reset_cache_stats()


# ===================================================================== #
# autotuner — opperf --kernels writes the persisted table               #
# ===================================================================== #

def test_opperf_kernels_writes_table(kernel_cache_dir):
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    import opperf

    res = opperf.bench_kernels(runs=2, warmup=1,
                               families=["twobit_compress",
                                         "twobit_decompress"])
    assert res["table_path"] and os.path.exists(res["table_path"])
    assert len(res["results"]) == 2
    for r in res["results"]:
        assert r["winner"] in ("kernel", "xla")
        if not kernels.on_tpu():
            assert r["interpret"] is True  # honest off-TPU stamp
    ktable.invalidate()  # force the disk round-trip (CRC verifies)
    t = ktable.load()
    assert len(t["entries"]) == 2
    assert t["opperf"]["runs"] == 2
    assert ktable.census()["corrupt_seen"] is None \
        or "CRC" not in ktable.census()["corrupt_seen"]
    # the measured winner now routes dispatch for that exact bucket
    import jax.numpy as jnp

    rng = np.random.RandomState(9)
    g = jnp.asarray(rng.randn(65536).astype(np.float32))
    r0 = jnp.zeros_like(g)
    choice, reason = kernels.choice_for("twobit_compress", g, r0, 0.5)
    assert reason == "tuned"
    key = "twobit_compress|" + \
        kernels.entry("twobit_compress").bucket(g, r0, 0.5)
    assert choice == t["entries"][key]["winner"]


# ===================================================================== #
# trainer integration — fused optimizer step parity                     #
# ===================================================================== #

@pytest.mark.slow
def test_trainer_parity_kernels_on_vs_off():
    """Three ShardedTrainer steps land on identical weights with the
    kernel layer enabled and with MXNET_TPU_KERNELS=0 — the end-to-end
    numerics-parity opt-out contract."""
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    def run():
        mx.random.seed(0)
        net = nn.Dense(4)
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(np.random.RandomState(0).randn(8, 6)
                        .astype(np.float32))
        y = mx.nd.array(np.random.RandomState(1).randint(0, 4, 8)
                        .astype(np.float32))
        net(x)  # materialize deferred shapes
        tr = ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            mesh=DeviceMesh({"dp": 1}), nan_guard=False)
        for _ in range(3):
            tr.step(x, y).wait_to_read()
        return {k: v.data().asnumpy() for k, v in
                net.collect_params().items()}

    base = run()
    os.environ["MXNET_TPU_KERNELS"] = "0"
    try:
        off = run()
    finally:
        os.environ.pop("MXNET_TPU_KERNELS", None)
    # gluon's global name counter differs between runs (dense0 vs
    # dense1) — compare positionally on the sorted suffix
    def vals(d):
        return [d[k] for k in sorted(d, key=lambda n: n.split("_", 1)[-1])]

    assert len(base) == len(off)
    for a, b in zip(vals(base), vals(off)):
        assert np.array_equal(a, b)
