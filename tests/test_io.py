"""IO + gluon.data tests (parity model: tests/python/unittest/test_io.py,
test_gluon_data.py)."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.io import DataBatch, DataDesc, NDArrayIter, PrefetchingIter, ResizeIter
from mxnet_tpu.gluon.data import (ArrayDataset, BatchSampler, DataLoader,
                                  RandomSampler, SequentialSampler,
                                  SimpleDataset)
from mxnet_tpu.gluon.data.vision import transforms


def test_ndarray_iter_basic():
    data = np.arange(100).reshape(25, 4).astype(np.float32)
    label = np.arange(25).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (5, 4)
    assert batches[0].label[0].shape == (5,)
    np.testing.assert_array_equal(batches[0].data[0].asnumpy(), data[:5])
    # reset + iterate again
    it.reset()
    assert len(list(it)) == 5


def test_ndarray_iter_pad_discard():
    data = np.arange(23 * 2).reshape(23, 2).astype(np.float32)
    it = NDArrayIter(data, batch_size=5, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 5
    assert batches[-1].pad == 2
    it = NDArrayIter(data, batch_size=5, last_batch_handle="discard")
    assert len(list(it)) == 4


def test_ndarray_iter_shuffle():
    data = np.arange(40).reshape(40, 1).astype(np.float32)
    it = NDArrayIter(data, batch_size=10, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(40))


def test_ndarray_iter_dict_multi_input():
    it = NDArrayIter({"a": np.zeros((10, 2)), "b": np.ones((10, 3))},
                     batch_size=5)
    names = sorted(d.name for d in it.provide_data)
    assert names == ["a", "b"]


def test_resize_iter():
    data = np.zeros((10, 2), np.float32)
    base = NDArrayIter(data, batch_size=5)
    it = ResizeIter(base, size=7)
    assert len(list(it)) == 7  # wraps around


def test_prefetching_iter():
    data = np.arange(20).reshape(20, 1).astype(np.float32)
    it = PrefetchingIter(NDArrayIter(data, batch_size=5))
    batches = list(it)
    assert len(batches) == 4
    it.reset()
    assert len(list(it)) == 4


def test_prefetching_iter_device_stage():
    """The device-placement stage: batches come out already device_put on
    the requested target (inside the fetch worker — double-buffered h2d),
    values unchanged."""
    import jax

    data = np.arange(40).reshape(20, 2).astype(np.float32)
    label = np.arange(20).astype(np.float32)
    plain = list(PrefetchingIter(NDArrayIter(data, label, batch_size=5)))
    staged = list(PrefetchingIter(NDArrayIter(data, label, batch_size=5),
                                  device=mx.cpu()))
    assert len(staged) == len(plain)
    for p, s in zip(plain, staged):
        assert isinstance(s.data[0]._data.sharding,
                          jax.sharding.SingleDeviceSharding)
        np.testing.assert_array_equal(p.data[0].asnumpy(),
                                      s.data[0].asnumpy())
        np.testing.assert_array_equal(p.label[0].asnumpy(),
                                      s.label[0].asnumpy())


def test_prefetching_iter_mesh_stage_matches_trainer_layout():
    """mesh= stages batches dp-sharded on dim 0 — exactly the layout
    ShardedTrainer._put_batch would produce, so the step's device_put is
    a no-op."""
    import jax

    from mxnet_tpu.parallel import DeviceMesh

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    mesh = DeviceMesh({"dp": 2})
    data = np.arange(48).reshape(8, 6).astype(np.float32)
    label = np.arange(8).astype(np.float32)
    it = PrefetchingIter(NDArrayIter(data, label, batch_size=4), mesh=mesh)
    batch = next(it)
    x_sh = batch.data[0]._data.sharding
    y_sh = batch.label[0]._data.sharding
    assert x_sh == mesh.sharding("dp", None)
    assert y_sh == mesh.sharding("dp")
    np.testing.assert_array_equal(batch.data[0].asnumpy(), data[:4])
    # explicit shardings= pair behaves identically
    it2 = PrefetchingIter(NDArrayIter(data, label, batch_size=4),
                          shardings=(mesh.sharding("dp", None),
                                     mesh.sharding("dp")))
    b2 = next(it2)
    assert b2.data[0]._data.sharding == x_sh
    assert b2.label[0]._data.sharding == y_sh


def test_prefetching_iter_stage_conflicting_args_rejected():
    from mxnet_tpu.parallel import DeviceMesh

    data = np.zeros((4, 2), np.float32)
    with pytest.raises(ValueError, match="at most one"):
        PrefetchingIter(NDArrayIter(data, batch_size=2),
                        device=mx.cpu(), mesh=DeviceMesh({"dp": 1}))


def test_prefetching_iter_stage_error_sticky():
    """A failing device transfer in the placement stage follows the
    deferred-error contract: sticky until reset()."""

    class _BadSharding:
        pass

    data = np.zeros((4, 2), np.float32)
    it = PrefetchingIter(NDArrayIter(data, batch_size=2),
                         shardings=_BadSharding())
    with pytest.raises(Exception):
        next(it)
    with pytest.raises(Exception):  # sticky
        it.iter_next()


def test_mnist_iter_from_files(tmp_path):
    """Write idx-format files and read via MNISTIter (parity:
    src/io/iter_mnist.cc)."""
    imgs = (np.random.rand(50, 28, 28) * 255).astype(np.uint8)
    labels = np.random.randint(0, 10, 50).astype(np.uint8)
    img_path = str(tmp_path / "train-images-idx3-ubyte")
    lbl_path = str(tmp_path / "train-labels-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 50, 28, 28))
        f.write(imgs.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, 50))
        f.write(labels.tobytes())
    from mxnet_tpu.io import MNISTIter

    it = MNISTIter(image=img_path, label=lbl_path, batch_size=10, shuffle=False)
    b = next(iter(it))
    assert b.data[0].shape == (10, 1, 28, 28)
    np.testing.assert_allclose(b.data[0].asnumpy(),
                               imgs[:10, None] / 255.0, rtol=1e-5)
    flat = MNISTIter(image=img_path, label=lbl_path, batch_size=10, flat=True,
                     shuffle=False)
    assert next(iter(flat)).data[0].shape == (10, 784)
    # data-parallel sharding
    part = MNISTIter(image=img_path, label=lbl_path, batch_size=5,
                     num_parts=2, part_index=0, shuffle=False)
    assert part.num_data == 25


def test_datasets_and_samplers():
    ds = SimpleDataset(list(range(10)))
    assert len(ds) == 10 and ds[3] == 3
    t = ds.transform(lambda x: x * 2)
    assert t[3] == 6
    pairs = ArrayDataset(np.arange(10), np.arange(10) * 10)
    x, y = pairs[2]
    assert x == 2 and y == 20
    tf = pairs.transform_first(lambda x: x + 100)
    x, y = tf[2]
    assert x == 102 and y == 20

    assert list(SequentialSampler(5)) == [0, 1, 2, 3, 4]
    assert sorted(RandomSampler(5)) == [0, 1, 2, 3, 4]
    bs = BatchSampler(SequentialSampler(7), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 1]
    bs = BatchSampler(SequentialSampler(7), 3, "discard")
    assert [len(b) for b in bs] == [3, 3]
    assert len(bs) == 2


def test_dataloader():
    x = np.random.rand(20, 3).astype(np.float32)
    y = np.arange(20).astype(np.float32)
    ds = ArrayDataset(x, y)
    for workers in (0, 2):
        loader = DataLoader(ds, batch_size=6, last_batch="keep",
                            num_workers=workers)
        batches = list(loader)
        assert len(batches) == 4
        xb, yb = batches[0]
        assert xb.shape == (6, 3)
        assert yb.shape == (6,)
        total = np.concatenate([b[1].asnumpy() for b in batches])
        assert sorted(total.tolist()) == list(range(20))
    assert len(loader) == 4


def test_dataloader_shuffle_batchify():
    ds = SimpleDataset([(np.full((2, 2), i, np.float32), i) for i in range(12)])
    loader = DataLoader(ds, batch_size=4, shuffle=True)
    xs, ys = zip(*list(loader))
    labels = np.concatenate([y.asnumpy() for y in ys])
    assert sorted(labels.tolist()) == list(range(12))
    assert xs[0].shape == (4, 2, 2)


def test_transforms():
    img = (np.random.rand(10, 8, 3) * 255).astype(np.uint8)
    x = mx.nd.array(img, dtype=np.uint8)
    out = transforms.ToTensor()(x)
    assert out.shape == (3, 10, 8)
    assert out.dtype == np.float32
    assert float(out.max().asscalar()) <= 1.0

    norm = transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(2, 2, 2))
    normed = norm(out)
    np.testing.assert_allclose(normed.asnumpy(),
                               (out.asnumpy() - 0.5) / 2, rtol=1e-5)

    resized = transforms.Resize((4, 6))(x)  # (w=4, h=6)
    assert resized.shape == (6, 4, 3)
    cropped = transforms.CenterCrop((4, 6))(x)
    assert cropped.shape == (6, 4, 3)
    rrc = transforms.RandomResizedCrop(4)(x)
    assert rrc.shape == (4, 4, 3)

    comp = transforms.Compose([transforms.ToTensor(),
                               transforms.Normalize(0.5, 0.5)])
    assert comp(x).shape == (3, 10, 8)

    flipped = transforms.RandomFlipLeftRight(p=1.0)(x)
    np.testing.assert_array_equal(flipped.asnumpy(), img[:, ::-1])

    bright = transforms.RandomBrightness(0.5)(x)
    assert bright.shape == img.shape


def test_dataset_with_dataloader_transform():
    imgs = [(np.random.rand(8, 8, 3) * 255).astype(np.uint8) for _ in range(8)]
    ds = SimpleDataset([(img, i) for i, img in enumerate(imgs)])
    ds = ds.transform_first(lambda im: transforms.ToTensor()(mx.nd.array(im, dtype=np.uint8)))
    loader = DataLoader(ds, batch_size=4)
    xb, yb = next(iter(loader))
    assert xb.shape == (4, 3, 8, 8)


def test_roll_over():
    """roll_over carries the partial tail into the next epoch (parity:
    io.py NDArrayIter last_batch_handle)."""
    data = np.arange(23).reshape(23, 1).astype(np.float32)
    it = NDArrayIter(data, batch_size=5, last_batch_handle="roll_over")
    ep1 = list(it)
    assert len(ep1) == 4  # 20 samples, 3 left over
    it.reset()
    ep2 = list(it)
    assert len(ep2) == 5  # 3 carried + 23 = 26 -> 5 full batches
    first = ep2[0].data[0].asnumpy().ravel()
    np.testing.assert_array_equal(first[:3], [20, 21, 22])  # carried samples


def test_prefetching_iter_protocol():
    data = np.arange(20).reshape(20, 1).astype(np.float32)
    it = PrefetchingIter(NDArrayIter(data, batch_size=5))
    count = 0
    while it.iter_next():
        assert it.getdata()[0].shape == (5, 1)
        count += 1
    assert count == 4


def test_mnist_seed_reproducible(tmp_path):
    imgs = (np.random.rand(30, 28, 28) * 255).astype(np.uint8)
    labels = np.random.randint(0, 10, 30).astype(np.uint8)
    img_path = str(tmp_path / "img")
    lbl_path = str(tmp_path / "lbl")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 30, 28, 28))
        f.write(imgs.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, 30))
        f.write(labels.tobytes())
    from mxnet_tpu.io import MNISTIter

    a = next(iter(MNISTIter(image=img_path, label=lbl_path, batch_size=10,
                            shuffle=True, seed=3))).label[0].asnumpy()
    b = next(iter(MNISTIter(image=img_path, label=lbl_path, batch_size=10,
                            shuffle=True, seed=3))).label[0].asnumpy()
    np.testing.assert_array_equal(a, b)


def test_recordio_roundtrip(tmp_path):
    """RecordIO format round trip (parity: python/mxnet/recordio.py)."""
    from mxnet_tpu import recordio

    rec_path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    payloads = []
    for i in range(5):
        header = recordio.IRHeader(0, float(i), i, 0)
        body = recordio.pack(header, bytes([i] * (i + 1)))
        payloads.append(bytes([i] * (i + 1)))
        w.write_idx(i, body)
    w.close()

    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r.keys == [0, 1, 2, 3, 4]
    for i in [3, 0, 4]:
        header, content = recordio.unpack(r.read_idx(i))
        assert header.label == float(i)
        assert content == payloads[i]
    # sequential read
    r2 = recordio.MXRecordIO(rec_path, "r")
    n = 0
    while r2.read() is not None:
        n += 1
    assert n == 5


def test_image_module(tmp_path):
    """imdecode/imresize + pack_img round trip."""
    from mxnet_tpu import image as img_mod, recordio

    arr = (np.random.rand(12, 10, 3) * 255).astype(np.uint8)
    body = recordio.pack_img(recordio.IRHeader(0, 7.0, 0, 0), arr,
                             img_fmt=".png")
    header, decoded = recordio.unpack_img(body)
    assert header.label == 7.0
    np.testing.assert_array_equal(decoded.asnumpy(), arr)  # png lossless
    resized = img_mod.imresize(mx.nd.array(arr, dtype=np.uint8), 5, 6)
    assert resized.shape == (6, 5, 3)
    short = img_mod.resize_short(mx.nd.array(arr, dtype=np.uint8), 5)
    assert min(short.shape[:2]) == 5


def test_hue_jitter():
    img = mx.nd.array((np.random.rand(8, 8, 3) * 255).astype(np.uint8),
                      dtype=np.uint8)
    out = transforms.RandomHue(0.5)(img)
    assert out.shape == (8, 8, 3)
    jitter = transforms.ColorJitter(brightness=0.1, hue=0.3)
    assert len(jitter._transforms) == 2


def test_ndarray_iter_discard_protocol():
    """`while it.iter_next(): it.getdata()` must never yield a None batch
    under last_batch_handle='discard' (ref io.py: epoch ends instead)."""
    data = np.arange(10 * 2, dtype=np.float32).reshape(10, 2)
    it = mx.io.NDArrayIter(data, batch_size=4, last_batch_handle="discard")
    seen = 0
    while it.iter_next():
        batch = it.getdata()
        assert batch is not None
        seen += 1
    assert seen == 2  # 10 // 4 full batches only


def test_libsvm_iter():
    import tempfile

    f = tempfile.NamedTemporaryFile("w", suffix=".libsvm", delete=False)
    f.write("1 0:1.5 3:2.0\n0 1:0.5\n1 2:3.0 3:1.0\n0 0:0.25\n")
    f.close()
    it = mx.io.LibSVMIter(data_libsvm=f.name, data_shape=(4,), batch_size=2)
    b = next(iter(it))
    assert b.data[0].stype == "csr"
    np.testing.assert_allclose(b.data[0].tostype("default").asnumpy(),
                               [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(b.label[0].asnumpy(), [1, 0])
    b2 = it.next()
    np.testing.assert_allclose(b2.data[0].tostype("default").asnumpy(),
                               [[0, 0, 3.0, 1.0], [0.25, 0, 0, 0]])
    it.reset()
    assert next(iter(it)).label[0].asnumpy().tolist() == [1, 0]


def test_libsvm_iter_round_batch():
    import tempfile

    f = tempfile.NamedTemporaryFile("w", suffix=".libsvm", delete=False)
    f.write("1 0:1.0\n0 1:2.0\n1 2:3.0\n")  # 3 rows, batch 2
    f.close()
    it = mx.io.LibSVMIter(data_libsvm=f.name, data_shape=(4,), batch_size=2,
                          round_batch=True)
    batches = list(it)
    assert len(batches) == 2
    assert batches[1].pad == 1  # wrapped one sample
    np.testing.assert_allclose(
        batches[1].data[0].tostype("default").asnumpy(),
        [[0, 0, 3.0, 0], [1.0, 0, 0, 0]])  # row 2 then wrap to row 0
    it2 = mx.io.LibSVMIter(data_libsvm=f.name, data_shape=(4,),
                           batch_size=2, round_batch=False)
    assert len(list(it2)) == 1


def test_csr_is_lazy():
    from mxnet_tpu.ndarray.sparse import CSRNDArray

    csr = CSRNDArray(np.array([1.0, 2.0], np.float32),
                     np.array([0, 2]), np.array([0, 1, 2]), (2, 1000))
    assert csr._dense_cache is None
    assert csr.shape == (2, 1000)  # metadata without densify
    assert csr._dense_cache is None
    dense = csr.tostype("default")
    assert float(dense.asnumpy()[1, 2]) == 2.0


def test_image_det_iter(tmp_path):
    """ImageDetIter: packed + flat label parsing, fixed (max_obj, width)
    label tensor with -1 filler, flip augmenter moves boxes
    (parity model: test_image.py TestImageDetIter)."""
    import os

    from mxnet_tpu import image as img_mod

    root = str(tmp_path)
    rng = np.random.RandomState(0)
    lines = []
    labels = [
        # flat k*5: one object
        [1.0, 0.1, 0.2, 0.5, 0.6],
        # packed: header=4, width=5, two extra header floats, 2 objects
        [4.0, 5.0, 0.0, 0.0,
         0.0, 0.0, 0.0, 0.4, 0.4, 2.0, 0.5, 0.5, 0.9, 0.8],
    ]
    for i, lab in enumerate(labels):
        arr = (rng.rand(10, 8, 3) * 255).astype(np.uint8)
        import mxnet_tpu.recordio as recordio

        body = recordio.pack_img(recordio.IRHeader(0, 0.0, 0, 0), arr,
                                 img_fmt=".png")
        _, img_bytes = recordio.unpack(body)
        fname = f"img{i}.png"
        with open(os.path.join(root, fname), "wb") as f:
            f.write(img_bytes)
        cols = "\t".join(str(x) for x in lab)
        lines.append(f"{i}\t{cols}\t{fname}")
    with open(os.path.join(root, "list.lst"), "w") as f:
        f.write("\n".join(lines) + "\n")

    it = img_mod.ImageDetIter(batch_size=2, data_shape=(3, 8, 8),
                              path_imglist=os.path.join(root, "list.lst"),
                              path_root=root,
                              aug_list=[])  # deterministic
    assert it.label_shape == (2, 5)  # max 2 objects, width 5
    batch = it.next()
    assert batch.data[0].shape == (2, 3, 8, 8)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (2, 2, 5)
    np.testing.assert_allclose(lab[0, 0], [1.0, 0.1, 0.2, 0.5, 0.6],
                               rtol=1e-6)
    assert lab[0, 1, 0] == -1.0  # filler row
    np.testing.assert_allclose(lab[1, 1], [2.0, 0.5, 0.5, 0.9, 0.8],
                               rtol=1e-6)

    # flip moves normalized x coords; filler rows untouched
    flip = img_mod.DetHorizontalFlipAug(p=1.1)  # always fires
    src = np.zeros((4, 4, 3), np.uint8)
    label = np.array([[0.0, 0.1, 0.2, 0.4, 0.6],
                      [-1.0, 0, 0, 0, 0]], np.float32)
    _, out = flip(src, label)
    np.testing.assert_allclose(out[0], [0.0, 0.6, 0.2, 0.9, 0.6],
                               rtol=1e-5)
    assert out[1, 0] == -1.0

    # sync_label_shape grows both iterators to the elementwise max
    it2 = img_mod.ImageDetIter(batch_size=2, data_shape=(3, 8, 8),
                               path_imglist=os.path.join(root, "list.lst"),
                               path_root=root, label_shape=(5, 6),
                               aug_list=[])
    it.sync_label_shape(it2)
    assert it.label_shape == (5, 6) and it2.label_shape == (5, 6)


def test_image_det_iter_validation(tmp_path):
    """Oversized labels raise instead of silently truncating; unsupported
    CreateDetAugmenter args raise."""
    import os

    import pytest

    import mxnet_tpu.recordio as recordio
    from mxnet_tpu import image as img_mod

    root = str(tmp_path)
    arr = (np.random.RandomState(1).rand(8, 8, 3) * 255).astype(np.uint8)
    body = recordio.pack_img(recordio.IRHeader(0, 0.0, 0, 0), arr,
                             img_fmt=".png")
    _, img_bytes = recordio.unpack(body)
    with open(os.path.join(root, "a.png"), "wb") as f:
        f.write(img_bytes)
    with open(os.path.join(root, "l.lst"), "w") as f:
        f.write("0\t" + "\t".join(
            str(x) for x in [1.0, 0.1, 0.1, 0.2, 0.2,
                             2.0, 0.3, 0.3, 0.4, 0.4]) + "\ta.png\n")
    it = img_mod.ImageDetIter(batch_size=1, data_shape=(3, 8, 8),
                              path_imglist=os.path.join(root, "l.lst"),
                              path_root=root, label_shape=(1, 5),
                              aug_list=[])
    with pytest.raises(ValueError, match="exceeds label_shape"):
        it.next()
    with pytest.raises(ValueError, match="unsupported"):
        img_mod.CreateDetAugmenter((3, 8, 8), rand_crop=0.5)


def _write_jpeg_rec(path, n=12, hw=40):
    import io as _io

    import numpy as np
    from PIL import Image

    from mxnet_tpu import recordio

    rs = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        arr = rs.randint(0, 255, (hw, hw, 3), np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=95)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 3), i, 0), buf.getvalue()))
    rec.close()
    return path + ".rec"


def test_image_record_iter_native_decode_matches_pil(tmp_path):
    """At decode size == source size (no resize) the native libjpeg path
    and the PIL path are bit-exact."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import native

    import pytest

    if not native.available() or native.decode_jpeg_batch([b""], 1, 1) \
            is None:
        pytest.skip("native JPEG decode not built on this host")
    rec = _write_jpeg_rec(str(tmp_path / "a"), n=8, hw=32)
    kw = dict(path_imgrec=rec, data_shape=(3, 32, 32), batch_size=8,
              prefetch_buffer=0)
    it_native = mx.io.ImageRecordIter(**kw)
    b_native = it_native.next().data[0].asnumpy()
    # force the PIL path by monkeypatching the native decode away
    it_pil = mx.io.ImageRecordIter(**kw)
    orig = native.decode_jpeg_batch
    try:
        native.decode_jpeg_batch = lambda *a, **k: None
        b_pil = it_pil.next().data[0].asnumpy()
    finally:
        native.decode_jpeg_batch = orig
    np.testing.assert_array_equal(b_native, b_pil)


def test_image_record_iter_augment_and_prefetch(tmp_path):
    """rand_crop/rand_mirror produce the right shapes; prefetching
    yields the same batch stream as the synchronous path."""
    import numpy as np

    import mxnet_tpu as mx

    rec = _write_jpeg_rec(str(tmp_path / "b"), n=16, hw=48)
    kw = dict(path_imgrec=rec, data_shape=(3, 32, 32), batch_size=4,
              rand_crop=True, rand_mirror=True, seed=3)
    sync = mx.io.ImageRecordIter(prefetch_buffer=0, **kw)
    pre = mx.io.ImageRecordIter(prefetch_buffer=2, **kw)
    for _ in range(2):  # two epochs incl. reset of the producer thread
        got_sync = [b.data[0].asnumpy() for b in sync]
        got_pre = [b.data[0].asnumpy() for b in pre]
        assert len(got_sync) == len(got_pre) == 4
        for a, b in zip(got_sync, got_pre):
            assert a.shape == (4, 3, 32, 32)
            np.testing.assert_array_equal(a, b)
        sync.reset()
        pre.reset()


def test_image_record_iter_corrupt_record_zero_filled(tmp_path):
    """A corrupt JPEG among good ones: the batch survives with that slot
    zero-filled + a warning (reference logs and continues)."""
    import warnings

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import native, recordio

    if not native.available() or native.decode_jpeg_batch([b""], 1, 1) \
            is None:
        import pytest

        pytest.skip("native JPEG decode not built on this host")
    rec_path = _write_jpeg_rec(str(tmp_path / "c"), n=4, hw=32)
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "c.idx"), rec_path,
                                     "w")  # rebuild with one bad record
    import io as _io

    from PIL import Image

    rs = np.random.RandomState(0)
    for i in range(4):
        if i == 2:
            payload = b"\xff\xd8 not a real jpeg"
        else:
            buf = _io.BytesIO()
            Image.fromarray(rs.randint(0, 255, (32, 32, 3), np.uint8)) \
                .save(buf, "JPEG")
            payload = buf.getvalue()
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), payload))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=rec_path,
                               data_shape=(3, 32, 32), batch_size=4,
                               prefetch_buffer=0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        batch = it.next().data[0].asnumpy()
    assert any("corrupt" in str(x.message) for x in w)
    assert np.all(batch[2] == 0)
    assert batch[1].any()


def test_image_record_iter_failed_records_retry_pil(tmp_path, monkeypatch):
    """Records the native JPEG decoder rejects in a mixed batch are
    retried individually through PIL, not zero-filled."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import native

    rec = _write_jpeg_rec(str(tmp_path / "d"), n=4, hw=32)

    def fake_decode(bufs, dh, dw, n_threads=0):
        # pretend the native path exists but rejected record 1
        return np.zeros((len(bufs), dh, dw, 3), np.uint8), [1]

    monkeypatch.setattr(native, "decode_jpeg_batch", fake_decode)
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                               batch_size=4, prefetch_buffer=0)
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        batch = it.next().data[0].asnumpy()
    assert not any("corrupt" in str(x.message) for x in w)
    assert batch[1].any()          # slot 1 recovered via PIL
    assert not batch[0].any()      # untouched native zeros stay (fake)
