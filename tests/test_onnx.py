"""ONNX export/import tests (parity model: tests/python-pytest/onnx/).

No onnx/onnxruntime in this environment, so verification is (a) codec
round-trips through our own spec-conformant parser and (b) NUMERIC
round-trips: export a zoo model, re-import, compare outputs bit-exactly.
When the official onnx package is present, its checker also runs.
"""
import os
import tempfile

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import onnx as mx_onnx
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.onnx import proto


def _export_zoo(name, shp, classes=10):
    net = vision.get_model(name, classes=classes)
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(0).rand(*shp).astype("float32"))
    ref = net(x).asnumpy()
    d = tempfile.mkdtemp()
    net.export(os.path.join(d, "n"), 0)
    sym, args, auxs = mx.model.load_checkpoint(os.path.join(d, "n"), 0)
    path = mx_onnx.export_model(sym, {**args, **auxs}, in_shapes=[shp],
                                onnx_file_path=os.path.join(d, "m.onnx"))
    return path, x, ref


# ---------------------------------------------------------------- codec ----

def test_proto_tensor_roundtrip():
    arr = onp.arange(24, dtype=onp.float32).reshape(2, 3, 4)
    name, back = proto.parse_tensor(proto.tensor("t", arr))
    assert name == "t"
    onp.testing.assert_array_equal(back, arr)
    iarr = onp.array([[1, -2], [3, 4]], onp.int64)
    _, iback = proto.parse_tensor(proto.tensor("i", iarr))
    onp.testing.assert_array_equal(iback, iarr)


def test_proto_attribute_roundtrip():
    for val in [3, 2.5, "hello", [1, 2, 3], [1.5, 2.5]]:
        name, back = proto.parse_attribute(proto.attribute("a", val))
        assert name == "a"
        if isinstance(val, list):
            assert list(back) == pytest.approx(val)
        else:
            assert back == val or back == pytest.approx(val)


def test_proto_node_roundtrip():
    buf = proto.node("Conv", ["x", "w"], ["y"], kernel_shape=[3, 3],
                     group=1)
    n = proto.parse_node(buf)
    assert n["op_type"] == "Conv"
    assert n["input"] == ["x", "w"] and n["output"] == ["y"]
    assert list(n["attrs"]["kernel_shape"]) == [3, 3]


# ----------------------------------------------------------- model level ----

def test_export_produces_wellformed_graph():
    path, _, _ = _export_zoo("resnet18_v1", (1, 3, 32, 32))
    with open(path, "rb") as f:
        m = proto.parse_model(f.read())
    assert m["opset"] == 13 and m["producer"] == "mxnet_tpu"
    g = m["graph"]
    assert g["inputs"][0]["name"] == "data"
    assert g["inputs"][0]["shape"] == (1, 3, 32, 32)
    produced = {vi["name"] for vi in g["inputs"]} | set(g["initializers"])
    for n in g["nodes"]:
        for i in n["input"]:
            assert i in produced, f"node {n['name']} consumes unknown {i}"
        produced.update(n["output"])
    assert g["outputs"][0]["name"] in produced
    ops = {n["op_type"] for n in g["nodes"]}
    assert {"Conv", "BatchNormalization", "Relu", "Gemm"} <= ops


@pytest.mark.parametrize("name,shp", [
    ("resnet18_v1", (1, 3, 32, 32)),
    ("mobilenet0_25", (1, 3, 32, 32)),
    ("squeezenet1_0", (1, 3, 64, 64)),
])
def test_numeric_roundtrip(name, shp):
    path, x, ref = _export_zoo(name, shp)
    sym2, args2, auxs2 = mx_onnx.import_model(path)
    out = sym2.eval_with({"data": x, **args2, **auxs2}).asnumpy()
    rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-9)
    assert rel < 1e-4, rel


def test_official_onnx_checker_if_available():
    onnx = pytest.importorskip("onnx")
    path, _, _ = _export_zoo("mobilenet0_25", (1, 3, 32, 32))
    model = onnx.load(path)
    onnx.checker.check_model(model)


def test_negative_int_attributes_roundtrip():
    # regression: varint decode must sign-extend (softmax axis=-1)
    _, v = proto.parse_attribute(proto.attribute("axis", -1))
    assert v == -1
    _, vs = proto.parse_attribute(proto.attribute("perm", [2, -1, 0]))
    assert list(vs) == [2, -1, 0]


def test_softmax_export_import_roundtrip():
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    sm = mx.sym.softmax(fc, name="sm")
    args = {"fc_weight": mx.nd.ones((4, 8)) * 0.1,
            "fc_bias": mx.nd.zeros((4,))}
    d = tempfile.mkdtemp()
    path = mx_onnx.export_model(sm, args, in_shapes=[(2, 8)],
                                onnx_file_path=os.path.join(d, "m.onnx"))
    sym2, args2, _ = mx_onnx.import_model(path)
    x = mx.nd.array(onp.random.RandomState(1).rand(2, 8).astype("float32"))
    ref = sm.eval_with({"data": x, **args}).asnumpy()
    out = sym2.eval_with({"data": x, **args2}).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=1e-5)


def test_dot_transpose_export():
    import mxnet_tpu as mx

    a = mx.sym.var("a")
    b = mx.sym.var("b")
    out_sym = mx.sym.dot(a, b, transpose_b=True, name="d")
    av = onp.random.RandomState(2).rand(3, 4).astype("float32")
    bv = onp.random.RandomState(3).rand(5, 4).astype("float32")
    d = tempfile.mkdtemp()
    path = mx_onnx.export_model(out_sym, {}, in_shapes=[(3, 4), (5, 4)],
                                onnx_file_path=os.path.join(d, "m.onnx"))
    sym2, args2, _ = mx_onnx.import_model(path)
    out = sym2.eval_with({"a": mx.nd.array(av), "b": mx.nd.array(bv),
                          **args2}).asnumpy()
    onp.testing.assert_allclose(out, av @ bv.T, rtol=1e-5)


def test_compression_disable_with_empty_params():
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit"})
    assert kv.gradient_compression
    kv.set_gradient_compression({})
    assert not kv.gradient_compression
