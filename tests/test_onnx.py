"""ONNX export/import tests (parity model: tests/python-pytest/onnx/).

No onnx/onnxruntime in this environment, so verification is (a) codec
round-trips through our own spec-conformant parser and (b) NUMERIC
round-trips: export a zoo model, re-import, compare outputs bit-exactly.
When the official onnx package is present, its checker also runs.
"""
import os
import tempfile

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import onnx as mx_onnx
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.onnx import proto


def _export_zoo(name, shp, classes=10):
    net = vision.get_model(name, classes=classes)
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(0).rand(*shp).astype("float32"))
    ref = net(x).asnumpy()
    d = tempfile.mkdtemp()
    net.export(os.path.join(d, "n"), 0)
    sym, args, auxs = mx.model.load_checkpoint(os.path.join(d, "n"), 0)
    path = mx_onnx.export_model(sym, {**args, **auxs}, in_shapes=[shp],
                                onnx_file_path=os.path.join(d, "m.onnx"))
    return path, x, ref


# ---------------------------------------------------------------- codec ----

def test_proto_tensor_roundtrip():
    arr = onp.arange(24, dtype=onp.float32).reshape(2, 3, 4)
    name, back = proto.parse_tensor(proto.tensor("t", arr))
    assert name == "t"
    onp.testing.assert_array_equal(back, arr)
    iarr = onp.array([[1, -2], [3, 4]], onp.int64)
    _, iback = proto.parse_tensor(proto.tensor("i", iarr))
    onp.testing.assert_array_equal(iback, iarr)


def test_proto_attribute_roundtrip():
    for val in [3, 2.5, "hello", [1, 2, 3], [1.5, 2.5]]:
        name, back = proto.parse_attribute(proto.attribute("a", val))
        assert name == "a"
        if isinstance(val, list):
            assert list(back) == pytest.approx(val)
        else:
            assert back == val or back == pytest.approx(val)


def test_proto_node_roundtrip():
    buf = proto.node("Conv", ["x", "w"], ["y"], kernel_shape=[3, 3],
                     group=1)
    n = proto.parse_node(buf)
    assert n["op_type"] == "Conv"
    assert n["input"] == ["x", "w"] and n["output"] == ["y"]
    assert list(n["attrs"]["kernel_shape"]) == [3, 3]


# ----------------------------------------------------------- model level ----

def test_export_produces_wellformed_graph():
    path, _, _ = _export_zoo("resnet18_v1", (1, 3, 32, 32))
    with open(path, "rb") as f:
        m = proto.parse_model(f.read())
    assert m["opset"] == 13 and m["producer"] == "mxnet_tpu"
    g = m["graph"]
    assert g["inputs"][0]["name"] == "data"
    assert g["inputs"][0]["shape"] == (1, 3, 32, 32)
    produced = {vi["name"] for vi in g["inputs"]} | set(g["initializers"])
    for n in g["nodes"]:
        for i in n["input"]:
            assert i in produced, f"node {n['name']} consumes unknown {i}"
        produced.update(n["output"])
    assert g["outputs"][0]["name"] in produced
    ops = {n["op_type"] for n in g["nodes"]}
    assert {"Conv", "BatchNormalization", "Relu", "Gemm"} <= ops


@pytest.mark.parametrize("name,shp", [
    ("resnet18_v1", (1, 3, 32, 32)),
    ("mobilenet0_25", (1, 3, 32, 32)),
    ("squeezenet1_0", (1, 3, 64, 64)),
])
def test_numeric_roundtrip(name, shp):
    path, x, ref = _export_zoo(name, shp)
    sym2, args2, auxs2 = mx_onnx.import_model(path)
    out = sym2.eval_with({"data": x, **args2, **auxs2}).asnumpy()
    rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-9)
    assert rel < 1e-4, rel


def test_official_onnx_checker_if_available():
    onnx = pytest.importorskip("onnx")
    path, _, _ = _export_zoo("mobilenet0_25", (1, 3, 32, 32))
    model = onnx.load(path)
    onnx.checker.check_model(model)


def test_negative_int_attributes_roundtrip():
    # regression: varint decode must sign-extend (softmax axis=-1)
    _, v = proto.parse_attribute(proto.attribute("axis", -1))
    assert v == -1
    _, vs = proto.parse_attribute(proto.attribute("perm", [2, -1, 0]))
    assert list(vs) == [2, -1, 0]


def test_softmax_export_import_roundtrip():
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    sm = mx.sym.softmax(fc, name="sm")
    args = {"fc_weight": mx.nd.ones((4, 8)) * 0.1,
            "fc_bias": mx.nd.zeros((4,))}
    d = tempfile.mkdtemp()
    path = mx_onnx.export_model(sm, args, in_shapes=[(2, 8)],
                                onnx_file_path=os.path.join(d, "m.onnx"))
    sym2, args2, _ = mx_onnx.import_model(path)
    x = mx.nd.array(onp.random.RandomState(1).rand(2, 8).astype("float32"))
    ref = sm.eval_with({"data": x, **args}).asnumpy()
    out = sym2.eval_with({"data": x, **args2}).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=1e-5)


def test_dot_transpose_export():
    import mxnet_tpu as mx

    a = mx.sym.var("a")
    b = mx.sym.var("b")
    out_sym = mx.sym.dot(a, b, transpose_b=True, name="d")
    av = onp.random.RandomState(2).rand(3, 4).astype("float32")
    bv = onp.random.RandomState(3).rand(5, 4).astype("float32")
    d = tempfile.mkdtemp()
    path = mx_onnx.export_model(out_sym, {}, in_shapes=[(3, 4), (5, 4)],
                                onnx_file_path=os.path.join(d, "m.onnx"))
    sym2, args2, _ = mx_onnx.import_model(path)
    out = sym2.eval_with({"a": mx.nd.array(av), "b": mx.nd.array(bv),
                          **args2}).asnumpy()
    onp.testing.assert_allclose(out, av @ bv.T, rtol=1e-5)


def test_compression_disable_with_empty_params():
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit"})
    assert kv.gradient_compression
    kv.set_gradient_compression({})
    assert not kv.gradient_compression


# ------------------------------------------------ per-op roundtrip sweep ---
# each case: build a small symbolic graph, export -> import -> compare
# outputs numerically (covers the widened translation set)

def _rt(build, feeds, rtol=1e-5, atol=1e-6):
    """build(vars) -> Symbol over named vars; feeds: {name: np array}."""
    syms = {k: mx.sym.var(k) for k in feeds}
    out = build(syms)
    d = tempfile.mkdtemp()
    path = mx_onnx.export_model(
        out, {}, in_shapes=[list(v.shape) for v in feeds.values()],
        onnx_file_path=os.path.join(d, "m.onnx"))
    nd_feeds = {k: mx.nd.array(v) for k, v in feeds.items()}
    ref = out.eval(**nd_feeds)
    ref = [r.asnumpy() for r in (ref if isinstance(ref, list) else [ref])]
    sym2, args, auxs = mx_onnx.import_model(path)
    got = sym2.eval(**nd_feeds, **{k: v for k, v in args.items()})
    got = [g.asnumpy() for g in (got if isinstance(got, list) else [got])]
    for r, g in zip(ref, got):
        onp.testing.assert_allclose(g, r, rtol=rtol, atol=atol)


_R = onp.random.RandomState(11)
_A = _R.rand(2, 6).astype("float32") + 0.1
_B = _R.rand(2, 6).astype("float32") + 0.1
_IMG = _R.rand(1, 3, 8, 8).astype("float32")

_OP_CASES = {
    "floor": lambda s: mx.sym.floor(s["a"] * 5),
    "ceil": lambda s: mx.sym.ceil(s["a"] * 5),
    "round": lambda s: mx.sym.round(s["a"] * 5),
    "sin": lambda s: mx.sym.sin(s["a"]),
    "cos": lambda s: mx.sym.cos(s["a"]),
    "arctan": lambda s: mx.sym.arctan(s["a"]),
    "erf": lambda s: mx.sym.erf(s["a"]),
    "sign": lambda s: mx.sym.sign(s["a"] - 0.5),
    "reciprocal": lambda s: mx.sym.reciprocal(s["a"]),
    "softsign": lambda s: mx.sym.softsign(s["a"]),
    "square": lambda s: mx.sym.square(s["a"]),
    "rsqrt": lambda s: mx.sym.rsqrt(s["a"]),
    "expm1": lambda s: mx.sym.expm1(s["a"]),
    "log1p": lambda s: mx.sym.log1p(s["a"]),
    "log_softmax": lambda s: mx.sym.log_softmax(s["a"]),
    "maximum": lambda s: mx.sym.broadcast_maximum(s["a"], s["b"]),
    "minimum": lambda s: mx.sym.broadcast_minimum(s["a"], s["b"]),
    "power": lambda s: mx.sym.broadcast_power(s["a"], s["b"]),
    "mod": lambda s: mx.sym.broadcast_mod(s["a"], s["b"]),
    "greater": lambda s: mx.sym.broadcast_greater(s["a"], s["b"]),
    "lesser_equal": lambda s: mx.sym.broadcast_lesser_equal(s["a"],
                                                            s["b"]),
    "logical_and": lambda s: mx.sym.broadcast_logical_and(s["a"] - 0.5,
                                                          s["b"] - 0.5),
    "logical_not": lambda s: mx.sym.logical_not(s["a"] - 0.5),
    "rminus_scalar": lambda s: 2.0 - s["a"],
    "rdiv_scalar": lambda s: 2.0 / s["a"],
    "power_scalar": lambda s: s["a"] ** 2.0,
    "maximum_scalar": lambda s: mx.sym.invoke("_maximum_scalar", s["a"],
                                              scalar=0.5),
    "sum": lambda s: mx.sym.sum(s["a"], axis=1),
    "sum_all": lambda s: mx.sym.sum(s["a"]),
    "mean": lambda s: mx.sym.mean(s["a"], axis=1, keepdims=True),
    "max": lambda s: mx.sym.max(s["a"], axis=0),
    "min": lambda s: mx.sym.min(s["a"], axis=1),
    "prod": lambda s: mx.sym.prod(s["a"], axis=1),
    "norm": lambda s: mx.sym.norm(s["a"], axis=1),
    "argmax": lambda s: mx.sym.argmax(s["a"], axis=1),
    "argmin": lambda s: mx.sym.argmin(s["a"], axis=1),
    "expand_dims": lambda s: mx.sym.expand_dims(s["a"], axis=1),
    "squeeze": lambda s: mx.sym.squeeze(
        mx.sym.expand_dims(s["a"], axis=1), axis=1),
    "slice": lambda s: mx.sym.invoke("slice", s["a"], begin=(0, 1),
                                     end=(2, 4)),
    "slice_axis": lambda s: mx.sym.slice_axis(s["a"], axis=1, begin=1,
                                              end=4),
    "tile": lambda s: mx.sym.tile(s["a"], reps=(2, 1)),
    "pad": lambda s: mx.sym.invoke(
        "pad", mx.sym.Reshape(s["a"], shape=(1, 2, 2, 3)),
        mode="constant", pad_width=(0, 0, 0, 0, 1, 1, 2, 2)),
    "broadcast_to": lambda s: mx.sym.broadcast_to(
        mx.sym.sum(s["a"], axis=0, keepdims=True), shape=(4, 6)),
    "stack": lambda s: mx.sym.invoke("stack", s["a"], s["b"], axis=0),
    "slice_channel": lambda s: mx.sym.SliceChannel(
        s["a"], num_outputs=2, axis=1)[0],
    "where": lambda s: mx.sym.invoke(
        "where", mx.sym.broadcast_greater(s["a"], s["b"]), s["a"],
        s["b"]),
    "cast": lambda s: mx.sym.Cast(s["a"] * 5, dtype="int32"),
    "zeros_like": lambda s: mx.sym.zeros_like(s["a"]),
    "ones_like": lambda s: mx.sym.ones_like(s["a"]),
    "batch_dot": lambda s: mx.sym.batch_dot(
        mx.sym.Reshape(s["a"], shape=(2, 2, 3)),
        mx.sym.Reshape(s["b"], shape=(2, 3, 2))),
}


@pytest.mark.parametrize("case", sorted(_OP_CASES))
def test_onnx_op_roundtrip(case):
    _rt(_OP_CASES[case], {"a": _A, "b": _B}, rtol=1e-4, atol=1e-5)


_NN_CASES = {
    "deconv": lambda s: mx.sym.Deconvolution(
        s["x"], mx.sym.var("w"), kernel=(3, 3), num_filter=2,
        no_bias=True),
    "lrn": lambda s: mx.sym.LRN(s["x"], nsize=3),
    "instance_norm": lambda s: mx.sym.InstanceNorm(
        s["x"], mx.sym.var("g"), mx.sym.var("be")),
    "l2_normalization": lambda s: mx.sym.L2Normalization(
        mx.sym.Flatten(s["x"])),
    "layer_norm": lambda s: mx.sym.LayerNorm(
        mx.sym.Flatten(s["x"]), mx.sym.var("g2"), mx.sym.var("b2")),
    "embedding_take": lambda s: mx.sym.take(
        mx.sym.Flatten(s["x"]),
        mx.sym.var("idx"), axis=1),
}


@pytest.mark.parametrize("case", sorted(_NN_CASES))
def test_onnx_nn_roundtrip(case):
    feeds = {"x": _IMG}
    if case == "deconv":
        feeds["w"] = _R.rand(3, 2, 3, 3).astype("float32") * 0.3
    elif case == "instance_norm":
        feeds["g"] = onp.ones(3, "float32")
        feeds["be"] = onp.zeros(3, "float32")
    elif case == "layer_norm":
        feeds["g2"] = onp.ones(192, "float32")
        feeds["b2"] = onp.zeros(192, "float32")
    elif case == "embedding_take":
        feeds["idx"] = onp.array([0, 5, 2], "float32")
    _rt(_NN_CASES[case], feeds, rtol=1e-4, atol=1e-5)


def test_onnx_argmax_flat_and_inf_zeros_like():
    """axis=None argmax flattens; zeros_like must not propagate inf/NaN
    (regressions found in review)."""
    a = _A.copy()
    _rt(lambda s: mx.sym.argmax(s["a"]), {"a": a})
    a_inf = a.copy()
    a_inf[0, 0] = onp.inf
    a_inf[1, 1] = onp.nan
    _rt(lambda s: mx.sym.zeros_like(s["a"]), {"a": a_inf})
    _rt(lambda s: mx.sym.ones_like(s["a"]), {"a": a_inf})
    _rt(lambda s: mx.sym.squeeze(mx.sym.expand_dims(s["a"], axis=0)),
        {"a": a})


def test_proto_wire_format_golden_bytes():
    """Pin the serialized wire format to spec-derived golden bytes so
    codec drift (field numbers / wire types diverging from onnx.proto3)
    cannot pass the self-roundtrip tests unnoticed. Field numbers
    asserted: TensorProto{dims=1, data_type=2, raw_data=9, name=8},
    NodeProto{input=1, output=2, name=3, op_type=4, attribute=5},
    AttributeProto{name=1, i=3, type=20}, ModelProto{ir_version=1,
    opset_import=8, graph=7}, OperatorSetIdProto{version=2}."""
    t = proto.tensor("w", onp.asarray([[1.0]], onp.float32))
    # dims: field1 PACKED varints [1,1]; data_type: field2 varint
    # (1=FLOAT); name: field8 "w"; raw_data: field9 4 bytes LE 1.0f
    assert t == bytes.fromhex("0a020101") + b"\x10\x01" + \
        b"\x42\x01w" + b"\x4a\x04" + onp.float32(1.0).tobytes()

    n = proto.node("Relu", ["x"], ["y"], name="r")
    assert n == b"\x0a\x01x" + b"\x12\x01y" + b"\x1a\x01r" + \
        b"\x22\x04Relu"

    a = proto.attribute("axis", 2)
    # name field1; i field3 varint; type field20 (=2 INT)
    assert a == b"\x0a\x04axis" + b"\x18\x02" + b"\xa0\x01\x02"

    g = proto.graph([], "g", [], [], [])
    m = proto.model(g, opset=13)
    # ModelProto: ir_version field1, graph field7, opset_import field8
    assert m.startswith(b"\x08")            # ir_version varint
    assert b"\x3a" in m                     # graph (field 7, wire 2)
    # OperatorSetIdProto: domain field1 (empty), version field2 = 13
    assert b"\x42\x04\x0a\x00\x10\x0d" in m  # opset_import submessage


# --------------------------------------------- opset / Mod / Unsqueeze -----

def test_opset_bumped_to_17_for_layer_norm():
    """LayerNormalization exists only from opset 17; plain graphs must
    keep declaring 13 (maximum runtime compatibility)."""
    d = tempfile.mkdtemp()
    x = mx.sym.var("x")
    ln = mx.sym.LayerNorm(x, mx.sym.var("g"), mx.sym.var("b"), name="ln")
    p_ln = mx_onnx.export_model(ln, {}, in_shapes=[(2, 6), (6,), (6,)],
                                onnx_file_path=os.path.join(d, "ln.onnx"))
    assert proto.parse_model(open(p_ln, "rb").read())["opset"] == 17

    plain = mx.sym.relu(mx.sym.var("x"), name="r")
    p_plain = mx_onnx.export_model(plain, {}, in_shapes=[(2, 6)],
                                   onnx_file_path=os.path.join(d, "p.onnx"))
    assert proto.parse_model(open(p_plain, "rb").read())["opset"] == 13


def test_mod_exports_with_fmod_for_float():
    """float Mod must carry fmod=1 (fmod=0 is integer-only per spec)."""
    d = tempfile.mkdtemp()
    out = mx.sym.broadcast_mod(mx.sym.var("a"), mx.sym.var("b"), name="m")
    path = mx_onnx.export_model(out, {}, in_shapes=[(2, 3), (2, 3)],
                                onnx_file_path=os.path.join(d, "m.onnx"))
    g = proto.parse_model(open(path, "rb").read())["graph"]
    mod_nodes = [n for n in g["nodes"] if n["op_type"] == "Mod"]
    assert mod_nodes and int(mod_nodes[0]["attrs"]["fmod"]) == 1


def _import_and_eval(path, feeds):
    sym2, args, _ = mx_onnx.import_model(path)
    out = sym2.eval(**{k: mx.nd.array(v) for k, v in feeds.items()}, **args)
    return (out[0] if isinstance(out, list) else out).asnumpy()


def test_unsqueeze_multi_axis_import():
    """ONNX Unsqueeze with several axes (attribute AND axes-input forms)
    must expand every axis, not silently use axes[0]."""
    src = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    d = tempfile.mkdtemp()

    # attribute form (opset < 13)
    g = proto.graph([proto.node("Unsqueeze", ["x"], ["y"], axes=[0, 3])],
                    "g", [], [proto.value_info("x", onp.float32, (2, 3))],
                    [proto.value_info("y", onp.float32, None)])
    p1 = os.path.join(d, "attr.onnx")
    open(p1, "wb").write(proto.model(g))
    got = _import_and_eval(p1, {"x": src})
    onp.testing.assert_array_equal(got, src.reshape(1, 2, 3, 1))

    # axes-as-input form (opset >= 13)
    g2 = proto.graph(
        [proto.node("Unsqueeze", ["x", "ax"], ["y"])], "g",
        [proto.tensor("ax", onp.asarray([0, 3], onp.int64))],
        [proto.value_info("x", onp.float32, (2, 3))],
        [proto.value_info("y", onp.float32, None)])
    p2 = os.path.join(d, "inp.onnx")
    open(p2, "wb").write(proto.model(g2))
    got2 = _import_and_eval(p2, {"x": src})
    onp.testing.assert_array_equal(got2, src.reshape(1, 2, 3, 1))
