"""Module / BucketingModule / export tests (parity model:
tests/python/unittest/test_module.py, train/test_mlp.py,
train/test_bucketing.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _toy_problem(n=512, d=16, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = np.argmax(X @ W, axis=1).astype(np.float32)
    return X, Y


def _mlp_sym(hidden=32, classes=3):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                                name="softmax")


def test_module_fit_converges():
    X, Y = _toy_problem()
    it = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym())
    mod.fit(it, num_epoch=8,
            optimizer_params=(("learning_rate", 0.5),
                              ("rescale_grad", 1.0 / 64)))
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc


def test_module_predict_and_outputs():
    X, Y = _toy_problem(n=128)
    it = mx.io.NDArrayIter(X, Y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym())
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params(mx.init.Uniform(0.1))
    preds = mod.predict(it)
    assert preds.shape == (128, 3)
    np.testing.assert_allclose(preds.asnumpy().sum(axis=1),
                               np.ones(128), rtol=1e-4)


def test_module_checkpoint_round_trip(tmp_path):
    X, Y = _toy_problem(n=128)
    it = mx.io.NDArrayIter(X, Y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym())
    mod.fit(it, num_epoch=2,
            optimizer_params=(("learning_rate", 0.1),
                              ("rescale_grad", 1.0 / 32)))
    ref = dict(mod.score(it, "acc"))["accuracy"]
    prefix = str(tmp_path / "toy")
    mod.save_checkpoint(prefix, 2)
    mod2 = mx.mod.Module.load(prefix, 2)
    mod2.bind(it.provide_data, it.provide_label, for_training=False)
    mod2.init_params_from_preload()
    acc = dict(mod2.score(it, "acc"))["accuracy"]
    assert abs(acc - ref) < 1e-6


def test_module_fixed_params():
    X, Y = _toy_problem(n=64)
    it = mx.io.NDArrayIter(X, Y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), fixed_param_names=["fc1_weight"])
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer_params=(("learning_rate", 0.5),))
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    batch = next(iter(it))
    mod.forward_backward(batch)
    mod.update()
    np.testing.assert_array_equal(
        w_before, mod._exec.arg_dict["fc1_weight"].asnumpy())
    # non-fixed param did change
    assert not np.allclose(
        mod._exec.arg_dict["fc2_weight"].asnumpy(),
        mod._exec.arg_dict["fc2_weight"].asnumpy() * 0 + w_before.mean())


def test_bucketing_module():
    """Variable-length inputs via per-bucket executables sharing weights
    (parity: bucketing_module.py:40; test model: train/test_bucketing.py)."""
    from mxnet_tpu.io.io import DataBatch, DataDesc

    vocab, emb, classes = 20, 8, 2
    rng = np.random.RandomState(0)

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=emb,
                                 name="embed")
        flat = mx.sym.Flatten(embed, name=f"flat{seq_len}")
        fc = mx.sym.FullyConnected(flat, num_hidden=classes, name="fc")
        sm = mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                                  name="softmax")
        return sm, ("data",), ("softmax_label",)

    # NOTE: fc weight depends on seq_len, so share only embed weights via
    # the bucketing contract: reference RNN buckets share time-invariant
    # params. Use a pooled representation to keep fc shape fixed instead.
    def sym_gen_pooled(seq_len):
        data = mx.sym.var("data")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=emb,
                                 name="embed")
        pooled = embed.mean(axis=1)
        fc = mx.sym.FullyConnected(pooled, num_hidden=classes, name="fc")
        sm = mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                                  name="softmax")
        return sm, ("data",), ("softmax_label",)

    bmod = mx.mod.BucketingModule(sym_gen_pooled, default_bucket_key=10)
    batch_size = 16

    def make_batch(seq_len):
        x = rng.randint(0, vocab, (batch_size, seq_len)).astype(np.float32)
        y = (x.sum(axis=1) % classes).astype(np.float32)
        return DataBatch(
            data=[mx.nd.array(x)], label=[mx.nd.array(y)], pad=0, index=None,
            provide_data=[DataDesc("data", (batch_size, seq_len))],
            provide_label=[DataDesc("softmax_label", (batch_size,))],
            bucket_key=seq_len)

    bmod.bind([DataDesc("data", (batch_size, 10))],
              [DataDesc("softmax_label", (batch_size,))])
    bmod.init_params(mx.init.Uniform(0.1))
    bmod.init_optimizer(optimizer_params=(("learning_rate", 0.1),))
    for seq_len in (10, 5, 7, 10, 5):
        batch = make_batch(seq_len)
        bmod.forward(batch, is_train=True)
        bmod.backward()
        bmod.update()
        assert bmod.get_outputs()[0].shape == (batch_size, classes)
    assert set(bmod._buckets) == {10, 5, 7}
    # embed weight is shared storage across buckets (identical handle)
    e10 = bmod._buckets[10]._exec.arg_dict["embed_weight"]
    e5 = bmod._buckets[5]._exec.arg_dict["embed_weight"]
    assert e10 is e5


def test_gluon_export_symbolblock_import(tmp_path):
    np.random.seed(0)
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1), gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"), gluon.nn.Flatten(),
            gluon.nn.Dense(5))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.rand(2, 3, 6, 6).astype(np.float32))
    y_ref = net(x).asnumpy()
    prefix = str(tmp_path / "net")
    net.export(prefix, epoch=7)
    sb = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                   prefix + "-0007.params")
    y2 = sb(x).asnumpy()
    np.testing.assert_allclose(y_ref, y2, rtol=1e-5, atol=1e-6)


def test_parameter_var():
    p = gluon.Parameter("w", shape=(3, 4))
    v = p.var()
    assert v.name == "w"
    assert v.list_arguments() == ["w"]


def test_module_input_grads():
    X, Y = _toy_problem(n=32)
    it = mx.io.NDArrayIter(X, Y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym())
    mod.bind(it.provide_data, it.provide_label, inputs_need_grad=True)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer()
    mod.forward_backward(next(iter(it)))
    (g,) = mod.get_input_grads()
    assert g.shape == (32, 16)
    assert float(np.abs(g.asnumpy()).max()) > 0


def test_symbolblock_trains_with_autograd():
    """Imported SymbolBlock parameters must receive gradients through the
    tape (reference: SymbolBlock runs through the ordinary CachedOp path)."""
    from mxnet_tpu import autograd

    sym = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4, name="fc")
    sb = gluon.SymbolBlock(sym, [mx.sym.var("data")])
    sb.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.rand(2, 6).astype(np.float32))
    trainer = gluon.Trainer(sb.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    w = sb.collect_params()["fc_weight"]
    w_before = None
    with autograd.record():
        loss = (sb(x) ** 2).sum()
    w_before = w.data().asnumpy().copy()
    loss.backward()
    assert float(np.abs(w.grad().asnumpy()).max()) > 0
    trainer.step(2)
    assert not np.allclose(w_before, w.data().asnumpy())


def test_symbolblock_without_params_defers_then_infers():
    sym = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=5, name="fc")
    sb = gluon.SymbolBlock(sym, "data")  # bare-string input accepted
    sb.initialize()
    out = sb(mx.nd.ones((3, 7)))
    assert out.shape == (3, 5)
    assert sb.collect_params()["fc_weight"].shape == (5, 7)


def test_frozen_weight_exports_as_argument():
    """grad_req='null' on a user weight must NOT make it an aux state in a
    traced graph (aux tracks differentiable=False, i.e. BatchNorm stats)."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4), gluon.nn.BatchNorm())
    net.initialize()
    net(mx.nd.ones((2, 3)))
    net.collect_params(".*weight").setattr("grad_req", "null")
    sym = net._trace_symbol()
    assert any(n.endswith("weight") for n in sym.list_arguments())
    assert sorted(sym.list_auxiliary_states()) == sorted(
        n for n in net.collect_params() if "running" in n)


def test_set_data_preserves_device_sharding():
    """set_data must keep existing placement (device AND sharding)."""
    import jax

    p = gluon.Parameter("w", shape=(4, 4))
    p.initialize(ctx=mx.cpu())
    dev_before = next(iter(p.data()._data.devices()))
    p.set_data(np.ones((4, 4), np.float32))
    assert next(iter(p.data()._data.devices())) == dev_before
    np.testing.assert_allclose(p.data().asnumpy(), 1.0)


def test_module_multi_context_data_parallel():
    """Module(context=[...]) runs ONE GSPMD executable over a dp mesh of
    the group (the reference's DataParallelExecutorGroup workflow,
    executor_group.py:144): gradients match the single-device run and
    training converges."""
    X, Y = _toy_problem()
    ctxs = [mx.cpu(i) for i in range(4)]

    def run(ctx):
        mx.random.seed(7)
        it = mx.io.NDArrayIter(X, Y, batch_size=64,
                               label_name="softmax_label")
        mod = mx.mod.Module(_mlp_sym(), context=ctx)
        mod.bind(it.provide_data, it.provide_label)
        mod.init_params(mx.init.Uniform(0.1))
        batch = next(iter(it))
        mod.forward(batch, is_train=True)
        mod.backward()
        return {n: g.asnumpy() for n, g in mod._exec.grad_dict.items()}

    g_single = run(mx.cpu(0))
    g_multi = run(ctxs)
    assert set(g_single) == set(g_multi)
    for name in g_single:
        np.testing.assert_allclose(g_multi[name], g_single[name],
                                   rtol=2e-4, atol=1e-5)

    # end-to-end: fit over the group converges like the reference demo
    it = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), context=ctxs)
    mod.fit(it, num_epoch=8,
            optimizer_params=(("learning_rate", 0.5),
                              ("rescale_grad", 1.0 / 64)))
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc


def test_executor_reshape_keeps_context_group():
    """reshape on a multi-context executor preserves the dp mesh; uneven
    batches warn once and replicate instead of silently degrading."""
    import warnings

    ctxs = [mx.cpu(i) for i in range(4)]
    sym = _mlp_sym()
    exe = sym.simple_bind(ctxs, data=(64, 16), softmax_label=(64,))
    assert exe._mesh is not None
    new = exe.reshape(data=(32, 16), softmax_label=(32,))
    assert new._mesh is not None and new._mesh.size("dp") == 4
    # uneven batch -> one warning, replicated run still correct
    exe2 = sym.simple_bind(ctxs, data=(10, 16), softmax_label=(10,))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        exe2.forward(is_train=False, data=mx.nd.ones((10, 16)))
        exe2.forward(is_train=False, data=mx.nd.ones((10, 16)))
    msgs = [str(x.message) for x in w if "not divisible" in str(x.message)]
    assert len(msgs) == 1, msgs
    assert exe2.outputs[0].shape == (10, 3)


def test_executor_argdict_feed_hint_and_scalar_cotangent():
    """Writing batches into arg_dict on a mesh executor hints once about
    kwargs feeding; scalar-output backward does not burn the uneven-batch
    warning (executor.py _place warn_uneven)."""
    import warnings

    ctxs = [mx.cpu(i) for i in range(4)]
    data = mx.sym.var("data")
    loss = mx.sym.make_loss(mx.sym.sum(data * mx.sym.var("w")))
    exe = loss.simple_bind(ctxs, grad_req={"w": "write"},
                           data=(8, 4), w=(8, 4))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        exe.arg_dict["data"][:] = mx.nd.ones((8, 4))
        exe.forward(is_train=True)
        exe.backward()  # scalar output -> replicated cotangent, no warning
    hints = [str(x.message) for x in w if "arg_dict" in str(x.message)]
    uneven = [str(x.message) for x in w if "not divisible" in str(x.message)]
    assert len(hints) == 1, hints
    assert not uneven, uneven
    np.testing.assert_allclose(exe.grad_dict["w"].asnumpy(),
                               np.ones((8, 4)), rtol=1e-5)


def test_module_multi_context_batchnorm_aux():
    """BN running stats update correctly when Module runs over a ctx
    group (mesh-resident aux writeback in executor.py forward)."""
    X, Y = _toy_problem(n=128)
    it = mx.io.NDArrayIter(X, Y, batch_size=32, label_name="softmax_label")
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(4)])
    mod.fit(it, num_epoch=4,
            optimizer_params=(("learning_rate", 0.2),
                              ("rescale_grad", 1.0 / 32)))
    _, aux = mod.get_params()
    mean = aux["bn1_moving_mean"].asnumpy()
    assert np.abs(mean).max() > 1e-3, "BN stats never updated under mesh"
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc
