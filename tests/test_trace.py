"""Gang-wide tracing plane (PR 12): propagated spans, fleet metric
aggregation, straggler detection, merged multi-rank Perfetto traces.

Headline guarantees under test:

* span correctness: nesting through the per-thread stack, propagated
  trace context, request-id uniqueness under concurrent submits;
* the serving pipeline commits a five-phase per-request breakdown
  (queue_wait / batch_collect / h2d / compute / respond) available on
  ``ServingFuture.breakdown()``, in the HTTP response (with the
  ``X-Request-Id`` propagated end to end) and in ``tools/loadgen.py``'s
  ``phase_breakdown`` report — cross-checked against
  ``serving.stats()`` percentiles;
* fleet aggregation: rank telemetry shards round-trip atomically, torn
  or partial shards are SKIPPED at merge, and the ``mxtpu_fleet_*``
  counter sums agree exactly with the per-rank scrapes;
* straggler detection: the cross-rank skew verdict flags a seeded slow
  rank, persistence requires consecutive NEW common steps, and the
  ``gang.straggler`` flight event is recorded once per episode;
* merged traces: clock-offset alignment preserves per-rank event order
  (monotonicity), and the merged ``trace.json`` validates against the
  Chrome trace-event schema with per-rank lanes;
* the overhead contract: tracing OFF is one module-global check per
  hook — ``opperf --dispatch`` and the serving predict path stay within
  noise of tracing-on (perf-marked A/B gate, like PR 7/PR 9's);
* the end-to-end drill: a 2-rank supervised run under load produces one
  fleet scrape whose sums agree with the per-rank scrapes, a straggler
  detection naming the delay-injected rank 1, and a merged trace with
  per-rank lanes and a serving request span showing all five phases.
"""
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, serving
from mxnet_tpu.telemetry import export, fleet, flight, registry, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PHASES = trace.REQUEST_PHASES


def _metric(text, name, **labels):
    pat = name + (r"\{" if labels else r"[ {]")
    for ln in text.splitlines():
        if not re.match(pat, ln):
            continue
        if all(f'{k}="{v}"' in ln for k, v in labels.items()):
            return float(ln.rsplit(" ", 1)[1])
    return None


def small_server(name="tr", seed=11, dim=6, buckets=(2,), max_wait_ms=1.0):
    mx.random.seed(seed)
    net = gluon.nn.Dense(4, in_units=dim)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, dim)))
    cont = serving.ModelContainer()
    cont.add_block(name, net, example_shape=(dim,), buckets=buckets)
    srv = serving.ModelServer(cont, max_wait_ms=max_wait_ms).start()
    srv.warmup()
    return srv


# ------------------------------------------------------------------ spans ---

def test_span_nesting_and_context():
    trace.clear()
    with trace.context("job-1"):
        with trace.span("outer") as outer:
            with trace.span("inner"):
                time.sleep(0.002)
    spans = {s["name"]: s for s in trace.tail()}
    assert spans["inner"]["parent"] == outer.span_id
    assert spans["outer"]["parent"] is None
    assert spans["inner"]["trace"] == spans["outer"]["trace"] == "job-1"
    assert spans["outer"]["dur_ms"] >= spans["inner"]["dur_ms"] > 0
    # context is scoped: outside the with-block nothing is bound
    assert trace.get_context() is None


def test_span_ring_bounded_and_configure():
    prev = trace.configure(16)
    try:
        for i in range(50):
            trace.commit(f"s{i}", time.monotonic(), 0.1)
        assert len(trace.tail()) == 16
        assert trace.tail()[-1]["name"] == "s49"
        # 0 disables: hooks become a single check, commits drop
        trace.configure(0)
        assert not trace.enabled()
        assert trace.commit("off", time.monotonic(), 0.1) is None
        assert trace.tail() == []
    finally:
        trace.configure(prev)


def test_request_id_uniqueness_under_concurrent_submits():
    """Request ids are minted from a GIL-atomic counter: concurrent
    submitters can never collide (and a served burst keeps one id per
    request end to end)."""
    ids, lock = set(), threading.Lock()

    def mint(n):
        got = [trace.new_request_id() for _ in range(n)]
        with lock:
            ids.update(got)

    threads = [threading.Thread(target=mint, args=(200,))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(ids) == 8 * 200

    srv = small_server("uniq", seed=3)
    try:
        futs = []

        def submit_some(tid):
            for i in range(10):
                futs.append(srv.submit(
                    "uniq", np.zeros((1, 6), np.float32)))

        workers = [threading.Thread(target=submit_some, args=(t,))
                   for t in range(4)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        for f in futs:
            f.result(10.0)
        rids = [f.request_id for f in futs]
        assert None not in rids and len(set(rids)) == len(rids)
    finally:
        srv.drain(timeout=10.0)
        srv.stop()


# ------------------------------------------------------- serving pipeline ---

def test_serving_request_span_five_phases():
    trace.clear()
    srv = small_server("fp", seed=5)
    try:
        fut = srv.submit("fp", np.zeros((1, 6), np.float32))
        fut.result(10.0)
        bd = fut.breakdown()
        assert bd is not None and bd["request_id"] == fut.request_id
        for k in PHASES:
            assert isinstance(bd[f"{k}_ms"], float) \
                and bd[f"{k}_ms"] >= 0.0, (k, bd)
        # the phases can never sum past the measured total
        assert sum(bd[f"{k}_ms"] for k in PHASES) \
            <= bd["total_ms"] * 1.05 + 0.5
        spans = trace.tail()
        req = [s for s in spans if s["kind"] == "request"
               and s["trace"] == fut.request_id]
        assert len(req) == 1 and req[0]["attrs"]["rows"] == 1
        children = [s for s in spans if s["kind"] == "phase"
                    and s["trace"] == fut.request_id]
        assert sorted(c["name"] for c in children) == sorted(PHASES)
        assert all(c["parent"] == req[0]["seq"] for c in children)
    finally:
        srv.drain(timeout=10.0)
        srv.stop()


def test_http_front_end_propagates_request_id_and_phases():
    srv = small_server("hp", seed=7)
    front = serving.HttpFrontEnd(srv).start()
    try:
        req = urllib.request.Request(
            front.url + "/v1/models/hp:predict",
            data=json.dumps(
                {"data": np.zeros((1, 6)).tolist()}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "caller-id-7"})
        with urllib.request.urlopen(req, timeout=10.0) as r:
            body = json.loads(r.read())
            assert r.headers.get("X-Request-Id") == "caller-id-7"
        assert body["request_id"] == "caller-id-7"
        for k in PHASES:
            assert body["phases"][k] is not None
        assert body["phases"]["total_ms"] > 0
        # the span ring keyed the whole pipeline on the caller's id
        kinds = {s["kind"] for s in trace.tail()
                 if s["trace"] == "caller-id-7"}
        assert kinds == {"request", "phase"}
        # without the header an id is minted and echoed
        req = urllib.request.Request(
            front.url + "/v1/models/hp:predict",
            data=json.dumps(
                {"data": np.zeros((1, 6)).tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10.0) as r:
            body2 = json.loads(r.read())
            assert r.headers.get("X-Request-Id") == body2["request_id"]
        assert body2["request_id"] != "caller-id-7"
    finally:
        front.close()
        srv.drain(timeout=10.0)
        srv.stop()


def test_loadgen_phase_breakdown_cross_checks_server_stats():
    """Satellite: loadgen's JSON line carries p50/p99 per phase from the
    spans, consistent with the server's own latency percentiles."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import loadgen

    rep = loadgen.run_inproc(duration=1.0, mode="closed", concurrency=4,
                             models=1, dim=8)
    assert rep["completed"] > 0 and rep["errors"] == 0
    pb = rep["phase_breakdown"]
    assert pb is not None and rep["traced_requests"] > 0
    for k in PHASES + ("total",):
        assert k in pb and pb[k]["p50_ms"] >= 0.0 \
            and pb[k]["p99_ms"] >= pb[k]["p50_ms"], (k, pb)
    # cross-check against serving.stats(): the span total measures the
    # same submit->fulfil interval the server's latency ring does
    stats = next(iter(rep["server_stats"].values()))
    assert stats["p50_ms"] is not None
    assert abs(pb["total"]["p50_ms"] - stats["p50_ms"]) \
        <= max(5.0, stats["p50_ms"] * 1.0), (pb["total"], stats)
    assert pb["total"]["p99_ms"] <= max(10.0, stats["p99_ms"] * 3.0)
    # the phase split accounts for (almost all of) the measured total
    phase_p50_sum = sum(pb[k]["p50_ms"] for k in PHASES)
    assert phase_p50_sum <= pb["total"]["p99_ms"] * 1.5 + 1.0


# ------------------------------------------------------------ rank shards ---

def _synthetic_shard(rank, *, generation=1, t_wall=None, t_mono=None,
                     counters=(), gauges=(), steps=(), spans=(),
                     flights=()):
    metrics = {}
    for name, labels, series in counters:
        metrics[name] = {"kind": "counter", "help": "", "labels": labels,
                         "series": [{"labels": lv, "value": v}
                                    for lv, v in series]}
    for name, labels, series in gauges:
        metrics[name] = {"kind": "gauge", "help": "", "labels": labels,
                         "series": [{"labels": lv, "value": v}
                                    for lv, v in series]}
    return {"version": 1, "rank": rank, "generation": generation,
            "pid": 1000 + rank, "seq": 1,
            "t_wall": time.time() if t_wall is None else t_wall,
            "t_mono": time.monotonic() if t_mono is None else t_mono,
            "metrics": metrics, "steps": list(steps),
            "spans": list(spans), "flight": list(flights)}


def test_shard_write_read_roundtrip(tmp_path):
    path = fleet.write_shard(tmp_path, rank=0, generation=3)
    assert os.path.basename(path) == "telemetry-rank-0.json"
    shards = fleet.read_shards(tmp_path)
    assert set(shards) == {0}
    sh = shards[0]
    assert sh["generation"] == 3 and sh["pid"] == os.getpid()
    assert isinstance(sh["metrics"], dict) and "t_mono" in sh
    # generation filter
    assert fleet.read_shards(tmp_path, generation=2) == {}
    assert set(fleet.read_shards(tmp_path, generation=3)) == {0}
    assert fleet.shard_ages(tmp_path)[0] < 60.0


def test_torn_and_partial_shards_skipped_at_merge(tmp_path):
    good = _synthetic_shard(0, spans=[
        {"seq": 1, "name": "s", "kind": "span", "trace": None,
         "parent": None, "t0": 1.0, "dur_ms": 2.0, "lane": 1}])
    with open(fleet.shard_path(tmp_path, 0), "w") as f:
        json.dump(good, f)
    # torn: truncated mid-object (a writer died between open and replace)
    with open(fleet.shard_path(tmp_path, 1), "w") as f:
        f.write(json.dumps(_synthetic_shard(1))[:40])
    # partial: parseable JSON but missing the clock pair
    with open(fleet.shard_path(tmp_path, 2), "w") as f:
        json.dump({"rank": 2, "spans": []}, f)
    # not even json
    with open(fleet.shard_path(tmp_path, 3), "w") as f:
        f.write("\x00\x01 garbage")
    shards = fleet.read_shards(tmp_path)
    assert set(shards) == {0}
    events = trace.merged_events(shards)
    assert {e["pid"] for e in events} == {0}


def test_fleet_counter_sums_and_straggler_gauges(tmp_path):
    mk = lambda r, total, ms: _synthetic_shard(
        r,
        counters=[("mxtpu_ttest_requests_total", ["outcome"],
                   [({"outcome": "completed"}, total)])],
        gauges=[("mxtpu_step_time_ms", [], [({}, ms)])],
        steps=[{"step": s, "duration_ms": ms,
                "phases": {"sync": ms * 0.1}} for s in (1, 2, 3)])
    for rank, total, ms in ((0, 5.0, 10.0), (1, 7.0, 40.0)):
        with open(fleet.shard_path(tmp_path, rank), "w") as f:
            json.dump(mk(rank, total, ms), f)
    fleet.install(tmp_path)
    try:
        text = export.render_prometheus()
    finally:
        fleet.uninstall()
    assert _metric(text, "mxtpu_fleet_ranks") == 2
    assert _metric(text, "mxtpu_fleet_ttest_requests_total",
                   outcome="completed") == 12.0
    # curated per-rank gauge re-export
    assert _metric(text, "mxtpu_fleet_step_time_ms", rank="0") == 10.0
    assert _metric(text, "mxtpu_fleet_step_time_ms", rank="1") == 40.0
    # straggler gauges ride the same scrape (single update: flagged,
    # not yet persistent)
    assert _metric(text, "mxtpu_gang_straggler_rank") == 1
    assert _metric(text, "mxtpu_gang_straggler_skew_ms") == 30.0
    assert _metric(text, "mxtpu_gang_straggler_score", rank="1") == 4.0
    assert _metric(text, "mxtpu_gang_straggler_persistent") == 0


def test_straggler_detector_persistence_and_flight_event(tmp_path):
    det = fleet.StragglerDetector(factor=1.5, persist=3)
    flight.clear()

    def shards(upto, slow_ms=80.0):
        out = {}
        for rank in (0, 1):
            ms = slow_ms if rank == 1 else 20.0
            out[rank] = _synthetic_shard(rank, steps=[
                {"step": s, "duration_ms": ms,
                 "phases": {"sync": 2.0 if rank == 0 else 0.5}}
                for s in range(1, upto + 1)])
        return out

    v = det.update(shards(1))
    assert v["status"] == "ok" and v["slowest_rank"] == 1
    assert not v["persistent"] and v["streak"] == 1
    # re-reading UNCHANGED shards must not advance the streak
    v = det.update(shards(1))
    assert v["streak"] == 1
    v = det.update(shards(2))
    assert v["streak"] == 2 and not v["persistent"]
    v = det.update(shards(3))
    assert v["persistent"] and v["streak"] == 3
    assert det.events == 1
    ev = [e for e in flight.tail() if e["kind"] == "gang.straggler"]
    assert len(ev) == 1 and ev[0]["point"] == "rank1"
    # still persistent on the next step: the episode records only once
    det.update(shards(4))
    assert det.events == 1
    # recovery (skew gone) clears the flag and re-arms the episode
    v = det.update(shards(5, slow_ms=21.0))
    assert not v["persistent"] and v["slowest_rank"] is None
    # sync-wait share computed per rank
    assert 0 < v["per_rank"][0]["sync_share"] <= 0.15


def test_straggler_detector_degenerate_inputs():
    det = fleet.StragglerDetector()
    assert det.update({})["status"] == "insufficient-ranks"
    one = {0: _synthetic_shard(0, steps=[{"step": 1,
                                          "duration_ms": 1.0}])}
    assert det.update(one)["status"] == "insufficient-ranks"
    disjoint = {
        0: _synthetic_shard(0, steps=[{"step": 1, "duration_ms": 1.0}]),
        1: _synthetic_shard(1, steps=[{"step": 9, "duration_ms": 1.0}])}
    assert det.update(disjoint)["status"] == "no-common-steps"


# ----------------------------------------------------------- merged trace ---

def _span(seq, name, t0, dur_ms, kind="span", trace_id=None,
          parent=None, lane=1):
    return {"seq": seq, "name": name, "kind": kind, "trace": trace_id,
            "parent": parent, "t0": t0, "dur_ms": dur_ms, "lane": lane}


def test_clock_offset_alignment_is_monotone_per_rank():
    """Two ranks whose wall clocks disagree by minutes: the merge aligns
    each via its own (t_wall, t_mono) pair, so within a rank the
    original monotonic order is preserved exactly and no event lands at
    a negative timestamp."""
    shards = {
        0: _synthetic_shard(
            0, t_wall=1000.0, t_mono=50.0,
            spans=[_span(i, f"a{i}", 40.0 + i * 0.5, 1.0)
                   for i in range(6)]),
        # rank 1's wall clock is 120s ahead and its mono epoch differs
        1: _synthetic_shard(
            1, t_wall=1120.0, t_mono=9050.0,
            spans=[_span(i, f"b{i}", 9041.0 + i * 0.25, 1.0)
                   for i in range(6)]),
    }
    events = trace.merged_events(shards)
    for rank in (0, 1):
        xs = [e for e in events if e["pid"] == rank and e["ph"] == "X"]
        names = [e["name"] for e in xs]
        assert names == sorted(names, key=lambda n: int(n[1:]))
        stamps = [e["ts"] for e in xs]
        assert stamps == sorted(stamps)
        assert all(ts >= 0 for ts in stamps)
    # per-rank lanes + metadata
    assert {e["pid"] for e in events} == {0, 1}
    meta = [e for e in events if e["ph"] == "M"
            and e["name"] == "process_name"]
    assert {m["pid"] for m in meta} == {0, 1}


def _validate_chrome(payload):
    assert set(payload) >= {"traceEvents", "displayTimeUnit"}
    events = payload["traceEvents"]
    assert events
    for ev in events:
        for key in ("name", "ph", "ts", "pid", "tid"):
            assert key in ev, (key, ev)
        assert ev["ph"] in ("X", "i", "C", "M"), ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        if ev["ph"] == "i":
            assert "s" in ev
    return events


def test_merged_dump_validates_chrome_schema(tmp_path):
    rid = "req-x"
    shards = {
        0: _synthetic_shard(0, spans=[
            _span(0, "request[m]", 10.0, 5.0, kind="request",
                  trace_id=rid),
            _span(1, "queue_wait", 10.0, 1.0, kind="phase",
                  trace_id=rid, parent=0)],
            flights=[{"seq": 0, "t_mono": 10.5, "t_wall": 0.0,
                      "kind": "serving.batch", "point": "m",
                      "label": None}]),
        1: _synthetic_shard(1, spans=[
            _span(0, "trainer.step", 12.0, 30.0, kind="step",
                  trace_id="step-g1-r1-3")]),
    }
    for rank, sh in shards.items():
        with open(fleet.shard_path(tmp_path, rank), "w") as f:
            json.dump(sh, f)
    out = trace.dump(str(tmp_path / "trace.json"), run_dir=tmp_path)
    assert trace.last_dump() == out
    with open(out) as f:
        events = _validate_chrome(json.load(f))
    assert {e["pid"] for e in events} == {0, 1}
    cats = {e.get("cat") for e in events}
    assert {"trace.request", "trace.phase", "trace.step",
            "flight"} <= cats


def test_local_dump_rebases_profiler_events(tmp_path):
    from mxnet_tpu import profiler

    trace.clear()
    profiler.reset()
    profiler.set_config(filename=str(tmp_path / "p.json"))
    profiler.set_state("run")
    try:
        v = mx.nd.ones((4, 4))
        (v * 2).wait_to_read()
    finally:
        profiler.set_state("stop")
    with trace.span("local-span"):
        time.sleep(0.001)
    out = trace.dump(str(tmp_path / "local.json"))
    with open(out) as f:
        events = _validate_chrome(json.load(f))
    names = {e["name"] for e in events}
    assert "local-span" in names
    # profiler op events rode along, on the same (non-negative) timeline
    prof = [e for e in events if e.get("cat") not in
            ("flight", "__metadata") and not str(e.get("cat", ""))
            .startswith("trace.")]
    assert prof and all(e["ts"] >= 0 for e in prof)
    profiler.reset()


# -------------------------------------------------------------- satellites --

def test_diagnose_tracing_section(capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import diagnose

    out = diagnose.check_tracing()
    text = capsys.readouterr().out
    assert "MXNET_TPU_TRACE" in text and "straggler" in text
    assert "effective" in out and out["effective"]["ring"] >= 0
    report = diagnose.collect(echo=False)
    assert "tracing" in report
    assert "straggler" in report["tracing"]


@pytest.mark.perf
def test_tracing_off_overhead_within_noise():
    """Satellite: tracing OFF must cost one module-global check — both
    the eager dispatch path (opperf --dispatch) and a serving batch stay
    within noise of tracing-on (the PR 7/PR 9-style A/B gate)."""
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    import opperf

    kw = dict(chain_len=8, bulk=8, size=256, iters=60, warmup=10,
              trials=3)
    on = opperf.bench_dispatch(**kw)
    prev = trace.configure(0)
    try:
        off = opperf.bench_dispatch(**kw)
    finally:
        trace.configure(prev)
    for k in ("unbulked_ns_per_op", "bulked_ns_per_op"):
        assert on[k] <= off[k] * 1.6 + 2000.0, (k, on, off)

    # one serving batch path: N sequential predicts traced vs untraced
    srv = small_server("perf", seed=13)
    x = np.zeros((1, 6), np.float32)
    try:
        def drive(n=40):
            t0 = time.perf_counter()
            for _ in range(n):
                srv.predict("perf", x, timeout=10.0)
            return (time.perf_counter() - t0) / n * 1e3
        drive(10)  # warm
        with_trace = drive()
        prev = trace.configure(0)
        try:
            drive(10)
            without = drive()
        finally:
            trace.configure(prev)
        # generous: CPU CI timing is noisy; the real per-request cost is
        # a handful of monotonic() reads + ring appends
        assert with_trace <= without * 1.75 + 2.0, (with_trace, without)
    finally:
        srv.drain(timeout=10.0)
        srv.stop()


# -------------------------------------------------- end-to-end gang drill ---

def test_gang_tracing_drill(tmp_path):
    """The PR 12 acceptance drill: a supervised 2-rank gang under load
    (trainer steps on both ranks + serving on rank 0, rank 1 slowed by
    a seeded trainer.step delay) must produce

    (a) ONE fleet scrape whose ``mxtpu_fleet_*`` counter sums agree
        exactly with the per-rank scrapes,
    (b) a live straggler detection naming rank 1 on the supervisor
        endpoint (persistent + gang.straggler flight event), and
    (c) a merged ``trace.json`` that validates against the chrome
        trace-event schema with per-rank lanes and at least one serving
        request span carrying all five phases."""
    child = os.path.join(REPO, "tests", "_gang_child.py")
    launch = os.path.join(REPO, "tools", "launch.py")
    run_dir = str(tmp_path / "run")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           "GC_BASE_DEVICES": "1", "GC_TOTAL": "16", "GC_EPOCH": "16",
           "GC_STEP_SLEEP": "0.03", "GC_STRAGGLE_RANK": "1",
           "GC_STRAGGLE_MS": "300", "GC_METRICS": "1", "GC_SERVE": "1",
           "GC_CKPT_DIR": str(tmp_path / "ckpt"),
           "MXNET_TPU_GANG_BEAT": "0.2"}
    for k in ("MXNET_TPU_FAULTS", "XLA_FLAGS", "MXTPU_GANG_DIR",
              "MXTPU_COORDINATOR", "MXTPU_NUM_WORKERS",
              "MXTPU_WORKER_ID", "MXTPU_GANG_GENERATION"):
        env.pop(k, None)
    proc = subprocess.Popen(
        [sys.executable, launch, "--supervise", "-n", "2",
         "--run-dir", run_dir, "--max-restarts", "0", "--poll", "0.05",
         "--metrics-port", "0", sys.executable, child],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    lines, errs = [], []
    threading.Thread(target=lambda: lines.extend(proc.stdout),
                     daemon=True).start()
    threading.Thread(target=lambda: errs.extend(proc.stderr),
                     daemon=True).start()
    try:
        url = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and url is None:
            for ln in list(lines):
                m = re.search(r"gang metrics: (http://\S+)/metrics", ln)
                if m:
                    url = m.group(1)
            time.sleep(0.1)
        assert url, "supervisor never announced its metrics endpoint"

        # (b) poll the ONE supervisor endpoint for the live straggler
        # verdict while the gang runs
        live = None
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline and proc.poll() is None:
            try:
                text = urllib.request.urlopen(
                    url + "/metrics", timeout=5).read().decode()
            except OSError:
                time.sleep(0.2)
                continue
            if _metric(text, "mxtpu_gang_straggler_rank") == 1 \
                    and _metric(text,
                                "mxtpu_gang_straggler_persistent") == 1:
                live = text
                break
            time.sleep(0.2)
        rc = proc.wait(timeout=240.0)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)
    assert rc == 0, f"gang exited {rc}:\n{''.join(errs[-30:])}"
    assert live is not None, \
        f"straggler never flagged live:\n{''.join(lines[-20:])}"
    assert _metric(live, "mxtpu_fleet_ranks") == 2
    assert _metric(live, "mxtpu_gang_straggler_score", rank="1") >= 1.5
    assert _metric(live, "mxtpu_flight_events_total",
                   kind="gang.straggler") >= 1

    # (a) fleet sums == per-rank scrape sums, exactly: each rank froze
    # its own /metrics text + a final shard at exit; re-render the
    # fleet view from the surviving shards and compare counters
    scrapes = []
    for rank in (0, 1):
        with open(os.path.join(run_dir,
                               f"rank-scrape-{rank}.txt")) as f:
            scrapes.append(f.read())
    registry.reset()
    fleet.install(run_dir)
    try:
        fleet_text = export.render_prometheus()
    finally:
        fleet.uninstall()
    checks = [("mxtpu_train_steps_total", {}),
              ("mxtpu_flight_events_total", {"kind": "step.end"}),
              ("mxtpu_serving_requests_total",
               {"model": "gangserve", "outcome": "completed"})]
    for name, labels in checks:
        per_rank = [_metric(s, name, **labels) or 0.0 for s in scrapes]
        fname = "mxtpu_fleet_" + name[len("mxtpu_"):]
        got = _metric(fleet_text, fname, **labels)
        assert got == sum(per_rank) > 0, (name, per_rank, got)
    # both ranks trained every step; only rank 0 served
    assert _metric(fleet_text, "mxtpu_fleet_train_steps_total") == 32.0
    assert _metric(fleet_text, "mxtpu_fleet_serving_requests_total",
                   model="gangserve", outcome="completed") == 4.0

    # (c) the merged trace: chrome-schema-valid, per-rank lanes, and a
    # serving request span showing all five phases
    out = trace.dump(str(tmp_path / "trace.json"), run_dir=run_dir)
    with open(out) as f:
        events = _validate_chrome(json.load(f))
    assert {0, 1} <= {e["pid"] for e in events}
    meta = [e for e in events if e["ph"] == "M"
            and e["name"] == "process_name"]
    assert {m["pid"] for m in meta} >= {0, 1}
    reqs = [e for e in events if e.get("cat") == "trace.request"
            and e["pid"] == 0]
    assert reqs, "no serving request span in the merged trace"
    rid = reqs[0]["args"]["trace"]
    phases = {e["name"] for e in events
              if e.get("cat") == "trace.phase"
              and e.get("args", {}).get("trace") == rid}
    assert phases >= set(PHASES), phases
    # step spans from BOTH ranks landed in their lanes
    for rank in (0, 1):
        assert any(e.get("cat") == "trace.step" and e["pid"] == rank
                   for e in events), rank
