"""Unified compile service (mxnet_tpu/compile.py): canonical keys,
two-level (memory + persistent disk) caching, AOT warmup manifests,
per-site metrics agreement with distcheck, corruption/fingerprint
fallback, and the eager-dispatch perf guard."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile as C

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_compile_child.py")


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    """Point the service at a fresh disk cache; restore memory-only mode
    (the suite default) afterwards."""
    d = str(tmp_path / "cache")
    monkeypatch.setenv("MXNET_TPU_CACHE_DIR", d)
    C.configure(cache_dir=d)
    yield d
    C.configure(cache_dir=None)


def _jnp_ones(shape):
    import jax.numpy as jnp

    return jnp.ones(shape, jnp.float32)


# ------------------------------------------------------------- in-memory ---

def test_service_hit_miss_accounting():
    C.reset_stats()
    fn = C.jit(lambda x: x * 2 + 1, site="svc-test", token=("acct", 1))
    x = _jnp_ones((4, 4))
    for _ in range(5):
        fn(x).block_until_ready()  # noqa: unbounded-sync — test code
    st = C.stats()["svc-test"]
    assert st["misses"] == 1 and st["compiles"] == 1
    assert st["hits"] == 4
    assert st["compile_ms"] > 0
    # a new signature (shape change) is a fresh miss, not a hit
    fn(_jnp_ones((2, 2))).block_until_ready()  # noqa: unbounded-sync
    st = C.stats()["svc-test"]
    assert st["misses"] == 2 and st["hits"] == 4


def test_signature_distinguishes_dtype_and_structure():
    import jax.numpy as jnp

    calls = []

    def f(x):
        calls.append(1)
        return x + 1

    fn = C.jit(f, site="svc-test", token=("sig", 2))
    fn(jnp.ones((3,), jnp.float32))
    fn(jnp.ones((3,), jnp.float32))
    assert len(calls) == 1  # same sig -> no retrace
    fn(jnp.ones((3,), jnp.int32))
    assert len(calls) == 2  # dtype flip -> new executable


def test_disabled_service_falls_through(monkeypatch):
    prev = C.set_enabled(False)
    try:
        fn = C.jit(lambda x: x - 1, site="svc-test", token=("off", 1))
        # env-disabled construction returns the raw jit object
        assert not isinstance(fn, C.ServiceFunction)
        out = fn(_jnp_ones((2,)))
        assert float(out.sum()) == 0.0
    finally:
        C.set_enabled(prev)
    # runtime toggle on an existing ServiceFunction bypasses accounting
    fn2 = C.jit(lambda x: x + 3, site="svc-toggle", token=("off", 2))
    C.reset_stats()
    prev = C.set_enabled(False)
    try:
        fn2(_jnp_ones((2,)))
    finally:
        C.set_enabled(prev)
    assert "svc-toggle" not in C.stats()


# ------------------------------------------------------------ disk layer ---

def test_disk_cache_roundtrip_in_process(cache_dir):
    C.reset_stats()
    fn = C.jit(lambda x: x * 5, site="svc-disk", token=("disk", 1))
    x = _jnp_ones((8,))
    assert float(fn(x)[0]) == 5.0
    st = C.stats()["svc-disk"]
    assert st["compiles"] == 1 and st["disk_hits"] == 0
    rep = C.disk_report()
    assert rep["dir"] == cache_dir and rep["entries"] >= 1
    # drop the in-memory map: the same signature must now come from disk
    C.clear_memory()
    assert float(fn(x)[0]) == 5.0
    st = C.stats()["svc-disk"]
    assert st["disk_hits"] == 1 and st["compiles"] == 1
    assert st["load_ms"] > 0


def test_disk_entries_are_crc_manifested(cache_dir):
    fn = C.jit(lambda x: x + 7, site="svc-disk", token=("crc", 1))
    fn(_jnp_ones((4,)))
    d = os.path.join(cache_dir, "exec", C.fingerprint())
    bins = [n for n in os.listdir(d) if n.endswith(".bin")]
    assert bins
    for b in bins:
        with open(os.path.join(d, b[:-4] + ".json")) as f:
            meta = json.load(f)
        # the .bin is framed (magic + embedded CRC meta + payload) so a
        # load never depends on the bin/json pairing; the sidecar must
        # mirror the embedded meta and size the raw payload
        with open(os.path.join(d, b), "rb") as f:
            emeta, payload = C._unframe(f.read())
        assert emeta == meta
        assert meta["size"] == len(payload)
        assert meta["fingerprint"] == C.fingerprint()
        assert "crc32" in meta and "site" in meta


def test_framed_entry_survives_mismatched_sidecar(cache_dir):
    """The concurrent-cold-writer race (a serving fleet's replicas
    warming the same ladder): interleaved renames can pair one writer's
    .bin with the OTHER writer's .json, and serialized executables are
    not byte-identical across processes. The framed .bin self-verifies,
    so a mixed pair still loads — zero recompiles, zero corrupt."""
    C.reset_stats()
    fn = C.jit(lambda x: x - 3, site="svc-mixed", token=("mix", 1))
    x = _jnp_ones((4,))
    fn(x)
    d = os.path.join(cache_dir, "exec", C.fingerprint())
    jsons = [n for n in os.listdir(d) if n.endswith(".json")]
    assert jsons
    for n in jsons:  # simulate the other writer's sidecar landing last
        with open(os.path.join(d, n)) as f:
            meta = json.load(f)
        meta["crc32"] = (meta["crc32"] + 1) % (1 << 32)
        meta["size"] = meta["size"] + 17
        with open(os.path.join(d, n), "w") as f:
            json.dump(meta, f)
    C.clear_memory()
    C.reset_stats()
    out = fn(x)
    assert float(out.sum()) == float((x - 3).sum())
    st = C.stats()["svc-mixed"]
    assert st["disk_hits"] == 1 and st["compiles"] == 0
    assert st["corrupt"] == 0


def test_corrupt_entry_falls_back_to_recompile(cache_dir):
    """faults.py corrupt mode on the compile.load payload: CRC mismatch
    must silently recompile, never load a flipped executable."""
    from mxnet_tpu import faults

    C.reset_stats()
    fn = C.jit(lambda x: x * 11, site="svc-corrupt", token=("cor", 1))
    x = _jnp_ones((4,))
    fn(x)
    C.clear_memory()
    faults.configure({"compile.load": "corrupt@*"})
    try:
        out = fn(x)  # corrupted read -> CRC fallback -> recompile
    finally:
        faults.reset()
    assert float(out[0]) == 11.0
    st = C.stats()["svc-corrupt"]
    assert st["corrupt"] >= 1
    assert st["compiles"] == 2 and st["disk_hits"] == 0


def test_truncated_entry_falls_back_and_gc_prunes(cache_dir):
    C.reset_stats()
    fn = C.jit(lambda x: x - 3, site="svc-trunc", token=("tr", 1))
    x = _jnp_ones((4,))
    fn(x)
    d = os.path.join(cache_dir, "exec", C.fingerprint())
    target = None
    for n in os.listdir(d):
        if n.endswith(".bin"):
            with open(os.path.join(d, n[:-4] + ".json")) as f:
                if json.load(f)["site"] == "svc-trunc":
                    target = os.path.join(d, n)
    assert target is not None
    with open(target, "r+b") as f:
        f.truncate(10)  # torn write
    C.clear_memory()
    out = fn(x)
    assert float(out[0]) == -2.0
    st = C.stats()["svc-trunc"]
    assert st["corrupt"] >= 1 and st["compiles"] == 2
    # gc removes exactly the corrupt pair (the recompile overwrote the
    # entry, so re-corrupt first to observe the prune)
    with open(target, "r+b") as f:
        f.truncate(10)
    out = C.gc_cache()
    assert out["removed_corrupt"] >= 1


def test_fingerprint_invalidation_and_gc(cache_dir, monkeypatch):
    """A jax-version/backend change (simulated via the salt knob) makes
    old entries invisible — recompile, never cross-fingerprint load —
    and gc prunes the stale fingerprint wholesale."""
    C.reset_stats()
    fn = C.jit(lambda x: x * 13, site="svc-fp", token=("fp", 1))
    x = _jnp_ones((4,))
    fn(x)
    old_fp = C.fingerprint()
    monkeypatch.setenv("MXNET_TPU_CACHE_SALT", "new-jax-version")
    C.configure()  # re-reads env; fingerprint recomputes
    assert C.fingerprint() != old_fp
    C.clear_memory()
    fn(x)
    st = C.stats()["svc-fp"]
    assert st["compiles"] == 2 and st["disk_hits"] == 0
    rep = C.disk_report()
    assert rep["stale_entries"] >= 1  # the old-fingerprint entry
    out = C.gc_cache()
    assert out["removed_stale"] >= 1
    assert C.disk_report()["stale_entries"] == 0


# ----------------------------------------------------------- warmup / AOT --

def test_warmup_manifest_records_and_replays(cache_dir):
    C.reset_stats()
    C.clear_manifest()
    fn = C.jit(lambda x, s: x * s, site="svc-warm", token=("warm", 1))
    fn(_jnp_ones((6, 2)), 3.0)
    entries = [e for e in C.manifest() if e["site"] == "svc-warm"]
    assert len(entries) == 1
    # array leaf: shape/dtype recorded; scalar leaf: type + sample value
    spec = entries[0]["args"]
    assert spec["items"][0]["shape"] == [6, 2]
    assert spec["items"][1]["t"] == "py"
    # replay into a fresh memory state: warmup loads from disk, then the
    # first real call is a pure HIT (compiled before traffic)
    C.clear_memory()
    C.reset_stats()
    report = C.warmup(entries)
    assert report["disk"] == 1 and report["errors"] == []
    out = fn(_jnp_ones((6, 2)), 3.0)
    assert float(out[0][0]) == 3.0
    st = C.stats()["svc-warm"]
    assert st["hits"] == 1 and st["compiles"] == 0
    assert C.last_warmup()["entries"] == 1


def test_warmup_pending_until_registration(cache_dir):
    """Entries for a not-yet-registered token stay pending and replay the
    moment the site registers (lazy sites: CachedOp builds on first
    call) — the compile then happens at build, not at first traffic."""
    C.clear_manifest()
    token = ("pend", 42)
    fn = C.jit(lambda x: x + 9, site="svc-pend", token=token)
    fn(_jnp_ones((3,)))
    entries = [e for e in C.manifest() if e["site"] == "svc-pend"]
    del fn  # registration is weak: the function dies
    report = C.warmup(entries)
    assert report["pending"] == 1
    C.reset_stats()
    fn2 = C.jit(lambda x: x + 9, site="svc-pend", token=token)
    st = C.stats()["svc-pend"]
    assert st["disk_hits"] + st["compiles"] == 1  # replayed at creation
    fn2(_jnp_ones((3,)))
    assert C.stats()["svc-pend"]["hits"] == 1


def test_manifest_save_and_file_roundtrip(cache_dir, tmp_path):
    C.clear_manifest()
    fn = C.jit(lambda x: x * 2, site="svc-save", token=("save", 1))
    fn(_jnp_ones((2, 2)))
    path = C.save_manifest(str(tmp_path / "m.json"))
    with open(path) as f:
        data = json.load(f)
    assert any(e["site"] == "svc-save" for e in data)
    # cache-dir manifest auto-accumulates too (the pod cold-start source)
    with open(os.path.join(cache_dir, C.MANIFEST_FILE)) as f:
        disk_entries = json.load(f)
    assert any(e["site"] == "svc-save" for e in disk_entries)
    C.clear_memory()
    report = C.warmup(str(path))
    assert report["errors"] == []
    assert report["disk"] + report["compiled"] + report["cached"] >= 1


def test_trainer_records_manifest_and_warmup(cache_dir):
    """ShardedTrainer signatures land in the warmup manifest
    automatically, and trainer.warmup() compiles before first traffic."""
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    C.clear_manifest()
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).rand(4, 6).astype(np.float32))
    y = mx.nd.array(np.arange(4, dtype=np.float32) % 2)
    net(x)
    tr = ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                        {"learning_rate": 0.1}, mesh=DeviceMesh({"dp": 1}))
    tr.step(x, y).wait_to_read()
    assert any(e["site"] == "trainer" for e in C.manifest())
    # non-donating steps are serializable: a first trainer records +
    # persists, then an identically-configured fresh trainer warms up
    # pre-traffic and its first step is a pure service hit (donating
    # steps dispatch through jit only — the AOT call path corrupts
    # donated buffers on CPU jaxlib — and warm via the native XLA cache)
    kw = dict(mesh=DeviceMesh({"dp": 1}), donate=False)
    tr2 = ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         {"learning_rate": 0.1}, **kw)
    tr2.step(x, y).wait_to_read()
    tr2b = ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                          {"learning_rate": 0.1}, **kw)
    report = tr2b.warmup(x, y)
    assert report["errors"] == []
    assert report["disk"] + report["compiled"] + report["cached"] >= 1
    C.reset_stats()
    tr2b.step(x, y).wait_to_read()
    st = C.stats().get("trainer", {})
    assert st.get("compiles", 0) == 0, st
    # a donating trainer still records + warms (native-cache seeding),
    # and steps stably through the jit path
    tr3 = ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                         {"learning_rate": 0.1},
                         mesh=DeviceMesh({"dp": 1}))
    assert tr3.warmup(x, y)["errors"] == []
    tr3.step(x, y).wait_to_read()


# --------------------------------------------------- cross-process (disk) --

def _run_child(cache_dir):
    env = dict(os.environ)
    env["MXNET_TPU_CACHE_DIR"] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run([sys.executable, CHILD], capture_output=True,
                         text=True, timeout=280, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    for line in out.stdout.splitlines():
        if line.startswith("CHILD_REPORT "):
            return json.loads(line[len("CHILD_REPORT "):])
    raise AssertionError(f"no report in child output: {out.stdout[-800:]}")


def test_subprocess_warm_start_hits_disk_cache(tmp_path):
    """ACCEPTANCE: a second process over the same cache dir satisfies
    >=90% of compile-cache lookups (zero XLA recompiles of previously-
    seen signatures) and its compile time collapses to disk-load time."""
    d = str(tmp_path / "cache")
    cold = _run_child(d)
    warm = _run_child(d)
    ct, wt = cold["totals"], warm["totals"]
    assert ct["compiles"] > 0 and ct["disk_hits"] == 0
    # zero recompiles of previously-seen signatures
    assert wt["compiles"] == 0, warm["stats"]
    assert wt["disk_hits"] == wt["misses"]
    hit_rate = (wt["hits"] + wt["disk_hits"]) / (wt["hits"] + wt["misses"])
    assert hit_rate >= 0.90, (hit_rate, warm["stats"])
    # warm "cold-start" compile cost measurably below cold
    warm_cost = wt["compile_ms"] + wt["load_ms"]
    assert warm_cost < ct["compile_ms"] * 0.5, (warm_cost, ct)
    # every site that compiled cold got disk hits warm
    for site, st in warm["stats"].items():
        if st["misses"]:
            assert st["compiles"] == 0, (site, st)
    # the manifest accumulated for future pods
    assert warm["manifest_entries"] >= 5


# ------------------------------------------------------- metrics parity ----

def test_churn_stats_agree_with_service(monkeypatch):
    """distcheck pass-4 (recompile churn) sees the service's per-site
    traffic through the 'service' cache family, with hit/miss counts
    matching compile.stats() exactly."""
    from mxnet_tpu.analysis import distcheck as dc

    dc.track_caches(True)
    try:
        dc.reset_cache_stats()
        C.reset_stats()
        fn = C.jit(lambda x: x * 4, site="svc-churn", token=("ch", 1))
        for n in (3, 3, 3, 4, 5):  # 3 sigs, 2 repeat hits
            fn(_jnp_ones((n,)))
        svc = C.stats()["svc-churn"]
        rec = dc.cache_stats()[("service", "svc-churn")]
        assert rec["hits"] == svc["hits"] == 2
        assert rec["misses"] == svc["misses"] == 3
        assert rec["distinct_keys"] == 3
    finally:
        dc.track_caches(dc.enabled())
        dc.reset_cache_stats()


def test_profiler_compile_cache_tracks():
    from mxnet_tpu import profiler

    profiler.reset()
    profiler.set_config(profile_imperative=True, aggregate_stats=True)
    profiler.set_state("run")
    try:
        fn = C.jit(lambda x: x * 6, site="svc-prof", token=("prof", 1))
        fn(_jnp_ones((7,)))
        fn(_jnp_ones((7,)))
    finally:
        profiler.set_state("stop")
    events = profiler._events
    names = {e["name"] for e in events}
    assert "compile[svc-prof]" in names
    assert "compile_cache.service.svc-prof.misses" in names


# ------------------------------------------------------------ perf guard ---

@pytest.mark.perf
def test_dispatch_overhead_within_noise():
    """CI guard: the compile-service layer must not tax the eager per-op
    hot path — opperf --dispatch ns/op with the service on stays within
    noise of the raw-jit baseline (service bypassed)."""
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    import opperf

    kw = dict(chain_len=8, bulk=8, size=256, iters=60, warmup=10, trials=3)
    on = opperf.bench_dispatch(**kw)
    prev = C.set_enabled(False)
    try:
        off = opperf.bench_dispatch(**kw)
    finally:
        C.set_enabled(prev)
    # generous envelope: CPU CI timing is noisy; the real overhead is one
    # dict probe + small tuple build (<~2us), the guard catches order-of-
    # magnitude regressions (accidental sync, per-call disk IO, ...)
    for k in ("unbulked_ns_per_op", "bulked_ns_per_op"):
        assert on[k] <= off[k] * 1.6 + 2000.0, (k, on, off)


# ------------------------------------------------------------- satellites --

def test_bench_train_cpu_emits_compile_fields(capsys, monkeypatch):
    monkeypatch.setenv("BENCH_TRAIN_CPU_BATCH", "8")
    monkeypatch.setenv("BENCH_TRAIN_CPU_ITERS", "2")
    sys.path.insert(0, REPO)
    import bench

    bench.bench_train_cpu()
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["unit"] == "ms/step" and line["platform"] == "cpu"
    assert line["value"] > 0 and line["img_per_s"] > 0
    for field in ("compile_ms", "cache_hits", "cache_misses",
                  "cache_disk_hits"):
        assert field in line


def test_bench_warm_start_compile_time_below_cold(tmp_path):
    """ACCEPTANCE: bench.py's emitted JSON shows warm-start compile time
    measurably below cold when a cache dir is set, with the misses
    absorbed as disk hits."""
    env = dict(os.environ)
    env.update({"MXNET_TPU_CACHE_DIR": str(tmp_path / "cache"),
                "BENCH_TRAIN_CPU_BATCH": "8",
                "BENCH_TRAIN_CPU_ITERS": "2"})
    env.setdefault("JAX_PLATFORMS", "cpu")

    def run():
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--train-only"],
            capture_output=True, text=True, timeout=280, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run()
    warm = run()
    assert cold["compile_ms"] > 0 and cold["cache_disk_hits"] == 0
    assert warm["cache_disk_hits"] > 0
    assert warm["compile_ms"] < cold["compile_ms"] * 0.5, (warm, cold)


def test_diagnose_reports_compile_cache(capsys, cache_dir):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import diagnose

    fn = C.jit(lambda x: x + 2, site="svc-diag", token=("diag", 1))
    fn(_jnp_ones((3,)))
    diagnose.check_compile_cache()
    out = capsys.readouterr().out
    assert "disk cache    : " + cache_dir in out
    assert "svc-diag" in out
    assert "fingerprint" in out
    # --gc prunes a planted stale fingerprint dir
    stale = os.path.join(cache_dir, "exec", "deadbeef0000")
    os.makedirs(stale, exist_ok=True)
    with open(os.path.join(stale, "x.bin"), "wb") as f:
        f.write(b"stale")
    diagnose.check_compile_cache(gc=True)
    assert not os.path.isdir(stale)
