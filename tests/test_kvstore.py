"""KVStore tests (parity model: tests/python/unittest/test_kvstore.py —
init/push/pull aggregation semantics, update-on-kvstore, row_sparse_pull)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv_mod
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def init_kv(kv_type="local"):
    kv = kv_mod.create(kv_type)
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(KEYS, [mx.nd.zeros(SHAPE)] * len(KEYS))
    return kv


@pytest.mark.parametrize("kv_type", ["local", "device"])
def test_single_kv_pair(kv_type):
    kv = init_kv(kv_type)
    kv.push(3, mx.nd.ones(SHAPE) * 4)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.ones(SHAPE) * 4)


def test_list_kv_pair():
    kv = init_kv()
    kv.push(KEYS, [mx.nd.ones(SHAPE) * 4] * len(KEYS))
    outs = [mx.nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        assert_almost_equal(o, np.ones(SHAPE) * 4)


def test_aggregation():
    """Multiple device values pushed for one key sum (parity:
    CommDevice::Reduce)."""
    kv = init_kv()
    vals = [mx.nd.ones(SHAPE), mx.nd.ones(SHAPE) * 2, mx.nd.ones(SHAPE) * 3]
    kv.push(3, vals)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.ones(SHAPE) * 6)


def test_update_on_kvstore():
    """Optimizer-on-store: push applies the update to the stored weight
    (parity: kvstore_dist_server ApplyUpdates + Updater)."""
    kv = kv_mod.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.init(0, mx.nd.ones(SHAPE))
    kv.push(0, mx.nd.ones(SHAPE))  # grad = 1 -> w -= 0.1
    out = mx.nd.empty(SHAPE)
    kv.pull(0, out=out)
    assert_almost_equal(out, np.ones(SHAPE) * 0.9, rtol=1e-5, atol=1e-6)


def test_row_sparse_pull():
    kv = kv_mod.create("local")
    w = np.arange(20, dtype=np.float32).reshape(5, 4)
    kv.init("emb", mx.nd.array(w))
    out = mx.nd.zeros((5, 4))
    rows = mx.nd.array([1, 3])
    kv.row_sparse_pull("emb", out=out, row_ids=rows)
    expect = np.zeros((5, 4), np.float32)
    expect[[1, 3]] = w[[1, 3]]
    assert_almost_equal(out, expect)


def test_broadcast_and_pushpull():
    kv = kv_mod.create("device")
    out = mx.nd.zeros(SHAPE)
    kv.broadcast(9, mx.nd.ones(SHAPE) * 2, out=out)
    assert_almost_equal(out, np.ones(SHAPE) * 2)
    out2 = mx.nd.zeros(SHAPE)
    kv.pushpull(9, mx.nd.ones(SHAPE), out=out2)
    assert float(out2.asnumpy().sum()) != 0


def test_str_keys():
    kv = kv_mod.create("local")
    kv.init("a", mx.nd.ones(SHAPE))
    out = mx.nd.empty(SHAPE)
    kv.pull("a", out=out)
    assert_almost_equal(out, np.ones(SHAPE))


def test_dist_sync_single_worker():
    """dist_device_sync degenerates to 1-worker group without a cluster
    (rank 0, num_workers 1) and still aggregates correctly."""
    kv = kv_mod.create("dist_device_sync")
    assert kv.rank == 0 and kv.num_workers == 1
    kv.init(0, mx.nd.zeros(SHAPE))
    kv.push(0, mx.nd.ones(SHAPE) * 3)
    out = mx.nd.empty(SHAPE)
    kv.pull(0, out=out)
    assert_almost_equal(out, np.ones(SHAPE) * 3)
    kv.barrier()


def test_optimizer_states_roundtrip(tmp_path):
    kv = kv_mod.create("local")
    kv.set_optimizer(mx.optimizer.Adam(learning_rate=0.1))
    kv.init(0, mx.nd.ones(SHAPE))
    kv.push(0, mx.nd.ones(SHAPE))
    f = str(tmp_path / "states")
    kv.save_optimizer_states(f)
    kv.load_optimizer_states(f)


def test_gradient_compression_api():
    kv = kv_mod.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    assert kv.gradient_compression["type"] == "2bit"


def test_unknown_type():
    with pytest.raises(ValueError):
        kv_mod.create("zookeeper")


def test_trainer_with_explicit_kvstore():
    """Trainer wired through a kvstore still trains (parity:
    update_on_kvstore=False path: push grads, pull aggregate)."""
    from mxnet_tpu import autograd as ag
    from mxnet_tpu.gluon import Trainer, nn, loss as gloss

    net = nn.Dense(1, in_units=4)
    net.initialize()
    kv = kv_mod.create("dist_sync")
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                      kvstore=kv)
    x = mx.nd.ones((8, 4))
    y = mx.nd.ones((8, 1))
    L = gloss.L2Loss()
    prev = float(L(net(x), y).mean().asscalar())
    for _ in range(10):
        with ag.record():
            loss = L(net(x), y)
        loss.backward()
        trainer.step(8)
    final = float(L(net(x), y).mean().asscalar())
    assert final < prev
