"""gluon.contrib tests (parity model:
tests/python/unittest/test_gluon_contrib.py + test_gluon_estimator.py)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib.estimator import (EarlyStoppingHandler,
                                               Estimator, StoppingHandler)


def test_identity_and_concurrent():
    x = mx.nd.array(onp.random.rand(2, 8, 4, 4).astype("float32"))
    assert (cnn.Identity()(x).asnumpy() == x.asnumpy()).all()
    for cls in (cnn.Concurrent, cnn.HybridConcurrent):
        c = cls(axis=1)
        c.add(cnn.Identity(), cnn.Identity())
        out = c(x)
        assert out.shape == (2, 16, 4, 4)
        onp.testing.assert_allclose(out.asnumpy()[:, :8], x.asnumpy())


def test_pixelshuffle_oracle():
    x = mx.nd.array(onp.arange(2 * 8 * 4 * 4,
                               dtype="float32").reshape(2, 8, 4, 4))
    out = cnn.PixelShuffle2D(2)(x)
    xn = x.asnumpy()
    n, c, h, w = xn.shape
    ref = xn.reshape(n, 2, 2, 2, h, w).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(n, 2, h * 2, w * 2)
    onp.testing.assert_allclose(out.asnumpy(), ref)
    x1 = mx.nd.array(onp.arange(12, dtype="float32").reshape(1, 4, 3))
    assert cnn.PixelShuffle1D(2)(x1).shape == (1, 2, 6)
    x3 = mx.nd.ones((1, 8, 2, 2, 2))
    assert cnn.PixelShuffle3D(2)(x3).shape == (1, 1, 4, 4, 4)


def test_sparse_embedding_grad_rows():
    se = cnn.SparseEmbedding(50, 8)
    se.initialize(mx.init.Xavier())
    idx = mx.nd.array([1, 3, 3], dtype="int32")
    with mx.autograd.record():
        out = se(idx)
        loss = out.sum()
    loss.backward()
    rs = se.grad_rows(idx)
    assert rs.stype == "row_sparse"
    assert rs.indices.asnumpy().tolist() == [1, 3]
    onp.testing.assert_allclose(rs.data.asnumpy()[0], onp.ones(8))
    onp.testing.assert_allclose(rs.data.asnumpy()[1], 2 * onp.ones(8))


def test_sync_batchnorm_forward():
    bn = cnn.SyncBatchNorm(in_channels=4)
    bn.initialize()
    x = mx.nd.array(onp.random.rand(2, 4, 3, 3).astype("float32"))
    out = bn(x)
    assert out.shape == x.shape


def _toy_data(n=256):
    rs = onp.random.RandomState(0)
    X = rs.randn(n, 10).astype("float32")
    y = (X[:, 0] > 0).astype("float32")
    return mx.io.NDArrayIter(X, y, batch_size=32)


def _toy_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    return net


def test_estimator_fit_and_evaluate():
    mx.random.seed(0)
    net = _toy_net()
    net.initialize(mx.init.Xavier())
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(), context=mx.cpu(),
                    trainer=Trainer(net.collect_params(), "adam",
                                    {"learning_rate": 0.01}))
    it = _toy_data()
    est.fit(it, epochs=8)
    res = est.evaluate(_toy_data())
    assert res["accuracy"] > 0.9, res
    assert "val_loss" in res


def test_estimator_early_stopping():
    mx.random.seed(0)
    net = _toy_net()
    net.initialize(mx.init.Xavier())
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(), context=mx.cpu())
    handler = EarlyStoppingHandler(monitor=est.train_loss_metric,
                                   patience=1, mode="min")
    est.fit(_toy_data(64), epochs=50, event_handlers=[
        handler, StoppingHandler(max_epoch=50)])
    # either converged loss triggered early stop, or max epochs hit
    assert handler.current_epoch <= 50


def test_estimator_max_batches():
    net = _toy_net()
    net.initialize(mx.init.Xavier())
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(), context=mx.cpu())
    stopper = StoppingHandler(max_batch=3)
    est.fit(_toy_data(), batches=3, event_handlers=[stopper])
    assert stopper.current_batch == 3


def test_conv_rnn_cells():
    """Conv1/2/3D RNN/LSTM/GRU cells preserve state spatial shape across
    unroll (parity: gluon/contrib/rnn/conv_rnn_cell.py)."""
    from mxnet_tpu.gluon.contrib import rnn as crnn

    cell = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=5,
                               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize(mx.init.Xavier())
    seq = [mx.nd.random.uniform(shape=(2, 3, 8, 8)) for _ in range(4)]
    outs, states = cell.unroll(4, seq)
    assert outs[0].shape == (2, 5, 8, 8)
    assert states[0].shape == (2, 5, 8, 8)
    assert states[1].shape == (2, 5, 8, 8)

    g = crnn.Conv1DGRUCell(input_shape=(2, 10), hidden_channels=4,
                           i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    g.initialize(mx.init.Xavier())
    outs, _ = g.unroll(
        3, [mx.nd.random.uniform(shape=(2, 2, 10)) for _ in range(3)])
    assert outs[0].shape == (2, 4, 10)

    r3 = crnn.Conv3DRNNCell(input_shape=(1, 4, 4, 4), hidden_channels=2,
                            i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    r3.initialize(mx.init.Xavier())
    outs, _ = r3.unroll(
        2, [mx.nd.random.uniform(shape=(1, 1, 4, 4, 4)) for _ in range(2)])
    assert outs[0].shape == (1, 2, 4, 4, 4)


def test_lstmp_cell():
    from mxnet_tpu.gluon.contrib import rnn as crnn

    p = crnn.LSTMPCell(hidden_size=16, projection_size=6)
    p.initialize(mx.init.Xavier())
    outs, st = p.unroll(
        3, [mx.nd.random.uniform(shape=(4, 10)) for _ in range(3)])
    assert outs[0].shape == (4, 6)
    assert st[0].shape == (4, 6) and st[1].shape == (4, 16)


def test_variational_dropout_cell():
    """Mask sampled once, reused across steps; no dropout at inference
    (parity: gluon/contrib/rnn/rnn_cell.py VariationalDropoutCell)."""
    import numpy as onp

    from mxnet_tpu.gluon.contrib import rnn as crnn

    base = mx.gluon.rnn.RNNCell(8)
    vd = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    vd.initialize(mx.init.Xavier())
    x = mx.nd.ones((2, 8))
    with mx.autograd.record():
        vd.reset()
        _, s = vd(x, vd.begin_state(2))
        m1 = vd._input_mask.asnumpy()
        vd(x, s)
        m2 = vd._input_mask.asnumpy()
    onp.testing.assert_array_equal(m1, m2)
    vd.reset()
    vd(x, vd.begin_state(2))
    assert vd._input_mask is None


def test_interval_sampler():
    """Reference doctest behavior (gluon/contrib/data/sampler.py:25)."""
    import pytest

    from mxnet_tpu.gluon.contrib.data import IntervalSampler

    assert list(IntervalSampler(13, interval=3)) == \
        [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    assert list(IntervalSampler(13, interval=3, rollover=False)) == \
        [0, 3, 6, 9, 12]
    assert len(IntervalSampler(13, interval=3)) == 13
    with pytest.raises(ValueError):
        IntervalSampler(3, interval=5)
    with pytest.raises(ValueError):
        IntervalSampler(3, interval=0)


def test_wikitext_local_file(tmp_path):
    """WikiText2 over a local token file: vocab (EOS-reserved), 1-shifted
    labels, seq_len folding (gluon/contrib/data/text.py)."""
    import os

    import pytest

    from mxnet_tpu.gluon.contrib.data import WikiText2

    root = str(tmp_path)
    txt = " the cat sat \n\n the cat ran \n"
    with open(os.path.join(root, "wiki.train.tokens"), "w") as f:
        f.write(txt)
    ds = WikiText2(root=root, segment="train", seq_len=4)
    # stream: the cat sat <eos> the cat ran <eos> -> 7 usable pairs -> 1 row
    assert len(ds) == 1
    data, label = ds[0]
    v = ds.vocabulary
    assert v.to_tokens(int(data[0].asscalar())) == "the"
    onp.testing.assert_array_equal(label.asnumpy()[:3],
                                   data.asnumpy()[1:])
    assert "<eos>" in v.reserved_tokens
    with pytest.raises(FileNotFoundError, match="token file not found"):
        WikiText2(root=root, segment="test")
    with pytest.raises(ValueError):
        WikiText2(root=root, segment="bogus")


def test_multi_head_attention_matches_oracle():
    """MultiHeadAttention (flash-kernel backed) equals a hand-built
    dense attention oracle with the same projection weights; causal
    masking and cross-attention both work; gradients flow."""
    import math

    from mxnet_tpu.gluon.contrib.nn import MultiHeadAttention

    B, S, U, H = 2, 16, 24, 4
    mx.random.seed(0)
    attn = MultiHeadAttention(U, H, causal=False)
    attn.initialize(mx.init.Xavier())
    x = mx.nd.random.uniform(-1, 1, (B, S, U))
    out = attn(x)
    assert out.shape == (B, S, U)

    # oracle using the block's own projection weights
    def dense_oracle(x):
        q = mx.nd.dot(x, attn.query.weight.data().T) + attn.query.bias.data()
        k = mx.nd.dot(x, attn.key.weight.data().T) + attn.key.bias.data()
        v = mx.nd.dot(x, attn.value.weight.data().T) + attn.value.bias.data()

        def split(t):
            return t.reshape((B, S, H, U // H)).transpose((0, 2, 1, 3))

        q, k, v = split(q), split(k), split(v)
        s = mx.nd.linalg_gemm2(q, k, transpose_b=True) / math.sqrt(U // H)
        p = mx.nd.softmax(s, axis=-1)
        o = mx.nd.linalg_gemm2(p, v)
        o = o.transpose((0, 2, 1, 3)).reshape((B, S, U))
        return mx.nd.dot(o, attn.proj.weight.data().T) + \
            attn.proj.bias.data()

    onp.testing.assert_allclose(out.asnumpy(), dense_oracle(x).asnumpy(),
                                rtol=2e-3, atol=2e-5)

    # causal + grads
    cattn = MultiHeadAttention(U, H, causal=True)
    cattn.initialize(mx.init.Xavier())
    with mx.autograd.record():
        loss = (cattn(x) ** 2).sum()
    loss.backward()
    g = cattn.query.weight.grad()
    assert float(g.abs().sum().asscalar()) > 0
    # cross attention: different kv length
    mem = mx.nd.random.uniform(-1, 1, (B, 8, U))
    assert attn(x, mem).shape == (B, S, U)
    # causal masking is rejected for cross attention
    import pytest

    with pytest.raises(ValueError, match="cross"):
        cattn(x, mem)


def test_transformer_encoder_cell_trains():
    """Pre-LN encoder stack trains on a toy seq task and hybridizes."""
    from mxnet_tpu.gluon.contrib.nn import TransformerEncoderCell

    mx.random.seed(1)
    B, S, U = 4, 8, 16
    net = nn.HybridSequential()
    net.add(TransformerEncoderCell(U, 32, 4, causal=True),
            TransformerEncoderCell(U, 32, 4, causal=True))
    net.initialize(mx.init.Xavier())
    x = mx.nd.random.uniform(-1, 1, (B, S, U))
    y = x * 0.5  # learn a simple map
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    loss_fn = gloss.L2Loss()
    losses = []
    for _ in range(20):
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(B)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    net.hybridize()
    assert net(x).shape == (B, S, U)


def test_multi_head_attention_kernel_path_and_export(tmp_path):
    """Kernel-friendly shapes through the Pallas interpreter (d%8==0,
    S%block==0) match the dense fallback; the block exports to Symbol
    (F-dispatch tracing) and round-trips."""
    from mxnet_tpu.gluon.contrib.nn import (MultiHeadAttention,
                                            TransformerEncoderCell)

    B, S, U, H = 1, 128, 32, 4  # head dim 8, S == block size
    mx.random.seed(2)
    flash = MultiHeadAttention(U, H, causal=True, interpret=True,
                               block_q=64, block_k=64)
    flash.initialize(mx.init.Xavier())
    x = mx.nd.random.uniform(-1, 1, (B, S, U))
    out_kernel = flash(x)
    dense = MultiHeadAttention(U, H, causal=True)
    dense.initialize()
    # same weights -> the two compute paths must agree
    for dst, src in zip(dense.collect_params().values(),
                        flash.collect_params().values()):
        dst.set_data(src.data())
    onp.testing.assert_allclose(out_kernel.asnumpy(),
                                dense(x).asnumpy(), rtol=2e-3, atol=2e-4)

    # export path: the encoder cell traces to Symbol and round-trips
    net = nn.HybridSequential()
    net.add(TransformerEncoderCell(U, 64, H, causal=True))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    ref = net(x)
    prefix = str(tmp_path / "enc")
    net.export(prefix, epoch=0)
    from mxnet_tpu import gluon

    back = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                     prefix + "-0000.params")
    onp.testing.assert_allclose(back(x).asnumpy(), ref.asnumpy(),
                                rtol=1e-5, atol=1e-6)
