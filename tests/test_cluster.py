"""The reconciling cluster control plane (mxnet_tpu.cluster).

Unit tier: spec validation, the shared restart-budget/backoff
primitives, the crash-safe world record (atomic writes, torn-record
degradation), and the re-adoption verdict logic — pid reuse detection by
/proc start-ticks, outage-exit classification from drain evidence.

Integration tier (real subprocess workers via tests/_cluster_child.py):
a trainer-gang role runs to completion under the supervisor; a
SIGKILL-equivalent supervisor death is recovered by a second incarnation
that re-adopts the still-running worker without restarting it; a torn
world record falls back to heartbeat-evidence scavenging; stale-pid and
died-during-outage records are classified instead of adopted. The
full-topology drill (train + bus + serve under launch.py --cluster,
supervisor SIGKILLed mid-load) lives in tools/chaos_smoke.py phase 16.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from mxnet_tpu import cluster, elastic

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_cluster_child.py")


@pytest.fixture(autouse=True)
def _cluster_env_guard():
    """In-process supervisors export MXTPU_CLUSTER_DIR for diagnose;
    never let one test's cluster leak into the next."""
    keys = ("MXTPU_CLUSTER_DIR", "MXTPU_GANG_DIR", "MXTPU_WORKER_ID",
            "MXTPU_GANG_GENERATION", "MXTPU_COORDINATOR")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _gang_spec(name="train", workers=1, port=9471, **over):
    cfg = {"kind": "trainer-gang", "command": [sys.executable, CHILD],
           "workers": workers, "max_restarts": 3, "backoff": 0.05,
           "grace": 10, "dead_after": 30, "coordinator_port": port}
    cfg.update(over)
    return {"cluster": "t-cluster", "roles": {name: cfg}}


def _child_env(total, sleep=0.01, **extra):
    env = {"JAX_PLATFORMS": "cpu", "CC_TOTAL": str(total),
           "CC_STEP_SLEEP": str(sleep), "CC_PUBLISH_EVERY": "0"}
    env.update(extra)
    return env


def _wait_armed(sup, role="train", timeout=60):
    """Tick until the worker is not just spawned but ARMED: the child
    writes ``armed-<rank>`` (with its pid) only after preempt.install(),
    and its heartbeat names the slot's pid. Waiting for slot state
    'running' alone races the child's interpreter startup — a SIGTERM
    landing before install() kills instead of draining, and the gang
    heartbeat arms early in the mxnet_tpu import, so it is no proof
    either."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sup.tick()
        s = sup.roles[role].slots.get(0)
        if s is not None and s.alive():
            beat = elastic.read_heartbeats(sup.roles[role].dir).get(0)
            try:
                with open(os.path.join(sup.roles[role].dir,
                                       "armed-0")) as f:
                    armed_pid = int(f.read() or 0)
            except (OSError, ValueError):
                armed_pid = None
            if beat and beat.get("pid") == s.pid \
                    and armed_pid == s.pid:
                return s
        time.sleep(0.05)
    sup.stop(graceful=False)
    _reap(sup)
    pytest.fail(f"worker never armed under {sup.world.cluster}")


def _reap(sup):
    """Reap any Popen children a supervisor still holds (zombies would
    otherwise linger for the rest of the pytest process)."""
    for role in sup.roles.values():
        for s in role.slots.values():
            if s.proc is not None:
                try:
                    s.proc.wait(timeout=10)
                except Exception:
                    pass


# ------------------------------------------------------------ spec layer --

def test_validate_spec_fills_defaults_and_resolves_paths(tmp_path):
    spec = cluster.validate_spec(
        {"cluster": "c", "roles": {
            "train": {"kind": "trainer-gang", "command": ["x"],
                      "workers": 2, "publish_to": "bus"},
            "bus": {"kind": "model-bus"},
            "serve": {"kind": "serving-fleet", "model_dir": "models",
                      "min": 1, "max": 3, "subscribe_to": "bus"}}},
        base_dir=str(tmp_path))
    train = spec["roles"]["train"]
    assert train["max_restarts"] == 5 and train["backoff"] == 0.5
    serve = spec["roles"]["serve"]
    # relative model_dir resolves against the spec's directory
    assert serve["model_dir"] == os.path.join(str(tmp_path), "models")
    # workers defaults to min, clamped into [min, max]
    assert serve["workers"] == 1
    assert spec["roles"]["bus"]["keep"] == 0


@pytest.mark.parametrize("bad,err", [
    ({}, "non-empty 'roles'"),
    ({"roles": {"r": {"kind": "nope"}}}, "unknown kind"),
    ({"roles": {"r": {"kind": "trainer-gang", "command": ["x"],
                      "frobnicate": 1}}}, "unknown option"),
    ({"roles": {"r": {"kind": "trainer-gang"}}}, "non-empty 'command'"),
    ({"roles": {"r": {"kind": "trainer-gang", "command": ["x"],
                      "workers": 0}}}, "workers must be >= 1"),
    ({"roles": {"r": {"kind": "serving-fleet"}}}, "model_dir"),
    ({"roles": {"r": {"kind": "serving-fleet", "model_dir": "m",
                      "min": 3, "max": 1}}}, "min <= max"),
    ({"roles": {"r": {"kind": "trainer-gang", "command": ["x"],
                      "publish_to": "ghost"}}}, "not a model-bus role"),
])
def test_validate_spec_rejects(bad, err):
    with pytest.raises(cluster.ClusterError, match=err):
        cluster.validate_spec(bad)


def test_load_spec_names_unreadable_and_malformed(tmp_path):
    with pytest.raises(cluster.ClusterError, match="cannot read"):
        cluster.load_spec(tmp_path / "missing.json")
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    with pytest.raises(cluster.ClusterError, match="malformed"):
        cluster.load_spec(p)


# ------------------------------------------------- restart primitives --

def test_next_backoff_curve():
    assert cluster.next_backoff(0.5, 30.0, 0) == 0.0
    assert cluster.next_backoff(0.5, 30.0, 1) == 0.5
    assert cluster.next_backoff(0.5, 30.0, 3) == 2.0
    assert cluster.next_backoff(0.5, 30.0, 50) == 30.0  # capped


def test_restart_ledger_role_wide_budget():
    led = cluster.RestartLedger(2, 0.5, 30.0)
    ok1, d1 = led.charge(reason="x")
    ok2, d2 = led.charge(reason="y")
    assert (ok1, ok2) == (True, True)
    assert (d1, d2) == (0.5, 1.0)
    ok3, _ = led.charge()
    assert not ok3 and led.exhausted
    assert led.restarts_total == 2


def test_restart_ledger_per_slot_round_trip():
    led = cluster.RestartLedger(1, 0.1, 5.0, per_slot=True)
    assert led.charge(slot=0)[0]
    assert led.charge(slot=1)[0]
    assert not led.charge(slot=0)[0]       # slot 0's budget is spent
    back = cluster.RestartLedger.from_dict(
        led.as_dict(), 1, 0.1, 5.0, True)
    assert back.restarts_total == 2
    assert back.used(slot=0) == 1 and back.used(slot=1) == 1
    assert back.exhausted


# -------------------------------------------------------- world record --

def test_world_state_round_trip(tmp_path):
    ws = cluster.WorldState(str(tmp_path))
    ws.cluster = "c"
    ws.incarnation = 3
    ws.generation = {"train": 2}
    ws.slots = {"train": {"0": {"slot": 0, "pid": 1234,
                                "state": "running"}}}
    for i in range(80):                    # the action log is capped
        ws.record_action("spawn", "train", 0, f"r{i}")
    ws.save()
    back = cluster.WorldState.load(str(tmp_path))
    assert not back.torn
    assert back.incarnation == 3
    assert back.generation == {"train": 2}
    assert back.slots["train"]["0"]["pid"] == 1234
    assert len(back.actions) == 64
    assert back.actions[-1]["reason"] == "r79"


def test_world_state_torn_record_degrades(tmp_path):
    (tmp_path / cluster.WORLD_FILE).write_text('{"cluster": "c", "slo')
    ws = cluster.WorldState.load(str(tmp_path))
    assert ws.torn and ws.incarnation == 0 and ws.slots == {}
    # structurally wrong types degrade the same way
    (tmp_path / cluster.WORLD_FILE).write_text('{"slots": [1, 2]}')
    assert cluster.WorldState.load(str(tmp_path)).torn


def test_atomic_record_leaves_no_tmp(tmp_path):
    path = str(tmp_path / "rec.json")
    cluster.atomic_record(path, {"a": 1})
    cluster.atomic_record(path, {"a": 2})
    with open(path) as f:
        assert json.load(f) == {"a": 2}
    assert os.listdir(tmp_path) == ["rec.json"]


# ------------------------------------------------- adoption verdicts --

def test_adoption_verdict_live_match_and_stale_ticks():
    pid = os.getpid()
    ticks = cluster.proc_start_ticks(pid)
    assert ticks is not None
    v, why = cluster.adoption_verdict(
        {"pid": pid, "start_ticks": ticks, "spawned": time.time()})
    assert v == "adopt" and "match" in why
    # same live pid, different recorded start-ticks: the pid was reused
    v, why = cluster.adoption_verdict(
        {"pid": pid, "start_ticks": ticks + 7, "spawned": time.time()})
    assert v == "stale-pid" and "reused" in why


def test_adoption_verdict_dead_pid():
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    v, _ = cluster.adoption_verdict(
        {"pid": p.pid, "start_ticks": 1, "spawned": time.time()})
    assert v == "dead"


def test_adoption_verdict_no_ticks_trust_window():
    rec = {"pid": os.getpid(), "start_ticks": None,
           "spawned": time.time()}
    assert cluster.adoption_verdict(rec)[0] == "adopt"
    rec["spawned"] = time.time() - 3600
    assert cluster.adoption_verdict(rec)[0] == "stale-pid"


def test_pid_alive_rejects_zombie():
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:   # un-reaped child -> zombie
        try:
            with open(f"/proc/{p.pid}/stat") as f:
                stat = f.read()
            if stat[stat.rindex(")") + 2:].split(" ", 1)[0] == "Z":
                break
        except OSError:
            break
        time.sleep(0.02)
    assert not cluster.pid_alive(p.pid)
    p.wait()


def test_classify_outage_exit_from_evidence():
    assert cluster.classify_outage_exit({}, {"state": "draining"}) == 75
    assert cluster.classify_outage_exit({}, {"state": "drained"}) == 75
    assert cluster.classify_outage_exit({}, {"state": "running"}) == 137
    assert cluster.classify_outage_exit({}, None) == 137


# --------------------------------------------- re-adoption edge cases --

def _seed_world(run_dir, slot_rec, role="train"):
    """Author a previous incarnation's world record by hand."""
    os.makedirs(run_dir, exist_ok=True)
    cluster.atomic_record(
        os.path.join(run_dir, cluster.WORLD_FILE),
        {"cluster": "t-cluster", "incarnation": 1,
         "supervisor": {"pid": 1, "start_ticks": 1,
                        "started": time.time() - 5,
                        "state": "reconciling"},
         "generation": {role: 1}, "next_slot": {role: 1},
         "slots": {role: {"0": slot_rec}},
         "ledger": {}, "actions": [], "router": {}})


def test_stale_pid_record_is_never_signalled(tmp_path):
    """A recycled pid (alive, wrong start-ticks) must be classified as
    an outage loss — never adopted, never killed. The recorded pid here
    is the TEST PROCESS itself: surviving the supervisor construction
    IS the assertion that re-adoption left the stranger alone."""
    run = str(tmp_path / "run")
    ticks = cluster.proc_start_ticks(os.getpid())
    _seed_world(run, {"slot": 0, "generation": 1, "pid": os.getpid(),
                      "start_ticks": ticks + 9,
                      "spawned": time.time() - 30, "state": "running",
                      "restarts": 0})
    sup = cluster.ClusterSupervisor(_gang_spec(port=9472), run_dir=run,
                                    poll=0.05, env=_child_env(2))
    try:
        assert sup.adopted == 0
        s = sup.roles["train"].slots[0]
        assert s.state == "exited-during-outage"
        assert s.pid is None                 # the stranger's pid dropped
        assert s.last_exit == 137            # no drain evidence
        outage = [a for a in sup.world.actions
                  if a["kind"] == "outage-exit"]
        assert outage and "reused" in outage[0]["reason"]
        assert not [a for a in sup.world.actions
                    if a["kind"] == "adopt"]
    finally:
        sup.stop(graceful=False)
        _reap(sup)


def test_worker_exit_during_outage_classified_from_drain_evidence(
        tmp_path):
    """A worker that drained and exited while the supervisor was down
    leaves only heartbeat evidence; the restarted incarnation must
    classify its exit 75 (drain), not 137."""
    run = str(tmp_path / "run")
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()                                 # dead, reaped: pid gone
    _seed_world(run, {"slot": 0, "generation": 1, "pid": p.pid,
                      "start_ticks": 12345, "spawned": time.time() - 30,
                      "state": "running", "restarts": 0})
    hb_dir = os.path.join(run, "train")
    os.makedirs(hb_dir, exist_ok=True)
    cluster.atomic_record(
        os.path.join(hb_dir, "rank-0.json"),
        {"rank": 0, "pid": p.pid, "generation": 1,
         "t_wall": time.time(), "state": "draining"})
    sup = cluster.ClusterSupervisor(_gang_spec(port=9473), run_dir=run,
                                    poll=0.05, env=_child_env(2))
    try:
        s = sup.roles["train"].slots[0]
        assert s.last_exit == 75
        outage = [a for a in sup.world.actions
                  if a["kind"] == "outage-exit"]
        assert outage and outage[0]["exit"] == 75
    finally:
        sup.stop(graceful=False)
        _reap(sup)


# ----------------------------------------- live supervisor lifecycle --

def test_supervisor_runs_gang_to_done(tmp_path):
    run = str(tmp_path / "run")
    sup = cluster.ClusterSupervisor(_gang_spec(port=9474), run_dir=run,
                                    poll=0.05, env=_child_env(total=3))
    try:
        rc = sup.run()
    finally:
        _reap(sup)
    assert rc == 0
    assert sup.roles["train"].state == "done"
    with open(os.path.join(run, cluster.WORLD_FILE)) as f:
        world = json.load(f)
    assert world["supervisor"]["state"] == "stopped"
    kinds = [a["kind"] for a in world["actions"]]
    assert "spawn" in kinds and "done" in kinds
    assert world["slots"]["train"]["0"]["last_exit"] == 0


def test_supervisor_crash_readopts_running_worker(tmp_path):
    """The headline robustness path, in-process: supervisor #1 dies
    without any teardown (its object is simply abandoned, as SIGKILL
    would leave things); supervisor #2 on the same run dir re-adopts
    the still-running worker by pid + start-ticks — zero restarts —
    and a graceful stop then drains it through exit 75 classified
    purely from heartbeat evidence (an adopted orphan has no waitpid
    status)."""
    run = str(tmp_path / "run")
    sup1 = cluster.ClusterSupervisor(
        _gang_spec(port=9475), run_dir=run, poll=0.05,
        env=_child_env(total=100000, sleep=0.05))
    pid = _wait_armed(sup1).pid
    # supervisor #1 "crashes": no stop(), no drain — the world record on
    # disk and the orphaned worker are all that survive
    sup2 = cluster.ClusterSupervisor(
        _gang_spec(port=9475), run_dir=run, poll=0.05,
        env=_child_env(total=100000, sleep=0.05))
    try:
        assert sup2.world.incarnation == 2
        assert sup2.adopted == 1
        s2 = sup2.roles["train"].slots[0]
        assert s2.pid == pid and s2.adopted
        assert s2.restarts == 0              # the healthy worker is free
        assert [a for a in sup2.world.actions if a["kind"] == "adopt"]
        sup2.tick()
        assert sup2.roles["train"].slots[0].pid == pid  # still adopted
    finally:
        sup2.stop()                          # graceful: SIGTERM -> drain
        _reap(sup1)
        _reap(sup2)
    s2 = sup2.roles["train"].slots[0]
    assert s2.last_exit == 75, \
        f"adopted worker's drain classified {s2.last_exit}"
    assert s2.state == "retired"


def test_torn_world_record_scavenges_from_heartbeats(tmp_path):
    """SIGKILL mid-write (pre-atomic-seam worlds) leaves a torn
    world.json: the restarted supervisor must rebuild the census from
    the workers' own heartbeat shards and still re-adopt, not orphan
    and double-spawn."""
    run = str(tmp_path / "run")
    sup1 = cluster.ClusterSupervisor(
        _gang_spec(port=9476), run_dir=run, poll=0.05,
        env=_child_env(total=100000, sleep=0.05))
    pid = _wait_armed(sup1).pid
    with open(os.path.join(run, cluster.WORLD_FILE), "w") as f:
        f.write('{"cluster": "t-cluster", "incarnation": 1, "slo')
    sup2 = cluster.ClusterSupervisor(
        _gang_spec(port=9476), run_dir=run, poll=0.05,
        env=_child_env(total=100000, sleep=0.05))
    try:
        assert sup2.adopted == 1
        assert sup2.roles["train"].slots[0].pid == pid
        kinds = [a["kind"] for a in sup2.world.actions]
        assert "scavenge" in kinds and "adopt" in kinds
    finally:
        sup2.stop()
        _reap(sup1)
        _reap(sup2)
    assert sup2.roles["train"].slots[0].last_exit == 75
