"""Parallelism tests on the 8-device virtual CPU mesh.

Parity model: tests/python/unittest/test_kvstore.py + multi_device_exec —
multi-device logic tested without accelerators (SURVEY §4 'multi-device
logic is testable without GPUs'); here the devices are the virtual CPU mesh.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer, sharding_rules


def _make_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(4, in_units=32))
    net.initialize(mx.init.Xavier())
    return net


def test_mesh_construction():
    mesh = DeviceMesh()
    assert mesh.num_devices == 8
    assert mesh.size("dp") == 8
    mesh = DeviceMesh({"dp": 4, "tp": 2})
    assert mesh.size("tp") == 2
    assert mesh.axis_names == ("dp", "tp")
    # smaller meshes take a device prefix
    assert DeviceMesh({"dp": 3}).num_devices == 3
    with pytest.raises(ValueError):
        DeviceMesh({"dp": 16})  # more than available


def test_sharding_rules():
    net = _make_net()
    mesh = DeviceMesh({"dp": 4, "tp": 2})
    rules = sharding_rules(net.collect_params(), mesh)
    w_specs = [v for k, v in rules.items() if k.endswith("weight")]
    assert all(s and s[0] == "tp" for s in w_specs)  # 32 and 4... 4%2==0
    b_specs = [v for k, v in rules.items() if k.endswith("bias")]
    assert all(s == () for s in b_specs)


@pytest.mark.parametrize("axes", [{"dp": 8}, {"dp": 4, "tp": 2}])
def test_sharded_trainer_converges(axes):
    np.random.seed(0)
    mx.random.seed(0)
    net = _make_net()
    mesh = DeviceMesh(axes)
    st = ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh)
    rng = np.random.default_rng(0)
    centers = rng.normal(0, 2.0, (4, 16))
    labels = rng.integers(0, 4, 64)
    data = (centers[labels] + rng.normal(0, 0.3, (64, 16))).astype(np.float32)
    x, y = mx.nd.array(data), mx.nd.array(labels.astype(np.float32))
    losses = [float(st.step(x, y).asscalar()) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.2, f"no convergence: {losses[::6]}"
    # sharded predict agrees with labels
    acc = (st.predict(x).argmax(axis=1).asnumpy() == labels).mean()
    assert acc > 0.95


def test_sharded_matches_single_device():
    """dp-sharded training step == single-device training step (the
    correctness core of data parallelism: allreduced grads = full-batch
    grads)."""
    def run(mesh_axes):
        np.random.seed(3)
        mx.random.seed(3)
        net = _make_net()
        mesh = DeviceMesh(mesh_axes, devices=None)
        st = ShardedTrainer(net, gloss.L2Loss(), "sgd",
                            {"learning_rate": 0.05}, mesh=mesh)
        rng = np.random.default_rng(1)
        x = mx.nd.array(rng.normal(size=(32, 16)).astype(np.float32))
        y = mx.nd.array(rng.normal(size=(32, 4)).astype(np.float32))
        for _ in range(5):
            loss = st.step(x, y)
        st.unshard()
        return [p.data().asnumpy() for p in net.collect_params().values()], \
            float(loss.asscalar())

    params8, loss8 = run({"dp": 8})
    params1, loss1 = run({"dp": 1})
    assert abs(loss8 - loss1) < 1e-5
    for a, b in zip(params8, params1):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_batchnorm_stats_update_in_sharded_step():
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8), nn.BatchNorm(axis=-1, in_channels=16),
            nn.Dense(2, in_units=16))
    net.initialize()
    bn = net[1]
    rm0 = bn.running_mean.data().asnumpy().copy()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd", {"learning_rate": 0.01},
                        mesh=DeviceMesh({"dp": 8}))
    x = mx.nd.array(np.random.rand(16, 8).astype(np.float32) + 1.0)
    y = mx.nd.array(np.random.rand(16, 2).astype(np.float32))
    st.step(x, y)
    rm1 = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1), "BN stats not updated in sharded step"


def test_uneven_batch_raises_cleanly():
    net = _make_net()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                        mesh=DeviceMesh({"dp": 8}))
    x = mx.nd.ones((12, 16))  # 12 % 8 != 0
    y = mx.nd.ones((12, 4))
    with pytest.raises(Exception):
        st.step(x, y)


def test_graft_entry_dryrun():
    """The driver's multichip dry run must pass on the virtual mesh."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_ring_attention_matches_reference():
    """Ring attention over the sp axis is numerically exact vs single-device
    attention (full and causal)."""
    import jax.numpy as jnp

    from mxnet_tpu.parallel import attention, ring_attention

    np.random.seed(0)
    B, H, S, D = 2, 4, 64, 16
    q = jnp.array(np.random.randn(B, H, S, D).astype(np.float32))
    k = jnp.array(np.random.randn(B, H, S, D).astype(np.float32))
    v = jnp.array(np.random.randn(B, H, S, D).astype(np.float32))
    mesh = DeviceMesh({"sp": 8})
    for causal in (False, True):
        ref = np.asarray(attention(q, k, v, causal=causal))
        out = np.asarray(ring_attention(q, k, v, mesh, causal=causal))
        assert np.abs(ref - out).max() < 1e-5, f"causal={causal}"


def test_ring_attention_ndarray_api():
    from mxnet_tpu.parallel import ring_attention

    q = mx.nd.random.uniform(shape=(1, 2, 32, 8))
    out = ring_attention(q, q, q, DeviceMesh({"sp": 8}), causal=True)
    assert out.shape == (1, 2, 32, 8)
    assert isinstance(out, mx.nd.NDArray)


def test_pipeline_parallel_matches_sequential():
    """GPipe microbatch pipeline over the pp axis: forward AND jax.grad
    backward are exact vs the sequential stack (parallel/pipeline.py)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import pipeline_apply, stack_stage_params

    S, M, B, D = 4, 8, 16, 12
    rs = np.random.RandomState(0)
    stage_params = [
        {"w": jnp.asarray(rs.randn(D, D) * 0.3, jnp.float32),
         "b": jnp.asarray(rs.randn(D) * 0.1, jnp.float32)}
        for _ in range(S)]
    stacked = stack_stage_params(stage_params)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    mesh = DeviceMesh({"pp": S})
    fn = pipeline_apply(stage_fn, mesh, num_microbatches=M)
    x = jnp.asarray(rs.randn(B, D), jnp.float32)
    ref = x
    for p in stage_params:
        ref = stage_fn(p, ref)
    assert float(jnp.abs(fn(stacked, x) - ref).max()) < 1e-5

    def loss_pipe(sp):
        return jnp.sum(fn(sp, x) ** 2)

    def loss_seq(plist):
        h = x
        for p in plist:
            h = stage_fn(p, h)
        return jnp.sum(h ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stage_params)
    for s in range(S):
        for k in ("w", "b"):
            assert float(jnp.abs(g_pipe[k][s] - g_seq[s][k]).max()) < 1e-4


def test_moe_expert_parallel_matches_dense():
    """Top-1 Switch MoE over the ep axis: output, aux loss and router
    gradient match the dense oracle (parallel/moe.py)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import moe_apply, stack_expert_params

    E, N, D = 8, 32, 6
    rs = np.random.RandomState(0)
    experts = [{"w": jnp.asarray(rs.randn(D, D) * 0.5, jnp.float32)}
               for _ in range(E)]
    router_w = jnp.asarray(rs.randn(D, E), jnp.float32)
    x = jnp.asarray(rs.randn(N, D), jnp.float32)

    def expert_fn(p, xx):
        return jnp.tanh(xx @ p["w"])

    mesh = DeviceMesh({"ep": E})
    fn = moe_apply(expert_fn, mesh)
    y, aux = fn(stack_expert_params(experts), router_w, x)

    probs = np.asarray(jax.nn.softmax(x @ router_w, axis=-1))
    assign = probs.argmax(-1)
    ref = np.zeros((N, D), np.float32)
    for i in range(N):
        e = assign[i]
        ref[i] = probs[i, e] * np.tanh(
            np.asarray(x[i]) @ np.asarray(experts[e]["w"]))
    assert float(np.abs(np.asarray(y) - ref).max()) < 1e-5
    f = np.bincount(assign, minlength=E) / N
    assert abs(float(aux) - E * float((f * probs.mean(0)).sum())) < 1e-5

    def loss(params, rw):
        yy, aa = fn(params, rw, x)
        return jnp.sum(yy ** 2) + 0.01 * aa

    g_router = jax.grad(loss, argnums=1)(stack_expert_params(experts),
                                         router_w)
    assert float(jnp.abs(g_router).max()) > 0


def _mk_trainer_net(seed=0):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    return net


def _train_steps(trainer_kwargs, steps=3, seed=0):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    net = _mk_trainer_net(seed)
    x = mx.nd.array(np.random.RandomState(1).randn(16, 12)
                    .astype(np.float32))
    y = mx.nd.array(np.random.RandomState(2).randint(0, 8, 16)
                    .astype(np.float32))
    net(x)
    tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
                        {"learning_rate": 0.01},
                        mesh=DeviceMesh({"dp": 8}), **trainer_kwargs)
    losses = [float(tr.step(x, y).asscalar()) for _ in range(steps)]
    tr.unshard()
    # positional (auto-names differ between nets: global name counters)
    params = [p.data().asnumpy() for p in net.collect_params().values()]
    return losses, params, tr


def test_sharded_trainer_zero_matches_baseline():
    """ZeRO-1 state sharding changes memory layout, not numerics: losses
    and params match the unsharded-state baseline, and the Adam moments
    really live dp-sharded (sharded_trainer.py _state_spec_for)."""
    base_losses, base_params, _ = _train_steps({})
    z_losses, z_params, tr = _train_steps({"zero": True})
    np.testing.assert_allclose(z_losses, base_losses, rtol=1e-4)
    for zp, bp in zip(z_params, base_params):
        np.testing.assert_allclose(zp, bp, rtol=2e-3, atol=1e-5)
    sharded = [s for per in tr._opt_raws for s in per
               if any(ax == "dp" for ax in (s.sharding.spec or ()))]
    assert sharded, "no optimizer state ended up dp-sharded under zero=True"


def test_sharded_trainer_remat_matches_baseline():
    """jax.checkpoint changes scheduling, not results."""
    base_losses, base_params, _ = _train_steps({})
    r_losses, r_params, _ = _train_steps({"remat": True})
    np.testing.assert_allclose(r_losses, base_losses, rtol=1e-5)
    for rp, bp in zip(r_params, base_params):
        np.testing.assert_allclose(rp, bp, rtol=1e-4, atol=1e-6)


def test_sharded_trainer_grad_accum():
    """accum_steps=N microbatch scan: numerics match the accum=1 run on
    a deterministic net; indivisible batches raise; sub-dp microbatches
    warn about idle devices."""
    import warnings

    import pytest

    base_losses, base_params, _ = _train_steps({})
    a_losses, a_params, _ = _train_steps({"accum_steps": 2})
    np.testing.assert_allclose(a_losses, base_losses, rtol=1e-4)
    for ap, bp in zip(a_params, base_params):
        np.testing.assert_allclose(ap, bp, rtol=2e-3, atol=1e-5)
    with pytest.raises(ValueError, match="not divisible by accum_steps"):
        _train_steps({"accum_steps": 5}, steps=1)  # 16 % 5 != 0
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        _train_steps({"accum_steps": 4}, steps=1)  # microbatch 4 < dp 8
    assert any("idle" in str(x.message) for x in w)


def test_sharded_trainer_checkpoint_resume():
    """save_states/load_states round-trip mid-training: a freshly built
    trainer (different gluon auto-prefixes, ZeRO layout, Dropout in the
    net) continues with EXACTLY the losses of the uninterrupted run —
    entries are positional and the RNG stream is restored
    (sharded_trainer.py save_states)."""
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    x = mx.nd.array(np.random.RandomState(1).randn(16, 12)
                    .astype(np.float32))
    y = mx.nd.array(np.random.RandomState(2).randint(0, 8, 16)
                    .astype(np.float32))

    def make(seed=0, **kw):
        mx.random.seed(seed)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"))
        net.add(gluon.nn.Dropout(0.3))
        net.add(gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        net(x)
        return net, ShardedTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 0.05}, mesh=DeviceMesh({"dp": 8}), **kw)

    _, tr = make()
    for _ in range(3):
        tr.step(x, y)
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        tr.save_states(f.name)
        ref = [float(tr.step(x, y).asscalar()) for _ in range(3)]

        # fresh net instance: new auto-prefixes, ZeRO state layout — the
        # positional format + RNG restore must still reproduce exactly
        net2, tr2 = make(seed=123, zero=True)
        tr2.load_states(f.name)
        got = [float(tr2.step(x, y).asscalar()) for _ in range(3)]

        # mismatched trainer (sgd: different state slots) must refuse
        # loudly BEFORE mutating anything
        net3 = _mk_trainer_net(7)
        net3(x)
        tr3 = ShardedTrainer(net3, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": 0.05},
                             mesh=DeviceMesh({"dp": 8}))
        before = [p.data().asnumpy().copy()
                  for p in net3.collect_params().values()]
        import pytest

        with pytest.raises(ValueError, match="does not match"):
            tr3.load_states(f.name)
        for b, p in zip(before, net3.collect_params().values()):
            np.testing.assert_array_equal(b, p.data().asnumpy())

        # same key set but different architecture (wider layer): shape
        # validation must refuse BEFORE mutating anything
        net4 = gluon.nn.HybridSequential()
        net4.add(gluon.nn.Dense(64, activation="relu"))
        net4.add(gluon.nn.Dropout(0.3))
        net4.add(gluon.nn.Dense(8))
        net4.initialize(mx.init.Xavier())
        net4(x)
        tr4 = ShardedTrainer(net4, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "adam", {"learning_rate": 0.05},
                             mesh=DeviceMesh({"dp": 8}))
        t4_before = tr4._t
        with pytest.raises(ValueError, match="has shape"):
            tr4.load_states(f.name)
        assert tr4._t == t4_before
    np.testing.assert_allclose(got, ref, rtol=1e-4)
    assert tr2._t == tr._t


def test_sharded_trainer_checkpoint_bf16():
    """bf16 params round-trip bit-exactly through the npz checkpoint
    (stored as uint16 bits — npy cannot hold bf16)."""
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    x = mx.nd.array(np.random.RandomState(0).randn(8, 6).astype(np.float32))
    net = _mk_trainer_net(5)
    net(x.astype("float32"))
    net.cast("bfloat16")
    xb = x.astype("bfloat16")
    y = mx.nd.array(np.zeros(8, np.float32))
    tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                        {"learning_rate": 0.01, "momentum": 0.9},
                        mesh=DeviceMesh({"dp": 8}))
    tr.step(xb, y)
    import jax

    want = [np.asarray(jax.device_get(h._data).astype("float32"))
            for h in tr._train_handles]
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        tr.save_states(f.name)
        tr.step(xb, y)  # mutate past the checkpoint
        tr.load_states(f.name)
    got = [np.asarray(jax.device_get(h._data).astype("float32"))
           for h in tr._train_handles]
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    assert str(tr._train_handles[0]._data.dtype) == "bfloat16"


def test_ring_attention_backward_matches_dense():
    """SP TRAINING guarantee: jax.grad through the ring schedule (scan of
    ppermutes) equals dense-attention gradients for q, k and v."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import attention, ring_attention_sharded

    np.random.seed(0)
    B, H, S, D = 2, 2, 32, 8
    q, k, v = (jnp.asarray(np.random.randn(B, H, S, D), jnp.float32)
               for _ in range(3))
    fn = ring_attention_sharded(DeviceMesh({"sp": 8}), causal=True)

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        assert float(jnp.abs(a - b).max()) < 1e-5


def test_sharded_trainer_lr_scheduler():
    """lr_scheduler in optimizer_params drives a per-step traced lr (no
    recompilation): the schedule's decayed steps must match manual SGD
    with the decayed rates exactly."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    # FactorScheduler is STATEFUL (base_lr decays in place): the
    # trainer and the manual reference each need their own instance
    def make_sched():
        return mx.lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                               base_lr=0.2)

    x = mx.nd.array(np.random.RandomState(1).randn(16, 12)
                    .astype(np.float32))
    y = mx.nd.array(np.random.RandomState(2).randn(16, 4)
                    .astype(np.float32))

    def make():
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(4, in_units=12))
        net.initialize(mx.init.Xavier())
        net(x)
        return net

    net = make()
    tr = ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                        {"learning_rate": 0.2,
                         "lr_scheduler": make_sched()},
                        mesh=DeviceMesh({"dp": 8}))
    for _ in range(4):
        tr.step(x, y)
    tr.unshard()
    got = [p.data().asnumpy() for p in net.collect_params().values()]

    # manual: same per-step decayed rates through separate trainers
    net2 = make()
    raws = [p.data()._data for p in net2.collect_params().values()]
    ref_sched = make_sched()
    lrs = [float(ref_sched(t)) for t in range(1, 5)]  # _t pre-increments

    def loss_fn(ws, x_, y_):
        import jax.numpy as jnp

        pred = x_ @ ws[0].T + ws[1]
        return jnp.mean(jnp.square(pred - y_)) / 2.0

    import jax.numpy as jnp

    xs, ys = jnp.asarray(x.asnumpy()), jnp.asarray(y.asnumpy())
    ws = [jnp.asarray(r) for r in raws]
    for lr in lrs:
        grads = jax.grad(loss_fn)(ws, xs, ys)
        # trainer wd defaults to 0; weight has wd_mult 1 but wd=0
        ws = [w - lr * g for w, g in zip(ws, grads)]
    for a, b in zip(got, ws):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-4, atol=1e-6)


def test_sharded_trainer_scheduler_checkpoint_rewind():
    """Schedulers decay in place; load_states must rewind their state so
    a resumed run reproduces the uninterrupted schedule exactly."""
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    x = mx.nd.array(np.random.RandomState(1).randn(16, 12)
                    .astype(np.float32))
    y = mx.nd.array(np.random.RandomState(2).randn(16, 4)
                    .astype(np.float32))

    def make():
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(4, in_units=12))
        net.initialize(mx.init.Xavier())
        net(x)
        return ShardedTrainer(
            net, gluon.loss.L2Loss(), "sgd",
            {"learning_rate": 0.2,
             "lr_scheduler": mx.lr_scheduler.FactorScheduler(
                 step=2, factor=0.5)},
            mesh=DeviceMesh({"dp": 8}))

    tr = make()
    # learning_rate must seed the scheduler's base_lr (Optimizer parity)
    assert tr._lr_scheduler.base_lr == 0.2
    for _ in range(4):
        tr.step(x, y)
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        tr.save_states(f.name)
        ref = [float(tr.step(x, y).asscalar()) for _ in range(4)]
        tr2 = make()
        for _ in range(10):  # decay tr2's scheduler well past step 4
            tr2.step(x, y)
        tr2.load_states(f.name)
        got = [float(tr2.step(x, y).asscalar()) for _ in range(4)]
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_sharded_trainer_set_learning_rate():
    """set_learning_rate changes the traced lr without recompilation;
    raises UserWarning while a scheduler drives it and the property
    consults the scheduler (gluon Trainer / Optimizer contract)."""
    x = mx.nd.ones((8, 12))
    y = mx.nd.zeros((8, 4))
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=12))
    net.initialize(mx.init.Xavier())
    net(x)
    tr = ShardedTrainer(net, gloss.L2Loss(), "sgd",
                        {"learning_rate": 0.1}, mesh=DeviceMesh({"dp": 8}))
    tr.step(x, y)
    compiled = tr._step_fn
    w_before = [p.data().asnumpy().copy()
                for p in net.collect_params().values()]
    tr.learning_rate = 0.0  # freeze (gluon property-setter idiom)
    tr.step(x, y)
    assert tr._step_fn is compiled  # no recompilation
    tr.unshard()
    for b, p in zip(w_before, net.collect_params().values()):
        np.testing.assert_allclose(p.data().asnumpy(), b, rtol=1e-6)
    tr2 = ShardedTrainer(net, gloss.L2Loss(), "sgd",
                         {"learning_rate": 0.1,
                          "lr_scheduler":
                          mx.lr_scheduler.FactorScheduler(step=5)},
                         mesh=DeviceMesh({"dp": 8}))
    with pytest.raises(UserWarning, match="LRScheduler"):
        tr2.set_learning_rate(0.5)
    assert tr.learning_rate == 0.0
    assert tr2.learning_rate == 0.1  # property consults the scheduler


# --------------------------------------------------------------------------
# full optimizer zoo inside the compiled step (VERDICT r4 item 4):
# ShardedTrainer numerics must equal the eager gluon Trainer driving the
# same optimizer (which itself is tested against reference numerics in
# test_optimizer.py)

_ZOO = [
    ("sgd", {"momentum": 0.9, "wd": 1e-3}),
    ("nag", {"momentum": 0.9}),
    ("signum", {"momentum": 0.9, "wd_lh": 1e-3}),
    ("lars", {"momentum": 0.9, "eta": 0.01}),
    ("lbsgd", {"momentum": 0.9, "warmup_strategy": "linear",
               "warmup_epochs": 1, "updates_per_epoch": 4}),
    ("dcasgd", {"momentum": 0.9, "lamda": 0.04}),
    ("adam", {}),
    ("ftml", {}),
    ("lamb", {}),
    ("adagrad", {}),
    ("rmsprop", {}),
    ("rmsprop", {"centered": True}),
    ("adadelta", {}),
    ("ftrl", {}),
    ("adamax", {}),
    ("nadam", {}),
    ("adamax", {"wd": 1e-3, "clip_gradient": 0.01}),
    ("nadam", {"wd": 1e-3, "clip_gradient": 0.01}),
    ("test", {}),
]


def _zoo_data():
    rs = np.random.RandomState(7)
    x = mx.nd.array(rs.randn(16, 12).astype(np.float32))
    y = mx.nd.array(rs.randn(16, 4).astype(np.float32))
    return x, y


def _zoo_net(x):
    mx.random.seed(3)
    net = nn.HybridSequential()
    # weight-only: gluon Trainer applies wd to every Parameter
    # (wd_mult=1.0 default) while the sharded step zeroes bias wd —
    # keep the comparison on the shared semantics
    net.add(nn.Dense(6, in_units=12, use_bias=False),
            nn.Dense(4, in_units=6, use_bias=False))
    net.initialize(mx.init.Xavier())
    net(x)
    return net


@pytest.mark.parametrize("name,params", _ZOO,
                         ids=[f"{n}-{i}" for i, (n, _) in enumerate(_ZOO)])
def test_sharded_trainer_matches_eager_optimizer(name, params):
    from mxnet_tpu import autograd, gluon

    x, y = _zoo_data()
    steps = 3

    # nadam's momentum-schedule state is per-parameter in the compiled
    # rule; the eager reference shares one schedule across params
    # (order-dependent), so compare on a single-parameter net
    def build():
        if name == "nadam":
            mx.random.seed(3)
            net = nn.HybridSequential()
            net.add(nn.Dense(4, in_units=12, use_bias=False))
            net.initialize(mx.init.Xavier())
            net(x)
            return net
        return _zoo_net(x)

    net_s = build()
    tr = ShardedTrainer(net_s, gloss.L2Loss(), name,
                        {"learning_rate": 0.05, **params},
                        mesh=DeviceMesh({"dp": 8}))
    for _ in range(steps):
        tr.step(x, y)
    tr.unshard()
    got = [p.data().asnumpy() for p in net_s.collect_params().values()]

    net_e = build()
    eager = gluon.Trainer(net_e.collect_params(), name,
                          {"learning_rate": 0.05, **params})
    for _ in range(steps):
        with autograd.record():
            loss = gloss.L2Loss()(net_e(x), y).mean()
        loss.backward()
        eager.step(1)
    want = [p.data().asnumpy() for p in net_e.collect_params().values()]

    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=2e-5, atol=2e-6)


def test_sharded_trainer_sgld_runs():
    """SGLD is stochastic (different rng streams eager vs compiled):
    check the compiled step trains and stays finite."""
    x, y = _zoo_data()
    net = _zoo_net(x)
    tr = ShardedTrainer(net, gloss.L2Loss(), "sgld",
                        {"learning_rate": 0.01},
                        mesh=DeviceMesh({"dp": 8}))
    before = [p.data().asnumpy().copy()
              for p in net.collect_params().values()]
    losses = [float(tr.step(x, y).asscalar()) for _ in range(3)]
    assert all(np.isfinite(losses))
    tr.unshard()
    after = [p.data().asnumpy() for p in net.collect_params().values()]
    assert all(np.isfinite(a).all() for a in after)
    assert any(np.abs(a - b).max() > 0 for a, b in zip(after, before))


def test_sharded_trainer_multi_precision_master_weights():
    """bf16 params + multi_precision=True: fp32 master copy leads each
    state tuple and the trajectory tracks the fp32 run far better than
    a pure-bf16 run after many steps."""
    x, y = _zoo_data()

    def build(dtype):
        net = _zoo_net(x)
        if dtype != "float32":
            net.cast(dtype)
            net(x.astype(dtype))
        return net

    def run(dtype, mp):
        net = build(dtype)
        tr = ShardedTrainer(
            net, gloss.L2Loss(),
            mx.optimizer.SGD(learning_rate=0.05, momentum=0.9,
                             multi_precision=mp),
            mesh=DeviceMesh({"dp": 8}))
        xx = x.astype(dtype) if dtype != "float32" else x
        for _ in range(20):
            tr.step(xx, y)
        if mp:
            assert all(str(per[0].dtype) == "float32"
                       for per in tr._opt_raws)
        tr.unshard()
        return [p.data().asnumpy().astype(np.float32)
                for p in net.collect_params().values()]

    ref = run("float32", False)
    got_mp = run("bfloat16", True)
    got_lp = run("bfloat16", False)
    err_mp = max(np.abs(a - b).max() for a, b in zip(got_mp, ref))
    err_lp = max(np.abs(a - b).max() for a, b in zip(got_lp, ref))
    assert err_mp < err_lp, (err_mp, err_lp)
    assert err_mp < 0.01


def test_sharded_trainer_optimizer_instance_lr_honored():
    """An Optimizer INSTANCE carries its own lr (and scheduler): the
    compiled step must use it, not the 0.01 default."""
    x, y = _zoo_data()
    net_a = _zoo_net(x)
    tr_a = ShardedTrainer(net_a, gloss.L2Loss(),
                          mx.optimizer.SGD(learning_rate=0.05),
                          mesh=DeviceMesh({"dp": 8}))
    assert tr_a.learning_rate == 0.05
    tr_a.step(x, y)
    tr_a.unshard()
    net_b = _zoo_net(x)
    tr_b = ShardedTrainer(net_b, gloss.L2Loss(), "sgd",
                          {"learning_rate": 0.05},
                          mesh=DeviceMesh({"dp": 8}))
    tr_b.step(x, y)
    tr_b.unshard()
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-6)
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    tr_c = ShardedTrainer(_zoo_net(x), gloss.L2Loss(),
                          mx.optimizer.SGD(learning_rate=0.4,
                                           lr_scheduler=sched),
                          mesh=DeviceMesh({"dp": 8}))
    assert tr_c._lr_scheduler is sched
    assert tr_c.learning_rate == 0.4


def test_sharded_trainer_nadam_zero_scalar_state():
    """ZeRO + a scalar state slot (Nadam momentum schedule) + a sharded
    weight: the per-slot sharding must not apply a param-rank spec to
    the rank-0 state."""
    x, y = _zoo_data()
    net = _zoo_net(x)
    tr = ShardedTrainer(net, gloss.L2Loss(), "nadam",
                        {"learning_rate": 0.01},
                        mesh=DeviceMesh({"dp": 4, "tp": 2}), zero=True)
    losses = [float(tr.step(x, y).asscalar()) for _ in range(2)]
    assert all(np.isfinite(losses))


def test_sharded_trainer_lbsgd_warmup_ramp():
    """batch_scale>1 LBSGD: the compiled step must apply the eager
    _get_lbmult lr ramp each step (accumulation itself is accum_steps'
    job). Reference trajectory: compiled SGD-momentum re-fed the ramped
    lr per step."""
    from mxnet_tpu.optimizer import LBSGD

    x, y = _zoo_data()
    base_lr, steps = 0.02, 5
    for strategy, epochs in [("sqrt", 1), ("linear", 0)]:
        mx.random.seed(11)
        net_a = _zoo_net(x)
        with pytest.warns(UserWarning, match="batch_scale"):
            tr_a = ShardedTrainer(
                net_a, gloss.L2Loss(), "lbsgd",
                {"learning_rate": base_lr, "momentum": 0.9,
                 "warmup_strategy": strategy, "batch_scale": 4,
                 "warmup_epochs": epochs, "updates_per_epoch": 3},
                mesh=DeviceMesh({"dp": 8}))
        for _ in range(steps):
            tr_a.step(x, y)
        tr_a.unshard()

        ref_opt = LBSGD(momentum=0.9, warmup_strategy=strategy,
                        batch_scale=4, warmup_epochs=epochs,
                        updates_per_epoch=3)
        mx.random.seed(11)
        net_b = _zoo_net(x)
        tr_b = ShardedTrainer(net_b, gloss.L2Loss(), "sgd",
                              {"learning_rate": base_lr, "momentum": 0.9},
                              mesh=DeviceMesh({"dp": 8}))
        for t in range(1, steps + 1):
            tr_b.set_learning_rate(base_lr * ref_opt._get_lbmult(t))
            tr_b.step(x, y)
        tr_b.unshard()
        for pa, pb in zip(net_a.collect_params().values(),
                          net_b.collect_params().values()):
            np.testing.assert_allclose(pa.data().asnumpy(),
                                       pb.data().asnumpy(),
                                       rtol=1e-5, atol=1e-7)


def test_sharded_trainer_instance_rejects_leftover_params():
    x, _ = _zoo_data()
    net = _zoo_net(x)
    with pytest.raises(ValueError, match="Optimizer instance"):
        ShardedTrainer(net, gloss.L2Loss(),
                       mx.optimizer.SGD(learning_rate=0.05),
                       {"momentum": 0.9}, mesh=DeviceMesh({"dp": 8}))


def test_sharded_trainer_instance_lr_seeds_param_scheduler():
    """A scheduler passed via optimizer_params must be seeded with the
    INSTANCE's lr, not the 0.01 default."""
    x, _ = _zoo_data()
    net = _zoo_net(x)
    sched = mx.lr_scheduler.FactorScheduler(step=100, factor=0.5)
    tr = ShardedTrainer(net, gloss.L2Loss(),
                        mx.optimizer.SGD(learning_rate=0.4),
                        {"lr_scheduler": sched},
                        mesh=DeviceMesh({"dp": 8}))
    assert sched.base_lr == 0.4
    assert tr.learning_rate == 0.4
