"""Registry-wide operator sweep.

Parity model: the reference's test_operator.py checks every registered op
forward against a numpy oracle and its gradient against finite
differences (check_numeric_gradient, python/mxnet/test_utils.py:1101).
Here a declarative CASES table drives one parametrized forward test per
op (oracle comparison, property check, or finite/shape self-consistency)
plus a numeric-gradient pass for a representative differentiable subset,
and a meta-test enforces that >=90% of `registry.list_ops()` names are
exercised somewhere in tests/.
"""
import glob
import os
import re

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import registry
from mxnet_tpu.test_utils import check_numeric_gradient

RS = np.random.RandomState(42)


def _seed_case(name):
    """Per-case deterministic data: F/FP/I draw from a RandomState
    seeded by the case name, so one case's inputs never depend on
    collection order or on which other cases exist."""
    import zlib

    global RS
    RS = np.random.RandomState(zlib.crc32(name.encode()) & 0x7FFFFFFF)

# name -> dict(inputs=callable->list[np.ndarray], kwargs, oracle, check,
#              rtol/atol)
CASES = {}


def case(name, inputs, kwargs=None, oracle=None, check=None, rtol=1e-4,
         atol=1e-5):
    assert name not in CASES, f"duplicate case {name}"
    CASES[name] = dict(inputs=inputs, kwargs=kwargs or {}, oracle=oracle,
                       check=check, rtol=rtol, atol=atol)


def F(*shape):
    """float32 data in (-1, 1), deterministic."""
    return (RS.rand(*shape).astype(np.float32) * 2 - 1) if shape else \
        np.float32(RS.rand() * 2 - 1)


def FP(*shape):
    """strictly positive float32 data in (0.1, 1.1)."""
    return RS.rand(*shape).astype(np.float32) + 0.1


def I(*shape, high=5):
    return RS.randint(0, high, shape).astype(np.int32)


def B(*shape):
    return RS.rand(*shape) > 0.5


# ----------------------------------------------------------- unary math ---
_UNARY = {
    "abs": (np.abs, F), "negative": (np.negative, F), "exp": (np.exp, F),
    "expm1": (np.expm1, F), "log": (np.log, FP), "log10": (np.log10, FP),
    "log2": (np.log2, FP), "log1p": (np.log1p, FP), "sqrt": (np.sqrt, FP),
    "rsqrt": (lambda x: 1 / np.sqrt(x), FP),
    "cbrt": (np.cbrt, F), "square": (np.square, F), "sign": (np.sign, F),
    "sin": (np.sin, F), "cos": (np.cos, F), "tan": (np.tan, F),
    "sinh": (np.sinh, F), "cosh": (np.cosh, F), "tanh": (np.tanh, F),
    "arcsin": (np.arcsin, F), "arccos": (np.arccos, F),
    "arctan": (np.arctan, F), "arcsinh": (np.arcsinh, F),
    "arccosh": (np.arccosh, lambda *s: FP(*s) + 1.5),
    "arctanh": (np.arctanh, lambda *s: F(*s) * 0.9),
    "ceil": (np.ceil, F), "floor": (np.floor, F), "trunc": (np.trunc, F),
    "rint": (np.rint, F), "round": (np.round, F), "fix": (np.fix, F),
    "reciprocal": (np.reciprocal, FP),
    "relu": (lambda x: np.maximum(x, 0), F),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), F),
    "softsign": (lambda x: x / (1 + np.abs(x)), F),
    "erf": (None, F), "erfinv": (None, lambda *s: F(*s) * 0.5),
    "gamma": (None, FP), "gammaln": (None, FP), "digamma": (None, FP),
    "logical_not": (lambda x: np.logical_not(x).astype(np.float32), F),
    "zeros_like": (np.zeros_like, F), "ones_like": (np.ones_like, F),
    "copy": (np.array, F), "BlockGrad": (np.array, F),
    "make_loss": (np.array, F), "relu6": (lambda x: np.minimum(
        np.maximum(x, 0), 6), lambda *s: F(*s) * 8),
    "softplus": (lambda x: np.log1p(np.exp(x)), F),
    "hard_sigmoid": (lambda x: np.clip(0.2 * x + 0.5, 0, 1), F),
    "degrees": (np.degrees, F), "radians": (np.radians, F),
    "argmax_channel": (lambda x: np.argmax(x, 1).astype(np.float32), F),
}
try:
    from scipy import special as _sp

    _UNARY["erf"] = (_sp.erf, F)
    _UNARY["erfinv"] = (_sp.erfinv, lambda *s: F(*s) * 0.5)
    _UNARY["gamma"] = (_sp.gamma, FP)
    _UNARY["gammaln"] = (_sp.gammaln, FP)
    _UNARY["digamma"] = (_sp.digamma, FP)
except ImportError:
    pass

for _n, (_fn, _gen) in _UNARY.items():
    case(_n, (lambda g=_gen: [g(2, 3)]), oracle=_fn)

# _npi twins of the unary family
_NPI_UNARY = {
    "_npi_absolute": np.abs, "_npi_negative": np.negative,
    "_npi_exp": np.exp, "_npi_expm1": np.expm1, "_npi_sign": np.sign,
    "_npi_square": np.square, "_npi_cbrt": np.cbrt, "_npi_ceil": np.ceil,
    "_npi_floor": np.floor, "_npi_trunc": np.trunc, "_npi_rint": np.rint,
    "_npi_around": np.round, "_npi_fix": np.fix, "_npi_sin": np.sin,
    "_npi_cos": np.cos, "_npi_tan": np.tan, "_npi_sinh": np.sinh,
    "_npi_cosh": np.cosh, "_npi_tanh": np.tanh, "_npi_arcsin": np.arcsin,
    "_npi_arccos": np.arccos, "_npi_arctan": np.arctan,
    "_npi_arcsinh": np.arcsinh, "_npi_deg2rad": np.deg2rad,
    "_npi_degrees": np.degrees, "_npi_rad2deg": np.rad2deg,
    "_npi_radians": np.radians, "_npi_isnan": np.isnan,
    "_npi_isinf": np.isinf, "_npi_isfinite": np.isfinite,
    "_npi_isposinf": np.isposinf, "_npi_isneginf": np.isneginf,
    "_npi_logical_not": np.logical_not, "_npi_conj": np.conj,
    "_npi_real": np.real, "_npi_imag": np.imag,
    "_np_copy": np.array,
}
for _n, _fn in _NPI_UNARY.items():
    case(_n, lambda: [F(2, 3)], oracle=_fn)
case("_npi_sqrt", lambda: [FP(2, 3)], oracle=np.sqrt)
case("_npi_log", lambda: [FP(2, 3)], oracle=np.log)
case("_npi_log2", lambda: [FP(2, 3)], oracle=np.log2)
case("_npi_log10", lambda: [FP(2, 3)], oracle=np.log10)
case("_npi_log1p", lambda: [FP(2, 3)], oracle=np.log1p)
case("_npi_reciprocal", lambda: [FP(2, 3)], oracle=np.reciprocal)
case("_npi_arccosh", lambda: [FP(2, 3) + 1.5], oracle=np.arccosh)
case("_npi_arctanh", lambda: [F(2, 3) * 0.9], oracle=np.arctanh)
case("_npi_bitwise_not", lambda: [I(2, 3)], oracle=np.bitwise_not)
case("_npi_invert", lambda: [I(2, 3)], oracle=np.invert)

# --------------------------------------------------------- binary math ----
_BINARY = {
    "elemwise_add": np.add, "elemwise_sub": np.subtract,
    "elemwise_mul": np.multiply, "elemwise_div": lambda a, b: a / b,
    "elemwise_maximum": np.maximum, "elemwise_minimum": np.minimum,
    "elemwise_power": None, "elemwise_hypot": np.hypot,
    "elemwise_arctan2": np.arctan2,
    "elemwise_equal": lambda a, b: (a == b).astype(np.float32),
    "elemwise_not_equal": lambda a, b: (a != b).astype(np.float32),
    "elemwise_greater": lambda a, b: (a > b).astype(np.float32),
    "elemwise_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "elemwise_lesser": lambda a, b: (a < b).astype(np.float32),
    "elemwise_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "elemwise_logical_and": lambda a, b: np.logical_and(a, b).astype(np.float32),
    "elemwise_logical_or": lambda a, b: np.logical_or(a, b).astype(np.float32),
    "elemwise_logical_xor": lambda a, b: np.logical_xor(a, b).astype(np.float32),
    "elemwise_mod": np.mod,
}
for _n, _fn in _BINARY.items():
    if _fn is not None:
        case(_n, lambda: [F(2, 3), FP(2, 3)], oracle=_fn)
case("elemwise_power", lambda: [FP(2, 3), F(2, 3)], oracle=np.power)

_BROADCAST = {
    "broadcast_add": np.add, "broadcast_sub": np.subtract,
    "broadcast_mul": np.multiply, "broadcast_div": lambda a, b: a / b,
    "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
    "broadcast_hypot": np.hypot, "broadcast_arctan2": np.arctan2,
    "broadcast_mod": np.mod,
    "broadcast_equal": lambda a, b: (a == b).astype(np.float32),
    "broadcast_not_equal": lambda a, b: (a != b).astype(np.float32),
    "broadcast_greater": lambda a, b: (a > b).astype(np.float32),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "broadcast_lesser": lambda a, b: (a < b).astype(np.float32),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "broadcast_logical_and": lambda a, b: np.logical_and(a, b).astype(np.float32),
    "broadcast_logical_or": lambda a, b: np.logical_or(a, b).astype(np.float32),
    "broadcast_logical_xor": lambda a, b: np.logical_xor(a, b).astype(np.float32),
}
for _n, _fn in _BROADCAST.items():
    case(_n, lambda: [F(2, 3), FP(1, 3)], oracle=_fn)
case("broadcast_power", lambda: [FP(2, 3), F(1, 3)], oracle=np.power)

_NPI_BINARY = {
    "_npi_add": np.add, "_npi_subtract": np.subtract,
    "_npi_multiply": np.multiply, "_npi_true_divide": np.true_divide,
    "_npi_maximum": np.maximum, "_npi_minimum": np.minimum,
    "_npi_fmax": np.fmax, "_npi_fmin": np.fmin, "_npi_fmod": np.fmod,
    "_npi_hypot": np.hypot, "_npi_arctan2": np.arctan2,
    "_npi_copysign": np.copysign, "_npi_logaddexp": np.logaddexp,
    "_npi_equal": np.equal, "_npi_not_equal": np.not_equal,
    "_npi_greater": np.greater, "_npi_greater_equal": np.greater_equal,
    "_npi_less": np.less, "_npi_less_equal": np.less_equal,
    "_npi_logical_and": np.logical_and, "_npi_logical_or": np.logical_or,
    "_npi_logical_xor": np.logical_xor, "_npi_mod": np.mod,
    "_npi_remainder": np.remainder, "_npi_ldexp": None,
}
for _n, _fn in _NPI_BINARY.items():
    if _fn is not None:
        case(_n, lambda: [F(2, 3), FP(2, 3)], oracle=_fn)
case("_npi_ldexp", lambda: [F(2, 3), I(2, 3)], oracle=np.ldexp)
case("_npi_power", lambda: [FP(2, 3), F(2, 3)], oracle=np.power)
case("_npi_floor_divide", lambda: [F(2, 3), FP(2, 3)],
     oracle=np.floor_divide)
case("_npi_bitwise_and", lambda: [I(2, 3), I(2, 3)], oracle=np.bitwise_and)
case("_npi_bitwise_or", lambda: [I(2, 3), I(2, 3)], oracle=np.bitwise_or)
case("_npi_bitwise_xor", lambda: [I(2, 3), I(2, 3)], oracle=np.bitwise_xor)
case("_npi_gcd", lambda: [I(2, 3), I(2, 3)], oracle=np.gcd)
case("_npi_lcm", lambda: [I(2, 3), I(2, 3)], oracle=np.lcm)
case("_npi_left_shift", lambda: [I(2, 3), I(2, 3, high=3)],
     oracle=np.left_shift)
case("_npi_right_shift", lambda: [I(2, 3), I(2, 3, high=3)],
     oracle=np.right_shift)

# ---------------------------------------------------------- scalar ops ----
_SCALAR = {
    "_plus_scalar": lambda x, scalar: x + scalar,
    "_minus_scalar": lambda x, scalar: x - scalar,
    "_rminus_scalar": lambda x, scalar: scalar - x,
    "_mul_scalar": lambda x, scalar: x * scalar,
    "_div_scalar": lambda x, scalar: x / scalar,
    "_rdiv_scalar": lambda x, scalar: scalar / x,
    "_mod_scalar": lambda x, scalar: np.mod(x, scalar),
    "_rmod_scalar": lambda x, scalar: np.mod(scalar, x),
    "_maximum_scalar": lambda x, scalar: np.maximum(x, scalar),
    "_minimum_scalar": lambda x, scalar: np.minimum(x, scalar),
    "_equal_scalar": lambda x, scalar: (x == scalar).astype(np.float32),
    "_not_equal_scalar": lambda x, scalar: (x != scalar).astype(np.float32),
    "_greater_scalar": lambda x, scalar: (x > scalar).astype(np.float32),
    "_greater_equal_scalar": lambda x, scalar: (x >= scalar).astype(np.float32),
    "_lesser_scalar": lambda x, scalar: (x < scalar).astype(np.float32),
    "_lesser_equal_scalar": lambda x, scalar: (x <= scalar).astype(np.float32),
}
for _n, _fn in _SCALAR.items():
    case(_n, lambda: [FP(2, 3)], kwargs={"scalar": 0.5}, oracle=_fn)
case("_power_scalar", lambda: [FP(2, 3)], kwargs={"scalar": 2.0},
     oracle=lambda x, scalar: np.power(x, scalar))
case("_rpower_scalar", lambda: [F(2, 3)], kwargs={"scalar": 2.0},
     oracle=lambda x, scalar: np.power(scalar, x))
case("smooth_l1", lambda: [F(2, 3)], kwargs={"scalar": 1.0},
     oracle=lambda x, scalar: np.where(
         np.abs(x) < 1.0 / scalar ** 2, 0.5 * (scalar * x) ** 2,
         np.abs(x) - 0.5 / scalar ** 2))

_NPI_SCALAR = {
    "_npi_add_scalar": lambda x, scalar: x + scalar,
    "_npi_subtract_scalar": lambda x, scalar: x - scalar,
    "_npi_rsubtract_scalar": lambda x, scalar: scalar - x,
    "_npi_multiply_scalar": lambda x, scalar: x * scalar,
    "_npi_true_divide_scalar": lambda x, scalar: x / scalar,
    "_npi_rtrue_divide_scalar": lambda x, scalar: scalar / x,
    "_npi_mod_scalar": lambda x, scalar: np.mod(x, scalar),
    "_npi_rmod_scalar": lambda x, scalar: np.mod(scalar, x),
    "_npi_floor_divide_scalar": lambda x, scalar: np.floor_divide(x, scalar),
    "_npi_rfloor_divide_scalar": lambda x, scalar: np.floor_divide(scalar, x),
}
for _n, _fn in _NPI_SCALAR.items():
    case(_n, lambda: [FP(2, 3)], kwargs={"scalar": 0.5}, oracle=_fn)
case("_npi_power_scalar", lambda: [FP(2, 3)], kwargs={"scalar": 2.0},
     oracle=lambda x, scalar: np.power(x, scalar))
case("_npi_rpower_scalar", lambda: [F(2, 3)], kwargs={"scalar": 2.0},
     oracle=lambda x, scalar: np.power(scalar, x))
case("_npi_bitwise_and_scalar", lambda: [I(2, 3)], kwargs={"scalar": 3},
     oracle=lambda x, scalar: np.bitwise_and(x, scalar))
case("_npi_bitwise_or_scalar", lambda: [I(2, 3)], kwargs={"scalar": 3},
     oracle=lambda x, scalar: np.bitwise_or(x, scalar))
case("_npi_bitwise_xor_scalar", lambda: [I(2, 3)], kwargs={"scalar": 3},
     oracle=lambda x, scalar: np.bitwise_xor(x, scalar))
case("_npi_lcm_scalar", lambda: [I(2, 3)], kwargs={"scalar": 4},
     oracle=lambda x, scalar: np.lcm(x, scalar))

# ----------------------------------------------------------- reductions ---
case("sum", lambda: [F(2, 3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.sum(x, axis=axis))
case("mean", lambda: [F(2, 3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.mean(x, axis=axis))
case("max", lambda: [F(2, 3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.max(x, axis=axis))
case("min", lambda: [F(2, 3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.min(x, axis=axis))
case("prod", lambda: [F(2, 3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.prod(x, axis=axis))
case("nansum", lambda: [F(2, 3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.nansum(x, axis=axis))
case("nanprod", lambda: [F(2, 3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.nanprod(x, axis=axis))
case("norm", lambda: [F(2, 3)], kwargs={},
     oracle=lambda x: np.linalg.norm(x))
case("argmax", lambda: [F(2, 5)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.argmax(x, axis=axis).astype(np.float32))
case("argmin", lambda: [F(2, 5)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.argmin(x, axis=axis).astype(np.float32))
case("moments", lambda: [F(2, 5)], kwargs={"axes": (1,)},
     oracle=lambda x, axes: (np.mean(x, axis=axes),
                             np.var(x, axis=axes)))

_NPI_RED = {
    "_npi_sum": np.sum, "_npi_mean": np.mean, "_npi_amax": np.amax,
    "_npi_amin": np.amin, "_npi_max": np.max, "_npi_min": np.min,
    "_npi_prod": np.prod, "_npi_nansum": np.nansum,
    "_npi_nanprod": np.nanprod, "_npi_std": np.std, "_npi_var": np.var,
    "_npi_all": np.all, "_npi_any": np.any, "_np_all": np.all,
    "_np_any": np.any, "_npi_median": np.median,
    "_npi_count_nonzero": np.count_nonzero, "_npi_ptp": np.ptp,
}


def _npi_red_case(name, fn):
    case(name, lambda: [F(3, 4)], kwargs={"axis": 1},
         oracle=lambda a, axis: fn(a, axis=axis))


for _n, _fn in _NPI_RED.items():
    _npi_red_case(_n, _fn)
case("_npi_norm", lambda: [F(2, 3)], oracle=lambda a: np.linalg.norm(a))
case("_npi_average", lambda: [F(3, 4), FP(3, 4)],
     oracle=lambda a, w: np.average(a, weights=w))
case("_npi_percentile", lambda: [F(3, 4)], kwargs={"q": 30.0},
     oracle=lambda a, q: np.percentile(a, q).astype(np.float32))
case("_npi_quantile", lambda: [F(3, 4)], kwargs={"q": 0.3},
     oracle=lambda a, q: np.quantile(a, q).astype(np.float32))
case("_npi_cumsum", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.cumsum(a, axis=axis))
case("_np_cumsum", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.cumsum(a, axis=axis))
case("_npi_cumprod", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.cumprod(a, axis=axis))
case("_npi_diff", lambda: [F(3, 4)],
     oracle=lambda a: np.diff(a))
case("_npi_ediff1d", lambda: [F(6)], oracle=np.ediff1d)
case("_npi_gradient_op", lambda: [F(6)],
     oracle=lambda a: np.gradient(a))
case("_npi_bincount", lambda: [I(8)],
     oracle=lambda a: np.bincount(a).astype(np.int32), atol=0)
case("_npi_interp", lambda: [np.array([0.5, 1.5], np.float32),
                             np.array([0.0, 1.0, 2.0], np.float32),
                             np.array([0.0, 10.0, 20.0], np.float32)],
     oracle=np.interp)
case("_npi_nan_to_num",
     lambda: [np.array([[1.0, np.nan], [np.inf, -np.inf]], np.float32)],
     oracle=lambda a: np.nan_to_num(a))

# ------------------------------------------------- shape / index / slice ---
case("reshape", lambda: [F(2, 6)], kwargs={"shape": (3, 4)},
     oracle=lambda x, shape: x.reshape(shape))
case("_np_reshape", lambda: [F(2, 6)], kwargs={"newshape": (3, 4)},
     oracle=lambda a, newshape: a.reshape(newshape))
case("_npi_reshape", lambda: [F(2, 6)], kwargs={"newshape": (3, 4)},
     oracle=lambda a, newshape: a.reshape(newshape))
case("_npx_reshape", lambda: [F(2, 6)], kwargs={"newshape": (3, 4)},
     oracle=lambda data, newshape: data.reshape(newshape))
case("reshape_like", lambda: [F(2, 6), F(3, 4)],
     oracle=lambda x, like: x.reshape(like.shape))
case("transpose", lambda: [F(2, 3)], kwargs={"axes": (1, 0)},
     oracle=lambda x, axes: np.transpose(x, axes))
case("_np_transpose", lambda: [F(2, 3)],
     oracle=lambda a: a.T)
case("_npi_transpose", lambda: [F(2, 3)],
     oracle=lambda a: a.T)
case("swapaxes", lambda: [F(2, 3, 4)], kwargs={"dim1": 0, "dim2": 2},
     oracle=lambda x, dim1, dim2: np.swapaxes(x, dim1, dim2))
case("_npi_swapaxes", lambda: [F(2, 3, 4)], kwargs={"dim1": 0, "dim2": 2},
     oracle=lambda a, dim1, dim2: np.swapaxes(a, dim1, dim2))
case("_npi_moveaxis", lambda: [F(2, 3, 4)],
     kwargs={"source": 0, "destination": 2},
     oracle=lambda a, source, destination: np.moveaxis(a, source,
                                                       destination))
case("_np_moveaxis", lambda: [F(2, 3, 4)],
     kwargs={"source": 0, "destination": 2},
     oracle=lambda a, source, destination: np.moveaxis(a, source,
                                                       destination))
case("expand_dims", lambda: [F(2, 3)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.expand_dims(x, axis))
case("_npi_expand_dims", lambda: [F(2, 3)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.expand_dims(a, axis))
case("squeeze", lambda: [F(2, 1, 3)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.squeeze(x, axis))
case("_np_squeeze", lambda: [F(2, 1, 3)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.squeeze(a, axis))
case("_npi_squeeze", lambda: [F(2, 1, 3)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.squeeze(a, axis))
case("Flatten", lambda: [F(2, 3, 4)],
     oracle=lambda x: x.reshape(2, 12))
case("flip", lambda: [F(2, 3)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.flip(x, axis))
case("reverse", lambda: [F(2, 3)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.flip(x, axis))
case("_npi_flip", lambda: [F(2, 3)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.flip(a, axis))
case("_npi_fliplr", lambda: [F(2, 3)], oracle=np.fliplr)
case("_npi_flipud", lambda: [F(2, 3)], oracle=np.flipud)
case("_npi_rot90", lambda: [F(2, 3)], kwargs={"k": 1, "axes": (0, 1)},
     oracle=lambda a, k, axes: np.rot90(a, k, axes))
case("_npi_roll", lambda: [F(2, 3)], kwargs={"shift": 1, "axis": 1},
     oracle=lambda a, shift, axis: np.roll(a, shift, axis))
case("_np_roll", lambda: [F(2, 3)], kwargs={"shift": 1, "axis": 1},
     oracle=lambda a, shift, axis: np.roll(a, shift, axis))
case("tile", lambda: [F(2, 3)], kwargs={"reps": (2, 1)},
     oracle=lambda x, reps: np.tile(x, reps))
case("_npi_tile", lambda: [F(2, 3)], kwargs={"reps": (2, 1)},
     oracle=lambda a, reps: np.tile(a, reps))
case("repeat", lambda: [F(2, 3)], kwargs={"repeats": 2, "axis": 1},
     oracle=lambda x, repeats, axis: np.repeat(x, repeats, axis))
case("_npi_repeat", lambda: [F(2, 3)], kwargs={"repeats": 2, "axis": 1},
     oracle=lambda a, repeats, axis: np.repeat(a, repeats, axis))
case("Concat", lambda: [F(2, 3), F(2, 4)], kwargs={"dim": 1},
     oracle=lambda a, b, dim: np.concatenate([a, b], axis=dim))
case("_npi_concatenate", lambda: [F(2, 3), F(2, 4)], kwargs={"axis": 1},
     oracle=lambda a, b, axis: np.concatenate([a, b], axis=axis))
case("stack", lambda: [F(2, 3), F(2, 3)], kwargs={"axis": 0},
     oracle=lambda a, b, axis: np.stack([a, b], axis=axis))
case("_npi_stack", lambda: [F(2, 3), F(2, 3)], kwargs={"axis": 0},
     oracle=lambda a, b, axis: np.stack([a, b], axis=axis))
case("_npi_vstack", lambda: [F(2, 3), F(2, 3)],
     oracle=lambda a, b: np.vstack([a, b]))
case("_npi_hstack", lambda: [F(2, 3), F(2, 3)],
     oracle=lambda a, b: np.hstack([a, b]))
case("_npi_dstack", lambda: [F(2, 3), F(2, 3)],
     oracle=lambda a, b: np.dstack([a, b]))
case("_npi_column_stack", lambda: [F(4), F(4)],
     oracle=lambda a, b: np.column_stack([a, b]))
case("add_n", lambda: [F(2, 3), F(2, 3), F(2, 3)],
     oracle=lambda a, b, c: a + b + c)
case("slice", lambda: [F(4, 5)], kwargs={"begin": (1, 0), "end": (3, 4)},
     oracle=lambda x, begin, end: x[1:3, 0:4])
case("slice_axis", lambda: [F(4, 5)],
     kwargs={"axis": 1, "begin": 1, "end": 4},
     oracle=lambda x, axis, begin, end: x[:, 1:4])
case("slice_like", lambda: [F(4, 5), F(2, 3)],
     oracle=lambda x, like: x[:2, :3])
case("SliceChannel", lambda: [F(2, 4)],
     kwargs={"num_outputs": 2, "axis": 1},
     oracle=lambda x, num_outputs, axis: (x[:, :2], x[:, 2:]))
case("_split_v2", lambda: [F(2, 4)], kwargs={"sections": 2, "axis": 1},
     oracle=lambda data, sections, axis: (data[:, :2], data[:, 2:]))
case("split_v2", lambda: [F(2, 4)], kwargs={"sections": 2, "axis": 1},
     oracle=lambda data, sections, axis: (data[:, :2], data[:, 2:]))
case("_npi_split", lambda: [F(2, 4)],
     kwargs={"indices_or_sections": 2, "axis": 1},
     oracle=lambda a, indices_or_sections, axis: (a[:, :2], a[:, 2:]))
case("_npi_array_split", lambda: [F(2, 4)],
     kwargs={"indices_or_sections": 2, "axis": 1},
     oracle=lambda a, indices_or_sections, axis: (a[:, :2], a[:, 2:]))
case("_npi_hsplit", lambda: [F(2, 4)],
     kwargs={"indices_or_sections": 2},
     oracle=lambda a, indices_or_sections: tuple(np.hsplit(a, 2)))
case("_npi_vsplit", lambda: [F(4, 2)],
     kwargs={"indices_or_sections": 2},
     oracle=lambda a, indices_or_sections: tuple(np.vsplit(a, 2)))
case("_npi_dsplit", lambda: [F(2, 2, 4)],
     kwargs={"indices_or_sections": 2},
     oracle=lambda a, indices_or_sections: tuple(np.dsplit(a, 2)))
case("clip", lambda: [F(2, 3)], kwargs={"a_min": -0.5, "a_max": 0.5},
     oracle=lambda x, a_min, a_max: np.clip(x, a_min, a_max))
case("_npi_clip", lambda: [F(2, 3)], kwargs={"a_min": -0.5, "a_max": 0.5},
     oracle=lambda a, a_min, a_max: np.clip(a, a_min, a_max))
case("take", lambda: [F(5, 3), I(2, high=5)], kwargs={"axis": 0},
     oracle=lambda a, idx, axis: np.take(a, idx, axis))
case("_npi_take", lambda: [F(5, 3), I(2, high=5)], kwargs={"axis": 0},
     oracle=lambda a, idx, axis: np.take(a, idx, axis))
case("_npi_take_along_axis", lambda: [F(3, 4), I(3, 1, high=4)],
     kwargs={"axis": 1},
     oracle=lambda a, idx, axis: np.take_along_axis(a, idx.astype(np.int64),
                                                    axis))
case("batch_take", lambda: [F(3, 4), I(3, high=4)],
     oracle=lambda a, idx: a[np.arange(3), idx])
case("pick", lambda: [F(3, 4), I(3, high=4).astype(np.float32)],
     kwargs={"axis": 1},
     oracle=lambda a, idx, axis: a[np.arange(3), idx.astype(np.int64)])
case("choose_element_0index", lambda: [F(3, 4),
                                       I(3, high=4).astype(np.float32)],
     oracle=lambda a, idx: a[np.arange(3), idx.astype(np.int64)])
case("gather_nd", lambda: [F(3, 4), I(2, 2, high=3)],
     oracle=lambda a, idx: a[idx[0], idx[1]])
case("one_hot", lambda: [I(4, high=5).astype(np.float32)],
     kwargs={"depth": 5},
     oracle=lambda idx, depth: np.eye(depth,
                                      dtype=np.float32)[idx.astype(int)])
case("where", lambda: [B(2, 3).astype(np.float32), F(2, 3), F(2, 3)],
     oracle=lambda c, x, y: np.where(c != 0, x, y))
case("_npi_where", lambda: [B(2, 3), F(2, 3), F(2, 3)],
     oracle=np.where)
case("_npi_where_lscalar", lambda: [B(2, 3), F(2, 3)],
     kwargs={"scalar": 2.0},
     oracle=lambda c, x, scalar: np.where(c, x, scalar))
case("_npi_where_rscalar", lambda: [B(2, 3), F(2, 3)],
     kwargs={"scalar": 2.0},
     oracle=lambda c, y, scalar: np.where(c, scalar, y))
case("_npi_where_scalar2", lambda: [B(2, 3)],
     kwargs={"lscalar": 2.0, "rscalar": 3.0},
     oracle=lambda c, lscalar, rscalar: np.where(c, lscalar, rscalar))
case("sort", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.sort(x, axis))
case("_npi_sort", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.sort(a, axis))
case("argsort", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda x, axis: np.argsort(x, axis).astype(np.float32))
case("_npi_argsort", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.argsort(a, axis))
case("_npi_argmax", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.argmax(a, axis))
case("_npi_argmin", lambda: [F(3, 4)], kwargs={"axis": 1},
     oracle=lambda a, axis: np.argmin(a, axis))
case("topk", lambda: [F(3, 8)], kwargs={"k": 2, "ret_typ": "value"},
     oracle=lambda x, k, ret_typ: -np.sort(-x, axis=-1)[:, :2])
case("_npi_searchsorted",
     lambda: [np.sort(F(8)), F(3)],
     oracle=lambda a, v: np.searchsorted(a, v))
case("pad", lambda: [F(1, 2, 3, 4)],
     kwargs={"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 2, 2)},
     oracle=lambda x, mode, pad_width: np.pad(
         x, [(0, 0), (0, 0), (1, 1), (2, 2)], mode=mode))
case("_npi_pad", lambda: [F(2, 3)],
     kwargs={"pad_width": ((1, 1), (2, 2)), "mode": "constant"},
     oracle=lambda a, pad_width, mode: np.pad(a, pad_width, mode=mode))
case("broadcast_to", lambda: [F(1, 3)], kwargs={"shape": (4, 3)},
     oracle=lambda x, shape: np.broadcast_to(x, shape))
case("_npi_broadcast_to", lambda: [F(1, 3)], kwargs={"shape": (4, 3)},
     oracle=lambda a, shape: np.broadcast_to(a, shape))
case("broadcast_axis", lambda: [F(1, 3)], kwargs={"axis": (0,),
                                                  "size": (4,)},
     oracle=lambda x, axis, size: np.broadcast_to(x, (4, 3)))
case("broadcast_like", lambda: [F(1, 3), F(4, 3)],
     oracle=lambda x, like: np.broadcast_to(x, like.shape))
case("depth_to_space", lambda: [F(1, 8, 2, 2)], kwargs={"block_size": 2},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4, 4))
case("space_to_depth", lambda: [F(1, 2, 4, 4)], kwargs={"block_size": 2},
     check=lambda outs, ins, kw: outs[0].shape == (1, 8, 2, 2))
case("diag", lambda: [F(4)], oracle=np.diag)
case("_npi_diag", lambda: [F(4)], oracle=np.diag)
case("_np_diag", lambda: [F(4)], oracle=np.diag)
case("_npi_diagflat", lambda: [F(2, 2)], oracle=np.diagflat)
case("_np_diagflat", lambda: [F(2, 2)], oracle=np.diagflat)
case("_npi_diagonal", lambda: [F(3, 3)], oracle=lambda a: np.diagonal(a))
case("_np_diagonal", lambda: [F(3, 3)], oracle=lambda a: np.diagonal(a))
case("_npi_tril", lambda: [F(3, 3)], oracle=np.tril)
case("_npi_triu", lambda: [F(3, 3)], oracle=np.triu)
case("_npi_trace", lambda: [F(3, 3)], oracle=lambda a: np.trace(a))
case("_np_trace", lambda: [F(3, 3)], oracle=lambda a: np.trace(a))
case("shape_array", lambda: [F(2, 3)],
     oracle=lambda x: np.array([2, 3], np.int64), atol=0)
case("size_array", lambda: [F(2, 3)],
     oracle=lambda x: np.array([6], np.int64), atol=0)
case("Cast", lambda: [F(2, 3)], kwargs={"dtype": "int32"},
     oracle=lambda x, dtype: x.astype(np.int32), atol=0)
case("amp_cast", lambda: [F(2, 3)], kwargs={"dtype": "float32"},
     oracle=lambda x, dtype: x)
case("_npi_atleast_1d", lambda: [F(3)], oracle=np.atleast_1d)
case("_npi_atleast_2d", lambda: [F(3)], oracle=np.atleast_2d)
case("_npi_atleast_3d", lambda: [F(3)], oracle=np.atleast_3d)
case("_npi_ravel", lambda: [F(2, 3)], oracle=np.ravel)
case("_npi_delete", lambda: [F(6)], kwargs={"obj": 2, "axis": 0},
     oracle=lambda data, obj, axis: np.delete(data, obj, axis))
case("_npi_insert_scalar", lambda: [F(5)],
     kwargs={"obj": 2, "val": 9.0, "axis": 0},
     oracle=lambda data, obj, val, axis: np.insert(data, obj,
                                                   np.float32(val), axis))
case("_ravel_multi_index",
     lambda: [np.array([[1, 0], [2, 3]], np.float32)],
     kwargs={"shape": (4, 5)},
     oracle=lambda data, shape: np.ravel_multi_index(
         data.astype(np.int64), shape).astype(np.float32))
case("ravel_multi_index",
     lambda: [np.array([[1, 0], [2, 3]], np.float32)],
     kwargs={"shape": (4, 5)},
     oracle=lambda data, shape: np.ravel_multi_index(
         data.astype(np.int64), shape).astype(np.float32))
case("_unravel_index", lambda: [np.array([7, 13], np.float32)],
     kwargs={"shape": (4, 5)},
     oracle=lambda data, shape: np.stack(np.unravel_index(
         data.astype(np.int64), shape)).astype(np.float32))
case("unravel_index", lambda: [np.array([7, 13], np.float32)],
     kwargs={"shape": (4, 5)},
     oracle=lambda data, shape: np.stack(np.unravel_index(
         data.astype(np.int64), shape)).astype(np.float32))
case("scatter_nd", lambda: [F(2), I(2, 2, high=3)],
     kwargs={"shape": (3, 3)},
     check=lambda outs, ins, kw: outs[0].shape == (3, 3))
case("_scatter_set_nd", lambda: [F(3, 3), F(2), I(2, 2, high=3)],
     check=lambda outs, ins, kw: outs[0].shape == (3, 3))
case("_slice_assign", lambda: [F(4, 5), F(2, 2)],
     kwargs={"begin": (0, 0), "end": (2, 2)},
     check=lambda outs, ins, kw: np.allclose(outs[0][:2, :2], ins[1]))
case("_slice_assign_scalar", lambda: [F(4, 5)],
     kwargs={"scalar": 7.0, "begin": (0, 0), "end": (2, 2)},
     check=lambda outs, ins, kw: np.allclose(outs[0][:2, :2], 7.0))
case("_npi_boolean_mask_assign_scalar", lambda: [F(2, 3), B(2, 3)],
     kwargs={"value": 5.0},
     check=lambda outs, ins, kw: np.allclose(outs[0][ins[1]], 5.0))
case("_npi_boolean_mask_assign_tensor",
     lambda: [F(2, 3), np.ones((2, 3), bool), F(2, 3)],
     check=lambda outs, ins, kw: np.allclose(outs[0], ins[2]))
case("_contrib_boolean_mask", lambda: [F(4, 3),
                                       np.array([1, 0, 1, 0], np.float32)],
     oracle=lambda data, idx: data[idx.astype(bool)])
case("boolean_mask", lambda: [F(4, 3),
                              np.array([1, 0, 1, 0], np.float32)],
     oracle=lambda data, idx: data[idx.astype(bool)])
case("_npi_unique", lambda: [I(8)],
     oracle=lambda a: np.unique(a).astype(np.int32), atol=0)
case("_npi_nonzero", lambda: [np.array([[1, 0], [0, 2]], np.float32)],
     check=lambda outs, ins, kw: outs[0].shape[0] == 2)
case("_npx_nonzero", lambda: [np.array([[1, 0], [0, 2]], np.float32)],
     check=lambda outs, ins, kw: outs[0].shape[0] == 2)
case("_contrib_getnnz", lambda: [np.array([[1, 0], [0, 2]], np.float32)],
     oracle=lambda data: np.array(2, np.int32), atol=0)
case("_sparse_retain", lambda: [F(4, 3), np.array([0, 2], np.float32)],
     check=lambda outs, ins, kw: np.allclose(outs[0][1], 0))
case("cast_storage", lambda: [F(2, 3)], kwargs={"stype": "default"},
     oracle=lambda data, stype: data)
case("_npi_share_memory", lambda: [F(2, 3), F(2, 3)],
     check=lambda outs, ins, kw: True)
case("_npi_diag_indices_from", lambda: [F(3, 3)],
     oracle=lambda data: np.stack(np.diag_indices_from(data)).astype(
         np.int32), atol=0)
case("fill_element_0index",
     lambda: [F(3, 4), F(3), I(3, high=4).astype(np.float32)],
     check=lambda outs, ins, kw: np.allclose(
         outs[0][np.arange(3), ins[2].astype(int)], ins[1]))
case("_identity_with_attr_like_rhs", lambda: [F(2, 3), F(2, 3)],
     oracle=lambda lhs, rhs: lhs)
case("_npi_ones", lambda: [], kwargs={"shape": (2, 3)},
     oracle=lambda shape: np.ones(shape, np.float32))
case("_npi_zeros", lambda: [], kwargs={"shape": (2, 3)},
     oracle=lambda shape: np.zeros(shape, np.float32))
case("_ones", lambda: [], kwargs={"shape": (2, 3)},
     oracle=lambda shape: np.ones(shape, np.float32))
case("_zeros", lambda: [], kwargs={"shape": (2, 3)},
     oracle=lambda shape: np.zeros(shape, np.float32))
case("_full", lambda: [], kwargs={"shape": (2, 3), "value": 2.5},
     oracle=lambda shape, value: np.full(shape, value, np.float32))
case("_npi_full", lambda: [], kwargs={"shape": (2, 3), "fill_value": 2.5},
     oracle=lambda shape, fill_value: np.full(shape, fill_value,
                                              np.float32))
case("_arange", lambda: [], kwargs={"start": 0.0, "stop": 5.0},
     oracle=lambda start, stop: np.arange(start, stop, dtype=np.float32))
case("_npi_arange", lambda: [], kwargs={"start": 0.0, "stop": 5.0},
     oracle=lambda start, stop: np.arange(start, stop, dtype=np.float32))
case("_linspace", lambda: [], kwargs={"start": 0.0, "stop": 1.0, "num": 5},
     oracle=lambda start, stop, num: np.linspace(start, stop, num,
                                                 dtype=np.float32))
case("_npi_linspace", lambda: [],
     kwargs={"start": 0.0, "stop": 1.0, "num": 5},
     oracle=lambda start, stop, num: np.linspace(start, stop, num,
                                                 dtype=np.float32))
case("_npi_logspace", lambda: [],
     kwargs={"start": 0.0, "stop": 2.0, "num": 3},
     oracle=lambda start, stop, num: np.logspace(start, stop, num,
                                                 dtype=np.float32))
case("_npi_eye", lambda: [], kwargs={"N": 3},
     oracle=lambda N: np.eye(N, dtype=np.float32))
case("_npi_indices", lambda: [], kwargs={"dimensions": (2, 3)},
     oracle=lambda dimensions: np.indices(dimensions).astype(np.int32),
     atol=0)
case("_npi_tril_indices", lambda: [], kwargs={"n": 3},
     oracle=lambda n: np.stack(np.tril_indices(n)).astype(np.int32),
     atol=0)
case("_contrib_arange_like", lambda: [F(2, 3)],
     oracle=lambda data: np.arange(6, dtype=np.float32))
case("_contrib_index_array", lambda: [F(2, 3)],
     check=lambda outs, ins, kw: outs[0].shape == (2, 3, 2))
case("_contrib_index_copy",
     lambda: [F(4, 3), np.array([1, 3], np.float32), F(2, 3)],
     check=lambda outs, ins, kw: np.allclose(outs[0][[1, 3]], ins[2]))
case("_npi_blackman", lambda: [], kwargs={"M": 8},
     oracle=lambda M: np.blackman(M).astype(np.float32), atol=1e-6)
case("_npi_hamming", lambda: [], kwargs={"M": 8},
     oracle=lambda M: np.hamming(M).astype(np.float32), atol=1e-6)
case("_npi_hanning", lambda: [], kwargs={"M": 8},
     oracle=lambda M: np.hanning(M).astype(np.float32), atol=1e-6)
case("_histogram", lambda: [F(20)], kwargs={"bin_cnt": 5,
                                            "range": (-1.0, 1.0)},
     oracle=lambda data, bin_cnt, range: np.histogram(
         data, bins=bin_cnt, range=range)[0].astype(np.int64), atol=0)
case("_npi_histogram", lambda: [F(20)], kwargs={"bins": 5,
                                                "range": (-1.0, 1.0)},
     check=lambda outs, ins, kw: int(outs[0].sum()) == 20)

# ------------------------------------------------------------- nn ops -----


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


case("softmax", lambda: [F(3, 5)], oracle=lambda x: _np_softmax(x))
case("log_softmax", lambda: [F(3, 5)],
     oracle=lambda x: np.log(_np_softmax(x)))
case("softmin", lambda: [F(3, 5)], oracle=lambda x: _np_softmax(-x))
case("SoftmaxActivation", lambda: [F(3, 5)],
     oracle=lambda data: _np_softmax(data))
case("softmax_cross_entropy",
     lambda: [F(3, 5), I(3, high=5).astype(np.float32)],
     oracle=lambda data, label: np.array(
         -np.log(_np_softmax(data))[np.arange(3),
                                    label.astype(int)].sum(),
         np.float32), rtol=1e-3)
case("Activation", lambda: [F(2, 3)], kwargs={"act_type": "tanh"},
     oracle=lambda data, act_type: np.tanh(data))
case("LeakyReLU", lambda: [F(2, 3)],
     kwargs={"act_type": "leaky", "slope": 0.1},
     oracle=lambda data, act_type, slope: np.where(data > 0, data,
                                                   slope * data))
case("FullyConnected", lambda: [F(2, 4), F(3, 4), F(3)],
     kwargs={"num_hidden": 3},
     oracle=lambda x, w, b, num_hidden: x @ w.T + b)
case("Convolution", lambda: [F(1, 2, 5, 5), F(3, 2, 3, 3), F(3)],
     kwargs={"kernel": (3, 3), "num_filter": 3},
     check=lambda outs, ins, kw: outs[0].shape == (1, 3, 3, 3))
case("Deconvolution", lambda: [F(1, 3, 3, 3), F(3, 2, 3, 3)],
     kwargs={"kernel": (3, 3), "num_filter": 2},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 5, 5))
case("Pooling", lambda: [F(1, 2, 4, 4)],
     kwargs={"kernel": (2, 2), "stride": (2, 2), "pool_type": "avg"},
     check=lambda outs, ins, kw: np.allclose(
         outs[0][0, 0, 0, 0], ins[0][0, 0, :2, :2].mean(), atol=1e-6))
case("BatchNorm",
     lambda: [F(2, 3, 4, 4), FP(3), F(3), F(3), FP(3)],
     kwargs={"use_global_stats": True, "fix_gamma": False},
     check=lambda outs, ins, kw: outs[0].shape == (2, 3, 4, 4))
case("BatchNorm_v1",
     lambda: [F(2, 3, 4, 4), FP(3), F(3), F(3), FP(3)],
     kwargs={"use_global_stats": True, "fix_gamma": False},
     check=lambda outs, ins, kw: outs[0].shape == (2, 3, 4, 4))
case("_contrib_BatchNormWithReLU",
     lambda: [F(2, 3, 4, 4), FP(3), F(3), F(3), FP(3)],
     kwargs={"use_global_stats": True, "fix_gamma": False},
     check=lambda outs, ins, kw: outs[0].min() >= 0)
case("_contrib_SyncBatchNorm",
     lambda: [F(2, 3, 4, 4), FP(3), F(3), F(3), FP(3)],
     kwargs={"use_global_stats": True, "fix_gamma": False},
     check=lambda outs, ins, kw: outs[0].shape == (2, 3, 4, 4))
case("LayerNorm", lambda: [F(2, 5), FP(5), F(5)],
     oracle=lambda x, g, b: (x - x.mean(-1, keepdims=True)) /
     np.sqrt(x.var(-1, keepdims=True) + 1e-5) * g + b, rtol=1e-3)
case("InstanceNorm", lambda: [F(2, 3, 5), FP(3), F(3)],
     check=lambda outs, ins, kw: outs[0].shape == (2, 3, 5))
case("GroupNorm", lambda: [F(2, 4, 5), FP(4), F(4)],
     kwargs={"num_groups": 2},
     check=lambda outs, ins, kw: outs[0].shape == (2, 4, 5))
case("L2Normalization", lambda: [F(2, 5)],
     oracle=lambda data: data / np.sqrt((data ** 2).sum(
         axis=1, keepdims=True) + 1e-10))
case("LRN", lambda: [F(1, 4, 3, 3)],
     check=lambda outs, ins, kw: outs[0].shape == (1, 4, 3, 3))
case("Dropout", lambda: [F(2, 3)], kwargs={"training": False},
     oracle=lambda data, training: data)
case("Embedding", lambda: [I(2, 3, high=5).astype(np.float32), F(5, 4)],
     kwargs={"input_dim": 5, "output_dim": 4},
     oracle=lambda data, weight, input_dim, output_dim:
     weight[data.astype(int)])
case("_contrib_SparseEmbedding",
     lambda: [I(2, 3, high=5).astype(np.float32), F(5, 4)],
     kwargs={"input_dim": 5, "output_dim": 4},
     oracle=lambda data, weight, input_dim, output_dim:
     weight[data.astype(int)])
case("MakeLoss", lambda: [F(2, 3)], oracle=lambda data: data)
case("IdentityAttachKLSparseReg", lambda: [FP(2, 3)],
     oracle=lambda data: data)
case("SoftmaxOutput", lambda: [F(3, 5), I(3, high=5).astype(np.float32)],
     oracle=lambda data, label: _np_softmax(data))
case("SVMOutput", lambda: [F(3, 5), I(3, high=5).astype(np.float32)],
     oracle=lambda data, label: data)
case("LinearRegressionOutput", lambda: [F(3, 2), F(3, 2)],
     oracle=lambda data, label: data)
case("MAERegressionOutput", lambda: [F(3, 2), F(3, 2)],
     oracle=lambda data, label: data)
case("LogisticRegressionOutput", lambda: [F(3, 2), F(3, 2)],
     oracle=lambda data, label: 1 / (1 + np.exp(-data)))
case("SequenceLast",
     lambda: [F(4, 2, 3), np.array([2, 4], np.float32)],
     kwargs={"use_sequence_length": True},
     oracle=lambda data, sl, use_sequence_length: np.stack(
         [data[1, 0], data[3, 1]]))
case("SequenceMask",
     lambda: [F(4, 2, 3), np.array([2, 4], np.float32)],
     kwargs={"use_sequence_length": True, "value": -1.0},
     check=lambda outs, ins, kw: np.allclose(outs[0][2:, 0], -1.0))
case("SequenceReverse",
     lambda: [F(4, 2, 3), np.array([2, 4], np.float32)],
     kwargs={"use_sequence_length": True},
     check=lambda outs, ins, kw: np.allclose(outs[0][0, 0], ins[0][1, 0]))
case("CTCLoss",
     lambda: [F(6, 2, 5), np.array([[1, 2], [2, 3]], np.float32)],
     check=lambda outs, ins, kw: outs[0].shape == (2,) and
     np.all(outs[0] > 0))
case("RNN", lambda: [F(3, 2, 4),
                     F(2 * ((4 + 4 + 2) * 4)).reshape(-1),
                     F(1, 2, 4)],
     kwargs={"state_size": 4, "num_layers": 1, "mode": "rnn_tanh"},
     check=lambda outs, ins, kw: outs[0].shape == (3, 2, 4))
case("GridGenerator", lambda: [F(1, 6)],
     kwargs={"transform_type": "affine", "target_shape": (4, 4)},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4, 4))
case("BilinearSampler",
     lambda: [F(1, 2, 4, 4),
              np.zeros((1, 2, 4, 4), np.float32)],
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4, 4))
case("SpatialTransformer",
     lambda: [F(1, 2, 4, 4),
              np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
     kwargs={"target_shape": (4, 4)},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4, 4))
case("ROIPooling",
     lambda: [F(1, 2, 8, 8), np.array([[0, 0, 0, 4, 4]], np.float32)],
     kwargs={"pooled_size": (2, 2), "spatial_scale": 1.0},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 2, 2))
case("_contrib_ROIAlign",
     lambda: [F(1, 2, 8, 8), np.array([[0, 0, 0, 4, 4]], np.float32)],
     kwargs={"pooled_size": (2, 2), "spatial_scale": 1.0},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 2, 2))
case("Correlation", lambda: [F(1, 2, 4, 4), F(1, 2, 4, 4)],
     check=lambda outs, ins, kw: np.all(np.isfinite(outs[0])))
case("Crop", lambda: [F(1, 2, 6, 6)],
     kwargs={"h_w": (4, 4)},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4, 4))
case("UpSampling", lambda: [F(1, 2, 3, 3)],
     kwargs={"scale": 2, "sample_type": "nearest"},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 6, 6))
case("_contrib_AdaptiveAvgPooling2D", lambda: [F(1, 2, 6, 6)],
     kwargs={"output_size": (3, 3)},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 3, 3))
case("_contrib_BilinearResize2D", lambda: [F(1, 2, 4, 4)],
     kwargs={"height": 8, "width": 8},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 8, 8))
case("im2col", lambda: [F(1, 2, 4, 4)],
     kwargs={"kernel": (3, 3)},
     check=lambda outs, ins, kw: outs[0].shape[1] == 18)
case("col2im", lambda: [F(1, 18, 4)],
     kwargs={"output_size": (4, 4), "kernel": (3, 3)},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4, 4))
case("_contrib_quadratic", lambda: [F(2, 3)],
     kwargs={"a": 1.0, "b": 2.0, "c": 3.0},
     oracle=lambda data, a, b, c: a * data ** 2 + b * data + c)
case("_contrib_allclose", lambda: [F(2, 3), F(2, 3)],
     oracle=lambda a, b: np.array(0.0, np.float32))

# box / detection family
case("_contrib_box_iou",
     lambda: [np.array([[0, 0, 2, 2]], np.float32),
              np.array([[1, 1, 3, 3]], np.float32)],
     oracle=lambda lhs, rhs: np.array([[1.0 / 7.0]], np.float32),
     rtol=1e-3)
case("box_nms",
     lambda: [np.array([[[0, 0.9, 0, 0, 2, 2], [0, 0.8, 0.1, 0.1, 2, 2],
                         [1, 0.7, 5, 5, 7, 7]]], np.float32)],
     kwargs={"overlap_thresh": 0.5},
     check=lambda outs, ins, kw: outs[0].shape == ins[0].shape)
case("_contrib_box_decode",
     lambda: [np.zeros((1, 2, 4), np.float32),
              np.array([[[0, 0, 2, 2], [1, 1, 3, 3]]], np.float32)],
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4))
case("_contrib_box_encode",
     lambda: [np.ones((1, 2), np.float32),
              np.array([[0, 1]], np.float32),
              np.array([[[0, 0, 2, 2], [1, 1, 3, 3]]], np.float32),
              np.array([[[0, 0, 2, 2], [1, 1, 3, 3]]], np.float32)],
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4))
case("_contrib_bipartite_matching",
     lambda: [np.array([[[0.9, 0.1], [0.3, 0.8]]], np.float32)],
     kwargs={"threshold": 0.05},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2))
case("MultiBoxPrior", lambda: [F(1, 2, 4, 4)],
     kwargs={"sizes": (0.5,), "ratios": (1.0,)},
     check=lambda outs, ins, kw: outs[0].shape == (1, 16, 4))
case("MultiBoxDetection",
     lambda: [_np_softmax(F(1, 2, 4), axis=1).astype(np.float32),
              F(1, 16), np.abs(F(1, 4, 4))],
     check=lambda outs, ins, kw: outs[0].shape[0] == 1)
case("MultiBoxTarget",
     lambda: [np.abs(F(1, 4, 4)),
              np.array([[[0, 0.1, 0.1, 0.8, 0.8]]], np.float32),
              _np_softmax(F(1, 2, 4), axis=1).astype(np.float32)],
     check=lambda outs, ins, kw: len(outs) == 3)

# fft / sketch / attention contrib
case("_contrib_fft", lambda: [F(2, 8)],
     oracle=lambda data: np.stack(
         [np.stack([np.fft.fft(r).real, np.fft.fft(r).imag], -1).reshape(-1)
          for r in data]), rtol=1e-3, atol=1e-4)
case("_contrib_ifft", lambda: [F(2, 16)],
     check=lambda outs, ins, kw: outs[0].shape == (2, 8))
case("_contrib_count_sketch",
     lambda: [F(2, 8), np.array([RS.randint(0, 16, 8)], np.float32),
              np.array([RS.choice([-1, 1], 8)], np.float32)],
     kwargs={"out_dim": 16},
     check=lambda outs, ins, kw: outs[0].shape == (2, 16))
case("_contrib_flash_attention",
     lambda: [F(1, 2, 4, 8), F(1, 2, 4, 8), F(1, 2, 4, 8)],
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 4, 8))


def _selfatt_qk_oracle(qkv, heads):
    # qkv: (L, B, 3*E) interleaved per head -> (B*heads, L, L) scores
    L, Bz, E3 = qkv.shape
    E = E3 // 3
    hd = E // heads
    proj = qkv.reshape(L, Bz, heads, 3, hd)
    q = proj[:, :, :, 0]
    k = proj[:, :, :, 1]
    q = q.transpose(1, 2, 0, 3).reshape(Bz * heads, L, hd)
    k = k.transpose(1, 2, 0, 3).reshape(Bz * heads, L, hd)
    return (q / np.sqrt(hd)) @ k.transpose(0, 2, 1)


case("_contrib_interleaved_matmul_selfatt_qk",
     lambda: [F(3, 2, 12)], kwargs={"heads": 2},
     check=lambda outs, ins, kw: outs[0].shape == (4, 3, 3))
case("_contrib_interleaved_matmul_selfatt_valatt",
     lambda: [F(3, 2, 12), _np_softmax(F(4, 3, 3)).astype(np.float32)],
     kwargs={"heads": 2},
     check=lambda outs, ins, kw: outs[0].shape == (3, 2, 4))
case("_contrib_interleaved_matmul_encdec_qk",
     lambda: [F(3, 2, 8), F(5, 2, 16)], kwargs={"heads": 2},
     check=lambda outs, ins, kw: outs[0].shape == (4, 3, 5))
case("_contrib_interleaved_matmul_encdec_valatt",
     lambda: [F(5, 2, 16), _np_softmax(F(4, 3, 5)).astype(np.float32)],
     kwargs={"heads": 2},
     check=lambda outs, ins, kw: outs[0].shape == (3, 2, 8))

# quantized family
case("_contrib_quantize",
     lambda: [F(2, 3), np.array([-1.0], np.float32),
              np.array([1.0], np.float32)],
     check=lambda outs, ins, kw: outs[0].dtype == np.int8 and
     len(outs) == 3)
case("_contrib_quantize_v2", lambda: [F(2, 3)],
     kwargs={"min_calib_range": -1.0, "max_calib_range": 1.0},
     check=lambda outs, ins, kw: outs[0].dtype == np.int8)
case("_contrib_quantize_asym", lambda: [F(2, 3)],
     kwargs={"min_calib_range": -1.0, "max_calib_range": 1.0},
     check=lambda outs, ins, kw: len(outs) == 3 and
     outs[0].dtype in (np.int8, np.uint8))
case("_contrib_dequantize",
     lambda: [I(2, 3, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     check=lambda outs, ins, kw: outs[0].dtype == np.float32)
case("_contrib_requantize",
     lambda: [I(2, 3, high=1000).astype(np.int32),
              np.array([-10.0], np.float32), np.array([10.0], np.float32)],
     kwargs={"min_calib_range": -5.0, "max_calib_range": 5.0},
     check=lambda outs, ins, kw: outs[0].dtype == np.int8)
case("_contrib_quantized_act",
     lambda: [I(2, 3, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     kwargs={"act_type": "relu"},
     check=lambda outs, ins, kw: outs[0].min() >= 0)
case("_contrib_quantized_flatten",
     lambda: [I(2, 3, 2, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     check=lambda outs, ins, kw: outs[0].shape == (2, 6))
case("_contrib_quantized_concat",
     lambda: [I(2, 3, high=100).astype(np.int8),
              I(2, 3, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     kwargs={"dim": 1, "num_args": 2},
     check=lambda outs, ins, kw: outs[0].shape == (2, 6))
case("_contrib_quantized_elemwise_add",
     lambda: [I(2, 3, high=100).astype(np.int8),
              I(2, 3, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     check=lambda outs, ins, kw: len(outs) == 3)
case("_contrib_quantized_elemwise_mul",
     lambda: [I(2, 3, high=100).astype(np.int8),
              I(2, 3, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     check=lambda outs, ins, kw: len(outs) == 3)

# --------------------------------------------------- matmul / linalg ------
case("dot", lambda: [F(3, 4), F(4, 2)], oracle=lambda a, b: a @ b)
case("batch_dot", lambda: [F(2, 3, 4), F(2, 4, 2)],
     oracle=lambda a, b: a @ b)
case("_npi_matmul", lambda: [F(3, 4), F(4, 2)], oracle=np.matmul)
case("_npi_dot", lambda: [F(3, 4), F(4, 2)], oracle=np.dot)
case("_np_dot", lambda: [F(3, 4), F(4, 2)], oracle=np.dot)
case("_npi_tensordot", lambda: [F(2, 3, 4), F(3, 4, 5)],
     kwargs={"axes": 2}, oracle=lambda a, b, axes: np.tensordot(a, b, axes))
case("_npi_tensordot_int_axes", lambda: [F(2, 3, 4), F(3, 4, 5)],
     kwargs={"axes": 2}, oracle=lambda a, b, axes: np.tensordot(a, b, axes))
case("_npi_inner", lambda: [F(3, 4), F(2, 4)], oracle=np.inner)
case("_npi_outer", lambda: [F(3), F(4)], oracle=np.outer)
case("_npi_vdot", lambda: [F(4), F(4)], oracle=np.vdot)
case("_npi_kron", lambda: [F(2, 2), F(2, 3)], oracle=np.kron)
case("_npi_cross", lambda: [F(3), F(3)], oracle=np.cross)
case("_npi_multi_dot", lambda: [F(2, 3), F(3, 4), F(4, 2)],
     oracle=lambda *ms: np.linalg.multi_dot(ms))
case("khatri_rao", lambda: [F(2, 3), F(4, 3)],
     check=lambda outs, ins, kw: outs[0].shape == (8, 3))
case("_npi_matrix_power", lambda: [F(3, 3)], kwargs={"n": 2},
     oracle=lambda a, n: np.linalg.matrix_power(a, n), rtol=1e-3)
case("_npi_polyval", lambda: [F(3), F(4)],
     oracle=lambda p, x: np.polyval(p, x))
case("_npi_meshgrid", lambda: [F(3), F(2)],
     oracle=lambda a, b: tuple(np.meshgrid(a, b)))
case("_npi_einsum", lambda: [F(2, 3), F(3, 4)],
     kwargs={"subscripts": "ij,jk->ik"},
     oracle=lambda a, b, subscripts: np.einsum(subscripts, a, b))


def PSD(n):
    m = F(n, n)
    return (m @ m.T + n * np.eye(n)).astype(np.float32)


case("_npi_cholesky", lambda: [PSD(3)],
     oracle=lambda a: np.linalg.cholesky(a), rtol=1e-3)
case("_npi_solve", lambda: [PSD(3), F(3, 2)],
     oracle=np.linalg.solve, rtol=1e-3)
case("_npi_inv", lambda: [PSD(3)], oracle=np.linalg.inv, rtol=1e-3)
case("_npi_det", lambda: [PSD(3)],
     oracle=lambda a: np.float32(np.linalg.det(a)), rtol=1e-3)
case("_npi_slogdet", lambda: [PSD(3)],
     oracle=lambda a: tuple(np.asarray(v, np.float32)
                            for v in np.linalg.slogdet(a)), rtol=1e-3)
case("_npi_eig", lambda: [PSD(3)],
     check=lambda outs, ins, kw: len(outs) == 2)
case("_npi_eigh", lambda: [PSD(3)],
     check=lambda outs, ins, kw: np.allclose(
         outs[1] @ np.diag(outs[0]) @ outs[1].T, ins[0], atol=1e-3))
case("_npi_eigvals", lambda: [PSD(3)],
     check=lambda outs, ins, kw: np.allclose(
         np.sort(np.real(outs[0])),
         np.sort(np.linalg.eigvalsh(ins[0])), atol=1e-3))
case("_npi_eigvalsh", lambda: [PSD(3)],
     oracle=lambda a: np.linalg.eigvalsh(a).astype(np.float32), rtol=1e-3)
case("_npi_qr", lambda: [F(3, 3)],
     check=lambda outs, ins, kw: np.allclose(outs[0] @ outs[1], ins[0],
                                             atol=1e-4))
case("_npi_svd", lambda: [F(3, 4)],
     check=lambda outs, ins, kw: len(outs) == 3)
case("_npi_pinv", lambda: [F(3, 4)],
     oracle=lambda a: np.linalg.pinv(a), rtol=1e-3, atol=1e-4)
case("_npi_lstsq", lambda: [F(4, 3), F(4, 2)],
     check=lambda outs, ins, kw: np.allclose(
         outs[0], np.linalg.lstsq(ins[0], ins[1], rcond=None)[0],
         atol=1e-3))
case("_npi_matrix_rank", lambda: [PSD(3)],
     oracle=lambda a: np.int32(3), atol=0)
case("_npi_tensorinv", lambda: [PSD(4).reshape(2, 2, 2, 2)],
     kwargs={"ind": 2},
     check=lambda outs, ins, kw: outs[0].shape == (2, 2, 2, 2))
case("_npi_tensorsolve", lambda: [PSD(4).reshape(2, 2, 2, 2), F(2, 2)],
     check=lambda outs, ins, kw: outs[0].shape == (2, 2))
case("_linalg_det", lambda: [PSD(3)],
     oracle=lambda A: np.float32(np.linalg.det(A)), rtol=1e-3)
case("linalg_det", lambda: [PSD(3)],
     oracle=lambda A: np.float32(np.linalg.det(A)), rtol=1e-3)
case("_linalg_slogdet", lambda: [PSD(3)],
     oracle=lambda A: tuple(np.asarray(v, np.float32)
                            for v in np.linalg.slogdet(A)), rtol=1e-3)
case("linalg_slogdet", lambda: [PSD(3)],
     oracle=lambda A: tuple(np.asarray(v, np.float32)
                            for v in np.linalg.slogdet(A)), rtol=1e-3)
case("_linalg_inverse", lambda: [PSD(3)],
     oracle=lambda A: np.linalg.inv(A), rtol=1e-3)
case("linalg_inverse", lambda: [PSD(3)],
     oracle=lambda A: np.linalg.inv(A), rtol=1e-3)
case("_linalg_potrf", lambda: [PSD(3)],
     oracle=lambda a: np.linalg.cholesky(a), rtol=1e-3)
case("linalg_potrf", lambda: [PSD(3)],
     oracle=lambda a: np.linalg.cholesky(a), rtol=1e-3)
case("_linalg_potri", lambda: [np.linalg.cholesky(PSD(3)).astype(
    np.float32)],
     check=lambda outs, ins, kw: np.allclose(
         outs[0], np.linalg.inv(ins[0] @ ins[0].T), atol=1e-2))
case("linalg_potri", lambda: [np.linalg.cholesky(PSD(3)).astype(
    np.float32)],
     check=lambda outs, ins, kw: np.allclose(
         outs[0], np.linalg.inv(ins[0] @ ins[0].T), atol=1e-2))
case("_linalg_sumlogdiag", lambda: [PSD(3)],
     oracle=lambda A: np.float32(np.sum(np.log(np.diag(A)))), rtol=1e-3)
case("linalg_sumlogdiag", lambda: [PSD(3)],
     oracle=lambda A: np.float32(np.sum(np.log(np.diag(A)))), rtol=1e-3)
case("_linalg_extractdiag", lambda: [F(3, 3)],
     oracle=lambda A: np.diag(A))
case("linalg_extractdiag", lambda: [F(3, 3)],
     oracle=lambda A: np.diag(A))
case("_linalg_makediag", lambda: [F(3)], oracle=np.diag)
case("linalg_makediag", lambda: [F(3)], oracle=np.diag)
case("_linalg_extracttrian", lambda: [F(3, 3)],
     check=lambda outs, ins, kw: outs[0].shape == (6,))
case("linalg_extracttrian", lambda: [F(3, 3)],
     check=lambda outs, ins, kw: outs[0].shape == (6,))
case("_linalg_maketrian", lambda: [F(6)],
     check=lambda outs, ins, kw: outs[0].shape == (3, 3))
case("linalg_maketrian", lambda: [F(6)],
     check=lambda outs, ins, kw: outs[0].shape == (3, 3))
case("_linalg_gemm", lambda: [F(2, 3), F(3, 4), F(2, 4)],
     kwargs={"alpha": 2.0, "beta": 0.5},
     oracle=lambda A, B, C, alpha, beta: alpha * (A @ B) + beta * C)
case("linalg_gemm", lambda: [F(2, 3), F(3, 4), F(2, 4)],
     kwargs={"alpha": 2.0, "beta": 0.5},
     oracle=lambda A, B, C, alpha, beta: alpha * (A @ B) + beta * C)
case("_linalg_gemm2", lambda: [F(2, 3), F(3, 4)],
     oracle=lambda a, b: a @ b)
case("linalg_gemm2", lambda: [F(2, 3), F(3, 4)],
     oracle=lambda a, b: a @ b)
case("_linalg_syrk", lambda: [F(2, 3)],
     oracle=lambda a: a @ a.T)
case("linalg_syrk", lambda: [F(2, 3)],
     oracle=lambda a: a @ a.T)
case("_linalg_trmm",
     lambda: [np.tril(F(3, 3)).astype(np.float32), F(3, 3)],
     oracle=lambda A, B: A @ B)
case("linalg_trmm",
     lambda: [np.tril(F(3, 3)).astype(np.float32), F(3, 3)],
     oracle=lambda A, B: A @ B)
case("_linalg_trsm",
     lambda: [(np.tril(F(3, 3)) + 3 * np.eye(3)).astype(np.float32),
              F(3, 3)],
     check=lambda outs, ins, kw: np.allclose(ins[0] @ outs[0], ins[1],
                                             atol=1e-4))
case("linalg_trsm",
     lambda: [(np.tril(F(3, 3)) + 3 * np.eye(3)).astype(np.float32),
              F(3, 3)],
     check=lambda outs, ins, kw: np.allclose(ins[0] @ outs[0], ins[1],
                                             atol=1e-4))
case("_linalg_gelqf", lambda: [F(2, 3)],
     check=lambda outs, ins, kw: np.allclose(outs[0] @ outs[1], ins[0],
                                             atol=1e-4))
case("linalg_gelqf", lambda: [F(2, 3)],
     check=lambda outs, ins, kw: np.allclose(outs[0] @ outs[1], ins[0],
                                             atol=1e-4))
case("_linalg_syevd", lambda: [PSD(3)],
     check=lambda outs, ins, kw: np.allclose(
         outs[0].T @ np.diag(outs[1]) @ outs[0], ins[0], atol=1e-2))
case("linalg_syevd", lambda: [PSD(3)],
     check=lambda outs, ins, kw: np.allclose(
         outs[0].T @ np.diag(outs[1]) @ outs[0], ins[0], atol=1e-2))

# ------------------------------------------------------------- random -----
_PRNG = "__PRNGKEY__"  # harness substitutes a raw uint32 key


def _finite(outs, ins, kw):
    return all(np.all(np.isfinite(o.astype(np.float64))) for o in outs)


KEY32 = np.zeros(2, np.uint32)
for _n in ["_random_uniform", "_random_normal", "_random_exponential",
           "_random_poisson", "_random_bernoulli"]:
    case(_n, lambda: [KEY32], kwargs={"shape": (3, 4)}, check=_finite)
case("_random_gamma", lambda: [KEY32],
     kwargs={"shape": (3, 4), "alpha": 2.0}, check=_finite)
case("_random_randint", lambda: [KEY32],
     kwargs={"shape": (3, 4), "low": 0, "high": 7},
     check=lambda outs, ins, kw: outs[0].max() < 7)
case("_random_negative_binomial", lambda: [KEY32],
     kwargs={"shape": (3, 4), "k": 2, "p": 0.5}, check=_finite)
case("_shuffle", lambda: [KEY32, F(6)],
     check=lambda outs, ins, kw: np.allclose(np.sort(outs[0]),
                                             np.sort(ins[1])))
case("_sample_multinomial",
     lambda: [KEY32, np.array([0.3, 0.7], np.float32)],
     kwargs={"shape": (5,)},
     check=lambda outs, ins, kw: outs[0].max() <= 1)
for _n, _kw in [("_npi_uniform", {"size": (3, 4)}),
                ("_npi_normal", {"size": (3, 4)}),
                ("_npi_normal_n", {"size": (3, 4)}),
                ("_npi_uniform_n", {"size": (3, 4)}),
                ("_npi_bernoulli", {"size": (3, 4)}),
                ("_npi_exponential", {"size": (3, 4)}),
                ("_npi_gamma", {"size": (3, 4), "shape_param": 2.0}),
                ("_npi_pareto", {"size": (3, 4)}),
                ("_npi_weibull", {"size": (3, 4)}),
                ("_npi_rayleigh", {"size": (3, 4)}),
                ("_npi_random_uniform", {"size": (3, 4)}),
                ("_npi_random_normal", {"size": (3, 4)}),
                ("_npi_random_exponential", {"size": (3, 4)}),
                ("_npi_random_gamma", {"size": (3, 4)}),
                ("_npi_random_poisson", {"size": (3, 4)}),
                ("_npi_random_bernoulli", {"size": (3, 4), "p": 0.5}),
                ("_npi_random_randint", {"size": (3, 4), "low": 0,
                                         "high": 7})]:
    case(_n, lambda: [], kwargs={**_kw, "key": _PRNG}, check=_finite)
case("_npi_multinomial", lambda: [np.array([0.3, 0.7], np.float32)],
     kwargs={"n": 5, "key": _PRNG, "size": (4,)},
     check=lambda outs, ins, kw: int(np.asarray(outs[0]).sum()) == 20)
case("_npi_choice", lambda: [F(6)], kwargs={"size": (3,), "key": _PRNG},
     check=_finite)
case("_npi_random_choice", lambda: [F(6)],
     kwargs={"size": (3,), "key": _PRNG}, check=_finite)
case("_npi_random_permutation", lambda: [F(6)], kwargs={"key": _PRNG},
     check=lambda outs, ins, kw: np.allclose(np.sort(outs[0]),
                                             np.sort(ins[0])))

# ------------------------------------------------- optimizer update ops ---
case("sgd_update", lambda: [F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1, "wd": 0.01},
     oracle=lambda w, g, lr, wd: w - lr * (g + wd * w))
case("sgd_mom_update", lambda: [F(3, 4), F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1, "momentum": 0.9},
     oracle=lambda w, g, m, lr, momentum: (w + momentum * m - lr * g,
                                           momentum * m - lr * g))
case("nag_mom_update", lambda: [F(3, 4), F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1, "momentum": 0.9},
     check=_finite)
case("signsgd_update", lambda: [F(3, 4), F(3, 4)], kwargs={"lr": 0.1},
     oracle=lambda w, g, lr: w - lr * np.sign(g))
case("signum_update", lambda: [F(3, 4), F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1, "momentum": 0.9}, check=_finite)
case("adam_update", lambda: [F(3, 4), F(3, 4), F(3, 4), FP(3, 4)],
     kwargs={"lr": 0.01}, check=_finite)
case("ftml_update",
     lambda: [F(3, 4), F(3, 4), FP(3, 4), FP(3, 4), F(3, 4)],
     kwargs={"lr": 0.01, "t": 1}, check=_finite)
case("rmsprop_update", lambda: [F(3, 4), F(3, 4), FP(3, 4)],
     kwargs={"lr": 0.01}, check=_finite)
case("rmspropalex_update",
     lambda: [F(3, 4), F(3, 4), FP(3, 4) + 1.0,
              F(3, 4) * 0.01, F(3, 4) * 0.01],
     kwargs={"lr": 0.01}, check=_finite)
case("ftrl_update", lambda: [F(3, 4), F(3, 4), F(3, 4), FP(3, 4)],
     kwargs={"lr": 0.1}, check=_finite)
case("adagrad_update", lambda: [F(3, 4), F(3, 4), FP(3, 4)],
     kwargs={"lr": 0.1},
     oracle=lambda w, g, h, lr: (
         w - lr * (g / (np.sqrt(h + g * g) + 1e-7)),
         h + g * g), rtol=1e-3)
case("adadelta_update",
     lambda: [F(3, 4), F(3, 4), FP(3, 4), FP(3, 4)], check=_finite)
case("lars_sgd_update", lambda: [F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1}, check=_finite)
case("lars_sgd_mom_update", lambda: [F(3, 4), F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1, "momentum": 0.9}, check=_finite)
case("lamb_update_phase1",
     lambda: [F(3, 4), F(3, 4), F(3, 4), FP(3, 4)],
     kwargs={"t": 1}, check=_finite)
case("lamb_update_phase2",
     lambda: [F(3, 4), F(3, 4), np.array(2.0, np.float32),
              np.array(1.0, np.float32)],
     kwargs={"lr": 0.1},
     oracle=lambda w, g, r1, r2, lr: w - lr * (r1 / r2) * g)
case("mp_sgd_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4)],
     kwargs={"lr": 0.1},
     check=lambda outs, ins, kw: outs[0].dtype == np.float16 and
     outs[1].dtype == np.float32, rtol=1e-2)
case("mp_sgd_mom_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1, "momentum": 0.9},
     check=lambda outs, ins, kw: outs[0].dtype == np.float16)
case("mp_nag_mom_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), F(3, 4)],
     kwargs={"lr": 0.1, "momentum": 0.9},
     check=lambda outs, ins, kw: outs[0].dtype == np.float16)
case("mp_lamb_update_phase1",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), FP(3, 4), F(3, 4)],
     kwargs={"t": 1}, check=_finite)
case("mp_lamb_update_phase2",
     lambda: [F(3, 4).astype(np.float16), F(3, 4),
              np.array(2.0, np.float32), np.array(1.0, np.float32),
              F(3, 4)],
     kwargs={"lr": 0.1},
     check=lambda outs, ins, kw: outs[0].dtype == np.float16)
case("_adamw_update",
     lambda: [F(3, 4), F(3, 4), F(3, 4), FP(3, 4),
              np.array([1.0], np.float32)],
     kwargs={"lr": 0.01}, check=_finite)
case("_mp_adamw_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), FP(3, 4), F(3, 4), np.array([1.0], np.float32)],
     kwargs={"lr": 0.01},
     check=lambda outs, ins, kw: outs[0].dtype == np.float16)
case("multi_sgd_update", lambda: [F(3, 4), F(3, 4), F(2, 3), F(2, 3)],
     kwargs={"lrs": (0.1, 0.2), "wds": (0.0, 0.0), "num_weights": 2},
     oracle=lambda w0, g0, w1, g1, lrs, wds, num_weights:
     (w0 - 0.1 * g0, w1 - 0.2 * g1))
case("multi_sgd_mom_update",
     lambda: [F(3, 4), F(3, 4), F(3, 4), F(2, 3), F(2, 3), F(2, 3)],
     kwargs={"lrs": (0.1, 0.2), "wds": (0.0, 0.0), "momentum": 0.9,
             "num_weights": 2}, check=_finite)
case("multi_mp_sgd_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), F(2, 3).astype(np.float16),
              F(2, 3).astype(np.float16), F(2, 3)],
     kwargs={"lrs": (0.1, 0.2), "wds": (0.0, 0.0), "num_weights": 2},
     check=_finite)
case("multi_mp_sgd_mom_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), F(3, 4), F(2, 3).astype(np.float16),
              F(2, 3).astype(np.float16), F(2, 3), F(2, 3)],
     kwargs={"lrs": (0.1, 0.2), "wds": (0.0, 0.0), "momentum": 0.9,
             "num_weights": 2}, check=_finite)
case("preloaded_multi_sgd_update",
     lambda: [F(3, 4), F(3, 4), F(2, 3), F(2, 3),
              np.array([0.1, 0.2], np.float32),
              np.array([0.0, 0.0], np.float32)],
     kwargs={"num_weights": 2},
     oracle=lambda w0, g0, w1, g1, lrs, wds, num_weights:
     (w0 - 0.1 * g0, w1 - 0.2 * g1), rtol=1e-3)
case("preloaded_multi_sgd_mom_update",
     lambda: [F(3, 4), F(3, 4), F(3, 4), F(2, 3), F(2, 3), F(2, 3),
              np.array([0.1, 0.2], np.float32),
              np.array([0.0, 0.0], np.float32)],
     kwargs={"momentum": 0.9, "num_weights": 2}, check=_finite)
case("preloaded_multi_mp_sgd_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), F(2, 3).astype(np.float16),
              F(2, 3).astype(np.float16), F(2, 3),
              np.array([0.1, 0.2], np.float32),
              np.array([0.0, 0.0], np.float32)],
     kwargs={"num_weights": 2}, check=_finite)
case("preloaded_multi_mp_sgd_mom_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), F(3, 4), F(2, 3).astype(np.float16),
              F(2, 3).astype(np.float16), F(2, 3), F(2, 3),
              np.array([0.1, 0.2], np.float32),
              np.array([0.0, 0.0], np.float32)],
     kwargs={"momentum": 0.9, "num_weights": 2}, check=_finite)
case("_multi_adamw_update",
     lambda: [F(3, 4), F(3, 4), F(3, 4), FP(3, 4),
              np.array([1.0], np.float32)],
     kwargs={"lrs": (0.01,), "wds": (0.0,), "etas": (1.0,),
             "num_weights": 1}, check=_finite)
case("_multi_mp_adamw_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), FP(3, 4), F(3, 4), np.array([1.0], np.float32)],
     kwargs={"lrs": (0.01,), "wds": (0.0,), "etas": (1.0,),
             "num_weights": 1}, check=_finite)
case("_multi_lamb_update",
     lambda: [F(3, 4), F(3, 4), F(3, 4), FP(3, 4)],
     kwargs={"learning_rates": (0.01,), "wds": (0.0,),
             "step_count": (1,), "num_tensors": 1}, check=_finite)
case("_multi_mp_lamb_update",
     lambda: [F(3, 4).astype(np.float16), F(3, 4).astype(np.float16),
              F(3, 4), FP(3, 4), F(3, 4)],
     kwargs={"learning_rates": (0.01,), "wds": (0.0,),
             "step_count": (1,), "num_tensors": 1}, check=_finite)
case("multi_lars",
     lambda: [FP(4), FP(4), FP(4), FP(4)],
     check=_finite)
case("multi_sum_sq", lambda: [F(3, 4), F(2, 3)],
     kwargs={"num_arrays": 2},
     oracle=lambda a, b, num_arrays: np.array(
         [(a * a).sum(), (b * b).sum()], np.float32), rtol=1e-3)
case("multi_all_finite", lambda: [F(3, 4), F(2, 3)],
     kwargs={"num_arrays": 2},
     oracle=lambda a, b, num_arrays: np.array([1.0], np.float32))
case("all_finite", lambda: [F(3, 4)],
     oracle=lambda data: np.array(1.0, np.float32))
case("reset_arrays", lambda: [F(3, 4), F(2, 3)],
     kwargs={"num_arrays": 2},
     oracle=lambda a, b, num_arrays: (np.zeros_like(a),
                                      np.zeros_like(b)))
case("amp_multicast",
     lambda: [F(2, 3).astype(np.float16), F(2, 3)],
     kwargs={"num_outputs": 2},
     check=lambda outs, ins, kw: all(o.dtype == np.float32
                                     for o in outs))
case("_contrib_group_adagrad_update",
     lambda: [F(3, 4), F(3, 4), FP(3, 1)],
     kwargs={"lr": 0.1}, check=_finite)
case("_contrib_calibrate_entropy",
     lambda: [np.abs(RS.randn(64)).astype(np.float32) * 10,
              np.linspace(0, 8, 65).astype(np.float32)],
     check=lambda outs, ins, kw: len(outs) >= 1)

# final stragglers for full-registry coverage
case("_npi_round", lambda: [F(2, 3)], kwargs={"decimals": 1},
     oracle=lambda a, decimals: np.round(a, decimals))
case("_npi_sign_nd", lambda: [F(2, 3)], oracle=np.sign)
case("_npi_powerd", lambda: [], kwargs={"size": (3, 4), "key": _PRNG},
     check=_finite)
case("_npi_random_beta", lambda: [],
     kwargs={"size": (3, 4), "key": _PRNG, "a": 2.0, "b": 3.0},
     check=lambda outs, ins, kw: 0 <= outs[0].min() and
     outs[0].max() <= 1)
case("_npi_pinv_scalar_rcond", lambda: [F(3, 4)],
     oracle=lambda a: np.linalg.pinv(a), rtol=1e-3, atol=1e-4)
case("_npi_insert_slice", lambda: [F(5), F(1)],
     kwargs={"start": 2, "stop": 3, "axis": 0},
     check=lambda outs, ins, kw: outs[0].shape == (6,))
case("_npi_insert_tensor",
     lambda: [F(5), np.array([2], np.int64), F(1)],
     kwargs={"axis": 0},
     check=lambda outs, ins, kw: outs[0].shape == (6,))
case("_npx_constraint_check", lambda: [np.ones((2,), np.float32)],
     check=lambda outs, ins, kw: bool(np.all(outs[0])))
case("_rnn_param_concat", lambda: [F(2, 3), F(4, 3)],
     kwargs={"dim": 0},
     oracle=lambda a, b, dim: np.concatenate([a.ravel(), b.ravel()]))
case("_image_normalize", lambda: [FP(3, 4, 4)],
     kwargs={"mean": (0.5,), "std": (2.0,)},
     oracle=lambda data, mean, std: (data - 0.5) / 2.0)
case("_contrib_quantized_embedding",
     lambda: [I(2, 3, high=5).astype(np.float32),
              I(5, 4, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     kwargs={"input_dim": 5, "output_dim": 4},
     check=lambda outs, ins, kw: outs[0].shape == (2, 3, 4))
case("_contrib_quantized_pooling",
     lambda: [I(1, 2, 4, 4, high=100).astype(np.int8),
              np.array([-1.0], np.float32), np.array([1.0], np.float32)],
     kwargs={"kernel": (2, 2), "stride": (2, 2)},
     check=lambda outs, ins, kw: outs[0].shape == (1, 2, 2, 2))
case("_contrib_quantized_batch_norm",
     lambda: [I(1, 2, 4, 4, high=100).astype(np.int8), FP(2), F(2),
              F(2), FP(2), np.array([-1.0], np.float32),
              np.array([1.0], np.float32)],
     kwargs={"min_calib_range": -1.0, "max_calib_range": 1.0},
     check=lambda outs, ins, kw: len(outs) >= 1)
case("_contrib_quantized_conv",
     lambda: [I(1, 2, 5, 5, high=100).astype(np.int8),
              I(3, 2, 3, 3, high=100).astype(np.int8),
              np.array([0.01], np.float32)],
     kwargs={"kernel": (3, 3), "num_filter": 3, "no_bias": True,
             "min_calib_range": -1.0, "max_calib_range": 1.0},
     check=lambda outs, ins, kw: outs[0].shape[:2] == (1, 3))
case("_contrib_quantized_fully_connected",
     lambda: [I(2, 4, high=100).astype(np.int8),
              I(3, 4, high=100).astype(np.int8),
              np.array([0.01], np.float32)],
     kwargs={"num_hidden": 3, "no_bias": True,
             "min_calib_range": -1.0, "max_calib_range": 1.0},
     check=lambda outs, ins, kw: outs[0].shape[:2] == (2, 3) or
     outs[0].shape == (2, 3))

# ------------------------------------------------------------ harness -----


def _to_nd(a):
    import jax.numpy as jnp

    from mxnet_tpu.ndarray import NDArray

    return NDArray(jnp.asarray(a))


def _sub_key(v):
    if isinstance(v, str) and v == _PRNG:
        import jax.numpy as jnp

        return jnp.zeros(2, jnp.uint32)
    return v


@pytest.mark.parametrize("name", sorted(CASES))
def test_op_forward(name):
    c = CASES[name]
    _seed_case(name)
    ins = [np.asarray(a) for a in c["inputs"]()]
    kwargs = {k: _sub_key(v) for k, v in c["kwargs"].items()}
    out = mx.nd.invoke(name, *[_to_nd(a) for a in ins], **kwargs)
    outs = list(out) if isinstance(out, tuple) else [out]
    outs_np = [o.asnumpy() for o in outs]
    if c["oracle"] is not None:
        want = c["oracle"](*ins, **c["kwargs"])
        want = list(want) if isinstance(want, tuple) else [want]
        assert len(outs_np) >= len(want), \
            f"{name}: {len(outs_np)} outputs < {len(want)} expected"
        for o, w in zip(outs_np, want):
            w = np.asarray(w)
            assert o.shape == w.shape, \
                f"{name}: shape {o.shape} != oracle {w.shape}"
            np.testing.assert_allclose(
                o.astype(np.float64), w.astype(np.float64),
                rtol=c["rtol"], atol=c["atol"], err_msg=name)
    elif c["check"] is not None:
        assert c["check"](outs_np, ins, c["kwargs"]), f"{name}: check failed"
    else:
        for o in outs_np:
            if np.issubdtype(o.dtype, np.floating):
                assert np.all(np.isfinite(o)), f"{name}: non-finite output"


# numeric-gradient pass over the differentiable single-output oracle ops
# (reference methodology: check_numeric_gradient, test_utils.py:1101)
_GRAD_SKIP = {
    # non-differentiable outputs / integer or index semantics / steps
    "sign", "ceil", "floor", "trunc", "rint", "round", "fix", "argmax",
    "argmin", "argmax_channel", "argsort", "one_hot", "shape_array",
    "size_array", "Cast", "logical_not", "zeros_like", "ones_like",
    "topk", "sort",
    # stop-gradient by contract: autograd is deliberately zero
    "BlockGrad",
    # loss heads: forward is the prediction but backward is the LOSS
    # gradient (reference custom-vjp semantics) — numeric grad of the
    # forward is the wrong oracle
    "SoftmaxOutput", "SVMOutput", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput",
    "softmax_cross_entropy",
    # float-encoded INDEX inputs: perturbing them numerically is
    # meaningless (covered by forward oracles instead)
    "Embedding", "SequenceLast", "pick", "choose_element_0index",
    "ravel_multi_index", "_ravel_multi_index", "unravel_index",
    "_unravel_index",
    # |x| can approach 1 where d/dx arccos explodes; finite differences
    # lose all precision there
    "arccos",
    # step functions: gradient is zero a.e. but finite differences spike
    # when an input lands within eps of the threshold
    "_equal_scalar", "_not_equal_scalar", "_greater_scalar",
    "_greater_equal_scalar", "_lesser_scalar", "_lesser_equal_scalar",
    "elemwise_equal", "elemwise_not_equal", "elemwise_greater",
    "elemwise_greater_equal", "elemwise_lesser", "elemwise_lesser_equal",
    "broadcast_equal", "broadcast_not_equal", "broadcast_greater",
    "broadcast_greater_equal", "broadcast_lesser",
    "broadcast_lesser_equal", "elemwise_logical_and",
    "elemwise_logical_or", "elemwise_logical_xor",
    "broadcast_logical_and", "broadcast_logical_or",
    "broadcast_logical_xor",
    # |x| can approach 1 where the derivative explodes
    "arcsin", "_npi_arcsin", "_npi_arccos", "_npi_arctanh", "arctanh",
    # piecewise-discontinuous at divisor multiples: finite differences
    # spike whenever an input lands near a wrap boundary
    "elemwise_mod", "broadcast_mod", "_mod_scalar", "_rmod_scalar",
    "_npi_mod", "_npi_remainder", "_npi_fmod", "_npi_mod_scalar",
    "_npi_rmod_scalar", "_npi_floor_divide",
    "_npi_floor_divide_scalar", "_npi_rfloor_divide_scalar",
    # (sign, logdet) multi-output with a non-differentiable sign slot
    "_npi_slogdet",
    # the case input deliberately contains nan/inf (that's the op's whole
    # point); central differences across non-finite inputs are undefined,
    # and the float-max substitutes for +-inf swamp every finite
    # perturbation in the sum (forward oracle covers the op)
    "_npi_nan_to_num",
}


def _grad_candidates():
    out = []
    for name, c in sorted(CASES.items()):
        if name in _GRAD_SKIP or c["oracle"] is None:
            continue
        if name.startswith(("_random", "_contrib_")):
            continue  # stochastic/contrib: forward checks suffice
        try:
            op = registry.get(name)
        except KeyError:
            continue
        if not op.differentiable:
            continue
        ins = c["inputs"]()
        if not ins or any(not np.issubdtype(np.asarray(a).dtype,
                                            np.floating) for a in ins):
            continue
        out.append(name)
    return out


@pytest.mark.parametrize("name", _grad_candidates())
def test_op_gradient(name):
    c = CASES[name]
    _seed_case("grad:" + name)
    ins = [np.asarray(a, np.float64) for a in c["inputs"]()]
    check_numeric_gradient(name, ins, kwargs=c["kwargs"], rtol=1e-2,
                           atol=1e-3)


# ------------------------------------------------------ coverage gate -----

def test_registry_coverage_by_tests():
    """>=90% of registered op names must be exercised somewhere in
    tests/ (VERDICT r4 item 3 — breadth must be TESTED breadth)."""
    ops = registry.list_ops()
    here = os.path.dirname(os.path.abspath(__file__))
    text = "".join(open(f).read()
                   for f in glob.glob(os.path.join(here, "*.py")))
    missing = [o for o in ops
               if not re.search(r"\b" + re.escape(o) + r"\b", text)]
    frac = 1 - len(missing) / len(ops)
    assert frac >= 0.9, (
        f"only {frac:.0%} of {len(ops)} registered ops exercised; "
        f"missing: {missing}")


def test_pooling_same_convention():
    """pooling_convention='same' -> out = ceil(in/stride) (TF SAME)."""
    x = mx.nd.array(F(1, 1, 7, 7))
    out = mx.nd.invoke("Pooling", x, kernel=(3, 3), stride=(2, 2),
                       pooling_convention="same")
    assert out.shape == (1, 1, 4, 4)


def test_load_json_validates_attrs():
    """Bad attrs in symbol JSON raise structured errors at LOAD time."""
    import json as _json

    from mxnet_tpu.ops.schema import OpParamError

    sym = mx.sym.Activation(mx.sym.Variable("data"), act_type="relu")
    js = _json.loads(sym.tojson())
    for node in js["nodes"]:
        if node["op"] == "Activation":
            node["attrs"]["act_type"] = "gelu_bogus"
    with pytest.raises(OpParamError, match="expected one of"):
        mx.sym.load_json(_json.dumps(js))
