"""Analysis subsystem tests: graph verifier (analysis/verify.py) and
sync-hazard sanitizer (analysis/sanitize.py) — the NNVM-pass analogue
(docs/ANALYSIS.md)."""
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import sanitize
from mxnet_tpu.analysis.verify import GraphVerifyError, verify_graph


def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                name="softmax")


# ------------------------------------------------------------- verifier ----

def test_verify_clean_graph():
    issues = _mlp().verify(data=(8, 100), softmax_label=(8,))
    assert issues == []


def test_verify_bad_kwarg_names_node():
    """A bad hyper-parameter is caught with the node name, op, and the
    valid choices (compose validates too, so plant it post-compose the way
    a corrupt JSON would)."""
    act = mx.sym.Activation(mx.sym.var("x"), act_type="relu", name="a1")
    act._entries[0][0].attrs["act_type"] = "rleu"
    with pytest.raises(GraphVerifyError) as ei:
        act.verify()
    msg = str(ei.value)
    assert "bad-kwarg" in msg and "'a1'" in msg and "Activation" in msg
    assert "relu" in msg  # valid choices listed
    issues = act.verify(raise_on_error=False)
    assert [i.code for i in issues if i.is_error] == ["bad-kwarg"]


def test_verify_shape_mismatch_names_node():
    a, b = mx.sym.var("a"), mx.sym.var("b")
    s = mx.sym.elemwise_add(a, b, name="add0")
    with pytest.raises(GraphVerifyError) as ei:
        s.verify(a=(2, 3), b=(4, 5))
    msg = str(ei.value)
    assert "shape-mismatch" in msg and "'add0'" in msg
    assert "(2, 3)" in msg and "(4, 5)" in msg


def test_verify_declared_shape_conflict():
    x = mx.sym.var("x", shape=(3, 3))
    y = mx.sym.relu(x, name="r")
    with pytest.raises(GraphVerifyError) as ei:
        y.verify(x=(4, 4))
    assert "shape-mismatch" in str(ei.value) and "'x'" in str(ei.value)


def test_verify_dangling_input_names_node():
    """An edge referencing an output its producer doesn't have."""
    net = _mlp()
    relu_node = None
    for node in net.get_internals()._entries:
        if node[0].name == "relu1":
            relu_node = node[0]
    child, _ = relu_node.inputs[0]
    relu_node.inputs[0] = (child, 7)
    with pytest.raises(GraphVerifyError) as ei:
        net.verify()
    msg = str(ei.value)
    assert "dangling-input" in msg and "'relu1'" in msg and "7" in msg


def test_verify_missing_inputs_flagged():
    net = _mlp()
    for node in net.get_internals()._entries:
        if node[0].name == "fc1":
            node[0].inputs = node[0].inputs[:1]  # drop the weight input
    issues = net.verify(raise_on_error=False)
    assert any(i.code == "dangling-input" and i.node == "fc1"
               for i in issues if i.is_error)


def test_verify_cycle_detected():
    net = _mlp()
    nodes = {n.name: n for n, _ in net.get_internals()._entries}
    # wire fc1's input list back to the head: a back edge
    nodes["fc1"].inputs.append((nodes["softmax"], 0))
    with pytest.raises(GraphVerifyError) as ei:
        net.verify()
    msg = str(ei.value)
    assert "cycle" in msg and "fc1" in msg and "softmax" in msg


def test_verify_duplicate_var_name_error():
    a1 = mx.sym.var("a")
    a2 = mx.sym.var("a")  # distinct node, same name
    s = mx.sym.elemwise_add(a1, a2, name="add0")
    with pytest.raises(GraphVerifyError) as ei:
        s.verify()
    assert "duplicate-name" in str(ei.value)


def test_verify_unused_hint_warning():
    issues = _mlp().verify(raise_on_error=False, data=(8, 100),
                           softmax_label=(8,), dta=(8, 100))
    warn = [i for i in issues if i.code == "unused-hint"]
    assert len(warn) == 1 and warn[0].node == "dta"
    assert not warn[0].is_error


def test_verify_dead_output_warning():
    x = mx.sym.var("x")
    parts = mx.sym.SliceChannel(x, num_outputs=3, axis=1, name="split0")
    head = parts[0] + 1.0  # outputs 1 and 2 never consumed
    issues = head.verify(raise_on_error=False, x=(2, 6))
    dead = [i for i in issues if i.code == "dead-output"]
    assert len(dead) == 1 and dead[0].node == "split0"
    assert "[1, 2]" in dead[0].message


def test_verify_output_arity_violation():
    x = mx.sym.var("x")
    parts = mx.sym.SliceChannel(x, num_outputs=3, axis=1, name="split0")
    node = parts._entries[0][0]
    node.attrs["num_outputs"] = 2  # lie about the hyper-parameter
    issues = mx.sym.Group(list(parts)).verify(raise_on_error=False,
                                              x=(2, 6))
    assert any(i.code == "output-arity" for i in issues if i.is_error)


def test_simple_bind_runs_verifier(monkeypatch):
    act = mx.sym.Activation(mx.sym.var("x"), act_type="relu", name="a1")
    act._entries[0][0].attrs["act_type"] = "rleu"
    with pytest.raises(GraphVerifyError):
        act.simple_bind(x=(2, 2))
    # opt-out restores the old behaviour (error surfaces later, if at all)
    monkeypatch.setenv("MXNET_TPU_VERIFY", "0")
    with pytest.raises(Exception) as ei:
        act.simple_bind(x=(2, 2))
    assert not isinstance(ei.value, GraphVerifyError)


def test_verify_group_and_json_roundtrip():
    net = _mlp()
    loaded = mx.sym.load_json(net.tojson())
    assert loaded.verify(data=(8, 100), softmax_label=(8,)) == []
    out1 = net.eval_with({"data": mx.nd.ones((2, 100)),
                          "fc1_weight": mx.nd.ones((16, 100)),
                          "fc1_bias": mx.nd.zeros((16,)),
                          "fc2_weight": mx.nd.ones((4, 16)),
                          "fc2_bias": mx.nd.zeros((4,)),
                          "softmax_label": mx.nd.zeros((2,))})
    assert out1.shape == (2, 4)


def test_infer_shape_error_names_node():
    """Satellite: infer_shape failures carry node-level diagnostics."""
    a, b = mx.sym.var("a"), mx.sym.var("b")
    s = mx.sym.elemwise_add(a, b, name="add0")
    with pytest.raises(mx.MXNetError) as ei:
        s.infer_shape(a=(2, 3), b=(4, 5))
    msg = str(ei.value)
    assert "'add0'" in msg and "elemwise_add" in msg
    assert "(2, 3)" in msg and "(4, 5)" in msg


def test_infer_type_error_names_node():
    x = mx.sym.var("x")
    y = mx.sym.Cast(x, dtype="float16", name="cast0")
    y._entries[0][0].attrs["dtype"] = "floatsixteen"
    with pytest.raises(mx.MXNetError) as ei:
        y.infer_type(x="float32")
    msg = str(ei.value)
    assert "'cast0'" in msg and "Cast" in msg


def test_verify_graph_function_api():
    issues = verify_graph(_mlp(), {"data": (8, 100)}, {"data": "float32"})
    assert issues == []


# ------------------------------------------------------------ sanitizer ----

@pytest.fixture
def clean_sanitizer():
    sanitize.reset()
    yield
    sanitize.disable()
    sanitize.reset()


def test_sanitizer_disabled_by_default(clean_sanitizer):
    x = mx.nd.ones((2, 2))
    _ = x.asnumpy()
    assert sanitize.events() == []


def test_sanitizer_records_syncs_with_callsite(clean_sanitizer):
    with sanitize.sanitize():
        x = mx.nd.ones((2, 2))
        _ = x.asnumpy()
        _ = (x.sum()).asscalar()
        _ = bool(x[0, 0] > 0)
        x.wait_to_read()
    kinds = [e.kind for e in sanitize.events()]
    assert kinds == ["asnumpy", "asscalar", "bool", "wait_to_read"]
    assert all(__file__ in e.site for e in sanitize.events())
    assert sanitize.hazards() == []  # no segment was open


def test_sanitizer_flags_mid_segment_sync(clean_sanitizer):
    """Acceptance: a planted host sync inside a live bulk segment is
    flagged as a hazard, exactly once, with the user call site."""
    with sanitize.sanitize():
        with mx.engine.bulk(8):
            a = mx.nd.ones((4, 4))
            c = (a * 2) + 1
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                np.testing.assert_allclose(c.asnumpy(), 3.0)  # splits it
            hazard_warns = [x for x in w
                            if issubclass(x.category,
                                          sanitize.SyncHazardWarning)]
            assert len(hazard_warns) == 1
            assert "split a live bulk segment of 2" in \
                str(hazard_warns[0].message)
    hz = sanitize.hazards()
    assert len(hz) == 1 and hz[0].kind == "asnumpy" and hz[0].pending == 2
    assert "test_analysis.py" in hz[0].site


def test_sanitizer_lazy_force_hazard(clean_sanitizer):
    """A raw buffer read (not via asnumpy) also records, as lazy-force."""
    with sanitize.sanitize():
        with mx.engine.bulk(8):
            a = mx.nd.ones((4, 4))
            b = a * 2
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", sanitize.SyncHazardWarning)
                _ = b._data  # direct force
    hz = sanitize.hazards()
    assert len(hz) == 1 and hz[0].kind == "lazy-force"


def test_sanitizer_clean_bulk_flush_not_flagged(clean_sanitizer):
    with sanitize.sanitize():
        with mx.engine.bulk(4):
            a = mx.nd.ones((4, 4))
            c = (a * 2) + 1
        # scope exit flushed the segment: reading now is not a hazard
        np.testing.assert_allclose(c.asnumpy(), 3.0)
    assert sanitize.hazards() == []


def test_sanitizer_contract_violation_eager(clean_sanitizer):
    """Acceptance: an output-aval contract violation (stale/poisoned
    inference cache) is reported with the op name and call site."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import registry

    op = registry.get("relu")
    x = mx.nd.ones((3,))
    in_sig = ((tuple(x.shape), x._data.dtype),)
    op._aval_cache[((), in_sig)] = (
        (jax.ShapeDtypeStruct((99,), jnp.float32),), True)
    try:
        with sanitize.sanitize():
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                y = mx.nd.invoke("relu", x)
            assert y.shape == (3,)  # execution itself is unaffected
            msgs = [str(x.message) for x in w
                    if issubclass(x.category, sanitize.SyncHazardWarning)]
            assert len(msgs) == 1
            assert "contract violation" in msgs[0] and "relu" in msgs[0]
            assert "(99,)" in msgs[0] and "(3,)" in msgs[0]
    finally:
        op._aval_cache.clear()
    ev = [e for e in sanitize.events() if e.kind == "contract"]
    assert len(ev) == 1 and ev[0].hazard


def test_sanitizer_contract_violation_in_segment(clean_sanitizer):
    """The fused-segment runner cross-checks too: poison the prediction the
    recorder will wire against, then flush."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import registry

    op = registry.get("_plus_scalar")
    x = mx.nd.ones((5,))
    in_sig = ((tuple(x.shape), x._data.dtype),)
    kwargs, key = op.checked({"scalar": 1.0})
    op._aval_cache[(key, in_sig)] = (
        (jax.ShapeDtypeStruct((7,), jnp.float32),), True)
    try:
        with sanitize.sanitize():
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                with mx.engine.bulk(8):
                    y = x + 1.0  # recorded with the poisoned aval
                assert y.shape == (7,)  # the recorder believed the lie
            msgs = [str(x.message) for x in w
                    if "contract violation" in str(x.message)]
            assert msgs and "bulk segment" in msgs[0]
    finally:
        op._aval_cache.clear()


def test_sanitizer_reset_and_bounded(clean_sanitizer):
    with sanitize.sanitize():
        x = mx.nd.ones((1,))
        for _ in range(3):
            x.asnumpy()
    assert len(sanitize.events()) == 3
    sanitize.reset()
    assert sanitize.events() == []
