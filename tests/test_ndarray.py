"""NDArray core semantics tests.

Parity model: tests/python/unittest/test_ndarray.py in the reference —
creation, arithmetic, mutation, slicing, context moves, serialization-ready
properties, async sync points.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, default_context


def test_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert a.asnumpy().sum() == 0

    from mxnet_tpu._jax_compat import enable_x64

    with enable_x64():
        b = mx.nd.ones((2,), dtype=np.float64)
        assert b.dtype == np.float64
        assert_almost_equal(b, np.ones(2))

    # python lists default to float32 regardless of content (parity:
    # mx.nd.array dtype rule — never int64/float64 from plain lists)
    assert mx.nd.array([1, 2, 3]).dtype == np.float32
    assert mx.nd.array([1.5]).dtype == np.float32
    # numpy sources keep their dtype
    assert mx.nd.array(np.array([1, 2], dtype=np.int32)).dtype == np.int32

    c = mx.nd.full((2, 2), 7)
    assert_almost_equal(c, np.full((2, 2), 7.0))

    d = mx.nd.array([[1, 2], [3, 4]])
    assert_almost_equal(d, np.array([[1, 2], [3, 4]]))

    e = mx.nd.arange(0, 10, 2)
    assert_almost_equal(e, np.arange(0, 10, 2, dtype=np.float32))

    f = mx.nd.eye(3)
    assert_almost_equal(f, np.eye(3))


def test_arithmetic():
    a_np = np.random.rand(3, 4).astype(np.float32)
    b_np = np.random.rand(3, 4).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    assert_almost_equal(a + b, a_np + b_np)
    assert_almost_equal(a - b, a_np - b_np)
    assert_almost_equal(a * b, a_np * b_np)
    assert_almost_equal(a / b, a_np / b_np)
    assert_almost_equal(a ** 2, a_np ** 2)
    assert_almost_equal(-a, -a_np)
    assert_almost_equal(abs(a - b), np.abs(a_np - b_np))
    # scalar, including reversed
    assert_almost_equal(a + 1, a_np + 1)
    assert_almost_equal(1 + a, 1 + a_np)
    assert_almost_equal(2 - a, 2 - a_np)
    assert_almost_equal(2 / a, 2 / a_np)
    assert_almost_equal(a % 2, a_np % 2)
    assert_almost_equal(2 ** a, 2 ** a_np)


def test_comparisons():
    a = mx.nd.array([1, 2, 3])
    b = mx.nd.array([3, 2, 1])
    assert_almost_equal(a == b, np.array([0, 1, 0], dtype=np.float32))
    assert_almost_equal(a != b, np.array([1, 0, 1], dtype=np.float32))
    assert_almost_equal(a > b, np.array([0, 0, 1], dtype=np.float32))
    assert_almost_equal(a >= 2, np.array([0, 1, 1], dtype=np.float32))
    assert_almost_equal(a < b, np.array([1, 0, 0], dtype=np.float32))


def test_broadcast():
    a = mx.nd.ones((3, 1))
    b = mx.nd.ones((1, 4))
    assert (a + b).shape == (3, 4)
    c = mx.nd.ones((3, 4))
    assert (c + 1.0).shape == (3, 4)
    assert a.broadcast_to((3, 4)).shape == (3, 4)


def test_mutation():
    a = mx.nd.zeros((3, 4))
    a[:] = 5
    assert a.asnumpy().sum() == 60
    a[1] = 0
    assert a.asnumpy()[1].sum() == 0
    a[0, 2] = 9
    assert a.asnumpy()[0, 2] == 9
    a += 1
    assert a.asnumpy()[1, 0] == 1
    b = mx.nd.ones((3, 4))
    a[:] = b
    assert_almost_equal(a, np.ones((3, 4)))


def test_indexing():
    a_np = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    a = mx.nd.array(a_np)
    assert_almost_equal(a[1], a_np[1])
    assert_almost_equal(a[0, 1], a_np[0, 1])
    assert_almost_equal(a[:, 1:3], a_np[:, 1:3])
    assert_almost_equal(a[1, 2, 3], a_np[1, 2, 3])
    idx = mx.nd.array([0, 1])
    assert_almost_equal(a[idx], a_np[[0, 1]])


def test_shape_ops():
    a = mx.nd.arange(0, 24).reshape(2, 3, 4)
    assert a.reshape(6, 4).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.transpose().shape == (4, 3, 2)
    assert a.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.expand_dims(0).squeeze(0).shape == (2, 3, 4)
    assert a.swapaxes(0, 2).shape == (4, 3, 2)
    assert mx.nd.concat(a, a, dim=1).shape == (2, 6, 4)
    assert mx.nd.stack(a, a, axis=0).shape == (2, 2, 3, 4)
    outs = a.split(3, axis=1)
    assert len(outs) == 3 and outs[0].shape == (2, 1, 4)


def test_reduce():
    a_np = np.random.rand(3, 4, 5).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(a.sum(), a_np.sum())
    assert_almost_equal(a.sum(axis=1), a_np.sum(axis=1))
    assert_almost_equal(a.mean(axis=(0, 2)), a_np.mean(axis=(0, 2)))
    assert_almost_equal(a.max(axis=0), a_np.max(axis=0))
    assert_almost_equal(a.min(), a_np.min())
    assert_almost_equal(a.argmax(axis=2), np.argmax(a_np, axis=2))
    assert_almost_equal(a.norm(), np.linalg.norm(a_np.reshape(-1)))


def test_dot():
    a_np = np.random.rand(4, 5).astype(np.float32)
    b_np = np.random.rand(5, 3).astype(np.float32)
    assert_almost_equal(mx.nd.dot(mx.nd.array(a_np), mx.nd.array(b_np)),
                        a_np @ b_np)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a_np), mx.nd.array(b_np.T), transpose_b=True),
        a_np @ b_np)


def test_astype_copy():
    a = mx.nd.ones((2, 2))
    b = a.astype(np.float16)
    assert b.dtype == np.float16
    c = a.copy()
    c[:] = 0
    assert a.asnumpy().sum() == 4  # copy is deep


def test_scalar_conversion():
    a = mx.nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == pytest.approx(3.5)
    with pytest.raises(ValueError):
        bool(mx.nd.ones((2,)))


def test_context_moves():
    ctx = default_context()
    a = mx.nd.ones((2, 2), ctx=ctx)
    assert a.context.device_type in ("cpu", "tpu", "gpu")
    b = a.as_in_context(mx.cpu(0))
    assert b.context.device_type == "cpu"
    c = mx.nd.zeros((2, 2))
    a.copyto(c)
    assert c.asnumpy().sum() == 4


def test_waitall_and_sync():
    a = mx.nd.ones((16, 16))
    for _ in range(5):
        a = a * 1.0 + 0.0
    a.wait_to_read()
    mx.nd.waitall()
    assert a.asnumpy().sum() == 256


def test_take_one_hot():
    a = mx.nd.array([[1, 2], [3, 4], [5, 6]])
    idx = mx.nd.array([0, 2])
    assert_almost_equal(a.take(idx), np.array([[1, 2], [5, 6]]))
    oh = mx.nd.array([1, 0, 2]).one_hot(3)
    assert_almost_equal(oh, np.eye(3)[[1, 0, 2]])


def test_iter_len():
    a = mx.nd.arange(0, 6).reshape(3, 2)
    assert len(a) == 3
    rows = list(a)
    assert len(rows) == 3 and rows[2].shape == (2,)


def test_int64_index_posture():
    """Large-tensor (int64 index) posture. The reference gates
    >2^31-element tensors behind MXNET_INT64_TENSOR_SIZE and tests them
    nightly (tests/nightly/test_large_array.py). Here the gate is JAX
    x64: with it OFF (production default) int64 inputs store as int32 —
    fine below 2^31 elements; inside `jax.experimental.enable_x64()`
    int64 indices/labels are preserved end-to-end, which is the
    large-tensor mode. This pins both halves of that contract."""
    import numpy as np

    import mxnet_tpu as mx

    # default runtime: int64 narrows to int32 (documented posture)
    idx32 = mx.nd.array(np.array([0, 2, 1], np.int64), dtype="int64")
    assert str(idx32.dtype) == "int32"
    data = mx.nd.array(np.arange(12).reshape(4, 3).astype("f"))
    out = mx.nd.take(data, idx32)
    np.testing.assert_array_equal(out.asnumpy(),
                                  data.asnumpy()[[0, 2, 1]])

    # large-tensor mode: x64 scope preserves int64 end-to-end
    import tempfile

    from mxnet_tpu._jax_compat import enable_x64

    with enable_x64():
        idx = mx.nd.array(np.array([0, 2, 1], np.int64), dtype="int64")
        assert str(idx.dtype) == "int64"
        out = mx.nd.take(data, idx)
        np.testing.assert_array_equal(out.asnumpy(),
                                      data.asnumpy()[[0, 2, 1]])
        with tempfile.NamedTemporaryFile(suffix=".npz") as f:
            mx.nd.save(f.name, {"i": idx})
            back = mx.nd.load(f.name)["i"]
        assert str(back.dtype) == "int64"
        np.testing.assert_array_equal(back.asnumpy(), idx.asnumpy())
