"""Serving fleet: router policies, autoscaler, serving-mode supervision,
zero-downtime rollout (mxnet_tpu/serving/fleet.py + worker.py,
docs/SERVING.md "Fleet").

Headline guarantees under test:

* routing — least-loaded picks the shallow queue (falling back to
  round-robin without depth data), the consistent-hash ring keeps
  placements stable under worker-set change;
* autoscaling — the decision core scales up after K sustained pressure
  samples, down on sustained idle, respects min/max bounds and the
  cooldown (table-tested on synthetic gauge series), and the LIVE loop
  demonstrably grows 1→2 under injected load and shrinks back on idle
  with the decisions visible in the gauges and the diagnose report;
* serving-mode supervision — a crashed slot restarts individually with
  backoff, a deliberately drained slot (exit 75) is retired, a restart
  budget parks a flapping slot as failed;
* rollout — the health gate refuses an unwarmed worker (pending
  compiles) leaving the old generation serving; the acceptance drill
  rolls a live fleet mid-load with ZERO dropped admitted requests and
  ZERO recompiles in the new generation (warm from the disk cache);
* hedging — hedged_call fires only past the threshold, first answer
  wins, a fast failure takes ordinary failover (never re-issued), and
  the HedgeGovernor's threshold/plan/straggler-flag/canary-probe and
  remote-penalty arithmetic table-test;
* multi-host — the hosts= grammar normalizes (and rejects) placement
  specs, locality-aware ordering spills to remote only past the
  measured penalty, and a live 2-pseudo-host fleet places slots
  round-robin with per-host run dirs merged at scrape;
* QoS — a provably-unmeetable deadline drops BEFORE consuming a batch
  slot; the prediction cache serves copies, stays bounded, and a live
  weight swap (model-bus version flip) can never serve stale data;
* loadgen — the keep-alive HTTP client reuses one connection per worker
  thread (connect time reported separately from request time).
"""
import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import elastic
from mxnet_tpu.serving import fleet as fleet_mod
from mxnet_tpu.serving import worker as worker_mod
from mxnet_tpu.serving.fleet import (Autoscaler, HashRing, ServingFleet,
                                     gate_ready, order_candidates,
                                     worker_metrics)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def _py(body):
    return [sys.executable, "-c", body]


# --------------------------------------------------------------- config ----

def test_fleet_config_grammar():
    cfg = fleet_mod._parse("min:2,max:6;up_queue:8,up_p99_ms:50.5,"
                           "k:2,idle_rps:0.5,idle_k:4,cooldown:3,"
                           "policy:hash,beat:0.1")
    assert cfg["min"] == 2 and cfg["max"] == 6
    assert cfg["up_queue"] == 8 and cfg["up_p99_ms"] == 50.5
    assert cfg["k"] == 2 and cfg["idle_k"] == 4
    assert cfg["policy"] == "hash" and cfg["beat"] == 0.1
    # untouched keys keep their defaults
    assert cfg["interval"] == fleet_mod.DEFAULTS["interval"]


def test_fleet_config_bad_specs():
    with pytest.raises(ValueError, match="unknown fleet option"):
        fleet_mod._parse("mni:2")
    with pytest.raises(ValueError, match="unknown fleet policy"):
        fleet_mod._parse("policy:fastest")
    with pytest.raises(ValueError, match="expected <option>:<value>"):
        fleet_mod._parse("min")
    with pytest.raises(ValueError, match="max .* < min"):
        fleet_mod._parse("min:4,max:2")
    with pytest.raises(ValueError, match=">= 1"):
        fleet_mod._parse("min:0")


# -------------------------------------------------------------- routing ----

def test_hash_ring_stable_under_worker_set_change():
    ring = HashRing([0, 1, 2, 3])
    keys = [f"model{i}" for i in range(200)]
    before = {k: ring.lookup(k) for k in keys}
    assert set(before.values()) == {0, 1, 2, 3}  # all slots own keys
    ring.rebuild([0, 1, 3])  # slot 2 dies
    after = {k: ring.lookup(k) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    # ONLY the dead slot's keys may move — the consistent-hash property
    assert all(before[k] == 2 for k in moved)
    assert all(after[k] != 2 for k in keys)
    # allowed= restricts without rebuilding (the router's live filter)
    ring2 = HashRing([0, 1, 2, 3])
    assert ring2.lookup("modelX", allowed={1}) == 1


def test_least_loaded_picks_the_shallow_queue():
    depths = {0: 7.0, 1: 0.0, 2: 12.0}
    order = order_candidates("least_loaded", "m", [0, 1, 2],
                             depths=depths, rr=0)
    assert order[0] == 1 and order[-1] == 2
    # unknown depth counts as an empty queue (a fresh worker)
    order = order_candidates("least_loaded", "m", [0, 1, 2],
                             depths={0: 5.0}, rr=0)
    assert order[-1] == 0
    # no depth data at all -> pure round-robin rotation
    a = order_candidates("least_loaded", "m", [0, 1, 2], depths={}, rr=1)
    b = order_candidates("least_loaded", "m", [0, 1, 2], depths={}, rr=2)
    assert a == [1, 2, 0] and b == [2, 0, 1]


def test_hash_policy_orders_owner_first():
    ring = HashRing([0, 1, 2])
    owner = ring.lookup("modelA")
    order = order_candidates("hash", "modelA", [0, 1, 2], rr=5, ring=ring)
    assert order[0] == owner and sorted(order) == [0, 1, 2]
    assert order_candidates("round_robin", "m", [], rr=3) == []


# ------------------------------------------------------------ autoscaler ----

def _scaler(**over):
    cfg = dict(fleet_mod.DEFAULTS)
    cfg.update({"min": 1, "max": 4, "up_queue": 10, "up_p99_ms": 100.0,
                "up_fill": 0.99, "k": 3, "idle_rps": 1.0, "idle_k": 2,
                "cooldown": 5.0})
    cfg.update(over)
    return Autoscaler(cfg)


def test_autoscaler_scales_up_after_k_sustained_samples():
    sc = _scaler()
    hot = {"queue_depth": 50, "p99_ms": 5.0, "fill": 0.5, "rps": 100.0}
    assert sc.decide(hot, workers=1, now=0.0)[0] is None
    assert sc.decide(hot, workers=1, now=1.0)[0] is None
    direction, rec = sc.decide(hot, workers=1, now=2.0)
    assert direction == "up" and "queue" in rec["reason"]
    # a non-pressure sample resets the streak
    sc2 = _scaler()
    sc2.decide(hot, 1, now=0.0)
    sc2.decide({"queue_depth": 0, "rps": 100.0}, 1, now=1.0)
    sc2.decide(hot, 1, now=2.0)
    assert sc2.decide(hot, 1, now=3.0)[0] is None  # streak restarted


def test_autoscaler_cooldown_and_bounds():
    sc = _scaler(k=1, cooldown=10.0)
    hot = {"queue_depth": 99, "rps": 50.0}
    assert sc.decide(hot, 1, now=0.0)[0] == "up"
    # cooling down: pressure persists but nothing fires
    direction, rec = sc.decide(hot, 2, now=1.0)
    assert direction is None and rec["reason"] == "cooling down"
    # past the cooldown it fires again
    assert sc.decide(hot, 2, now=11.0)[0] == "up"
    # at max: held, named
    sc3 = _scaler(k=1)
    d, rec = sc3.decide(hot, 4, now=0.0)
    assert d is None and "at max" in rec["reason"]


def test_autoscaler_scales_down_on_sustained_idle():
    sc = _scaler(idle_k=3, cooldown=0.0)
    idle = {"queue_depth": 0, "p99_ms": 2.0, "fill": 0.2, "rps": 0.0}
    assert sc.decide(idle, 3, now=0.0)[0] is None
    assert sc.decide(idle, 3, now=1.0)[0] is None
    d, rec = sc.decide(idle, 3, now=2.0)
    assert d == "down" and "idle" in rec["reason"]
    # at min: held
    sc2 = _scaler(idle_k=1)
    d, rec = sc2.decide(idle, 1, now=0.0)
    assert d is None and "at min" in rec["reason"]
    # busy samples are not idle (rps above the floor)
    sc3 = _scaler(idle_k=1)
    assert sc3.decide({"queue_depth": 0, "rps": 500.0}, 3,
                      now=0.0)[0] is None
    assert sc.describe()["decisions"]["down"] == 1


# ----------------------------------------------------- gate + shard files ---

def test_health_gate_refuses_unwarmed_announce():
    ready = {"state": "serving", "ready": True, "pending_compiles": 0}
    assert gate_ready(ready)
    assert not gate_ready(None)
    assert not gate_ready({})
    assert not gate_ready(dict(ready, pending_compiles=5, ready=False))
    assert not gate_ready(dict(ready, pending_compiles=3))
    assert not gate_ready(dict(ready, state="drained"))


def test_worker_metrics_reads_serving_gauges_from_shards(tmp_path):
    shard = {
        "version": 1, "rank": 7, "generation": 2, "pid": 1, "seq": 1,
        "t_wall": time.time(), "t_mono": 0.0,
        "metrics": {
            "mxtpu_serving_queue_depth": {
                "kind": "gauge", "labels": ["model"],
                "series": [{"labels": {"model": "a"}, "value": 3.0},
                           {"labels": {"model": "b"}, "value": 2.0}]},
            "mxtpu_serving_latency_ms": {
                "kind": "gauge", "labels": ["model", "quantile"],
                "series": [{"labels": {"model": "a", "quantile": "p99"},
                            "value": 12.5},
                           {"labels": {"model": "a", "quantile": "p50"},
                            "value": 4.0}]},
            "mxtpu_serving_requests_total": {
                "kind": "counter", "labels": ["model", "outcome"],
                "series": [{"labels": {"model": "a",
                                       "outcome": "completed"},
                            "value": 41.0}]},
        }}
    path = tmp_path / "telemetry-rank-7.json"
    path.write_text(json.dumps(shard))
    m = worker_metrics(tmp_path)
    assert m[7]["queue_depth"] == 5.0       # summed over models
    assert m[7]["p99_ms"] == 12.5           # p99 only, p50 ignored
    assert m[7]["completed"] == 41.0
    assert m[7]["generation"] == 2
    # slots filter
    assert worker_metrics(tmp_path, slots={3}) == {}
    # torn shard skipped
    (tmp_path / "telemetry-rank-8.json").write_text("{\"rank\": 8")
    assert 8 not in worker_metrics(tmp_path)


def test_read_workers_skips_torn_announces(tmp_path):
    worker_mod._write_announce(tmp_path, 3, {"slot": 3, "state": "x"})
    (tmp_path / "worker-4.json").write_text("{nope")
    out = worker_mod.read_workers(tmp_path)
    assert list(out) == [3]


def test_spec_roundtrip_demo_and_checkpoint(tmp_path):
    import mxnet_tpu as mx

    spec = worker_mod.demo_spec(models=2, dim=8, seed=3, buckets=(2, 4))
    # a checkpoint entry next to the demo pair
    x = mx.sym.var("data")
    sym = mx.sym.FullyConnected(x, num_hidden=4, name="fl_fc")
    rng = np.random.RandomState(0)
    args = {"fl_fc_weight": mx.nd.array(rng.randn(4, 8).astype("float32")),
            "fl_fc_bias": mx.nd.zeros((4,))}
    mx.model.save_checkpoint(str(tmp_path / "ck"), 2, sym, args, {})
    spec.append({"kind": "checkpoint", "name": "ckm", "prefix": "ck",
                 "epoch": 2, "example_shape": [8], "buckets": [2, 4]})
    worker_mod.write_spec(tmp_path, spec)
    container, loaded = worker_mod.load_container(tmp_path)
    assert container.names() == ["model0", "model1", "ckm"]
    assert container["model0"].buckets == (2, 4)
    # demo models are seed-deterministic: a second build bit-matches
    container2, _ = worker_mod.load_container(tmp_path)
    xq = rng.randn(2, 8).astype("float32")
    a = container["model0"].run(xq)[0]
    b = container2["model0"].run(xq)[0]
    np.testing.assert_array_equal(a, b)
    # malformed specs fail loudly, naming the entry
    worker_mod.write_spec(tmp_path, [{"kind": "zeppelin", "name": "z"}])
    with pytest.raises(ValueError, match="unknown kind 'zeppelin'"):
        worker_mod.load_container(tmp_path)
    with pytest.raises(ValueError, match="no serving spec"):
        worker_mod.load_container(tmp_path / "nope")


# ------------------------------------------------- serving supervision -----

def _sup(run_dir, body, **kw):
    kw.setdefault("backoff", 0.05)
    kw.setdefault("grace", 5.0)
    kw.setdefault("dead_after", 0)
    return elastic.ServingSupervisor(
        lambda slot, gen: _py(body), run_dir, **kw)


def _poll_until(sup, pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        census = sup.poll()
        if pred(census):
            return census
        time.sleep(0.05)
    raise AssertionError(f"condition not reached; census={sup.census()} "
                         f"events={sup.events}")


def test_serving_supervisor_restarts_crashed_slot(tmp_path):
    """An unrequested death restarts the SLOT individually (not a gang):
    first spawn crashes with a real error code, the restart stays up."""
    marker = tmp_path / "flag"
    body = ("import os, sys, time\n"
            f"m = {str(marker)!r}\n"
            "if os.path.exists(m):\n"
            "    time.sleep(60)\n"
            "open(m, 'w').close()\n"
            "sys.exit(7)\n")
    sup = _sup(tmp_path / "run", body)
    sup.spawn(0, 1)
    census = _poll_until(
        sup, lambda c: c.get(0, {}).get("alive")
        and c[0].get("restarts") == 1)
    assert census[0]["generation"] == 1
    kinds = [e["kind"] for e in sup.events]
    assert "restart" in kinds
    restart = next(e for e in sup.events if e["kind"] == "restart")
    assert restart["exit_code"] == 7
    assert sup.restarts_total == 1
    assert sup.stop_all(graceful=False)


def test_serving_supervisor_deliberate_drain_retires_slot(tmp_path):
    """drain_slot -> SIGTERM -> exit 75 removes the slot (rollout /
    scale-down semantics) instead of restarting it."""
    armed = tmp_path / "armed"
    body = ("import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))\n"
            f"open({str(armed)!r}, 'w').close()\n"
            "while True:\n"
            "    time.sleep(0.05)\n")
    sup = _sup(tmp_path / "run", body)
    sup.spawn(4, 2)
    # wait for the handler to be armed — a SIGTERM into interpreter
    # startup would take the default disposition (exit 143) instead
    _poll_until(sup, lambda c: armed.exists())
    sup.drain_slot(4, reason="test-retire")
    _poll_until(sup, lambda c: 4 not in c)
    ev = next(e for e in sup.events if e["kind"] == "drained")
    assert ev["slot"] == 4 and ev["exit_code"] == 75
    assert ev["generation"] == 2
    assert sup.drained_total == 1 and sup.restarts_total == 0


def test_serving_supervisor_restart_budget_parks_slot(tmp_path):
    sup = _sup(tmp_path / "run", "import sys; sys.exit(5)",
               max_restarts=2, backoff=0.01)
    sup.spawn(0, 1)
    census = _poll_until(
        sup, lambda c: c.get(0, {}).get("state") == "failed")
    assert census[0]["restarts"] == 2
    assert any(e["kind"] == "slot_failed" for e in sup.events)
    desc = sup.describe()
    assert desc["restarts_total"] == 2
    sup.stop_all(graceful=False)


# --------------------------------------------------------- hedging -------

def _gov(**over):
    cfg = dict(fleet_mod.DEFAULTS)
    cfg.update(over)
    return fleet_mod.HedgeGovernor(cfg)


def test_hedged_call_fires_only_past_threshold():
    """A primary that answers inside the threshold is returned as-is —
    the hedge closure is never invoked."""
    hedged = []
    rec = fleet_mod.hedged_call(lambda: "fast",
                                lambda: hedged.append(1) or "h",
                                hedge_after=0.5)
    assert rec["winner"] == "primary" and rec["value"] == "fast"
    assert rec["hedged"] is False and not hedged


def test_hedged_call_first_answer_wins():
    """Past the threshold the hedge is issued and the FIRST successful
    answer wins; the slow loser is abandoned, not awaited."""
    t0 = time.monotonic()
    rec = fleet_mod.hedged_call(lambda: time.sleep(2.0) or "slow",
                                lambda: "rescue",
                                hedge_after=0.02)
    assert rec["winner"] == "hedge" and rec["value"] == "rescue"
    assert rec["hedged"] is True
    assert time.monotonic() - t0 < 1.5  # did not wait for the loser


def test_hedged_call_fast_failure_is_not_hedged():
    """A primary that FAILS before the threshold takes the ordinary
    failover path — hedging never re-issues after a failure."""
    hedged = []

    def boom():
        raise ConnectionRefusedError("dead worker")

    rec = fleet_mod.hedged_call(boom, lambda: hedged.append(1) or "h",
                                hedge_after=0.5)
    assert rec["winner"] is None and rec["hedged"] is False
    assert isinstance(rec["primary_error"], ConnectionRefusedError)
    assert not hedged


def test_hedged_call_late_primary_error_waits_for_inflight_hedge():
    """Once the hedge is in flight, a primary failure (e.g. a timeout)
    legally waits for the ALREADY-ISSUED hedge — nothing new is issued
    after a failure, and both failing surfaces the primary's error."""
    def slow_fail():
        time.sleep(0.05)
        raise TimeoutError("upstream timeout")

    rec = fleet_mod.hedged_call(slow_fail,
                                lambda: time.sleep(0.1) or 42,
                                hedge_after=0.01)
    assert rec["winner"] == "hedge" and rec["value"] == 42

    def fail_too():
        time.sleep(0.05)
        raise ConnectionResetError("hedge died too")

    rec = fleet_mod.hedged_call(slow_fail, fail_too, hedge_after=0.01)
    assert rec["winner"] is None and rec["hedged"] is True
    assert isinstance(rec["primary_error"], TimeoutError)
    assert isinstance(rec["hedge_error"], ConnectionResetError)


def test_hedge_governor_threshold_table():
    g = _gov(hedge_min_ms=20.0, hedge_factor=2.0, timeout_ms=30000.0)
    assert g.threshold(0) is None          # <16 samples: signal too thin
    for _ in range(32):
        g.note(0, 10.0)
    assert g.threshold(0) == 20.0          # p99*factor floored at min_ms
    for _ in range(32):
        g.note(0, 100.0)
    assert g.threshold(0) == 200.0         # p99 100 x factor 2
    # capped at half the upstream timeout
    assert _gov(timeout_ms=300.0).threshold(0) is None
    g2 = _gov(hedge_min_ms=20.0, hedge_factor=2.0, timeout_ms=300.0)
    for _ in range(32):
        g2.note(0, 100.0)
    assert g2.threshold(0) == 150.0
    # a flagged straggler gets the floor immediately, no ring needed
    g3 = _gov(hedge_min_ms=25.0)
    g3.stragglers = frozenset({3})
    assert g3.threshold(3) == 25.0


def test_hedge_governor_plan_table():
    ep = {0: "http://a", 1: "http://b"}.get
    g = _gov(hedge=0)
    for _ in range(32):
        g.note(0, 10.0)
    assert g.plan(0, [0, 1], ep) == (None, None)      # hedging off
    g = _gov(hedge=1, hedge_min_ms=20.0)
    assert g.plan(0, [0, 1], ep) == (None, None)      # thin signal
    for _ in range(32):
        g.note(0, 10.0)
    assert g.plan(0, [0], ep) == (None, None)         # no second cand
    assert g.plan(0, [0, 2], ep) == (None, None)      # no live endpoint
    cand, thr = g.plan(0, [0, 1], ep)
    assert cand == 1 and thr == 20.0


def test_hedge_governor_straggler_flag_reorder_and_probe():
    g = _gov()
    for _ in range(8):
        g.note(0, 10.0)
        g.note(1, 150.0)
    # the flag needs `persist` consecutive verdicts, not one
    assert g.update_stragglers([0, 1]) == frozenset()
    assert g.update_stragglers([0, 1]) == frozenset()
    assert g.update_stragglers([0, 1]) == frozenset({1})
    # flagged slots stable-move to the tail of every candidate order...
    assert g.reorder([1, 0], rr=1) == [0, 1]
    assert g.reorder([1, 0, 2], rr=7) == [0, 2, 1]
    # ...EXCEPT the canary probe, which keeps its natural placement
    assert g.reorder([1, 0], rr=0) == [1, 0]
    assert g.reorder([1, 0], rr=g.PROBE_EVERY) == [1, 0]
    # recovery: the probes' fast answers decay the EWMA and the flag
    # clears on the next interval
    for _ in range(40):
        g.note(1, 10.0)
    assert g.update_stragglers([0, 1]) == frozenset()
    assert g.reorder([1, 0], rr=1) == [1, 0]


def test_hedge_governor_remote_penalty():
    g = _gov(hedge=1)
    g._locality_of = lambda slot: "remote" if slot >= 2 else "local"
    assert g.remote_penalty() == 0.0       # no signal yet
    g.note(0, 10.0)
    assert g.remote_penalty() == 0.0       # one locality only
    g.note(2, 30.0)
    assert g.remote_penalty() == pytest.approx(2.0)  # (30-10)/10


# ------------------------------------------------------- multi-host -------

def test_normalize_hosts_grammar():
    hosts = fleet_mod.normalize_hosts(
        ["local", "gpu@farm-3", {"name": "b", "locality": "local"},
         {"ssh": "edge-1", "advertise": "10.0.0.7",
          "env": {"X": "1"}, "cwd": "/srv/repo"}])
    local, farm, b, edge = hosts
    assert local == {"name": "local", "ssh": None, "cwd": None,
                     "env": {}, "advertise": "127.0.0.1",
                     "locality": "local"}
    assert farm["ssh"] == "gpu@farm-3" and farm["name"] == "gpu_farm-3"
    assert farm["locality"] == "remote" and farm["advertise"] == "farm-3"
    assert b["ssh"] is None and b["locality"] == "local"
    assert edge["advertise"] == "10.0.0.7" and edge["env"] == {"X": "1"}
    assert edge["cwd"] == "/srv/repo" and edge["locality"] == "remote"


def test_normalize_hosts_rejects_bad_specs():
    with pytest.raises(ValueError, match="expected a name/ssh string"):
        fleet_mod.normalize_hosts([42])
    with pytest.raises(ValueError, match="bad fleet host spec keys"):
        fleet_mod.normalize_hosts([{"hostname": "a"}])
    with pytest.raises(ValueError, match="duplicate fleet host name"):
        fleet_mod.normalize_hosts(["local", {"name": "local"}])
    with pytest.raises(ValueError, match="bad fleet host locality"):
        fleet_mod.normalize_hosts([{"name": "a", "locality": "ici"}])


def test_order_candidates_locality_and_penalty():
    loc = {0: "local", 1: "remote"}
    # an idle remote worker beats a queued local one while the measured
    # penalty is small...
    order = order_candidates("least_loaded", "m", [0, 1],
                             depths={0: 2.0, 1: 0.0}, rr=0,
                             localities=loc, remote_penalty=0.0)
    assert order[0] == 1
    # ...and loses once the remote hop costs more than the queue saves
    order = order_candidates("least_loaded", "m", [0, 1],
                             depths={0: 2.0, 1: 0.0}, rr=0,
                             localities=loc, remote_penalty=3.0)
    assert order[0] == 0
    # round_robin / hash stable-partition local-first
    order = order_candidates("round_robin", "m", [0, 1, 2], rr=0,
                             localities={0: "remote", 1: "local",
                                         2: "local"})
    assert order == [1, 2, 0]


# ------------------------------------------------- deadline / cache -------

def _tiny_server(**kw):
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.gluon import nn

    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, 8)))
    container = serving.ModelContainer()
    container.add_block("m", net, example_shape=(8,), buckets=(2, 4))
    return serving.ModelServer(container, max_wait_ms=1.0, **kw).start()


def test_deadline_drop_before_batch_slot():
    """A provably-unmeetable deadline is dropped with DeadlineExceeded
    BEFORE consuming a batch slot: the batches counter does not move
    for the doomed request and the drop is counted by `where`."""
    from mxnet_tpu import serving

    server = _tiny_server()
    try:
        server.warmup()
        x = np.random.RandomState(0).randn(1, 8).astype(np.float32)
        # seed the batch-execution estimate with real measured batches
        for _ in range(4):
            server.submit("m", x).result(timeout=30.0)
        before = server.stats()["models"]["m"]
        assert before.get("deadline_dropped", {}) == {}
        with pytest.raises(serving.DeadlineExceeded) as ei:
            server.submit("m", x, deadline_ms=1e-4)
        assert ei.value.where == "submit"
        after = server.stats()["models"]["m"]
        assert after["deadline_dropped"] == {"submit": 1}
        assert after["batches"] == before["batches"]  # no slot consumed
        # a meetable deadline sails through and is counted as met
        server.submit("m", x, deadline_ms=30000.0).result(timeout=30.0)
        final = server.stats()["models"]["m"]
        assert final["deadline_met"] == 1
    finally:
        server.drain(timeout=10.0)


def test_prediction_cache_correct_across_version_flip():
    """Cache hits serve the pinned version's answer; a live weight swap
    (the model-bus path) flips the content keys so the next request
    recomputes against the NEW weights — never stale data."""
    server = _tiny_server(cache=True)
    try:
        server.warmup()
        x = np.random.RandomState(1).randn(1, 8).astype(np.float32)
        f1 = server.submit("m", x)
        r1 = np.asarray(f1.result(timeout=30.0)[0])
        assert f1.cache_hit is False
        f2 = server.submit("m", x)
        r2 = np.asarray(f2.result(timeout=30.0)[0])
        assert f2.cache_hit is True and np.allclose(r1, r2)
        # the model-bus version flip: same shapes, new weights
        model = server.container.get("m")
        praws, araws, _v = model.pinned()
        model.swap_params([np.asarray(p) * 1.5 for p in praws],
                          version=7, aux_raws=araws)
        f3 = server.submit("m", x)
        r3 = np.asarray(f3.result(timeout=30.0)[0])
        assert f3.cache_hit is False           # old keys died with v0
        assert not np.allclose(r1, r3)         # computed on new weights
        f4 = server.submit("m", x)
        assert f4.cache_hit is True
        assert np.allclose(r3, np.asarray(f4.result(timeout=30.0)[0]))
    finally:
        server.drain(timeout=10.0)


def test_prediction_cache_unit_lru_and_invalidation():
    from mxnet_tpu.serving import cache as cache_mod

    pc = cache_mod.PredictionCache(capacity=2)
    a = np.zeros((1, 4), np.float32)
    k1 = cache_mod.content_key("m", 1, a)
    assert cache_mod.content_key("m", 2, a) != k1  # version in the key
    assert pc.get(k1) is None
    pc.put(k1, a, version=1)
    hit = pc.get(k1)
    assert hit is not None
    hit[:] = 99.0                                  # copies never alias
    assert float(pc.get(k1)[0, 0]) == 0.0
    # bounded: eldest falls off past capacity
    pc.put("k2", a, version=1)
    pc.put("k3", a, version=1)
    assert len(pc) == 2 and pc.get(k1) is None
    # observe_version on a flip drops the dead generation
    pc.observe_version(1)
    assert len(pc) == 2
    pc.observe_version(2)
    assert len(pc) == 0 and pc.stats()["invalidations"] == 2


# ------------------------------------------------------- live fleet -------

def _predict(client, model, x):
    body = json.dumps({"data": x.tolist()}).encode()
    status, payload, _ = client.request(
        "POST", f"/v1/models/{model}:predict", body=body,
        headers={"Content-Type": "application/json"})
    return status, payload


@pytest.fixture()
def fleet_cleanup():
    fleets = []
    yield fleets
    for fl in fleets:
        try:
            fl.stop(drain=False)
        except Exception:
            pass


def test_fleet_rollout_mid_load_zero_drops_zero_recompiles(
        tmp_path, fleet_cleanup):
    """The acceptance drill: a live 1-worker fleet rolls out a new model
    dir mid-load. Zero dropped admitted requests (no client-visible
    errors; the drained worker answered everything it admitted), the
    old generation exits 75, the new generation serves DIFFERENT
    outputs and compiled NOTHING (its whole ladder loaded from the
    disk cache the first generation wrote)."""
    import loadgen

    v1 = tmp_path / "v1"
    v2 = tmp_path / "v2"
    worker_mod.write_spec(v1, worker_mod.demo_spec(models=1, seed=910,
                                                   buckets=(2, 4)))
    worker_mod.write_spec(v2, worker_mod.demo_spec(models=1, seed=911,
                                                   buckets=(2, 4)))
    fl = ServingFleet(v1, workers=1, run_dir=str(tmp_path / "run"),
                      config={"min": 1, "max": 1, "beat": 0.2,
                              "grace": 20}, name="t-rollout")
    fleet_cleanup.append(fl)
    fl.start(timeout=90)

    stop = threading.Event()
    lock = threading.Lock()
    outs, errors = [], []
    x = np.random.RandomState(1).randn(1, 16).astype(np.float32)

    def load():
        cl = loadgen.KeepAliveClient(fl.url)
        while not stop.is_set():
            try:
                status, payload = _predict(cl, "model0", x)
            except Exception as e:
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                continue
            if status == 200:
                with lock:
                    outs.append(json.loads(payload)["outputs"][0][0][0])
            elif status not in (429, 503):
                with lock:
                    errors.append(f"HTTP {status}")
            time.sleep(0.005)

    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    first = outs[0]

    rec = fl.rollout(v2, timeout=90)
    time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)

    assert not errors, errors[:3]
    assert rec["state"] == "done"
    assert list(rec["drained"].values()) == [75]
    # the drained generation answered every admitted request
    (final,) = rec["old_final"].values()
    assert final["state"] == "drained" and final["failed"] == 0
    assert final["answered"] == final["admitted"] > 0
    # the new generation is serving a DIFFERENT model now
    assert outs and outs[-1] != first
    # zero recompiles: generation 2 warmed entirely from the disk cache
    anns = worker_mod.read_workers(fl.run_dir)
    gen2 = [a for a in anns.values() if a["generation"] == 2]
    assert len(gen2) == 1
    assert gen2[0]["compile_serving"]["compiles"] == 0
    assert gen2[0]["compile_serving"]["disk_hits"] == 2  # both buckets
    # rollout generation is visible in the stats + summary file
    assert fl.generation == 2
    summary = json.loads(
        (tmp_path / "run" / "fleet.json").read_text())
    assert summary["generation"] == 2
    assert summary["rollouts"][-1]["state"] == "done"


def test_fleet_rollout_health_gate_refuses_unwarmed_worker(
        tmp_path, fleet_cleanup):
    """A generation whose workers announce pending compiles (unwarmed
    ladder) must NOT take traffic: the rollout aborts on the gate
    deadline and the old generation keeps serving."""
    import loadgen

    v1 = tmp_path / "v1"
    v2 = tmp_path / "v2"
    worker_mod.write_spec(v1, worker_mod.demo_spec(models=1, seed=920,
                                                   buckets=(2,)))
    worker_mod.write_spec(v2, worker_mod.demo_spec(models=1, seed=921,
                                                   buckets=(2,)))
    fl = ServingFleet(v1, workers=1, run_dir=str(tmp_path / "run"),
                      config={"min": 1, "max": 1, "beat": 0.2},
                      name="t-gate")
    fleet_cleanup.append(fl)
    fl.start(timeout=90)
    # future generations skip warmup -> announce pending compiles
    fl._warmup = False
    with pytest.raises(fleet_mod.FleetError, match="health gate"):
        fl.rollout(v2, timeout=6.0)
    assert fl.generation == 1 and fl.state == "serving"
    assert fl.rollouts[-1]["state"] == "aborted"
    gate = fl.rollouts[-1]["gate_failures"]
    assert any(v.get("pending_compiles") for v in gate.values())
    # the old generation still answers
    cl = loadgen.KeepAliveClient(fl.url)
    x = np.random.RandomState(1).randn(1, 16).astype(np.float32)
    status, _ = _predict(cl, "model0", x)
    assert status == 200


def test_fleet_autoscaler_scales_up_under_load_and_down_on_idle(
        tmp_path, fleet_cleanup, monkeypatch):
    """The live acceptance: injected load grows the fleet 1 -> 2 (the
    decision visible in the autoscale counters / fleet gauges), idling
    shrinks it back to 1 through a deliberate drain — and the diagnose
    'Serving Fleet' report carries the census + last decision."""
    import urllib.request

    import loadgen

    md = tmp_path / "m"
    worker_mod.write_spec(md, worker_mod.demo_spec(models=1, seed=930,
                                                   buckets=(2, 4)))
    fl = ServingFleet(
        md, workers=1, run_dir=str(tmp_path / "run"),
        config={"min": 1, "max": 2, "beat": 0.2, "interval": 0.3,
                "k": 2, "up_p99_ms": 0.05,  # any real traffic = pressure
                "idle_rps": 2.0, "idle_k": 3, "cooldown": 0.5,
                "grace": 20},
        name="t-scale")
    fleet_cleanup.append(fl)
    fl.start(timeout=90)
    assert fl.stats(light=True)["desired"] == 1

    stop = threading.Event()

    def load():
        cl = loadgen.KeepAliveClient(fl.url)
        x = np.random.RandomState(2).randn(1, 16).astype(np.float32)
        while not stop.is_set():
            try:
                _predict(cl, "model0", x)
            except Exception:
                time.sleep(0.01)

    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if fl.stats(light=True)["desired"] == 2:
            break
        time.sleep(0.2)
    else:
        raise AssertionError(
            f"never scaled up: {fl.stats()['autoscaler']}")
    assert fl._scaler.decisions["up"] >= 1
    up = fl._scaler.last_action
    assert up["direction"] == "up" and "p99" in up["reason"]

    # the decision is visible on the router's /metrics scrape
    text = urllib.request.urlopen(fl.url + "/metrics",
                                  timeout=10).read().decode()
    assert 'mxtpu_fleet_autoscale_total{direction="up"} 1' in text
    assert "mxtpu_fleet_workers_desired 2" in text

    # idle: load off -> completion rate collapses -> scale back down
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if fl.stats(light=True)["desired"] == 1:
            break
        time.sleep(0.2)
    else:
        raise AssertionError(
            f"never scaled down: {fl.stats()['autoscaler']}")
    assert fl._scaler.decisions["down"] >= 1
    # the drained slot retired through the deliberate-drain path
    deadline = time.monotonic() + 30.0
    while fl._sup.drained_total < 1 and time.monotonic() < deadline:
        time.sleep(0.1)
    assert fl._sup.drained_total >= 1

    # diagnose: the Serving Fleet section reports census + decisions
    import diagnose

    monkeypatch.setenv("MXTPU_FLEET_DIR", str(tmp_path / "run"))
    out = diagnose.check_fleet()
    assert out["summary"]["autoscaler"]["decisions"]["up"] >= 1
    assert out["summary"]["autoscaler"]["decisions"]["down"] >= 1
    assert out["summary"]["generation"] == 1
    assert out["summary"]["workers"]


def test_fleet_two_host_placement_and_merged_scrape(
        tmp_path, fleet_cleanup):
    """Multi-host live: two localhost pseudo-hosts under one fleet —
    slots place round-robin across them, each host gets its own run dir
    (host-<name>/) for announces + telemetry shards, and read_workers /
    worker_metrics merge the per-host shards into one fleet view the
    router serves traffic from."""
    import loadgen

    v1 = tmp_path / "v1"
    worker_mod.write_spec(v1, worker_mod.demo_spec(models=1, seed=920,
                                                   buckets=(2, 4)))
    fl = ServingFleet(
        str(v1), workers=2, run_dir=str(tmp_path / "run"),
        hosts=["local", {"name": "b", "locality": "local"}],
        config={"min": 2, "max": 2, "beat": 0.2, "grace": 20},
        name="twohost")
    fleet_cleanup.append(fl)
    fl.start(timeout=120)
    # placement: hosts[slot % 2] — slot 0 on "local", slot 1 on "b"
    st = fl.stats()
    assert {s: w["host"] for s, w in st["workers"].items()} == \
        {"0": "local", "1": "b"}
    assert all(w["locality"] == "local" for w in st["workers"].values())
    assert {h["name"]: h["slots"] for h in st["hosts"]} == \
        {"local": [0], "b": [1]}
    # per-host run dirs own the announces; the scrape merges them
    assert (tmp_path / "run" / "host-local" / "worker-0.json").exists()
    assert (tmp_path / "run" / "host-b" / "worker-1.json").exists()
    assert sorted(worker_mod.read_workers(fl.run_dir)) == [0, 1]
    # traffic flows through both placements
    cl = loadgen.KeepAliveClient(fl.url)
    x = np.random.RandomState(0).randn(1, 16).astype(np.float32)
    for _ in range(30):
        status, _ = _predict(cl, "model0", x)
        assert status == 200
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        m = worker_metrics(fl.run_dir)
        if sorted(m) == [0, 1] and all(
                (m[s].get("rps") or 0) >= 0 for s in m):
            break
        time.sleep(0.2)
    assert sorted(worker_metrics(fl.run_dir)) == [0, 1]


# ----------------------------------------------------------- loadgen ------

def test_loadgen_keepalive_reuses_connections():
    """--via-http now drives persistent connections: one connect per
    worker thread (not per request), connect time reported separately."""
    import loadgen

    rep = loadgen.run_inproc(duration=1.5, mode="closed", concurrency=4,
                             models=1, via_http=True)
    assert rep["errors"] == 0, rep["first_errors"]
    assert rep["completed"] > rep["connects"]
    # keep-alive: connects == threads (reconnects only on failure)
    assert rep["connects"] <= 4 + rep["reconnects"]
    assert rep["connect_ms_mean"] is not None
    assert rep["connect_ms_total"] < 1000.0


def test_loadgen_fleet_mode_short(tmp_path):
    """--workers N end to end: an N-worker fleet driven through the
    router, report carrying router counters + per-worker census."""
    import loadgen

    rep = loadgen.run_fleet(workers=1, duration=1.5, concurrency=4,
                            models=1, run_dir=str(tmp_path))
    assert rep["harness"] == "loadgen-fleet" and rep["workers"] == 1
    assert rep["errors"] == 0, rep["first_errors"]
    assert rep["completed"] > 0 and rep["rps"] > 0
    assert rep["router"]["completed"] >= rep["completed"]
    assert rep["per_worker"] and rep["connects"] >= 4
