"""int8 quantization tests (parity model:
tests/python/quantization/test_quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def _conv_fc_sym():
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                              name="conv1")
    act = mx.sym.Activation(conv, act_type="relu")
    pool = mx.sym.Pooling(act, kernel=(2, 2), stride=(2, 2), pool_type="max")
    return mx.sym.FullyConnected(pool, num_hidden=10, name="fc1")


def _init_args(sym, data_shape):
    arg_shapes, _, _ = sym.infer_shape(data=data_shape)
    rng = np.random.RandomState(0)
    return {n: mx.nd.array(rng.randn(*s).astype(np.float32) * 0.1)
            for n, s in zip(sym.list_arguments(), arg_shapes) if n != "data"}


def test_quantize_dequantize_roundtrip():
    x = mx.nd.array(np.linspace(-3, 3, 101, dtype=np.float32))
    qv, mn, mxr = mx.nd.invoke("_contrib_quantize_v2", x)
    assert np.dtype(qv.dtype).name == "int8"
    back = mx.nd.invoke("_contrib_dequantize", qv, mn, mxr)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=3 / 127.0)


def test_quantize_v2_calibrated_range():
    x = mx.nd.array(np.array([-1.0, 0.0, 5.0], np.float32))
    qv, mn, mxr = mx.nd.invoke("_contrib_quantize_v2", x,
                               min_calib_range=-2.0, max_calib_range=2.0)
    assert float(mn.asscalar()) == -2.0
    assert int(qv.asnumpy()[2]) == 127  # clipped at the calibrated max


def test_quantized_fc_matches_fp32():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 16).astype(np.float32)
    w = (rng.randn(8, 16) * 0.1).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    ref = x @ w.T + b
    absmax = np.abs(w).max(axis=1)
    scale = absmax / 127.0
    qw = np.clip(np.round(w / scale[:, None]), -127, 127).astype(np.int8)
    out = mx.nd.invoke(
        "_contrib_quantized_fully_connected", mx.nd.array(x),
        mx.nd.array(qw, dtype="int8"), mx.nd.array(scale), mx.nd.array(b),
        num_hidden=8, min_calib_range=float(x.min()),
        max_calib_range=float(x.max()))
    rel = np.abs(out.asnumpy() - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_quantize_model_symbol_path():
    sym = _conv_fc_sym()
    args = _init_args(sym, (4, 3, 8, 8))
    X = np.random.RandomState(2).randn(64, 3, 8, 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, batch_size=16, label_name=None)
    qsym, qargs, auxs = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        num_calib_examples=64)
    assert "conv1_weight_quantize" in qargs
    assert "fc1_weight_scale" in qargs
    assert np.dtype(qargs["conv1_weight_quantize"].dtype).name == "int8"
    x = mx.nd.array(X[:4])
    ref = sym.eval_with({"data": x, **args}).asnumpy()
    out = qsym.eval_with({"data": x, **qargs}).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_model_excluded_layer():
    sym = _conv_fc_sym()
    args = _init_args(sym, (4, 3, 8, 8))
    X = np.random.RandomState(3).randn(32, 3, 8, 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, batch_size=16, label_name=None)
    qsym, qargs, _ = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        excluded_sym_names=["fc1"])
    assert "conv1_weight_quantize" in qargs
    assert "fc1_weight" in qargs and "fc1_weight_quantize" not in qargs


def test_quantize_net_gluon_path():
    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(64, 3, 8, 8).astype(np.float32)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"), nn.Flatten(),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    qblock = q.quantize_net(net, X[:32])
    x = mx.nd.array(X[:4])
    ref = net(x).asnumpy()
    out = qblock(x).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_model_requires_calib():
    sym = _conv_fc_sym()
    with pytest.raises(ValueError):
        q.quantize_model(sym, {}, {}, calib_data=None)
