"""int8 quantization tests (parity model:
tests/python/quantization/test_quantization.py) — plus the PR-14
surface: the true KL entropy calibration, per-channel/per-tensor
granularity, the quantized-embedding pass, ONNX QLinear round trips and
the int8 serving ladder (dtype reporting + disk-cache warm start)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def _conv_fc_sym():
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                              name="conv1")
    act = mx.sym.Activation(conv, act_type="relu")
    pool = mx.sym.Pooling(act, kernel=(2, 2), stride=(2, 2), pool_type="max")
    return mx.sym.FullyConnected(pool, num_hidden=10, name="fc1")


def _init_args(sym, data_shape):
    arg_shapes, _, _ = sym.infer_shape(data=data_shape)
    rng = np.random.RandomState(0)
    return {n: mx.nd.array(rng.randn(*s).astype(np.float32) * 0.1)
            for n, s in zip(sym.list_arguments(), arg_shapes) if n != "data"}


def test_quantize_dequantize_roundtrip():
    x = mx.nd.array(np.linspace(-3, 3, 101, dtype=np.float32))
    qv, mn, mxr = mx.nd.invoke("_contrib_quantize_v2", x)
    assert np.dtype(qv.dtype).name == "int8"
    back = mx.nd.invoke("_contrib_dequantize", qv, mn, mxr)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=3 / 127.0)


def test_quantize_v2_calibrated_range():
    x = mx.nd.array(np.array([-1.0, 0.0, 5.0], np.float32))
    qv, mn, mxr = mx.nd.invoke("_contrib_quantize_v2", x,
                               min_calib_range=-2.0, max_calib_range=2.0)
    assert float(mn.asscalar()) == -2.0
    assert int(qv.asnumpy()[2]) == 127  # clipped at the calibrated max


def test_quantized_fc_matches_fp32():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 16).astype(np.float32)
    w = (rng.randn(8, 16) * 0.1).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    ref = x @ w.T + b
    absmax = np.abs(w).max(axis=1)
    scale = absmax / 127.0
    qw = np.clip(np.round(w / scale[:, None]), -127, 127).astype(np.int8)
    out = mx.nd.invoke(
        "_contrib_quantized_fully_connected", mx.nd.array(x),
        mx.nd.array(qw, dtype="int8"), mx.nd.array(scale), mx.nd.array(b),
        num_hidden=8, min_calib_range=float(x.min()),
        max_calib_range=float(x.max()))
    rel = np.abs(out.asnumpy() - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_quantize_model_symbol_path():
    sym = _conv_fc_sym()
    args = _init_args(sym, (4, 3, 8, 8))
    X = np.random.RandomState(2).randn(64, 3, 8, 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, batch_size=16, label_name=None)
    qsym, qargs, auxs = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        num_calib_examples=64)
    assert "conv1_weight_quantize" in qargs
    assert "fc1_weight_scale" in qargs
    assert np.dtype(qargs["conv1_weight_quantize"].dtype).name == "int8"
    x = mx.nd.array(X[:4])
    ref = sym.eval_with({"data": x, **args}).asnumpy()
    out = qsym.eval_with({"data": x, **qargs}).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_model_excluded_layer():
    sym = _conv_fc_sym()
    args = _init_args(sym, (4, 3, 8, 8))
    X = np.random.RandomState(3).randn(32, 3, 8, 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, batch_size=16, label_name=None)
    qsym, qargs, _ = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        excluded_sym_names=["fc1"])
    assert "conv1_weight_quantize" in qargs
    assert "fc1_weight" in qargs and "fc1_weight_quantize" not in qargs


def test_quantize_net_gluon_path():
    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(64, 3, 8, 8).astype(np.float32)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"), nn.Flatten(),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    qblock = q.quantize_net(net, X[:32])
    x = mx.nd.array(X[:4])
    ref = net(x).asnumpy()
    out = qblock(x).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_model_requires_calib():
    sym = _conv_fc_sym()
    with pytest.raises(ValueError):
        q.quantize_model(sym, {}, {}, calib_data=None)


def test_quantize_model_rejects_unknown_mode():
    sym = _conv_fc_sym()
    X = np.zeros((8, 3, 8, 8), np.float32)
    it = mx.io.NDArrayIter(X, batch_size=8, label_name=None)
    with pytest.raises(ValueError):
        q.quantize_model(sym, {}, {}, calib_data=it, calib_mode="kl")
    with pytest.raises(ValueError):
        q.quantize_graph(sym, quantize_granularity="rowwise")


# ------------------------------------------------------- KL threshold ---

def test_kl_threshold_synthetic_outliers():
    """Pure-numpy KL search on a known distribution: nearly all mass is
    gaussian; a few far outliers must be clipped, not absorbed."""
    rng = np.random.RandomState(0)
    a = np.concatenate([rng.randn(200_000),
                        np.asarray([40.0, -42.0, 38.0])])
    hist, edges = np.histogram(a, bins=2048, range=(-42.0, 42.0))
    th, kl = q.kl_optimal_threshold(hist, edges)
    # the optimal threshold ignores the 3/200k outlier tail: it must sit
    # far inside the observed range yet cover the gaussian bulk
    assert 2.0 < th < 21.0, th
    assert kl >= 0.0
    # deterministic: same histogram -> bit-identical result
    assert q.kl_optimal_threshold(hist, edges) == (th, kl)
    # threshold is a bin edge of the folded |x| histogram
    abs_edges = edges[len(hist) // 2:]
    assert np.isclose(abs_edges, th).any()


def test_kl_threshold_uniform_keeps_range():
    """With no outlier tail (uniform mass), clipping only loses mass:
    the search must keep (nearly) the full range."""
    rng = np.random.RandomState(1)
    u = rng.uniform(-3, 3, 100_000)
    hist, edges = np.histogram(u, bins=2048, range=(-3.0, 3.0))
    th, _ = q.kl_optimal_threshold(hist, edges)
    assert th >= 2.9, th


def test_kl_threshold_rejects_odd_bins():
    with pytest.raises(ValueError):
        q.kl_optimal_threshold(np.ones(5), np.linspace(-1, 1, 6))


def test_entropy_calibration_deterministic():
    """The whole entropy calibration (histogram accumulation + KL
    search) is pure numpy: two runs over the same data produce
    bit-identical thresholds."""
    sym = _conv_fc_sym()
    args = _init_args(sym, (4, 3, 8, 8))
    X = np.random.RandomState(7).randn(64, 3, 8, 8).astype(np.float32)
    records = []
    for _ in range(2):
        it = mx.io.NDArrayIter(X, batch_size=16, label_name=None)
        q.quantize_model(sym, args, {}, data_names=("data",),
                         calib_data=it, calib_mode="entropy")
        records.append(q.last_calibration())
    assert records[0]["mode"] == "entropy"
    assert records[0]["tensors"] == records[1]["tensors"]
    assert all("threshold" in rec
               for rec in records[0]["tensors"].values())


def _deep_conv_sym():
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    return mx.sym.FullyConnected(net, num_hidden=10, name="fc")


def test_accuracy_delta_entropy_vs_naive_vs_percentile():
    """The satellite acceptance: on a seeded calib set with heavy-tailed
    activations, the true KL entropy mode holds top-1 against fp32
    (bounded drop) and beats the naive min/max calibration that the
    outliers poison; percentile rides along as the A/B."""
    sym = _deep_conv_sym()
    rng = np.random.RandomState(0)
    arg_shapes, _, _ = sym.infer_shape(data=(4, 3, 8, 8))
    args = {n: mx.nd.array((rng.randn(*s) * 0.2).astype(np.float32))
            for n, s in zip(sym.list_arguments(), arg_shapes)
            if n != "data"}
    calib = rng.randn(256, 3, 8, 8).astype(np.float32)
    calib[rng.choice(256, 6, replace=False)] *= 30.0  # outlier batches
    eval_x = rng.randn(256, 3, 8, 8).astype(np.float32)
    ref_top1 = sym.eval_with(
        {"data": mx.nd.array(eval_x), **args}).asnumpy().argmax(1)
    agree = {}
    for mode in ("naive", "percentile", "entropy"):
        it = mx.io.NDArrayIter(calib, batch_size=32, label_name=None)
        qs, qa, _ = q.quantize_model(sym, args, {}, data_names=("data",),
                                     calib_data=it, calib_mode=mode)
        out = qs.eval_with({"data": mx.nd.array(eval_x), **qa}).asnumpy()
        agree[mode] = float((out.argmax(1) == ref_top1).mean())
    # entropy: bounded top-1 drop vs fp32, and strictly better than the
    # outlier-poisoned naive range (measured ~0.91 vs ~0.70 vs ~0.77)
    assert agree["entropy"] >= 0.85, agree
    assert agree["entropy"] >= agree["naive"] + 0.05, agree
    assert agree["entropy"] >= agree["percentile"], agree


# ------------------------------------------------------- granularity ---

def test_granularity_channel_vs_tensor():
    """Per-channel scales track per-channel weight magnitude spread;
    tensor-wise collapses to one scalar scale (the A/B) and loses
    accuracy on spread weights."""
    rng = np.random.RandomState(3)
    x = rng.randn(8, 16).astype(np.float32)
    w = (rng.randn(8, 16) * 0.1).astype(np.float32)
    w *= (0.05 * (np.arange(8) + 1))[:, None]  # per-channel spread
    data = mx.sym.var("data")
    sym = mx.sym.FullyConnected(data, num_hidden=8, no_bias=True,
                                name="fc1")
    args = {"fc1_weight": mx.nd.array(w)}
    ref = x @ w.T
    outs = {}
    for gran in ("channel-wise", "tensor-wise"):
        it = mx.io.NDArrayIter(x, batch_size=8, label_name=None)
        qs, qa, _ = q.quantize_model(
            sym, args, {}, data_names=("data",), calib_data=it,
            quantize_granularity=gran)
        expect = (8,) if gran == "channel-wise" else (1,)
        assert qa["fc1_weight_scale"].shape == expect
        outs[gran] = qs.eval_with(
            {"data": mx.nd.array(x), **qa}).asnumpy()
    err_c = np.abs(outs["channel-wise"] - ref).max()
    err_t = np.abs(outs["tensor-wise"] - ref).max()
    assert err_c < err_t, (err_c, err_t)
    assert err_c / np.abs(ref).max() < 0.05
    assert q.last_quantization()["granularity"] == "tensor-wise"


# ---------------------------------------------------------- embedding ---

def _embedding_sym(vocab=500, dim=16):
    ids = mx.sym.var("data")
    emb = mx.sym.Embedding(ids, input_dim=vocab, output_dim=dim,
                           name="embed")
    pooled = mx.sym.mean(emb, axis=1)
    return mx.sym.FullyConnected(pooled, num_hidden=4, name="out")


def _embedding_args(rng, vocab=500, dim=16):
    return {"embed_weight": mx.nd.array(
                (rng.randn(vocab, dim) * 0.1).astype(np.float32)),
            "out_weight": mx.nd.array(
                (rng.randn(4, dim) * 0.1).astype(np.float32)),
            "out_bias": mx.nd.array(np.zeros(4, np.float32))}


def test_quantized_embedding_pass():
    """Embedding weights quantize per-tensor into an int8 table gather +
    dequantize (the bandwidth-bound serving win); numerics stay close
    to fp32 and the census records the 'embedding' kind."""
    rng = np.random.RandomState(5)
    sym = _embedding_sym()
    args = _embedding_args(rng)
    ids = rng.randint(0, 500, (32, 12)).astype(np.float32)
    it = mx.io.NDArrayIter(ids, batch_size=16, label_name=None)
    qsym, qargs, _ = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        calib_mode="entropy")
    assert np.dtype(qargs["embed_weight_quantize"].dtype).name == "int8"
    assert "embed_weight_min" in qargs and "embed_weight_max" in qargs
    x = mx.nd.array(ids[:4])
    ref = sym.eval_with({"data": x, **args}).asnumpy()
    out = qsym.eval_with({"data": x, **qargs}).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel
    census = q.last_quantization()
    assert census["weights"]["embed_weight"] == "embedding"
    assert census["ops"]["_contrib_quantized_embedding"] == 1


# -------------------------------------------------------- ONNX export ---

def _onnx_ops(path):
    from mxnet_tpu.onnx import proto

    with open(path, "rb") as f:
        m = proto.parse_model(f.read())
    return {n["op_type"] for n in m["graph"]["nodes"]}, m


def test_onnx_quantized_roundtrip(tmp_path):
    """A calibrated quantized graph exports in the ONNX QLinear form
    (QuantizeLinear / QLinearConv / QLinearMatMul / DequantizeLinear,
    opset >= 13) and re-imports numerically identical."""
    from mxnet_tpu.onnx import mx2onnx, onnx2mx

    sym = _conv_fc_sym()
    args = _init_args(sym, (4, 3, 8, 8))
    X = np.random.RandomState(2).randn(64, 3, 8, 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, batch_size=16, label_name=None)
    qsym, qargs, _ = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        calib_mode="entropy")
    path = mx2onnx.export_model(qsym, qargs, in_shapes=[(4, 3, 8, 8)],
                                onnx_file_path=str(tmp_path / "q.onnx"))
    ops, model = _onnx_ops(path)
    assert {"QuantizeLinear", "QLinearConv", "QLinearMatMul",
            "DequantizeLinear"} <= ops
    assert model["opset"] >= 13
    isym, iargs, _ = onnx2mx.import_model(path)
    x = mx.nd.array(X[:4])
    ref = qsym.eval_with({"data": x, **qargs}).asnumpy()
    out = isym.eval_with({"data": x, **iargs}).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_onnx_quantized_embedding_roundtrip(tmp_path):
    """The int8 embedding-table graph round-trips: Gather over the int8
    initializer + DequantizeLinear with the table's constant scale."""
    from mxnet_tpu.onnx import mx2onnx, onnx2mx

    rng = np.random.RandomState(11)
    sym = _embedding_sym()
    args = _embedding_args(rng)
    ids = rng.randint(0, 500, (32, 12)).astype(np.float32)
    it = mx.io.NDArrayIter(ids, batch_size=16, label_name=None)
    qsym, qargs, _ = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        calib_mode="entropy")
    path = mx2onnx.export_model(qsym, qargs, in_shapes=[(4, 12)],
                                onnx_file_path=str(tmp_path / "qe.onnx"))
    ops, _ = _onnx_ops(path)
    assert {"Gather", "DequantizeLinear", "QuantizeLinear",
            "QLinearMatMul"} <= ops
    isym, iargs, _ = onnx2mx.import_model(path)
    x = mx.nd.array(ids[:4])
    ref = qsym.eval_with({"data": x, **qargs}).asnumpy()
    out = isym.eval_with({"data": x, **iargs}).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------ int8 serving ---

def test_served_int8_model_reports_dtype():
    """A quantized symbol/params pair loads through the standard serving
    loaders, is detected as int8 (weight_dtype in stats(), model_info,
    the /v1/models detail) and predicts exactly what direct graph eval
    produces."""
    from mxnet_tpu import serving

    rng = np.random.RandomState(9)
    sym = _conv_fc_sym()
    args = _init_args(sym, (4, 3, 8, 8))
    X = rng.randn(64, 3, 8, 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, batch_size=16, label_name=None)
    qsym, qargs, _ = q.quantize_model(
        sym, args, {}, data_names=("data",), calib_data=it,
        calib_mode="entropy")
    container = serving.ModelContainer()
    container.add_symbol("qmodel", qsym, qargs,
                         example_shape=(3, 8, 8), buckets=(2, 4))
    fmodel = container.add_symbol("fmodel", sym, args,
                                  example_shape=(3, 8, 8), buckets=(2, 4))
    assert container.get("qmodel").weight_dtype == "int8"
    assert container.get("qmodel").quantized
    assert fmodel.weight_dtype == "float32" and not fmodel.quantized
    server = serving.ModelServer(container, max_wait_ms=1.0).start()
    try:
        server.warmup()
        info = server.model_info()
        assert info["qmodel"]["weight_dtype"] == "int8"
        assert info["qmodel"]["quantized"] is True
        assert info["fmodel"]["weight_dtype"] == "float32"
        x = X[:2]
        got = server.predict("qmodel", x, timeout=30.0)
        ref = qsym.eval_with({"data": mx.nd.array(x), **qargs}).asnumpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        stats = server.stats()["models"]["qmodel"]
        assert stats["weight_dtype"] == "int8"
        assert stats["dtype"] == "float32"
    finally:
        server.drain(timeout=10.0)


def test_int8_ladder_warms_from_disk_cache(tmp_path):
    """The acceptance census: a warm subprocess serves the whole int8
    bucket ladder with ZERO compiles — every executable loads from the
    persistent disk cache — and traffic itself never recompiles."""
    import json
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MXNET_TPU_CACHE_DIR"] = str(tmp_path / "cache")
    env.pop("MXNET_TPU_FAULTS", None)
    child = os.path.join(os.path.dirname(__file__), "_quant_child.py")
    reports = []
    for _ in range(2):
        proc = subprocess.run([_sys.executable, child],
                              capture_output=True, text=True,
                              timeout=420, env=env)
        lines = [l for l in proc.stdout.splitlines()
                 if l.startswith("QCHILD ")]
        assert proc.returncode == 0 and lines, \
            f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr[-2000:]}"
        reports.append(json.loads(lines[-1].split(" ", 1)[1]))
    cold, warm = reports
    assert cold["weight_dtype"] == "int8"
    assert cold["misses"] == len(cold["buckets"])  # one per bucket
    # warm pod: the whole int8 ladder came off disk, nothing compiled
    assert warm["misses"] == 0, warm
    assert warm["disk_hits"] >= len(warm["buckets"]), warm
    assert warm["recompiles_during_traffic"] == 0, warm
    # traffic covered every ladder bucket in both runs
    for rep in reports:
        assert sorted(int(b) for b in rep["bucket_census"]) == \
            sorted(rep["buckets"]), rep
