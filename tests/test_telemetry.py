"""Unified telemetry layer (mxnet_tpu/telemetry/, docs/OBSERVABILITY.md).

Headline guarantees under test:

* the metrics registry renders valid Prometheus text with bounded label
  cardinality, and a ``/metrics`` scrape on a live serving front end
  carries serving (rps/p99/queue depth), compile (hits/misses/
  compile_ms), watchdog (stalls) and memory (live/peak bytes) series
  whose values AGREE with ``serving.stats()`` / ``compile.stats()``;
* the flight recorder is always-on, constant-size, and its tail is
  embedded in every watchdog crash bundle (``flight.json``) and every
  preemption drain event (``flight_tail``) — an injected hang's bundle
  names the wedged point and carries the preceding step events;
* the compile service captures XLA ``cost_analysis``/``memory_analysis``
  per executable, from which ``ShardedTrainer.step_report()`` and
  ``bench.py`` derive ``mfu_xla`` and the per-step phase breakdown;
* trace integrity: a full ``profiler.dump()`` of a bulked + compile +
  serving run is a valid Chrome-trace envelope with monotone-timestamped
  counter tracks;
* the overhead contract: telemetry-enabled ``opperf --dispatch`` stays
  within noise of disabled (perf-marked A/B gate, like the compile
  service's).
"""
import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile as C
from mxnet_tpu import faults, gluon, serving, telemetry, watchdog
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer
from mxnet_tpu.telemetry import costs, flight, memory, registry, steps

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_trainer(seed=0, dim=8, nan_guard=True):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(seed).randn(8, dim)
                    .astype(np.float32))
    y = mx.nd.array(np.random.RandomState(seed + 1).randn(8, 4)
                    .astype(np.float32))
    net(x)
    trainer = ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                             {"learning_rate": 0.01},
                             mesh=DeviceMesh({"dp": 1}),
                             nan_guard=nan_guard)
    return trainer, x, y


# ---------------------------------------------------------------- registry --

def test_registry_counter_gauge_histogram_render():
    c = registry.counter("mxtpu_t_reg_total", "a counter",
                         labels=("site",))
    c.inc(2, "a")
    c.inc(1, "a")
    c.inc(5, "b")
    g = registry.gauge("mxtpu_t_reg_gauge", "a gauge")
    g.set(2.5)
    h = registry.histogram("mxtpu_t_reg_hist", "a histogram")
    h.observe(3.0)
    h.observe(700.0)
    text = registry.render_prometheus()
    assert '# TYPE mxtpu_t_reg_total counter' in text
    assert 'mxtpu_t_reg_total{site="a"} 3' in text
    assert 'mxtpu_t_reg_total{site="b"} 5' in text
    assert 'mxtpu_t_reg_gauge 2.5' in text
    assert 'mxtpu_t_reg_hist_bucket{le="5"} 1' in text
    assert 'mxtpu_t_reg_hist_bucket{le="+Inf"} 2' in text
    assert 'mxtpu_t_reg_hist_count 2' in text
    # every non-comment line is "name{labels} value" — parseable
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, _, value = line.rpartition(" ")
        assert name and (value == "+Inf" or float(value) is not None)


def test_registry_label_cardinality_bounded():
    c = registry.counter("mxtpu_t_card_total", "bounded", labels=("k",))
    for i in range(registry.MAX_SERIES + 50):
        c.inc(1, f"v{i}")
    series = c.series()
    assert len(series) <= registry.MAX_SERIES + 1
    assert ("__other__",) in series and series[("__other__",)] >= 50


def test_registry_kind_mismatch_rejected():
    registry.counter("mxtpu_t_kind_total", "x")
    with pytest.raises(ValueError):
        registry.gauge("mxtpu_t_kind_total", "x")


# ------------------------------------------------------------------ flight --

def test_flight_ring_constant_size_and_order():
    flight.clear()
    n = flight.size()
    assert n > 0
    for i in range(n + 100):
        flight.rec("t.ring", "p", i)
    tail = flight.tail()
    assert len(tail) == n  # constant memory: never grows past the ring
    seqs = [e["seq"] for e in tail]
    assert seqs == sorted(seqs)
    assert tail[-1]["label"] == n + 99  # newest survives a full lap
    assert flight.counts()["t.ring"] == n + 100
    assert len(flight.tail(5)) == 5
    flight.clear()


def test_flight_disabled_is_noop():
    flight.clear()
    prev = telemetry.set_enabled(False)
    try:
        flight.rec("t.off", "p")
        assert flight.tail() == []
    finally:
        telemetry.set_enabled(prev)
    flight.clear()


# ----------------------------------------------------------- cost / peaks ---

def test_peak_table_per_device_kind():
    assert costs.nominal_peak_tflops("TPU v5p chip") == 459.0
    assert costs.nominal_peak_tflops("TPU v5e") == 197.0
    assert costs.nominal_peak_tflops("TPU v5 lite") == 197.0
    assert costs.nominal_peak_tflops("TPU v6e") == 918.0
    assert costs.nominal_peak_tflops("TPU v4") == 275.0
    assert costs.nominal_peak_tflops("cpu") == costs.CPU_FALLBACK_TFLOPS
    assert costs.nominal_peak_tflops("unknown accelerator") == 459.0


def test_peak_env_override(monkeypatch):
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "123.5")
    assert costs.peak_tflops(env="BENCH_PEAK_TFLOPS") == 123.5
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "0")  # 0 = auto-detect
    assert costs.peak_tflops(env="BENCH_PEAK_TFLOPS") \
        == costs.nominal_peak_tflops()


def test_mfu_xla_arithmetic():
    # 1 TFLOP/step at 100 steps/s on a 200-TFLOPS part = 0.5 MFU
    assert costs.mfu_xla(1e12, 100.0, devices=1, peak=200.0) \
        == pytest.approx(0.5)
    assert costs.mfu_xla(1e12, 100.0, devices=2, peak=200.0) \
        == pytest.approx(0.25)
    assert costs.mfu_xla(None, 100.0) is None
    assert costs.mfu_xla(1e12, 0.0) is None


def test_trainer_cost_capture_and_step_report():
    trainer, x, y = small_trainer(seed=3)
    for _ in range(3):
        trainer.step(x, y)
    rep = trainer.step_report()
    assert rep is not None and rep["step"] >= 3
    phases = rep["phases"]
    for key in ("data_wait", "h2d", "compute", "optimizer", "sync"):
        assert key in phases
    assert phases["h2d"] >= 0 and phases["compute"] > 0
    # the compile service captured cost_analysis for the step executable
    assert rep.get("flops", 0) > 0
    assert 0 <= rep["mfu_xla"] < 1.0
    token = trainer._step_fn._token_key
    assert costs.flops_for(token) == rep["flops"]
    # and the step gauges flow into the registry
    snap = telemetry.metrics_snapshot()
    assert snap["mxtpu_step_time_ms"]["series"][0]["value"] > 0
    assert any(s["labels"]["phase"] == "compute"
               for s in snap["mxtpu_step_phase_ms"]["series"])


def test_step_abort_on_injected_fault():
    # earlier tests may have filled the 256-record ring to its cap, where
    # "len grows by one" can never hold — start from a known-empty ring
    # (regression guard for the full-suite order dependency)
    steps.reset()
    trainer, x, y = small_trainer(seed=4)
    trainer.step(x, y)
    before = len(steps.history())
    faults.configure("trainer.step:raise@1", seed=0)
    try:
        with pytest.raises(faults.InjectedFault):
            trainer.step(x, y)
    finally:
        faults.reset()
    # the raising step abandoned its record instead of logging a torn one
    assert len(steps.history()) == before
    trainer.step(x, y)
    assert len(steps.history()) == before + 1


def test_step_history_semantics_at_ring_cap():
    """The abandoned-record contract must hold even when the history ring
    is already at its maxlen cap — the exact state the full suite leaves
    behind (the pre-fix flake: len(history()) can't grow at the cap, so
    assertions must key on record identity, not length)."""
    steps.reset()
    trainer, x, y = small_trainer(seed=4)
    trainer.step(x, y)
    template = steps.last()
    cap = steps._HIST.maxlen
    while len(steps._HIST) < cap:
        steps._HIST.append(dict(template, step=len(steps._HIST)))
    last_before = steps.last()
    faults.configure("trainer.step:raise@1", seed=0)
    try:
        with pytest.raises(faults.InjectedFault):
            trainer.step(x, y)
    finally:
        faults.reset()
    # aborted step left no record: the newest entry is unchanged
    assert steps.last() == last_before
    trainer.step(x, y)
    assert len(steps.history()) == cap  # ring stays at cap...
    assert steps.last() != last_before  # ...but the new record landed
    steps.reset()


def test_memory_sample_and_oom_report(tmp_path, monkeypatch):
    recs = memory.sample(reason="test")
    assert recs, "memory sample must produce at least a host record"
    for r in recs:
        assert r["live_bytes"] >= 0 and r["peak_bytes"] >= r["live_bytes"]
    # with a cache dir the trainer compiles AOT -> memory_analysis lands
    d = str(tmp_path / "cache")
    monkeypatch.setenv("MXNET_TPU_CACHE_DIR", d)
    C.configure(cache_dir=d)
    try:
        trainer, x, y = small_trainer(seed=11)
        trainer.step(x, y)
        top = memory.top_executables(5)
        assert top and top[0]["resident_bytes"] > 0
        assert any(r["site"] == "trainer" for r in top)
        rep = memory.oom_report()
        assert rep["top_executables"] and rep["devices"] is not None
        assert "trainer" in rep["aggregate"]
    finally:
        C.configure(cache_dir=None)


# ------------------------------------------------------- /metrics endpoint --

def _scrape(url, path="/metrics"):
    with urllib.request.urlopen(url + path, timeout=10) as resp:
        return resp.read().decode(), resp.headers.get("Content-Type")


def _metric_value(text, name, **labels):
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        if line.startswith(name + "{") or line.startswith(name + " "):
            if all(f'{k}="{v}"' in line for k, v in labels.items()):
                return float(line.rsplit(" ", 1)[1])
    return None


def test_http_metrics_agree_with_stats():
    """Acceptance: curl /metrics on a running ModelServer returns
    Prometheus text with serving, compile, watchdog and memory series
    whose values agree with serving.stats()/compile.stats()."""
    mx.random.seed(21)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, 6)))
    container = serving.ModelContainer()
    container.add_block("tel_model", net, example_shape=(6,),
                        buckets=(2, 4))
    server = serving.ModelServer(container, max_wait_ms=1.0).start()
    try:
        server.warmup()
        front = serving.HttpFrontEnd(server).start()
        try:
            rows = np.random.RandomState(0).randn(1, 6).astype(np.float32)
            for _ in range(12):
                server.predict("tel_model", rows, timeout=10.0)
            text, ctype = _scrape(front.url)
            assert ctype.startswith("text/plain")
            st = server.stats()["models"]["tel_model"]
            # serving series agree with server.stats()
            assert _metric_value(text, "mxtpu_serving_requests_total",
                                 model="tel_model",
                                 outcome="completed") == st["completed"]
            assert _metric_value(text, "mxtpu_serving_queue_depth",
                                 model="tel_model") == st["queue_depth"]
            assert _metric_value(text, "mxtpu_serving_latency_ms",
                                 model="tel_model",
                                 quantile="p99") == pytest.approx(
                                     st["p99_ms"], rel=0.01)
            if st["rps"]:
                assert _metric_value(text, "mxtpu_serving_rps",
                                     model="tel_model") > 0
            # compile series agree with compile.stats()
            cstats = C.stats()["serving"]
            assert _metric_value(text, "mxtpu_compile_cache_hits_total",
                                 site="serving") == cstats["hits"]
            assert _metric_value(text, "mxtpu_compile_cache_misses_total",
                                 site="serving") == cstats["misses"]
            assert _metric_value(text, "mxtpu_compile_ms_total",
                                 site="serving") == pytest.approx(
                                     cstats["compile_ms"], rel=0.01)
            # watchdog + memory series present
            assert _metric_value(text,
                                 "mxtpu_watchdog_stalls_total") is not None
            assert [l for l in text.splitlines()
                    if l.startswith("mxtpu_device_memory_live_bytes")]
            # the JSON twin parses and carries the same families
            jtext, jtype = _scrape(front.url, "/metrics.json")
            snap = json.loads(jtext)
            assert jtype.startswith("application/json")
            assert "mxtpu_serving_requests_total" in snap
        finally:
            front.close()
    finally:
        server.drain(timeout=10.0)
        server.stop()


def test_standalone_metrics_server():
    from mxnet_tpu.telemetry import MetricsServer

    srv = MetricsServer(port=0).start()
    try:
        text, ctype = _scrape(srv.url)
        assert ctype.startswith("text/plain")
        assert "mxtpu_flight_ring_size" in text
        health, _ = _scrape(srv.url, "/healthz")
        assert json.loads(health)["status"] == "ok"
    finally:
        srv.close()


# --------------------------------------------- crash bundles + drain tails --

def test_watchdog_bundle_embeds_flight_tail(tmp_path):
    trainer, x, y = small_trainer(seed=7)
    trainer.step(x, y)
    trainer.step(x, y)
    hang = 1.2
    watchdog.configure({"trainer.step": 0.4},
                       crash_dir=str(tmp_path / "crash"), interval=0.1)
    faults.configure(f"trainer.step:hang@1:{hang}", seed=0)
    try:
        with pytest.raises(watchdog.StallError) as ei:
            trainer.step(x, y)
    finally:
        faults.reset()
        watchdog.configure_from_env()
    bundle = ei.value.bundle
    assert bundle and os.path.isdir(bundle)
    with open(os.path.join(bundle, "flight.json")) as f:
        tail = json.load(f)
    assert tail, "flight tail must never be empty after trainer steps"
    # the tail names the wedged point and carries the preceding steps
    assert any(e["kind"] == "watchdog.stall"
               and e["point"] == "trainer.step" for e in tail)
    assert any(e["kind"] == "step.begin" for e in tail)
    assert any(e["kind"] == "step.end" for e in tail)
    # OOM-forensics memory section rides in the report
    with open(os.path.join(bundle, "report.json")) as f:
        rep = json.load(f)
    assert "memory" in rep and "devices" in rep["memory"]
    time.sleep(hang + 0.3)  # let the abandoned waiter drain out


def test_drain_event_embeds_flight_tail(tmp_path):
    from mxnet_tpu import preempt

    flight.rec("t.drain", "p", "before-drain")
    preempt.request("telemetry-test")
    try:
        ev = preempt.drain(save=False, exit=False,
                           directory=str(tmp_path))
    finally:
        preempt.clear()
    assert ev["flight_tail"], "drain event must embed the flight tail"
    kinds = {e["kind"] for e in ev["flight_tail"]}
    assert "preempt.request" in kinds
    # and the on-disk record carries it too
    rec = preempt.last_drain(directory=str(tmp_path))
    assert rec and rec["flight_tail"]


# --------------------------------------------------------- trace integrity --

def test_trace_integrity_bulk_compile_serving(tmp_path):
    """Load a full profiler.dump() of a bulked + compile + serving run:
    valid Chrome-trace envelope, every counter track monotone-timestamped."""
    fname = str(tmp_path / "trace.json")
    mx.profiler.reset()
    mx.profiler.set_config(filename=fname, aggregate_stats=True)
    mx.profiler.set_state("run")
    try:
        # bulked eager segment
        with mx.engine.bulk(4):
            v = mx.nd.ones((8, 8))
            for _ in range(6):
                v = v * 1.01 + 0.1
            v.wait_to_read()
        # compile-service traffic
        import jax.numpy as jnp

        fn = C.jit(lambda a: a * 3, site="svc-tele-prof",
                   token=("tele-prof", 1))
        fn(jnp.ones((4,))).block_until_ready()  # noqa: unbounded-sync — test code
        fn(jnp.ones((4,))).block_until_ready()  # noqa: unbounded-sync — test code
        # serving traffic
        mx.random.seed(31)
        net = gluon.nn.Dense(4, in_units=6)
        net.initialize(mx.init.Xavier())
        net(mx.nd.zeros((2, 6)))
        cont = serving.ModelContainer()
        cont.add_block("tel_trace", net, example_shape=(6,),
                       buckets=(2,))
        srv = serving.ModelServer(cont, max_wait_ms=1.0).start()
        try:
            srv.warmup()
            rows = np.zeros((1, 6), np.float32)
            for _ in range(3):
                srv.predict("tel_trace", rows, timeout=10.0)
        finally:
            srv.drain(timeout=10.0)
            srv.stop()
    finally:
        mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(fname) as f:
        payload = json.load(f)
    events = payload["traceEvents"]
    assert events and payload["displayTimeUnit"] == "ms"
    counters = {}
    for ev in events:
        # the universal envelope: every event carries these fields
        for key in ("name", "cat", "ph", "ts", "pid", "tid"):
            assert key in ev, (key, ev)
        assert ev["ph"] in ("X", "i", "C")
        assert "dur" in ev
        if ev["ph"] == "C":
            counters.setdefault(ev["name"], []).append(ev["ts"])
    # every counter track is monotone-timestamped
    assert counters, "expected counter tracks in the trace"
    for name, stamps in counters.items():
        assert stamps == sorted(stamps), f"counter {name} not monotone"
    names = {e["name"] for e in events}
    assert any(n.startswith("BulkSegment[") for n in names)
    assert "serving[tel_trace]" in names
    assert any(n.startswith("compile_cache.service.") for n in names)
    mx.profiler.reset()


# ------------------------------------------------------------- satellites ---

def test_bench_train_cpu_emits_mfu_xla(capsys, monkeypatch):
    monkeypatch.setenv("BENCH_TRAIN_CPU_BATCH", "8")
    monkeypatch.setenv("BENCH_TRAIN_CPU_ITERS", "2")
    sys.path.insert(0, REPO)
    import bench

    bench.bench_train_cpu()
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["unit"] == "ms/step"
    assert line.get("xla_flops_per_call", 0) > 0
    assert 0 <= line["mfu_xla"] < 1.0


def test_telemetry_describe_and_snapshot():
    d = telemetry.describe()
    assert d["enabled"] in (True, False)
    assert d["flight_ring"] == flight.size()
    snap = telemetry.metrics_snapshot()
    assert "mxtpu_flight_ring_size" in snap


# ------------------------------------------------------------ perf guard ---

@pytest.mark.perf
def test_telemetry_dispatch_overhead_within_noise():
    """CI guard: telemetry-on must not tax the eager per-op hot path —
    opperf --dispatch ns/op with push instrumentation enabled stays
    within noise of disabled (the PR 7-style A/B gate)."""
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    import opperf

    kw = dict(chain_len=8, bulk=8, size=256, iters=60, warmup=10, trials=3)
    on = opperf.bench_dispatch(**kw)
    prev = telemetry.set_enabled(False)
    try:
        off = opperf.bench_dispatch(**kw)
    finally:
        telemetry.set_enabled(prev)
    # generous envelope: CPU CI timing is noisy; the real per-sync cost
    # is one ring-slot write (~1us per CHAIN, not per op) — the guard
    # catches order-of-magnitude regressions (per-op recording, locks,
    # allocation storms)
    for k in ("unbulked_ns_per_op", "bulked_ns_per_op"):
        assert on[k] <= off[k] * 1.6 + 2000.0, (k, on, off)
