"""Migration smoke tests: idiomatic MXNet-1.x user code, unchanged.

Each test is the body of a typical reference user script (the patterns
from the reference's crash course / tutorials — NDArray basics, gluon
training, Module workflow, hybridize+export, autograd, KVStore) run
against this framework with only the import swapped. This is the
product contract from README: "an MXNet user can switch with a context
swap to mx.tpu()".
"""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def test_crash_course_ndarray():
    """NDArray manipulation exactly as the crash course teaches."""
    x = nd.ones((3, 4))
    y = nd.random.uniform(-1, 1, (3, 4))
    z = x * y + 2
    assert z.shape == (3, 4)
    assert z.size == 12
    assert z.dtype == np.float32
    n = z.asnumpy()
    assert isinstance(n, np.ndarray)
    w = nd.array(n)
    np.testing.assert_allclose(w.asnumpy(), n)
    # indexing/slicing idioms
    assert y[1, 2].shape == ()
    assert y[:, 1:3].shape == (3, 2)
    y[:, 1:3] = 2
    assert float(y[0, 1].asscalar()) == 2
    y[1:2, 0:2] = 4
    assert float(y[1, 0].asscalar()) == 4
    # reshape/transpose/dot chain
    a = nd.arange(12).reshape((3, 4))
    b = nd.dot(a, a.T)
    assert b.shape == (3, 3)
    assert float(nd.sum(a).asscalar()) == 66


def test_crash_course_gluon_train_loop():
    """The canonical gluon loop: net/loss/Trainer/record/backward/step."""
    mx.random.seed(0)
    np.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    X = np.random.randn(64, 8).astype(np.float32)
    Yv = (X.sum(axis=1) > 0).astype(np.float32)
    first = last = None
    for _ in range(30):
        data, label = nd.array(X), nd.array(Yv)
        with autograd.record():
            out = net(data)
            loss = loss_fn(out, label)
        loss.backward()
        trainer.step(batch_size=64)
        cur = float(loss.mean().asscalar())
        first = first if first is not None else cur
        last = cur
    assert last < first * 0.7, (first, last)
    acc = ((net(nd.array(X)).argmax(axis=1).asnumpy() == Yv).mean())
    assert acc > 0.9


def test_hybridize_export_symbolblock_roundtrip(tmp_path):
    """hybridize -> export -> SymbolBlock.imports, the deployment path."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    x = nd.random.uniform(shape=(2, 5))
    net.hybridize()
    ref = net(x)
    prefix = str(tmp_path / "model")
    net.export(prefix, epoch=0)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0000.params")
    back = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                     prefix + "-0000.params")
    np.testing.assert_allclose(back(x).asnumpy(), ref.asnumpy(), rtol=1e-6)


def test_module_workflow_checkpoints(tmp_path):
    """Symbolic Module: bind/fit/score/save/load, the 1.x classic."""
    # the Xavier init draws from the GLOBAL streams: seed them so the
    # convergence assert does not depend on suite ordering
    mx.random.seed(0)
    np.random.seed(0)
    rng = np.random.RandomState(0)
    X = rng.randn(128, 10).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=32,
                           label_name="softmax_label")
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=6, initializer=mx.init.Xavier(),
            optimizer_params=(("learning_rate", 0.3),
                              ("rescale_grad", 1.0 / 32)))
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 6)
    sym, arg, aux = mx.model.load_checkpoint(prefix, 6)
    mod2 = mx.mod.Module(sym)
    mod2.bind(it.provide_data, it.provide_label, for_training=False)
    mod2.set_params(arg, aux)
    assert dict(mod2.score(it, "acc"))["accuracy"] == acc


def test_autograd_head_gradient_and_pause():
    """attach_grad/record/backward with a head gradient + pause."""
    x = nd.array([[1.0, 2], [3, 4]])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y * x
    head = nd.array([[10.0, 1], [0.1, 0.01]])
    z.backward(head)
    np.testing.assert_allclose(x.grad.asnumpy(),
                               (4 * x.asnumpy()) * head.asnumpy(),
                               rtol=1e-6)
    with autograd.record():
        y = x * 2
        with autograd.pause():
            frozen = y * 3  # not recorded
        out = (y + frozen.detach()).sum()
    out.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * np.ones((2, 2)),
                               rtol=1e-6)


def test_kvstore_push_pull_aggregation():
    """The kvstore tutorial: init/push/pull with aggregation."""
    kv = mx.kv.create("local")
    shape = (2, 3)
    kv.init(3, nd.ones(shape))
    out = nd.zeros(shape)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(shape))
    kv.push(3, [nd.ones(shape)] * 4)  # 4-worker aggregate
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 4 * np.ones(shape))


def test_lr_scheduler_and_optimizer_surface():
    """Optimizer + scheduler wiring exactly as 1.x docs show."""
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                            base_lr=1.0)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched,
                           momentum=0.9, wd=1e-4)
    trainer = gluon.Trainer({}, opt)
    assert trainer.learning_rate == 1.0
    net = gluon.nn.Dense(2)
    net.initialize()
    x = nd.ones((4, 3))
    loss_fn = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), opt)
    for i in range(5):
        with autograd.record():
            loss = loss_fn(net(x), nd.zeros((4, 2)))
        loss.backward()
        tr.step(4)
    assert opt.learning_rate < 1.0  # scheduler decayed


def test_np_interop_and_context():
    """mx.np + context handling as the 'NumPy users' guide teaches."""
    with mx.Context("cpu"):
        a = mx.np.ones((2, 3))
        assert a.shape == (2, 3)
    b = mx.np.arange(6).reshape(2, 3)
    c = np.asarray(b.asnumpy())  # explicit host copy
    np.testing.assert_allclose((a + b).asnumpy(), c + 1)
    # __array_function__ dispatch: numpy functions on mx.np arrays
    s = np.sum(b)
    assert float(s) == 15


def test_loss_head_label_auto_creation_and_inference():
    """Loss heads auto-create '<name>_label' and infer its shape from
    data alone — the standard inference idiom binds without label shapes
    (reference backward shape inference)."""
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    for head, expect in [
            (mx.sym.SoftmaxOutput(fc, name="softmax"), (8,)),
            (mx.sym.SVMOutput(fc, name="svm"), (8,)),
            (mx.sym.LinearRegressionOutput(fc, name="lin"), (8, 4))]:
        label_name = [n for n in head.list_arguments()
                      if n.endswith("_label")]
        assert len(label_name) == 1, head.list_arguments()
        arg_shapes, out_shapes, _ = head.infer_shape(data=(8, 10))
        shapes = dict(zip(head.list_arguments(), arg_shapes))
        assert shapes[label_name[0]] == expect
    # inference-only bind with no label shapes (mod.bind(provide_data))
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net)
    mod.bind([("data", (8, 10))], for_training=False)
    mod.init_params(mx.init.Uniform(0.1))
    mod.forward(mx.io.DataBatch(data=[nd.ones((8, 10))], label=None))
    assert mod.get_outputs()[0].shape == (8, 4)


def test_gluon_data_pipeline_training_flow():
    """The crash-course data chapter: Dataset -> transform -> DataLoader
    -> training loop, unchanged."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    from mxnet_tpu.gluon.data.vision import transforms

    rng = np.random.RandomState(0)
    imgs = (rng.rand(64, 8, 8, 3) * 255).astype(np.uint8)
    labels = (imgs.reshape(64, -1).mean(axis=1) > 127).astype(np.float32)
    ds = ArrayDataset(imgs, labels)
    tf = transforms.Compose([transforms.ToTensor(),
                             transforms.Normalize(0.5, 0.25)])
    ds = ds.transform_first(
        lambda im: tf(nd.array(im, dtype=np.uint8)))
    loader = DataLoader(ds, batch_size=16, shuffle=True)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(8):
        for xb, yb in loader:
            with autograd.record():
                loss = loss_fn(net(xb.reshape((xb.shape[0], -1))), yb)
            loss.backward()
            trainer.step(xb.shape[0])
    preds = []
    for xb, yb in DataLoader(ds, batch_size=16):
        preds.append(net(xb.reshape((xb.shape[0], -1)))
                     .argmax(axis=1).asnumpy())
    acc = (np.concatenate(preds) == labels).mean()
    assert acc > 0.85, acc


def test_bucketing_module_over_context_group():
    """BucketingModule inherits the ctx-list dp mesh through its bucket
    Modules (module/bucketing_module.py passing context through)."""
    rng = np.random.RandomState(0)
    ctxs = [mx.cpu(i) for i in range(4)]

    def sym_gen(seq_len):
        # params must be bucket-shape-independent (the bucketing regime):
        # embedding + time-pool + FC works for any seq_len
        data = mx.sym.var("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=8,
                               name="emb")
        pooled = mx.sym.sum(emb, axis=1)
        net = mx.sym.FullyConnected(pooled, num_hidden=8, name="fc")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=ctxs)
    mod.bind([("data", (8, 16))], [("softmax_label", (8,))])
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer_params=(("learning_rate", 0.1),))
    X = rng.randint(0, 20, (8, 16)).astype(np.float32)
    Y = (X[:, 0] > 10).astype(np.float32)
    batch = mx.io.DataBatch(data=[nd.array(X)], label=[nd.array(Y)],
                            bucket_key=16,
                            provide_data=[("data", (8, 16))],
                            provide_label=[("softmax_label", (8,))])
    for _ in range(3):
        mod.forward(batch)
        mod.backward()
        mod.update()
    assert mod._curr_module._exec._mesh is not None  # dp mesh active
    assert mod.get_outputs()[0].shape == (8, 8)
    # switch to a NEW bucket: _gen_module + shared-params bind must
    # inherit the ctx-group mesh too, and share parameter handles
    batch8 = mx.io.DataBatch(data=[nd.array(X[:, :8])],
                             label=[nd.array(Y)], bucket_key=8,
                             provide_data=[("data", (8, 8))],
                             provide_label=[("softmax_label", (8,))])
    mod.forward(batch8)
    assert mod._curr_module._exec._mesh is not None
    assert mod.get_outputs()[0].shape == (8, 8)


def _example_module(relpath, name):
    import importlib.util
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "examples", relpath)
    for d in (os.path.dirname(path), root):
        if d not in sys.path:
            sys.path.insert(0, d)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_factorization_machine_example():
    """BASELINE config: sparse FM on the real row_sparse kvstore path
    (reference example/sparse/factorization_machine) learns a planted
    FM dataset CPU-small."""
    fm = _example_module("sparse/factorization_machine.py",
                         "fm_example")
    acc = fm.main(["--num-epoch", "12", "--input-size", "300",
                   "--num-examples", "960", "--factor-size", "4",
                   "--nnz", "8"])
    assert acc > 0.7, acc


def test_transformer_finetune_example(tmp_path):
    """BASELINE config: BERT-class pretrain->fine-tune over flash
    attention + ShardedTrainer (stands in for the GluonNLP config)."""
    tf = _example_module("gluon/transformer_finetune.py",
                         "transformer_finetune_example")
    acc = tf.main(["--num-examples", "256", "--pretrain-steps", "10",
                   "--finetune-epochs", "4", "--layers", "1",
                   "--seq-len", "12",
                   "--checkpoint", str(tmp_path / "backbone.params")])
    assert acc > 0.8, acc


def test_train_imagenet_benchmark_mode():
    """The flagship fit driver's --benchmark synthetic mode produces a
    throughput run end-to-end (reference fit.py:150-321)."""
    ti = _example_module("image_classification/train_imagenet.py",
                         "train_imagenet_example")
    model = ti.main(["--benchmark", "1", "--network", "resnet18_v1",
                     "--batch-size", "8", "--image-shape", "3,32,32",
                     "--num-classes", "10", "--num-examples", "32",
                     "--ctx", "cpu", "--disp-batches", "2"])
    assert model is not None


def test_dcgan_example():
    """Adversarial two-optimizer training loop (reference
    example/gluon/dcgan): alternating D/G steps with a detached fake
    batch; both losses stay finite and the generator produces samples."""
    dc = _example_module("gluon/dcgan.py", "dcgan_example")
    d_loss, g_loss = dc.main(["--epochs", "2", "--num-examples", "96",
                              "--batch-size", "16"])
    import numpy as np

    assert np.isfinite(d_loss) and np.isfinite(g_loss)


def test_matrix_factorization_example():
    """SURVEY §2.9 sparse row: MF with row_sparse user/item factors over
    the kvstore (reference example/sparse/matrix_factorization)."""
    mf = _example_module("sparse/matrix_factorization.py", "mf_example")
    rmse = mf.main(["--num-epoch", "12", "--num-ratings", "3000",
                    "--num-users", "300", "--num-items", "250"])
    assert rmse < 1.8, rmse
