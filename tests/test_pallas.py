"""Pallas flash-attention kernel tests (interpreter mode on CPU; the
same kernel lowers natively on TPU — driven on the real chip in
verification)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops import pallas_ops


def _qkv(B=1, H=2, S=256, D=64, seed=0):
    rs = onp.random.RandomState(seed)
    return [mx.nd.array(rs.randn(B, H, S, D).astype("float32") * 0.3)
            for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    import jax.numpy as jnp

    q, k, v = _qkv()
    out = nd.contrib.flash_attention(q, k, v, causal=causal, interpret=True)
    ref = pallas_ops.flash_attention_reference(
        jnp.asarray(q.asnumpy()), jnp.asarray(k.asnumpy()),
        jnp.asarray(v.asnumpy()), 1.0 / 8.0, causal)
    onp.testing.assert_allclose(out.asnumpy(), onp.asarray(ref),
                                rtol=1e-3, atol=1e-4)


def test_flash_kernel_path_taken():
    """The pallas kernel (not the dense fallback) runs for aligned
    shapes under interpret mode."""
    import jax.numpy as jnp

    q, k, v = _qkv(S=128)
    out = pallas_ops._flash_forward(
        jnp.asarray(q.asnumpy()), jnp.asarray(k.asnumpy()),
        jnp.asarray(v.asnumpy()), 0.125, False, 128, 128,
        interpret=True)
    ref = pallas_ops.flash_attention_reference(
        jnp.asarray(q.asnumpy()), jnp.asarray(k.asnumpy()),
        jnp.asarray(v.asnumpy()), 0.125, False)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-4, atol=1e-5)


def test_flash_unaligned_falls_back():
    q, k, v = _qkv(S=100)  # not divisible by block
    out = nd.contrib.flash_attention(q, k, v)
    assert out.shape == q.shape


def test_flash_gradients():
    q, k, v = _qkv(S=128)
    for x in (q, k, v):
        x.attach_grad()
    with mx.autograd.record():
        out = nd.contrib.flash_attention(q, k, v, interpret=True)
        loss = (out * out).sum()
    loss.backward()
    # oracle: dense attention gradients
    import jax
    import jax.numpy as jnp

    def dense_loss(qr, kr, vr):
        o = pallas_ops.flash_attention_reference(qr, kr, vr, 0.125, False)
        return (o * o).sum()

    grads = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q.asnumpy()), jnp.asarray(k.asnumpy()),
        jnp.asarray(v.asnumpy()))
    for x, g in zip((q, k, v), grads):
        onp.testing.assert_allclose(x.grad.asnumpy(), onp.asarray(g),
                                    rtol=1e-3, atol=1e-4)
