"""Gluon RNN tests (parity model: tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.gluon import rnn
from mxnet_tpu.test_utils import assert_almost_equal


def test_rnn_cells_step():
    for cell_cls, n_states in [(rnn.RNNCell, 1), (rnn.LSTMCell, 2),
                               (rnn.GRUCell, 1)]:
        cell = cell_cls(16, input_size=8)
        cell.initialize()
        x = mx.nd.random.uniform(shape=(4, 8))
        states = cell.begin_state(4)
        out, new_states = cell(x, states)
        assert out.shape == (4, 16)
        assert len(new_states) == n_states


def test_cell_unroll():
    cell = rnn.LSTMCell(8, input_size=4)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 5, 4))  # NTC
    outs, states = cell.unroll(5, x, merge_outputs=True)
    assert outs.shape == (2, 5, 8)
    outs_list, _ = cell.unroll(5, x, merge_outputs=False)
    assert len(outs_list) == 5 and outs_list[0].shape == (2, 8)


def test_deferred_input_size():
    cell = rnn.GRUCell(8)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(3, 6))
    out, _ = cell(x, cell.begin_state(3))
    assert cell.i2h_weight.shape == (24, 6)


def test_sequential_stack():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=4))
    stack.add(rnn.LSTMCell(6, input_size=8))
    stack.initialize()
    x = mx.nd.random.uniform(shape=(2, 4))
    states = stack.begin_state(2)
    assert len(states) == 4
    out, new_states = stack(x, states)
    assert out.shape == (2, 6)


def test_residual_dropout_cells():
    cell = rnn.ResidualCell(rnn.GRUCell(4, input_size=4))
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 4))
    out, _ = cell(x, cell.begin_state(2))
    assert out.shape == (2, 4)
    d = rnn.DropoutCell(0.5)
    out2, _ = d(x, [])
    assert_almost_equal(out2, x)  # inference: identity


def test_bidirectional_unroll():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(6, input_size=4),
                               rnn.LSTMCell(6, input_size=4))
    bi.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 4))
    outs, states = bi.unroll(3, x, merge_outputs=True)
    assert outs.shape == (2, 3, 12)


@pytest.mark.parametrize("layer_cls,mode_states", [
    (rnn.RNN, 1), (rnn.LSTM, 2), (rnn.GRU, 1)])
def test_fused_layers(layer_cls, mode_states):
    layer = layer_cls(16, num_layers=2, input_size=8)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(5, 3, 8))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 16)
    states = layer.begin_state(3)
    out, new_states = layer(x, states)
    assert len(new_states) == mode_states
    assert new_states[0].shape == (2, 3, 16)


def test_fused_layer_ntc_and_bidirectional():
    layer = rnn.LSTM(8, layout="NTC", bidirectional=True, input_size=4)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(2, 6, 4))
    out = layer(x)
    assert out.shape == (2, 6, 16)  # 2*hidden for bidir


def test_fused_lstm_matches_cell_unroll():
    """The fused LSTM layer must match step-by-step LSTMCell unrolling when
    weights are tied (parity: test_gluon_rnn.py fused-vs-stack checks)."""
    np.random.seed(0)
    mx.random.seed(0)
    T, B, I, H = 4, 2, 3, 5
    layer = rnn.LSTM(H, input_size=I)
    layer.initialize()
    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    # copy fused layer weights into the cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())

    x = mx.nd.random.uniform(shape=(T, B, I))
    fused_out = layer(x)
    cell_out, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    # cell unroll uses TNC: outputs stacked on axis 0
    assert_almost_equal(fused_out, cell_out, rtol=1e-4, atol=1e-5)


def test_rnn_gradients_flow():
    layer = rnn.GRU(8, num_layers=2, input_size=4)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(5, 2, 4))
    x.attach_grad()
    with ag.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    assert float(x.grad.norm().asscalar()) > 0
    g = layer.l0_i2h_weight.grad()
    assert np.isfinite(g.asnumpy()).all() and float(g.norm().asscalar()) > 0


def test_rnn_trains():
    """Tiny sequence task: predict sum of inputs (convergence check)."""
    from mxnet_tpu.gluon import Trainer, nn as gnn, loss as gloss

    np.random.seed(0)
    mx.random.seed(0)
    lstm = rnn.LSTM(16, input_size=2)
    head = gnn.Dense(1, in_units=16)
    lstm.initialize()
    head.initialize()
    params = list(lstm.collect_params().values()) + \
        list(head.collect_params().values())
    trainer = Trainer(params, "adam", {"learning_rate": 0.01})
    L = gloss.L2Loss()
    x_np = np.random.rand(8, 16, 2).astype(np.float32)  # TNC
    y_np = x_np.sum(axis=(0, 2), keepdims=False)[:, None].astype(np.float32)
    x, y = mx.nd.array(x_np), mx.nd.array(y_np)
    first = last = None
    for i in range(30):
        with ag.record():
            seq = lstm(x)
            pred = head(seq.slice_axis(0, 7, 8).squeeze(0))
            loss = L(pred, y)
        loss.backward()
        trainer.step(16)
        v = float(loss.mean().asscalar())
        first = first if first is not None else v
        last = v
    assert last < first * 0.5, f"LSTM did not train: {first} -> {last}"
