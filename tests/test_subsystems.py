"""Tests for profiler / callback / monitor / visualization / runtime /
util / amp (parity model: tests/python/unittest/test_profiler.py,
test_amp.py, and the callback/monitor doctests in the reference)."""
import json
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, util
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss


# ------------------------------------------------------------- profiler ----

def test_profiler_trace_and_aggregate(tmp_path):
    fname = str(tmp_path / "profile.json")
    mx.profiler.reset()
    mx.profiler.set_config(filename=fname, aggregate_stats=True)
    mx.profiler.set_state("run")
    a = mx.nd.ones((32, 32))
    ((a * 2) + 1).sum().wait_to_read()
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    trace = json.load(open(fname))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "sum" in names and "_mul_scalar" in names
    assert all({"ts", "dur", "ph"} <= set(e) for e in trace["traceEvents"])
    table = mx.profiler.dumps(sort_by="count")
    assert "sum" in table and "Count" in table


def test_profiler_pause_resume():
    mx.profiler.reset()
    mx.profiler.set_state("run")
    mx.profiler.pause()
    mx.nd.ones((4,)).sum().wait_to_read()
    mx.profiler.resume()
    assert mx.profiler.state() == "run"
    mx.profiler.set_state("stop")
    # nothing recorded while paused
    assert "sum" not in mx.profiler.dumps()


def test_profiler_instrumentation_objects(tmp_path):
    mx.profiler.reset()
    mx.profiler.set_state("run")
    domain = mx.profiler.Domain("test")
    with domain.new_task("work"):
        pass
    counter = domain.new_counter("ctr", 10)
    counter += 5
    domain.new_marker("mark").mark()
    mx.profiler.set_state("stop")
    fname = str(tmp_path / "p.json")
    mx.profiler.set_config(filename=fname)
    mx.profiler.dump()
    evs = json.load(open(fname))["traceEvents"]
    assert any(e["name"] == "work" for e in evs)
    assert any(e["ph"] == "C" for e in evs)
    assert any(e["ph"] == "i" for e in evs)


def test_profiler_hybrid_cachedop_event():
    mx.profiler.reset()
    net = nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 8))
    net(x)  # compile outside profile window
    mx.profiler.set_state("run")
    net(x).wait_to_read()
    mx.profiler.set_state("stop")
    assert "CachedOp" in mx.profiler.dumps()


# ------------------------------------------------------------- callback ----

def _batch_param(epoch, nbatch, metric=None):
    from mxnet_tpu.module.base_module import BatchEndParam

    return BatchEndParam(epoch=epoch, nbatch=nbatch, eval_metric=metric,
                         locals=None)


def test_speedometer_logs(caplog):
    sp = mx.callback.Speedometer(batch_size=32, frequent=2, auto_reset=False)
    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array([1, 1])], [mx.nd.array([[0.1, 0.9],
                                                       [0.8, 0.2]])])
    with caplog.at_level(logging.INFO):
        for i in range(1, 5):
            sp(_batch_param(0, i, metric))
    assert any("samples/sec" in r.message for r in caplog.records)


def test_do_checkpoint(tmp_path):
    prefix = str(tmp_path / "model")
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    arg = {"fc_weight": mx.nd.ones((3, 4)), "fc_bias": mx.nd.zeros((3,))}
    cb = mx.callback.do_checkpoint(prefix, period=1)
    cb(0, fc, arg, {})
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0001.params")
    sym, args, auxs = mx.model.load_checkpoint(prefix, 1)
    np.testing.assert_allclose(args["fc_weight"].asnumpy(), np.ones((3, 4)))


def test_log_train_metric(caplog):
    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array([1])], [mx.nd.array([[0.1, 0.9]])])
    cb = mx.callback.log_train_metric(1)
    with caplog.at_level(logging.INFO):
        cb(_batch_param(0, 1, metric))
    assert any("accuracy" in r.message for r in caplog.records)


# -------------------------------------------------------------- monitor ----

def test_monitor_collects_stats():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    ex = fc.simple_bind(mx.cpu(), data=(2, 4))
    ex.copy_params_from({"fc_weight": mx.nd.ones((3, 4)),
                         "fc_bias": mx.nd.zeros((3,))})
    mon = mx.monitor.Monitor(interval=1, pattern=".*weight.*", sort=True)
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True, data=np.ones((2, 4), np.float32))
    res = mon.toc()
    names = [k for _, k, _ in res]
    assert "fc_weight" in names
    assert all("bias" not in k for k in names)
    mon.toc_print()  # smoke


def test_monitor_interval():
    mon = mx.monitor.Monitor(interval=2)
    mon.tic()
    assert mon.activated
    res = mon.toc()
    mon.tic()  # step 1: not activated (1 % 2 != 0)
    assert not mon.activated


# -------------------------------------------------------- visualization ----

def test_print_summary_counts_params(capsys):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    total = mx.visualization.print_summary(fc2, shape={"data": (32, 100)})
    out = capsys.readouterr().out
    assert total == 100 * 64 + 64 + 64 * 10 + 10
    assert "fc1(FullyConnected)" in out
    assert "(32, 64)" in out


def test_plot_network_gated():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    try:
        import graphviz  # noqa: F401

        dot = mx.visualization.plot_network(fc)
        assert "fc" in dot.source
    except ImportError:
        with pytest.raises(ImportError):
            mx.visualization.plot_network(fc)


# -------------------------------------------------------------- runtime ----

def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA")
    assert feats.is_enabled("CPU")
    assert not feats.is_enabled("CUDNN")
    with pytest.raises(RuntimeError):
        feats.is_enabled("NO_SUCH_FEATURE")
    assert repr(mx.runtime.Feature("X", True)).endswith("X")


# ----------------------------------------------------------------- util ----

def test_util_np_scopes():
    assert not util.is_np_shape() and not util.is_np_array()
    with util.np_shape(True):
        assert util.is_np_shape()
        with util.np_array(True):
            assert util.is_np_array()
        assert not util.is_np_array()
    assert not util.is_np_shape()


def test_util_use_np_decorator():
    @util.use_np
    def inner():
        return util.is_np_shape(), util.is_np_array()

    assert inner() == (True, True)
    assert not util.is_np_shape()
    util.set_np()
    assert util.is_np_shape() and util.is_np_array()
    util.reset_np()
    assert not util.is_np_shape()


def test_util_env():
    util.setenv("MXNET_TPU_TEST_ENV", "42")
    assert util.getenv("MXNET_TPU_TEST_ENV") == "42"
    util.setenv("MXNET_TPU_TEST_ENV", None)
    assert util.getenv("MXNET_TPU_TEST_ENV") is None


# ------------------------------------------------------------------ amp ----

def _dt(x):
    return np.dtype(x.dtype).name


@pytest.fixture
def amp_off():
    yield
    amp.turn_off()


def test_amp_eager_and_hybrid_cast(amp_off):
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = mx.nd.random.uniform(shape=(4, 8))
    ref = net(x).asnumpy()
    amp.init("bfloat16")
    out = net(x)
    assert _dt(out) == "bfloat16"
    net.hybridize()
    out_h = net(x)
    assert _dt(out_h) == "bfloat16"
    np.testing.assert_allclose(out.asnumpy().astype(np.float32), ref,
                               rtol=0.05, atol=0.05)


def test_amp_fp32_ops_stay_fp32(amp_off):
    amp.init("bfloat16")
    x = mx.nd.ones((2, 3)).astype("bfloat16")
    assert str(mx.nd.softmax(x).dtype) == "float32"
    assert str(mx.nd.sum(x).dtype) == "float32"


def test_amp_symbol_path(amp_off):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    sm = mx.sym.softmax(fc)
    amp.init("bfloat16")
    ex = sm.simple_bind(mx.cpu(), data=(2, 8))
    out = ex.forward(is_train=False,
                     data=np.random.rand(2, 8).astype(np.float32))
    assert str(out[0].dtype) == "float32"  # softmax forced fp32


def test_amp_training_converges(amp_off):
    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(128, 10).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    amp.init("bfloat16")
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    lfn = gloss.SoftmaxCrossEntropyLoss()
    Xn, yn = mx.nd.array(X), mx.nd.array(y)
    losses = []
    for _ in range(40):
        with mx.autograd.record():
            loss = lfn(net(Xn), yn).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asscalar()))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


def test_amp_loss_scaler_dynamics():
    scaler = amp.LossScaler(init_scale=1024, scale_factor=2, scale_window=2)
    scaler.update_scale(overflow=True)
    assert scaler.loss_scale == 512
    scaler.update_scale(False)
    scaler.update_scale(False)
    assert scaler.loss_scale == 1024  # doubled after window


def test_amp_convert_hybrid_block(amp_off):
    net = nn.Dense(4)
    net.initialize()
    x = mx.nd.ones((2, 8))
    net(x)
    net2 = amp.convert_hybrid_block(net, "bfloat16")
    out = net2(x)
    assert _dt(out) == "bfloat16"


def test_amp_generation_invalidates_caches(amp_off):
    net = nn.Dense(3)
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 5))
    out1 = net(x)
    assert _dt(out1) == "float32"
    amp.init("bfloat16")
    out2 = net(x)
    assert _dt(out2) == "bfloat16"
    amp.turn_off()
    out3 = net(x)
    assert _dt(out3) == "float32"


def test_log_get_logger(tmp_path):
    """parity: python/mxnet/log.py getLogger + formatter."""
    import logging

    from mxnet_tpu import log as mxlog

    logfile = str(tmp_path / "t.log")
    lg = mxlog.get_logger("mxtpu_test_logger", filename=logfile,
                          level=mxlog.INFO)
    lg.info("hello %d", 42)
    for h in lg.handlers:
        h.flush()
    assert "hello 42" in open(logfile).read()
    # idempotent: second call must not duplicate handlers
    lg2 = mxlog.get_logger("mxtpu_test_logger")
    assert lg2 is lg and len(lg.handlers) == 1
    assert mxlog.getLogger is mxlog.get_logger
    logging.getLogger("mxtpu_test_logger").handlers.clear()


def test_feedforward_legacy_api(tmp_path):
    """parity: model.py FeedForward — fit/predict/score/save/load over the
    Module adapter."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.model import FeedForward

    rs = np.random.RandomState(0)
    X = rs.rand(128, 8).astype("f")
    w = rs.randn(8).astype("f")
    y = (X @ w > 0).astype("f")

    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=16)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                            name="softmax")

    model = FeedForward.create(net, X, y, num_epoch=12, optimizer="adam",
                               learning_rate=0.05, numpy_batch_size=32)
    acc = model.score(mx.io.NDArrayIter(X, y, batch_size=32))
    assert acc > 0.8, acc
    preds = model.predict(mx.io.NDArrayIter(X, batch_size=32))
    assert preds.shape == (128, 2)

    prefix = str(tmp_path / "ff")
    model.save(prefix, epoch=12)
    loaded = FeedForward.load(prefix, 12)
    preds2 = loaded.predict(mx.io.NDArrayIter(X, batch_size=32))
    np.testing.assert_allclose(preds2, preds, rtol=1e-5)


def test_model_zoo_get_model_names():
    from mxnet_tpu.gluon.model_zoo import vision

    names = vision.get_model_names()
    assert "resnet50_v1" in names and "mobilenet1_0" in names \
        and len(names) >= 25


def test_ensure_live_backend_respects_pin(monkeypatch):
    """An explicit MXTPU_PLATFORM pin short-circuits the backend probe
    (base.py ensure_live_backend)."""
    monkeypatch.setenv("MXTPU_PLATFORM", "cpu")
    from mxnet_tpu.base import ensure_live_backend

    assert ensure_live_backend() == "cpu"


def test_ensure_live_backend_fallback_paths(monkeypatch):
    """Timeout -> cpu-fallback (env pinned only after success); crash ->
    RuntimeError after retry, env untouched (base.py ensure_live_backend)."""
    import subprocess

    import pytest

    from mxnet_tpu import base

    monkeypatch.delenv("MXTPU_PLATFORM", raising=False)
    # an earlier in-process probe success latches MXTPU_PROBE_OK and would
    # short-circuit the probe entirely (regression guard for the full-suite
    # order dependency fixed alongside conftest's _probe_env_guard)
    monkeypatch.delenv("MXTPU_PROBE_OK", raising=False)

    def hang(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=kw["timeout"])

    monkeypatch.setattr(subprocess, "run", hang)
    # conftest already pinned the cpu platform, so config.update succeeds
    assert base.ensure_live_backend(timeout_s=0.1) == "cpu-fallback"
    assert os.environ["MXTPU_PLATFORM"] == "cpu"

    monkeypatch.delenv("MXTPU_PLATFORM", raising=False)
    calls = []

    class Boom:
        returncode = 1
        stderr = b"device busy"

    def crash(*a, **kw):
        calls.append(1)
        return Boom()

    monkeypatch.setattr(subprocess, "run", crash)
    with pytest.raises(RuntimeError, match="crash, not a hang"):
        base.ensure_live_backend(timeout_s=0.1, retries=1)
    assert len(calls) == 2  # initial + one retry
    assert "MXTPU_PLATFORM" not in os.environ


# ----------------------------------------------------------- bulking -------

def _mlp():
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return net


def _run_mlp(net, x_np, bulk_size):
    x = mx.nd.array(x_np)
    with mx.engine.bulk(bulk_size):
        with mx.autograd.record():
            out = net(x)
            loss = (out * out).sum()
        loss.backward()
        fwd = out.asnumpy()
        grads = {k: p.grad().asnumpy()
                 for k, p in net.collect_params().items()}
    return fwd, grads


def test_bulk_numerics_match_unbulked_mlp():
    """Fused-segment execution and its one-tape-node VJP must reproduce
    per-op dispatch numerics (forward AND parameter grads)."""
    np.random.seed(7)
    mx.random.seed(7)
    net = _mlp()
    x_np = np.random.rand(8, 12).astype(np.float32)
    fwd_u, grads_u = _run_mlp(net, x_np, 1)       # today's per-op path
    for p in net.collect_params().values():
        p.zero_grad()
    fwd_b, grads_b = _run_mlp(net, x_np, 16)      # bulked
    np.testing.assert_allclose(fwd_b, fwd_u, rtol=1e-5, atol=1e-6)
    assert grads_u.keys() == grads_b.keys()
    for k in grads_u:
        np.testing.assert_allclose(grads_b[k], grads_u[k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_bulk_flush_on_sync_points():
    with mx.engine.bulk(8):
        a = mx.nd.ones((4,))
        b = a * 2
        c = b + 1
        assert mx.engine.bulk_pending() == 2
        # metadata is statically known: no flush
        assert b.shape == (4,) and str(c.dtype) == "float32"
        assert mx.engine.bulk_pending() == 2
        # value read flushes the whole segment
        np.testing.assert_allclose(c.asnumpy(), np.full(4, 3.0))
        assert mx.engine.bulk_pending() == 0
        # waitall is a sync point
        d = a + 5
        assert mx.engine.bulk_pending() == 1
        mx.nd.waitall()
        assert mx.engine.bulk_pending() == 0
        np.testing.assert_allclose(d.asnumpy(), np.full(4, 6.0))
        # control flow on values forces too
        e = (a * 3).sum()
        assert mx.engine.bulk_pending() == 2  # _mul_scalar + sum
        assert bool(e > 11.0)
        assert mx.engine.bulk_pending() == 0
        # in-place mutation is a sync point (ordering + tape identity)
        f = a * 7
        assert mx.engine.bulk_pending() == 1
        a[:] = 0
        assert mx.engine.bulk_pending() == 0
        np.testing.assert_allclose(f.asnumpy(), np.full(4, 7.0))
        # segment-size limit auto-flushes (BulkFlush analogue)
        x = mx.nd.ones((4,))
        for _ in range(9):
            x = x * 1.5
        assert mx.engine.bulk_pending() == 1
        np.testing.assert_allclose(x.asnumpy(), np.full(4, 1.5 ** 9),
                                   rtol=1e-6)
    assert mx.engine.bulk_pending() == 0  # scope exit flushed


def test_bulk_naive_engine_disables(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    assert mx.engine.bulk_size() == 1
    with mx.engine.bulk(8):
        assert mx.engine.bulk_size() == 1  # naive wins over the knob
        a = mx.nd.ones((4,))
        b = a * 2
        assert mx.engine.bulk_pending() == 0  # executed eagerly
        np.testing.assert_allclose(b.asnumpy(), np.full(4, 2.0))


def test_bulk_nested_contexts(monkeypatch):
    monkeypatch.delenv("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", raising=False)
    monkeypatch.setattr(mx.engine, "_env_bulk", None)
    monkeypatch.setattr(mx.engine._tls, "bulk_size", None, raising=False)
    assert mx.engine.bulk_size() == 1  # default: per-op dispatch
    with mx.engine.bulk(4):
        assert mx.engine.bulk_size() == 4
        a = mx.nd.ones((2,))
        b = a * 2
        assert mx.engine.bulk_pending() == 1
        with mx.engine.bulk(0):
            # entering the inner scope flushed the outer segment
            assert mx.engine.bulk_pending() == 0
            c = b + 1  # bulking off: executes per-op
            assert mx.engine.bulk_pending() == 0
        assert mx.engine.bulk_size() == 4  # restored
        d = c * 3
        assert mx.engine.bulk_pending() == 1
        np.testing.assert_allclose(d.asnumpy(), np.full(2, 9.0))
    assert mx.engine.bulk_size() == 1
    assert mx.engine.bulk_pending() == 0


def test_bulk_profiler_segment_events(tmp_path):
    fname = str(tmp_path / "bulk_profile.json")
    mx.profiler.reset()
    mx.profiler.set_config(filename=fname, aggregate_stats=True)
    a = mx.nd.ones((8,))
    with mx.engine.bulk(8):
        mx.profiler.set_state("run")
        ((a * 2) + 1).sum().wait_to_read()
        mx.profiler.set_state("stop")
    mx.profiler.dump()
    evs = json.load(open(fname))["traceEvents"]
    seg = [e for e in evs if e["name"].startswith("BulkSegment")]
    assert seg and seg[0]["args"]["op_count"] == 3
    assert "_mul_scalar" in seg[0]["args"]["ops"]
