"""Symbol API tests (parity model: tests/python/unittest/test_symbol.py +
test_executor.py + test_infer_shape.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                name="softmax")


def test_compose_and_listing():
    mlp = _mlp()
    assert mlp.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert mlp.list_outputs() == ["softmax_output"]
    assert mlp.list_auxiliary_states() == []
    assert mlp.name == "softmax"


def test_auto_names_and_no_bias():
    x = mx.sym.var("x")
    fc = mx.sym.FullyConnected(x, num_hidden=3, no_bias=True)
    args = fc.list_arguments()
    assert args[0] == "x" and len(args) == 2  # no bias var created
    assert args[1].endswith("_weight")


def test_infer_shape():
    mlp = _mlp()
    arg_shapes, out_shapes, aux_shapes = mlp.infer_shape(
        data=(8, 100), softmax_label=(8,))
    assert arg_shapes == [(8, 100), (16, 100), (16,), (4, 16), (4,), (8,)]
    assert out_shapes == [(8, 4)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                              name="conv")
    bn = mx.sym.BatchNorm(conv, name="bn")
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(2, 3, 8, 8))
    args = bn.list_arguments()
    shapes = dict(zip(args, arg_shapes))
    assert shapes["conv_weight"] == (8, 3, 3, 3)
    assert shapes["bn_gamma"] == (8,)
    assert out_shapes[0] == (2, 8, 8, 8)
    assert aux_shapes == [(8,), (8,)]
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]


def test_infer_type():
    x = mx.sym.var("x")
    y = x.sum()
    arg_t, out_t, _ = y.infer_type(x="float32")
    assert np.dtype(out_t[0]) == np.float32


def test_symbol_arithmetic_eval():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = (a + b) * 2.0 - a / b
    av = mx.nd.array(np.array([2.0, 4.0], np.float32))
    bv = mx.nd.array(np.array([1.0, 2.0], np.float32))
    out = c.eval_with({"a": av, "b": bv})
    np.testing.assert_allclose(out.asnumpy(), [4.0, 10.0], rtol=1e-6)


def test_group_and_internals():
    mlp = _mlp()
    internals = mlp.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]
    grouped = mx.sym.Group([fc1, mlp])
    assert len(grouped.list_outputs()) == 2


def test_json_round_trip():
    mlp = _mlp()
    js = mlp.tojson()
    loaded = mx.sym.load_json(js)
    assert loaded.list_arguments() == mlp.list_arguments()
    assert loaded.list_outputs() == mlp.list_outputs()
    # and still executable with identical results
    shapes = {"data": (4, 10), "softmax_label": (4,)}
    ex1 = mlp.simple_bind(mx.cpu(), **shapes)
    rng = np.random.RandomState(0)
    feeds = {}
    for name, arr in ex1.arg_dict.items():
        feeds[name] = mx.nd.array(
            rng.uniform(-1, 1, arr.shape).astype(np.float32))
    ex2 = loaded.simple_bind(mx.cpu(), **shapes)
    o1 = ex1.forward(is_train=False, **feeds)[0].asnumpy()
    o2 = ex2.forward(is_train=False, **feeds)[0].asnumpy()
    np.testing.assert_allclose(o1, o2, rtol=1e-6)


def test_bn_json_marks_aux():
    data = mx.sym.var("data")
    bn = mx.sym.BatchNorm(data, name="bn")
    loaded = mx.sym.load_json(bn.tojson())
    assert loaded.list_auxiliary_states() == ["bn_moving_mean",
                                              "bn_moving_var"]


def test_executor_forward_backward_matches_autograd():
    """Symbolic grads == imperative autograd grads for the same graph."""
    from mxnet_tpu import autograd

    rng = np.random.RandomState(7)
    xv = rng.uniform(-1, 1, (5, 6)).astype(np.float32)
    wv = rng.uniform(-1, 1, (3, 6)).astype(np.float32)
    bv = rng.uniform(-1, 1, (3,)).astype(np.float32)

    x = mx.sym.var("x")
    out = mx.sym.FullyConnected(x, num_hidden=3, name="fc")
    out = mx.sym.Activation(out, act_type="tanh")
    ex = out.bind(mx.cpu(), {"x": mx.nd.array(xv), "fc_weight": mx.nd.array(wv),
                             "fc_bias": mx.nd.array(bv)},
                  grad_req={"fc_weight": "write", "x": "write"})
    ex.forward(is_train=True)
    ex.backward()

    xi = mx.nd.array(xv)
    wi = mx.nd.array(wv)
    xi.attach_grad()
    wi.attach_grad()
    with autograd.record():
        y = mx.nd.invoke("FullyConnected", xi, wi, mx.nd.array(bv),
                         num_hidden=3).tanh()
    y.backward()
    np.testing.assert_allclose(ex.grad_dict["fc_weight"].asnumpy(),
                               wi.grad.asnumpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               xi.grad.asnumpy(), rtol=1e-5, atol=1e-6)


def test_softmax_output_gradient():
    """SoftmaxOutput backward = (p - onehot) * grad_scale (reference
    softmax_output-inl.h custom gradient, not the softmax jacobian)."""
    rng = np.random.RandomState(3)
    logits = rng.uniform(-1, 1, (4, 5)).astype(np.float32)
    label = np.array([0, 2, 1, 4], np.float32)
    sym = mx.sym.SoftmaxOutput(mx.sym.var("data"), mx.sym.var("label"),
                               grad_scale=2.0, name="sm")
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array(logits),
                             "label": mx.nd.array(label)},
                  grad_req={"data": "write"})
    out = ex.forward(is_train=True)[0].asnumpy()
    ex.backward()
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               (out - onehot) * 2.0, rtol=1e-5, atol=1e-6)


def test_executor_grad_req_add_and_null():
    x = mx.sym.var("x")
    y = (x * 2.0).sum()
    ex = y.bind(mx.cpu(), {"x": mx.nd.ones((3,))}, grad_req="add")
    ex.forward(is_train=True)
    ex.backward()
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), [4.0] * 3)
    ex2 = y.bind(mx.cpu(), {"x": mx.nd.ones((3,))}, grad_req="null")
    ex2.forward(is_train=True)
    ex2.backward()  # no grads requested: must not fail
    assert ex2.grad_arrays == [None]


def test_executor_reshape():
    mlp = _mlp()
    ex = mlp.simple_bind(mx.cpu(), data=(4, 10), softmax_label=(4,))
    ex2 = ex.reshape(data=(8, 10), softmax_label=(8,))
    out = ex2.forward(is_train=False,
                      data=np.zeros((8, 10), np.float32),
                      softmax_label=np.zeros((8,), np.float32))
    assert out[0].shape == (8, 4)
    # weights carried over
    np.testing.assert_allclose(ex.arg_dict["fc1_weight"].asnumpy(),
                               ex2.arg_dict["fc1_weight"].asnumpy())


def test_bn_aux_update_in_training():
    data = mx.sym.var("data")
    bn = mx.sym.BatchNorm(data, name="bn", momentum=0.5)
    ex = bn.simple_bind(mx.cpu(), data=(16, 4))
    ex.arg_dict["bn_gamma"]._rebind(mx.nd.ones((4,))._data)
    rng = np.random.RandomState(0)
    x = (rng.rand(16, 4) * 2 + 3).astype(np.float32)
    before = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True, data=x)
    after = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(before, after)  # stats moved toward batch mean
    expected = before * 0.5 + x.mean(axis=0) * 0.5
    np.testing.assert_allclose(after, expected, rtol=1e-4)
    # inference forward must NOT move stats
    ex.forward(is_train=False, data=x)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               after, rtol=1e-6)


def test_var_shape_dtype_hints():
    x = mx.sym.var("x", shape=(2, 3), dtype="float32")
    y = x * 3.0
    arg_shapes, out_shapes, _ = y.infer_shape()
    assert arg_shapes == [(2, 3)] and out_shapes == [(2, 3)]


def test_symbol_save_load_file(tmp_path):
    mlp = _mlp()
    fname = str(tmp_path / "mlp-symbol.json")
    mlp.save(fname)
    loaded = mx.sym.load(fname)
    assert loaded.list_arguments() == mlp.list_arguments()


def test_symbol_op_method_sugar():
    x = mx.sym.var("x")
    y = x.reshape(shape=(2, 2)).sum()
    out = y.eval_with({"x": mx.nd.array(np.arange(4, dtype=np.float32))})
    assert float(out.asnumpy()) == 6.0


def test_missing_input_error():
    x = mx.sym.var("x")
    y = x + mx.sym.var("y")
    with pytest.raises(mx.MXNetError):
        y.eval_with({"x": mx.nd.ones((2,))})


def test_optimize_for_pass_registry():
    """Symbol.optimize_for over the registered graph passes (subgraph
    framework analogue; parity: symbol.py optimize_for:1449)."""
    from mxnet_tpu.symbol import symbol as S

    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=4)
    assert net.optimize_for("default") is net
    for p in ("default", "amp", "int8"):
        assert p in S.list_passes()

    calls = []

    @S.register_pass("test_identity_pass")
    def _p(sym, args=None, aux=None, **kw):
        calls.append(kw)
        return sym

    out = net.optimize_for("test_identity_pass", custom_opt=3)
    assert out is net and calls[0]["custom_opt"] == 3
    with pytest.raises(mx.MXNetError):
        net.optimize_for("not_a_backend")
    S.GRAPH_PASSES.pop("test_identity_pass")


def test_name_prefix_scope():
    """mx.name.Prefix prefixes auto-generated names (parity: name.py)."""
    import mxnet_tpu.name as mxname

    data = mx.sym.var("data")
    with mxname.Prefix("mlp_"):
        net = mx.sym.FullyConnected(data, num_hidden=4)
    assert net.name.startswith("mlp_fullyconnected")
    plain = mx.sym.FullyConnected(data, num_hidden=4)
    assert not plain.name.startswith("mlp_")


def test_attr_scope():
    """AttrScope attrs land on symbols created in scope, nest with inner
    priority, never leak into op execution (parity: attribute.py)."""
    data = mx.sym.var("data")
    with mx.AttrScope(ctx_group="stage1", __lr_mult__="0.1"):
        w = mx.sym.var("w")
        net = mx.sym.FullyConnected(data, weight=w, num_hidden=3,
                                    no_bias=True)
        with mx.AttrScope(ctx_group="stage2"):
            inner = mx.sym.var("b")
    assert w.attr("ctx_group") == "stage1"
    assert w.attr("__ctx_group__") == "stage1"  # storage form
    assert w.attr("lr_mult") == "0.1"
    assert net.attr("ctx_group") == "stage1"
    assert inner.attr("ctx_group") == "stage2"
    assert inner.attr("lr_mult") == "0.1"  # inherited from outer scope
    outside = mx.sym.var("o")
    assert outside.attr("ctx_group") is None
    # scope attrs must not reach the op callable: bind + run the net
    exe = net.simple_bind(mx.cpu(), data=(2, 5), w=(3, 5))
    exe.forward(is_train=False, data=mx.nd.ones((2, 5)),
                w=mx.nd.ones((3, 5)))
    assert exe.outputs[0].shape == (2, 3)
    # attrs survive a json round-trip
    back = mx.sym.load_json(net.tojson())
    assert back.attr("ctx_group") == "stage1"


def test_attr_and_name_scope_edge_cases():
    """User attrs override scope attrs on the canonical form; reused
    scopes don't leak parent attrs; fresh NameManagers restart numbering;
    gluon blocks honor name.Prefix."""
    import mxnet_tpu.name as mxname
    from mxnet_tpu import gluon

    # user override wins on the storage form too
    with mx.AttrScope(ctx_group="a"):
        w = mx.sym.var("w", attr={"ctx_group": "b"})
    assert w.attr("ctx_group") == "b"
    assert w.attr("__ctx_group__") == "b"

    # reusing a scope object after nesting must not leak parent attrs
    s = mx.AttrScope(a="1")
    with mx.AttrScope(b="2"):
        with s:
            pass
    with s:
        v = mx.sym.var("x2")
    assert v.attr("b") is None and v.attr("a") == "1"

    # fresh NameManager scopes restart numbering -> deterministic names
    data = mx.sym.var("data")
    with mxname.NameManager():
        n1 = mx.sym.FullyConnected(data, num_hidden=2).name
    with mxname.NameManager():
        n2 = mx.sym.FullyConnected(data, num_hidden=2).name
    assert n1 == n2 == "fullyconnected0"

    # gluon auto-prefix flows through the name scope
    with mxname.Prefix("pp_"):
        d = gluon.nn.Dense(3)
    assert d.prefix.startswith("pp_dense")


def test_set_attr_does_not_poison_validation_cache():
    """node-attr mutation after compose must not leak into the op's
    cached validated kwargs (checked() hands out a shared dict)."""
    import mxnet_tpu as mx

    s = mx.sym.Activation(mx.sym.Variable("data"), act_type="relu")
    s._set_attr(force_mirroring="True")
    # same static kwargs through the imperative path: must still work
    out = mx.nd.Activation(mx.nd.ones((2, 2)), act_type="relu")
    assert out.shape == (2, 2)
