"""Subprocess workload for the compile-service persistence tests.

Runs a representative mixed workload — eager dispatch, a bulked segment,
a hybridized (CachedOp) forward, a symbol executor forward, and two
ShardedTrainer steps — with whatever MXNET_TPU_CACHE_DIR the parent set,
then prints ONE json line of compile-service totals + per-site stats.

The parent runs it twice against the same cache dir: the first (cold) run
compiles everything; the second (warm) run must satisfy every miss from
the persistent cache — zero XLA recompiles of previously-seen signatures.

Determinism contract: shapes, dtypes, op sequence and net structure are
fixed so both runs produce identical service tokens + signatures.
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("MXNET_TEST_DEVICE", "cpu").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import compile as C
from mxnet_tpu.gluon import loss as gloss, nn
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer


def main():
    mx.random.seed(0)
    rng = np.random.RandomState(0)

    # --- eager dispatch (registry site): fixed op/kwarg/shape sequence
    a = mx.nd.array(rng.rand(4, 4).astype(np.float32))
    b = mx.nd.array(rng.rand(4, 4).astype(np.float32))
    (mx.nd.dot(a, b) + 1.0).wait_to_read()
    mx.nd.softmax(a).wait_to_read()

    # --- bulked segment (bulk site)
    with mx.engine.bulk(8):
        z = (a * 2.0 + b).sum()
        z.wait_to_read()

    # --- CachedOp (hybridize site)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(rng.rand(8, 8).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, 8).astype(np.float32))
    net(x)  # deferred init (eager)
    net.hybridize()
    net(x).wait_to_read()

    # --- symbol executor site
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=4, no_bias=True,
                                name="fc")
    exe = out.simple_bind(mx.cpu(), data=(8, 8))
    exe.forward(data=x)

    # --- ShardedTrainer site, 2 steps = 1 signature. donate=False so the
    # step executable is serializable: donating executables dispatch
    # through jit's C++ path only (see compile.ServiceFunction) and warm
    # through the native XLA cache instead of executable deserialization
    trainer = ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                             {"learning_rate": 0.1},
                             mesh=DeviceMesh({"dp": 1}), donate=False)
    trainer.step(x, y).wait_to_read()
    trainer.step(x, y).wait_to_read()

    report = {"totals": C.totals(), "stats": C.stats(),
              "disk": C.disk_report(),
              "manifest_entries": len(C.manifest())}
    print("CHILD_REPORT " + json.dumps(report), flush=True)


if __name__ == "__main__":
    main()
