"""Native C++ IO runtime tests (parity model: dmlc-core recordio tests +
iter_image_recordio_2 coverage)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native, recordio


def _write_rec(tmp_path, n=20):
    rec = recordio.MXRecordIO(str(tmp_path / "t.rec"), "w")
    payloads = [onp.random.RandomState(i).bytes(50 + 13 * i)
                for i in range(n)]
    for p in payloads:
        rec.write(p)
    rec.close()
    return str(tmp_path / "t.rec"), payloads


def test_scan_and_read_roundtrip(tmp_path):
    path, payloads = _write_rec(tmp_path)
    offs, lens = native.recordio_scan(path)
    assert len(offs) == len(payloads)
    assert native.recordio_read(path, offs, lens) == payloads


def test_python_fallback_scan_matches(tmp_path):
    path, payloads = _write_rec(tmp_path, n=7)
    offs_n, lens_n = native.recordio_scan(path)
    offs_p, lens_p = native._py_scan(path)
    onp.testing.assert_array_equal(offs_n, offs_p)
    onp.testing.assert_array_equal(lens_n, lens_p)


def test_pack_framing_matches_writer(tmp_path):
    path, payloads = _write_rec(tmp_path, n=5)
    packed = native.recordio_pack(payloads)
    with open(path, "rb") as f:
        assert packed == f.read()


def test_normalize_batch_oracle():
    imgs = onp.random.RandomState(0).randint(0, 256, (3, 6, 5, 3),
                                             dtype=onp.uint8)
    mean = onp.array([10.0, 20.0, 30.0], onp.float32)
    std = onp.array([2.0, 3.0, 4.0], onp.float32)
    out = native.normalize_batch(imgs, mean, std, scale=1.0)
    ref = ((imgs.astype(onp.float32) - mean) / std).transpose(0, 3, 1, 2)
    onp.testing.assert_allclose(out, ref, rtol=1e-5)
    assert out.shape == (3, 3, 6, 5)


def test_indexed_reader_rebuilds_missing_idx(tmp_path):
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                     str(tmp_path / "x.rec"), "w")
    for i in range(6):
        rec.write_idx(i, b"payload-%d" % i)
    rec.close()
    os.remove(tmp_path / "x.idx")
    rec2 = recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                      str(tmp_path / "x.rec"), "r")
    assert rec2.keys == list(range(6))
    assert rec2.read_idx(3) == b"payload-3"


def test_image_record_iter(tmp_path):
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "im.idx"),
                                     str(tmp_path / "im.rec"), "w")
    rs = onp.random.RandomState(0)
    for i in range(8):
        img = (rs.rand(12, 12, 3) * 255).astype("uint8")
        header = recordio.IRHeader(0, float(i % 4), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=str(tmp_path / "im.rec"),
                               data_shape=(3, 12, 12), batch_size=4)
    b = next(iter(it))
    assert b.data[0].shape == (4, 3, 12, 12)
    assert b.label[0].asnumpy().tolist() == [0.0, 1.0, 2.0, 3.0]
    # resize path: ask for a different spatial size
    it2 = mx.io.ImageRecordIter(path_imgrec=str(tmp_path / "im.rec"),
                                data_shape=(3, 8, 8), batch_size=4)
    assert next(iter(it2)).data[0].shape == (4, 3, 8, 8)
