"""Subprocess half of the data-plane kill-and-resume drill.

Iterates an AUGMENTED ImageRecordIter (fused native decode+rand-crop+
mirror+color-jitter, prefetch producer running), persisting the
iterator's ``state_dict`` through a CheckpointManager after every
consumed batch, and either

* SIGKILLs itself mid-epoch after ``DP_KILL_AFTER`` batches (no exit
  handler runs — the hard-preemption scenario), or
* resumes from the manager's last good entry (``DP_RESUME=1``) and
  writes the REMAINING stream's checksums, or
* runs the epoch uninterrupted (the reference stream).

Output npz: per-batch CRC32 of the augmented pixel bytes + labels
(proof the resumed stream is bit-exact, augmentation included), and
``__start__`` = the batch index the run began at.

Env: DP_REC, DP_CKPT, DP_OUT, DP_KILL_AFTER, DP_RESUME, DP_BATCH,
DP_PARTS, DP_PART, DP_SHAPE (default 3,24,24).
"""
import json
import os
import signal
import sys
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import checkpoint


def main():
    rec = os.environ["DP_REC"]
    ckpt_dir = os.environ["DP_CKPT"]
    out = os.environ.get("DP_OUT")
    kill_after = int(os.environ.get("DP_KILL_AFTER", "0") or 0)
    resume = os.environ.get("DP_RESUME") == "1"
    batch = int(os.environ.get("DP_BATCH", "4"))
    parts = int(os.environ.get("DP_PARTS", "1"))
    part = int(os.environ.get("DP_PART", "0"))
    shape = tuple(int(x) for x in
                  os.environ.get("DP_SHAPE", "3,24,24").split(","))

    it = mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=shape, batch_size=batch,
        shuffle=True, rand_crop=True, rand_mirror=True, color_jitter=0.2,
        seed=3, round_batch=False, preprocess_threads=2,
        prefetch_buffer=2, num_parts=parts, part_index=part)
    manager = checkpoint.CheckpointManager(ckpt_dir, prefix="dp", keep=3)

    start = 0
    if resume:
        entry, paths = manager.load()
        with open(paths["iter"]) as f:
            state = json.load(f)
        it.load_state_dict(state)
        start = it._consumed

    crcs, labels = [], []
    n = start
    for b in it:
        data = np.ascontiguousarray(b.data[0].asnumpy())
        lab = np.ascontiguousarray(b.label[0].asnumpy())
        crcs.append(zlib.crc32(data.tobytes())
                    ^ zlib.crc32(lab.tobytes()))
        labels.extend(int(x) for x in lab.reshape(-1))
        n += 1
        state = json.dumps(it.state_dict()).encode()
        manager.save(n, {"iter": state})
        if kill_after and n >= kill_after:
            # hard preemption INSIDE the streaming loop: the prefetch
            # producer is mid-decode on the next batches right now
            os.kill(os.getpid(), signal.SIGKILL)
    if out:
        np.savez(out, crcs=np.asarray(crcs, np.uint64),
                 labels=np.asarray(labels, np.int64),
                 __start__=np.asarray(start))
    return 0


if __name__ == "__main__":
    sys.exit(main())
