"""Metric tests (parity model: tests/python/unittest/test_metric.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric


def test_accuracy():
    m = metric.Accuracy()
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert acc == pytest.approx(2.0 / 3)
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk():
    m = metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.6, 0.3, 0.1]])
    label = mx.nd.array([1, 1])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(1.0)  # both labels in top-2
    m.reset()
    label = mx.nd.array([1, 2])  # row1 top-2 = {0,1}, misses 2
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_f1_mcc():
    pred = mx.nd.array([[0.7, 0.3], [0.2, 0.8], [0.1, 0.9], [0.6, 0.4]])
    label = mx.nd.array([0, 1, 1, 1])
    f1 = metric.F1()
    f1.update([label], [pred])
    # tp=2 fp=0 fn=1 -> p=1, r=2/3, f1=0.8
    assert f1.get()[1] == pytest.approx(0.8)
    mcc = metric.MCC()
    mcc.update([label], [pred])
    assert -1 <= mcc.get()[1] <= 1


def test_mae_mse_rmse():
    pred = mx.nd.array([1.0, 2.0, 3.0])
    label = mx.nd.array([1.5, 2.0, 2.5])
    mae = metric.MAE()
    mae.update([label], [pred])
    assert mae.get()[1] == pytest.approx(1.0 / 3)
    mse = metric.MSE()
    mse.update([label], [pred])
    assert mse.get()[1] == pytest.approx(0.25 * 2 / 3)
    rmse = metric.RMSE()
    rmse.update([label], [pred])
    assert rmse.get()[1] == pytest.approx(np.sqrt(0.25 * 2 / 3))


def test_perplexity_crossentropy():
    pred = mx.nd.array([[0.25, 0.75], [0.5, 0.5]])
    label = mx.nd.array([1, 0])
    ce = metric.CrossEntropy()
    ce.update([label], [pred])
    expect = -(np.log(0.75) + np.log(0.5)) / 2
    assert ce.get()[1] == pytest.approx(expect, rel=1e-5)
    ppl = metric.Perplexity()
    ppl.update([label], [pred])
    assert ppl.get()[1] == pytest.approx(np.exp(expect), rel=1e-5)


def test_pearson():
    m = metric.PearsonCorrelation()
    pred = mx.nd.array([1.0, 2.0, 3.0, 4.0])
    label = mx.nd.array([2.0, 4.0, 6.0, 8.0])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(1.0)


def test_composite_create_custom():
    comp = metric.create(["acc", "mae"])
    assert isinstance(comp, metric.CompositeEvalMetric)
    pred = mx.nd.array([[0.3, 0.7]])
    label = mx.nd.array([1])
    comp.update([label], [pred])
    names, values = comp.get()
    assert "accuracy" in names and "mae" in names

    custom = metric.np(lambda label, pred: float(np.abs(label - pred.argmax(1)).sum()))
    custom.update([label], [pred])
    assert custom.get()[1] == 0.0

    m = metric.create("acc")
    assert isinstance(m, metric.Accuracy)
    with pytest.raises(ValueError):
        metric.create("unknown_metric")


def test_loss_metric():
    m = metric.Loss()
    m.update(None, [mx.nd.array([1.0, 2.0, 3.0])])
    assert m.get()[1] == pytest.approx(2.0)


def test_accuracy_column_labels():
    m = metric.Accuracy()
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1]])
    label = mx.nd.array([[1], [0]])  # (N,1) column labels
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(1.0)


def test_perplexity_axis():
    pred = mx.nd.array(np.moveaxis(np.array([[[0.25, 0.75], [0.5, 0.5]]]), -1, 1))
    label = mx.nd.array([[1, 0]])
    ppl = metric.Perplexity(axis=1)
    ppl.update([label], [pred])
    expect = np.exp(-(np.log(0.75) + np.log(0.5)) / 2)
    assert ppl.get()[1] == pytest.approx(expect, rel=1e-5)
