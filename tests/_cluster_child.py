"""Long-lived trainer-gang worker for the cluster control-plane drills
(tools/chaos_smoke.py phase 16 and tests/test_cluster.py).

One rank of a ``cluster.json`` trainer-gang: heartbeats ride the normal
worker-side arming (``MXTPU_GANG_DIR`` is set by the supervisor, so
importing :mod:`mxnet_tpu` starts the daemon), SIGTERM drains gracefully
through :mod:`mxnet_tpu.preempt` (exit 75), and — when the spec wires
``publish_to`` a model-bus role (``MXTPU_MODELBUS_DIR``) — rank 0 streams
live "weights" into the bus: the deterministic serving demo model's
params plus a per-step drift, so a serving-fleet role subscribed to the
same bus applies real version updates while this gang trains. The drill
kills the SUPERVISOR, not this child — the child's job is to stay busy
and observable.

Env knobs (CC_* are this child's; MXTPU_* come from the supervisor):

    CC_TOTAL          steps before a clean exit 0 (default 100000 —
                      effectively "run until drained")
    CC_STEP_SLEEP     seconds per step (default 0.05)
    CC_SEED           demo-model seed — MUST match the serving role's
                      model dir spec seed (default 777)
    CC_PUBLISH_EVERY  bus publish cadence in steps (default 5; 0 = never)
    CC_DELTA          per-step param drift magnitude (default 0.01)
"""
import os
import sys
import time

# this gang's mesh is process-local — see tests/_gang_child.py
os.environ.pop("MXTPU_COORDINATOR", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import mxnet_tpu  # noqa: E402,F401  (arms heartbeat via MXTPU_GANG_DIR)
from mxnet_tpu import preempt  # noqa: E402


def main():
    total = int(os.environ.get("CC_TOTAL", "100000"))
    sleep_s = float(os.environ.get("CC_STEP_SLEEP", "0.05") or 0.05)
    rank = int(os.environ.get("MXTPU_WORKER_ID", "0") or 0)
    every = int(os.environ.get("CC_PUBLISH_EVERY", "5") or 0)
    bus_dir = os.environ.get("MXTPU_MODELBUS_DIR")
    preempt.install()
    gang_dir = os.environ.get("MXTPU_GANG_DIR")
    if gang_dir:
        # the heartbeat daemon arms EARLY in the mxnet_tpu import, well
        # before install() above — a SIGTERM in that window kills
        # instead of draining, so drills that want a drainable worker
        # must wait for this marker, not for the first heartbeat
        with open(os.path.join(gang_dir, f"armed-{rank}"), "w") as f:
            f.write(str(os.getpid()))

    params = None
    bus = None
    if bus_dir and rank == 0 and every > 0:
        from mxnet_tpu.modelbus import ModelBus
        from mxnet_tpu.serving import worker as worker_mod

        seed = int(os.environ.get("CC_SEED", "777"))
        net = worker_mod.build_demo_model(seed)
        params = [(name, p.data().asnumpy())
                  for name, p in net.collect_params().items()]
        bus = ModelBus(bus_dir)

    delta = float(os.environ.get("CC_DELTA", "0.01"))
    published = 0
    for step in range(1, total + 1):
        if preempt.requested():
            preempt.drain(save=False)  # SystemExit(75)
        if bus is not None and step % every == 0:
            version = bus.publish(
                [(name, arr + delta * step) for name, arr in params],
                step=step, model="model0")
            if version is not None:
                published += 1
        time.sleep(sleep_s)
    print(f"CLUSTER_CHILD_DONE rank={rank} steps={total} "
          f"published={published}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
