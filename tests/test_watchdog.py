"""Watchdog subsystem: hang detection, deadline-bounded syncs, crash
bundles (mxnet_tpu/watchdog.py + the `hang` fault mode).

Acceptance (ISSUE 4): a deterministically injected hang at each of the
four instrumented point classes — data fetch (io.fetch), engine flush
(engine.flush), trainer step (trainer.step), host sync (host.sync) — is
detected within the configured deadline, writes a crash bundle containing
all-thread tracebacks plus the last-N heartbeats, and surfaces as a
catchable StallError (or checkpoint-then-abort when configured).
"""
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, watchdog

# hang long enough that only the watchdog can end the wait inside the
# deadline, short enough that abandoned daemon waiters drain quickly
HANG = 3.0
DEADLINE = 0.5


@pytest.fixture(autouse=True)
def _restore():
    """Every test leaves the ambient (conftest observe-mode) config and a
    clean fault schedule behind."""
    yield
    faults.reset()
    watchdog.configure_from_env()


def _configure(tmp_path, point, deadline=DEADLINE, **opts):
    watchdog.configure({point: deadline}, crash_dir=str(tmp_path),
                       interval=0.05, **opts)


def _check_bundle(path):
    """Bundle completeness: all-thread tracebacks + heartbeats + report."""
    assert path and os.path.isdir(path)
    names = set(os.listdir(path))
    assert {"threads.txt", "heartbeats.json", "report.json",
            "sanitize.json"} <= names
    tb = open(os.path.join(path, "threads.txt")).read()
    assert "Thread" in tb and "File" in tb  # faulthandler all-thread dump
    beats = json.load(open(os.path.join(path, "heartbeats.json")))
    assert beats, "bundle must carry the last-N heartbeats"
    assert all({"t_mono", "point", "thread"} <= set(b) for b in beats)
    rep = json.load(open(os.path.join(path, "report.json")))
    assert rep["deadline_s"] == pytest.approx(DEADLINE)
    assert rep["elapsed_s"] >= DEADLINE
    assert "faults" in rep and "live_bulk_segments" in rep
    return rep


# ------------------------------------------------------------- grammar ----

def test_grammar_parsing():
    cfg = watchdog._parse("trainer.step:120,io.fetch:30;*:600,"
                          "action:abort,warn:0.25,interval:2,"
                          "dir:/tmp/x,beats:64")
    assert cfg.deadlines == {"trainer.step": 120.0, "io.fetch": 30.0}
    assert cfg.default == 600.0
    assert cfg.action == "abort"
    assert cfg.warn_fraction == 0.25
    assert cfg.interval == 2.0
    assert cfg.crash_dir == "/tmp/x"
    assert cfg.beats == 64
    assert cfg.deadline_for("trainer.step") == 120.0
    assert cfg.deadline_for("anything.else") == 600.0


@pytest.mark.parametrize("bad", ["trainer.step", "action:bogus", "x:,",
                                 "action:raise"])
def test_grammar_rejects(bad):
    with pytest.raises(ValueError):
        watchdog._parse(bad)


def test_configure_dict_and_options(tmp_path):
    watchdog.configure({"host.sync": 9}, action="observe",
                       crash_dir=str(tmp_path))
    d = watchdog.describe()
    assert d["enabled"] and d["deadlines"] == {"host.sync": 9.0}
    assert d["action"] == "observe" and d["crash_dir"] == str(tmp_path)
    watchdog.configure(None)
    assert watchdog.describe() == {"enabled": False}


def test_disabled_sync_is_transparent():
    watchdog.configure(None)
    assert watchdog.sync("host.sync", lambda: 41) == 41
    with pytest.raises(KeyError):
        watchdog.sync("host.sync", lambda: {}["missing"])
    assert not watchdog.enabled()


# ------------------------------------------- the four hang point classes ---

def test_hang_host_sync_detected(tmp_path):
    _configure(tmp_path, "host.sync")
    faults.configure(f"host.sync:hang@1:{HANG}")
    a = mx.nd.ones((2, 2))
    t0 = time.monotonic()
    with pytest.raises(watchdog.StallError) as ei:
        a.wait_to_read()
    elapsed = time.monotonic() - t0
    assert elapsed < HANG, "the watchdog, not the hang, ended the wait"
    assert elapsed < DEADLINE * 3
    err = ei.value
    assert err.point == "host.sync" and err.deadline == DEADLINE
    rep = _check_bundle(err.bundle)
    assert rep["point"] == "host.sync"
    # the stalled span shows up in the bundle's active-span snapshot
    assert any(s["point"] == "host.sync" for s in rep["active_spans"])


def test_hang_engine_flush_detected(tmp_path):
    _configure(tmp_path, "engine.flush")
    faults.configure(f"engine.flush:hang@1:{HANG}")
    t0 = time.monotonic()
    with pytest.raises(watchdog.StallError) as ei:
        mx.nd.waitall()
    assert time.monotonic() - t0 < HANG
    _check_bundle(ei.value.bundle)


def test_hang_bulk_segment_flush_detected(tmp_path):
    """A hang inside a fused bulk-segment flush stalls at the sync point
    and stays sticky on the segment (deferred-exception contract)."""
    _configure(tmp_path, "engine.flush")
    faults.configure(f"engine.flush:hang@1:{HANG}")
    with mx.engine.bulk(8):
        a = mx.nd.ones((4,))
        b = a + 1
        c = b * 2
        with pytest.raises(watchdog.StallError) as ei:
            c.asnumpy()  # forces the segment
        _check_bundle(ei.value.bundle)
        # sticky: a second force re-raises without re-executing
        with pytest.raises(watchdog.StallError):
            c.asnumpy()


def test_hang_io_fetch_detected(tmp_path):
    _configure(tmp_path, "io.fetch")
    faults.configure(f"io.fetch:hang@1:{HANG}")
    base = mx.io.NDArrayIter(np.arange(48, dtype=np.float32).reshape(12, 4),
                             np.arange(12, dtype=np.float32), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    t0 = time.monotonic()
    with pytest.raises(watchdog.StallError) as ei:
        it.next()
    assert time.monotonic() - t0 < HANG
    rep = _check_bundle(ei.value.bundle)
    assert rep["point"] == "io.fetch"
    # sticky until reset(): the staged state is torn
    with pytest.raises(watchdog.StallError):
        it.next()
    # reset() abandons the wedged daemon worker and recovers cleanly
    it.reset()
    batch = it.next()
    assert batch.data[0].shape == (4, 4)


def test_hang_trainer_step_detected(tmp_path):
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import ShardedTrainer

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    y = mx.nd.array(np.random.RandomState(1).randn(8, 2).astype(np.float32))
    net(x)
    trainer = ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                             {"learning_rate": 0.1})
    trainer.step(x, y)  # compile OUTSIDE the deadline window
    _configure(tmp_path, "trainer.step")
    faults.configure(f"trainer.step:hang@1:{HANG}")
    t0 = time.monotonic()
    with pytest.raises(watchdog.StallError) as ei:
        trainer.step(x, y)
    assert time.monotonic() - t0 < HANG
    rep = _check_bundle(ei.value.bundle)
    assert rep["point"] == "trainer.step"
    # the abandoned waiter finishes in the background; drain it before
    # touching the trainer again, then training continues
    faults.reset()
    watchdog.configure(None)
    time.sleep(HANG + 0.5)
    loss = trainer.step(x, y)
    assert np.isfinite(loss.asnumpy()).all()


# ----------------------------------------------------- escalation ladder ---

def test_injected_fault_propagates_through_bounded_sync(tmp_path):
    """A raise-mode fault inside a bounded sync surfaces as InjectedFault,
    not StallError — the waiter relays the real error."""
    _configure(tmp_path, "engine.flush")
    faults.configure("engine.flush:raise@1")
    with pytest.raises(faults.InjectedFault):
        mx.nd.waitall()
    mx.nd.waitall()  # schedule consumed; clean barrier works


def test_observe_mode_bundles_without_raising(tmp_path):
    """action:observe — the monitor writes the bundle; nothing raises and
    the caller's result survives (the CI conftest configuration)."""
    _configure(tmp_path, "engine.flush", action="observe")
    faults.configure(f"engine.flush:delay@1:{DEADLINE * 2.5}")
    mx.nd.waitall()  # blocks past the deadline but completes normally
    bundle = watchdog.latest_bundle(str(tmp_path))
    assert bundle is not None
    rep = json.load(open(os.path.join(bundle, "report.json")))
    assert rep["point"] == "engine.flush"


def test_warning_fires_before_stall(tmp_path, caplog):
    import logging

    _configure(tmp_path, "host.sync")
    faults.configure(f"host.sync:hang@1:{HANG}")
    a = mx.nd.ones((2,))
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.watchdog"):
        with pytest.raises(watchdog.StallError):
            a.wait_to_read()
    msgs = [r.message for r in caplog.records]
    assert any("has been blocking" in m for m in msgs), msgs
    assert any("crash bundle written" in m for m in msgs), msgs


def test_abort_action_runs_last_resort_checkpoint(tmp_path, monkeypatch):
    """action:abort — last-resort checkpoint hook runs, then the process
    exit hook fires with the watchdog's dedicated code."""
    exits = []
    monkeypatch.setattr(watchdog, "_exit_fn",
                        lambda code: exits.append(code))
    saved = []
    watchdog.set_last_resort(lambda: saved.append(True))
    try:
        _configure(tmp_path, "host.sync", action="abort")
        faults.configure(f"host.sync:hang@1:{HANG}")
        a = mx.nd.ones((2,))
        # the stubbed exit returns, so sync falls through to StallError —
        # in production os._exit(86) never returns
        with pytest.raises(watchdog.StallError):
            a.wait_to_read()
    finally:
        watchdog.set_last_resort(None)
    assert saved == [True], "final checkpoint hook must run before abort"
    assert exits == [watchdog.ABORT_EXIT_CODE]
    assert watchdog.latest_bundle(str(tmp_path)) is not None


# ------------------------------------------------------------ heartbeats ---

def test_heartbeats_recorded_with_labels(tmp_path):
    _configure(tmp_path, "engine.flush", deadline=30)
    mx.nd.waitall()
    beats = watchdog.heartbeats()
    points = {b["point"] for b in beats}
    assert "engine.flush" in points
    labels = {b["label"] for b in beats if b["point"] == "engine.flush"}
    assert any(lb and "wait_all" in lb for lb in labels)
    assert all(b["t_mono"] <= time.monotonic() for b in beats)


def test_kvstore_points_report_liveness(tmp_path):
    _configure(tmp_path, "engine.flush", deadline=30)  # enables beats
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((3,)))
    kv.push("w", mx.nd.ones((3,)))
    out = mx.nd.zeros((3,))
    kv.pull("w", out=out)
    points = {b["point"] for b in watchdog.heartbeats()}
    assert {"kvstore.push", "kvstore.pull"} <= points


# --------------------------------------------- PrefetchingIter recovery ----

def test_prefetch_error_sticky_until_reset():
    """Satellite: a deferred worker error is sticky until reset(), and
    reset() restages the fetch cleanly."""
    faults.configure("io.fetch:raise@2")
    base = mx.io.NDArrayIter(np.arange(32, dtype=np.float32).reshape(8, 4),
                             np.arange(8, dtype=np.float32), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    it.next()  # batch 1 ok (fetch 2 staged in background -> fires)
    with pytest.raises(faults.InjectedFault):
        it.next()
    # sticky: no restaged fetch, same error again — not a stale batch
    with pytest.raises(faults.InjectedFault):
        it.next()
    faults.reset()
    it.reset()
    batch = it.next()
    assert batch.data[0].shape == (4, 4)


def test_prefetch_workers_are_daemons():
    base = mx.io.NDArrayIter(np.arange(32, dtype=np.float32).reshape(8, 4),
                             np.arange(8, dtype=np.float32), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    it.iter_next()
    assert it._threads, "a fetch must be staged"
    assert all(t.daemon for t in it._threads), \
        "hung fetch threads must never block interpreter exit"


# ------------------------------------------------------- retry deadline ----

def test_retry_deadline_caps_total_elapsed():
    """Satellite: retry() stops on the elapsed-time cap, not only on the
    attempt cap — a retry storm cannot itself become a hang."""
    calls = []

    def always_fails():
        calls.append(1)
        raise OSError("flaky")

    t0 = time.monotonic()
    with pytest.raises(OSError):
        faults.retry(always_fails, retries=1000, backoff=0.02,
                     deadline=0.25)()
    assert time.monotonic() - t0 < 1.0
    assert 1 < len(calls) < 20, "deadline, not attempt count, must stop it"


def test_retry_deadline_none_keeps_attempt_semantics():
    calls = []

    def fails_twice():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("flaky")
        return "ok"

    assert faults.retry(fails_twice, retries=3, backoff=0.001)() == "ok"
    assert len(calls) == 3


# -------------------------------------------------------------- tooling ----

def test_latest_bundle_and_crash_dir(tmp_path):
    assert watchdog.latest_bundle(str(tmp_path / "nope")) is None
    _configure(tmp_path, "host.sync")
    faults.configure(f"host.sync:hang@1:{HANG}")
    a = mx.nd.ones((2,))
    with pytest.raises(watchdog.StallError) as ei:
        a.wait_to_read()
    assert watchdog.latest_bundle(str(tmp_path)) == ei.value.bundle
    assert watchdog.crash_dir() == str(tmp_path)


def test_hang_fault_mode_without_watchdog_just_delays():
    """`hang` with a short arg and no watchdog behaves like a long delay —
    the library is wedged exactly as a real stall would be."""
    watchdog.configure(None)
    faults.configure("host.sync:hang@1:0.3")
    a = mx.nd.ones((2,))
    t0 = time.monotonic()
    a.wait_to_read()
    assert time.monotonic() - t0 >= 0.25


def test_profiler_counts_stalls(tmp_path):
    from mxnet_tpu import profiler

    profiler.set_config(filename=str(tmp_path / "prof.json"))
    profiler.set_state("run")
    try:
        _configure(tmp_path, "host.sync")
        faults.configure(f"host.sync:hang@1:{HANG}")
        a = mx.nd.ones((2,))
        with pytest.raises(watchdog.StallError):
            a.wait_to_read()
    finally:
        profiler.set_state("stop")
    profiler.dump()
    trace = json.load(open(str(tmp_path / "prof.json")))
    names = {e["name"] for e in trace["traceEvents"]}
    assert "watchdog.stall" in names and "watchdog.stalls" in names
    profiler.reset()
