"""Operator correctness tests.

Parity model: tests/python/unittest/test_operator.py — forward vs numpy
oracle, backward vs central finite differences (check_numeric_gradient),
shapes/dtypes, multi-output ops.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  simple_forward)


def test_elemwise_unary_forward():
    x = np.random.rand(3, 4).astype(np.float32) + 0.5
    cases = {
        "sqrt": np.sqrt, "square": np.square, "exp": np.exp, "log": np.log,
        "abs": np.abs, "sign": np.sign, "floor": np.floor, "ceil": np.ceil,
        "round": np.round, "rsqrt": lambda a: 1 / np.sqrt(a),
        "reciprocal": lambda a: 1 / a, "cbrt": np.cbrt,
        "log2": np.log2, "log10": np.log10, "log1p": np.log1p,
        "expm1": np.expm1, "sin": np.sin, "cos": np.cos, "tan": np.tan,
        "sinh": np.sinh, "cosh": np.cosh, "tanh": np.tanh,
        "arcsin": lambda a: np.arcsin(a - 0.5), "arctan": np.arctan,
        "sigmoid": lambda a: 1 / (1 + np.exp(-a)),
        "relu": lambda a: np.maximum(a, 0),
        "softsign": lambda a: a / (1 + np.abs(a)),
        "erf": None, "gamma": None, "gammaln": None, "erfinv": None,
    }
    for name, ref in cases.items():
        if name == "arcsin":
            out = simple_forward(name, x - 0.5)
            assert_almost_equal(out, ref(x), names=(name, "numpy"))
            continue
        out = simple_forward(name, x)
        if ref is not None:
            assert_almost_equal(out, ref(x), names=(name, "numpy"))
        else:
            assert out.shape == x.shape


def test_elemwise_binary_forward():
    a = np.random.rand(3, 4).astype(np.float32) + 0.5
    b = np.random.rand(3, 4).astype(np.float32) + 0.5
    for name, ref in {
        "elemwise_add": np.add, "elemwise_sub": np.subtract,
        "elemwise_mul": np.multiply, "elemwise_div": np.divide,
        "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
        "broadcast_hypot": np.hypot, "broadcast_power": np.power,
    }.items():
        assert_almost_equal(simple_forward(name, a, b), ref(a, b),
                            names=(name, "numpy"))


def test_numeric_gradients():
    x = np.random.rand(2, 3) + 0.5
    for op in ["sqrt", "exp", "log", "sigmoid", "tanh", "square"]:
        check_numeric_gradient(op, [x])
    check_numeric_gradient("broadcast_mul", [x, np.random.rand(2, 3) + 0.5])
    check_numeric_gradient("dot", [np.random.rand(2, 3), np.random.rand(3, 2)])


def test_fully_connected():
    data = np.random.rand(4, 10).astype(np.float32)
    w = np.random.rand(5, 10).astype(np.float32)
    b = np.random.rand(5).astype(np.float32)
    out = simple_forward("FullyConnected", data, w, b, num_hidden=5)
    assert_almost_equal(out, data @ w.T + b, rtol=1e-3, atol=1e-4)
    out = simple_forward("FullyConnected", data, w, num_hidden=5, no_bias=True)
    assert_almost_equal(out, data @ w.T, rtol=1e-3, atol=1e-4)


def test_convolution_shapes():
    # NCHW conv, kernel 3x3, pad 1: same spatial dims
    data = np.random.rand(2, 3, 8, 8).astype(np.float32)
    w = np.random.rand(4, 3, 3, 3).astype(np.float32)
    b = np.zeros(4, np.float32)
    out = simple_forward("Convolution", data, w, b, kernel=(3, 3), pad=(1, 1),
                         num_filter=4)
    assert out.shape == (2, 4, 8, 8)
    out = simple_forward("Convolution", data, w, b, kernel=(3, 3), stride=(2, 2),
                         num_filter=4)
    assert out.shape == (2, 4, 3, 3)


def test_convolution_vs_naive():
    # tiny conv checked against explicit loops
    data = np.random.rand(1, 1, 4, 4).astype(np.float32)
    w = np.random.rand(1, 1, 2, 2).astype(np.float32)
    out = simple_forward("Convolution", data, w, np.zeros(1, np.float32),
                         kernel=(2, 2), num_filter=1)
    ref = np.zeros((1, 1, 3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            ref[0, 0, i, j] = (data[0, 0, i:i + 2, j:j + 2] * w[0, 0]).sum()
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_pooling():
    data = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = simple_forward("Pooling", data, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    assert_almost_equal(out, np.array([[[[5, 7], [13, 15]]]], np.float32))
    out = simple_forward("Pooling", data, kernel=(2, 2), stride=(2, 2),
                         pool_type="avg")
    assert_almost_equal(out, np.array([[[[2.5, 4.5], [10.5, 12.5]]]], np.float32))
    out = simple_forward("Pooling", data, global_pool=True, pool_type="avg",
                         kernel=(2, 2))
    assert out.shape == (1, 1, 1, 1)
    assert out[0, 0, 0, 0] == pytest.approx(7.5)


def test_softmax():
    x = np.random.rand(3, 5).astype(np.float32)
    out = simple_forward("softmax", x)
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    assert_almost_equal(out, e / e.sum(axis=-1, keepdims=True))
    assert_almost_equal(simple_forward("log_softmax", x),
                        np.log(e / e.sum(axis=-1, keepdims=True)),
                        rtol=1e-4, atol=1e-5)


def test_batchnorm_inference_and_training():
    x = np.random.rand(4, 3, 5, 5).astype(np.float32)
    gamma = np.random.rand(3).astype(np.float32)
    beta = np.random.rand(3).astype(np.float32)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    out = simple_forward("BatchNorm", x, gamma, beta, mean, var,
                         use_global_stats=True, fix_gamma=False)
    if isinstance(out, tuple):
        out = out[0]
    ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-3)
    ref = ref * gamma[None, :, None, None] + beta[None, :, None, None]
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


def test_layernorm():
    x = np.random.rand(4, 10).astype(np.float32)
    g = np.ones(10, np.float32)
    b = np.zeros(10, np.float32)
    out = simple_forward("LayerNorm", x, g, b)
    if isinstance(out, tuple):
        out = out[0]
    mu = x.mean(-1, keepdims=True)
    sd = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    assert_almost_equal(out, (x - mu) / sd, rtol=1e-3, atol=1e-4)


def test_activation():
    x = np.random.randn(3, 4).astype(np.float32)
    for act, ref in {
        "relu": lambda a: np.maximum(a, 0),
        "sigmoid": lambda a: 1 / (1 + np.exp(-a)),
        "tanh": np.tanh,
        "softrelu": lambda a: np.log1p(np.exp(a)),
    }.items():
        assert_almost_equal(simple_forward("Activation", x, act_type=act),
                            ref(x), names=(act, "numpy"))


def test_embedding():
    w = np.random.rand(10, 4).astype(np.float32)
    idx = np.array([1, 3, 5], np.float32)
    out = simple_forward("Embedding", idx, w, input_dim=10, output_dim=4)
    assert_almost_equal(out, w[[1, 3, 5]])


def test_transpose_slice_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    assert_almost_equal(simple_forward("transpose", x, axes=(2, 0, 1)),
                        x.transpose(2, 0, 1))
    assert_almost_equal(
        simple_forward("slice", x, begin=(0, 1, 0), end=(2, 3, 2)),
        x[0:2, 1:3, 0:2])
    assert_almost_equal(
        simple_forward("slice_axis", x, axis=1, begin=1, end=3), x[:, 1:3])
    assert_almost_equal(simple_forward("flip", x, axis=1), x[:, ::-1])
    assert_almost_equal(simple_forward("tile", x, reps=(1, 2, 1)),
                        np.tile(x, (1, 2, 1)))


def test_where_clip_maximum():
    cond = np.array([1, 0, 1], np.float32)
    a = np.array([1, 2, 3], np.float32)
    b = np.array([10, 20, 30], np.float32)
    assert_almost_equal(simple_forward("where", cond, a, b),
                        np.where(cond > 0, a, b))
    x = np.array([-2, 0.5, 3], np.float32)
    assert_almost_equal(simple_forward("clip", x, a_min=-1, a_max=1),
                        np.clip(x, -1, 1))


def test_topk_sort():
    x = np.array([[3, 1, 2], [0, 5, 4]], np.float32)
    out = simple_forward("topk", x, k=2, ret_typ="value")
    assert_almost_equal(out, np.array([[3, 2], [5, 4]], np.float32))
    assert_almost_equal(simple_forward("sort", x), np.sort(x))
    assert_almost_equal(simple_forward("argsort", x), np.argsort(x))


def test_gather_scatter():
    x = np.random.rand(3, 4).astype(np.float32)
    idx = np.array([[0, 2], [1, 3]], np.float32)
    out = simple_forward("gather_nd", x, idx)
    assert_almost_equal(out, x[[0, 2], [1, 3]])


def test_batch_dot():
    a = np.random.rand(4, 2, 3).astype(np.float32)
    b = np.random.rand(4, 3, 5).astype(np.float32)
    assert_almost_equal(simple_forward("batch_dot", a, b),
                        np.einsum("bij,bjk->bik", a, b), rtol=1e-3, atol=1e-4)


def test_sequence_mask():
    x = np.ones((4, 2, 3), np.float32)  # (seq, batch, feat)
    lens = np.array([2, 4], np.float32)
    out = simple_forward("SequenceMask", x, lens, use_sequence_length=True,
                         value=0.0)
    assert out[2:, 0].sum() == 0
    assert out[:, 1].sum() == 12


def test_optimizer_ops():
    w = np.random.rand(5).astype(np.float32)
    g = np.random.rand(5).astype(np.float32)
    out = simple_forward("sgd_update", w, g, lr=0.1, wd=0.0)
    assert_almost_equal(out, w - 0.1 * g)
    # momentum
    mom = np.zeros(5, np.float32)
    out_w, out_m = simple_forward("sgd_mom_update", w, g, mom, lr=0.1,
                                  momentum=0.9, wd=0.0)
    assert_almost_equal(out_m, -0.1 * g)
    assert_almost_equal(out_w, w - 0.1 * g)
    # adam
    m = np.zeros(5, np.float32)
    v = np.zeros(5, np.float32)
    out = simple_forward("adam_update", w, g, m, v, lr=0.01, beta1=0.9,
                         beta2=0.999, epsilon=1e-8, wd=0.0)
    assert len(out) == 3


def test_dropout_modes():
    x = mx.nd.ones((100, 100))
    key = mx.nd.NDArray(mx.random.next_key())
    out = mx.nd.invoke("Dropout", x, key, p=0.5, training=True)
    if isinstance(out, tuple):
        out = out[0]
    # prediction: identity without a key
    ident = mx.nd.invoke("Dropout", x, p=0.5, training=False)
    assert ident.asnumpy().sum() == 100 * 100
    # roughly half zeroed, survivors scaled by 2
    frac = (out.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


def test_random_ops():
    mx.random.seed(42)
    u = mx.nd.random.uniform(0.0, 1.0, shape=(1000,))
    arr = u.asnumpy()
    assert arr.min() >= 0 and arr.max() <= 1
    assert 0.4 < arr.mean() < 0.6
    n = mx.nd.random.normal(0.0, 1.0, shape=(2000,))
    assert abs(n.asnumpy().mean()) < 0.2
    # seeding reproduces streams (parity: mx.random.seed)
    mx.random.seed(7)
    a = mx.nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert (a == b).all()


def test_multi_device_consistency():
    """parity: check_consistency across ctxs (test_utils.py:1546)."""
    from mxnet_tpu.test_utils import check_consistency

    check_consistency(lambda a, b: mx.nd.dot(a, b), [(3, 4), (4, 5)])
    check_consistency(lambda a: a.sigmoid().sum() * 2, [(6, 6)])


def test_legacy_tail_ops():
    """batch_take/diag/split_v2/UpSampling/Crop/relu6/fill_element_0index/
    unravel+ravel/multi_sum_sq/digamma (parity: indexing_op.cc,
    diag_op.cc, matrix_op.cc split_v2, upsampling.cc, crop.cc)."""
    a = mx.nd.array(np.arange(12, dtype="float32").reshape(3, 4))
    assert mx.nd.batch_take(a, mx.nd.array([1, 2, 0])).asnumpy().tolist() \
        == [1.0, 6.0, 8.0]
    np.testing.assert_allclose(
        mx.nd.diag(mx.nd.array([1.0, 2.0])).asnumpy(),
        np.diag([1.0, 2.0]))
    p = mx.nd.split_v2(a, sections=2, axis=1)
    assert p[0].shape == (3, 2) and p[1].shape == (3, 2)
    p2 = mx.nd.split_v2(a, indices=(1, 3), axis=1)
    assert [x.shape[1] for x in p2] == [1, 2, 1]
    u = mx.nd.UpSampling(mx.nd.array(np.arange(4, dtype="f").reshape(
        1, 1, 2, 2)), scale=2)
    assert u.shape == (1, 1, 4, 4)
    assert u.asnumpy()[0, 0, 0, 1] == 0.0  # nearest: repeated
    c = mx.nd.Crop(mx.nd.ones((1, 1, 8, 8)), h_w=(4, 4), center_crop=True)
    assert c.shape == (1, 1, 4, 4)
    assert mx.nd.relu6(mx.nd.array([-1.0, 8.0])).asnumpy().tolist() \
        == [0.0, 6.0]
    fl = mx.nd.fill_element_0index(
        a.copy(), mx.nd.array([9.0, 9.0, 9.0]), mx.nd.array([0, 1, 2]))
    assert fl.asnumpy()[1, 1] == 9
    ui = mx.nd.unravel_index(mx.nd.array([5, 7], dtype="int32"),
                             shape=(3, 4))
    assert ui.asnumpy().tolist() == [[1, 1], [1, 3]]
    ri = mx.nd.ravel_multi_index(mx.nd.array([[1, 1], [1, 3]],
                                             dtype="int32"), shape=(3, 4))
    assert ri.asnumpy().tolist() == [5, 7]
    s2 = mx.nd.multi_sum_sq(mx.nd.array([3.0, 4.0]), mx.nd.array([1.0]))
    assert s2.shape == (2,)  # ONE output vector (contrib/multi_sum_sq.cc)
    assert s2.asnumpy().tolist() == [25.0, 1.0]
    # multi-input nearest upsampling: inputs scaled to a common size, then
    # channel-concatenated (upsampling.cc multi_input_mode='concat')
    um = mx.nd.UpSampling(mx.nd.ones((1, 1, 2, 2)), mx.nd.ones((1, 2, 4, 4)),
                          scale=2, num_args=2)
    assert um.shape == (1, 3, 4, 4)
    assert float(mx.nd.digamma(mx.nd.array([1.0])).asscalar()) < 0


def test_multi_tensor_optimizer_ops():
    """multi_sgd/preloaded/multi_lamb/adamw families (parity:
    optimizer_op.cc MultiSGDUpdate, contrib/adamw.cc, multi_lamb.cc)."""
    rs = np.random.RandomState(0)
    w1, g1 = rs.rand(3).astype("f"), rs.rand(3).astype("f")
    w2, g2 = rs.rand(2).astype("f"), rs.rand(2).astype("f")
    o = mx.nd.multi_sgd_update(mx.nd.array(w1), mx.nd.array(g1),
                               mx.nd.array(w2), mx.nd.array(g2),
                               lrs=(0.1, 0.2), wds=(0.0, 0.0),
                               num_weights=2)
    np.testing.assert_allclose(o[0].asnumpy(), w1 - 0.1 * g1, rtol=1e-5)
    np.testing.assert_allclose(o[1].asnumpy(), w2 - 0.2 * g2, rtol=1e-5)
    op = mx.nd.preloaded_multi_sgd_update(
        mx.nd.array(w1), mx.nd.array(g1), mx.nd.array(w2), mx.nd.array(g2),
        mx.nd.array([0.1, 0.2]), mx.nd.array([0.0, 0.0]), num_weights=2)
    np.testing.assert_allclose(op[0].asnumpy(), o[0].asnumpy(), rtol=1e-6)

    # adamw: loss-scale skip contract — non-finite rescale = no update
    w = mx.nd.array(rs.rand(4).astype("f"))
    g = mx.nd.array(rs.rand(4).astype("f"))
    m, v = mx.nd.zeros((4,)), mx.nd.zeros((4,))
    upd = mx.nd.adamw_update(w, g, m, v, mx.nd.array([1.0]), lr=0.1)
    assert not np.allclose(upd[0].asnumpy(), w.asnumpy())
    skip = mx.nd.adamw_update(w, g, m, v, mx.nd.array([np.inf]), lr=0.1)
    np.testing.assert_allclose(skip[0].asnumpy(), w.asnumpy())

    ml = mx.nd.multi_lamb_update(
        mx.nd.array(w1), mx.nd.array(g1), mx.nd.zeros((3,)),
        mx.nd.zeros((3,)), learning_rates=(0.01,), wds=(0.0,),
        step_count=(1,), num_tensors=1)
    assert len(ml) == 3 and not np.allclose(ml[0].asnumpy(), w1)

    # all_finite / reset_arrays / amp_multicast
    assert float(mx.nd.all_finite(mx.nd.array([1.0, 2.0])).asscalar()) == 1
    assert float(mx.nd.all_finite(
        mx.nd.array([1.0, np.inf])).asscalar()) == 0
    z = mx.nd.reset_arrays(mx.nd.ones((2,)), mx.nd.ones((3,)),
                           num_arrays=2)
    assert z[0].asnumpy().sum() == 0 and z[1].asnumpy().sum() == 0
    outs = mx.nd.amp_multicast(mx.nd.ones((2,)).astype("float16"),
                               mx.nd.ones((2,)), num_outputs=2)
    assert str(outs[0].dtype) == "float32"


def test_quantized_op_tail():
    """quantized act/flatten/concat/elemwise/pooling + asym quantize + KL
    calibration (parity: src/operator/quantization/)."""
    rs = np.random.RandomState(1)
    x = rs.randn(2, 4).astype("f")
    q, mn, mxr = mx.nd._contrib_quantize_v2(mx.nd.array(x))
    scale = max(abs(float(mn.asscalar())), abs(float(mxr.asscalar()))) / 127
    deq = mx.nd._contrib_dequantize(q, mn, mxr)
    np.testing.assert_allclose(deq.asnumpy(), x, atol=scale * 1.01)
    a = mx.nd._contrib_quantized_act(q, mn, mxr)
    assert int(a[0].asnumpy().min()) >= 0
    f = mx.nd._contrib_quantized_flatten(q, mn, mxr)
    assert f[0].shape == (2, 4)
    cc = mx.nd._contrib_quantized_concat(q, q, mn, mxr, mn, mxr, dim=1)
    assert cc[0].shape == (2, 8)
    ea = mx.nd._contrib_quantized_elemwise_add(q, q, mn, mxr, mn, mxr)
    np.testing.assert_allclose(
        mx.nd._contrib_dequantize(ea[0], ea[1], ea[2]).asnumpy(),
        2 * x, atol=4 * scale)
    qa = mx.nd._contrib_quantize_asym(mx.nd.array(x))
    assert str(qa[0].dtype) == "int8"
    h, e = mx.nd._histogram(mx.nd.array(x), bin_cnt=32, range=(-3, 3))
    lo, hi = mx.nd._contrib_calibrate_entropy(h, e)
    assert float(hi.asscalar()) > 0 > float(lo.asscalar())


def test_transformer_interleaved_matmuls():
    """parity: contrib/transformer.cc interleaved attention matmuls vs
    einsum oracle."""
    rs = np.random.RandomState(2)
    seq, b, h, d = 5, 2, 3, 4
    qkv = rs.randn(seq, b, 3 * h * d).astype("f")
    att = mx.nd._contrib_interleaved_matmul_selfatt_qk(mx.nd.array(qkv),
                                                       heads=h)
    x = qkv.reshape(seq, b, h, 3, d)
    q, k, v = x[:, :, :, 0], x[:, :, :, 1], x[:, :, :, 2]
    ref = np.einsum("qbhd,kbhd->bhqk", q / np.sqrt(d), k) \
        .reshape(b * h, seq, seq)
    np.testing.assert_allclose(att.asnumpy(), ref, atol=1e-5)
    out = mx.nd._contrib_interleaved_matmul_selfatt_valatt(
        mx.nd.array(qkv), att, heads=h)
    ref_out = np.einsum("bhqk,kbhd->qbhd", ref.reshape(b, h, seq, seq),
                        v).reshape(seq, b, h * d)
    np.testing.assert_allclose(out.asnumpy(), ref_out, atol=1e-5)


def test_box_codec_and_matching():
    anchors = np.array([[[0., 0., 2., 2.], [1., 1., 3., 3.]]], "f")
    dec = mx.nd._contrib_box_decode(mx.nd.array(np.zeros((1, 2, 4), "f")),
                                    mx.nd.array(anchors))
    np.testing.assert_allclose(dec.asnumpy(), anchors, atol=1e-5)
    data = np.array([[[0.9, 0.1], [0.8, 0.75]]], "f")
    rowm, colm = mx.nd._contrib_bipartite_matching(mx.nd.array(data),
                                                   threshold=0.0)
    assert rowm.asnumpy().tolist() == [[0.0, 1.0]]
    assert colm.asnumpy().tolist() == [[0.0, 1.0]]


def test_npi_tail_and_image_ops():
    rs = np.random.RandomState(3)
    np.testing.assert_allclose(mx.nd._npi_hanning(M=5).asnumpy(),
                               np.hanning(5), atol=1e-6)
    assert mx.nd._npi_delete(mx.nd.array([1., 2., 3.]),
                             obj=1).asnumpy().tolist() == [1., 3.]
    parts = mx.nd._npi_hsplit(mx.nd.ones((2, 6)), indices_or_sections=3)
    assert len(parts) == 3 and parts[0].shape == (2, 2)
    assert mx.nd._npi_ediff1d(mx.nd.array([1., 4., 9.]),
                              to_begin=0.0).asnumpy().tolist() == [0., 3., 5.]
    img = mx.nd.array(rs.randint(0, 255, (4, 6, 3)).astype("uint8"))
    t = mx.nd._image_to_tensor(img)
    assert t.shape == (3, 4, 6) and float(t.asnumpy().max()) <= 1.0
    assert mx.nd._image_resize(img, size=(3, 2)).shape == (2, 3, 3)
    assert mx.nd._image_crop(img, x=1, y=1, width=3,
                             height=2).shape == (2, 3, 3)
    # legacy creation + sparse_retain
    assert mx.nd.invoke("_arange", start=0.0, stop=3.0,
                        repeat=2).asnumpy().tolist() == [0, 0, 1, 1, 2, 2]
    sr = mx.nd._sparse_retain(
        mx.nd.array(np.arange(6, dtype="f").reshape(3, 2)),
        mx.nd.array([0, 2]))
    assert sr.asnumpy()[1].tolist() == [0, 0]
    a = rs.rand(3, 3).astype("f")
    a = a @ a.T + 3 * np.eye(3, dtype="f")
    np.testing.assert_allclose(
        mx.nd._linalg_det(mx.nd.array(a)).asnumpy(),
        np.linalg.det(a), rtol=1e-4)


# ------------------------------------------------------ parameter schema ---
# SURVEY §5.6: dmlc::Parameter equivalent (exemplar declaration:
# reference src/operator/control_flow.cc:35-59) — reflected per-op param
# schemas with validation, string coercion, and schema dumps.

def test_schema_unknown_param_structured_error():
    from mxnet_tpu.ops.schema import OpParamError

    x = mx.nd.ones((2, 3))
    with pytest.raises(OpParamError, match="'softmax'.*'axsi'.*axis"):
        mx.nd.invoke("softmax", x, axsi=1)
    # symbolic path: error at COMPOSE time, before any execution
    data = mx.sym.Variable("data")
    with pytest.raises(OpParamError, match="unknown parameter"):
        mx.sym.invoke("softmax", data, axsi=1)


def test_schema_string_coercion():
    """dmlc-style parsing: symbol-JSON/C-ABI string params become typed."""
    x = mx.nd.random.uniform(shape=(1, 3, 8, 8))
    w = mx.nd.random.uniform(shape=(4, 3, 3, 3))
    out = mx.nd.invoke("Convolution", x, w, kernel="(3, 3)",
                       num_filter="4", no_bias="True")
    assert out.shape == (1, 4, 6, 6)


def test_schema_choices_and_range():
    from mxnet_tpu.ops.schema import OpParamError

    x = mx.nd.ones((2, 3))
    with pytest.raises(OpParamError, match="expected one of"):
        mx.nd.invoke("Activation", x, act_type="gelu_bogus")
    with pytest.raises(OpParamError, match="above maximum"):
        mx.nd.invoke("Dropout", x, p=1.5)


def test_schema_dump():
    from mxnet_tpu.ops import registry

    schemas = registry.op_schemas()
    assert len(schemas) == len(registry.list_ops())
    conv = schemas["Convolution"]
    assert "data" in conv["inputs"]
    names = {p["name"]: p for p in conv["params"]}
    assert names["num_filter"]["default"] == 1
    act = {p["name"]: p for p in schemas["Activation"]["params"]}
    assert "relu" in act["act_type"]["choices"]


def test_schema_type_enforcement_and_override_check():
    from mxnet_tpu.ops.schema import OpParamError, OpSchema

    x = mx.nd.random.uniform(shape=(1, 3, 8, 8))
    w = mx.nd.random.uniform(shape=(4, 3, 3, 3))
    with pytest.raises(OpParamError, match="expected tuple"):
        mx.nd.invoke("Convolution", x, w, kernel=3, num_filter=4)
    with pytest.raises(OpParamError, match="expected int"):
        mx.nd.invoke("Convolution", x, w, kernel=(3, 3), num_filter="(4,)")
    # typo'd enrichment keys must fail loudly, not mint new params
    with pytest.raises(ValueError, match="does not match"):
        OpSchema.from_fn("Pooling",
                         lambda data, pool_type="max": data,
                         {"pool_typ": {"choices": ("max",)}})


def test_schema_optional_arrays_are_inputs():
    from mxnet_tpu.ops import registry

    conv = registry.get("Convolution").schema.describe()
    assert "bias" in conv["inputs"]
    assert "bias" not in [p["name"] for p in conv["params"]]
    drop = registry.get("Dropout").schema.describe()
    assert "key" in drop["inputs"]
