"""Serving subsystem: continuous-batching predict server with bounded
tail latency (mxnet_tpu/serving/, docs/SERVING.md).

Headline guarantees under test:

* padded-bucket correctness — a request's response is BIT-IDENTICAL no
  matter which bucket or batch-mates it was coalesced with (padding
  never leaks into outputs);
* admission control — a full queue fast-rejects (ServerBusyError),
  a draining server rejects (ServerDrainingError) while every admitted
  request is still answered;
* multi-tenant isolation — one model's wedged batch (watchdog
  StallError + crash bundle) never blocks another model's queue, and
  the stalled model keeps serving afterwards;
* zero recompiles after warmup — the compile service's ``serving`` site
  shows only cache hits once traffic flows;
* the MXPred C-ABI predictor path compiles under its own ``predictor``
  site token (the PR 7 leftover).
"""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving
from mxnet_tpu.gluon import nn

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def make_net(seed, dim=16, hidden=32, classes=10):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu"), nn.Dense(classes))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, dim)))
    return net


def direct_forward(net, rows, pad_to=None):
    """Reference output: the raw block forward on a (optionally padded)
    batch, sliced back to the real rows."""
    n = rows.shape[0]
    if pad_to and pad_to > n:
        rows = np.concatenate(
            [rows, np.zeros((pad_to - n,) + rows.shape[1:], rows.dtype)])
    out = net(mx.nd.array(rows)).asnumpy()
    return np.asarray(out)[:n]


@pytest.fixture()
def server():
    """A fresh 2-model server per test (cheap: the compile service token
    is stable across identically-built nets, so re-runs hit the cache)."""
    c = serving.ModelContainer()
    c.add_block("a", make_net(1), example_shape=(16,), buckets=(2, 4, 8))
    c.add_block("b", make_net(2), example_shape=(16,), buckets=(2, 4))
    srv = serving.ModelServer(c, max_wait_ms=1.0).start()
    srv.warmup()
    yield srv
    try:
        srv.drain(timeout=5.0)
    finally:
        srv.stop()


# --------------------------------------------------------------- config ----

def test_config_grammar():
    cfg = serving.configure("buckets:2|4;max_queue:7,max_wait_ms:1.5,"
                            "timeout_ms:500,stage:0")
    try:
        assert cfg["buckets"] == (2, 4)
        assert cfg["max_queue"] == 7
        assert cfg["max_wait_ms"] == 1.5
        assert cfg["stage"] is False
        assert serving.effective()["max_queue"] == 7
        d = serving.describe()
        assert d["buckets"] == (2, 4) and "env" in d
    finally:
        serving.configure_from_env()
    assert serving.effective()["max_queue"] == 1024  # defaults restored


def test_config_bad_specs():
    with pytest.raises(ValueError, match="unknown serving option"):
        serving.configure("max_qeue:5")
    with pytest.raises(ValueError, match="buckets"):
        serving.configure("buckets:a|b")
    with pytest.raises(ValueError, match="expected <option>:<value>"):
        serving.configure("max_queue")
    serving.configure_from_env()


# ----------------------------------------------------------- model layer ---

def test_bucket_selection_and_validation():
    m = serving.ServedModel.from_block("m", make_net(3), example_shape=(16,),
                                       buckets=(2, 4, 8))
    assert m.bucket_for(1) == 2 and m.bucket_for(2) == 2
    assert m.bucket_for(3) == 4 and m.bucket_for(8) == 8
    assert m.bucket_for(9) is None
    # bare example-shape rows get the k=1 batch dim
    assert m.validate(np.zeros(16, np.float32)).shape == (1, 16)
    with pytest.raises(ValueError, match="expects rows shaped"):
        m.validate(np.zeros((1, 7), np.float32))
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        m.validate(np.zeros((9, 16), np.float32))


def test_symbol_loader_errors():
    net = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4, name="fc")
    with pytest.raises(ValueError, match="example_shape"):
        serving.ServedModel.from_symbol("s", net)
    with pytest.raises(ValueError, match="no parameter values"):
        serving.ServedModel.from_symbol("s", net, input_name="data",
                                        example_shape=(8,))


# ------------------------------------------------------------ correctness --

def test_predict_matches_direct_forward(server):
    net = make_net(1)
    x = np.random.RandomState(0).randn(3, 16).astype(np.float32)
    got = server.predict("a", x, timeout=10.0)
    ref = direct_forward(net, x, pad_to=4)  # 3 rows -> bucket 4
    assert got.shape == (3, 10)
    assert np.allclose(got, ref, atol=0, rtol=0)


def test_bit_identical_across_buckets_and_batchmates(server):
    """The headline padded-bucket guarantee: the SAME request coalesced
    (a) alone into the smallest bucket, (b) with random batch-mates into
    a mid bucket, (c) into the largest bucket, yields bit-identical
    bytes — padding and batch-mates never leak into a response."""
    rs = np.random.RandomState(42)
    x = rs.randn(1, 16).astype(np.float32)

    # (a) alone -> bucket 2 (1 real row + 1 padding row)
    alone = server.predict("a", x, timeout=10.0)

    # (b) with 3 mates -> bucket 4: submit in one burst; max_wait_ms=1.0
    # coalesces them (census-checked below)
    mates = [rs.randn(1, 16).astype(np.float32) for _ in range(3)]
    futs = [server.submit("a", arr) for arr in [x] + mates]
    with_mates = futs[0].result(10.0)

    # (c) an 8-row request puts x in the largest bucket at row 5
    big = rs.randn(8, 16).astype(np.float32)
    big[5] = x[0]
    big_out = server.predict("a", big, timeout=10.0)

    assert np.array_equal(alone, with_mates)
    assert np.array_equal(alone[0], big_out[5])
    census = server.stats()["models"]["a"]["bucket_census"]
    assert set(census) >= {2, 8}  # the ladder was actually exercised


def test_multi_output_symbol_model():
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    out = mx.sym.Group([mx.sym.softmax(h, name="sm"),
                        mx.sym.sum(h, axis=1, name="s")])
    rs = np.random.RandomState(5)
    args = {"fc1_weight": mx.nd.array(rs.randn(8, 6).astype("f") * 0.3),
            "fc1_bias": mx.nd.array(rs.randn(8).astype("f") * 0.1)}
    c = serving.ModelContainer()
    c.add_symbol("two", out, args, example_shape=(6,), buckets=(2, 4))
    srv = serving.ModelServer(c, max_wait_ms=1.0).start()
    try:
        srv.warmup()
        x = rs.randn(3, 6).astype(np.float32)
        got = srv.predict("two", x, timeout=10.0)
        assert isinstance(got, list) and len(got) == 2
        assert got[0].shape == (3, 8) and got[1].shape == (3,)
        ref = out.eval_with({"data": np.concatenate(
            [x, np.zeros((1, 6), np.float32)])}, param_feed=args)
        assert np.array_equal(got[0], np.asarray(ref[0].asnumpy())[:3])
        assert np.array_equal(got[1], np.asarray(ref[1].asnumpy())[:3])
    finally:
        srv.drain(timeout=5.0)
        srv.stop()


def test_checkpoint_and_onnx_loaders(tmp_path):
    """The MXPred model zoo serves: a save_checkpoint pair and an ONNX
    export of the same net produce matching servable models."""
    from mxnet_tpu.model import save_checkpoint
    from mxnet_tpu.onnx.mx2onnx import export_model

    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=5, name="fc")
    net = mx.sym.softmax(net, name="sm")
    rs = np.random.RandomState(7)
    args = {"fc_weight": mx.nd.array(rs.randn(5, 12).astype("f") * 0.2),
            "fc_bias": mx.nd.array(rs.randn(5).astype("f") * 0.1)}
    prefix = str(tmp_path / "m")
    save_checkpoint(prefix, 3, net, args, {})
    onnx_file = str(tmp_path / "m.onnx")
    export_model(net, args, in_shapes=[(2, 12)], onnx_file_path=onnx_file)

    c = serving.ModelContainer()
    c.add_checkpoint("ckpt", prefix, 3, example_shape=(12,),
                     buckets=(2, 4))
    c.add_onnx("onnx", onnx_file, example_shape=(12,), buckets=(2, 4))
    srv = serving.ModelServer(c, max_wait_ms=1.0).start()
    try:
        srv.warmup()
        x = rs.randn(2, 12).astype(np.float32)
        y_ckpt = srv.predict("ckpt", x, timeout=10.0)
        y_onnx = srv.predict("onnx", x, timeout=10.0)
        ref = net.eval_with({"data": x}, param_feed=args)
        ref = np.asarray(ref.asnumpy())
        assert np.allclose(y_ckpt, ref, atol=1e-6)
        assert np.allclose(y_onnx, ref, atol=1e-6)
    finally:
        srv.drain(timeout=5.0)
        srv.stop()


# ------------------------------------------------------- admission control --

def test_unknown_model_and_not_started(server):
    with pytest.raises(serving.ModelNotFound, match="available"):
        server.submit("nope", np.zeros((1, 16), np.float32))
    idle = serving.ModelServer(serving.ModelContainer())
    with pytest.raises(RuntimeError, match="not started"):
        idle.submit("x", np.zeros((1, 16), np.float32))


def test_admission_fast_reject(tmp_path):
    """Queue-depth bound -> immediate ServerBusyError (429 semantics):
    the reject happens AT submit, in microseconds, not after queueing."""
    from mxnet_tpu import faults

    c = serving.ModelContainer()
    c.add_block("m", make_net(11), example_shape=(16,), buckets=(2,))
    srv = serving.ModelServer(c, max_queue=2, max_wait_ms=0.5).start()
    try:
        srv.warmup()
        # every batch sleeps 300ms -> runner busy + staged slot full +
        # 2 rows queued = the 5th submit must bounce
        faults.configure("serving.batch:delay@*:0.3")
        x = np.zeros((1, 16), np.float32)
        futs = []
        for _ in range(2):  # popped into the pipeline
            futs.append(srv.submit("m", x))
            time.sleep(0.08)
        for _ in range(2):  # these fill the waiting queue (max_queue=2)
            futs.append(srv.submit("m", x))
        t0 = time.perf_counter()
        with pytest.raises(serving.ServerBusyError, match="queue is full"):
            srv.submit("m", x)
        assert time.perf_counter() - t0 < 0.1  # FAST reject
        assert srv.stats()["models"]["m"]["rejected"] == 1
        for f in futs:  # everything admitted still completes
            f.result(10.0)
    finally:
        faults.reset()
        srv.drain(timeout=10.0)
        srv.stop()


def test_drain_answers_admitted_then_rejects(server):
    x = np.zeros((1, 16), np.float32)
    futs = [server.submit("a", x) for _ in range(20)]
    assert server.drain(timeout=10.0)
    for f in futs:
        assert f.result(1.0).shape == (1, 10)  # all admitted answered
    with pytest.raises(serving.ServerDrainingError):
        server.submit("a", x)
    assert server.stats()["last_drain"]["answered"] >= 20


# ----------------------------------------------------- stalls & isolation --

def test_stall_isolation_bundle_and_recovery(tmp_path):
    """An injected serving.batch hang on model A: the watchdog converts
    it into a crash bundle + typed RequestError, model B keeps serving
    THROUGHOUT, and A serves again once the fault clears."""
    from mxnet_tpu import faults, watchdog

    c = serving.ModelContainer()
    c.add_block("A", make_net(21), example_shape=(16,), buckets=(2,))
    c.add_block("B", make_net(22), example_shape=(16,), buckets=(2,))
    srv = serving.ModelServer(c, max_wait_ms=0.5).start()
    hang = 1.5
    try:
        srv.warmup()
        watchdog.configure({"serving.batch": 0.4},
                           crash_dir=str(tmp_path), interval=0.05)
        faults.configure(f"serving.batch:hang@1:{hang}")
        x = np.zeros((1, 16), np.float32)
        fut_a = srv.submit("A", x)      # hits invocation 1 -> wedged
        time.sleep(0.1)
        t0 = time.perf_counter()
        y_b = srv.predict("B", x, timeout=5.0)   # B unaffected
        b_lat = time.perf_counter() - t0
        assert y_b.shape == (1, 10)
        assert b_lat < 1.0  # served while A was still wedged
        with pytest.raises(serving.RequestError) as ei:
            fut_a.result(5.0)
        assert isinstance(ei.value.cause, watchdog.StallError)
        bundle = ei.value.cause.bundle
        assert bundle and os.path.isdir(bundle)
        assert srv.stats()["models"]["A"]["stalled_batches"] == 1
        faults.reset()
        time.sleep(hang)  # the abandoned waiter drains out
        y_a = srv.predict("A", x, timeout=5.0)  # A kept serving
        assert y_a.shape == (1, 10)
    finally:
        faults.reset()
        watchdog.configure_from_env()
        srv.drain(timeout=5.0)
        srv.stop()


def test_future_timeout_is_bounded(tmp_path):
    """With no watchdog armed a wedged batch still cannot hang the
    CLIENT: result() raises RequestTimeout at its deadline."""
    from mxnet_tpu import faults

    c = serving.ModelContainer()
    c.add_block("m", make_net(31), example_shape=(16,), buckets=(2,))
    srv = serving.ModelServer(c, max_wait_ms=0.5).start()
    hang = 1.0
    try:
        srv.warmup()
        faults.configure(f"serving.batch:hang@1:{hang}")
        fut = srv.submit("m", np.zeros((1, 16), np.float32))
        with pytest.raises(serving.RequestTimeout, match="not answered"):
            fut.result(0.2)
        fut.result(hang + 5.0)  # the batch itself eventually completes
    finally:
        faults.reset()
        srv.drain(timeout=5.0)
        srv.stop()


# -------------------------------------------------------- observability ----

def test_metrics_snapshot(server):
    x = np.zeros((1, 16), np.float32)
    for _ in range(5):
        server.predict("a", x, timeout=10.0)
    m = server.stats()["models"]["a"]
    assert m["completed"] >= 5 and m["submitted"] >= 5
    assert m["p50_ms"] is not None and m["p99_ms"] >= m["p50_ms"]
    assert 0 < m["batch_fill_ratio"] <= 1.0
    assert sum(m["bucket_census"].values()) == m["batches"]
    assert m["queue_depth"] == 0


def test_percentile_helper():
    from mxnet_tpu.serving.metrics import percentile

    assert percentile([], 99) is None
    assert percentile([5.0], 50) == 5.0
    xs = list(range(1, 101))
    assert percentile(xs, 50) in (50, 51)  # nearest-rank
    assert percentile(xs, 99) in (99, 100)
    assert percentile(xs, 0) == 1 and percentile(xs, 100) == 100


def test_profiler_serving_tracks(server):
    from mxnet_tpu import profiler

    profiler.set_state("run")
    try:
        server.predict("a", np.zeros((1, 16), np.float32), timeout=10.0)
        time.sleep(0.05)
    finally:
        profiler.set_state("stop")
    events = profiler._events
    names = {e["name"] for e in events}
    assert "serving[a]" in names
    assert "serving.a.queue_depth" in names
    assert "serving.a.batch_rows" in names
    profiler.reset()


def test_compile_service_serving_site_zero_recompiles(server):
    """After warmup the serving site serves ONLY cache hits — the
    zero-recompiles acceptance criterion, in miniature."""
    from mxnet_tpu import compile as _compile

    st0 = _compile.stats()["serving"]
    rs = np.random.RandomState(3)
    for k in (1, 2, 3, 5, 8):  # every bucket in a's + b's ladders
        server.predict("a", rs.randn(k, 16).astype(np.float32),
                       timeout=10.0)
    for k in (1, 3):
        server.predict("b", rs.randn(k, 16).astype(np.float32),
                       timeout=10.0)
    st1 = _compile.stats()["serving"]
    assert st1["misses"] == st0["misses"]  # zero recompiles
    assert st1["hits"] > st0["hits"]


def test_diagnose_serving_report(server, capsys):
    sys.path.insert(0, TOOLS)
    try:
        import diagnose

        diagnose.check_serving()
    finally:
        sys.path.remove(TOOLS)
    out = capsys.readouterr().out
    assert "Serving Knobs" in out
    assert "MXNET_TPU_SERVING" in out
    assert "bucket census" in out  # the live server's models listed
    assert "a" in out and "b" in out


# ------------------------------------------------------------ drain/preempt --

def test_run_until_drained_preempt(monkeypatch, tmp_path):
    """The SIGTERM protocol in-process: a pending preempt request makes
    run_until_drained stop admission, answer admitted traffic and hand
    back the drain event with exit code 75."""
    from mxnet_tpu import preempt

    monkeypatch.setenv("MXNET_TPU_PREEMPT_DIR", str(tmp_path))
    c = serving.ModelContainer()
    c.add_block("m", make_net(41), example_shape=(16,), buckets=(2,))
    srv = serving.ModelServer(c, max_wait_ms=0.5).start()
    try:
        srv.warmup()
        futs = [srv.submit("m", np.zeros((1, 16), np.float32))
                for _ in range(8)]
        preempt.request("test-preemption")
        ev = srv.run_until_drained(install=False, exit=False)
        assert ev["exit_code"] == 75
        assert ev["serving"]["drained"] is True
        for f in futs:
            assert f.result(1.0).shape == (1, 10)
        with pytest.raises(serving.ServerDrainingError):
            srv.submit("m", np.zeros((1, 16), np.float32))
        assert any(f.startswith("drain-") for f in os.listdir(tmp_path))
    finally:
        preempt.clear()
        srv.stop()


# ---------------------------------------------------------------- http -----

def test_http_front_end(server):
    import urllib.error
    import urllib.request

    front = serving.HttpFrontEnd(server).start()
    try:
        with urllib.request.urlopen(front.url + "/healthz",
                                    timeout=5.0) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(front.url + "/v1/models",
                                    timeout=5.0) as r:
            assert json.loads(r.read())["models"] == ["a", "b"]
        x = np.random.RandomState(1).randn(2, 16).astype(np.float32)
        req = urllib.request.Request(
            front.url + "/v1/models/a:predict",
            data=json.dumps({"data": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10.0) as r:
            body = json.loads(r.read())
        out = np.asarray(body["outputs"][0], np.float32)
        ref = server.predict("a", x, timeout=10.0)
        assert np.allclose(out, ref, atol=1e-6)
        with pytest.raises(urllib.error.HTTPError) as ei:
            req = urllib.request.Request(
                front.url + "/v1/models/ghost:predict",
                data=b'{"data": [[0]]}',
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=5.0)
        assert ei.value.code == 404
        with urllib.request.urlopen(front.url + "/v1/stats",
                                    timeout=5.0) as r:
            stats = json.loads(r.read())
        assert "a" in stats["models"]
    finally:
        front.close()


# ------------------------------------------------------------- predictor ---

def test_capi_predictor_compiles_under_predictor_site():
    """The MXPred C-ABI path (capi_bridge._Predictor) routes through the
    unified compile service under its own 'predictor' site token — the
    headline compile path PR 7 left out."""
    from mxnet_tpu import capi_bridge
    from mxnet_tpu import compile as _compile

    net = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=6,
                                name="fc1")
    net = mx.sym.softmax(net, name="sm")
    pred = capi_bridge.pred_create(net.tojson(), b"", ["data"], [(2, 9)])
    rs = np.random.RandomState(9)
    x = rs.randn(2, 9).astype(np.float32)
    capi_bridge.pred_set_input(pred, "data", x.tobytes())
    st0 = _compile.stats().get("predictor", {"hits": 0, "misses": 0})
    capi_bridge.pred_forward(pred)
    st1 = _compile.stats()["predictor"]
    assert st1["misses"] == st0["misses"] + 1  # first forward compiles
    assert capi_bridge.pred_num_outputs(pred) == 1
    shape = capi_bridge.pred_output_shape(pred, 0)
    assert tuple(shape) == (2, 6)
    out = np.frombuffer(capi_bridge.pred_output_bytes(pred, 0),
                        np.float32).reshape(2, 6)
    # params default to simple_bind zeros -> softmax over zeros is uniform
    assert np.allclose(out, 1.0 / 6.0, atol=1e-6)
    capi_bridge.pred_forward(pred)
    st2 = _compile.stats()["predictor"]
    assert st2["hits"] == st1["hits"] + 1  # second forward is a hit


# --------------------------------------------------------------- loadgen ---

def test_loadgen_inproc_short():
    """tools/loadgen.py drives a 2-model container: completions flow,
    latency percentiles exist, and the run holds the zero-recompile
    contract."""
    sys.path.insert(0, TOOLS)
    try:
        import loadgen

        rep = loadgen.run_inproc(duration=1.0, mode="closed",
                                 concurrency=4, models=2)
    finally:
        sys.path.remove(TOOLS)
    assert rep["errors"] == 0, rep["first_errors"]
    assert rep["completed"] > 50
    assert rep["rps"] > 50
    assert rep["p50_ms"] is not None and rep["p99_ms"] is not None
    assert rep["recompiles_during_run"] == 0
    assert 0 < rep["batch_fill_ratio"] <= 1.0


def test_loadgen_open_loop_short():
    sys.path.insert(0, TOOLS)
    try:
        import loadgen

        rep = loadgen.run_inproc(duration=1.0, mode="open", rate=300.0,
                                 concurrency=4, models=1)
    finally:
        sys.path.remove(TOOLS)
    assert rep["errors"] == 0, rep["first_errors"]
    assert rep["completed"] > 50
    assert rep["recompiles_during_run"] == 0
