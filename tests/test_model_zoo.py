"""Model zoo tests (parity model: tests/python/unittest/test_gluon_model_zoo.py
— every registered model builds and forwards; spot-check parameter counts)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision


def _n_params(net):
    return sum(int(np.prod(p.shape)) for p in net.collect_params().values())


@pytest.mark.parametrize("name,size", [
    ("resnet18_v1", 224), ("resnet18_v2", 224), ("mobilenet0_25", 224),
    ("mobilenet_v2_0_25", 224), ("squeezenet1_1", 224),
])
def test_small_models_forward(name, size):
    net = vision.get_model(name, classes=10)
    net.initialize()
    out = net(mx.nd.random.uniform(shape=(1, 3, size, size)))
    assert out.shape == (1, 10)
    # hybridized parity
    ref = out.asnumpy()
    net.hybridize()
    out2 = net(mx.nd.random.uniform(shape=(1, 3, size, size)))
    assert out2.shape == (1, 10)


def test_resnet50_structure():
    """ResNet-50 must have the canonical ~25.5M parameters."""
    net = vision.resnet50_v1(classes=1000)
    net.initialize()
    net(mx.nd.random.uniform(shape=(1, 3, 224, 224)))
    n = _n_params(net)
    assert 25.4e6 < n < 25.8e6, f"resnet50 param count {n}"


def test_resnet18_param_count():
    net = vision.resnet18_v1(classes=1000)
    net.initialize()
    net(mx.nd.random.uniform(shape=(1, 3, 224, 224)))
    n = _n_params(net)
    assert 11.6e6 < n < 11.8e6, f"resnet18 param count {n}"


def test_get_model_errors():
    with pytest.raises(ValueError):
        vision.get_model("resnet1999")


def test_thumbnail_mode():
    net = vision.get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize()
    out = net(mx.nd.random.uniform(shape=(2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_model_zoo_registry_complete():
    names = set(vision.__all__)
    # the reference's families (vision/__init__.py:112)
    for family in ["alexnet", "densenet121", "inception_v3", "resnet50_v1",
                   "resnet50_v2", "squeezenet1_0", "vgg16", "vgg16_bn",
                   "mobilenet1_0", "mobilenet_v2_1_0"]:
        assert family in names, f"missing {family}"


def test_train_small_resnet():
    """A thumbnail resnet trains on synthetic data (train-convergence tier)."""
    from mxnet_tpu import autograd as ag
    from mxnet_tpu.gluon import Trainer, loss as gloss

    np.random.seed(0)
    mx.random.seed(0)
    net = vision.get_model("resnet18_v1", classes=4, thumbnail=True)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    rng = np.random.default_rng(0)
    centers = rng.normal(0, 2.0, (4, 3 * 64))
    labels = rng.integers(0, 4, 128)
    data = (centers[labels] + rng.normal(0, 0.3, (128, 3 * 64))) \
        .astype(np.float32).reshape(-1, 3, 8, 8)
    x, y = mx.nd.array(data), mx.nd.array(labels.astype(np.float32))
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    L = gloss.SoftmaxCrossEntropyLoss()
    first = last = None
    for i in range(10):
        with ag.record():
            loss = L(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
        v = float(loss.mean().asscalar())
        first = first if first is not None else v
        last = v
    assert last < first, f"resnet loss did not decrease: {first} -> {last}"
