"""Train-convergence tests.

Parity model: tests/python/train/test_mlp.py & test_conv.py — short real
training runs asserting accuracy thresholds on (here: synthetic) MNIST-like
data. This is the framework's end-to-end slice: data iterator -> hybridized
net -> loss -> Trainer -> metric.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, metric
from mxnet_tpu.gluon import Trainer, nn, loss as gloss
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader


def make_synthetic_mnist(n=600, nclass=4, seed=0):
    """Class-conditional blobs rendered as 8x8 'images' — learnable quickly,
    deterministic, no files needed."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 1.5, (nclass, 64))
    labels = rng.integers(0, nclass, n)
    data = centers[labels] + rng.normal(0, 0.5, (n, 64))
    return data.astype(np.float32).reshape(n, 1, 8, 8), labels.astype(np.float32)


def evaluate(net, loader):
    m = metric.Accuracy()
    for x, y in loader:
        m.update([y], [net(x)])
    return m.get()[1]


def test_train_mlp():
    np.random.seed(0)
    mx.random.seed(0)
    data, labels = make_synthetic_mnist()
    train_ds = ArrayDataset(data[:500], labels[:500])
    val_ds = ArrayDataset(data[500:], labels[500:])
    train_loader = DataLoader(train_ds, batch_size=50, shuffle=True)
    val_loader = DataLoader(val_ds, batch_size=50)

    net = nn.HybridSequential()
    net.add(nn.Flatten(), nn.Dense(64, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    L = gloss.SoftmaxCrossEntropyLoss()

    for epoch in range(4):
        for x, y in train_loader:
            with ag.record():
                loss = L(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
    acc = evaluate(net, val_loader)
    assert acc > 0.95, f"MLP failed to converge: val acc {acc}"


def test_train_conv():
    np.random.seed(0)
    mx.random.seed(0)
    data, labels = make_synthetic_mnist(400)
    loader = DataLoader(ArrayDataset(data, labels), batch_size=40, shuffle=True)

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(),
            nn.Flatten(),
            nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 0.005})
    L = gloss.SoftmaxCrossEntropyLoss()
    for epoch in range(4):
        for x, y in loader:
            with ag.record():
                loss = L(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
    acc = evaluate(net, loader)
    assert acc > 0.9, f"conv net failed to converge: train acc {acc}"


def test_train_with_ndarray_iter_module_style():
    """The Module-style loop over DataBatch (parity: common/fit.py flow)."""
    from mxnet_tpu.io import NDArrayIter

    np.random.seed(0)
    data, labels = make_synthetic_mnist(300)
    it = NDArrayIter(data, labels, batch_size=30, shuffle=True,
                     label_name="label")
    net = nn.HybridSequential()
    net.add(nn.Flatten(), nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize()
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    L = gloss.SoftmaxCrossEntropyLoss()
    m = metric.Accuracy()
    for epoch in range(5):
        it.reset()
        m.reset()
        for batch in it:
            x, y = batch.data[0], batch.label[0]
            with ag.record():
                loss = L(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
            m.update([y], [net(x)])
    assert m.get()[1] > 0.9
