"""OpSchema / ParamSpec error-path coverage (satellite of the analysis PR):
OpParamError message quality — op name, parameter, valid choices /
expected types — asserted across representative ops, plus the
tojson -> load -> verify round trip."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import registry
from mxnet_tpu.ops.schema import OpParamError, OpSchema, ParamSpec


# ------------------------------------------------ representative op errors --

def test_activation_bad_choice_message():
    with pytest.raises(OpParamError) as ei:
        registry.get("Activation").check_kwargs({"act_type": "rleu"})
    msg = str(ei.value)
    assert "'Activation'" in msg and "'act_type'" in msg
    assert "'rleu'" in msg and "relu" in msg and "sigmoid" in msg
    assert ei.value.op_name == "Activation"
    assert ei.value.param == "act_type"


def test_pooling_bad_choice_message():
    with pytest.raises(OpParamError) as ei:
        registry.get("Pooling").check_kwargs({"pool_type": "average"})
    msg = str(ei.value)
    assert "'Pooling'" in msg and "'pool_type'" in msg
    assert "max" in msg and "avg" in msg


def test_dropout_range_message():
    with pytest.raises(OpParamError) as ei:
        registry.get("Dropout").check_kwargs({"p": 1.5})
    msg = str(ei.value)
    assert "'Dropout'" in msg and "'p'" in msg and "maximum" in msg
    with pytest.raises(OpParamError) as ei:
        registry.get("Dropout").check_kwargs({"p": -0.1})
    assert "minimum" in str(ei.value)


def test_fully_connected_unknown_param_suggests():
    with pytest.raises(OpParamError) as ei:
        registry.get("FullyConnected").check_kwargs({"num_hiden": 16})
    msg = str(ei.value)
    assert "'FullyConnected'" in msg and "'num_hiden'" in msg
    assert "did you mean 'num_hidden'" in msg
    assert "valid parameters" in msg and "no_bias" in msg


def test_convolution_scalar_for_shape_message():
    with pytest.raises(OpParamError) as ei:
        registry.get("Convolution").check_kwargs({"kernel": 3,
                                                  "num_filter": 8})
    msg = str(ei.value)
    assert "'Convolution'" in msg and "'kernel'" in msg
    assert "expected tuple" in msg and "int" in msg


def test_concat_string_parse_failure_message():
    with pytest.raises(OpParamError) as ei:
        registry.get("Concat").check_kwargs({"dim": "one"})
    msg = str(ei.value)
    assert "'Concat'" in msg and "'dim'" in msg and "cannot parse" in msg


def test_registry_unknown_op_suggests():
    with pytest.raises(KeyError) as ei:
        registry.get("Activaton")
    assert "Activation" in str(ei.value)


# ------------------------------------------------------- string coercion ----

def test_coerce_dmlc_string_forms():
    """Symbol-JSON attrs arrive as dmlc strings; coercion must round them
    back to typed values."""
    op = registry.get("Pooling")
    out = op.check_kwargs({"kernel": "(2, 2)", "stride": "(2, 2)",
                           "global_pool": "True", "pool_type": "avg"})
    assert out["kernel"] == (2, 2) and isinstance(out["kernel"], tuple)
    assert out["global_pool"] is True
    out = registry.get("Dropout").check_kwargs({"p": "0.25"})
    assert out["p"] == pytest.approx(0.25)


def test_coerce_int_float_promotions():
    spec = ParamSpec("x", type=float, default=0.0)
    assert spec.coerce("op", 2) == 2.0
    spec_i = ParamSpec("n", type=int, default=1)
    assert spec_i.coerce("op", 3.0) == 3
    spec_b = ParamSpec("flag", type=bool, default=False)
    assert spec_b.coerce("op", 1) is True


def test_coerce_choices_and_range_direct():
    spec = ParamSpec("mode", type=str, default="a", choices=("a", "b"))
    with pytest.raises(OpParamError) as ei:
        spec.coerce("myop", "c")
    assert "'myop'" in str(ei.value) and "['a', 'b']" in str(ei.value)
    spec = ParamSpec("k", type=int, default=1, low=1, high=5)
    with pytest.raises(OpParamError):
        spec.coerce("myop", 0)
    with pytest.raises(OpParamError):
        spec.coerce("myop", 9)
    assert spec.coerce("myop", "3") == 3


def test_schema_from_fn_override_typo_rejected():
    def fake_op(data, alpha=1.0):
        return data

    with pytest.raises(ValueError) as ei:
        OpSchema.from_fn("fake", fake_op, {"alhpa": {"low": 0.0}})
    assert "alhpa" in str(ei.value)


def test_validate_does_not_mutate_input():
    op = registry.get("Dropout")
    kwargs = {"p": "0.5"}
    out = op.schema.validate(kwargs)
    assert kwargs == {"p": "0.5"} and out["p"] == 0.5


# ------------------------------------------------------ JSON round trip -----

def test_tojson_load_verify_roundtrip():
    """Acceptance: save -> load -> verify() stays clean, and a corrupted
    attr in the JSON is caught at load (compose-time validation) while a
    corrupted wiring is caught by verify()."""
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                              pad=(1, 1), name="conv")
    bn = mx.sym.BatchNorm(conv, name="bn")
    act = mx.sym.Activation(bn[0] if len(bn) > 1 else bn,
                            act_type="relu", name="act")
    pool = mx.sym.Pooling(act, kernel=(2, 2), stride=(2, 2),
                          pool_type="max", name="pool")
    js = pool.tojson()
    loaded = mx.sym.load_json(js)
    assert loaded.verify(data=(2, 3, 8, 8)) == []
    assert loaded.list_arguments() == pool.list_arguments()
    # shapes agree through the round trip
    s1 = pool.infer_shape(data=(2, 3, 8, 8))[1]
    s2 = loaded.infer_shape(data=(2, 3, 8, 8))[1]
    assert s1 == s2

    # corrupt a hyper-parameter value: structured error at load
    bad = js.replace('"pool_type": "max"', '"pool_type": "mox"')
    with pytest.raises(OpParamError) as ei:
        mx.sym.load_json(bad)
    assert "'Pooling'" in str(ei.value) and "'mox'" in str(ei.value)

    # corrupt the wiring: verify() reports it with the node name
    import json as _json

    graph = _json.loads(js)
    for node in graph["nodes"]:
        if node["name"] == "act":
            node["inputs"][0][1] = 5  # bogus output index
    mangled = mx.sym.load_json(_json.dumps(graph))
    issues = mangled.verify(raise_on_error=False)
    assert any(i.code == "dangling-input" and i.node == "act"
               for i in issues if i.is_error)
