"""Op-corpus tail tests: control flow, la_op suite, fft, detection,
ROI/STN, regression outputs (parity model:
tests/python/unittest/test_contrib_control_flow.py, test_operator.py
la_op / detection sections)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient

RS = onp.random.RandomState(7)


def _rand(*shape):
    return RS.randn(*shape).astype(onp.float32)


# --------------------------------------------------------- control flow ----

def test_foreach_cumsum():
    data = nd.array(onp.arange(12, dtype="float32").reshape(4, 3))
    init = nd.zeros((3,))
    outs, final = nd.contrib.foreach(lambda x, s: (x + s, x + s), data, init)
    ref = onp.cumsum(onp.arange(12).reshape(4, 3), axis=0)
    onp.testing.assert_allclose(outs.asnumpy(), ref)
    onp.testing.assert_allclose(final.asnumpy(), ref[-1])


def test_foreach_gradient_through_closure():
    data = nd.array(_rand(4, 3))
    init = nd.zeros((1,))
    w = nd.array(onp.ones(3, "float32"))
    w.attach_grad()
    with mx.autograd.record():
        o, _ = nd.contrib.foreach(lambda x, s: ((x * w).sum(), s), data,
                                  init)
        loss = o.sum()
    loss.backward()
    onp.testing.assert_allclose(w.grad.asnumpy(),
                                data.asnumpy().sum(axis=0), rtol=1e-5)


def test_foreach_multiple_data_and_states():
    d1, d2 = nd.array(_rand(3, 2)), nd.array(_rand(3, 2))
    s1, s2 = nd.zeros((2,)), nd.ones((2,))

    def body(xs, states):
        a, b = xs
        u, v = states
        return [a + u, b * v], [u + a, v]

    outs, states = nd.contrib.foreach(body, [d1, d2], [s1, s2])
    assert len(outs) == 2 and len(states) == 2
    onp.testing.assert_allclose(
        states[0].asnumpy(), d1.asnumpy().sum(axis=0), rtol=1e-5)


def test_while_loop():
    def cond_fn(i, s):
        return i < 5

    def func(i, s):
        return (s,), (i + 1, s + i)

    outs, (i_f, s_f) = nd.contrib.while_loop(
        cond_fn, func, (nd.array([0.0]), nd.array([0.0])),
        max_iterations=8)
    assert float(i_f.asscalar()) == 5
    assert float(s_f.asscalar()) == 10
    assert outs[0].shape == (8, 1)  # padded to max_iterations


def test_cond():
    t = lambda: nd.array([2.0])  # noqa: E731
    f = lambda: nd.array([3.0])  # noqa: E731
    assert float(nd.contrib.cond(nd.array([1.0]), t, f).asscalar()) == 2.0
    assert float(nd.contrib.cond(nd.array([0.0]), t, f).asscalar()) == 3.0


def test_control_flow_in_hybrid_trace():
    """foreach inside a hybridized block compiles to one executable."""
    from mxnet_tpu.gluon import nn

    class Scan(nn.HybridBlock):
        def hybrid_forward(self, F, x):
            outs, _ = nd.contrib.foreach(
                lambda xi, s: (xi * 2, s), x, nd.zeros((1,)))
            return outs

    net = Scan()
    net.hybridize()
    x = nd.array(_rand(4, 3))
    out = net(x)
    onp.testing.assert_allclose(out.asnumpy(), 2 * x.asnumpy(), rtol=1e-6)


# ---------------------------------------------------------------- la_op ----

def test_linalg_gemm():
    A, B, C = _rand(3, 4), _rand(4, 5), _rand(3, 5)
    out = nd.linalg_gemm(nd.array(A), nd.array(B), nd.array(C), alpha=2.0,
                         beta=0.5)
    onp.testing.assert_allclose(out.asnumpy(), 2 * A @ B + 0.5 * C,
                                rtol=1e-4, atol=1e-5)
    out_t = nd.linalg_gemm(nd.array(A.T), nd.array(B), nd.array(C),
                           transpose_a=True)
    onp.testing.assert_allclose(out_t.asnumpy(), A @ B + C, rtol=1e-4,
                                atol=1e-5)


def test_linalg_trsm_trmm():
    A = onp.tril(RS.rand(4, 4).astype("float32")) + \
        2 * onp.eye(4, dtype="float32")
    B = _rand(4, 3)
    X = nd.linalg_trsm(nd.array(A), nd.array(B))
    onp.testing.assert_allclose(A @ X.asnumpy(), B, rtol=1e-4, atol=1e-4)
    Y = nd.linalg_trmm(nd.array(A), nd.array(B))
    onp.testing.assert_allclose(Y.asnumpy(), onp.tril(A) @ B, rtol=1e-4,
                                atol=1e-5)


def test_linalg_potri_inverse_det():
    B = _rand(4, 4)
    spd = B @ B.T + 4 * onp.eye(4, dtype="float32")
    L = onp.linalg.cholesky(spd).astype(onp.float32)
    inv = nd.linalg_potri(nd.array(L))
    onp.testing.assert_allclose(inv.asnumpy(), onp.linalg.inv(spd),
                                rtol=1e-3, atol=1e-4)
    onp.testing.assert_allclose(
        nd.linalg_inverse(nd.array(spd)).asnumpy(), onp.linalg.inv(spd),
        rtol=1e-3, atol=1e-4)
    onp.testing.assert_allclose(
        nd.linalg_det(nd.array(spd)).asnumpy(), onp.linalg.det(spd),
        rtol=1e-3)


def test_linalg_syevd_gelqf():
    B = _rand(4, 4)
    spd = B @ B.T + 4 * onp.eye(4, dtype="float32")
    U, L = nd.linalg_syevd(nd.array(spd))
    onp.testing.assert_allclose(
        U.asnumpy().T @ onp.diag(L.asnumpy()) @ U.asnumpy(), spd,
        rtol=1e-3, atol=1e-3)
    A = _rand(2, 4)
    Lq, Q = nd.linalg_gelqf(nd.array(A))
    onp.testing.assert_allclose(Lq.asnumpy() @ Q.asnumpy(), A, rtol=1e-4,
                                atol=1e-5)
    onp.testing.assert_allclose(Q.asnumpy() @ Q.asnumpy().T,
                                onp.eye(2), rtol=1e-4, atol=1e-5)


def test_linalg_diag_helpers():
    A = _rand(3, 3)
    onp.testing.assert_allclose(
        nd.linalg_extractdiag(nd.array(A)).asnumpy(), onp.diag(A))
    d = _rand(3)
    onp.testing.assert_allclose(
        nd.linalg_makediag(nd.array(d)).asnumpy(), onp.diag(d))
    spd = A @ A.T + 4 * onp.eye(3, dtype="float32")
    L = onp.linalg.cholesky(spd).astype(onp.float32)
    onp.testing.assert_allclose(
        nd.linalg_sumlogdiag(nd.array(L)).asnumpy(),
        onp.log(onp.diag(L)).sum(), rtol=1e-5)


def test_linalg_sumlogdiag_gradient():
    B = _rand(3, 3)
    spd = B @ B.T + 4 * onp.eye(3, dtype="float32")
    L = onp.linalg.cholesky(spd).astype(onp.float32)
    check_numeric_gradient("linalg_sumlogdiag", [nd.array(L)])


# ------------------------------------------------------------------ fft ----

def test_fft_roundtrip_and_oracle():
    x = _rand(2, 8)
    f = nd.contrib.fft(nd.array(x))
    ref = onp.fft.fft(x, axis=-1)
    inter = onp.stack([ref.real, ref.imag], axis=-1).reshape(2, 16)
    onp.testing.assert_allclose(f.asnumpy(), inter, rtol=1e-4, atol=1e-4)
    back = nd.contrib.ifft(f) / 8
    onp.testing.assert_allclose(back.asnumpy(), x, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------ detection ----

def test_multibox_prior():
    anchors = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 4, 4)),
                                       sizes=(0.5, 0.25), ratios=(1.0, 2.0))
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()[0]
    # first anchor at cell (0,0): centered at (0.125, 0.125), size 0.5
    onp.testing.assert_allclose(a[0], [0.125 - 0.25, 0.125 - 0.25,
                                       0.125 + 0.25, 0.125 + 0.25],
                                atol=1e-6)


def test_box_iou():
    iou = nd.contrib.box_iou(nd.array([[0.0, 0.0, 1.0, 1.0]]),
                             nd.array([[0.0, 0.0, 1.0, 1.0],
                                       [0.5, 0.5, 1.5, 1.5]]))
    onp.testing.assert_allclose(iou.asnumpy(), [[1.0, 0.25 / 1.75]],
                                rtol=1e-5)


def test_box_nms():
    dets = nd.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5],
                      [0, 0.8, 0.12, 0.12, 0.52, 0.52],
                      [1, 0.7, 0.6, 0.6, 0.9, 0.9]]])
    kept = nd.contrib.box_nms(dets, overlap_thresh=0.5)
    k = kept.asnumpy()[0]
    assert k[0][1] == pytest.approx(0.9)  # top box kept
    assert k[1][0] == -1                  # overlapping same-class removed
    assert k[2][0] == 1                   # other class kept
    # force_suppress ignores class ids
    dets2 = nd.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5],
                       [1, 0.8, 0.1, 0.1, 0.5, 0.5]]])
    k2 = nd.contrib.box_nms(dets2, overlap_thresh=0.5,
                            force_suppress=True).asnumpy()[0]
    assert k2[1][0] == -1


def test_multibox_target_and_detection():
    anc = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 2, 2)), sizes=(0.5,),
                                   ratios=(1.0,))
    lab = nd.array([[[0, 0.1, 0.1, 0.6, 0.6]]])
    cls_pred = nd.zeros((1, 2, 4))
    lt, lm, ct = nd.contrib.MultiBoxTarget(anc, lab, cls_pred)
    assert lt.shape == (1, 16) and lm.shape == (1, 16) and ct.shape == (1, 4)
    assert ct.asnumpy().max() == 1.0  # one anchor matched to class 0 (+1)
    cls_prob = nd.array(RS.rand(1, 2, 4).astype("float32"))
    det = nd.contrib.MultiBoxDetection(cls_prob, nd.zeros((1, 16)), anc)
    assert det.shape == (1, 4, 6)


# ------------------------------------------------------------- roi / stn ----

def test_roi_pooling_and_align():
    img = nd.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    rois = nd.array([[0, 0, 0, 3, 3]])
    out = nd.ROIPooling(img, rois, pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    # max of each quadrant
    onp.testing.assert_allclose(out.asnumpy()[0, 0], [[5, 7], [13, 15]])
    ra = nd.contrib.ROIAlign(img, rois, pooled_size=(2, 2),
                             spatial_scale=1.0)
    assert ra.shape == (1, 1, 2, 2)


def test_spatial_transformer_identity_and_shift():
    img = nd.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    ident = nd.array([[1.0, 0.0, 0.0, 0.0, 1.0, 0.0]])
    out = nd.SpatialTransformer(img, ident, target_shape=(4, 4))
    onp.testing.assert_allclose(out.asnumpy(), img.asnumpy(), atol=1e-4)
    # zoom x2 (theta scales coordinates by 0.5 -> center crop upsampled)
    zoom = nd.array([[0.5, 0.0, 0.0, 0.0, 0.5, 0.0]])
    out2 = nd.SpatialTransformer(img, zoom, target_shape=(4, 4))
    assert out2.shape == (1, 1, 4, 4)


def test_bilinear_sampler_grad():
    img = nd.array(_rand(1, 1, 4, 4))
    ys = onp.linspace(-0.9, 0.9, 3, dtype="float32")
    xs = onp.linspace(-0.9, 0.9, 3, dtype="float32")
    gy, gx = onp.meshgrid(ys, xs, indexing="ij")
    grid = nd.array(onp.stack([gx, gy])[None])
    check_numeric_gradient("BilinearSampler", [img, grid], rtol=5e-2,
                           atol=1e-2)


def test_bilinear_resize():
    img = nd.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    out = nd.contrib.BilinearResize2D(img, height=8, width=8)
    assert out.shape == (1, 1, 8, 8)
    onp.testing.assert_allclose(out.asnumpy()[0, 0, 0, 0], 0.0, atol=1e-5)


# ----------------------------------------------------------- loss heads ----

def test_regression_outputs():
    x = nd.array([[1.0, 2.0]])
    lbl = nd.array([[0.5, 0.5]])
    num_output = 2  # reference grad normalization: grad_scale / num_output
    for op_name, fwd, grad in [
        ("LinearRegressionOutput", lambda v: v, lambda v, l: v - l),
        ("MAERegressionOutput", lambda v: v,
         lambda v, l: onp.sign(v - l)),
        ("LogisticRegressionOutput",
         lambda v: 1 / (1 + onp.exp(-v)),
         lambda v, l: 1 / (1 + onp.exp(-v)) - l),
    ]:
        xc = x.copy()
        xc.attach_grad()
        with mx.autograd.record():
            out = nd.invoke(op_name, xc, lbl)
        onp.testing.assert_allclose(out.asnumpy(), fwd(x.asnumpy()),
                                    rtol=1e-5)
        out.backward()
        onp.testing.assert_allclose(
            xc.grad.asnumpy(),
            grad(x.asnumpy(), lbl.asnumpy()) / num_output, rtol=1e-5)


def test_svm_output_grad():
    x = nd.array([[2.0, 1.0, 0.0]])
    lbl = nd.array([0.0])
    x.attach_grad()
    with mx.autograd.record():
        out = nd.SVMOutput(x, lbl, margin=1.0,
                           regularization_coefficient=1.0, use_linear=True)
    onp.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    out.backward()
    # class1 violates (margin 1 - (2-1) = 0, not > 0), class2 violates
    # (1 - (2-0) = -1 < 0): actually neither violates -> zero grad
    onp.testing.assert_allclose(x.grad.asnumpy(), [[0.0, 0.0, 0.0]])
    x2 = nd.array([[0.5, 1.0, 0.0]])
    x2.attach_grad()
    with mx.autograd.record():
        out = nd.SVMOutput(x2, lbl, use_linear=True)
    out.backward()
    g = x2.grad.asnumpy()[0]
    assert g[1] > 0 and g[0] < 0  # violating class pushed down, true up


def test_block_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = (x * 2).sum() + nd.BlockGrad(x * 100).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2.0, 2.0])


# ------------------------------------------------------------------ misc ----

def test_im2col():
    img = nd.array(_rand(1, 2, 4, 4))
    out = nd.im2col(img, kernel=(2, 2), stride=(1, 1))
    assert out.shape == (1, 2 * 4, 9)


def test_multi_all_finite():
    ok = nd.multi_all_finite(nd.ones((2, 2)), nd.ones((3,)))
    assert float(ok.asscalar()) == 1.0
    bad = nd.multi_all_finite(nd.array([onp.inf]), nd.ones((3,)))
    assert float(bad.asscalar()) == 0.0


def test_correlation_shape():
    a = nd.array(_rand(1, 2, 4, 4))
    out = nd.Correlation(a, a, max_displacement=1)
    assert out.shape == (1, 9, 4, 4)
    # zero displacement channel == mean over channels of a*a
    onp.testing.assert_allclose(
        out.asnumpy()[0, 4], (a.asnumpy()[0] ** 2).mean(axis=0), rtol=1e-4)


def test_boolean_mask_index_copy():
    bm = nd.contrib.boolean_mask(nd.array([[1.0, 2], [3, 4], [5, 6]]),
                                 nd.array([1, 0, 1]))
    onp.testing.assert_allclose(bm.asnumpy(), [[1, 2], [5, 6]])
    ic = nd.contrib.index_copy(nd.zeros((3, 2)),
                               nd.array([1], dtype="int32"),
                               nd.array([[7.0, 8.0]]))
    onp.testing.assert_allclose(ic.asnumpy(), [[0, 0], [7, 8], [0, 0]])


def test_maketrian_roundtrip():
    A = _rand(4, 4)
    for offset, lower in [(0, True), (0, False), (-1, True), (1, False)]:
        packed = nd.linalg_extracttrian(nd.array(A), offset=offset,
                                        lower=lower)
        back = nd.linalg_maketrian(packed, offset=offset, lower=lower)
        tri = onp.tril(A, offset) if lower else onp.triu(A, offset)
        if offset < 0:
            tri = onp.tril(A, offset)
        elif offset > 0:
            tri = onp.triu(A, offset)
        onp.testing.assert_allclose(back.asnumpy(), tri, rtol=1e-6)


def test_box_nms_out_format():
    # center in -> corner out conversion applied to surviving rows
    dets = nd.array([[[0, 0.9, 0.5, 0.5, 0.4, 0.4]]])  # cx,cy,w,h
    kept = nd.contrib.box_nms(dets, in_format="center", out_format="corner")
    onp.testing.assert_allclose(kept.asnumpy()[0, 0, 2:],
                                [0.3, 0.3, 0.7, 0.7], rtol=1e-5)


def test_multibox_target_negative_mining():
    anc = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 4, 4)), sizes=(0.3,),
                                   ratios=(1.0,))
    lab = nd.array([[[0, 0.1, 0.1, 0.4, 0.4]]])
    cls_pred = nd.array(RS.rand(1, 2, 16).astype("float32"))
    lt, lm, ct = nd.contrib.MultiBoxTarget(
        anc, lab, cls_pred, negative_mining_ratio=2.0, ignore_label=-1.0)
    c = ct.asnumpy()[0]
    num_pos = (c == 1.0).sum()
    num_neg = (c == 0.0).sum()
    num_ign = (c == -1.0).sum()
    assert num_pos >= 1
    assert num_neg <= 2 * num_pos
    assert num_ign > 0  # the rest ignored


def test_arange_like_repeat():
    x = nd.zeros((6,))
    out = nd.contrib.arange_like(x, start=1.0, step=0.5, repeat=2)
    onp.testing.assert_allclose(out.asnumpy(),
                                [1.0, 1.0, 1.5, 1.5, 2.0, 2.0])


# ------------------------------------------------------------ DGL ops -----
# parity: src/operator/contrib/dgl_graph.cc (sampling ops for DGL)

def _full_graph(mx):
    import numpy as np

    data = np.arange(1, 21, dtype=np.int64)
    indices = np.array([1, 2, 3, 4, 0, 2, 3, 4, 0, 1, 3, 4,
                        0, 1, 2, 4, 0, 1, 2, 3], np.int64)
    indptr = np.array([0, 4, 8, 12, 16, 20], np.int64)
    from mxnet_tpu.ndarray.sparse import csr_matrix

    return csr_matrix((data, indices, indptr), shape=(5, 5))


def test_dgl_neighbor_uniform_sample():
    """Reference docstring example (dgl_graph.cc:761): full 5-vertex
    graph, all-vertex seed, num_neighbor=2 -> all vertices sampled,
    sub-CSR keeps original edge values, layers valid."""
    import numpy as np

    import mxnet_tpu as mx

    mx.random.seed(0)
    a = _full_graph(mx)
    seed = mx.nd.array(np.arange(5), dtype="int64")
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        a, seed, num_args=2, num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    verts = out[0].asnumpy()
    assert verts.shape == (6,)
    assert verts[-1] == 5  # all five vertices sampled (all were seeds)
    np.testing.assert_array_equal(np.sort(verts[:5]), np.arange(5))
    sub = out[1].asnumpy()
    assert sub.shape == (5, 5)
    dense = a.asnumpy()
    nz = sub != 0
    assert nz.sum() > 0
    np.testing.assert_array_equal(sub[nz], dense[nz])  # original values
    # each row samples at most num_neighbor edges
    assert (nz.sum(axis=1) <= 2).all()
    layers = out[2].asnumpy()
    assert ((layers == 0)[:5]).all()  # seeds are layer 0


def test_dgl_non_uniform_sample_and_subgraph():
    import numpy as np

    import mxnet_tpu as mx

    mx.random.seed(1)
    a = _full_graph(mx)
    prob = mx.nd.array(np.array([1, 0, 0, 0, 1], np.float32))
    seed = mx.nd.array(np.array([0], np.int64))
    out = mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
        a, prob, seed, num_args=3, num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    verts, probs = out[0].asnumpy(), out[1].asnumpy()
    n = int(verts[-1])
    # zero-probability neighbors are never sampled: only vertex 4 can
    # join seed 0 (vertices 1,2,3 have p=0)
    assert set(verts[:n]) <= {0, 4}
    assert probs[0] == 1.0

    subs = mx.nd.contrib.dgl_subgraph(
        a, mx.nd.array(np.array([0, 1, 3], np.int64)), num_args=2,
        return_mapping=True)
    sub, mapping = subs[0].asnumpy(), subs[1].asnumpy()
    assert sub.shape == (3, 3)
    # induced edges: all pairs among {0,1,3} are connected in the full
    # graph; diagonal stays empty
    np.testing.assert_array_equal(sub, 1 - np.eye(3))
    # mapping carries ORIGINAL edge ids: (0->1) is edge value 1
    assert mapping[0, 1] == 1.0


def test_dgl_edge_id_adjacency_compact():
    import numpy as np

    import mxnet_tpu as mx

    a = _full_graph(mx)
    ids = mx.nd.contrib.edge_id(
        a, mx.nd.array(np.array([0, 0, 2], np.int64)),
        mx.nd.array(np.array([1, 0, 3], np.int64))).asnumpy()
    np.testing.assert_array_equal(ids, [1, -1, 11])

    adj = mx.nd.contrib.dgl_adjacency(a)
    dense = adj.asnumpy()
    assert set(np.unique(dense)) == {0.0, 1.0}
    assert dense.sum() == 20

    mx.random.seed(2)
    seed = mx.nd.array(np.array([0, 1], np.int64))
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        a, seed, num_args=2, num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    n = int(out[0].asnumpy()[-1])
    compacted = mx.nd.contrib.dgl_graph_compact(
        out[1], num_args=1, return_mapping=False,
        graph_sizes=(n,))[0]
    assert compacted.shape == (n, n)


def test_dgl_sample_local_indices_nonidentity():
    """Sub-CSR rows AND columns are LOCAL positions (review regression):
    seeds {3,4} with a capped vertex budget produce a consistent local
    matrix, and compacting stays in bounds."""
    import numpy as np

    import mxnet_tpu as mx

    mx.random.seed(5)
    a = _full_graph(mx)
    seed = mx.nd.array(np.array([3, 4], np.int64))
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        a, seed, num_args=2, num_hops=1, num_neighbor=2,
        max_num_vertices=3)
    verts = out[0].asnumpy()
    n = int(verts[-1])
    sub = out[1].asnumpy()
    nz_rows, nz_cols = np.nonzero(sub)
    assert nz_cols.max(initial=0) < n  # local, in-bounds columns
    dense = a.asnumpy()
    for r, c in zip(nz_rows, nz_cols):
        # local (r, c) must carry the ORIGINAL edge value between the
        # corresponding global vertices
        assert sub[r, c] == dense[verts[r], verts[c]]
    compacted = mx.nd.contrib.dgl_graph_compact(
        out[1], num_args=1, return_mapping=False,
        graph_sizes=(n,))[0]
    assert compacted.asnumpy().shape == (n, n)
    import pytest

    with pytest.raises(ValueError, match="graph_sizes"):
        mx.nd.contrib.dgl_graph_compact(out[1], num_args=1)
