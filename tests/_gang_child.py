"""Elastic gang worker for tests/test_elastic.py and chaos_smoke phase 8.

One rank of a supervised gang (``tools/launch.py --supervise``): a tiny
deterministic ShardedTrainer fit over GLOBAL steps, checkpointing through a
CheckpointManager shared across the gang, draining gracefully on SIGTERM
(the supervisor's coordinated teardown) and ALWAYS resuming from the
manager's latest good checkpoint — so a generation-N+1 incarnation picks up
exactly where the drained generation stopped, resharding onto the surviving
census when the mesh shrank.

Census -> mesh: each rank simulates ``GC_BASE_DEVICES x MXTPU_NUM_WORKERS``
local CPU devices (or the explicit ``GC_DEVICES`` override for solo
reference runs), so a gang that shrank from 2 workers to 1 resumes on half
the devices — a genuine topology-portable reshard. Ranks train the SAME
data-parallel trajectory (the mesh is process-local: multiprocess CPU
collectives are not available on every jax in CI; the TCP rendezvous layer
itself is unit-tested through base.maybe_init_distributed), and only rank 0
writes checkpoints/outputs.

Env knobs (GC_* are this child's; MXTPU_* come from the supervisor):

    GC_CKPT_DIR       checkpoint dir (default: <MXTPU_GANG_DIR>/ckpt)
    GC_TOTAL          total global steps (default 12)
    GC_EPOCH          steps per epoch -> checkpoint cadence (default 4)
    GC_BASE_DEVICES   simulated devices per worker (default 2)
    GC_DEVICES        explicit device count override (reference runs)
    GC_STEP_SLEEP     seconds slept per step (default 0 — drills set ~0.2
                      so a mid-epoch kill lands mid-epoch, not after done)
    GC_OUT            rank 0: np.savez final params + per-step losses +
                      __start__ (resume step) + __generation__/__devices__
    GC_FAULTS_GEN1    fault spec armed ONLY by rank 0 in generation 1
                      (e.g. "trainer.step:peerloss@6:1" — kill rank 1 at
                      step 6); later generations run clean, so the drill
                      converges instead of re-killing every incarnation
    GC_STRAGGLE_RANK  this rank arms a per-step delay fault
                      (trainer.step:delay@*) — the deterministic
                      straggler for the PR 12 skew-detection drills
    GC_STRAGGLE_MS    the straggler's per-step delay (default 200)
    GC_METRICS        "1": start a per-rank telemetry MetricsServer,
                      advertise its port in the rank's telemetry shard,
                      and before exiting (a) scrape the OWN endpoint
                      into <gang dir>/rank-scrape-<r>.txt — the
                      fleet-sum acceptance compares the fleet scrape
                      against these — and (b) write one final shard
    GC_SERVE          "1": rank 0 serves a tiny model for a few traced
                      requests after training (request spans with all
                      five phases land in its shard for the merged
                      gang trace)
"""
import os
import sys

# device census must land before anything touches the XLA backend
_workers = int(os.environ.get("MXTPU_NUM_WORKERS", "1") or 1)
_n = int(os.environ.get("GC_DEVICES", "0") or 0) or \
    int(os.environ.get("GC_BASE_DEVICES", "2")) * _workers
# the gang mesh here is process-local (see module docstring): drop the
# rendezvous address so jax.distributed does not try to form a global
# device pool this jax/backend cannot serve
os.environ.pop("MXTPU_COORDINATOR", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", _n)
except AttributeError:  # jax < 0.5 spells this flag via XLA_FLAGS
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_n}")

import time  # noqa: E402

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import faults, gluon, preempt  # noqa: E402
from mxnet_tpu.checkpoint import CheckpointManager  # noqa: E402
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer  # noqa: E402


def batch_for(epoch, step):
    rs = np.random.RandomState(1000 * epoch + step)
    x = rs.randn(8, 6).astype(np.float32)
    y = (x @ rs.randn(6, 4) * 0.5).astype(np.float32)
    return mx.nd.array(x), mx.nd.array(y)


def main():
    total = int(os.environ.get("GC_TOTAL", "12"))
    per_epoch = int(os.environ.get("GC_EPOCH", "4"))
    sleep_s = float(os.environ.get("GC_STEP_SLEEP", "0") or 0)
    rank = int(os.environ.get("MXTPU_WORKER_ID", "0") or 0)
    generation = int(os.environ.get("MXTPU_GANG_GENERATION", "1") or 1)
    gang_dir = os.environ.get("MXTPU_GANG_DIR")
    ckpt_dir = os.environ.get("GC_CKPT_DIR") or (
        os.path.join(gang_dir, "ckpt") if gang_dir else None)
    if ckpt_dir is None:
        raise SystemExit("GC_CKPT_DIR or MXTPU_GANG_DIR is required")
    out = os.environ.get("GC_OUT") if rank == 0 else None

    preempt.install()
    spec = os.environ.get("GC_FAULTS_GEN1")
    if spec and rank == 0 and generation == 1:
        faults.configure(spec)
    straggle = os.environ.get("GC_STRAGGLE_RANK")
    if straggle is not None and rank == int(straggle):
        delay_s = float(os.environ.get("GC_STRAGGLE_MS", "200")) / 1e3
        faults.configure(f"trainer.step:delay@*:{delay_s}")
    metrics_server = None
    if os.environ.get("GC_METRICS"):
        from mxnet_tpu.telemetry import fleet
        from mxnet_tpu.telemetry.export import MetricsServer

        metrics_server = MetricsServer(port=0).start()
        fleet.set_shard_info(metrics_port=metrics_server.port)

    mx.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(batch_for(1, 0)[0])
    trainer = ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                             {"learning_rate": 0.05},
                             mesh=DeviceMesh({"dp": jax.device_count()}))
    manager = CheckpointManager(ckpt_dir, prefix="gang", keep=5)

    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the reshard notice on a shrink
        entry = trainer.resume(manager)
    start = entry["step"] if entry is not None else 0

    losses = []
    for g in range(start, total):
        epoch, s = divmod(g, per_epoch)
        x, y = batch_for(epoch + 1, s)
        losses.append(float(trainer.step(x, y).asscalar()))
        if sleep_s:
            time.sleep(sleep_s)
        if rank == 0 and (g + 1) % per_epoch == 0:
            trainer.save_checkpoint(manager, (g + 1) // per_epoch)
        if preempt.requested():
            # rank 0's last-resort hook writes the final checkpoint; the
            # others must not race it in the shared manager
            preempt.drain(save=None if rank == 0 else False,
                          directory=ckpt_dir)  # SystemExit(75)

    if os.environ.get("GC_SERVE") and rank == 0:
        # a few traced requests so the gang trace carries serving
        # request spans (five phases) alongside the step spans
        from mxnet_tpu import serving

        snet = gluon.nn.Dense(4, in_units=6)
        snet.initialize(mx.init.Xavier())
        snet(mx.nd.zeros((2, 6)))
        cont = serving.ModelContainer()
        cont.add_block("gangserve", snet, example_shape=(6,),
                       buckets=(2,))
        srv = serving.ModelServer(cont, max_wait_ms=1.0).start()
        srv.warmup()
        for i in range(4):
            srv.predict("gangserve",
                        np.zeros((1, 6), np.float32), timeout=10.0)
        srv.drain(timeout=10.0)
        srv.stop()

    if metrics_server is not None and gang_dir:
        # freeze this rank's story: scrape the own endpoint (the
        # per-rank truth the fleet sums are checked against), then
        # write a final telemetry shard carrying the same counters
        import urllib.request

        from mxnet_tpu.telemetry import fleet

        text = urllib.request.urlopen(
            metrics_server.url + "/metrics", timeout=10).read().decode()
        with open(os.path.join(gang_dir, f"rank-scrape-{rank}.txt"),
                  "w") as f:
            f.write(text)
        fleet.write_shard(gang_dir, rank, generation)

    if out:
        np.savez(out, __losses__=np.asarray(losses, np.float64),
                 __start__=np.int64(start),
                 __generation__=np.int64(generation),
                 __devices__=np.int64(jax.device_count()),
                 **{name: p.data().asnumpy()
                    for name, p in net.collect_params().items()})
    print(f"GANG_DONE rank={rank} generation={generation} start={start} "
          f"t={trainer._t} devices={jax.device_count()}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
