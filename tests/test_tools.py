"""Tools layer (parity model: tools/ in the reference — launch.py,
parse_log.py, diagnose.py, bandwidth/measure.py, rec2idx.py)."""
import os
import subprocess
import sys

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_parse_log_roundtrip(tmp_path):
    import parse_log

    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Train-accuracy=0.812345\n"
        "INFO Epoch[0] Time cost=12.345\n"
        "INFO Epoch[0] Validation-accuracy=0.798000\n"
        "INFO Epoch[1] Train-accuracy=0.901000\n"
        "INFO Epoch[1] Time cost=11.000\n"
        "INFO Epoch[1] Validation-accuracy=0.888000\n")
    rows = parse_log.main([str(log), "--format", "none"])
    assert rows[0]["train"]["accuracy"] == pytest.approx(0.812345)
    assert rows[1]["val"]["accuracy"] == pytest.approx(0.888)
    assert rows[1]["time"] == pytest.approx(11.0)


def test_launch_local_sets_worker_env(tmp_path):
    import launch

    out = tmp_path / "env"
    script = (
        "import os, pathlib\n"
        "p = pathlib.Path(%r) / os.environ['MXTPU_WORKER_ID']\n"
        "p.write_text(os.environ['MXTPU_COORDINATOR'] + ' ' +\n"
        "             os.environ['MXTPU_NUM_WORKERS'])\n" % str(out))
    out.mkdir()
    rc = launch.launch_local(3, [sys.executable, "-c", script])
    assert rc == 0
    files = sorted(os.listdir(out))
    assert files == ["0", "1", "2"]
    for f in files:
        coord, n = (out / f).read_text().split()
        assert coord.startswith("127.0.0.1:") and n == "3"


def test_bandwidth_measure_cpu_mesh():
    sys.path.insert(0, os.path.join(REPO, "tools", "bandwidth"))
    import measure

    rows = measure.measure([0.25], iters=2, warmup=1)
    assert rows and rows[0]["algo_gbps"] > 0
    assert rows[0]["devices"] >= 1


def test_diagnose_runs(capsys):
    import diagnose

    diagnose.main()
    out = capsys.readouterr().out
    assert "Framework Info" in out and "Version" in out
    assert "jax" in out
    # watchdog knobs + most-recent-crash-bundle report (docs/ROBUSTNESS.md)
    assert "Watchdog Knobs" in out and "MXNET_TPU_WATCHDOG" in out
    # gang supervision knobs (docs/ROBUSTNESS.md "Gang supervision")
    assert "Gang" in out and "MXNET_TPU_GANG_MAX_RESTARTS" in out
    # telemetry section (docs/OBSERVABILITY.md)
    assert "Telemetry" in out and "MXNET_TPU_TELEMETRY" in out


def test_diagnose_json_machine_readable(capsys):
    """--json: one JSON document with every report section, for CI
    scraping; the human text stays the default (covered above)."""
    import json

    import diagnose

    diagnose.main(["--json"])
    out = capsys.readouterr().out
    report = json.loads(out)  # exactly one parseable document, no prose
    for section in ("python", "framework", "dependencies", "hardware",
                    "environment", "analysis", "compile_cache",
                    "serving", "watchdog", "preempt", "gang",
                    "telemetry"):
        assert section in report, section
    assert report["python"]["version"]
    assert "jax" in report["dependencies"]
    tele = report["telemetry"]
    assert "metrics" in tele and "flight_tail" in tele
    assert "device_memory" in tele


def test_diagnose_gang_report_reads_run_dir(tmp_path, capsys,
                                            monkeypatch):
    """The Gang section reports the run dir's gang.json (generation,
    per-incarnation restart reasons), per-rank last heartbeats, and any
    post-mortem bundle."""
    import json
    import time

    import diagnose

    summary = {"state": "failed", "generation": 3, "restarts_used": 2,
               "max_restarts": 2,
               "history": [{"generation": 1, "exits": {"0": 137},
                            "reason": "rank 0 exited 137 (killed)"},
                           {"generation": 2, "exits": {"0": 86},
                            "reason": "rank 0 exited 86 "
                                      "(watchdog-abort)"},
                           {"generation": 3, "exits": {"0": 86},
                            "reason": "rank 0 exited 86 "
                                      "(watchdog-abort)"}]}
    (tmp_path / "gang.json").write_text(json.dumps(summary))
    (tmp_path / "rank-0.json").write_text(json.dumps(
        {"rank": 0, "generation": 3, "state": "running", "steps": 7,
         "pid": 12345, "t_wall": time.time() - 4.0}))
    (tmp_path / "postmortem-x-p1.json").write_text("{}")
    monkeypatch.setenv("MXNET_TPU_GANG_DIR", str(tmp_path))

    out = diagnose.check_gang()
    text = capsys.readouterr().out
    assert out["summary"]["generation"] == 3
    assert out["heartbeats"][0]["steps"] == 7
    assert out["postmortems"] == ["postmortem-x-p1.json"]
    assert "restarts 2/2" in text and "watchdog-abort" in text
    assert "rank 0 beat" in text and "postmortem-x-p1.json" in text


def test_rec2idx_matches_writer(tmp_path):
    import rec2idx

    from mxnet_tpu import recordio

    rec_path = str(tmp_path / "d.rec")
    idx_path = str(tmp_path / "d.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    payloads = [bytes([i]) * (10 + i) for i in range(12)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()
    written = open(idx_path).read()

    rebuilt = str(tmp_path / "rebuilt.idx")
    rec2idx.main([rec_path, rebuilt])
    assert open(rebuilt).read().split() == written.split()

    # the rebuilt index actually seeks correctly
    r = recordio.MXIndexedRecordIO(rebuilt, rec_path, "r")
    assert r.read_idx(7) == payloads[7]


@pytest.mark.lint
def test_mxlint_self_run_clean():
    """CI gate: the repo must lint clean against the committed baseline —
    new violations of the framework rules (docs/ANALYSIS.md) fail here.
    Addressable alone via `pytest -m lint`."""
    import mxlint

    rc = mxlint.main(["mxnet_tpu"])
    assert rc == 0, "new mxlint violations vs tools/mxlint_baseline.txt"


@pytest.mark.lint
def test_mxlint_catches_planted_violations(tmp_path):
    """The linter actually fires on each rule it claims to enforce."""
    import mxlint

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"                                    # unused-import
        "import numpy as np\n"
        "import jax\n"
        "from jax.experimental import enable_x64\n"      # raw-jax-compat
        "from mxnet_tpu.ops.registry import register\n"
        "def f(x, y=[]):\n"                              # mutable-default
        "    try:\n"
        "        v = x.asnumpy()\n"                      # host-sync
        "    except:\n"                                  # bare-except
        "        v = np.random.uniform()\n"              # unseeded-random
        "    return v\n"
        "@register('badop')\n"
        "def badop(data):\n"                             # no-schema-doc
        "    return data\n"
        "g = jax.jit(badop)\n"                           # raw-jit
        "from jax.sharding import PartitionSpec as P\n"
        "spec = P('dpp', None)\n")                       # partition-spec-literal
    findings = mxlint.run([str(bad)], root=str(tmp_path))
    rules = {f.rule for f in findings}
    assert rules == {"unused-import", "raw-jax-compat", "raw-jit",
                     "mutable-default", "host-sync", "bare-except",
                     "unseeded-random", "no-schema-doc",
                     "partition-spec-literal"}
    psl = [f for f in findings if f.rule == "partition-spec-literal"]
    assert "did you mean" in psl[0].message  # difflib near-miss hint
    # the canonical vocabulary, and parallel/ itself, stay clean
    good_spec = tmp_path / "good_spec.py"
    good_spec.write_text("from jax.sharding import PartitionSpec as P\n"
                         "spec = P('dp', ('tp', 'sp'))\n")
    assert mxlint.run([str(good_spec)], root=str(tmp_path)) == []
    par = tmp_path / "mxnet_tpu" / "parallel"
    par.mkdir(parents=True)
    exempt = par / "exempt.py"
    exempt.write_text("from jax.sharding import PartitionSpec as P\n"
                      "spec = P('stage')\n")
    assert mxlint.run([str(exempt)], root=str(tmp_path)) == []
    # noqa suppression works, per-rule
    ok = tmp_path / "ok.py"
    ok.write_text("v = x.asnumpy()  # noqa: host-sync\n")
    assert mxlint.run([str(ok)], root=str(tmp_path)) == []


@pytest.mark.lint
def test_mxlint_raw_jit_rule_scoping(tmp_path):
    """raw-jit fires on direct jax.jit calls and 'from jax import jit',
    but compile.py (the service home) and _jax_compat.py are exempt."""
    import mxlint

    direct = tmp_path / "site.py"
    direct.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    assert {f.rule for f in mxlint.run([str(direct)],
                                       root=str(tmp_path))} == {"raw-jit"}
    imported = tmp_path / "site2.py"
    imported.write_text("from jax import jit\nf = jit(lambda x: x)\n")
    assert "raw-jit" in {f.rule for f in mxlint.run([str(imported)],
                                                    root=str(tmp_path))}
    exempt = tmp_path / "compile.py"
    exempt.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    assert mxlint.run([str(exempt)], root=str(tmp_path)) == []
    # the service call spelling stays clean
    good = tmp_path / "site3.py"
    good.write_text("from mxnet_tpu import compile as _compile\n"
                    "f = _compile.jit(lambda x: x, site='s', token=('t',))\n")
    assert mxlint.run([str(good)], root=str(tmp_path)) == []


@pytest.mark.lint
def test_mxlint_raw_pallas_call_rule(tmp_path):
    """raw-pallas-call fires on pl.pallas_call outside mxnet_tpu/kernels/
    (with a did-you-mean pointing at the registry) and is exempt inside
    kernels/ — the one blessed home of raw Pallas call sites."""
    import mxlint

    src = ("from jax.experimental import pallas as pl\n"
           "def f(x):\n"
           "    return pl.pallas_call(lambda i, o: None)(x)\n")
    ops = tmp_path / "mxnet_tpu" / "ops"
    ops.mkdir(parents=True)
    bad = ops / "planted.py"
    bad.write_text(src)
    findings = [f for f in mxlint.run([str(bad)], root=str(tmp_path))
                if f.rule == "raw-pallas-call"]
    assert len(findings) == 1
    assert "register_kernel" in findings[0].message
    assert "kernels.dispatch" in findings[0].message

    kern = tmp_path / "mxnet_tpu" / "kernels"
    kern.mkdir(parents=True)
    ok = kern / "mykernel.py"
    ok.write_text(src)
    assert [f for f in mxlint.run([str(ok)], root=str(tmp_path))
            if f.rule == "raw-pallas-call"] == []

    # the real tree carries zero raw-pallas-call debt: flash moved into
    # the registry, so the baseline must not need a single entry
    findings = [f for f in mxlint.run(["mxnet_tpu"])
                if f.rule == "raw-pallas-call"]
    assert findings == []
    with open(mxlint.DEFAULT_BASELINE) as fh:
        assert "raw-pallas-call" not in fh.read()


@pytest.mark.lint
def test_mxlint_serving_blocking_call_rule(tmp_path):
    """serving-blocking-call: serving/ code may not block outside a
    watchdog.sync span — device syncs and zero-arg waits fire; callables
    passed to *.sync(...) (lambda or by name) are exempt, as is the same
    code outside serving/."""
    import mxlint

    serving_dir = tmp_path / "mxnet_tpu" / "serving"
    serving_dir.mkdir(parents=True)
    bad = serving_dir / "bad.py"
    bad.write_text(
        "def f(x, t, q):\n"
        "    x.wait_to_read()\n"        # device sync
        "    jax.block_until_ready(x)\n"  # device sync
        "    t.join()\n"                # zero-arg unbounded wait
        "    q.get()\n"                 # zero-arg unbounded wait
        "    t.join(timeout=1.0)\n"     # bounded: clean
        "    q.get(timeout=0.5)\n"      # bounded: clean
    )
    findings = [f for f in mxlint.run([str(bad)], root=str(tmp_path))
                if f.rule == "serving-blocking-call"]
    assert len(findings) == 4
    assert "bounded-tail-latency" in findings[0].message
    # the watchdog.sync exemption: inline lambda AND a local fn by name
    ok = serving_dir / "ok.py"
    ok.write_text(
        "def g(model, x, w):\n"
        "    def run():\n"
        "        out = model(x)\n"
        "        jax.block_until_ready(out)\n"
        "        return out\n"
        "    a = w.sync('serving.batch', run)\n"
        "    b = w.sync('serving.batch', lambda: x.wait_to_read())\n"
        "    return a, b\n")
    assert [f for f in mxlint.run([str(ok)], root=str(tmp_path))
            if f.rule == "serving-blocking-call"] == []
    # identical blocking code OUTSIDE serving/ is not this rule's business
    other = tmp_path / "mxnet_tpu" / "elsewhere.py"
    other.write_text("def f(x):\n    x.wait_to_read()\n")
    assert [f for f in mxlint.run([str(other)], root=str(tmp_path))
            if f.rule == "serving-blocking-call"] == []
    # the real serving package is clean under the rule
    findings = [f for f in mxlint.run(["mxnet_tpu/serving"])
                if f.rule == "serving-blocking-call"]
    assert findings == [], findings


@pytest.mark.lint
def test_mxlint_print_call_rule(tmp_path):
    """print-call: bare print() inside the mxnet_tpu/ package fires;
    __main__ demo blocks, tools/-style scripts outside the package, and
    noqa'd lines are exempt."""
    import mxlint

    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir(parents=True)
    bad = pkg / "planted.py"
    bad.write_text(
        "def report(x):\n"
        "    print('status:', x)\n"          # fires
        "    print('ok')  # noqa: print-call\n"  # suppressed
        "    return x\n"
        "if __name__ == '__main__':\n"
        "    print(report(1))\n")             # __main__ block: exempt
    findings = [f for f in mxlint.run([str(bad)], root=str(tmp_path))
                if f.rule == "print-call"]
    assert len(findings) == 1 and findings[0].line == 2
    assert "mxnet_tpu.log" in findings[0].message
    # identical code OUTSIDE the package (tools/, scripts) is exempt
    script = tmp_path / "tools" / "script.py"
    script.parent.mkdir()
    script.write_text("def f(x):\n    print(x)\n")
    assert [f for f in mxlint.run([str(script)], root=str(tmp_path))
            if f.rule == "print-call"] == []
    # the telemetry package itself is print-free (structured export only)
    findings = [f for f in mxlint.run(["mxnet_tpu/telemetry"])
                if f.rule == "print-call"]
    assert findings == [], findings


@pytest.mark.lint
def test_mxlint_baseline_gate_blocks_regressions(tmp_path):
    """Baseline semantics: within-count passes, one extra finding fails."""
    import mxlint

    f = tmp_path / "m.py"
    f.write_text("a = x.asnumpy()\n")
    base = tmp_path / "base.txt"
    base.write_text("host-sync m.py 1  # tolerated legacy sync\n")
    assert mxlint.main([str(f), "--root", str(tmp_path),
                        "--baseline", str(base)]) == 0
    f.write_text("a = x.asnumpy()\nb = y.asnumpy()\n")
    assert mxlint.main([str(f), "--root", str(tmp_path),
                        "--baseline", str(base)]) == 1


def test_verifier_smoke_every_model_zoo_symbol():
    """Every model-zoo network traces to a Symbol that passes the graph
    verifier with only an input-shape hint (deferred-init parameter shapes
    resolve abstractly — no forward pass, no device compile)."""
    from mxnet_tpu.gluon.model_zoo import vision

    checked = 0
    for name in vision.__all__:
        if name == "get_model":
            continue
        net = getattr(vision, name)(classes=10)
        net.initialize()
        sym = net._trace_symbol()
        issues = sym.verify(raise_on_error=False, data=(1, 3, 224, 224))
        errors = [i for i in issues if i.is_error]
        assert not errors, f"{name}: {errors[:3]}"
        checked += 1
    assert checked >= 30  # the whole zoo, not a sample


def test_chaos_smoke_recovers(tmp_path):
    """tools/chaos_smoke.py: 2-epoch toy fit under the canned fault
    schedule — NaN guard absorbs a poisoned batch, checkpoint-write
    retry absorbs an injected write failure, an injected crash is
    recovered via CheckpointManager resume, an injected hang surfaces as
    a StallError + bundle, an injected SIGTERM preemption drains
    gracefully and resumes resharded on half the simulated devices, and
    the phase-6 serving drill passes (wedged serving batch -> bundle +
    continued service; subprocess SIGTERM under load -> all admitted
    requests answered, exit 75), and the phase-8 gang drill recovers a
    supervised 2-worker run from a mid-epoch SIGKILL (generation bump,
    resharded resume, loss parity) — exit code 0. The phase-17 planet-
    scale drill (four fleets' worth of subprocess workers) is skipped
    here to hold the tier-1 budget; test_chaos_smoke_hedging_drill
    runs it in the slow tier."""
    import chaos_smoke

    from mxnet_tpu import faults, preempt

    faults.reset()
    try:
        rc = chaos_smoke.main(["--epochs", "2", "--steps", "4",
                               "--skip-hedging-drill",
                               "--dir", str(tmp_path)])
    finally:
        faults.reset()
        preempt.uninstall()
    assert rc == 0
    assert (tmp_path / "MANIFEST.json").exists()
    # phase 4 left a drain-event record next to the checkpoints
    assert any(f.startswith("drain-") for f in os.listdir(tmp_path))
    # phase 6 wrote a serving-stall crash bundle into the crash dir
    crash = tmp_path / "crash"
    assert crash.is_dir() and any(
        "serving_batch" in f for f in os.listdir(crash))
    # phase 7 verified the /metrics scrape; every bundle embeds a
    # non-empty flight-recorder tail (telemetry acceptance)
    import json

    for bundle in os.listdir(crash):
        with open(crash / bundle / "flight.json") as f:
            assert json.load(f), f"empty flight tail in {bundle}"
    # phase 8 left the cluster supervisor's world record: a 1-restart
    # generation-2 recovery, stopped cleanly
    with open(tmp_path / "gang" / "run" / "world.json") as f:
        world = json.load(f)
    assert world["supervisor"]["state"] == "stopped"
    assert world["generation"]["train"] == 2
    assert world["ledger"]["train"]["restarts_total"] == 1
    # phase 16 left the SIGKILLed-and-restarted supervisor's record:
    # incarnation 2 with re-adoptions and zero healthy-worker restarts
    with open(tmp_path / "cluster" / "run" / "world.json") as f:
        world = json.load(f)
    assert world["incarnation"] == 2
    assert any(a["kind"] == "adopt" for a in world["actions"])


@pytest.mark.slow
def test_chaos_smoke_hedging_drill(tmp_path):
    """tools/chaos_smoke.py --phases 17: the planet-scale serving
    drill on its own — the 2-host straggler fleet where hedging must
    cut p99 >=3x with zero errors, the full host loss under one
    cluster.json with zero client-visible errors, and the QoS
    starvation order (batch starves before interactive; unmeetable
    deadlines drop before a batch slot) — exit code 0."""
    import chaos_smoke

    from mxnet_tpu import faults, preempt

    faults.reset()
    try:
        rc = chaos_smoke.main(["--phases", "17",
                               "--dir", str(tmp_path)])
    finally:
        faults.reset()
        preempt.uninstall()
    assert rc == 0
    # drill A left both fleets' per-host run dirs behind — the merged-
    # scrape topology the router placed workers across
    for label in ("hedge-off", "hedge-on"):
        run = tmp_path / "hedge" / label / "run"
        assert (run / "host-local").is_dir()
        assert (run / "host-slow").is_dir()
