"""Tools layer (parity model: tools/ in the reference — launch.py,
parse_log.py, diagnose.py, bandwidth/measure.py, rec2idx.py)."""
import os
import subprocess
import sys

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_parse_log_roundtrip(tmp_path):
    import parse_log

    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Train-accuracy=0.812345\n"
        "INFO Epoch[0] Time cost=12.345\n"
        "INFO Epoch[0] Validation-accuracy=0.798000\n"
        "INFO Epoch[1] Train-accuracy=0.901000\n"
        "INFO Epoch[1] Time cost=11.000\n"
        "INFO Epoch[1] Validation-accuracy=0.888000\n")
    rows = parse_log.main([str(log), "--format", "none"])
    assert rows[0]["train"]["accuracy"] == pytest.approx(0.812345)
    assert rows[1]["val"]["accuracy"] == pytest.approx(0.888)
    assert rows[1]["time"] == pytest.approx(11.0)


def test_launch_local_sets_worker_env(tmp_path):
    import launch

    out = tmp_path / "env"
    script = (
        "import os, pathlib\n"
        "p = pathlib.Path(%r) / os.environ['MXTPU_WORKER_ID']\n"
        "p.write_text(os.environ['MXTPU_COORDINATOR'] + ' ' +\n"
        "             os.environ['MXTPU_NUM_WORKERS'])\n" % str(out))
    out.mkdir()
    rc = launch.launch_local(3, [sys.executable, "-c", script])
    assert rc == 0
    files = sorted(os.listdir(out))
    assert files == ["0", "1", "2"]
    for f in files:
        coord, n = (out / f).read_text().split()
        assert coord.startswith("127.0.0.1:") and n == "3"


def test_bandwidth_measure_cpu_mesh():
    sys.path.insert(0, os.path.join(REPO, "tools", "bandwidth"))
    import measure

    rows = measure.measure([0.25], iters=2, warmup=1)
    assert rows and rows[0]["algo_gbps"] > 0
    assert rows[0]["devices"] >= 1


def test_diagnose_runs(capsys):
    import diagnose

    diagnose.main()
    out = capsys.readouterr().out
    assert "Framework Info" in out and "Version" in out
    assert "jax" in out


def test_rec2idx_matches_writer(tmp_path):
    import rec2idx

    from mxnet_tpu import recordio

    rec_path = str(tmp_path / "d.rec")
    idx_path = str(tmp_path / "d.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    payloads = [bytes([i]) * (10 + i) for i in range(12)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()
    written = open(idx_path).read()

    rebuilt = str(tmp_path / "rebuilt.idx")
    rec2idx.main([rec_path, rebuilt])
    assert open(rebuilt).read().split() == written.split()

    # the rebuilt index actually seeks correctly
    r = recordio.MXIndexedRecordIO(rebuilt, rec_path, "r")
    assert r.read_idx(7) == payloads[7]


def test_chaos_smoke_recovers(tmp_path):
    """tools/chaos_smoke.py: 2-epoch toy fit under the canned fault
    schedule — NaN guard absorbs a poisoned batch, checkpoint-write
    retry absorbs an injected write failure, and an injected crash is
    recovered via CheckpointManager resume — exit code 0."""
    import chaos_smoke

    from mxnet_tpu import faults

    faults.reset()
    try:
        rc = chaos_smoke.main(["--epochs", "2", "--steps", "4",
                               "--dir", str(tmp_path)])
    finally:
        faults.reset()
    assert rc == 0
    assert (tmp_path / "MANIFEST.json").exists()
