"""Elastic preemption child for tests/test_elastic.py.

Runs a tiny deterministic ShardedTrainer fit over GLOBAL steps (so a
mid-epoch drain can resume at the exact batch), checkpointing through
CheckpointManager at epoch boundaries and draining gracefully on SIGTERM.
Driven entirely by env vars so the parent test can run every variant of
the SAME trajectory:

    EL_CKPT_DIR   checkpoint directory (shared between drain + resume runs)
    EL_TOTAL      total global steps (default 12)
    EL_EPOCH      steps per epoch (default 4)
    EL_DEVICES    simulated device count — applied BEFORE the jax backend
                  initialises (jax_num_cpu_devices, or the XLA_FLAGS
                  --xla_force_host_platform_device_count fallback for
                  jax<0.5, exactly like tests/conftest.py)
    EL_RESUME     "1" -> resume from the manager's latest good checkpoint
    EL_RESHARD    "0" -> forbid cross-topology resume (reshard=False)
    EL_OUT        where to np.savez the final params + per-step losses
    MXNET_TPU_FAULTS  e.g. "trainer.step:preempt@6" — SIGTERM to self at
                      step 6; the preempt handlers drain: step 6 finishes,
                      a final checkpoint lands, exit code 75

The per-(epoch, step) batches are regenerated from a derived seed, so a
resumed run replays the identical data stream from `entry["step"]` — the
data-position half of the drain/resume contract.
"""
import os
import sys

# device count must land before anything touches the XLA backend
_n = int(os.environ.get("EL_DEVICES", "0"))
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if _n:
    try:
        jax.config.update("jax_num_cpu_devices", _n)
    except AttributeError:  # jax < 0.5 spells this flag via XLA_FLAGS
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={_n}")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, preempt  # noqa: E402
from mxnet_tpu.checkpoint import CheckpointManager  # noqa: E402
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer  # noqa: E402


def batch_for(epoch, step):
    rs = np.random.RandomState(1000 * epoch + step)
    x = rs.randn(8, 6).astype(np.float32)
    y = (x @ rs.randn(6, 4) * 0.5).astype(np.float32)
    return mx.nd.array(x), mx.nd.array(y)


def main():
    total = int(os.environ.get("EL_TOTAL", "12"))
    per_epoch = int(os.environ.get("EL_EPOCH", "4"))
    ckpt_dir = os.environ["EL_CKPT_DIR"]
    out = os.environ.get("EL_OUT")

    preempt.install()
    mx.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(batch_for(1, 0)[0])
    trainer = ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                             {"learning_rate": 0.05},
                             mesh=DeviceMesh({"dp": jax.device_count()}))
    manager = CheckpointManager(ckpt_dir, prefix="el", keep=5)

    start = 0
    if os.environ.get("EL_RESUME") == "1":
        reshard = None if os.environ.get("EL_RESHARD") != "0" else False
        entry = trainer.resume(manager, reshard=reshard)
        if entry is not None:
            start = entry["step"]  # exact data position, mid-epoch included

    losses = []
    for g in range(start, total):
        epoch, s = divmod(g, per_epoch)
        x, y = batch_for(epoch + 1, s)
        losses.append(float(trainer.step(x, y).asscalar()))
        if (g + 1) % per_epoch == 0:
            trainer.save_checkpoint(manager, (g + 1) // per_epoch)
        if preempt.requested():
            preempt.drain(directory=ckpt_dir)  # final ckpt + SystemExit(75)

    if out:
        np.savez(out, __losses__=np.asarray(losses, np.float64),
                 **{name: p.data().asnumpy()
                    for name, p in net.collect_params().items()})
    print(f"EL_DONE t={trainer._t} devices={jax.device_count()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
