"""Exception propagation at sync points.

Parity model: tests/python/unittest/test_exc_handling.py in the reference —
ops that fail inside the engine must surface their exception at the next
sync point (wait_to_read / waitall / asnumpy), in imperative, symbolic and
Gluon paths, and synchronously under NaiveEngine. On TPU the async engine
is PJRT; host-side failures (callbacks, shape/type validation) raise on the
dispatching thread, device-side deferred errors drain at
``jax.effects_barrier`` via ``mx.nd.waitall``."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine


class _Exploding(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        raise ValueError("boom-forward")

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise ValueError("boom-backward")


@mx.operator.register("test_exploding")
class _ExplodingProp(mx.operator.CustomOpProp):
    def create_operator(self, ctx, shapes, dtypes):
        return _Exploding()


class _ExplodingBwd(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise ValueError("boom-backward-only")


@mx.operator.register("test_exploding_bwd")
class _ExplodingBwdProp(mx.operator.CustomOpProp):
    def create_operator(self, ctx, shapes, dtypes):
        return _ExplodingBwd()


def _sync():
    """Drain every pending computation, re-raising deferred errors
    (Engine::WaitForAll parity)."""
    mx.nd.waitall()


def test_imperative_invalid_op_raises_immediately():
    with pytest.raises(Exception):
        mx.nd.invoke("not_a_real_op", mx.nd.ones((2,)))


def test_imperative_shape_error_raises():
    # dot with mismatched inner dims must fail on the dispatching thread
    with pytest.raises(Exception):
        mx.nd.dot(mx.nd.ones((2, 3)), mx.nd.ones((4, 5)))
        _sync()


def test_engine_surfaces_callback_failure_at_sync_point():
    """A failing engine task (here: a host callback inside the async
    stream) must raise at wait/asnumpy, not be swallowed."""
    x = mx.nd.ones((4,))
    with pytest.raises(Exception, match="boom-forward"):
        y = mx.nd.Custom(x, op_type="test_exploding")
        y.asnumpy()  # sync point


def test_engine_failure_surfaces_at_waitall():
    x = mx.nd.ones((4,))
    with pytest.raises(Exception, match="boom-forward"):
        mx.nd.Custom(x, op_type="test_exploding")
        _sync()
    # engine must be usable again after a failure (reference: exception
    # clears once thrown, threaded_engine.cc OnComplete)
    _sync()
    onp.testing.assert_allclose((x + 1).asnumpy(), onp.full(4, 2.0))


def test_backward_failure_surfaces_on_backward_sync():
    x = mx.nd.ones((3,))
    x.attach_grad()
    with pytest.raises(Exception, match="boom-backward-only"):
        with mx.autograd.record():
            y = mx.nd.Custom(x, op_type="test_exploding_bwd")
        y.backward()
        _sync()
    _sync()


def test_symbolic_executor_failure():
    data = mx.sym.var("data")
    s = mx.sym.Custom(data, op_type="test_exploding")
    ex = s.simple_bind(mx.cpu(), data=(2, 2))
    with pytest.raises(Exception, match="boom-forward"):
        outs = ex.forward(data=mx.nd.ones((2, 2)))
        outs[0].asnumpy()
    _sync()


def test_gluon_hybrid_failure():
    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Custom(x, op_type="test_exploding")

    net = Net()
    net.hybridize()
    with pytest.raises(Exception, match="boom-forward"):
        net(mx.nd.ones((2, 2))).asnumpy()
    _sync()


def test_naive_engine_raises_synchronously(monkeypatch):
    """MXNET_ENGINE_TYPE=NaiveEngine blocks after every op, so the failure
    raises on the invoking statement itself (race-bisection debug mode,
    naive_engine.cc parity)."""
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    assert engine.is_naive()
    x = mx.nd.ones((4,))
    with pytest.raises(Exception, match="boom-forward"):
        mx.nd.Custom(x, op_type="test_exploding")
    _sync()


def test_exception_does_not_poison_later_work():
    for _ in range(2):
        with pytest.raises(Exception):
            mx.nd.Custom(mx.nd.ones((2,)), op_type="test_exploding")
            _sync()
    _sync()
    a = mx.nd.random.uniform(shape=(8, 8))
    b = mx.nd.dot(a, a)
    assert b.asnumpy().shape == (8, 8)


def test_bulked_segment_failure_surfaces_at_sync_point():
    """An op failing INSIDE a bulk(N) fused segment must not raise at the
    recording call site — it surfaces at the next sync point (here a
    buffer read), per the engine's deferred-exception contract
    (mxnet_tpu/bulk.py BulkSegment.run). The failure is injected at the
    'engine.flush' point, which fires exactly where a fused-executable
    failure would."""
    from mxnet_tpu import faults

    faults.configure("engine.flush:raise@1")
    try:
        x = mx.nd.ones((4,))
        with engine.bulk(8):
            y = x + 1          # recorded, NOT executed — must not raise
            z = y * 2
            assert engine.bulk_pending() == 2
            with pytest.raises(faults.InjectedFault):
                z.asnumpy()    # sync point: deferred error surfaces here
            # sticky: the failed segment re-raises on every later force
            with pytest.raises(faults.InjectedFault):
                y.asnumpy()
    finally:
        faults.reset()
    # engine usable again after the failure
    onp.testing.assert_allclose((x + 1).asnumpy(), onp.full(4, 2.0))


def test_bulked_segment_failure_surfaces_at_waitall():
    from mxnet_tpu import faults

    # trigger 2: waitall's own sync fires the point once before the
    # barrier and once when flushing the pending segment — arm the
    # segment-flush invocation (the first one hit)
    faults.configure("engine.flush:raise@1")
    try:
        with engine.bulk(8):
            y = mx.nd.ones((4,)) + 1
            with pytest.raises(faults.InjectedFault):
                _sync()
    finally:
        faults.reset()
    _sync()


def test_bulked_trace_time_failure_raises_at_call_site():
    """Shape errors are detected at RECORD time (static shape inference
    gates bulkability), so they raise immediately even inside a bulk
    scope — same contract as eager dispatch."""
    with engine.bulk(8):
        with pytest.raises(Exception):
            mx.nd.dot(mx.nd.ones((2, 3)), mx.nd.ones((4, 5)))
    _sync()


def test_bulk_failure_does_not_poison_later_segments():
    from mxnet_tpu import faults

    faults.configure("engine.flush:raise@1")
    try:
        with engine.bulk(4):
            y = mx.nd.ones((3,)) * 3
            with pytest.raises(faults.InjectedFault):
                y.asnumpy()
    finally:
        faults.reset()
    # a fresh segment after the failure computes correctly
    with engine.bulk(4):
        z = mx.nd.ones((3,)) * 5
        onp.testing.assert_allclose(z.asnumpy(), onp.full(3, 5.0))


def test_bad_simple_bind_shape_raises():
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, num_hidden=4)
    with pytest.raises(Exception):
        ex = out.simple_bind(mx.cpu(), data=(2, 3))
        ex.forward(data=mx.nd.ones((5, 7)))  # mismatched bind vs feed
        ex.outputs[0].asnumpy()
