"""Int8 serving ladder warm-start drill (tests/test_quantization.py).

Builds a DETERMINISTIC entropy-calibrated int8 model (explicit node
names + seeded params -> a process-stable serving compile token),
serves it through a 3-bucket ladder with MXNET_TPU_CACHE_DIR set, and
prints one ``QCHILD <json>`` line with the serving compile-site stats
(misses / disk hits / compile ms), the traffic-window recompile count
and the bucket census. Run twice against the same cache dir by the
parent test: the SECOND (warm) run must show zero compiles — the whole
int8 ladder loads from the persistent disk cache.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUCKETS = (2, 4, 8)


def main():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import compile as _compile
    from mxnet_tpu import serving
    from mxnet_tpu.contrib import quantization as quant

    rng = np.random.RandomState(0)
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="qc_fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="qc_fc2")
    args = {"qc_fc1_weight": mx.nd.array(
                (rng.randn(16, 8) * 0.2).astype(np.float32)),
            "qc_fc1_bias": mx.nd.array(np.zeros(16, np.float32)),
            "qc_fc2_weight": mx.nd.array(
                (rng.randn(4, 16) * 0.2).astype(np.float32)),
            "qc_fc2_bias": mx.nd.array(np.zeros(4, np.float32))}
    calib = mx.io.NDArrayIter(rng.randn(64, 8).astype(np.float32),
                              batch_size=16, label_name=None)
    qsym, qargs, _ = quant.quantize_model(
        net, args, {}, data_names=("data",), calib_data=calib,
        calib_mode="entropy")

    container = serving.ModelContainer()
    container.add_symbol("qchild", qsym, qargs, example_shape=(8,),
                         buckets=BUCKETS)
    server = serving.ModelServer(container, max_wait_ms=1.0).start()
    server.warmup()
    pre = _compile.stats().get("serving", {})
    for rows in (1, 2, 3, 4, 5, 8, 7, 6):
        y = server.predict(
            "qchild", rng.randn(rows, 8).astype(np.float32), timeout=30.0)
        assert y.shape == (rows, 4), y.shape
    post = _compile.stats().get("serving", {})
    stats = server.stats()["models"]["qchild"]
    server.drain(timeout=10.0)
    print("QCHILD " + json.dumps({
        "misses": post.get("misses", 0),
        "hits": post.get("hits", 0),
        "disk_hits": post.get("disk_hits", 0),
        "compile_ms": post.get("compile_ms", 0.0),
        "recompiles_during_traffic":
            post.get("misses", 0) - pre.get("misses", 0),
        "weight_dtype": stats.get("weight_dtype"),
        "buckets": stats.get("buckets"),
        "bucket_census": stats.get("bucket_census"),
    }), flush=True)


if __name__ == "__main__":
    main()
