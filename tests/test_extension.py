"""Custom operators + extension libraries.

Parity model: tests/python/unittest/test_operator.py::test_custom_op (the
reference's CustomOp suite) and example/extensions/lib_custom_op tests
(MXLoadLib). Covers the mx.operator CustomOp/CustomOpProp host on every
execution path (eager, tape, Symbol, hybridize) and mx.library.load for
both compiled and Python extensions."""
import os
import shutil
import subprocess

import numpy as onp
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        y = 1.0 / (1.0 + mx.nd.exp(-in_data[0]))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


@mx.operator.register("test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        return _Sigmoid()


def _ref_sigmoid(x):
    return 1.0 / (1.0 + onp.exp(-x))


def test_custom_op_eager_and_grad():
    x_np = onp.random.RandomState(0).randn(2, 5).astype(onp.float32)
    x = mx.nd.array(x_np)
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="test_sigmoid")
    y.backward(mx.nd.ones((2, 5)))
    s = _ref_sigmoid(x_np)
    onp.testing.assert_allclose(y.asnumpy(), s, rtol=1e-5)
    onp.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_custom_op_symbol_and_hybrid():
    x_np = onp.random.RandomState(1).randn(3, 4).astype(onp.float32)
    ref = _ref_sigmoid(x_np)

    data = mx.sym.var("data")
    s = mx.sym.Custom(data, op_type="test_sigmoid")
    ex = s.simple_bind(mx.cpu(), data=(3, 4))
    out = ex.forward(data=mx.nd.array(x_np))[0]
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)

    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Custom(x, op_type="test_sigmoid")

    net = Net()
    net.hybridize()
    out = net(mx.nd.array(x_np))
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)


class _AddSub(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] + in_data[1])
        self.assign(out_data[1], req[1], in_data[0] - in_data[1])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0] + out_grad[1])
        self.assign(in_grad[1], req[1], out_grad[0] - out_grad[1])


@mx.operator.register("test_addsub")
class _AddSubProp(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["a", "b"]

    def list_outputs(self):
        return ["sum", "diff"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _AddSub()


def test_custom_op_multi_output_grad():
    a_np = onp.random.RandomState(2).randn(4).astype(onp.float32)
    b_np = onp.random.RandomState(3).randn(4).astype(onp.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    a.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        s, d = mx.nd.Custom(a, b, op_type="test_addsub")
        loss = (s * 2 + d * 3).sum()
    loss.backward()
    onp.testing.assert_allclose(s.asnumpy(), a_np + b_np, rtol=1e-5)
    onp.testing.assert_allclose(d.asnumpy(), a_np - b_np, rtol=1e-5)
    onp.testing.assert_allclose(a.grad.asnumpy(), onp.full(4, 5.0), rtol=1e-5)
    onp.testing.assert_allclose(b.grad.asnumpy(), onp.full(4, -1.0), rtol=1e-5)


def test_custom_op_multi_output_symbol():
    """Regression: symbolic Custom must resolve its output count from the
    prop's list_outputs (used to build a 1-output node)."""
    a, b = mx.sym.var("a"), mx.sym.var("b")
    s = mx.sym.Custom(a, b, op_type="test_addsub")
    assert len(s.list_outputs()) == 2
    ex = s.simple_bind(mx.cpu(), a=(3,), b=(3,))
    outs = ex.forward(a=mx.nd.array([1.0, 2.0, 3.0]),
                      b=mx.nd.array([4.0, 5.0, 6.0]))
    onp.testing.assert_allclose(outs[0].asnumpy(), [5.0, 7.0, 9.0])
    onp.testing.assert_allclose(outs[1].asnumpy(), [-3.0, -3.0, -3.0])


def test_dynamic_output_ops_symbolic():
    """Regression: split/split_v2 node output counts follow their
    hyper-parameters symbolically."""
    d = mx.sym.var("d")
    s3 = mx.sym.split_v2(d, sections=3, axis=1)
    assert len(s3.list_outputs()) == 3
    ex = s3.simple_bind(mx.cpu(), d=(2, 6))
    outs = ex.forward(d=mx.nd.ones((2, 6)))
    assert [o.shape for o in outs] == [(2, 2)] * 3

    sc = mx.sym.SliceChannel(d, num_outputs=3, axis=1)
    assert len(sc.list_outputs()) == 3

    si = mx.sym.split_v2(d, indices=(1, 3), axis=1)
    assert len(si.list_outputs()) == 3


def test_custom_op_registry_queries():
    assert "test_sigmoid" in mx.operator.get_all_registered_operators()
    with pytest.raises(ValueError):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="definitely_not_registered")


def test_sym_varargs_inputs_not_spilled():
    """Regression: positional symbols must all land in the *arrays slot,
    never in trailing scalar-param slots (concat used to drop input 3)."""
    a, b, c = mx.sym.var("a"), mx.sym.var("b"), mx.sym.var("c")
    s = mx.sym.concat(a, b, c, dim=0)
    assert s.list_arguments() == ["a", "b", "c"]
    ex = s.simple_bind(mx.cpu(), a=(1, 2), b=(1, 2), c=(1, 2))
    out = ex.forward(a=mx.nd.ones((1, 2)), b=mx.nd.ones((1, 2)) * 2,
                     c=mx.nd.ones((1, 2)) * 3)[0]
    onp.testing.assert_allclose(out.asnumpy()[:, 0], [1.0, 2.0, 3.0])


# ---------------------------------------------------------------- library ---

@pytest.fixture(scope="module")
def ext_lib(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ available")
    src = os.path.join(REPO, "examples", "extensions", "lib_custom_op",
                       "relu_lib.cc")
    out = str(tmp_path_factory.mktemp("ext") / "librelu_lib.so")
    subprocess.run([gxx, "-shared", "-fPIC", "-O2", "-o", out, src],
                   check=True)
    return out


def test_library_load_so(ext_lib):
    info = mx.library.load(ext_lib)
    assert set(info["ops"]) == {"my_relu", "my_gemm"}
    x_np = onp.random.RandomState(4).randn(3, 7).astype(onp.float32)
    out = mx.nd.my_relu(mx.nd.array(x_np))
    onp.testing.assert_allclose(out.asnumpy(), onp.maximum(x_np, 0),
                                rtol=1e-6)
    a = onp.random.RandomState(5).randn(4, 3).astype(onp.float32)
    b = onp.random.RandomState(6).randn(3, 5).astype(onp.float32)
    out = mx.nd.my_gemm(mx.nd.array(a), mx.nd.array(b))
    onp.testing.assert_allclose(out.asnumpy(), a @ b, rtol=1e-4)


def test_library_load_so_in_hybrid_block(ext_lib):
    mx.library.load(ext_lib)

    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.my_relu(x)

    net = Net()
    net.hybridize()
    x_np = onp.array([[-1.0, 2.0]], onp.float32)
    onp.testing.assert_allclose(net(mx.nd.array(x_np)).asnumpy(),
                                [[0.0, 2.0]])


def test_library_load_py(tmp_path):
    ext = tmp_path / "my_ext.py"
    ext.write_text(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.ops import registry\n"
        "import jax.numpy as jnp\n"
        "registry.register('py_double')(lambda x: x * 2)\n")
    mx.library.load(str(ext))
    # loaded ops appear as mx.nd.<name>, like reference MXLoadLib ops
    out = mx.nd.py_double(mx.nd.ones((2, 2)))
    onp.testing.assert_allclose(out.asnumpy(), onp.full((2, 2), 2.0))


def test_library_load_missing_path():
    with pytest.raises(ValueError):
        mx.library.load("/nonexistent/lib.so")
