"""Test harness configuration.

Forces an 8-device virtual CPU mesh BEFORE jax initialises, so multi-device
sharding/collective tests run on any host (parity trick: the reference tests
multi-device logic with multiple cpu Contexts, SURVEY §4; TPU translation is
XLA's --xla_force_host_platform_device_count).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
