"""Test harness configuration.

Forces an 8-device virtual CPU mesh so multi-device sharding/collective
tests run on any host (parity trick: the reference tests multi-device logic
with multiple cpu Contexts, SURVEY §4; the TPU translation is XLA's
--xla_force_host_platform_device_count / jax_num_cpu_devices).

jax may already be imported by the environment's sitecustomize with a TPU
platform selected, so env vars are too late — use jax.config.update, which
takes effect as long as no backend has been initialised yet.

x64 is NOT enabled globally — production runs with it off, and the suite
must see production dtype semantics. float64 numeric-gradient checks scope
it locally via jax.experimental.enable_x64() (see test_utils).
Set MXNET_TEST_DEVICE=tpu:0 to run the suite against the real chip instead.
"""
import os

import jax

if os.environ.get("MXNET_TEST_DEVICE", "cpu").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


import numpy as _onp
import pytest as _pytest


@_pytest.fixture(autouse=True)
def _mxnet_test_seed():
    """Deterministic reruns under MXNET_TEST_SEED (parity: the reference
    test framework's with_seed decorator + tools/flakiness_checker)."""
    seed = os.environ.get("MXNET_TEST_SEED")
    if seed is not None:
        import mxnet_tpu as mx

        _onp.random.seed(int(seed))
        mx.random.seed(int(seed))
    yield
