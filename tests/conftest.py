"""Test harness configuration.

Forces an 8-device virtual CPU mesh so multi-device sharding/collective
tests run on any host (parity trick: the reference tests multi-device logic
with multiple cpu Contexts, SURVEY §4; the TPU translation is XLA's
--xla_force_host_platform_device_count / jax_num_cpu_devices).

jax may already be imported by the environment's sitecustomize with a TPU
platform selected, so env vars are too late — use jax.config.update, which
takes effect as long as no backend has been initialised yet.

x64 is NOT enabled globally — production runs with it off, and the suite
must see production dtype semantics. float64 numeric-gradient checks scope
it locally via jax.experimental.enable_x64() (see test_utils).
Set MXNET_TEST_DEVICE=tpu:0 to run the suite against the real chip instead.
"""
import os

import jax

if os.environ.get("MXNET_TEST_DEVICE", "cpu").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # jax < 0.5 spells this flag via XLA_FLAGS; still early enough as
        # long as no backend has been initialised
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")


# ------------------------------------------------- watchdog (observe mode) --
# CI hang diagnostics: a generous observe-mode deadline BELOW pytest's
# faulthandler_timeout (570s, pytest.ini) so a wedged test writes a crash
# bundle (all-thread tracebacks + last-N heartbeats) before faulthandler's
# stack dump fires — observe mode never interrupts anything and spawns no
# waiter threads. setdefault: an explicit MXNET_TPU_WATCHDOG wins. Tests
# that exercise the watchdog configure their own deadlines and restore the
# ambient config via watchdog.configure_from_env().
os.environ.setdefault("MXNET_TPU_WATCHDOG",
                      "*:540,action:observe,interval:60")

import numpy as _onp
import pytest as _pytest


# The backend-liveness probe (base.ensure_live_backend) latches its result
# into the process environment ON PURPOSE — MXTPU_PROBE_OK memoises a
# successful probe for the whole process tree, MXTPU_PLATFORM(+_FALLBACK)
# pin the CPU fallback. Inside one pytest process that latch is leaked
# global state: any test that runs an example main() in-process (they call
# probe_backend_or_fallback) flips MXTPU_PROBE_OK for every LATER test,
# which made test_ensure_live_backend_fallback_paths order-dependent in
# the full suite. Restore the probe vars around every test so no test can
# observe another's probe outcome.
_PROBE_ENV = ("MXTPU_PROBE_OK", "MXTPU_PLATFORM", "MXTPU_PLATFORM_FALLBACK")


@_pytest.fixture(autouse=True)
def _probe_env_guard():
    saved = {k: os.environ.get(k) for k in _PROBE_ENV}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@_pytest.fixture(autouse=True)
def _mxnet_test_seed():
    """Deterministic reruns under MXNET_TEST_SEED (parity: the reference
    test framework's with_seed decorator + tools/flakiness_checker)."""
    seed = os.environ.get("MXNET_TEST_SEED")
    if seed is not None:
        import mxnet_tpu as mx

        _onp.random.seed(int(seed))
        mx.random.seed(int(seed))
    yield


# ---------------------------------------------------------------- tiers ----
# Two test tiers (VERDICT r4 item 10): `pytest -m "not slow"` is the
# <3-minute smoke gate for inner-loop/driver use; the full suite stays the
# real gate. Slow = compile-heavy model sweeps, 2-process suites, and
# long-training tests, marked here centrally so the split is one list.
_SLOW_FILES = {
    "test_model_zoo.py",     # full model sweep, one XLA compile per arch
    "test_gluon_rnn.py",     # scan compiles + LM training
    "test_sparse_dist.py",   # 2-process distributed suites
    "test_onnx.py",          # export/import numeric roundtrips
    "test_op_sweep.py",      # 800-test registry-wide sweep (~2 min)
    "test_c_api.py",         # builds libmxtpu + four C host programs
}
_SLOW_TESTS = {
    "test_graft_entry_dryrun",
    "test_feedforward_legacy_api",
    "test_transformer_encoder_cell_trains",
    "test_multi_head_attention_kernel_path_and_export",
    "test_multi_head_attention_matches_oracle",
    "test_conv_rnn_cells",
    "test_norm_layers",
    "test_activations",
    "test_conv_layers",
    "test_train_conv",
    "test_train_mlp",
    "test_train_with_ndarray_iter_module_style",
    "test_gluon_data_pipeline_training_flow",
    "test_crash_course_gluon_train_loop",
    "test_module_workflow_checkpoints",
    "test_flash_gradients",
    "test_launch_local_sets_worker_env",
    "test_ring_attention_backward_matches_dense",
    "test_pipeline_parallel_matches_sequential",
    "test_amp_training_converges",
    "test_predict_abi_end_to_end",
    "test_sharded_trainer_matches_eager_optimizer",
    "test_factorization_machine_example",
    "test_transformer_finetune_example",
    "test_train_imagenet_benchmark_mode",
    "test_dcgan_example",
    "test_matrix_factorization_example",
    "test_multi_threaded_inference_abi",
    "test_sharded_trainer_multi_precision_master_weights",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.name.split("[")[0]
        if item.fspath.basename in _SLOW_FILES or base in _SLOW_TESTS:
            item.add_marker(_pytest.mark.slow)


# ------------------------------------------------------- per-test timeout --
# One hung test (deadlocked prefetch thread, wedged collective) must not
# eat the whole suite budget: raise TimeoutError inside the test after
# `test_timeout` seconds (pytest.ini; 0 disables). SIGALRM only fires on
# the main thread, which is where pytest runs tests; background threads a
# test spawned keep running and are the test's job to join. Complements
# the faulthandler_timeout stack dump (also pytest.ini).

def pytest_addoption(parser):
    parser.addini("test_timeout",
                  "per-test SIGALRM timeout in seconds (0 = off)",
                  default="0")


@_pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    import signal
    import threading

    seconds = int(item.config.getini("test_timeout") or 0)
    if (seconds <= 0 or not hasattr(signal, "SIGALRM")
            or threading.current_thread() is not threading.main_thread()):
        return (yield)

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"test exceeded the {seconds}s per-test timeout "
            "(test_timeout in pytest.ini)")

    old_handler = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(seconds)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old_handler)
