"""Optimizer / Trainer / lr_scheduler tests.

Parity model: tests/python/unittest/test_optimizer.py — each optimizer
checked against a pure-numpy reference implementation over several steps.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import lr_scheduler
from mxnet_tpu.gluon import nn, Trainer, loss as gloss
from mxnet_tpu.test_utils import assert_almost_equal


def run_optimizer(opt, w0, grads):
    w = mx.nd.array(w0.copy())
    state = opt.create_state(0, w)
    for g in grads:
        opt.update(0, w, mx.nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(5)]
    out = run_optimizer(mx.optimizer.SGD(learning_rate=0.1, wd=0.01), w0, grads)
    w = w0.copy()
    for g in grads:
        w = w - 0.1 * (g + 0.01 * w)
    assert_almost_equal(out, w, rtol=1e-5, atol=1e-6)


def test_sgd_momentum_matches_numpy():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(5)]
    out = run_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9), w0, grads)
    w = w0.copy()
    mom = np.zeros_like(w)
    for g in grads:
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    assert_almost_equal(out, w, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(5)]
    out = run_optimizer(mx.optimizer.Adam(learning_rate=0.01), w0, grads)
    w = w0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t, g in enumerate(grads, 1):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        w = w - lr * m / (np.sqrt(v) + eps)
    assert_almost_equal(out, w, rtol=1e-5, atol=1e-6)


def test_all_optimizers_step():
    """Every registered optimizer takes a step without error and changes w."""
    for name, klass in mx.optimizer.Optimizer.opt_registry.items():
        opt = klass()
        w = mx.nd.array(np.random.rand(4, 3).astype(np.float32))
        w0 = w.asnumpy().copy()
        state = opt.create_state(0, w)
        opt.update(0, w, mx.nd.array(np.random.rand(4, 3).astype(np.float32) + 0.1),
                   state)
        assert not np.allclose(w.asnumpy(), w0), f"{name} did not update"


def test_multi_precision():
    opt = mx.optimizer.SGD(learning_rate=0.1, multi_precision=True)
    w = mx.nd.array(np.random.rand(4).astype(np.float16), dtype=np.float16)
    state = opt.create_state_multi_precision(0, w)
    assert state[0].dtype == np.float32  # master weights
    g = mx.nd.array(np.random.rand(4).astype(np.float16), dtype=np.float16)
    opt.update_multi_precision(0, w, g, state)
    assert w.dtype == np.float16


def test_lr_mult_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=1.0,
                           param_idx2name={0: "w_weight", 1: "b_bias"}, wd=0.1)
    opt.set_lr_mult({"w_weight": 0.5})
    assert opt._get_lr(0) == 0.5
    assert opt._get_lr(1) == 1.0
    # bias gets wd 0 by default rule
    assert opt._get_wd(1) == 0.0


def test_create_by_name():
    opt = mx.optimizer.create("adam", learning_rate=0.1)
    assert isinstance(opt, mx.optimizer.Adam)
    assert opt.lr == 0.1
    with pytest.raises(ValueError):
        mx.optimizer.create("nope")


def test_trainer_training_decreases_loss():
    np.random.seed(1)
    mx.random.seed(1)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(1))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.05, "momentum": 0.9})
    L = gloss.L2Loss()
    x_np = np.random.rand(64, 8).astype(np.float32)
    y_np = (x_np.sum(axis=1, keepdims=True) * 0.5).astype(np.float32)
    x, y = mx.nd.array(x_np), mx.nd.array(y_np)
    losses = []
    for _ in range(40):
        with ag.record():
            out = net(x)
            loss = L(out, y)
        loss.backward()
        trainer.step(64)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.2, f"loss did not decrease: {losses[::10]}"


def test_trainer_states_roundtrip(tmp_path):
    net = nn.Dense(4, in_units=3)
    net.initialize()
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 0.1})
    x = mx.nd.ones((2, 3))
    with ag.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(2)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    trainer2 = Trainer(net.collect_params(), "adam", {"learning_rate": 0.1})
    trainer2.load_states(fname)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update


def test_learning_rate_property():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5})
    assert trainer.learning_rate == 0.5
    trainer.set_learning_rate(0.1)
    assert trainer.learning_rate == 0.1


def test_factor_scheduler():
    s = lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25


def test_multifactor_scheduler():
    s = lr_scheduler.MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert s(2) == 1.0
    assert abs(s(7) - 0.1) < 1e-9
    assert abs(s(12) - 0.01) < 1e-9


def test_poly_cosine_schedulers():
    p = lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0, pwr=2)
    assert p(0) == 1.0
    assert p(100) == 0.0
    c = lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.1)
    assert abs(c(0) - 1.0) < 1e-9
    assert abs(c(100) - 0.1) < 1e-9
    assert 0.1 < c(50) < 1.0


def test_warmup():
    s = lr_scheduler.FactorScheduler(step=100, factor=1.0, base_lr=1.0,
                                     warmup_steps=10, warmup_begin_lr=0.0)
    assert s(0) == 0.0
    assert abs(s(5) - 0.5) < 1e-9
    assert s(10) == 1.0


def test_optimizer_with_scheduler():
    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = mx.nd.ones((2,))
    state = opt.create_state(0, w)
    for _ in range(6):
        opt.update(0, w, mx.nd.ones((2,)), state)
    assert opt._get_lr(0) < 1.0


def test_stale_grad_detection():
    """parity: trainer.py raises UserWarning on stale grads; skip with
    ignore_stale_grad=True."""
    d1 = nn.Dense(4, in_units=3)
    d2 = nn.Dense(4, in_units=3)
    d1.initialize()
    d2.initialize()
    params = list(d1.collect_params().values()) + list(d2.collect_params().values())
    from mxnet_tpu.gluon import Trainer as T

    trainer = T(params, "sgd", {"learning_rate": 0.1})
    x = mx.nd.ones((2, 3))
    with ag.record():
        loss = d1(x).sum()  # d2 unused
    loss.backward()
    with pytest.raises(UserWarning):
        trainer.step(2)
    w2_before = d2.weight.data().asnumpy().copy()
    trainer.step(2, ignore_stale_grad=True)
    assert np.allclose(d2.weight.data().asnumpy(), w2_before)  # skipped


def test_adam_clips_after_wd():
    """Adam-family kernels clip rescale*grad + wd*weight (the sum), unlike
    SGD-family which clips before wd (ref optimizer_op-inl.h AdamUpdateKernel)."""
    w = mx.nd.array(np.full(4, 10.0, np.float32))
    g = mx.nd.array(np.full(4, 1.0, np.float32))
    mean = mx.nd.zeros(4)
    var = mx.nd.zeros(4)
    wd, clip, lr, b1, b2, eps = 0.1, 0.5, 0.01, 0.9, 0.999, 1e-8
    w2, mean2, var2 = mx.nd.invoke("adam_update", w, g, mean, var, lr=lr, beta1=b1,
                             beta2=b2, epsilon=eps, wd=wd, rescale_grad=1.0,
                             clip_gradient=clip)
    # grad + wd*w = 1 + 1.0 = 2.0 -> clipped to 0.5 (clip-before-wd would
    # give clip(1)=0.5 then +1.0 = 1.5)
    g_eff = 0.5
    m_ref = (1 - b1) * g_eff
    v_ref = (1 - b2) * g_eff ** 2
    w_ref = 10.0 - lr * m_ref / (np.sqrt(v_ref) + eps)
    np.testing.assert_allclose(mean2.asnumpy(), m_ref, rtol=1e-6)
    np.testing.assert_allclose(var2.asnumpy(), v_ref, rtol=1e-6)
    np.testing.assert_allclose(w2.asnumpy(), w_ref, rtol=1e-6)


def test_lbsgd_accumulates_and_warms_up():
    """LBSGD parity: gradient accumulation over batch_scale micro-batches;
    weight only changes at macro-batch boundaries; warmup ramps the lr."""
    opt = mx.optimizer.create("lbsgd", learning_rate=0.1, batch_scale=2,
                              warmup_strategy="linear", warmup_epochs=1,
                              updates_per_epoch=4)
    w = mx.nd.array([1.0])
    g = mx.nd.array([0.5])
    state = opt.create_state(0, w)
    before = float(w.asscalar())
    opt.update(0, w, g, state)  # micro-batch 1: accumulate only
    assert float(w.asscalar()) == before
    opt.update(0, w, g, state)  # micro-batch 2: apply averaged grad
    after = float(w.asscalar())
    assert after != before
    # averaged grad = 0.5; lr warmup mult at nup=2, nwup=4 -> 1 + 1*2/4
    expected = before - 0.1 * (1 + 1 * 2 / 4) * 0.5
    np.testing.assert_allclose(after, expected, rtol=1e-5)


def test_lbsgd_lars_strategy():
    opt = mx.optimizer.create("lbsgd", learning_rate=0.1, batch_scale=1,
                              warmup_strategy="lars")
    w = mx.nd.array([3.0, 4.0])   # ||w|| = 5
    g = mx.nd.array([0.3, 0.4])   # ||g|| = 0.5
    state = opt.create_state(0, w)
    opt.update(0, w, g, state)
    # lars = sqrt(25 / 0.25) = 10 -> effective lr 1.0
    np.testing.assert_allclose(w.asnumpy(), [3.0 - 0.3, 4.0 - 0.4],
                               rtol=1e-5)


def test_factor_milestones_absolute_under_warmup():
    """Decay windows/milestones are ABSOLUTE update counts — warmup must
    not shift the schedule (reference timing)."""
    s = lr_scheduler.MultiFactorScheduler(step=[100, 200], factor=0.1,
                                          base_lr=1.0, warmup_steps=50)
    assert abs(s(101) - 0.1) < 1e-12  # drops just after update 100
    assert abs(s(150) - 0.1) < 1e-12  # NOT shifted to 150
    f = lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0,
                                     warmup_steps=5)
    assert f(10) == 1.0
    assert f(11) == 0.5


def test_warmup_tracks_reseeded_base_lr():
    """Optimizer seeds scheduler.base_lr post-construction; the warmup
    ramp must end exactly at the new base lr (no discontinuity)."""
    s = lr_scheduler.FactorScheduler(step=1000, base_lr=0.01,
                                     warmup_steps=10, warmup_begin_lr=0.0)
    s.base_lr = 1.0
    assert abs(s(5) - 0.5) < 1e-12
    assert s(10) == 1.0


def test_span_scheduler_rejects_empty_anneal():
    with pytest.raises(ValueError, match="warmup_steps"):
        lr_scheduler.CosineScheduler(max_update=10, warmup_steps=10)
    with pytest.raises(ValueError, match="warmup_steps"):
        lr_scheduler.PolyScheduler(max_update=10, warmup_steps=15)


def test_scheduler_stateless_replay():
    """Calls are pure: out-of-order and repeated evaluation agree (the
    reference's stateful walk could not rewind — checkpoint-resume
    relies on this)."""
    s = lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    seq = [s(t) for t in (21, 1, 11, 21, 1)]
    assert seq == [0.25, 1.0, 0.5, 0.25, 1.0]
