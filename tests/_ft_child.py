"""Kill-and-resume child process for tests/test_fault_tolerance.py.

Runs a tiny deterministic ShardedTrainer fit, checkpointing through
CheckpointManager after every epoch. Driven entirely by env vars so the
parent test can run three variants of the SAME trajectory:

    FT_CKPT_DIR   checkpoint directory (shared between kill + resume runs)
    FT_EPOCHS     total epochs (default 3)
    FT_STEPS      steps per epoch (default 4)
    FT_RESUME     "1" -> resume from the manager's latest good checkpoint
    FT_OUT        where to np.savez the final parameter values
    MXNET_TPU_FAULTS  e.g. "trainer.step:kill@6" — SIGKILL mid-epoch-2,
                      exactly like a TPU preemption (no cleanup, no atexit)

Per-epoch batches are regenerated from a seed derived from the epoch
number, so a resumed run replays the identical data stream from the epoch
boundary; the trainer checkpoint restores params + optimizer state + step
counter + the RNG stream, so the continued trajectory is bit-exact versus
the uninterrupted run.
"""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer


def batch_for(epoch, step):
    rs = np.random.RandomState(1000 * epoch + step)
    x = rs.randn(8, 6).astype(np.float32)
    y = (x @ rs.randn(6, 4) * 0.5).astype(np.float32)
    return mx.nd.array(x), mx.nd.array(y)


def main():
    epochs = int(os.environ.get("FT_EPOCHS", "3"))
    steps = int(os.environ.get("FT_STEPS", "4"))
    ckpt_dir = os.environ["FT_CKPT_DIR"]
    out = os.environ["FT_OUT"]

    mx.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(batch_for(1, 0)[0])
    trainer = ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                             {"learning_rate": 0.05},
                             mesh=DeviceMesh({"dp": 1}))
    manager = CheckpointManager(ckpt_dir, prefix="ft", keep=3)

    start_epoch = 0
    if os.environ.get("FT_RESUME") == "1":
        entry = trainer.resume(manager)
        if entry is not None:
            start_epoch = entry["epoch"]

    for epoch in range(start_epoch + 1, epochs + 1):
        for step in range(steps):
            x, y = batch_for(epoch, step)
            trainer.step(x, y)
        trainer.save_checkpoint(manager, epoch)

    np.savez(out, **{name: p.data().asnumpy()
                     for name, p in net.collect_params().items()})
    print(f"FT_DONE t={trainer._t}")


if __name__ == "__main__":
    main()
