"""Model bus: live weight streaming from a training gang into a serving
fleet (mxnet_tpu/modelbus.py, docs/SERVING.md "Online updates").

Headline guarantees under test:

* record discipline — payload-then-manifest atomic writes with a CRC32
  manifest; full / int8-per-row / top-k-sparse-row encodings round-trip
  through the ONE decode seam (``decode_update``), and the publisher's
  finite gate never lets a NaN update onto the bus;
* subscriber validation — CRC corruption, census mismatch, and decoded
  non-finiteness each REJECT + quarantine the version while serving
  stays pinned on the last good one; torn manifests are skipped through
  the warn-once latch (counter keeps the true total);
* atomic flips — a version applies between batches as ONE pinned-tuple
  rebind: every response's outputs are consistent with its stamped
  ``model_version`` even while swaps hammer the server, and the warmed
  bucket ladder survives every flip with ZERO recompiles;
* compressed apply == full apply — the watcher's int8-row apply is
  bit-equal to manually decoding the record and swapping the raws;
* rollback = re-publish — a quarantined bus head triggers one idempotent
  re-publication of the newest good version, and subscribers converge;
* end to end — a real fleet worker subprocess subscribed via
  ``--bus-dir`` flips its served weights mid-load; every in-flight HTTP
  response sees exactly one consistent (version, outputs) pair.
"""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, modelbus, serving
from mxnet_tpu.gluon import nn
from mxnet_tpu.modelbus import BusWatcher, ModelBus, decode_update

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def make_net(seed, dim=8, hidden=16, classes=4):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu"), nn.Dense(classes))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, dim)))
    return net


def net_params(net, delta=0.0):
    """``[(name, host array + delta)]`` in collect_params order — the
    publisher's view of a gluon net."""
    return [(n, p.data().asnumpy() + delta)
            for n, p in net.collect_params().items()]


@pytest.fixture()
def servers():
    """Cleanup registry: every server appended here is drained."""
    out = []
    yield out
    for s in out:
        try:
            s.drain(timeout=10.0)
        except Exception:
            pass
    faults.reset()


def serve(net, servers, name="m", dim=8):
    c = serving.ModelContainer()
    c.add_block(name, net, example_shape=(dim,), buckets=(2, 4))
    server = serving.ModelServer(c, max_wait_ms=1.0).start()
    servers.append(server)
    return server, next(iter(c))


# ----------------------------------------------------- record round-trip ---

def test_roundtrip_full_and_int8(tmp_path):
    bus = ModelBus(tmp_path / "bus", compress_threshold=64)
    rs = np.random.RandomState(0)
    w = rs.randn(32, 16).astype(np.float32)      # 512 elems -> int8_rows
    w[3] = 0.0                                   # zero row: exact decode
    b = rs.randn(8).astype(np.float32)           # small -> full
    v = bus.publish([("w", w), ("b", b)], step=7, aux=[("mean", b * 2)])
    assert v == 1
    manifest, blob = bus.read(v)                 # size+CRC verified
    assert manifest["step"] == 7
    assert [e["encoding"] for e in manifest["params"]] == \
        ["int8_rows", "full"]
    (dw, db), (dmean,) = decode_update(manifest, blob)
    assert np.array_equal(db, b)                 # full rides exact
    assert np.array_equal(dmean, b * 2)
    assert np.array_equal(dw[3], w[3])           # zero row exact
    # int8-per-row: error bounded by half a quantization step per row
    step_sz = np.abs(w).max(axis=1, keepdims=True) / 127.0
    assert (np.abs(dw - w) <= step_sz * 0.5 + 1e-7).all()
    assert dw.dtype == w.dtype and dw.shape == w.shape


def test_topk_rows_diff_against_previous_publish(tmp_path):
    bus = ModelBus(tmp_path / "bus")
    rs = np.random.RandomState(1)
    table = rs.randn(64, 8).astype(np.float32)
    v1 = bus.publish([("table", table)], step=1, topk={"table": 4})
    m1 = bus.latest()
    # nothing to diff against yet -> self-contained full record
    assert m1["params"][0]["encoding"] == "full"
    assert m1["base_version"] is None

    new = table.copy()
    hot = [3, 17, 40, 63]
    new[hot] += 5.0                              # the k most-changed rows
    new += rs.randn(*new.shape).astype(np.float32) * 1e-4  # background drift
    v2 = bus.publish([("table", new)], step=2, topk={"table": 4})
    manifest, blob = bus.read(v2)
    ent = manifest["params"][0]
    assert ent["encoding"] == "topk_rows" and ent["rows"] == 4
    assert manifest["base_version"] == v1
    params, _aux = decode_update(manifest, blob, base_params=[table])
    dec = params[0]
    assert np.array_equal(dec[hot], new[hot])    # hot rows ride exact
    cold = [i for i in range(64) if i not in hot]
    assert np.array_equal(dec[cold], table[cold])  # cold rows = base


def test_finite_gate_never_publishes_nan(tmp_path):
    bus = ModelBus(tmp_path / "bus")
    before = modelbus.stats()
    bad = np.ones((4, 4), np.float32)
    bad[1, 2] = np.nan
    assert bus.publish([("w", bad)], step=1) is None
    assert bus.manifests() == [] and bus.versions() == []
    after = modelbus.stats()
    assert after["publish_skipped_nonfinite"] == \
        before["publish_skipped_nonfinite"] + 1
    assert after["published"] == before["published"]


def test_torn_manifest_skipped_with_warn_once_latch(tmp_path, monkeypatch):
    warns = []
    monkeypatch.setattr(
        modelbus._logger, "warning",
        lambda msg, *a, **k: warns.append(msg % a if a else msg))
    bus = ModelBus(tmp_path / "bus")
    v = bus.publish([("w", np.ones((2, 2), np.float32))], step=1)
    (tmp_path / "bus" / "v00000009.json").write_text("{ torn")
    before = modelbus.stats()["torn_skips"]
    assert [m["version"] for m in bus.manifests()] == [v]
    assert [m["version"] for m in bus.manifests()] == [v]
    # the counter saw both skips; the log saw exactly one line
    assert bus.torn_skips == 2
    assert modelbus.stats()["torn_skips"] == before + 2
    assert len([w for w in warns if "torn" in w]) == 1


# -------------------------------------------------- subscriber validation ---

def test_crc_corruption_quarantined(tmp_path, servers):
    net = make_net(20)
    server, model = serve(net, servers)
    bus = ModelBus(tmp_path / "bus")
    v = bus.publish(net_params(net, delta=0.5), step=1)
    blob = bytearray((tmp_path / "bus" / f"v{v:08d}.update").read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    (tmp_path / "bus" / f"v{v:08d}.update").write_bytes(bytes(blob))

    w = BusWatcher(server, bus, worker="t-crc")
    assert w.poll_once() is None
    assert w.rejected == {v: "crc_mismatch"}
    assert bus.quarantined() == {v}
    assert model.version == 0 and w.applied_version == 0
    (rej,) = [r for r in bus.rejects() if r["version"] == v]
    assert rej["worker"] == "t-crc" and rej["reason"] == "crc_mismatch"
    # quarantined versions are never retried
    assert w.poll_once() is None


def test_census_mismatch_rejected(tmp_path, servers):
    net = make_net(21)
    server, model = serve(net, servers)
    bus = ModelBus(tmp_path / "bus")
    v = bus.publish([("w", np.ones((3, 3), np.float32))], step=1)
    w = BusWatcher(server, bus, worker="t-census")
    assert w.poll_once() is None
    assert w.rejected == {v: "census_mismatch"}
    assert model.version == 0


def test_poisoned_update_rejected_serving_stays_pinned(tmp_path, servers):
    net = make_net(22)
    server, model = serve(net, servers)
    bus = ModelBus(tmp_path / "bus")
    w = BusWatcher(server, bus, worker="t-poison")
    good = bus.publish(net_params(net, delta=0.25), step=1)
    assert w.poll_once() == good

    # in-transit poison: the injection point fires AFTER the finite
    # gate, so the record publishes and the SUBSCRIBER must catch it
    faults.configure("modelbus.publish:nan@1", seed=0)
    try:
        poisoned = bus.publish(net_params(net, delta=0.75), step=2)
    finally:
        faults.reset()
    assert poisoned is not None
    assert w.poll_once() is None
    assert w.rejected[poisoned] == "nonfinite"
    assert poisoned in bus.quarantined()
    assert model.version == good and w.applied_version == good


# ------------------------------------------------------------ live swaps ---

def test_swap_applies_new_weights_with_zero_recompiles(tmp_path, servers):
    from mxnet_tpu import compile as _compile

    net = make_net(23)
    server, model = serve(net, servers)
    server.warmup()
    misses0 = _compile.stats()["serving"]["misses"]
    x = np.random.RandomState(3).randn(2, 8).astype(np.float32)
    y0 = np.asarray(server.predict("m", x, timeout=10.0))

    bus = ModelBus(tmp_path / "bus")
    v = bus.publish(net_params(net, delta=0.5), step=9)
    w = BusWatcher(server, bus, worker="t-swap")
    assert w.poll_once() == v

    fut = server.submit("m", x)
    y1 = np.asarray(fut.result(10.0))
    assert fut.model_version == v            # responses carry the version
    assert not np.allclose(y0, y1)           # the weights really flipped
    assert model.version == v and model.swaps == 1
    assert w.age_steps() == 0 and w.applied_models == ["m"]
    assert _compile.stats()["serving"]["misses"] == misses0
    st = server.stats()
    assert st["models"]["m"]["model_version"] == v
    assert st["models"]["m"]["weight_swaps"] == 1
    assert st["model_bus"] is None           # watch_bus() not used here


def test_compressed_apply_bit_equal_to_full_apply(tmp_path, servers):
    """The decode seam: a watcher applying an int8-compressed record
    leaves the SAME device bytes as manually decoding the record and
    swapping the raws — compression changes the wire format, never the
    applied weights."""
    import jax

    net_a, net_b = make_net(24), make_net(24)
    server_a, model_a = serve(net_a, servers, name="a")
    server_b, model_b = serve(net_b, servers, name="b")
    bus = ModelBus(tmp_path / "bus", compress_threshold=32)
    v = bus.publish(net_params(net_a, delta=0.5), step=1)
    assert "int8_rows" in {e["encoding"]
                           for e in bus.latest()["params"]}

    w = BusWatcher(server_a, bus, worker="t-seam")
    assert w.poll_once() == v                      # the watcher's apply
    manifest, blob = bus.read(v)
    params, aux = decode_update(manifest, blob)    # the manual apply
    # net_b carries its own gluon auto-prefix, so the record maps onto
    # it positionally (collect_params order) — the watcher's fallback
    model_b.swap_params(params, v)

    for ra, rb in zip(model_a.pinned()[0], model_b.pinned()[0]):
        assert np.array_equal(np.asarray(jax.device_get(ra)),
                              np.asarray(jax.device_get(rb)))
    x = np.random.RandomState(4).randn(3, 8).astype(np.float32)
    assert np.array_equal(
        np.asarray(server_a.predict("a", x, timeout=10.0)),
        np.asarray(server_b.predict("b", x, timeout=10.0)))


def test_atomic_flip_every_response_consistent_with_its_version(
        tmp_path, servers):
    """Hammer swaps under load: output = bias = the version constant, so
    a torn flip (some new params, some old, or a version stamp that does
    not match the weights) is directly visible in any response."""
    net = make_net(25)
    params = list(net.collect_params().values())
    for p in params:
        p.set_data(mx.nd.zeros(p.shape))
    server, model = serve(net, servers)
    bus = ModelBus(tmp_path / "bus")
    w = BusWatcher(server, bus, worker="t-atomic")
    names = list(net.collect_params())
    shapes = [tuple(p.shape) for p in params]

    stop = threading.Event()
    bad, checked = [], [0]
    x = np.zeros((1, 8), np.float32)

    def load():
        while not stop.is_set():
            fut = server.submit("m", x)
            out = np.asarray(fut.result(10.0))
            v = fut.model_version
            # all outputs equal the bias constant of ONE version, and
            # that version is the one stamped on the response
            if not np.array_equal(out, np.full_like(out, float(v))):
                bad.append((v, out.tolist()))
            checked[0] += 1

    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    for v in range(1, 7):
        pub = [(n, np.full(s, float(v), np.float32)
                if len(s) == 1 else np.zeros(s, np.float32))
               for n, s in zip(names, shapes)]
        assert bus.publish(pub, step=v) == v
        assert w.poll_once() == v
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    assert not bad, bad[:3]
    assert checked[0] > 0 and model.version == 6


# --------------------------------------------------------------- rollback ---

def test_rollback_republishes_last_good_version(tmp_path, servers):
    import jax

    net = make_net(26)
    server, model = serve(net, servers)
    bus = ModelBus(tmp_path / "bus")
    w = BusWatcher(server, bus, worker="t-rollback")
    before = modelbus.stats()["rollbacks"]
    good = bus.publish(net_params(net, delta=0.25), step=1)
    assert w.poll_once() == good
    good_raws = [np.asarray(jax.device_get(r))
                 for r in model.pinned()[0]]

    faults.configure("modelbus.publish:nan@1", seed=0)
    try:
        poisoned = bus.publish(net_params(net, delta=0.75), step=2)
    finally:
        faults.reset()
    assert w.poll_once() is None and poisoned in bus.quarantined()

    # rollback = re-publication of the newest good version
    rb = bus.auto_rollback(worker="publisher")
    assert rb == poisoned + 1
    m = bus.latest()
    assert m["version"] == rb and m["step"] == 1
    assert m["meta"] == {"rollback_of": poisoned,
                         "source_version": good}
    assert modelbus.stats()["rollbacks"] == before + 1
    assert bus.auto_rollback(worker="publisher") is None   # idempotent

    assert w.poll_once() == rb
    for ra, g in zip(model.pinned()[0], good_raws):
        assert np.array_equal(np.asarray(jax.device_get(ra)), g)
    assert w.stats()["applied_version"] == rb
    assert w.stats()["rejected"] == {poisoned: "nonfinite"}


# -------------------------------------------------------------- publisher ---

def test_trainer_publishes_every_k_steps(tmp_path):
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    net = make_net(27)
    trainer = ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                             {"learning_rate": 0.01}, mesh=DeviceMesh())
    bus = trainer.publish_to(tmp_path / "bus", every=2)
    assert isinstance(bus, ModelBus)
    rs = np.random.RandomState(5)
    for _ in range(4):
        x = mx.nd.array(rs.randn(16, 8).astype(np.float32))
        y = mx.nd.array(rs.randn(16, 4).astype(np.float32))
        trainer.step(x, y)
    assert trainer.published_versions == [1, 2]
    mans = bus.manifests()
    assert [m["step"] for m in mans] == [2, 4]
    assert [e["name"] for e in mans[-1]["params"]] == \
        list(net.collect_params())
    # the published weights are the trainer's CURRENT weights
    manifest, blob = bus.read(mans[-1]["version"])
    params, _aux = decode_update(manifest, blob)
    live = [p.data().asnumpy() for p in net.collect_params().values()]
    for got, want in zip(params, live):
        assert np.allclose(got, want)


# ------------------------------------------------------------- end to end ---

def test_fleet_worker_streams_versions_end_to_end(tmp_path):
    """A real fleet worker subprocess subscribed via --bus-dir: served
    outputs change across a mid-load version flip, every in-flight HTTP
    response sees exactly one consistent (model_version, outputs) pair,
    and the fleet surfaces the bus in its stats."""
    import loadgen
    from mxnet_tpu.serving import fleet as fleet_mod
    from mxnet_tpu.serving import worker as worker_mod

    model_dir = tmp_path / "models"
    bus_dir = tmp_path / "bus"
    worker_mod.write_spec(
        model_dir, worker_mod.demo_spec(models=1, seed=777,
                                        buckets=(2, 4)))
    fl = fleet_mod.ServingFleet(
        model_dir, workers=1, run_dir=str(tmp_path / "run"),
        bus_dir=str(bus_dir),
        config={"min": 1, "max": 1, "beat": 0.2, "grace": 20},
        name="t-bus")
    stop = threading.Event()
    lock = threading.Lock()
    seen, errors = [], []     # (model_version, outputs tuple)
    x = np.random.RandomState(9).randn(1, 16).astype(np.float32)
    body = json.dumps({"data": x.tolist()}).encode()

    def load():
        cl = loadgen.KeepAliveClient(fl.url)
        while not stop.is_set():
            try:
                status, payload, _ = cl.request(
                    "POST", "/v1/models/model0:predict", body=body,
                    headers={"Content-Type": "application/json"})
            except Exception as e:
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                continue
            if status == 200:
                data = json.loads(payload)
                with lock:
                    seen.append((data["model_version"],
                                 tuple(data["outputs"][0][0])))
            elif status not in (429, 503):
                with lock:
                    errors.append(f"HTTP {status}")
            time.sleep(0.005)

    try:
        fl.start(timeout=90)
        threads = [threading.Thread(target=load, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with lock:
                if any(v == 0 for v, _o in seen):
                    break
            time.sleep(0.05)

        # publish from the "trainer" process: same seeded demo net, new
        # weights (param names differ across processes — the census
        # falls back to positional matching)
        net = worker_mod.build_demo_model(777)
        bus = ModelBus(bus_dir)
        v = bus.publish(net_params(net, delta=0.25), step=50,
                        model="model0")
        while time.monotonic() < deadline:
            with lock:
                if any(vv == v for vv, _o in seen):
                    break
            time.sleep(0.05)
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        stats = fl.stats()
    finally:
        stop.set()
        fl.stop()

    assert not errors, errors[:3]
    versions = {vv for vv, _o in seen}
    assert {0, v} <= versions, versions
    by_version = {}
    for vv, outs in seen:
        by_version.setdefault(vv, set()).add(outs)
    # exactly one consistent output per version — no torn flips, and
    # the flip REALLY changed what the model serves
    assert all(len(outs) == 1 for outs in by_version.values()), \
        {vv: len(o) for vv, o in by_version.items()}
    assert by_version[0] != by_version[v]
    assert stats["bus_dir"] == str(bus_dir)
    ann = worker_mod.read_workers(fl.run_dir)[0]
    mb = ann.get("model_bus")
    assert mb is not None and mb["bus_dir"] == str(bus_dir)
