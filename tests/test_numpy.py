"""mx.np / mx.npx oracle tests vs real NumPy (parity model:
tests/python/unittest/test_numpy_op.py + test_numpy_interoperability.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx

np = mx.np
npx = mx.npx

RS = onp.random.RandomState(42)


def _rand(*shape):
    return RS.randn(*shape).astype(onp.float32)


def _check(mx_out, onp_out, rtol=1e-5, atol=1e-5):
    onp.testing.assert_allclose(mx_out.asnumpy(), onp_out, rtol=rtol,
                                atol=atol)


# ------------------------------------------------------------- creation ----

def test_creation_functions():
    assert np.ones((2, 3)).shape == (2, 3)
    assert np.zeros(4).shape == (4,)
    _check(np.full((2, 2), 7.0), onp.full((2, 2), 7.0))
    _check(np.arange(10), onp.arange(10))
    _check(np.linspace(0, 1, 5), onp.linspace(0, 1, 5).astype("float32"))
    _check(np.eye(3), onp.eye(3, dtype="float32"))
    a = np.array([[1, 2], [3, 4]], dtype="float32")
    _check(np.zeros_like(a), onp.zeros((2, 2), "float32"))
    _check(np.ones_like(a), onp.ones((2, 2), "float32"))
    assert np.array(3.5).shape == ()  # zero-dim supported


UNARY_CASES = [
    ("absolute", onp.abs), ("sqrt", onp.sqrt), ("exp", onp.exp),
    ("log", onp.log), ("sin", onp.sin), ("cos", onp.cos),
    ("tanh", onp.tanh), ("floor", onp.floor), ("ceil", onp.ceil),
    ("square", onp.square), ("sign", onp.sign), ("log1p", onp.log1p),
    ("expm1", onp.expm1), ("arctan", onp.arctan), ("sinh", onp.sinh),
    ("cbrt", onp.cbrt), ("radians", onp.radians), ("degrees", onp.degrees),
]


@pytest.mark.parametrize("name,ofn", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_oracle(name, ofn):
    x = onp.abs(_rand(3, 4)) + 0.5  # positive domain works for all cases
    _check(getattr(np, name)(np.array(x)), ofn(x), rtol=1e-4, atol=1e-5)


BINARY_CASES = [
    ("add", onp.add), ("subtract", onp.subtract),
    ("multiply", onp.multiply), ("true_divide", onp.true_divide),
    ("power", onp.power), ("maximum", onp.maximum),
    ("minimum", onp.minimum), ("hypot", onp.hypot),
    ("arctan2", onp.arctan2), ("logaddexp", onp.logaddexp),
    ("fmod", onp.fmod), ("copysign", onp.copysign),
]


@pytest.mark.parametrize("name,ofn", BINARY_CASES,
                         ids=[c[0] for c in BINARY_CASES])
def test_binary_oracle(name, ofn):
    a, b = onp.abs(_rand(3, 4)) + 0.5, onp.abs(_rand(3, 4)) + 0.5
    _check(getattr(np, name)(np.array(a), np.array(b)), ofn(a, b),
           rtol=1e-4, atol=1e-5)


def test_broadcasting_and_scalars():
    a = _rand(3, 1)
    b = _rand(1, 4)
    _check(np.array(a) + np.array(b), a + b)
    _check(np.array(a) * 2.5, a * 2.5)
    _check(3.0 - np.array(a), 3.0 - a)
    _check(2.0 / np.array(onp.abs(a) + 1), 2.0 / (onp.abs(a) + 1))


def test_comparisons_return_bool():
    a = np.array([1.0, 2.0, 3.0])
    m = a > 2.0
    assert onp.dtype(m.dtype) == onp.bool_
    _check(m.astype("float32"), onp.array([0.0, 0.0, 1.0]))
    assert bool((np.array([1.0]) == np.array([1.0])).item())


def test_boolean_indexing():
    x = _rand(4, 5)
    a = np.array(x)
    mask = a > 0
    _check(a[mask], x[x > 0])
    # fancy integer indexing
    idx = onp.array([2, 0, 3])
    _check(a[np.array(idx, dtype="int32")], x[idx])


REDUCE_CASES = [
    ("sum", onp.sum), ("mean", onp.mean), ("prod", onp.prod),
    ("max", onp.max), ("min", onp.min), ("std", onp.std), ("var", onp.var),
]


@pytest.mark.parametrize("name,ofn", REDUCE_CASES,
                         ids=[c[0] for c in REDUCE_CASES])
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_reductions_oracle(name, ofn, axis):
    x = _rand(3, 4)
    _check(getattr(np, name)(np.array(x), axis=axis), ofn(x, axis=axis),
           rtol=1e-4, atol=1e-5)


def test_argmax_sort_cumsum():
    x = _rand(4, 5)
    a = np.array(x)
    _check(np.argmax(a, axis=1), onp.argmax(x, axis=1))
    _check(np.argmin(a, axis=0), onp.argmin(x, axis=0))
    _check(np.sort(a, axis=1), onp.sort(x, axis=1))
    _check(np.argsort(a, axis=1), onp.argsort(x, axis=1))
    _check(np.cumsum(a, axis=0), onp.cumsum(x, axis=0), rtol=1e-4)


def test_shape_manipulation():
    x = _rand(2, 3, 4)
    a = np.array(x)
    _check(a.reshape(6, 4), x.reshape(6, 4))
    _check(a.T, x.T)
    _check(np.transpose(a, (2, 0, 1)), onp.transpose(x, (2, 0, 1)))
    _check(np.swapaxes(a, 0, 2), onp.swapaxes(x, 0, 2))
    _check(np.expand_dims(a, 1), onp.expand_dims(x, 1))
    _check(np.squeeze(np.ones((1, 3, 1))), onp.ones(3, "float32"))
    _check(np.broadcast_to(np.ones((1, 3)), (4, 3)),
           onp.ones((4, 3), "float32"))
    _check(np.tile(a, (2, 1, 1)), onp.tile(x, (2, 1, 1)))
    _check(np.repeat(a, 2, axis=1), onp.repeat(x, 2, axis=1))
    _check(np.flip(a, axis=0), onp.flip(x, axis=0))
    _check(np.roll(a, 1, axis=2), onp.roll(x, 1, axis=2))


def test_concatenate_stack_split():
    x, y = _rand(2, 3), _rand(2, 3)
    _check(np.concatenate([np.array(x), np.array(y)], axis=0),
           onp.concatenate([x, y], axis=0))
    _check(np.stack([np.array(x), np.array(y)], axis=1),
           onp.stack([x, y], axis=1))
    _check(np.vstack([np.array(x), np.array(y)]), onp.vstack([x, y]))
    _check(np.hstack([np.array(x), np.array(y)]), onp.hstack([x, y]))
    parts = np.split(np.array(x), 3, axis=1)
    oparts = onp.split(x, 3, axis=1)
    assert len(parts) == 3
    for p, op_ in zip(parts, oparts):
        _check(p, op_)


def test_where_take_clip():
    x = _rand(3, 4)
    a = np.array(x)
    _check(np.where(a > 0, a, np.zeros_like(a)), onp.where(x > 0, x, 0))
    _check(np.clip(a, -0.5, 0.5), onp.clip(x, -0.5, 0.5))
    idx = onp.array([0, 2])
    _check(np.take(a, np.array(idx, "int32"), axis=1),
           onp.take(x, idx, axis=1))


def test_einsum_oracle():
    a, b = _rand(3, 4), _rand(4, 5)
    _check(np.einsum("ij,jk->ik", np.array(a), np.array(b)),
           onp.einsum("ij,jk->ik", a, b), rtol=1e-4)
    c = _rand(2, 3, 4)
    _check(np.einsum("bij->bji", np.array(c)), onp.einsum("bij->bji", c))
    _check(np.einsum("ii->", np.array(_rand(4, 4) * 0 + onp.eye(4, dtype="float32"))),
           onp.array(4.0, "float32"))


def test_tensordot_matmul_dot():
    a, b = _rand(3, 4), _rand(4, 5)
    _check(np.tensordot(np.array(a), np.array(b), axes=1), a @ b, rtol=1e-4)
    _check(np.matmul(np.array(a), np.array(b)), a @ b, rtol=1e-4)
    _check(np.array(a) @ np.array(b), a @ b, rtol=1e-4)
    _check(np.dot(np.array(a), np.array(b)), onp.dot(a, b), rtol=1e-4)
    t1, t2 = _rand(2, 3, 4), _rand(4, 3, 2)
    _check(np.tensordot(np.array(t1), np.array(t2), axes=((1, 2), (1, 0))),
           onp.tensordot(t1, t2, axes=((1, 2), (1, 0))), rtol=1e-4)


def test_linalg_oracle():
    a = _rand(4, 4) + 4 * onp.eye(4, dtype="float32")  # well-conditioned
    A = np.array(a)
    _check(np.linalg.inv(A), onp.linalg.inv(a), rtol=1e-3, atol=1e-4)
    _check(np.linalg.det(A), onp.linalg.det(a), rtol=1e-3)
    sign, logdet = np.linalg.slogdet(A)
    osign, ologdet = onp.linalg.slogdet(a)
    assert float(sign.item()) == pytest.approx(float(osign))
    assert float(logdet.item()) == pytest.approx(float(ologdet), rel=1e-3)
    b = _rand(4, 2)
    _check(np.linalg.solve(A, np.array(b)), onp.linalg.solve(a, b),
           rtol=1e-3, atol=1e-4)
    q, r = np.linalg.qr(np.array(a))
    onp.testing.assert_allclose((q.asnumpy() @ r.asnumpy()), a, atol=1e-4)
    spd = a @ a.T + onp.eye(4, dtype="float32")
    L = np.linalg.cholesky(np.array(spd))
    onp.testing.assert_allclose(L.asnumpy() @ L.asnumpy().T, spd, rtol=1e-3,
                                atol=1e-3)
    w, v = np.linalg.eigh(np.array(spd))
    ow = onp.linalg.eigvalsh(spd)
    onp.testing.assert_allclose(onp.sort(w.asnumpy()), onp.sort(ow),
                                rtol=1e-3, atol=1e-3)
    _check(np.linalg.norm(A), onp.linalg.norm(a), rtol=1e-4)
    u, s, vt = np.linalg.svd(np.array(a))
    onp.testing.assert_allclose(
        u.asnumpy() @ onp.diag(s.asnumpy()) @ vt.asnumpy(), a, atol=1e-3)


def test_random_sanity():
    np.random.seed(7)
    u = np.random.uniform(2.0, 3.0, size=(1000,))
    arr = u.asnumpy()
    assert arr.min() >= 2.0 and arr.max() <= 3.0
    assert abs(arr.mean() - 2.5) < 0.05
    n = np.random.normal(0.0, 1.0, size=(2000,)).asnumpy()
    assert abs(n.mean()) < 0.1 and abs(n.std() - 1.0) < 0.1
    r = np.random.randint(0, 10, size=(500,)).asnumpy()
    assert r.min() >= 0 and r.max() < 10
    # seeding reproduces
    np.random.seed(3)
    a1 = np.random.uniform(size=(5,)).asnumpy()
    np.random.seed(3)
    a2 = np.random.uniform(size=(5,)).asnumpy()
    onp.testing.assert_array_equal(a1, a2)
    assert np.random.choice(5, size=(3,)).shape == (3,)
    p = np.random.permutation(10).asnumpy()
    assert sorted(p.tolist()) == list(range(10))


def test_np_autograd():
    w = np.array([1.0, 2.0, 3.0])
    w.attach_grad()
    with mx.autograd.record():
        loss = np.sum(w * w + np.exp(w))
    loss.backward()
    onp.testing.assert_allclose(
        w.grad.asnumpy(), 2 * onp.array([1, 2, 3]) + onp.exp([1, 2, 3]),
        rtol=1e-5)
    assert isinstance(w.grad, np.ndarray)


def test_np_einsum_autograd():
    a = np.array(_rand(3, 4))
    b = np.array(_rand(4, 5))
    a.attach_grad()
    with mx.autograd.record():
        out = np.einsum("ij,jk->ik", a, b).sum()
    out.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                b.asnumpy().sum(axis=1)[None, :].repeat(3, 0),
                                rtol=1e-4)


def test_npx_nn_ops():
    x = np.array(_rand(2, 8))
    w = np.array(_rand(4, 8))
    b = np.array(_rand(4))
    out = npx.fully_connected(x, w, b, num_hidden=4)
    _check(out, x.asnumpy() @ w.asnumpy().T + b.asnumpy(), rtol=1e-4)
    assert isinstance(out, np.ndarray)
    r = npx.relu(np.array([-1.0, 1.0]))
    _check(r, onp.array([0.0, 1.0]))
    sm = npx.softmax(np.array([[1.0, 2.0, 3.0]]))
    e = onp.exp([1.0, 2.0, 3.0])
    _check(sm, (e / e.sum())[None, :].astype("float32"), rtol=1e-5)
    oh = npx.one_hot(np.array([0, 2], dtype="int32"), depth=3)
    _check(oh, onp.eye(3, dtype="float32")[[0, 2]])


def test_npx_set_np_roundtrip():
    assert not npx.is_np_array()
    npx.set_np()
    assert npx.is_np_array() and npx.is_np_shape()
    npx.reset_np()
    assert not npx.is_np_array()


def test_np_save_load(tmp_path):
    f = str(tmp_path / "arrs.npz")
    d = {"a": np.ones((2, 2)), "b": np.arange(3)}
    npx.save(f, d)
    loaded = npx.load(f)
    assert isinstance(loaded["a"], np.ndarray)
    _check(loaded["a"], onp.ones((2, 2), "float32"))


def test_np_nd_interop():
    a = np.ones((2, 2))
    legacy = a.as_nd_ndarray()
    assert type(legacy).__name__ == "NDArray"
    back = np._as_np(legacy)
    assert isinstance(back, np.ndarray)


def test_np_statistics():
    x = _rand(100)
    a = np.array(x)
    _check(np.median(a), onp.median(x), rtol=1e-5)
    _check(np.percentile(a, 30.0), onp.percentile(x, 30.0).astype("float32"),
           rtol=1e-3)
    _check(np.diff(a), onp.diff(x), rtol=1e-4)
    h, edges = np.histogram(a, bins=10)
    oh, oe = onp.histogram(x, bins=10)
    onp.testing.assert_array_equal(h.asnumpy(), oh)


def test_positional_args_bind_correctly():
    # regression: _op1 used to silently drop positional args
    x = onp.array([[1.0, 2.0], [3.0, 4.0]], "float32")
    a = np.array(x)
    _check(np.tril(a, 1), onp.tril(x, 1))
    _check(np.tril(a, -1), onp.tril(x, -1))
    _check(np.triu(a, 1), onp.triu(x, 1))
    _check(np.cumsum(a, 1), onp.cumsum(x, 1))
    _check(np.diag(np.array([1.0, 2.0]), 1), onp.diag(onp.array([1.0, 2.0], "float32"), 1))


def test_dynamic_shape_ops_eager():
    # regression: nonzero/unique/bincount used to fail under the op jit
    x = onp.array([[0.0, 1.0], [2.0, 0.0]], "float32")
    a = np.array(x)
    rows, cols = np.nonzero(a)
    onp.testing.assert_array_equal(rows.asnumpy(), [0, 1])
    onp.testing.assert_array_equal(cols.asnumpy(), [1, 0])
    idx = np.where(a > 0)
    assert isinstance(idx, tuple) and len(idx) == 2
    u = np.unique(np.array([3, 1, 3, 2], dtype="int32"))
    onp.testing.assert_array_equal(u.asnumpy(), [1, 2, 3])
    bc = np.bincount(np.array([0, 1, 1, 3], dtype="int32"))
    onp.testing.assert_array_equal(bc.asnumpy(), [1, 2, 0, 1])


def test_np_gradient():
    x = onp.array([1.0, 2.0, 4.0, 7.0], "float32")
    _check(np.gradient(np.array(x)), onp.gradient(x))


def test_result_type_no_transfer():
    a = np.ones((2, 2))
    assert np.result_type(a, "float64") == onp.float64


def test_np_frontend_tail():
    """windows/polyval/ediff1d/insert/delete/dsplit/angle-conv/around +
    linalg tensor solvers + tail samplers (parity: numpy/multiarray.py
    over the npi tail)."""
    onp.testing.assert_allclose(np.hanning(5).asnumpy(), onp.hanning(5),
                                atol=1e-6)
    onp.testing.assert_allclose(np.hamming(4).asnumpy(), onp.hamming(4),
                                atol=1e-6)
    onp.testing.assert_allclose(
        np.polyval(np.array([1., 2., 3.]), np.array([2.0])).asnumpy(),
        [11.0])
    assert np.delete(np.array([1., 2., 3.]), 1).asnumpy().tolist() \
        == [1., 3.]
    assert np.insert(np.array([1., 3.]), 1, 2.0).asnumpy().tolist() \
        == [1., 2., 3.]
    assert np.ediff1d(np.array([1., 4., 9.])).asnumpy().tolist() == [3., 5.]
    assert np.dsplit(np.ones((2, 2, 4)), 2)[0].shape == (2, 2, 2)
    onp.testing.assert_allclose(np.deg2rad(np.array([180.0])).asnumpy(),
                                [onp.pi], rtol=1e-6)
    onp.testing.assert_allclose(np.rad2deg(np.array([onp.pi])).asnumpy(),
                                [180.0], rtol=1e-6)
    onp.testing.assert_allclose(
        np.around(np.array([1.256]), decimals=1).asnumpy(), [1.3],
        rtol=1e-5)
    a = onp.random.RandomState(0).rand(4, 4).astype("f") + \
        onp.eye(4, dtype="f") * 3
    onp.testing.assert_allclose(np.linalg.pinv(np.array(a)).asnumpy(),
                                onp.linalg.pinv(a), atol=1e-4)
    import mxnet_tpu as mx

    mx.random.seed(0)
    assert np.random.pareto(2.0, size=(3,)).shape == (3,)
    assert np.random.weibull(2.0, size=(3,)).shape == (3,)
    assert np.random.rayleigh(1.0, size=(3,)).shape == (3,)
    assert np.random.multinomial(
        7, [0.0, 1.0, 0.0]).asnumpy().tolist() == [0, 7, 0]


def test_numpy_dispatch_protocol():
    """__array_ufunc__/__array_function__ interop (parity:
    numpy_dispatch_protocol.py + numpy_op_fallback.py): numpy functions on
    mx.np arrays return mx.np arrays, via the mx implementation when one
    exists and via wrapped-numpy fallback otherwise."""
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    m = onp.mean(a)
    assert isinstance(m, type(a)) and float(m.asnumpy()) == 2.5
    s = onp.add(a, 1)
    assert isinstance(s, type(a))
    onp.testing.assert_allclose(s.asnumpy(), a.asnumpy() + 1)
    c = onp.concatenate([a, a])
    assert isinstance(c, type(a)) and c.shape == (4, 2)
    d = onp.dot(a, a)
    assert isinstance(d, type(a))
    onp.testing.assert_allclose(d.asnumpy(), a.asnumpy() @ a.asnumpy())
    sq = onp.sqrt(a)
    assert isinstance(sq, type(a))
    onp.testing.assert_allclose(sq.asnumpy(), onp.sqrt(a.asnumpy()))
    w = onp.where(a > 2, a, 0 * a)
    assert isinstance(w, type(a))


def test_numpy_dispatch_out_where_inplace():
    """Review regressions: out= contract, where= semantics (untouched
    positions keep out's prior values), in-place ufunc methods write back
    through rebind rather than mutating the jax buffer view."""
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    b = np.array([[10.0, 10.0], [10.0, 10.0]])
    c = np.zeros((2, 2))
    r = onp.add(a, b, out=c)
    assert r is c
    onp.testing.assert_allclose(c.asnumpy(), a.asnumpy() + 10)

    m = onp.add(a, b, where=onp.array([[True, False], [False, True]]),
                out=np.zeros((2, 2)))
    assert m.asnumpy().tolist() == [[11.0, 0.0], [0.0, 14.0]]

    d = onp.multiply(a, b, dtype=onp.float64)
    onp.testing.assert_allclose(d.asnumpy(), a.asnumpy() * 10)

    e = np.array([1.0, 2.0, 3.0])
    raw_before = e._data
    onp.add.at(e, [0, 1], 5.0)
    assert e.asnumpy().tolist() == [6.0, 7.0, 3.0]
    assert raw_before is not e._data  # rebind, not view mutation

    assert onp.add.reduce(a).asnumpy().tolist() == [4.0, 6.0]

    co = np.zeros((4, 2))
    r = onp.concatenate([a, a], out=co)
    assert r is co
    onp.testing.assert_allclose(co.asnumpy()[:2], a.asnumpy())
