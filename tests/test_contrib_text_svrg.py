"""contrib.text + contrib.svrg_optimization tests (parity model:
tests/python/unittest/test_contrib_text.py, test_contrib_svrg_module.py,
test_contrib_svrg_optimizer.py)."""
from collections import Counter

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text
from mxnet_tpu.contrib.svrg_optimization import SVRGModule


# ------------------------------------------------------------------ text --
def test_count_tokens_from_str():
    c = text.utils.count_tokens_from_str(" Life is great! \n life is good . \n")
    assert c["is"] == 2 and c["Life"] == 1 and c["life"] == 1
    c2 = text.utils.count_tokens_from_str("Life is\nlife is", to_lower=True)
    assert c2["life"] == 2 and c2["is"] == 2
    base = Counter({"is": 5})
    c3 = text.utils.count_tokens_from_str("is it", counter_to_update=base)
    assert c3["is"] == 6 and c3["it"] == 1
    # regex metacharacters are literal delimiters
    c4 = text.utils.count_tokens_from_str("a.b c.d", token_delim=".",
                                          seq_delim=" ")
    assert c4 == Counter({"a": 1, "b": 1, "c": 1, "d": 1})


def test_vocabulary_indexing():
    counter = Counter({"a": 5, "b": 3, "c": 3, "d": 1})
    v = text.vocab.Vocabulary(counter, most_freq_count=None, min_freq=2,
                              unknown_token="<unk>",
                              reserved_tokens=["<pad>"])
    # order: unk, reserved, then by freq desc (ties alphabetical)
    assert v.idx_to_token == ["<unk>", "<pad>", "a", "b", "c"]
    assert len(v) == 5
    assert v.to_indices("a") == 2
    assert v.to_indices(["d", "b"]) == [0, 3]  # d filtered by min_freq
    assert v.to_tokens([0, 4]) == ["<unk>", "c"]
    with pytest.raises(ValueError):
        v.to_tokens(99)
    with pytest.raises(ValueError):
        text.vocab.Vocabulary(counter, reserved_tokens=["<unk>"])
    capped = text.vocab.Vocabulary(counter, most_freq_count=2)
    assert len(capped) == 3  # unk + 2 most frequent


def _write_vec_file(path, rows, header=None):
    with open(path, "w") as f:
        if header:
            f.write(header + "\n")
        for token, vec in rows:
            f.write(token + " " + " ".join(str(x) for x in vec) + "\n")


def test_custom_embedding_and_lookup(tmp_path):
    p = str(tmp_path / "vecs.txt")
    _write_vec_file(p, [("hello", [1.0, 2.0, 3.0]),
                        ("world", [4.0, 5.0, 6.0]),
                        ("hello", [9.0, 9.0, 9.0])])  # dup: first wins
    emb = text.embedding.CustomEmbedding(p)
    assert emb.vec_len == 3
    assert len(emb) == 3  # unk + 2
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [1, 2, 3])
    out = emb.get_vecs_by_tokens(["world", "missing"])
    np.testing.assert_allclose(out.asnumpy(),
                               [[4, 5, 6], [0, 0, 0]])
    # lower_case_backup
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens(["HELLO"],
                               lower_case_backup=True).asnumpy(),
        [[1, 2, 3]])
    # update_token_vectors
    emb.update_token_vectors("world", mx.nd.array([7.0, 7.0, 7.0]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [7, 7, 7])
    with pytest.raises(ValueError):
        emb.update_token_vectors("nope", mx.nd.array([1.0, 1.0, 1.0]))
    # fastText-style header line is skipped
    p2 = str(tmp_path / "ft.vec")
    _write_vec_file(p2, [("tok", [1.0, 1.0])], header="1 2")
    emb2 = text.embedding.CustomEmbedding(p2)
    assert emb2.vec_len == 2 and "tok" in emb2.token_to_idx
    # a file-provided <unk> vector lands in row 0 and wins over the
    # initializer (parity: embedding.py:300)
    p3 = str(tmp_path / "unk.txt")
    _write_vec_file(p3, [("<unk>", [8.0, 8.0]), ("w", [1.0, 2.0])])
    emb3 = text.embedding.CustomEmbedding(
        p3, init_unknown_vec=lambda shape: mx.nd.ones(shape))
    np.testing.assert_allclose(
        emb3.get_vecs_by_tokens("missing").asnumpy(), [8, 8])


def test_embedding_with_vocabulary_and_composite(tmp_path):
    p = str(tmp_path / "vecs.txt")
    _write_vec_file(p, [("a", [1.0, 2.0]), ("b", [3.0, 4.0]),
                        ("c", [5.0, 6.0])])
    vocab = text.vocab.Vocabulary(Counter({"b": 2, "z": 2}))
    emb = text.embedding.CustomEmbedding(p, vocabulary=vocab)
    assert emb.idx_to_token == vocab.idx_to_token
    assert emb.idx_to_vec.shape == (len(vocab), 2)
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("b").asnumpy(), [3, 4])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("z").asnumpy(), [0, 0])  # not in file
    # composite: concat two sources over one vocab
    emb_a = text.embedding.CustomEmbedding(p)
    comp = text.embedding.CompositeEmbedding(vocab, [emb_a, emb_a])
    assert comp.vec_len == 4
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("b").asnumpy(), [3, 4, 3, 4])


def test_embedding_registry():
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    assert "glove.6B.50d.txt" in names["glove"]
    with pytest.raises(KeyError):
        text.embedding.create("glove", pretrained_file_name="not-a-file")
    with pytest.raises(FileNotFoundError):
        # known name but absent from the (empty) local cache
        text.embedding.create("glove",
                              pretrained_file_name="glove.6B.50d.txt")


# ------------------------------------------------------------------ svrg --
def _linreg_sym():
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    return mx.sym.LinearRegressionOutput(out, mx.sym.var("lin_reg_label"),
                                         name="linreg")


def _linreg_data(n=128, d=4, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    Y = (X @ w).reshape(n).astype(np.float32)
    return mx.io.NDArrayIter(X, Y, batch_size=batch,
                             label_name="lin_reg_label")


def test_svrg_update_freq_validation():
    with pytest.raises(TypeError):
        SVRGModule(_linreg_sym(), label_names=("lin_reg_label",),
                   update_freq=0)
    with pytest.raises(TypeError):
        SVRGModule(_linreg_sym(), label_names=("lin_reg_label",),
                   update_freq=None)


def test_svrg_full_grads_are_dataset_mean():
    """mu must equal the mean of per-batch gradients at the snapshot."""
    it = _linreg_data()
    mod = SVRGModule(_linreg_sym(), label_names=("lin_reg_label",),
                     update_freq=2)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),))
    mod.update_full_grads(it)
    # manual accumulation through the plain Module path
    expect = {}
    nb = 0
    it.reset()
    for batch in it:
        mod._mod_aux.forward(batch, is_train=True)
        mod._mod_aux.backward()
        for name, g in mod._mod_aux._exec.grad_dict.items():
            expect[name] = expect.get(name, 0) + g.asnumpy()
        nb += 1
    for name, mu in mod._full_grads.items():
        np.testing.assert_allclose(mu.asnumpy(), expect[name] / nb,
                                   rtol=1e-5, atol=1e-6)


def test_svrg_gradient_at_snapshot_equals_full_grad():
    """At w == w~ the corrected gradient collapses to mu exactly —
    the defining SVRG identity."""
    it = _linreg_data()
    mod = SVRGModule(_linreg_sym(), label_names=("lin_reg_label",),
                     update_freq=1)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.0),))
    mod.update_full_grads(it)
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update_svrg_gradients()
    for name, mu in mod._full_grads.items():
        np.testing.assert_allclose(mod._exec.grad_dict[name].asnumpy(),
                                   mu.asnumpy(), rtol=1e-4, atol=1e-5)


def test_svrg_reshape_preserves_params():
    it = _linreg_data()
    mod = SVRGModule(_linreg_sym(), label_names=("lin_reg_label",),
                     update_freq=1)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    before, _ = mod.get_params()
    mod.reshape([("data", (16, 4))], [("lin_reg_label", (16,))])
    after, _ = mod.get_params()
    for name in before:
        np.testing.assert_allclose(after[name].asnumpy(),
                                   before[name].asnumpy())


def test_svrg_fit_resumes_off_refresh_grid():
    """begin_epoch not a multiple of update_freq must still seed mu."""
    it = _linreg_data()
    mod = SVRGModule(_linreg_sym(), label_names=("lin_reg_label",),
                     update_freq=2)
    mod.fit(it, eval_metric="mse", num_epoch=3, begin_epoch=1, kvstore=None,
            optimizer_params=(("learning_rate", 0.01),
                              ("rescale_grad", 1.0 / 32)))


def test_svrg_fit_converges():
    it = _linreg_data(n=256, batch=32)
    mod = SVRGModule(_linreg_sym(), label_names=("lin_reg_label",),
                     update_freq=2)
    mod.fit(it, eval_metric="mse", num_epoch=10, kvstore=None,
            optimizer="sgd",
            optimizer_params=(("learning_rate", 0.05),
                              ("rescale_grad", 1.0 / 32)))
    mse = dict(mod.score(it, "mse"))["mse"]
    assert mse < 0.05, mse
