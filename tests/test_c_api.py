"""C ABI (libmxtpu) — build the library, compile a C host program against
include/mxtpu/c_api.h, and run it end-to-end in a clean environment.

Parity model: the reference's C ABI is its language-binding surface
(include/mxnet/c_api.h + src/c_api/c_api.cc); the capability under test is
"a C program can create arrays, invoke ops, read results, and get error
strings without any Python of its own"."""
import os
import shutil
import subprocess
import sysconfig

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _python_embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    return [f"-I{inc}"], [f"-L{libdir}", f"-lpython{ver}",
                          f"-Wl,-rpath,{libdir}"]


@pytest.fixture(scope="module")
def capi_lib(tmp_path_factory):
    gxx = shutil.which("g++")
    gcc = shutil.which("gcc") or gxx
    if gxx is None:
        pytest.skip("no g++ available")
    build = tmp_path_factory.mktemp("capi")
    lib = str(build / "libmxtpu.so")
    inc_flags, ld_flags = _python_embed_flags()
    subprocess.run(
        [gxx, "-O2", "-shared", "-fPIC", "-std=c++17",
         os.path.join(REPO, "mxnet_tpu", "native", "mxtpu_c_api.cc"),
         "-o", lib] + inc_flags + ld_flags,
        check=True, capture_output=True)
    exe = str(build / "smoke")
    subprocess.run(
        [gcc, os.path.join(REPO, "examples", "extensions", "c_binding",
                           "smoke.c"),
         "-I", os.path.join(REPO, "include"),
         "-L", str(build), "-lmxtpu", f"-Wl,-rpath,{build}", "-o", exe],
        check=True, capture_output=True)
    return exe


def test_c_host_program_end_to_end(capi_lib):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # clean: no axon sitecustomize preload
    env["MXTPU_PLATFORM"] = "cpu"
    proc = subprocess.run([capi_lib], capture_output=True, text=True,
                          timeout=600, env=env)
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "C API OK" in proc.stdout
    # the ABI exposes the full op registry
    ops_line = [l for l in proc.stdout.splitlines() if l.startswith("ops=")]
    assert ops_line and int(ops_line[0].split("=")[1]) > 400
