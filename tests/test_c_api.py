"""C ABI (libmxtpu) — build the library, compile a C host program against
include/mxtpu/c_api.h, and run it end-to-end in a clean environment.

Parity model: the reference's C ABI is its language-binding surface
(include/mxnet/c_api.h + src/c_api/c_api.cc); the capability under test is
"a C program can create arrays, invoke ops, read results, and get error
strings without any Python of its own"."""
import os
import shutil
import subprocess
import sysconfig

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _python_embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    return [f"-I{inc}"], [f"-L{libdir}", f"-lpython{ver}",
                          f"-Wl,-rpath,{libdir}"]


@pytest.fixture(scope="module")
def capi_lib(tmp_path_factory):
    gxx = shutil.which("g++")
    gcc = shutil.which("gcc") or gxx
    if gxx is None:
        pytest.skip("no g++ available")
    build = tmp_path_factory.mktemp("capi")
    lib = str(build / "libmxtpu.so")
    inc_flags, ld_flags = _python_embed_flags()
    subprocess.run(
        [gxx, "-O2", "-shared", "-fPIC", "-std=c++17",
         os.path.join(REPO, "mxnet_tpu", "native", "mxtpu_c_api.cc"),
         "-o", lib] + inc_flags + ld_flags,
        check=True, capture_output=True)
    exe = str(build / "smoke")
    subprocess.run(
        [gcc, os.path.join(REPO, "examples", "extensions", "c_binding",
                           "smoke.c"),
         "-I", os.path.join(REPO, "include"),
         "-L", str(build), "-lmxtpu", f"-Wl,-rpath,{build}", "-o", exe],
        check=True, capture_output=True)
    return exe


def test_c_host_program_end_to_end(capi_lib):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # clean: no axon sitecustomize preload
    env["MXTPU_PLATFORM"] = "cpu"
    proc = subprocess.run([capi_lib], capture_output=True, text=True,
                          timeout=600, env=env)
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "C API OK" in proc.stdout
    # the ABI exposes the full op registry
    ops_line = [l for l in proc.stdout.splitlines() if l.startswith("ops=")]
    assert ops_line and int(ops_line[0].split("=")[1]) > 400




def _build_c_example(capi_lib, src_name, out_name, extra_flags=()):
    """Compile one examples/extensions/c_binding host program against
    the freshly-built libmxtpu (shared across the ABI fixtures)."""
    build = os.path.dirname(capi_lib)
    gcc = shutil.which("gcc") or shutil.which("g++")
    exe = os.path.join(build, out_name)
    subprocess.run(
        [gcc, os.path.join(REPO, "examples", "extensions", "c_binding",
                           src_name),
         "-I", os.path.join(REPO, "include"),
         "-L", build, "-lmxtpu", f"-Wl,-rpath,{build}",
         *extra_flags, "-o", exe],
        check=True, capture_output=True)
    return exe


@pytest.fixture(scope="module")
def predict_exe(capi_lib):
    return _build_c_example(capi_lib, "predict.c", "predict")


def test_predict_abi_end_to_end(predict_exe, tmp_path):
    """MXPredCreate/SetInput/Forward/GetOutput from pure C against a
    checkpoint produced by the Python frontend — the deployment handoff
    the reference's c_predict_api exists for. The C result must match the
    Python executor bit-for-bit (same executable)."""
    gen = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "data = mx.sym.var('data')\n"
        "net = mx.sym.FullyConnected(data, num_hidden=16, name='fc1')\n"
        "net = mx.sym.Activation(net, act_type='relu')\n"
        "net = mx.sym.FullyConnected(net, num_hidden=4, name='fc2')\n"
        "net = mx.sym.softmax(net)\n"
        "ex = net.simple_bind(mx.cpu(), data=(1, 8))\n"
        "rs = np.random.RandomState(7)\n"
        "args = {n: mx.nd.array(rs.randn(*a.shape).astype('f') * 0.3)\n"
        "        for n, a in ex.arg_dict.items() if n != 'data'}\n"
        "ex.copy_params_from(args)\n"
        "out = ex.forward(data=mx.nd.ones((1, 8)))[0].asnumpy()\n"
        "np.save(%r, out)\n"
        "from mxnet_tpu.model import save_checkpoint\n"
        "save_checkpoint(%r, 0, net, args, {})\n"
    )
    prefix = str(tmp_path / "mlp")
    ref_out = str(tmp_path / "ref.npy")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    subprocess.run([os.sys.executable, "-c", gen % (ref_out, prefix)],
                   check=True, env=env, timeout=300)
    import numpy as onp

    ref = onp.load(ref_out)
    env["MXTPU_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [predict_exe, f"{prefix}-symbol.json", f"{prefix}-0000.params"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"
    assert "PREDICT OK" in proc.stdout
    argmax_line = [l for l in proc.stdout.splitlines()
                   if l.startswith("argmax=")][0]
    c_argmax = int(argmax_line.split("=")[1].split()[0])
    c_sum = float(argmax_line.split("sum=")[1])
    assert c_argmax == int(ref.argmax())
    assert abs(c_sum - float(ref.sum())) < 1e-4  # softmax sums to 1


@pytest.fixture(scope="module")
def symbol_io_exe(capi_lib):
    return _build_c_example(capi_lib, "symbol_io.c", "symbol_io")


def test_symbol_and_container_abi(symbol_io_exe, tmp_path):
    """Symbol load/introspect/json-roundtrip, per-op schema info, and
    NDArray container save/load — all from pure C (parity:
    MXSymbolCreateFromJSON & co., MXNDArraySave/Load)."""
    gen = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import mxnet_tpu as mx\n"
        "net = mx.sym.FullyConnected(mx.sym.var('data'), num_hidden=4)\n"
        "net = mx.sym.BatchNorm(net)\n"
        "net = mx.sym.softmax(net)\n"
        "net.save(%r)\n"
    )
    sym_path = str(tmp_path / "net-symbol.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    subprocess.run([os.sys.executable, "-c", gen % sym_path],
                   check=True, env=env, timeout=300)
    env["MXTPU_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [symbol_io_exe, sym_path, str(tmp_path / "params.nd")],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, \
        f"stdout={proc.stdout}\nstderr={proc.stderr}"
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("SYMBOL_IO_OK")][0]
    # data + fc weight/bias + bn gamma/beta (+2 aux moving stats)
    assert "args=5" in line and "aux=2" in line, line


@pytest.fixture(scope="module")
def multi_pred_exe(capi_lib):
    return _build_c_example(capi_lib, "multi_pred.c", "multi_pred",
                            extra_flags=("-pthread",))


def test_multi_threaded_inference_abi(multi_pred_exe, tmp_path):
    """Concurrent predictors from N host threads over one checkpoint —
    the reference's example/multi_threaded_inference capability. Each
    thread owns a PredictorHandle; all must produce identical results
    with no crashes or cross-talk."""
    gen = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "net = mx.sym.FullyConnected(mx.sym.var('data'), num_hidden=8,\n"
        "                            name='fc1')\n"
        "net = mx.sym.Activation(net, act_type='relu')\n"
        "net = mx.sym.softmax(mx.sym.FullyConnected(net, num_hidden=3,\n"
        "                                           name='fc2'))\n"
        "ex = net.simple_bind(mx.cpu(), data=(1, 8))\n"
        "rs = np.random.RandomState(3)\n"
        "args = {n: mx.nd.array(rs.randn(*a.shape).astype('f') * 0.3)\n"
        "        for n, a in ex.arg_dict.items() if n != 'data'}\n"
        "from mxnet_tpu.model import save_checkpoint\n"
        "save_checkpoint(%r, 0, net, args, {})\n"
    )
    prefix = str(tmp_path / "mlp")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    subprocess.run([os.sys.executable, "-c", gen % prefix],
                   check=True, env=env, timeout=300)
    env["MXTPU_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [multi_pred_exe, prefix + "-symbol.json",
         prefix + "-0000.params", "4", "5"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, \
        f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "MULTI_PRED_OK" in proc.stdout
