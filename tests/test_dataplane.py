"""Streaming data plane tests: fused native decode+augment (bit-parity
with the Python fallback), per-host sharded readers, deterministic
mid-epoch resume (in-process and SIGKILL-subprocess), TokenRecordIter,
trainer checkpoint integration, and the native-unavailable surfacing."""
import io as _io
import json
import os
import signal
import subprocess
import sys
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native, recordio
from mxnet_tpu.io import (ImageRecordIter, NDArrayIter, PrefetchingIter,
                          TokenRecordIter, write_token_shard)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_rec(path, n=40, hw=32, png_at=None, seed=0):
    """JPEG .rec whose source size equals the rand_crop decode size for
    data_shape (3,24,24) — so native and PIL decodes are bit-identical
    (no resize) and the augmentation stream is the only variable."""
    from PIL import Image

    rs = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        arr = rs.randint(0, 255, (hw, hw, 3), np.uint8)
        buf = _io.BytesIO()
        if png_at is not None and i == png_at:
            Image.fromarray(arr).save(buf, "PNG")
        else:
            Image.fromarray(arr).save(buf, "JPEG", quality=95)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), buf.getvalue()))
    rec.close()
    return path + ".rec"


def _aug_kw(rec, **over):
    kw = dict(path_imgrec=rec, data_shape=(3, 24, 24), batch_size=4,
              shuffle=True, rand_crop=True, rand_mirror=True,
              color_jitter=0.2, seed=5, round_batch=False,
              prefetch_buffer=0, num_parts=1, part_index=0)
    kw.update(over)
    return kw


def _stream(it):
    return [b.data[0].asnumpy() for b in it]


def _force_python_augment(monkeypatch):
    monkeypatch.setattr(native, "decode_augment_batch",
                        lambda *a, **k: None)
    monkeypatch.setattr(native, "decode_jpeg_batch",
                        lambda *a, **k: None)


# ------------------------------------------------------------- tentpole --

def test_augmented_stream_deterministic(tmp_path):
    rec = _write_rec(str(tmp_path / "a"))
    a = _stream(ImageRecordIter(**_aug_kw(rec)))
    b = _stream(ImageRecordIter(**_aug_kw(rec)))
    assert len(a) == 10
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    # a different seed draws a different augmentation stream
    c = _stream(ImageRecordIter(**_aug_kw(rec, seed=6)))
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_native_augment_bit_parity_with_python(tmp_path, monkeypatch):
    """The fused native loop and the pure-Python fallback produce
    bit-identical augmented batches at seed parity (crop + mirror +
    color jitter; source size == decode size so no resize divergence)."""
    if not native.status()["augment"]:
        pytest.skip("native fused augment not built on this host")
    rec = _write_rec(str(tmp_path / "b"))
    nat = _stream(ImageRecordIter(**_aug_kw(rec)))
    _force_python_augment(monkeypatch)
    py = _stream(ImageRecordIter(**_aug_kw(rec)))
    assert len(nat) == len(py) == 10
    for x, y in zip(nat, py):
        np.testing.assert_array_equal(x, y)


def test_augment_failed_record_retried_with_same_params(tmp_path):
    """A record the native libjpeg loop rejects (a PNG) is retried
    through PIL INSIDE the augmented path with the SAME per-image
    params — the whole stream matches an all-PIL run bit-exactly."""
    if not native.status()["augment"]:
        pytest.skip("native fused augment not built on this host")
    rec = _write_rec(str(tmp_path / "c"), png_at=3)
    nat = _stream(ImageRecordIter(**_aug_kw(rec)))
    orig_a, orig_j = native.decode_augment_batch, native.decode_jpeg_batch
    native.decode_augment_batch = lambda *a, **k: None
    native.decode_jpeg_batch = lambda *a, **k: None
    try:
        py = _stream(ImageRecordIter(**_aug_kw(rec)))
    finally:
        native.decode_augment_batch = orig_a
        native.decode_jpeg_batch = orig_j
    for x, y in zip(nat, py):
        np.testing.assert_array_equal(x, y)


def test_mid_epoch_state_resume(tmp_path):
    """state_dict at batch N -> fresh iterator -> identical remaining
    stream, including the next epoch's shuffle."""
    rec = _write_rec(str(tmp_path / "d"))
    it = ImageRecordIter(**_aug_kw(rec))
    ref = _stream(it)
    it.reset()
    ref2 = _stream(it)  # epoch 1 (different shuffle than epoch 0)
    assert any(not np.array_equal(x, y) for x, y in zip(ref, ref2))

    it3 = ImageRecordIter(**_aug_kw(rec))
    seen = [it3.next().data[0].asnumpy() for _ in range(3)]
    state = it3.state_dict()
    assert state["global_pos"] == 12 and state["epoch"] == 0
    it4 = ImageRecordIter(**_aug_kw(rec))
    it4.load_state_dict(state)
    rest = _stream(it4)
    assert len(rest) == len(ref) - 3
    for x, y in zip(seen + rest, ref):
        np.testing.assert_array_equal(x, y)
    it4.reset()  # epoch rolls over exactly like the uninterrupted run
    for x, y in zip(_stream(it4), ref2):
        np.testing.assert_array_equal(x, y)


def test_state_resume_with_prefetch_producer(tmp_path):
    """The in-iterator prefetch producer runs ahead of the consumer;
    state_dict still snapshots the CONSUMED position."""
    rec = _write_rec(str(tmp_path / "e"))
    ref = _stream(ImageRecordIter(**_aug_kw(rec)))
    it = ImageRecordIter(**_aug_kw(rec, prefetch_buffer=2))
    for _ in range(2):
        it.next()
    state = it.state_dict()
    assert state["consumed"] == 2
    it2 = ImageRecordIter(**_aug_kw(rec, prefetch_buffer=2))
    it2.load_state_dict(state)
    rest = _stream(it2)
    for x, y in zip(rest, ref[2:]):
        np.testing.assert_array_equal(x, y)


def test_sharded_readers_tile_the_epoch(tmp_path):
    """Union of the rank streams == the epoch prefix, no overlap, equal
    step counts (block-cyclic slicing)."""
    rec = _write_rec(str(tmp_path / "f"), n=64)
    streams = {}
    for r in range(4):
        it = ImageRecordIter(**_aug_kw(rec, num_parts=4, part_index=r))
        streams[r] = [int(l) for b in it for l in b.label[0].asnumpy()]
    sizes = {r: len(v) for r, v in streams.items()}
    assert sizes == {0: 16, 1: 16, 2: 16, 3: 16}
    allseen = sum(streams.values(), [])
    assert len(allseen) == len(set(allseen)) == 64  # disjoint + complete
    # every rank shuffles identically: the union IS the global order
    it0 = ImageRecordIter(**_aug_kw(rec, num_parts=1, part_index=0))
    global_order = [int(l) for b in it0 for l in b.label[0].asnumpy()]
    assert set(allseen) == set(global_order)


def test_shard_shrink_4_to_2_repartitions_bitexact(tmp_path):
    """A checkpoint cut on a 4-rank gang resumes on 2 ranks at the same
    GLOBAL stream position — remaining batches (augmentation included)
    match the uninterrupted 2-rank run bit-exactly."""
    rec = _write_rec(str(tmp_path / "g"), n=64)
    it4 = ImageRecordIter(**_aug_kw(rec, num_parts=4, part_index=0))
    for _ in range(2):
        it4.next()
    state = it4.state_dict()
    assert state["global_pos"] == 32
    for r in range(2):
        ref = _stream(ImageRecordIter(
            **_aug_kw(rec, num_parts=2, part_index=r)))
        it2 = ImageRecordIter(**_aug_kw(rec, num_parts=2, part_index=r))
        it2.load_state_dict(state)
        rest = _stream(it2)
        start = state["global_pos"] // (4 * 2)
        assert len(rest) == len(ref) - start
        for x, y in zip(rest, ref[start:]):
            np.testing.assert_array_equal(x, y)


def test_indivisible_resume_position_raises(tmp_path):
    rec = _write_rec(str(tmp_path / "h"), n=64)
    it4 = ImageRecordIter(**_aug_kw(rec, num_parts=4, part_index=0))
    it4.next()
    state = it4.state_dict()  # global_pos 16
    it3 = ImageRecordIter(**_aug_kw(rec, num_parts=3, part_index=0))
    with pytest.raises(ValueError, match="global batch boundary"):
        it3.load_state_dict(state)  # 16 % (4*3) != 0


def test_prefetching_iter_state_excludes_staged(tmp_path):
    """PrefetchingIter.state_dict snapshots at the consumer position:
    the staged-ahead batch replays after a load."""
    data = np.arange(80).reshape(40, 2).astype(np.float32)
    ref = _stream(PrefetchingIter(NDArrayIter(data, batch_size=4)))
    it = PrefetchingIter(NDArrayIter(data, batch_size=4))
    for _ in range(3):
        it.next()
    state = it.state_dict()
    assert state["delivered"] == 3
    it2 = PrefetchingIter(NDArrayIter(data, batch_size=4))
    it2.load_state_dict(state)
    rest = _stream(it2)
    assert len(rest) == len(ref) - 3
    for x, y in zip(rest, ref[3:]):
        np.testing.assert_array_equal(x, y)


def test_prefetching_iter_state_wraps_record_reader(tmp_path):
    rec = _write_rec(str(tmp_path / "i"))
    ref = _stream(PrefetchingIter(ImageRecordIter(**_aug_kw(rec))))
    it = PrefetchingIter(ImageRecordIter(**_aug_kw(rec)))
    for _ in range(2):
        it.next()
    state = it.state_dict()
    assert state["iters"][0]["consumed"] == 2  # not the staged position
    it2 = PrefetchingIter(ImageRecordIter(**_aug_kw(rec)))
    it2.load_state_dict(state)
    for x, y in zip(_stream(it2), ref[2:]):
        np.testing.assert_array_equal(x, y)


def test_token_record_iter(tmp_path):
    """Fixed-length token blocks through the native reader: next-token
    shift, deterministic shuffle, sharding and state grammar."""
    path = str(tmp_path / "t.rec")
    toks = np.arange(2000, dtype=np.int32)
    nblk = write_token_shard(path, toks, seq_len=16)
    assert nblk == 124  # ceil((2000 - 16) / 16) stride-16 windows
    it = TokenRecordIter(path, seq_len=16, batch_size=4, shuffle=True,
                         seed=1, num_parts=1, part_index=0)
    b = it.next()
    assert b.data[0].shape == (4, 16) and b.label[0].shape == (4, 16)
    np.testing.assert_array_equal(b.data[0].asnumpy()[:, 1:],
                                  b.label[0].asnumpy()[:, :-1])
    # blocks overlap by one token (stride seq_len): consecutive records
    # of the unshuffled stream continue the corpus
    it_seq = TokenRecordIter(path, seq_len=16, batch_size=2,
                             num_parts=1, part_index=0)
    b0 = it_seq.next()
    assert int(b0.data[0].asnumpy()[1, 0]) == \
        int(b0.label[0].asnumpy()[0, -1])
    # state resume
    st = it.state_dict()
    it2 = TokenRecordIter(path, seq_len=16, batch_size=4, shuffle=True,
                          seed=1, num_parts=1, part_index=0)
    it2.load_state_dict(st)
    np.testing.assert_array_equal(it2.next().data[0].asnumpy(),
                                  it.next().data[0].asnumpy())
    # sharding tiles the epoch
    ids = []
    for r in range(2):
        itr = TokenRecordIter(path, seq_len=16, batch_size=4,
                              shuffle=True, seed=1, num_parts=2,
                              part_index=r)
        ids += [int(b.data[0].asnumpy()[i, 0]) for b in itr
                for i in range(4)]
    assert len(ids) == len(set(ids))
    # malformed shard refused with a named error
    bad = str(tmp_path / "bad.rec")
    with open(bad, "wb") as f:
        f.write(native.recordio_pack([b"x" * 7]))
    with pytest.raises(ValueError, match="fixed-length token blocks"):
        TokenRecordIter(bad, seq_len=16)


def test_trainer_checkpoint_carries_data_state(tmp_path):
    """ShardedTrainer.save_checkpoint(data_iter=) persists the stream
    position in the CRC-manifested checkpoint meta; resume(data_iter=)
    restores it — the full CheckpointManager round trip."""
    from mxnet_tpu import checkpoint
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    rec = _write_rec(str(tmp_path / "j"))

    def build(seed):
        mx.random.seed(seed)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
        net.initialize(mx.init.Xavier())
        net(mx.nd.zeros((2, 3 * 24 * 24)))
        return ShardedTrainer(net, gloss.L2Loss(), "sgd",
                              {"learning_rate": 0.01},
                              mesh=DeviceMesh({"dp": 1}))

    manager = checkpoint.CheckpointManager(str(tmp_path / "ck"),
                                           prefix="dp", keep=3)
    it = ImageRecordIter(**_aug_kw(rec))
    ref = _stream(ImageRecordIter(**_aug_kw(rec)))
    trainer = build(0)
    for i in range(3):
        b = it.next()
        trainer.step(b.data[0].reshape((4, -1)), mx.nd.zeros((4, 2)))
    trainer.save_checkpoint(manager, epoch=1, data_iter=it)
    entry, _paths = manager.load()
    assert entry["meta"]["data_state"]["consumed"] == 3  # JSON round trip

    trainer2 = build(1)
    it2 = ImageRecordIter(**_aug_kw(rec))
    entry2 = trainer2.resume(manager, data_iter=it2)
    assert entry2["epoch"] == 1
    rest = _stream(it2)
    assert len(rest) == len(ref) - 3
    for x, y in zip(rest, ref[3:]):
        np.testing.assert_array_equal(x, y)


def test_sigkill_mid_epoch_resume_bitexact(tmp_path):
    """The acceptance drill, as subprocesses: SIGKILL at batch N inside
    the augmented streaming loop -> resume from the manager-persisted
    state -> the remaining stream (augmentation included) is bit-exact
    vs the uninterrupted run. Also resharded: the 4-rank cut resumes on
    a 2-rank gang matching the uninterrupted 2-rank stream."""
    rec = _write_rec(str(tmp_path / "k"), n=48)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "DP_REC": rec,
           "DP_BATCH": "4"}
    env.pop("MXNET_TPU_FAULTS", None)

    def run(**kv):
        e = {**env, **{k: str(v) for k, v in kv.items()}}
        return subprocess.run([sys.executable,
                               os.path.join(REPO, "tests",
                                            "_dataplane_child.py")],
                              env=e, capture_output=True, text=True,
                              timeout=120)

    ref_out = str(tmp_path / "ref.npz")
    p = run(DP_OUT=ref_out, DP_CKPT=str(tmp_path / "refck"))
    assert p.returncode == 0, p.stderr[-1500:]
    p = run(DP_KILL_AFTER=3, DP_CKPT=str(tmp_path / "ck"))
    assert p.returncode == -signal.SIGKILL, (p.returncode,
                                             p.stderr[-1500:])
    res_out = str(tmp_path / "res.npz")
    p = run(DP_RESUME=1, DP_OUT=res_out, DP_CKPT=str(tmp_path / "ck"))
    assert p.returncode == 0, p.stderr[-1500:]
    ref, res = dict(np.load(ref_out)), dict(np.load(res_out))
    assert int(res["__start__"]) == 3
    np.testing.assert_array_equal(res["crcs"], ref["crcs"][3:])

    # resharded 4 -> 2: kill a 4-rank reader, resume as 2 ranks
    ref2_out = str(tmp_path / "ref2.npz")
    p = run(DP_OUT=ref2_out, DP_CKPT=str(tmp_path / "ref2ck"),
            DP_PARTS=2, DP_PART=0)
    assert p.returncode == 0, p.stderr[-1500:]
    p = run(DP_KILL_AFTER=2, DP_CKPT=str(tmp_path / "ck4"),
            DP_PARTS=4, DP_PART=0)
    assert p.returncode == -signal.SIGKILL
    res2_out = str(tmp_path / "res2.npz")
    p = run(DP_RESUME=1, DP_OUT=res2_out, DP_CKPT=str(tmp_path / "ck4"),
            DP_PARTS=2, DP_PART=0)
    assert p.returncode == 0, p.stderr[-1500:]
    ref2, res2 = dict(np.load(ref2_out)), dict(np.load(res2_out))
    start = int(res2["__start__"])  # 2 4-rank batches == 4 2-rank ones
    assert start == 4
    np.testing.assert_array_equal(res2["crcs"], ref2["crcs"][start:])


# ----------------------------------------------------------- satellites --

def test_native_status_and_unavailable_warns_once(monkeypatch, caplog):
    """_build/_load failure is cached, surfaced ONCE as a warning +
    telemetry counter, and explained by status()/diagnose."""
    import ctypes as _ctypes
    import logging

    from mxnet_tpu.telemetry import registry as _registry

    st = native.status()
    assert st["available"] and st["error"] is None
    saved = (native._lib, native._tried, native._error)
    cmd = ["g++"]
    monkeypatch.setattr(native, "_build", lambda: (_ for _ in ()).throw(
        subprocess.CalledProcessError(1, cmd, stderr=b"jpeglib.h: no")))
    monkeypatch.setattr(_ctypes, "CDLL",
                        lambda *a, **k: (_ for _ in ()).throw(
                            OSError("undefined symbol")))
    native._lib, native._tried, native._error = None, False, None
    try:
        with caplog.at_level(logging.WARNING, logger="mxnet_tpu.native"):
            assert not native.available()
            assert not native.available()  # cached: probes once
        warns = [r for r in caplog.records
                 if "native IO library unavailable" in r.getMessage()]
        assert len(warns) == 1
        bad = native.status()
        assert bad["available"] is False
        assert "build failed" in bad["error"]
        assert "jpeglib" in bad["error"]
        series = _registry.counter(
            "mxtpu_native_unavailable_total",
            "Native IO library probe/build failures (Python fallback "
            "active)")
        assert series.series().get((), 0.0) >= 1
    finally:
        native._lib, native._tried, native._error = saved


def test_backend_reprobe_unlatches_fallback(monkeypatch):
    """bench.py's per-run reprobe: a CPU pin latched by an earlier
    fallback is re-tested and released when the default backend answers;
    a deliberate pin (no fallback marker) is never touched."""
    import jax

    from mxnet_tpu import base

    calls = {}

    def fake_run(cmd, timeout=None, capture_output=None, env=None):
        calls["env"] = env

        class R:
            returncode = 0
            stderr = b""
        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(jax.config, "update", lambda *a, **k: None)
    monkeypatch.setenv("MXTPU_PLATFORM", "cpu")
    monkeypatch.setenv("MXTPU_PLATFORM_FALLBACK", "1")
    # setenv-then-delenv: delenv on an ABSENT var records no teardown,
    # and ensure_live_backend writes MXTPU_PROBE_OK directly — this way
    # teardown restores the original (unset) state instead of leaking
    # the probe latch into later tests
    monkeypatch.setenv("MXTPU_PROBE_OK", "stale")
    monkeypatch.delenv("MXTPU_PROBE_OK")
    assert base.ensure_live_backend(reprobe=True) == "default"
    assert "MXTPU_PLATFORM" not in os.environ
    assert "MXTPU_PLATFORM_FALLBACK" not in os.environ
    assert os.environ.get("MXTPU_PROBE_OK") == "1"
    assert "MXTPU_PLATFORM" not in calls["env"]  # probed the DEFAULT

    # a deliberate user pin has no fallback marker: honoured untouched
    monkeypatch.setenv("MXTPU_PLATFORM", "cpu")
    monkeypatch.delenv("MXTPU_PLATFORM_FALLBACK", raising=False)
    assert base.ensure_live_backend(reprobe=True) == "cpu"
    assert os.environ["MXTPU_PLATFORM"] == "cpu"

    # still down: the probe times out, the latch stays
    def timeout_run(cmd, timeout=None, capture_output=None, env=None):
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(subprocess, "run", timeout_run)
    monkeypatch.setenv("MXTPU_PLATFORM_FALLBACK", "1")
    assert base.ensure_live_backend(reprobe=True) == "cpu"
    assert os.environ["MXTPU_PLATFORM"] == "cpu"


def test_iter_bench_augment_mode(tmp_path):
    """benchmark/iter_bench.py --augment: reports img/s, img/s/core,
    the Python-fallback comparison and per-thread scaling, and drops
    the result where diagnose finds it."""
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    import iter_bench

    line = iter_bench.run_augment(num_images=24, src_size=48,
                                  batch_size=8,
                                  data_shape=(3, 32, 32), epochs=1,
                                  threads=2)
    assert line["metric"] == "iter_bench_augment"
    assert line["value"] > 0 and line["img_s_per_core"] > 0
    assert line["python_img_s"] > 0
    assert "1" in line["thread_scaling"]
    assert line["native_augment"] == native.status()["augment"]
    iter_bench._persist(line)
    with open(iter_bench.LAST_RESULT_PATH) as f:
        assert json.load(f)["metric"] == "iter_bench_augment"


def test_diagnose_dataplane_section():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import diagnose

    out = diagnose.check_dataplane()
    assert out["native"]["available"] == native.available()
    assert out["native"]["augment"] == native.status()["augment"]
    assert "cores" in out


def test_dataplane_records_counter(tmp_path):
    from mxnet_tpu.telemetry import registry as _registry

    rec = _write_rec(str(tmp_path / "m"), n=8)
    counter = _registry.counter(
        "mxtpu_dataplane_records_total",
        "Records decoded by the streaming data plane", labels=("path",))
    path = "native" if native.status()["augment"] else "python"
    before = counter.series().get((path,), 0.0)
    list(ImageRecordIter(**_aug_kw(rec)))
    assert counter.series().get((path,), 0.0) >= before + 8


@pytest.mark.perf
def test_augment_overhead_within_noise_at_one_thread(tmp_path):
    """Fusing the augmenters into the decode loop must be ~free: the
    augmented native path stays within noise of plain decode at 1
    thread (generous envelope — decode dominates; the guard catches a
    quadratic augmenter or an accidental extra copy)."""
    import time

    if not native.status()["augment"]:
        pytest.skip("native fused augment not built on this host")
    rec = _write_rec(str(tmp_path / "p"), n=48)

    def run(**over):
        kw = _aug_kw(rec, preprocess_threads=1, shuffle=False, **over)
        it = ImageRecordIter(**kw)
        list(it)  # warm (page cache, pools)
        it.reset()
        t0 = time.perf_counter()
        list(it)
        return time.perf_counter() - t0

    plain = min(run(rand_crop=False, rand_mirror=False, color_jitter=0.0)
                for _ in range(3))
    aug = min(run() for _ in range(3))
    assert aug <= plain * 1.8 + 0.05, (aug, plain)
