"""Gluon tests.

Parity model: tests/python/unittest/test_gluon.py (3.3k LoC) — the core
fixture: run every layer hybridized AND unhybridized and cross-assert
outputs; parameter management; deferred init; save/load round trips.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.gluon.parameter import DeferredInitializationError, Parameter
from mxnet_tpu.test_utils import assert_almost_equal


def check_layer_forward(layer, shape, dtype=np.float32):
    """The central gluon fixture: eager forward == hybridized forward, and
    grads flow (parity: test_gluon.py check_layer_forward)."""
    layer.initialize()
    x = mx.nd.array(np.random.uniform(-1, 1, shape).astype(dtype))
    x.attach_grad()
    with ag.record():
        out1 = layer(x)
    out1.backward()
    np_out1 = out1.asnumpy()
    np_dx1 = x.grad.asnumpy()

    layer.hybridize()
    with ag.record():
        out2 = layer(x)
    out2.backward()
    assert_almost_equal(np_out1, out2.asnumpy(), rtol=1e-4, atol=1e-5,
                        names=("eager", "hybrid"))
    assert_almost_equal(np_dx1, x.grad.asnumpy(), rtol=1e-4, atol=1e-5,
                        names=("eager_grad", "hybrid_grad"))
    return np_out1


def test_dense():
    out = check_layer_forward(nn.Dense(8), (4, 16))
    assert out.shape == (4, 8)
    check_layer_forward(nn.Dense(8, activation="relu", use_bias=False), (4, 16))
    check_layer_forward(nn.Dense(8, flatten=False), (4, 5, 16))
    # flatten=True collapses trailing dims
    out = check_layer_forward(nn.Dense(8), (4, 2, 8))
    assert out.shape == (4, 8)


def test_dense_deferred_and_explicit():
    net = nn.Dense(4, in_units=6)
    net.initialize()
    assert net.weight.shape == (4, 6)
    net2 = nn.Dense(4)
    net2.initialize()
    with pytest.raises(DeferredInitializationError):
        net2.weight.data()
    _ = net2(mx.nd.ones((2, 6)))
    assert net2.weight.shape == (4, 6)


def test_conv_layers():
    check_layer_forward(nn.Conv1D(4, 3), (2, 3, 10))
    check_layer_forward(nn.Conv2D(4, 3, padding=1), (2, 3, 8, 8))
    check_layer_forward(nn.Conv2D(4, 3, strides=2, use_bias=False), (2, 3, 8, 8))
    check_layer_forward(nn.Conv2D(4, (3, 5), padding=(1, 2), dilation=(2, 1)),
                        (2, 3, 10, 10))
    check_layer_forward(nn.Conv2D(4, 3, groups=1, activation="relu"), (2, 2, 8, 8))
    check_layer_forward(nn.Conv3D(2, 3), (2, 2, 6, 6, 6))
    check_layer_forward(nn.Conv2DTranspose(3, 3), (2, 4, 5, 5))
    check_layer_forward(nn.Conv1DTranspose(3, 3, strides=2), (2, 4, 5))


def test_conv2d_vs_numpy():
    layer = nn.Conv2D(1, 2, in_channels=1, use_bias=False)
    layer.initialize()
    x = mx.nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    out = layer(x).asnumpy()
    w = layer.weight.data().asnumpy()
    ref = np.zeros((1, 1, 3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            ref[0, 0, i, j] = (x.asnumpy()[0, 0, i:i + 2, j:j + 2] * w[0, 0]).sum()
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_pool_layers():
    check_layer_forward(nn.MaxPool2D(), (2, 3, 8, 8))
    check_layer_forward(nn.MaxPool2D(3, 2, 1), (2, 3, 9, 9))
    check_layer_forward(nn.AvgPool2D(), (2, 3, 8, 8))
    check_layer_forward(nn.GlobalAvgPool2D(), (2, 3, 8, 8))
    check_layer_forward(nn.GlobalMaxPool2D(), (2, 3, 8, 8))
    check_layer_forward(nn.MaxPool1D(), (2, 3, 8))
    check_layer_forward(nn.AvgPool3D(), (2, 3, 4, 4, 4))
    out = nn.GlobalAvgPool2D()
    out.initialize()
    y = out(mx.nd.ones((2, 3, 5, 5)))
    assert y.shape == (2, 3, 1, 1)


def test_norm_layers():
    check_layer_forward(nn.BatchNorm(), (4, 3, 8, 8))
    check_layer_forward(nn.BatchNorm(axis=-1), (4, 8, 3))
    check_layer_forward(nn.LayerNorm(), (4, 10))
    check_layer_forward(nn.InstanceNorm(), (4, 3, 8, 8))
    check_layer_forward(nn.GroupNorm(num_groups=2), (4, 4, 8, 8))


def test_batchnorm_running_stats():
    layer = nn.BatchNorm(momentum=0.5)
    layer.initialize()
    x = mx.nd.array(np.random.rand(8, 3, 4, 4).astype(np.float32) + 2.0)
    with ag.record():
        layer(x)
    rm = layer.running_mean.data().asnumpy()
    # after one update: 0.5*0 + 0.5*batch_mean
    expect = 0.5 * x.asnumpy().mean(axis=(0, 2, 3))
    assert_almost_equal(rm, expect, rtol=1e-3, atol=1e-4)
    # inference uses running stats (not batch stats)
    y = layer(x).asnumpy()
    rv = layer.running_var.data().asnumpy()
    ref = (x.asnumpy() - rm[None, :, None, None]) / np.sqrt(
        rv[None, :, None, None] + 1e-5)
    assert_almost_equal(y, ref * layer.gamma.data().asnumpy()[None, :, None, None]
                        + layer.beta.data().asnumpy()[None, :, None, None],
                        rtol=1e-3, atol=1e-4)


def test_activations():
    for layer in [nn.Activation("relu"), nn.Activation("sigmoid"),
                  nn.Activation("tanh"), nn.Activation("softrelu"),
                  nn.LeakyReLU(0.1), nn.ELU(), nn.SELU(), nn.GELU(),
                  nn.Swish(), nn.PReLU()]:
        check_layer_forward(layer, (4, 8))


def test_embedding_flatten_dropout():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array([1, 2, 3])
    out = emb(idx)
    assert out.shape == (3, 4)
    assert_almost_equal(out, emb.weight.data().asnumpy()[[1, 2, 3]])

    check_layer_forward(nn.Flatten(), (2, 3, 4, 5))
    d = nn.Dropout(0.5)
    d.initialize()
    x = mx.nd.ones((100, 100))
    assert d(x).asnumpy().sum() == 100 * 100  # inference: identity
    with ag.record():
        y = d(x)
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


def test_sequential_variants():
    for cls in (nn.Sequential, nn.HybridSequential):
        net = cls()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
        net.initialize()
        out = net(mx.nd.ones((2, 6)))
        assert out.shape == (2, 4)
        assert len(net) == 2
        assert isinstance(net[0], nn.Dense)
        sub = net[0:1]
        assert len(sub) == 1


def test_block_registration_and_params():
    class Net(nn.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.fc1 = nn.Dense(8)
                self.fc2 = nn.Dense(4)

        def hybrid_forward(self, F, x):
            return self.fc2(self.fc1(x))

    net = Net(prefix="net_")
    names = list(net.collect_params().keys())
    assert names == ["net_dense0_weight", "net_dense0_bias",
                     "net_dense1_weight", "net_dense1_bias"]
    net.initialize()
    out = net(mx.nd.ones((2, 5)))
    assert out.shape == (2, 4)
    net.hybridize()
    out2 = net(mx.nd.ones((2, 5)))
    assert_almost_equal(out, out2)
    # regex select
    weights = net.collect_params(".*weight")
    assert len(weights) == 2


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.BatchNorm(), nn.Dense(4))
    net.initialize()
    x = mx.nd.ones((2, 6))
    ref = net(x).asnumpy()
    fname = str(tmp_path / "net.params")
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, activation="relu"), nn.BatchNorm(), nn.Dense(4))
    net2.initialize()  # different random init
    net2(x)
    net2.load_parameters(fname)
    assert_almost_equal(net2(x).asnumpy(), ref)


def test_parameter_api():
    p = Parameter("w", shape=(3, 4))
    p.initialize()
    assert p.data().shape == (3, 4)
    p.set_data(mx.nd.ones((3, 4)))
    assert p.data().asnumpy().sum() == 12
    p.grad_req = "null"
    assert p.data()._grad is None
    p.grad_req = "write"
    assert p.grad() is not None
    p.zero_grad()
    assert p.grad().asnumpy().sum() == 0


def test_initializers_dispatch():
    net = nn.Dense(16, in_units=16)
    net.initialize(mx.init.Xavier())
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    assert (b == 0).all()          # bias stays zeros under global Xavier
    assert w.std() > 0
    bound = np.sqrt(3.0 / ((16 + 16) / 2))
    assert np.abs(w).max() <= bound + 1e-6

    bn = nn.BatchNorm(in_channels=4)
    bn.initialize(mx.init.Normal(1.0))
    assert (bn.running_var.data().asnumpy() == 1).all()
    assert (bn.gamma.data().asnumpy() == 1).all()


def test_losses():
    pred = mx.nd.array(np.random.rand(4, 10).astype(np.float32))
    label_idx = mx.nd.array(np.random.randint(0, 10, (4,)).astype(np.float32))

    l = gloss.SoftmaxCrossEntropyLoss()(pred, label_idx)
    lp = pred.asnumpy()
    ls = np.exp(lp - lp.max(-1, keepdims=True))
    ls = ls / ls.sum(-1, keepdims=True)
    expect = -np.log(ls[np.arange(4), label_idx.asnumpy().astype(int)])
    assert_almost_equal(l, expect, rtol=1e-4, atol=1e-5)

    a = mx.nd.array(np.random.rand(4, 3).astype(np.float32))
    b = mx.nd.array(np.random.rand(4, 3).astype(np.float32))
    assert_almost_equal(gloss.L2Loss()(a, b),
                        0.5 * ((a.asnumpy() - b.asnumpy()) ** 2).mean(-1))
    assert_almost_equal(gloss.L1Loss()(a, b),
                        np.abs(a.asnumpy() - b.asnumpy()).mean(-1))

    # sigmoid BCE from logits vs manual
    logits = mx.nd.array(np.random.randn(4, 3).astype(np.float32))
    labels = mx.nd.array((np.random.rand(4, 3) > 0.5).astype(np.float32))
    out = gloss.SigmoidBCELoss()(logits, labels).asnumpy()
    z = logits.asnumpy()
    ref = np.maximum(z, 0) - z * labels.asnumpy() + np.log1p(np.exp(-np.abs(z)))
    assert_almost_equal(out, ref.mean(-1), rtol=1e-4, atol=1e-5)

    # hinge / huber shapes + grads flow
    for L in [gloss.HingeLoss(), gloss.SquaredHingeLoss(), gloss.LogisticLoss(),
              gloss.HuberLoss(), gloss.KLDivLoss(from_logits=False)]:
        la = mx.nd.ones((4, 3))
        a2 = mx.nd.array(np.random.rand(4, 3).astype(np.float32))
        a2.attach_grad()
        with ag.record():
            out = L(a2, la)
        out.backward()
        assert out.shape == (4,)
        assert np.isfinite(a2.grad.asnumpy()).all()


def test_loss_weight_and_sample_weight():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    l_plain = gloss.L2Loss()(a, b).asnumpy()
    l_weighted = gloss.L2Loss(weight=4.0)(a, b).asnumpy()
    assert_almost_equal(l_weighted, 4 * l_plain)
    sw = mx.nd.array([[1.0], [0.0]])
    l_sw = gloss.L2Loss()(a, b, sw).asnumpy()
    assert l_sw[1] == 0


def test_triplet_cosine():
    a = mx.nd.array(np.random.rand(4, 8).astype(np.float32))
    p = mx.nd.array(np.random.rand(4, 8).astype(np.float32))
    n = mx.nd.array(np.random.rand(4, 8).astype(np.float32))
    out = gloss.TripletLoss()(a, p, n)
    assert out.shape == (4,)
    lbl = mx.nd.array([1, -1, 1, -1])
    out = gloss.CosineEmbeddingLoss()(a, p, lbl)
    assert out.shape == (4,)


def test_lambda_blocks():
    lam = nn.Lambda(lambda x: x * 2)
    assert lam(mx.nd.ones((2, 2))).asnumpy().sum() == 8
    hlam = nn.HybridLambda(lambda F, x: F.invoke("relu", x) + 1)
    out = hlam(mx.nd.array([-1.0, 2.0]))
    assert_almost_equal(out, np.array([1.0, 3.0]))


def test_cast():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.cast(np.float16)
    assert net.weight.dtype == np.float16
    out = net(mx.nd.ones((2, 3), dtype=np.float16))
    assert out.dtype == np.float16


def test_reflection_pad():
    layer = nn.ReflectionPad2D(1)
    layer.initialize()
    x = mx.nd.array(np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3))
    out = layer(x)
    assert out.shape == (1, 1, 5, 5)
    ref = np.pad(x.asnumpy(), ((0, 0), (0, 0), (1, 1), (1, 1)), mode="reflect")
    assert_almost_equal(out, ref)


def test_grad_through_hybrid_params():
    """Gradients reach parameters through the compiled path and match the
    eager path (parity: the check_consistency idea applied to hybridize)."""
    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(1, in_units=16))
        return net

    np.random.seed(0)
    mx.random.seed(0)
    net_e = build()
    net_e.initialize()
    net_h = build()
    net_h.initialize()
    # copy weights
    for pe, ph in zip(net_e.collect_params().values(),
                      net_h.collect_params().values()):
        ph.set_data(pe.data())
    net_h.hybridize()

    x = mx.nd.array(np.random.rand(4, 8).astype(np.float32))
    grads = []
    for net in (net_e, net_h):
        with ag.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        grads.append([p.grad().asnumpy() for p in net.collect_params().values()])
    for ge, gh in zip(*grads):
        assert_almost_equal(ge, gh, rtol=1e-4, atol=1e-5)


def test_multi_threaded_inference():
    """Thread-safe hybridized inference (parity capability:
    example/multi_threaded_inference — the reference's thread-safe
    CachedOp). Many host threads share one compiled executable; results
    must match the single-threaded oracle exactly."""
    import threading

    import numpy as onp

    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(32, activation="relu"))
        net.add(mx.gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net.hybridize(static_alloc=True)

    rs = onp.random.RandomState(0)
    batches = [rs.rand(4, 16).astype("f") for _ in range(16)]
    oracle = [net(mx.nd.array(b)).asnumpy() for b in batches]

    results = [None] * len(batches)
    errors = []

    def worker(i):
        try:
            results[i] = net(mx.nd.array(batches[i])).asnumpy()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(batches))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for got, want in zip(results, oracle):
        onp.testing.assert_allclose(got, want, rtol=1e-6)
