"""Autograd tape tests.

Parity model: tests/python/unittest/test_autograd.py — record/pause
semantics, backward through op chains, grad accumulation reqs, detach,
autograd.grad, custom Function, exception-at-sync semantics.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.test_utils import assert_almost_equal


def test_record_flags():
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
        with ag.predict_mode():
            assert ag.is_recording()
            assert not ag.is_training()
    assert not ag.is_recording()


def test_simple_backward():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * np.array([1.0, 2.0, 3.0]))


def test_chain_rule():
    x = mx.nd.array(np.random.rand(3, 4).astype(np.float32))
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = (y * y + y).sum()
    z.backward()
    xn = x.asnumpy()
    assert_almost_equal(x.grad, 8 * xn + 2)


def test_multiple_uses():
    # x used on two tape paths: grads must sum
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x + x * 3
    y.backward()
    assert_almost_equal(x.grad, np.array([2 * 2.0 + 3]))


def test_grad_accumulation_add():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 3 * 2 * np.array([1.0, 2.0]))


def test_grad_req_write_overwrites():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()  # write
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 2 * np.array([1.0, 2.0]))


def test_detach():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    # dz/dx = y.detach() = 9 (no flow through y)
    assert_almost_equal(x.grad, np.array([9.0]))


def test_head_grad():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
    y.backward(mx.nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([20.0, 200.0]))


def test_backward_non_scalar():
    x = mx.nd.ones((2, 3))
    x.attach_grad()
    with ag.record():
        y = x * 5
    y.backward()  # default head grad = ones
    assert_almost_equal(x.grad, 5 * np.ones((2, 3)))


def test_autograd_grad():
    x = mx.nd.array([2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x ** 3).sum()
    (g,) = ag.grad([y], [x])
    assert_almost_equal(g, 3 * np.array([2.0, 3.0]) ** 2)


def test_mark_variables():
    x = mx.nd.array([4.0])
    g = mx.nd.zeros((1,))
    ag.mark_variables([x], [g])
    with ag.record():
        y = x * x
    y.backward()
    assert_almost_equal(g, np.array([8.0]))


def test_no_record_no_grad():
    x = mx.nd.array([1.0])
    x.attach_grad()
    y = x * x  # not recording
    with pytest.raises(ValueError):
        y.backward()


def test_inplace_on_recorded_raises():
    x = mx.nd.array([1.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        with pytest.raises(mx.MXNetError):
            y += 1


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = 1 / (1 + (-x).exp())
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array([0.5, -0.5])
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(y, sig)
    assert_almost_equal(x.grad, sig * (1 - sig))


def test_multi_output_op_grad():
    x = mx.nd.array(np.random.rand(2, 6).astype(np.float32))
    x.attach_grad()
    with ag.record():
        parts = x.split(3, axis=1)
        y = parts[0].sum() + (parts[2] * 2).sum()
    y.backward()
    expect = np.zeros((2, 6), np.float32)
    expect[:, 0:2] = 1
    expect[:, 4:6] = 2
    assert_almost_equal(x.grad, expect)


def test_matmul_grad():
    a_np = np.random.rand(3, 4).astype(np.float32)
    b_np = np.random.rand(4, 2).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        y = mx.nd.dot(a, b).sum()
    y.backward()
    ones = np.ones((3, 2), np.float32)
    assert_almost_equal(a.grad, ones @ b_np.T)
    assert_almost_equal(b.grad, a_np.T @ ones)


def test_training_flag_dropout_semantics():
    # is_training drives Dropout behavior at the layer level; here check flag
    with ag.record(train_mode=False):
        assert ag.is_recording() and not ag.is_training()
    with ag.train_mode():
        assert ag.is_training()


def test_grad_create_graph_second_order():
    """grad-of-grad matches the analytic second derivative (parity:
    reference autograd.py:271 create_graph)."""
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with mx.autograd.record():
        y = (x ** 3).sum()
        gx = mx.autograd.grad([y], [x], create_graph=True)[0]
        z = gx.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6 * np.array([1.0, 2.0, 3.0]),
                               rtol=1e-5)


def test_grad_create_graph_matches_finite_differences():
    xv = np.array([0.5, -0.7], np.float32)
    x = mx.nd.array(xv)
    x.attach_grad()
    with mx.autograd.record():
        y = (mx.nd.exp(x) * mx.nd.sin(x)).sum()
        g = mx.autograd.grad([y], [x], create_graph=True)[0]
        z = (g * g).sum()
    z.backward()

    def first(v):
        return np.exp(v) * (np.sin(v) + np.cos(v))

    eps = 1e-3
    fd = ((first(xv + eps) ** 2).astype(np.float64)
          - (first(xv - eps) ** 2)) / (2 * eps)
    np.testing.assert_allclose(x.grad.asnumpy(), fd, rtol=1e-2)


def test_grad_create_graph_multi_variable():
    a, b = mx.nd.array([1.5]), mx.nd.array([2.5])
    a.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        y = (a * a * b).sum()
        ga, gb = mx.autograd.grad([y], [a, b], create_graph=True)
        z = (ga * gb).sum()  # (2ab)(a^2)
    z.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [6 * 1.5 ** 2 * 2.5],
                               rtol=1e-5)
    np.testing.assert_allclose(b.grad.asnumpy(), [2 * 1.5 ** 3], rtol=1e-5)


def test_grad_create_graph_non_leaf_raises():
    x = mx.nd.array([1.0])
    x.attach_grad()
    with mx.autograd.record():
        y = x * x
        z = (y * y).sum()
        with pytest.raises(ValueError):
            mx.autograd.grad([z], [y], create_graph=True)


def test_grad_create_graph_none_head_grads():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with mx.autograd.record():
        y1 = (x * x).sum()
        y2 = (x * x * x).sum()
        g = mx.autograd.grad([y1, y2], [x],
                             head_grads=[mx.nd.array([2.0]), None],
                             create_graph=True)[0]
    np.testing.assert_allclose(g.asnumpy(), [2 * 2 * 2.0 + 3 * 4.0],
                               rtol=1e-5)
