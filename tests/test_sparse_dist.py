"""Real sparse storage + dist kvstore hardening tests (parity model:
tests/python/unittest/test_sparse_ndarray.py, test_kvstore.py dist
sections, gradient_compression tests)."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.sparse import (RowSparseNDArray, merge_duplicates,
                                      row_sparse_array, sparse_add)


# two-process suites need multiprocess collectives on the CPU backend,
# which this jax/jaxlib only implements from 0.5 on (older versions raise
# XlaRuntimeError: "Multiprocess computations aren't implemented on the
# CPU backend" inside the child ranks)
_JAX_VERSION = tuple(int(x) for x in __import__("jax").__version__
                     .split(".")[:2])
_needs_multiprocess_cpu = pytest.mark.skipif(
    _JAX_VERSION < (0, 5),
    reason="multiprocess CPU collectives unsupported by jax "
           f"{__import__('jax').__version__} (needs >= 0.5)")


def test_row_sparse_is_lazy():
    """Construction must NOT materialize dense storage."""
    rs = row_sparse_array((onp.ones((2, 4), "float32"), [1, 5]),
                          shape=(100, 4))
    assert rs._dense_cache is None        # nothing densified yet
    assert rs.shape == (100, 4)           # metadata without densify
    assert rs.stype == "row_sparse"
    assert rs._dense_cache is None
    dense = rs.tostype("default")         # explicit densify
    assert dense.shape == (100, 4)
    onp.testing.assert_allclose(dense.asnumpy()[1], onp.ones(4))
    onp.testing.assert_allclose(dense.asnumpy()[0], onp.zeros(4))


def test_sparse_add_row_union():
    a = row_sparse_array((onp.ones((2, 3), "float32"), [0, 2]), shape=(5, 3))
    b = row_sparse_array((2 * onp.ones((2, 3), "float32"), [2, 4]),
                         shape=(5, 3))
    c = sparse_add(a, b)
    assert c.stype == "row_sparse"
    assert c.indices.asnumpy().tolist() == [0, 2, 4]
    onp.testing.assert_allclose(c.data.asnumpy()[1], 3 * onp.ones(3))
    ref = a.tostype("default").asnumpy() + b.tostype("default").asnumpy()
    onp.testing.assert_allclose(c.tostype("default").asnumpy(), ref)


def test_merge_duplicates():
    rs = RowSparseNDArray(onp.ones((3, 2), "float32"), [1, 1, 3],
                          shape=(5, 2))
    m = merge_duplicates(rs)
    assert m.indices.asnumpy().tolist() == [1, 3]
    onp.testing.assert_allclose(m.data.asnumpy()[0], [2.0, 2.0])
    # duplicate indices also densify correctly (scatter-ADD)
    onp.testing.assert_allclose(rs.tostype("default").asnumpy()[1],
                                [2.0, 2.0])


def test_sparse_sgd_update_matches_dense():
    """Lazy row_sparse SGD touches only the gradient's rows and matches
    the dense update on those rows."""
    w_np = onp.random.RandomState(0).rand(8, 3).astype("float32")
    g_rows = onp.random.RandomState(1).rand(2, 3).astype("float32")
    idx = [1, 5]
    opt = mx.optimizer.create("sgd", learning_rate=0.1, wd=0.01)
    w_sparse = nd.array(w_np.copy())
    state = opt.create_state(0, w_sparse)
    opt.update(0, w_sparse, row_sparse_array((g_rows, idx), shape=(8, 3)),
               state)
    out = w_sparse.asnumpy()
    # untouched rows identical (lazy update: no decay off-rows)
    for r in range(8):
        if r not in idx:
            onp.testing.assert_allclose(out[r], w_np[r])
    for j, r in enumerate(idx):
        expect = w_np[r] - 0.1 * (g_rows[j] + 0.01 * w_np[r])
        onp.testing.assert_allclose(out[r], expect, rtol=1e-5)


def test_sparse_sgd_momentum_rows():
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    w = nd.array(onp.ones((6, 2), "float32"))
    state = opt.create_state(0, w)
    g = row_sparse_array((onp.ones((1, 2), "float32"), [3]), shape=(6, 2))
    opt.update(0, w, g, state)
    opt.update(0, w, g, state)
    out = w.asnumpy()
    onp.testing.assert_allclose(out[0], [1.0, 1.0])  # untouched
    # row 3: two momentum steps: m1=-0.1, w=0.9; m2=0.9*(-0.1)-0.1=-0.19
    onp.testing.assert_allclose(out[3], [1.0 - 0.1 - 0.19] * 2, rtol=1e-5)


def test_kvstore_sparse_push_pull():
    kv = mx.kv.create("local")
    kv.init("emb", nd.zeros((10, 4)))
    g1 = row_sparse_array((onp.ones((2, 4), "float32"), [0, 3]),
                          shape=(10, 4))
    g2 = row_sparse_array((onp.ones((2, 4), "float32"), [3, 7]),
                          shape=(10, 4))
    opt = mx.optimizer.create("sgd", learning_rate=1.0)
    kv.set_optimizer(opt)
    kv.push("emb", [g1, g2])
    # row_sparse_pull of selected rows
    out = row_sparse_array((onp.zeros((3, 4), "float32"), [0, 3, 7]),
                           shape=(10, 4))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array([0, 3, 7]))
    vals = out.data.asnumpy()
    onp.testing.assert_allclose(vals[0], -onp.ones(4))       # grad 1
    onp.testing.assert_allclose(vals[1], -2 * onp.ones(4))   # merged rows
    onp.testing.assert_allclose(vals[2], -onp.ones(4))


def test_gradient_compression_quantize_and_feedback():
    kv = mx.kv.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    g = nd.array([0.7, -0.9, 0.2, 0.0])
    out = kv._compressed_cross_host_sum("k", g)
    # quantized to {-thr, 0, +thr}
    onp.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
    # error feedback: residual carries the quantization error
    res = kv._residuals["k"].tolist() if hasattr(
        kv._residuals["k"], "tolist") else list(kv._residuals["k"])
    onp.testing.assert_allclose(
        onp.asarray(res), [0.2, -0.4, 0.2, 0.0], atol=1e-6)
    # a second small push accumulates: 0.2 + 0.31 > 0.5 -> fires
    out2 = kv._compressed_cross_host_sum("k", nd.array([0.31, 0.0, 0.0,
                                                        0.0]))
    assert out2.asnumpy()[0] == 0.5


def test_gradient_compression_rejects_unknown():
    kv = mx.kv.create("local")
    with pytest.raises(ValueError):
        kv.set_gradient_compression({"type": "1bit"})


def _run_two_process(tmp_path, child_src, ok_token, timeout=240):
    """Launch the 2-process localhost jax.distributed harness: write the
    child script, run both ranks, skip when the distributed runtime is
    unavailable/hung, assert both ranks print `ok_token`."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "dist_child.py"
    script.write_text(child_src)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), port, str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.getcwd()) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # one rank dying at an assert leaves the other blocked at a
        # collective; surface the dead rank's traceback instead of
        # skipping the regression as an environment problem
        dead = [(i, p) for i, p in enumerate(procs)
                if p.poll() not in (None, 0)]
        for p in procs:
            p.kill()
        if dead:
            msgs = []
            for i, p in dead:
                try:
                    msgs.append(f"rank {i}:\n" +
                                (p.communicate(timeout=10)[0] or "")[-1200:])
                except Exception:
                    pass
            raise AssertionError(
                "rank(s) failed while peers waited at a collective:\n" +
                "\n".join(msgs))
        pytest.skip("distributed runtime hung in this environment")
    if any(p.returncode != 0 for p in procs):
        joined = "\n".join(outs)
        if "DISTRIBUTED" in joined.upper() or "initialize" in joined:
            pytest.skip(f"jax.distributed unavailable: {joined[-300:]}")
        raise AssertionError(joined[-1500:])
    assert all(ok_token in o for o in outs), outs


_DIST_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import mxnet_tpu as mx
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 2, kv.num_workers
    kv.init("w", mx.nd.zeros((4,)))
    g = mx.nd.array([float(kv.rank + 1)] * 4)
    kv.push("w", g)
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    vals = out.asnumpy().tolist()
    assert vals == [3.0] * 4, vals  # 1 + 2 summed across both workers
    print("DIST_OK", kv.rank)
""")


@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_dist_sync_exact_aggregate(tmp_path):
    """2-process localhost jax.distributed: dist_sync push/pull must
    produce the exact cross-worker sum on both ranks."""
    _run_two_process(tmp_path, _DIST_CHILD, "DIST_OK", timeout=180)


_ASYNC_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import mxnet_tpu as mx
    kv = mx.kv.create("dist_async")
    assert kv.num_workers == 2
    kv.init("w", mx.nd.zeros((3,)))
    # sign-SGD updater: nonlinear in the gradient, so per-push updates
    # (async PS semantics) give a different result than one update on the
    # summed gradient: async -> -2, sync-sum -> -1
    def updater(idx, grad, weight):
        weight[:] = weight - mx.nd.sign(grad)
    kv._updater = updater
    g = mx.nd.array([float(kv.rank + 1)] * 3)
    kv.push("w", g)
    out = mx.nd.zeros((3,))
    kv.pull("w", out=out)
    vals = out.asnumpy().tolist()
    assert vals == [-2.0] * 3, vals  # two separate sign-steps
    print("ASYNC_OK", kv.rank)
""")


@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_dist_async_per_push_updates(tmp_path):
    """dist_async applies every worker's push as its own optimizer step
    (kvstore_dist_server.h async ApplyUpdates parity), observable via a
    gradient-nonlinear updater."""
    _run_two_process(tmp_path, _ASYNC_CHILD, "ASYNC_OK", timeout=180)


_TRAINER_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:  # jax < 0.5 spells this flag via XLA_FLAGS
        import os
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=2")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    assert len(jax.devices()) == 4  # 2 procs x 2 local cpu devices

    def make_net():
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
                gluon.nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier())
        return net

    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)   # GLOBAL batch
    Y = rng.randn(16, 4).astype(np.float32)

    # multi-host trainer: dp over all 4 devices; this process feeds its
    # HALF of the global batch
    net = make_net()
    tr = ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                        {"learning_rate": 0.05},
                        mesh=DeviceMesh({"dp": 4}))
    lo, hi = (0, 8) if pid == 0 else (8, 16)
    losses = []
    for _ in range(3):
        loss = tr.step(mx.nd.array(X[lo:hi]), mx.nd.array(Y[lo:hi]))
        losses.append(float(loss.asscalar()))

    # reference: LOCAL-only trainer over this process's 2 devices with
    # the full global batch — identical numerics expected
    ref_net = make_net()
    ref = ShardedTrainer(ref_net, gluon.loss.L2Loss(), "sgd",
                         {"learning_rate": 0.05},
                         mesh=DeviceMesh({"dp": 2},
                                         devices=jax.local_devices()))
    ref_losses = [float(ref.step(mx.nd.array(X),
                                 mx.nd.array(Y)).asscalar())
                  for _ in range(3)]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)

    # multi-host checkpoint round-trip: rank 0 writes, everyone loads
    import tempfile, os
    from jax.experimental import multihost_utils
    ckpt = os.path.join(tempfile.gettempdir(), "st_ckpt_" + port + ".npz")
    tr.save_states(ckpt)
    multihost_utils.sync_global_devices("ckpt_written")
    cont = float(tr.step(mx.nd.array(X[lo:hi]),
                         mx.nd.array(Y[lo:hi])).asscalar())
    net2 = make_net()
    tr2 = ShardedTrainer(net2, gluon.loss.L2Loss(), "sgd",
                         {"learning_rate": 0.05},
                         mesh=DeviceMesh({"dp": 4}))
    tr2.load_states(ckpt)
    resumed = float(tr2.step(mx.nd.array(X[lo:hi]),
                             mx.nd.array(Y[lo:hi])).asscalar())
    np.testing.assert_allclose(resumed, cont, rtol=1e-5)
    multihost_utils.sync_global_devices("done")
    if pid == 0:
        os.remove(ckpt)
    print("TRAINER_OK", pid, losses[-1])
""")


@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_sharded_trainer(tmp_path):
    """Multi-host ShardedTrainer: 2 processes x 2 devices, each feeding
    its half of the global batch — losses must equal a single-process
    run over the full batch (sharded_trainer.py _put_batch/_global_put)."""
    _run_two_process(tmp_path, _TRAINER_CHILD, "TRAINER_OK", timeout=240)


_PIPELINE_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:  # jax < 0.5 spells this flag via XLA_FLAGS
        import os
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=2")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import jax.numpy as jnp
    import numpy as np
    from mxnet_tpu.parallel import (DeviceMesh, pipeline_apply,
                                    stack_stage_params)

    S = 4  # stages over 2 processes x 2 devices: activations cross hosts
    mesh = DeviceMesh({"pp": S})
    assert mesh.is_multiprocess
    rs = np.random.RandomState(0)
    d = 8
    stages = [{"w": jnp.asarray(rs.randn(d, d) * 0.3, jnp.float32)}
              for _ in range(S)]
    stage_fn = lambda p, a: jnp.tanh(a @ p["w"])
    stacked_host = stack_stage_params(stages)
    stacked = jax.tree_util.tree_map(
        lambda p: mesh.global_put(p, "pp"), stacked_host)
    x = mesh.global_put(jnp.asarray(rs.randn(8, d), jnp.float32))
    fn = pipeline_apply(stage_fn, mesh, num_microbatches=4)
    out = np.asarray(fn(stacked, x))
    h = jnp.asarray(np.asarray(jax.device_get(x)), jnp.float32)
    for p in stages:
        h = stage_fn(p, h)
    err = float(np.abs(out - np.asarray(h)).max())
    assert err < 1e-4, err
    print("PIPE_OK", pid, err)
""")


@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_pipeline_parallel(tmp_path):
    """GPipe pipeline over a mesh spanning 2 processes: stage-to-stage
    ppermutes cross host boundaries; output exact vs the sequential
    stack (parallel/pipeline.py + mesh.global_put)."""
    _run_two_process(tmp_path, _PIPELINE_CHILD, "PIPE_OK")


_RING_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:  # jax < 0.5 spells this flag via XLA_FLAGS
        import os
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=2")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import jax.numpy as jnp
    import numpy as np
    from mxnet_tpu.parallel import (DeviceMesh, attention,
                                    ring_attention_sharded)

    mesh = DeviceMesh({"sp": 4})  # sequence sharded over 2 hosts x 2 dev
    assert mesh.is_multiprocess
    rs = np.random.RandomState(0)
    B, H, S, D = 1, 2, 32, 8
    q, k, v = (jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
               for _ in range(3))
    gq = mesh.global_put(q, None, None, "sp", None)
    gk = mesh.global_put(k, None, None, "sp", None)
    gv = mesh.global_put(v, None, None, "sp", None)
    fn = ring_attention_sharded(mesh, causal=True)
    out = fn(gq, gk, gv)
    from jax.experimental import multihost_utils
    out_np = multihost_utils.process_allgather(out, tiled=True)
    ref = np.asarray(attention(q, k, v, causal=True))
    err = float(np.abs(out_np - ref).max())
    assert err < 1e-4, err
    print("RING_OK", pid, err)
""")


@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_ring_attention(tmp_path):
    """Long-context SP across hosts: the k/v ring ppermutes cross the
    process boundary every step; output exact vs dense attention
    (parallel/ring_attention.py over a 2-process mesh)."""
    _run_two_process(tmp_path, _RING_CHILD, "RING_OK")


_MOE_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:  # jax < 0.5 spells this flag via XLA_FLAGS
        import os
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=2")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import jax.numpy as jnp
    import numpy as np
    from mxnet_tpu.parallel import (DeviceMesh, moe_apply,
                                    stack_expert_params)

    E, N, D = 4, 16, 6  # experts split over 2 hosts x 2 devices
    mesh = DeviceMesh({"ep": E})
    assert mesh.is_multiprocess
    rs = np.random.RandomState(0)
    experts = [{"w": jnp.asarray(rs.randn(D, D) * 0.5, jnp.float32)}
               for _ in range(E)]
    router_w = jnp.asarray(rs.randn(D, E), jnp.float32)
    x = jnp.asarray(rs.randn(N, D), jnp.float32)
    fn = moe_apply(lambda p, t: jnp.tanh(t @ p["w"]), mesh)
    y, aux = fn(jax.tree_util.tree_map(
                    lambda p: mesh.global_put(p, "ep"),
                    stack_expert_params(experts)),
                mesh.global_put(router_w), mesh.global_put(x))
    probs = np.asarray(jax.nn.softmax(x @ router_w, axis=-1))
    assign = probs.argmax(-1)
    ref = np.stack([probs[i, assign[i]] *
                    np.tanh(np.asarray(x[i]) @
                            np.asarray(experts[assign[i]]["w"]))
                    for i in range(N)])
    from jax.experimental import multihost_utils
    y_np = multihost_utils.process_allgather(y, tiled=True)
    err = float(np.abs(y_np - ref).max())
    assert err < 1e-4, err
    print("MOE_OK", pid, err)
""")


@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_expert_parallel(tmp_path):
    """Switch MoE with experts split across 2 processes: the dense-
    dispatch psum crosses the host boundary; output exact vs the dense
    oracle (parallel/moe.py over a multi-host mesh)."""
    _run_two_process(tmp_path, _MOE_CHILD, "MOE_OK")
