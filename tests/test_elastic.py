"""Elastic preemption-tolerant training (ISSUE 5).

The headline contracts:

* a SIGTERM (planned preemption) DRAINS the run — the in-flight step
  finishes, a final checkpoint lands through CheckpointManager (atomic,
  CRC-verified), and the process exits 75 so wrappers reschedule;
* checkpoints are topology-portable — written in canonical host layout
  with a MANIFEST ``meta.topology`` record, so a drained run resumes
  bit-exact on the SAME mesh and *resharded* on a different device count
  (matching the uninterrupted trajectory within tolerance), while
  resharding-disabled resume fails with a mesh-naming error;
* a lost peer turns a kvstore collective into a structured
  ``PeerLostError`` (with crash bundle) instead of an unbounded wedge.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, gluon, preempt, watchdog
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.kvstore import PeerLostError
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    """Every test starts and ends with no armed faults, no preempt
    handlers/flag, and the ambient watchdog config."""
    faults.reset()
    preempt.uninstall()
    yield
    faults.reset()
    preempt.uninstall()
    watchdog.configure_from_env()


def _batch(epoch, step):
    rs = np.random.RandomState(1000 * epoch + step)
    x = rs.randn(8, 6).astype(np.float32)
    y = (x @ rs.randn(6, 4) * 0.5).astype(np.float32)
    return mx.nd.array(x), mx.nd.array(y)


def _make_trainer(seed=7, mesh=None, **kw):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(_batch(1, 0)[0])
    return net, ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                               {"learning_rate": 0.05},
                               mesh=mesh or DeviceMesh({"dp": 8}), **kw)


def _params_of(net):
    return {k: p.data().asnumpy().copy()
            for k, p in net.collect_params().items()}


# ------------------------------------------------------------ preempt.py ---

def test_sigterm_sets_drain_flag_and_uninstall_restores():
    prev = signal.getsignal(signal.SIGTERM)
    assert preempt.install()
    assert preempt.installed()
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(0.05)
    assert preempt.requested()
    ev = preempt.event()
    assert ev["signal"] == "SIGTERM" and ev["pid"] == os.getpid()
    preempt.uninstall()
    assert not preempt.installed() and not preempt.requested()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_second_signal_exits_immediately(monkeypatch):
    codes = []
    monkeypatch.setattr(preempt, "_exit_fn", codes.append)
    preempt.install()
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(0.05)
    assert preempt.requested() and not codes
    os.kill(os.getpid(), signal.SIGTERM)  # grace expired: exit NOW
    time.sleep(0.05)
    assert codes == [preempt.DRAIN_EXIT_CODE]


def test_faults_preempt_mode_delivers_sigterm_and_continues():
    preempt.install()
    faults.configure("p:preempt@2")
    faults.point("p")
    assert not preempt.requested()
    out = faults.point("p", "payload")  # SIGTERM to self, then CONTINUES
    time.sleep(0.05)
    assert out == "payload"
    assert preempt.requested()
    assert preempt.event()["signal"] == "SIGTERM"


def test_env_auto_install(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PREEMPT", "sigterm")
    assert preempt.maybe_install_from_env()
    assert preempt.installed()
    preempt.uninstall()
    monkeypatch.setenv("MXNET_TPU_PREEMPT", "0")
    assert not preempt.maybe_install_from_env()
    assert not preempt.installed()


def test_step_refuses_new_work_once_draining():
    net, tr = _make_trainer()
    tr.step(*_batch(1, 0))
    before = _params_of(net)
    preempt.request("test")
    with pytest.raises(preempt.DrainRequested, match="drain requested"):
        tr.step(*_batch(1, 1))
    # the refused step mutated nothing
    for k, v in _params_of(net).items():
        np.testing.assert_array_equal(before[k], v)
    preempt.clear()
    tr.step(*_batch(1, 1))  # cleared: training continues


def test_drain_writes_final_checkpoint_event_and_exit_code(tmp_path):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    for s in range(4):
        tr.step(*_batch(1, s))
    tr.save_checkpoint(mgr, 1)
    preempt.request("drill")
    with pytest.raises(SystemExit) as exc:
        preempt.drain(directory=str(tmp_path))
    assert exc.value.code == preempt.DRAIN_EXIT_CODE == 75
    # drained checkpoint: epoch last+1, exact step, drain meta, CRC-good
    entry, paths = mgr.load()
    assert entry["epoch"] == 2 and entry["step"] == 4
    assert entry["meta"]["drain"]["reason"] == "drill"
    assert mgr.verify(entry)
    # drain event recorded for diagnose.py
    ev = preempt.last_drain(str(tmp_path))
    assert ev is not None
    assert ev["final_checkpoint"] == "written"
    assert ev["exit_code"] == 75


def test_drain_without_hook_still_exits_with_code(tmp_path):
    saved = watchdog.set_last_resort(None)
    try:
        preempt.request("no-hook")
        with pytest.raises(SystemExit) as exc:
            preempt.drain(directory=str(tmp_path))
        assert exc.value.code == 75
        assert preempt.last_drain(
            str(tmp_path))["final_checkpoint"] == "no hook installed"
    finally:
        watchdog.set_last_resort(saved)


# -------------------------------------------------- topology portability ---

def test_manifest_records_topology(tmp_path):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    tr.step(*_batch(1, 0))
    tr.save_checkpoint(mgr, 1)
    manifest = json.loads((tmp_path / "MANIFEST.json").read_text())
    topo = manifest["checkpoints"][-1]["meta"]["topology"]
    assert topo["format"] == "canonical-host-v1"
    assert topo["mesh"]["axes"] == {"dp": 8}
    assert topo["mesh"]["num_devices"] == 8
    assert "jax" in topo["host"] and "device_count" in topo["host"]
    # one spec per trainable param, JSON-able (None -> null round trip)
    assert set(topo["param_sharding"]) == set(tr._param_names)


def test_resume_topology_mismatch_raises_when_reshard_disabled(tmp_path):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    tr.step(*_batch(1, 0))
    tr.save_checkpoint(mgr, 1)
    net2, tr2 = _make_trainer(seed=999, mesh=DeviceMesh({"dp": 4}))
    with pytest.raises(ValueError) as exc:
        tr2.resume(mgr, reshard=False)
    msg = str(exc.value)
    # a clear, mesh-naming error: both topologies and the way out
    assert "DeviceMesh({'dp': 8})" in msg
    assert "DeviceMesh({'dp': 4})" in msg
    assert "reshard" in msg


def test_resume_topology_mismatch_env_knob(tmp_path, monkeypatch):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    tr.step(*_batch(1, 0))
    tr.save_checkpoint(mgr, 1)
    net2, tr2 = _make_trainer(seed=999, mesh=DeviceMesh({"dp": 2}))
    monkeypatch.setenv("MXNET_TPU_PREEMPT_RESHARD", "0")
    with pytest.raises(ValueError, match="resharding"):
        tr2.resume(mgr)


def test_resharded_resume_matches_same_mesh_resume(tmp_path):
    """Drain on dp:8, resume on dp:4 AND on dp:8: the resharded trainer
    must match the same-topology one — bit-exact at load, and within
    reduction-order tolerance after further training."""
    steps = 6
    net_a, tr_a = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    for s in range(steps):
        tr_a.step(*_batch(1, s))
    tr_a.save_checkpoint(mgr, 1)

    net_same, tr_same = _make_trainer(seed=999)  # same mesh: bit-exact
    entry = tr_same.resume(mgr)
    assert entry["epoch"] == 1 and entry["step"] == steps
    for (ka, va), (kb, vb) in zip(_params_of(net_a).items(),
                                  _params_of(net_same).items()):
        np.testing.assert_array_equal(va, vb, err_msg=f"{ka} vs {kb}")

    net_half, tr_half = _make_trainer(seed=555, mesh=DeviceMesh({"dp": 4}))
    with pytest.warns(UserWarning, match="topology change"):
        entry = tr_half.resume(mgr)
    assert entry["step"] == steps and tr_half._t == steps
    # canonical-layout arrays re-placed on the new mesh: values identical
    for (ka, va), (kb, vb) in zip(_params_of(net_same).items(),
                                  _params_of(net_half).items()):
        np.testing.assert_array_equal(va, vb, err_msg=f"{ka} vs {kb}")
    # continued training tracks the same-topology run within tolerance
    for s in range(3):
        la = tr_same.step(*_batch(2, s)).asscalar()
        lb = tr_half.step(*_batch(2, s)).asscalar()
        np.testing.assert_allclose(la, lb, rtol=1e-4, atol=1e-5)
    for (ka, va), (kb, vb) in zip(_params_of(net_same).items(),
                                  _params_of(net_half).items()):
        np.testing.assert_allclose(va, vb, rtol=1e-4, atol=1e-5,
                                   err_msg=f"{ka} vs {kb}")


def test_resharded_resume_with_zero_optimizer_state(tmp_path):
    """ZeRO-1 shards optimizer state over dp; the state is still saved in
    canonical host layout, so resume onto a different dp size reshards it
    too (the hard half of topology portability)."""
    net_a, tr_a = _make_trainer(zero=True)
    mgr = CheckpointManager(tmp_path, prefix="z")
    for s in range(4):
        tr_a.step(*_batch(1, s))
    tr_a.save_checkpoint(mgr, 1)
    ref = [[np.asarray(s) for s in per] for per in tr_a._opt_raws]

    net_b, tr_b = _make_trainer(seed=999, zero=True,
                                mesh=DeviceMesh({"dp": 2}))
    with pytest.warns(UserWarning, match="topology change"):
        tr_b.resume(mgr)
    for per_a, per_b in zip(ref, tr_b._opt_raws):
        for sa, sb in zip(per_a, per_b):
            np.testing.assert_array_equal(sa, np.asarray(sb))
    tr_b.step(*_batch(2, 0))  # the resharded state actually steps


# --------------------------------------------------------- fit-loop drain --

def test_estimator_fit_drains_with_final_checkpoint(tmp_path):
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                                   Estimator)

    mx.random.seed(3)
    net = gluon.nn.Dense(3)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((4, 5)))
    rs = np.random.RandomState(0)
    data = [(mx.nd.array(rs.randn(4, 5).astype(np.float32)),
             mx.nd.array(rs.randint(0, 3, 4).astype(np.float32)))
            for _ in range(4)]
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(), context=mx.cpu(),
                    trainer=Trainer(net.collect_params(), "sgd",
                                    {"learning_rate": 0.05}))
    handler = CheckpointHandler(str(tmp_path), model_prefix="m",
                                max_checkpoints=3)
    preempt.request("estimator-drill")
    with pytest.raises(SystemExit) as exc:
        est.fit(data, epochs=3, event_handlers=[handler])
    assert exc.value.code == 75
    manifest = json.loads((tmp_path / "MANIFEST.json").read_text())
    entry = manifest["checkpoints"][-1]
    assert entry["epoch"] == 1  # mid-epoch-1 drain
    assert entry["meta"]["drain"]["reason"] == "estimator-drill"
    assert (tmp_path / "m-0001.params").exists()


def test_module_fit_drains_through_epoch_end_callbacks(tmp_path):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    sym = mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                               name="softmax")
    rs = np.random.RandomState(0)
    X = rs.randn(64, 5).astype(np.float32)
    Y = rs.randint(0, 3, 64).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym)
    saved = []
    preempt.request("module-drill")
    with pytest.raises(SystemExit) as exc:
        mod.fit(it, num_epoch=4,
                epoch_end_callback=lambda e, s, a, x: saved.append(e))
    assert exc.value.code == 75
    assert saved == [0]  # the drain ran the checkpoint callbacks once


# ------------------------------------------------------ peer-loss (gang) ---

def test_kvstore_barrier_raises_peer_lost_with_bundle(tmp_path):
    kv = mx.kv.create("dist_sync")  # 1-worker group without a tracker
    kv.init("w", mx.nd.zeros((3,)))
    watchdog.configure({"kvstore.sync": 0.4},
                       crash_dir=str(tmp_path), interval=0.05)
    faults.configure("kvstore.sync:hang@1:3")  # the dead-peer wedge
    with pytest.raises(PeerLostError, match="peer lost") as exc:
        kv.barrier()
    e = exc.value
    assert isinstance(e, watchdog.StallError)  # stall handlers still catch
    assert e.op == "barrier" and e.rank == 0 and e.num_workers == 1
    assert e.bundle and os.path.isdir(e.bundle)
    assert "threads.txt" in os.listdir(e.bundle)
    watchdog.configure_from_env()
    time.sleep(3.2)  # drain the abandoned daemon waiter


def test_kvstore_cross_host_sum_raises_peer_lost(tmp_path):
    kv = mx.kv.create("dist_sync")
    watchdog.configure({"kvstore.sync": 0.4},
                       crash_dir=str(tmp_path), interval=0.05)
    faults.configure("kvstore.sync:hang@1:3")
    with pytest.raises(PeerLostError, match="cross_host_sum"):
        kv._cross_host_sum(mx.nd.ones((4,)))
    watchdog.configure_from_env()
    time.sleep(3.2)


def test_kvstore_barrier_unbounded_without_deadline_still_works():
    kv = mx.kv.create("dist_sync")
    kv.barrier()  # no kvstore.sync deadline armed: plain inline barrier


# --------------------------------------------- subprocess drain + resume ---

CHILD = os.path.join(REPO, "tests", "_elastic_child.py")


def _run_child(ckpt_dir, out=None, devices=4, extra=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
           "EL_CKPT_DIR": str(ckpt_dir), "EL_TOTAL": "12", "EL_EPOCH": "4",
           "EL_DEVICES": str(devices)}
    env.pop("MXNET_TPU_FAULTS", None)
    env.pop("XLA_FLAGS", None)  # the child sets its own device count
    if out is not None:
        env["EL_OUT"] = str(out)
    env.update(extra or {})
    return subprocess.run([sys.executable, CHILD], env=env,
                          capture_output=True, text=True, timeout=240)


@pytest.mark.skipif(not hasattr(os, "kill"), reason="needs POSIX signals")
def test_sigterm_drain_then_same_topology_resume_bit_exact(tmp_path):
    """SIGTERM mid-epoch (fault mode 'preempt' at global step 6 of 12):
    the child drains — finishes step 6, writes a final checkpoint, exits
    75 — and a same-topology restart resumes from the EXACT step to
    bit-exact final params vs the uninterrupted run."""
    ref_out = tmp_path / "ref.npz"
    proc = _run_child(tmp_path / "ref", ref_out)
    assert proc.returncode == 0, proc.stderr

    drain_dir = tmp_path / "drain"
    proc = _run_child(drain_dir, tmp_path / "never.npz",
                      extra={"MXNET_TPU_FAULTS": "trainer.step:preempt@6"})
    assert proc.returncode == 75, (proc.returncode, proc.stderr)
    assert not (tmp_path / "never.npz").exists()
    manifest = json.loads((drain_dir / "MANIFEST.json").read_text())
    entry = manifest["checkpoints"][-1]
    assert entry["step"] == 6  # drained AFTER the in-flight step finished
    assert entry["meta"]["drain"]["signal"] == "SIGTERM"
    assert [f for f in os.listdir(drain_dir)
            if f.startswith("drain-")], "drain event record missing"

    res_out = tmp_path / "resumed.npz"
    proc = _run_child(drain_dir, res_out, extra={"EL_RESUME": "1"})
    assert proc.returncode == 0, proc.stderr
    ref, got = dict(np.load(ref_out)), dict(np.load(res_out))
    assert ref.keys() == got.keys()
    for k in ref:
        if k == "__losses__":
            continue  # per-run loss logs cover different step ranges
        np.testing.assert_array_equal(ref[k], got[k]), k
    # the resumed run replayed exactly the post-drain losses
    np.testing.assert_array_equal(ref["__losses__"][6:], got["__losses__"])


@pytest.mark.skipif(not hasattr(os, "kill"), reason="needs POSIX signals")
def test_sigterm_drain_then_resharded_resume_across_device_counts(tmp_path):
    """The acceptance headline: drain on N=4 simulated devices, resume on
    M=2 — the resharded run must reach the uninterrupted 4-device run's
    loss trajectory and final params within tolerance; and with
    resharding disabled the mismatch fails loudly, naming both meshes."""
    ref_out = tmp_path / "ref.npz"
    proc = _run_child(tmp_path / "ref", ref_out, devices=4)
    assert proc.returncode == 0, proc.stderr

    drain_dir = tmp_path / "drain"
    proc = _run_child(drain_dir, devices=4,
                      extra={"MXNET_TPU_FAULTS": "trainer.step:preempt@6"})
    assert proc.returncode == 75, (proc.returncode, proc.stderr)

    # resharding disabled: loud, mesh-naming failure
    proc = _run_child(drain_dir, devices=2,
                      extra={"EL_RESUME": "1", "EL_RESHARD": "0"})
    assert proc.returncode != 0 and proc.returncode != 75
    assert "DeviceMesh({'dp': 4})" in proc.stderr
    assert "DeviceMesh({'dp': 2})" in proc.stderr

    # resharding on (the default): N=4 -> M=2 resume completes and tracks
    res_out = tmp_path / "resumed.npz"
    proc = _run_child(drain_dir, res_out, devices=2,
                      extra={"EL_RESUME": "1"})
    assert proc.returncode == 0, proc.stderr
    assert "devices=2" in proc.stdout
    ref, got = dict(np.load(ref_out)), dict(np.load(res_out))
    np.testing.assert_allclose(ref["__losses__"][6:], got["__losses__"],
                               rtol=1e-4, atol=1e-5)
    for k in ref:
        if k == "__losses__":
            continue
        np.testing.assert_allclose(ref[k], got[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)
