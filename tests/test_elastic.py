"""Elastic preemption-tolerant training (ISSUE 5).

The headline contracts:

* a SIGTERM (planned preemption) DRAINS the run — the in-flight step
  finishes, a final checkpoint lands through CheckpointManager (atomic,
  CRC-verified), and the process exits 75 so wrappers reschedule;
* checkpoints are topology-portable — written in canonical host layout
  with a MANIFEST ``meta.topology`` record, so a drained run resumes
  bit-exact on the SAME mesh and *resharded* on a different device count
  (matching the uninterrupted trajectory within tolerance), while
  resharding-disabled resume fails with a mesh-naming error;
* a lost peer turns a kvstore collective into a structured
  ``PeerLostError`` (with crash bundle) instead of an unbounded wedge.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic, faults, gluon, preempt, watchdog
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.kvstore import PeerLostError
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean():
    """Every test starts and ends with no armed faults, no preempt
    handlers/flag, no gang worker plumbing, and the ambient watchdog
    config."""
    faults.reset()
    preempt.uninstall()
    yield
    faults.reset()
    preempt.uninstall()
    elastic.stop_heartbeat()
    elastic.uninstall_excepthook()
    watchdog.configure_from_env()


def _batch(epoch, step):
    rs = np.random.RandomState(1000 * epoch + step)
    x = rs.randn(8, 6).astype(np.float32)
    y = (x @ rs.randn(6, 4) * 0.5).astype(np.float32)
    return mx.nd.array(x), mx.nd.array(y)


def _make_trainer(seed=7, mesh=None, **kw):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(_batch(1, 0)[0])
    return net, ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                               {"learning_rate": 0.05},
                               mesh=mesh or DeviceMesh({"dp": 8}), **kw)


def _params_of(net):
    return {k: p.data().asnumpy().copy()
            for k, p in net.collect_params().items()}


# ------------------------------------------------------------ preempt.py ---

def test_sigterm_sets_drain_flag_and_uninstall_restores():
    prev = signal.getsignal(signal.SIGTERM)
    assert preempt.install()
    assert preempt.installed()
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(0.05)
    assert preempt.requested()
    ev = preempt.event()
    assert ev["signal"] == "SIGTERM" and ev["pid"] == os.getpid()
    preempt.uninstall()
    assert not preempt.installed() and not preempt.requested()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_second_signal_exits_immediately(monkeypatch):
    codes = []
    monkeypatch.setattr(preempt, "_exit_fn", codes.append)
    preempt.install()
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(0.05)
    assert preempt.requested() and not codes
    os.kill(os.getpid(), signal.SIGTERM)  # grace expired: exit NOW
    time.sleep(0.05)
    assert codes == [preempt.DRAIN_EXIT_CODE]


def test_faults_preempt_mode_delivers_sigterm_and_continues():
    preempt.install()
    faults.configure("p:preempt@2")
    faults.point("p")
    assert not preempt.requested()
    out = faults.point("p", "payload")  # SIGTERM to self, then CONTINUES
    time.sleep(0.05)
    assert out == "payload"
    assert preempt.requested()
    assert preempt.event()["signal"] == "SIGTERM"


def test_env_auto_install(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PREEMPT", "sigterm")
    assert preempt.maybe_install_from_env()
    assert preempt.installed()
    preempt.uninstall()
    monkeypatch.setenv("MXNET_TPU_PREEMPT", "0")
    assert not preempt.maybe_install_from_env()
    assert not preempt.installed()


def test_step_refuses_new_work_once_draining():
    net, tr = _make_trainer()
    tr.step(*_batch(1, 0))
    before = _params_of(net)
    preempt.request("test")
    with pytest.raises(preempt.DrainRequested, match="drain requested"):
        tr.step(*_batch(1, 1))
    # the refused step mutated nothing
    for k, v in _params_of(net).items():
        np.testing.assert_array_equal(before[k], v)
    preempt.clear()
    tr.step(*_batch(1, 1))  # cleared: training continues


def test_drain_writes_final_checkpoint_event_and_exit_code(tmp_path):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    for s in range(4):
        tr.step(*_batch(1, s))
    tr.save_checkpoint(mgr, 1)
    preempt.request("drill")
    with pytest.raises(SystemExit) as exc:
        preempt.drain(directory=str(tmp_path))
    assert exc.value.code == preempt.DRAIN_EXIT_CODE == 75
    # drained checkpoint: epoch last+1, exact step, drain meta, CRC-good
    entry, paths = mgr.load()
    assert entry["epoch"] == 2 and entry["step"] == 4
    assert entry["meta"]["drain"]["reason"] == "drill"
    assert mgr.verify(entry)
    # drain event recorded for diagnose.py
    ev = preempt.last_drain(str(tmp_path))
    assert ev is not None
    assert ev["final_checkpoint"] == "written"
    assert ev["exit_code"] == 75


def test_drain_without_hook_still_exits_with_code(tmp_path):
    saved = watchdog.set_last_resort(None)
    try:
        preempt.request("no-hook")
        with pytest.raises(SystemExit) as exc:
            preempt.drain(directory=str(tmp_path))
        assert exc.value.code == 75
        assert preempt.last_drain(
            str(tmp_path))["final_checkpoint"] == "no hook installed"
    finally:
        watchdog.set_last_resort(saved)


# -------------------------------------------------- topology portability ---

def test_manifest_records_topology(tmp_path):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    tr.step(*_batch(1, 0))
    tr.save_checkpoint(mgr, 1)
    manifest = json.loads((tmp_path / "MANIFEST.json").read_text())
    topo = manifest["checkpoints"][-1]["meta"]["topology"]
    assert topo["format"] == "canonical-host-v1"
    assert topo["mesh"]["axes"] == {"dp": 8}
    assert topo["mesh"]["num_devices"] == 8
    assert "jax" in topo["host"] and "device_count" in topo["host"]
    # one spec per trainable param, JSON-able (None -> null round trip)
    assert set(topo["param_sharding"]) == set(tr._param_names)


def test_resume_topology_mismatch_raises_when_reshard_disabled(tmp_path):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    tr.step(*_batch(1, 0))
    tr.save_checkpoint(mgr, 1)
    net2, tr2 = _make_trainer(seed=999, mesh=DeviceMesh({"dp": 4}))
    with pytest.raises(ValueError) as exc:
        tr2.resume(mgr, reshard=False)
    msg = str(exc.value)
    # a clear, mesh-naming error: both topologies and the way out
    assert "DeviceMesh({'dp': 8})" in msg
    assert "DeviceMesh({'dp': 4})" in msg
    assert "reshard" in msg


def test_resume_topology_mismatch_env_knob(tmp_path, monkeypatch):
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    tr.step(*_batch(1, 0))
    tr.save_checkpoint(mgr, 1)
    net2, tr2 = _make_trainer(seed=999, mesh=DeviceMesh({"dp": 2}))
    monkeypatch.setenv("MXNET_TPU_PREEMPT_RESHARD", "0")
    with pytest.raises(ValueError, match="resharding"):
        tr2.resume(mgr)


def test_resharded_resume_matches_same_mesh_resume(tmp_path):
    """Drain on dp:8, resume on dp:4 AND on dp:8: the resharded trainer
    must match the same-topology one — bit-exact at load, and within
    reduction-order tolerance after further training."""
    steps = 6
    net_a, tr_a = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="el")
    for s in range(steps):
        tr_a.step(*_batch(1, s))
    tr_a.save_checkpoint(mgr, 1)

    net_same, tr_same = _make_trainer(seed=999)  # same mesh: bit-exact
    entry = tr_same.resume(mgr)
    assert entry["epoch"] == 1 and entry["step"] == steps
    for (ka, va), (kb, vb) in zip(_params_of(net_a).items(),
                                  _params_of(net_same).items()):
        np.testing.assert_array_equal(va, vb, err_msg=f"{ka} vs {kb}")

    net_half, tr_half = _make_trainer(seed=555, mesh=DeviceMesh({"dp": 4}))
    with pytest.warns(UserWarning, match="topology change"):
        entry = tr_half.resume(mgr)
    assert entry["step"] == steps and tr_half._t == steps
    # canonical-layout arrays re-placed on the new mesh: values identical
    for (ka, va), (kb, vb) in zip(_params_of(net_same).items(),
                                  _params_of(net_half).items()):
        np.testing.assert_array_equal(va, vb, err_msg=f"{ka} vs {kb}")
    # continued training tracks the same-topology run within tolerance
    for s in range(3):
        la = tr_same.step(*_batch(2, s)).asscalar()
        lb = tr_half.step(*_batch(2, s)).asscalar()
        np.testing.assert_allclose(la, lb, rtol=1e-4, atol=1e-5)
    for (ka, va), (kb, vb) in zip(_params_of(net_same).items(),
                                  _params_of(net_half).items()):
        np.testing.assert_allclose(va, vb, rtol=1e-4, atol=1e-5,
                                   err_msg=f"{ka} vs {kb}")


def test_resharded_resume_with_zero_optimizer_state(tmp_path):
    """ZeRO-1 shards optimizer state over dp; the state is still saved in
    canonical host layout, so resume onto a different dp size reshards it
    too (the hard half of topology portability)."""
    net_a, tr_a = _make_trainer(zero=True)
    mgr = CheckpointManager(tmp_path, prefix="z")
    for s in range(4):
        tr_a.step(*_batch(1, s))
    tr_a.save_checkpoint(mgr, 1)
    ref = [[np.asarray(s) for s in per] for per in tr_a._opt_raws]

    net_b, tr_b = _make_trainer(seed=999, zero=True,
                                mesh=DeviceMesh({"dp": 2}))
    with pytest.warns(UserWarning, match="topology change"):
        tr_b.resume(mgr)
    for per_a, per_b in zip(ref, tr_b._opt_raws):
        for sa, sb in zip(per_a, per_b):
            np.testing.assert_array_equal(sa, np.asarray(sb))
    tr_b.step(*_batch(2, 0))  # the resharded state actually steps


# --------------------------------------------------------- fit-loop drain --

def test_estimator_fit_drains_with_final_checkpoint(tmp_path):
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                                   Estimator)

    mx.random.seed(3)
    net = gluon.nn.Dense(3)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((4, 5)))
    rs = np.random.RandomState(0)
    data = [(mx.nd.array(rs.randn(4, 5).astype(np.float32)),
             mx.nd.array(rs.randint(0, 3, 4).astype(np.float32)))
            for _ in range(4)]
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(), context=mx.cpu(),
                    trainer=Trainer(net.collect_params(), "sgd",
                                    {"learning_rate": 0.05}))
    handler = CheckpointHandler(str(tmp_path), model_prefix="m",
                                max_checkpoints=3)
    preempt.request("estimator-drill")
    with pytest.raises(SystemExit) as exc:
        est.fit(data, epochs=3, event_handlers=[handler])
    assert exc.value.code == 75
    manifest = json.loads((tmp_path / "MANIFEST.json").read_text())
    entry = manifest["checkpoints"][-1]
    assert entry["epoch"] == 1  # mid-epoch-1 drain
    assert entry["meta"]["drain"]["reason"] == "estimator-drill"
    assert (tmp_path / "m-0001.params").exists()


def test_module_fit_drains_through_epoch_end_callbacks(tmp_path):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    sym = mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                               name="softmax")
    rs = np.random.RandomState(0)
    X = rs.randn(64, 5).astype(np.float32)
    Y = rs.randint(0, 3, 64).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym)
    saved = []
    preempt.request("module-drill")
    with pytest.raises(SystemExit) as exc:
        mod.fit(it, num_epoch=4,
                epoch_end_callback=lambda e, s, a, x: saved.append(e))
    assert exc.value.code == 75
    assert saved == [0]  # the drain ran the checkpoint callbacks once


# ------------------------------------------------------ peer-loss (gang) ---

def test_kvstore_barrier_raises_peer_lost_with_bundle(tmp_path):
    kv = mx.kv.create("dist_sync")  # 1-worker group without a tracker
    kv.init("w", mx.nd.zeros((3,)))
    watchdog.configure({"kvstore.sync": 0.4},
                       crash_dir=str(tmp_path), interval=0.05)
    faults.configure("kvstore.sync:hang@1:3")  # the dead-peer wedge
    with pytest.raises(PeerLostError, match="peer lost") as exc:
        kv.barrier()
    e = exc.value
    assert isinstance(e, watchdog.StallError)  # stall handlers still catch
    assert e.op == "barrier" and e.rank == 0 and e.num_workers == 1
    assert e.bundle and os.path.isdir(e.bundle)
    assert "threads.txt" in os.listdir(e.bundle)
    watchdog.configure_from_env()
    time.sleep(3.2)  # drain the abandoned daemon waiter


def test_kvstore_cross_host_sum_raises_peer_lost(tmp_path):
    kv = mx.kv.create("dist_sync")
    watchdog.configure({"kvstore.sync": 0.4},
                       crash_dir=str(tmp_path), interval=0.05)
    faults.configure("kvstore.sync:hang@1:3")
    with pytest.raises(PeerLostError, match="cross_host_sum"):
        kv._cross_host_sum(mx.nd.ones((4,)))
    watchdog.configure_from_env()
    time.sleep(3.2)


def test_kvstore_barrier_unbounded_without_deadline_still_works():
    kv = mx.kv.create("dist_sync")
    kv.barrier()  # no kvstore.sync deadline armed: plain inline barrier


# --------------------------------------------- subprocess drain + resume ---

CHILD = os.path.join(REPO, "tests", "_elastic_child.py")


def _run_child(ckpt_dir, out=None, devices=4, extra=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
           "EL_CKPT_DIR": str(ckpt_dir), "EL_TOTAL": "12", "EL_EPOCH": "4",
           "EL_DEVICES": str(devices)}
    env.pop("MXNET_TPU_FAULTS", None)
    env.pop("XLA_FLAGS", None)  # the child sets its own device count
    if out is not None:
        env["EL_OUT"] = str(out)
    env.update(extra or {})
    return subprocess.run([sys.executable, CHILD], env=env,
                          capture_output=True, text=True, timeout=240)


@pytest.mark.skipif(not hasattr(os, "kill"), reason="needs POSIX signals")
def test_sigterm_drain_then_same_topology_resume_bit_exact(tmp_path):
    """SIGTERM mid-epoch (fault mode 'preempt' at global step 6 of 12):
    the child drains — finishes step 6, writes a final checkpoint, exits
    75 — and a same-topology restart resumes from the EXACT step to
    bit-exact final params vs the uninterrupted run."""
    ref_out = tmp_path / "ref.npz"
    proc = _run_child(tmp_path / "ref", ref_out)
    assert proc.returncode == 0, proc.stderr

    drain_dir = tmp_path / "drain"
    proc = _run_child(drain_dir, tmp_path / "never.npz",
                      extra={"MXNET_TPU_FAULTS": "trainer.step:preempt@6"})
    assert proc.returncode == 75, (proc.returncode, proc.stderr)
    assert not (tmp_path / "never.npz").exists()
    manifest = json.loads((drain_dir / "MANIFEST.json").read_text())
    entry = manifest["checkpoints"][-1]
    assert entry["step"] == 6  # drained AFTER the in-flight step finished
    assert entry["meta"]["drain"]["signal"] == "SIGTERM"
    assert [f for f in os.listdir(drain_dir)
            if f.startswith("drain-")], "drain event record missing"

    res_out = tmp_path / "resumed.npz"
    proc = _run_child(drain_dir, res_out, extra={"EL_RESUME": "1"})
    assert proc.returncode == 0, proc.stderr
    ref, got = dict(np.load(ref_out)), dict(np.load(res_out))
    assert ref.keys() == got.keys()
    for k in ref:
        if k == "__losses__":
            continue  # per-run loss logs cover different step ranges
        np.testing.assert_array_equal(ref[k], got[k]), k
    # the resumed run replayed exactly the post-drain losses
    np.testing.assert_array_equal(ref["__losses__"][6:], got["__losses__"])


@pytest.mark.skipif(not hasattr(os, "kill"), reason="needs POSIX signals")
def test_sigterm_drain_then_resharded_resume_across_device_counts(tmp_path):
    """The acceptance headline: drain on N=4 simulated devices, resume on
    M=2 — the resharded run must reach the uninterrupted 4-device run's
    loss trajectory and final params within tolerance; and with
    resharding disabled the mismatch fails loudly, naming both meshes."""
    ref_out = tmp_path / "ref.npz"
    proc = _run_child(tmp_path / "ref", ref_out, devices=4)
    assert proc.returncode == 0, proc.stderr

    drain_dir = tmp_path / "drain"
    proc = _run_child(drain_dir, devices=4,
                      extra={"MXNET_TPU_FAULTS": "trainer.step:preempt@6"})
    assert proc.returncode == 75, (proc.returncode, proc.stderr)

    # resharding disabled: loud, mesh-naming failure
    proc = _run_child(drain_dir, devices=2,
                      extra={"EL_RESUME": "1", "EL_RESHARD": "0"})
    assert proc.returncode != 0 and proc.returncode != 75
    assert "DeviceMesh({'dp': 4})" in proc.stderr
    assert "DeviceMesh({'dp': 2})" in proc.stderr

    # resharding on (the default): N=4 -> M=2 resume completes and tracks
    res_out = tmp_path / "resumed.npz"
    proc = _run_child(drain_dir, res_out, devices=2,
                      extra={"EL_RESUME": "1"})
    assert proc.returncode == 0, proc.stderr
    assert "devices=2" in proc.stdout
    ref, got = dict(np.load(ref_out)), dict(np.load(res_out))
    np.testing.assert_allclose(ref["__losses__"][6:], got["__losses__"],
                               rtol=1e-4, atol=1e-5)
    for k in ref:
        if k == "__losses__":
            continue
        np.testing.assert_allclose(ref[k], got[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


# ============================================= gang supervisor (ISSUE 10) ---

def _py(body):
    return [sys.executable, "-c", body]


def _supervise(cmd, tmp_path, n=2, **kw):
    kw.setdefault("poll", 0.05)
    kw.setdefault("backoff", 0.01)
    kw.setdefault("grace", 2.0)
    return elastic.GangSupervisor(cmd, num_workers=n,
                                  run_dir=str(tmp_path / "run"), **kw)


def test_exit_ladder_helpers():
    assert preempt.canonical_exit(-9) == 137  # Popen SIGKILL convention
    assert preempt.classify_exit(75) == "drain"
    assert preempt.classify_exit(76) == "peer-lost"
    assert preempt.classify_exit(86) == "watchdog-abort"
    assert preempt.classify_exit(-9) == "killed"
    assert preempt.classify_exit(3) == "error"
    # severity: ok < drain < peer-lost < abort < killed < real error
    sevs = [preempt.exit_severity(c) for c in (0, 75, 76, 86, 137, 1)]
    assert sevs == sorted(sevs) and len(set(sevs)) == len(sevs)
    assert preempt.most_severe([0, 75, 0]) == 75
    assert preempt.most_severe([75, -9, 86]) == 137
    assert preempt.most_severe([137, 1, 75]) == 1  # a real bug outranks
    assert preempt.most_severe([]) == 0
    assert PeerLostError.exit_code == preempt.PEERLOST_EXIT_CODE == 76


def test_supervisor_all_ok_is_done(tmp_path):
    sup = _supervise(_py("import sys; sys.exit(0)"), tmp_path)
    assert sup.run() == 0
    assert sup.state == "done" and sup.generation == 1
    assert sup.restarts_used == 0
    summary = json.loads((tmp_path / "run" / "gang.json").read_text())
    assert summary["state"] == "done"


def test_supervisor_restart_on_drain_code(tmp_path):
    """Exit 75 at generation 1 -> gang-wide restart at generation 2."""
    body = ("import os, sys; sys.exit("
            "75 if os.environ['MXTPU_GANG_GENERATION'] == '1' else 0)")
    sup = _supervise(_py(body), tmp_path)
    assert sup.run() == 0
    assert sup.state == "done" and sup.generation == 2
    assert sup.restarts_used == 1
    assert "drain" in sup.history[0]["reason"]
    states = [s for _, s in sup.state_history]
    for want in ("degraded", "rescheduling", "resuming", "done"):
        assert want in states, states
    # ranks were NOT shrunk: 75 is a clean drain, the slot survives
    assert len(sup.slots) == 2


def test_supervisor_restart_on_watchdog_abort_notes_bundles(tmp_path):
    """Exit 86 restarts too, and the incarnation record carries the crash
    bundles the aborting worker left behind."""
    run = tmp_path / "run"
    (run / "crash" / "bundle-test-p1-1-trainer_step").mkdir(parents=True)
    body = ("import os, sys; sys.exit("
            "86 if os.environ['MXTPU_GANG_GENERATION'] == '1' else 0)")
    sup = _supervise(_py(body), tmp_path, n=1)
    assert sup.run() == 0
    assert sup.generation == 2 and sup.restarts_used == 1
    assert "watchdog-abort" in sup.history[0]["reason"]
    assert any("bundle-test" in b
               for b in sup.history[0]["crash_bundles"])


def test_supervisor_kill_shrinks_census_and_renumbers(tmp_path):
    """A SIGKILLed rank (137) is a lost slot under shrink_on_kill: the
    next generation runs with fewer, densely renumbered ranks at a fresh
    coordinator epoch; survivors are drained (SIGTERM) first."""
    out = tmp_path / "census"
    out.mkdir()
    body = (
        "import os, sys, time, signal, pathlib\n"
        "gen = os.environ['MXTPU_GANG_GENERATION']\n"
        "rank = os.environ['MXTPU_WORKER_ID']\n"
        "pathlib.Path(%r, 'gen%%s-rank%%s' %% (gen, rank)).write_text(\n"
        "    os.environ['MXTPU_NUM_WORKERS'] + ' '\n"
        "    + os.environ['MXTPU_COORDINATOR'])\n"
        "if gen == '1' and rank == '1':\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "time.sleep(0.4)\n"
        "sys.exit(0)\n" % str(out))
    sup = _supervise(_py(body), tmp_path, shrink_on_kill=True)
    assert sup.run() == 0
    assert sup.state == "done" and sup.generation == 2
    assert len(sup.slots) == 1
    assert sup.history[0]["shrunk"] == [{"rank": 1, "host": "local"}]
    files = sorted(os.listdir(out))
    assert files == ["gen1-rank0", "gen1-rank1", "gen2-rank0"]
    n1, coord1 = (out / "gen1-rank0").read_text().split()
    n2, coord2 = (out / "gen2-rank0").read_text().split()
    assert (n1, n2) == ("2", "1")
    assert coord1 != coord2  # new generation == new coordinator epoch


def test_supervisor_budget_exhaustion_writes_postmortem(tmp_path):
    sup = _supervise(_py("import sys; sys.exit(86)"), tmp_path, n=1,
                     max_restarts=1)
    assert sup.run() == 1
    assert sup.state == "failed" and sup.generation == 2
    assert sup.postmortem_path and os.path.isfile(sup.postmortem_path)
    pm = json.loads(open(sup.postmortem_path).read())
    assert "restart budget exhausted (1/1)" in pm["reason"]
    assert [g["exits"] for g in pm["generations"]] == [{"0": 86}] * 2
    for key in ("heartbeats", "crash_bundles", "drain_events",
                "state_history", "supervisor_flight_tail"):
        assert key in pm


def test_supervisor_fatal_exit_no_restart(tmp_path):
    """A non-ladder exit is a real bug: no restart, post-mortem, the
    child's code propagates."""
    sup = _supervise(_py("import sys; sys.exit(3)"), tmp_path, n=1)
    assert sup.run() == 3
    assert sup.state == "failed" and sup.generation == 1
    assert sup.restarts_used == 0
    assert "error" in sup.history[0]["reason"]
    assert sup.postmortem_path and os.path.isfile(sup.postmortem_path)


def test_supervisor_heartbeat_dead_worker_is_killed(tmp_path):
    """Slow-vs-dead: a live process whose heartbeats stop is declared
    dead (SIGKILL) instead of being trusted forever."""
    body = (
        "import json, os, time\n"
        "d = os.environ['MXTPU_GANG_DIR']\n"
        "rec = {'rank': 0, 'pid': os.getpid(), 't_wall': time.time(),\n"
        "       'generation': int(os.environ['MXTPU_GANG_GENERATION'])}\n"
        "json.dump(rec, open(os.path.join(d, 'rank-0.json'), 'w'))\n"
        "time.sleep(30)\n")
    sup = _supervise(_py(body), tmp_path, n=1, max_restarts=0,
                     dead_after=0.6)
    assert sup.run() == 1  # budget 0: first loss already exhausts it
    assert sup.state == "failed"
    assert sup.history[0]["liveness_killed"] == [0]
    assert "heartbeat-lost" in sup.history[0]["reason"]


def test_heartbeat_roundtrip(tmp_path):
    hb = elastic.start_heartbeat(tmp_path, rank=3, generation=2,
                                 interval=0.05)
    assert hb is elastic.start_heartbeat(tmp_path, 3, 2)  # idempotent
    time.sleep(0.15)
    beats = elastic.read_heartbeats(tmp_path)
    assert 3 in beats
    rec = beats[3]
    assert rec["pid"] == os.getpid() and rec["generation"] == 2
    assert rec["state"] == "running" and rec["age_s"] < 5.0
    assert "flight_tail" in rec
    elastic.stop_heartbeat()


def test_kill_peer_and_peerloss_fault_mode(tmp_path, monkeypatch):
    """The seedable gang drill: 'peerloss' SIGKILLs the named rank via
    its heartbeat file."""
    sleeper = subprocess.Popen([sys.executable, "-c",
                                "import time; time.sleep(60)"])
    try:
        (tmp_path / "rank-1.json").write_text(
            json.dumps({"rank": 1, "pid": sleeper.pid,
                        "generation": 1, "t_wall": time.time()}))
        monkeypatch.setenv("MXTPU_GANG_DIR", str(tmp_path))
        faults.configure("p:peerloss@2:1")
        faults.point("p")                      # 1st invocation: no fire
        assert sleeper.poll() is None
        assert faults.point("p", "payload") == "payload"  # fires, returns
        assert sleeper.wait(timeout=10) == -signal.SIGKILL
    finally:
        if sleeper.poll() is None:
            sleeper.kill()
    # a peerloss without a target or without a gang is a loud error
    with pytest.raises(RuntimeError, match="no target rank"):
        elastic.kill_peer(None)
    with pytest.raises(RuntimeError, match="no heartbeat for rank 7"):
        elastic.kill_peer(7, run_dir=str(tmp_path))


def test_excepthook_maps_exit_code(monkeypatch, capsys):
    codes = []
    monkeypatch.setattr(elastic, "_exit_fn", codes.append)
    prev = sys.excepthook
    elastic.install_excepthook()
    try:
        class _Lost(RuntimeError):
            exit_code = 76

        sys.excepthook(_Lost, _Lost("peer gone"), None)
        assert codes == [76]
        assert "peer gone" in capsys.readouterr().err  # traceback printed
        sys.excepthook(RuntimeError, RuntimeError("plain"), None)
        assert codes == [76]  # no exit_code attr: normal handling only
    finally:
        elastic.uninstall_excepthook()
    assert sys.excepthook is prev


def test_gang_metrics_exported(tmp_path):
    """mxtpu_gang_generation / restart counters ride the standard
    /metrics scrape path."""
    body = ("import os, sys; sys.exit("
            "75 if os.environ['MXTPU_GANG_GENERATION'] == '1' else 0)")
    sup = _supervise(_py(body), tmp_path, n=1)
    assert sup.run() == 0
    from mxnet_tpu.telemetry import export

    text = export.render_prometheus()
    assert f"mxtpu_gang_generation {sup.generation}" in text
    assert 'mxtpu_gang_restarts_total{reason="drain"}' in text
    assert "mxtpu_gang_state_code" in text
    snap = export.metrics_snapshot()
    assert snap["mxtpu_gang_generation"]["series"][0]["value"] == \
        sup.generation


def test_maybe_init_distributed_re_rendezvous(monkeypatch):
    """A new gang generation means a new coordinator epoch: an
    already-joined process shuts its old client down and re-initializes
    at the new address; the same generation is a no-op."""
    import jax
    from jax._src import distributed as _dist

    from mxnet_tpu import base

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(("init", kw)))
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: calls.append(("shutdown",)))
    monkeypatch.setattr(_dist.global_state, "client", object(),
                        raising=False)
    monkeypatch.setenv("MXTPU_COORDINATOR", "127.0.0.1:9999")
    monkeypatch.setenv("MXTPU_NUM_WORKERS", "2")
    monkeypatch.setenv("MXTPU_WORKER_ID", "1")
    monkeypatch.setenv("MXTPU_GANG_GENERATION", "3")
    monkeypatch.setattr(base, "_dist_generation", 1)
    base.maybe_init_distributed()
    assert calls[0] == ("shutdown",)
    assert calls[1][0] == "init"
    assert calls[1][1] == {"coordinator_address": "127.0.0.1:9999",
                           "num_processes": 2, "process_id": 1}
    assert base._dist_generation == 3
    calls.clear()
    base.maybe_init_distributed()  # same generation: already joined
    assert calls == []


def test_launch_local_propagates_most_severe(tmp_path):
    import launch

    drain = ("import os, sys; "
             "sys.exit([0, 75][int(os.environ['MXTPU_WORKER_ID'])])")
    assert launch.launch_local(2, _py(drain), grace=5.0) == 75
    err = ("import os, sys; "
           "sys.exit([1, 75][int(os.environ['MXTPU_WORKER_ID'])])")
    assert launch.launch_local(2, _py(err), grace=5.0) == 1
    assert launch.most_severe([0, None, -9, 75]) == 137


def test_launch_ssh_command_quoting():
    import launch

    argv = launch._ssh_command("host1", {"A": "x y", "B": "1"},
                               ["python", "train.py", "--name", "a b"],
                               cwd="/tmp/w d")
    assert argv[:4] == ["ssh", "-o", "StrictHostKeyChecking=no", "-tt"]
    assert argv[4] == "host1"
    remote = argv[5]
    assert "cd '/tmp/w d'" in remote
    assert "exec env" in remote and "A='x y'" in remote
    assert remote.endswith("python train.py --name 'a b'")


# ------------------------------------- supervised kill-and-recover drill ---

GANG_CHILD = os.path.join(REPO, "tests", "_gang_child.py")
LAUNCH = os.path.join(REPO, "tools", "launch.py")


def _gang_env(extra=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    for k in ("MXNET_TPU_FAULTS", "XLA_FLAGS", "MXTPU_GANG_DIR",
              "MXTPU_COORDINATOR", "MXTPU_NUM_WORKERS",
              "MXTPU_WORKER_ID", "MXTPU_GANG_GENERATION"):
        env.pop(k, None)
    env.update(extra or {})
    return env


@pytest.mark.skipif(not hasattr(os, "kill"), reason="needs POSIX signals")
def test_gang_supervisor_kill_and_recover_resharded(tmp_path):
    """The acceptance drill: under ``tools/launch.py --supervise -n 2``,
    SIGKILLing one worker mid-epoch (seeded ``peerloss`` fault at rank
    0's step 6) auto-recovers with ZERO human intervention — the
    supervisor drains the survivor (its checkpoint lands at the exact
    step), shrinks the census 2 -> 1, bumps to generation 2 at a fresh
    coordinator epoch, and the resumed worker reshards 4 -> 2 devices and
    matches the uninterrupted run's loss trajectory within 1e-4."""
    ref_out = tmp_path / "ref.npz"
    proc = subprocess.run(
        [sys.executable, GANG_CHILD],
        env=_gang_env({"GC_DEVICES": "4", "GC_TOTAL": "12",
                       "GC_EPOCH": "4",
                       "GC_CKPT_DIR": str(tmp_path / "refck"),
                       "GC_OUT": str(ref_out)}),
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr

    run_dir = tmp_path / "run"
    out = tmp_path / "out.npz"
    proc = subprocess.run(
        [sys.executable, LAUNCH, "--supervise", "-n", "2",
         "--run-dir", str(run_dir), "--shrink-on-kill",
         "--max-restarts", "3", "--backoff", "0.1", "--grace", "60",
         "--poll", "0.05", sys.executable, GANG_CHILD],
        env=_gang_env({"GC_BASE_DEVICES": "2", "GC_TOTAL": "12",
                       "GC_EPOCH": "4", "GC_STEP_SLEEP": "0.25",
                       "GC_OUT": str(out),
                       "GC_FAULTS_GEN1": "trainer.step:peerloss@6:1"}),
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)

    summary = json.loads((run_dir / "gang.json").read_text())
    assert summary["state"] == "done"
    assert summary["generation"] == 2 and summary["restarts_used"] == 1
    assert "killed" in summary["history"][0]["reason"]
    assert summary["history"][0]["shrunk"] == [{"rank": 1,
                                                "host": "local"}]

    ref, got = dict(np.load(ref_out)), dict(np.load(out))
    start = int(got["__start__"])
    assert 0 < start < 12          # resumed mid-run, not from scratch
    assert int(got["__generation__"]) == 2
    assert int(got["__devices__"]) == 2  # resharded from the ref's 4
    np.testing.assert_allclose(ref["__losses__"][start:],
                               got["__losses__"], rtol=1e-4, atol=1e-5)
    for k in ref:
        if k.startswith("__"):
            continue
        np.testing.assert_allclose(ref[k], got[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)

    # the survivor's drain was recorded with its gang coordinates
    drains = [n for n in os.listdir(run_dir / "ckpt")
              if n.startswith("drain-")]
    assert drains, "no drain event recorded by the drained survivor"
    ev = json.loads((run_dir / "ckpt" / sorted(drains)[-1]).read_text())
    assert ev["gang"]["generation"] == "1"
    assert ev["final_checkpoint"] == "written"
