"""Concurrency analyzer (analysis.concur) tests.

One planted bug per pass: an AB/BA lock cycle (pass 1), an unlocked
cross-thread dict write (pass 2), a raw ``open(..., "w")`` shard writer
plus a pid-only tmp name plus an unguarded ``json.load`` (pass 3), and a
runtime acquisition-order inversion caught by the witness (pass 4) —
each reported with the exact file:line site.  Plus the knob
(``MXNET_TPU_CONCUR=0``), the suppression grammar, the mxlint rule
bridge with its ratcheted baseline, the whole-package clean scan, and
regression tests for the pid+thread tmp-name fixes the analyzer found
in ``checkpoint.atomic_write`` / ``elastic._atomic_json`` /
``serving.worker.write_spec``.
"""
import json
import os
import sys
import threading

import pytest

from mxnet_tpu.analysis import concur

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean_witness():
    """Every test starts and ends with the witness disarmed + empty."""
    concur.untrace_locks()
    concur.reset_witness()
    yield
    concur.untrace_locks()
    concur.reset_witness()


# ------------------------------------------------- pass 1: lock order --

DEADLOCK_SRC = """\
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def forward():
    with LOCK_A:
        with LOCK_B:
            pass


def backward():
    with LOCK_B:
        with LOCK_A:
            pass
"""


def test_lock_order_cycle_is_site_named(tmp_path):
    f = tmp_path / "planted.py"
    f.write_text(DEADLOCK_SRC)
    issues = concur.check_lock_order(root=str(tmp_path), files=[str(f)])
    errs = [i for i in issues if i.code == "lock-order-cycle"]
    assert errs and all(i.is_error for i in errs)
    # both acquisition sites are named: AB nests at line 9, BA at 15
    blob = " ".join(i.message + " " + i.node for i in errs)
    assert "planted.py:9" in blob and "planted.py:15" in blob
    assert "LOCK_A" in blob and "LOCK_B" in blob


def test_consistent_order_is_clean(tmp_path):
    f = tmp_path / "ok.py"
    f.write_text(DEADLOCK_SRC.replace(
        "    with LOCK_B:\n        with LOCK_A:",
        "    with LOCK_A:\n        with LOCK_B:"))
    assert concur.check_lock_order(root=str(tmp_path),
                                   files=[str(f)]) == []


# ---------------------------------------------- pass 2: shared state --

SHARED_SRC = """\
import threading

STATS = {}
LOCK = threading.Lock()


def worker():
    STATS["beats"] = STATS.get("beats", 0) + 1


def start():
    t = threading.Thread(target=worker)
    t.start()
    STATS["started"] = 1
"""


def test_unlocked_cross_thread_write_is_flagged(tmp_path):
    f = tmp_path / "shared.py"
    f.write_text(SHARED_SRC)
    issues = concur.check_shared_state(root=str(tmp_path),
                                       files=[str(f)])
    hits = [i for i in issues if i.code == "unlocked-shared-state"]
    assert hits, issues
    sites = {i.node for i in hits}
    # the thread-reachable write (line 8) and/or the main write (14):
    # at least one is named, and the message names STATS
    assert sites & {"shared.py:8", "shared.py:14"}, sites
    assert any("STATS" in i.message for i in hits)


def test_shared_state_lock_and_suppression(tmp_path):
    # the same write under the common lock is clean
    locked = SHARED_SRC.replace(
        '    STATS["beats"] = STATS.get("beats", 0) + 1',
        '    with LOCK:\n'
        '        STATS["beats"] = STATS.get("beats", 0) + 1').replace(
        '    STATS["started"] = 1',
        '    with LOCK:\n        STATS["started"] = 1')
    f = tmp_path / "locked.py"
    f.write_text(locked)
    assert concur.check_shared_state(root=str(tmp_path),
                                     files=[str(f)]) == []
    # ...and the explicit marker suppresses (must terminate the line)
    suppressed = SHARED_SRC.replace(
        '    STATS["beats"] = STATS.get("beats", 0) + 1',
        '    STATS["beats"] = STATS.get("beats", 0) + 1'
        '  # concur: atomic').replace(
        '    STATS["started"] = 1',
        '    STATS["started"] = 1  # concur: atomic')
    g = tmp_path / "suppressed.py"
    g.write_text(suppressed)
    assert concur.check_shared_state(root=str(tmp_path),
                                     files=[str(g)]) == []


# ------------------------------------------------ pass 3: torn files --

TORN_SRC = """\
import json
import os


def write_shard(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)


def read_shard(path):
    with open(path) as f:
        return json.load(f)
"""


def test_raw_writer_and_unguarded_reader_flagged(tmp_path):
    f = tmp_path / "torn.py"
    f.write_text(TORN_SRC)
    issues = concur.check_torn_files(root=str(tmp_path), files=[str(f)])
    codes = {i.code for i in issues}
    assert "torn-file-write" in codes and "torn-read" in codes
    write = [i for i in issues if i.code == "torn-file-write"][0]
    read = [i for i in issues if i.code == "torn-read"][0]
    assert write.node == "torn.py:6"
    assert read.node == "torn.py:12"


def test_torn_tmp_name_must_embed_pid_and_thread(tmp_path):
    f = tmp_path / "seam.py"
    f.write_text(
        "import json\n"
        "import os\n"
        "\n"
        "\n"
        "def atomic_write(path, obj):\n"
        "    tmp = f\"{path}.tmp.{os.getpid()}\"\n"
        "    with open(tmp, 'w') as fh:\n"
        "        json.dump(obj, fh)\n"
        "    os.replace(tmp, path)\n")
    concur.register_seam("seam", "atomic_write", "test seam")
    try:
        issues = concur.check_torn_files(root=str(tmp_path),
                                         files=[str(f)])
        tmp_issues = [i for i in issues if i.code == "torn-tmp-name"]
        assert tmp_issues, issues
        assert "thread" in tmp_issues[0].message
        # pid+thread-ident tmp name passes
        f.write_text(f.read_text().replace(
            "{os.getpid()}", "{os.getpid()}.{threading.get_ident()}")
            .replace("import os\n", "import os\nimport threading\n"))
        issues = concur.check_torn_files(root=str(tmp_path),
                                         files=[str(f)])
        assert [i for i in issues if i.code == "torn-tmp-name"] == []
    finally:
        concur.TORN_SEAMS.pop(("seam", "atomic_write"), None)


def test_torn_ok_suppression(tmp_path):
    f = tmp_path / "torn.py"
    f.write_text(TORN_SRC.replace(
        '    with open(path, "w") as f:',
        '    with open(path, "w") as f:  # concur: torn-ok').replace(
        "        json.dump(obj, f)",
        "        json.dump(obj, f)  # concur: torn-ok").replace(
        "        return json.load(f)",
        "        return json.load(f)  # concur: torn-ok"))
    assert concur.check_torn_files(root=str(tmp_path),
                                   files=[str(f)]) == []


def test_guarded_reader_is_clean(tmp_path):
    f = tmp_path / "guarded.py"
    f.write_text(
        "import json\n"
        "\n"
        "\n"
        "def read_shard(path):\n"
        "    try:\n"
        "        with open(path) as f:\n"
        "            return json.load(f)\n"
        "    except (OSError, ValueError):\n"
        "        return None\n")
    assert [i for i in concur.check_torn_files(root=str(tmp_path),
                                               files=[str(f)])
            if i.code == "torn-read"] == []


# -------------------------------------------------- pass 4: witness --

def test_witness_catches_runtime_inversion():
    a = concur.wrap(threading.Lock(), "test.A")
    b = concur.wrap(threading.Lock(), "test.B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t = threading.Thread(target=ab)
    t.start()
    t.join()
    t = threading.Thread(target=ba)
    t.start()
    t.join()
    with pytest.raises(concur.LockOrderError) as ei:
        concur.check_witness(static=False)
    msg = str(ei.value)
    assert "test.A" in msg and "test.B" in msg
    # both witnessing sites are named (this file)
    assert msg.count("test_concur.py:") >= 2
    # non-raising form returns the inversion for tooling
    assert concur.check_witness(raise_=False, static=False)
    assert concur.witness_state()["last_inversion"]


def test_witness_consistent_order_is_clean():
    a = concur.wrap(threading.Lock(), "test.A")
    b = concur.wrap(threading.Lock(), "test.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert concur.check_witness(static=False) == []
    st = concur.witness_state()
    assert st["pairs"] == 1 and st["ring"] >= 6


def test_witness_delegates_lock_api():
    lk = concur.wrap(threading.Lock(), "test.delegate")
    assert lk.acquire(timeout=1.0)
    assert lk.locked()
    lk.release()
    cond = concur.wrap(threading.Condition(), "test.cond")
    with cond:
        cond.notify_all()  # Condition API reachable through the wrapper


def test_trace_locks_wraps_and_restores_package_locks():
    n = concur.trace_locks()
    assert n >= 10  # the package's module-level control-plane locks
    from mxnet_tpu import faults

    # wrapped attribute is a witness, and survives a real acquire
    assert isinstance(faults._lock, concur._WitnessLock)
    with faults._lock:
        pass
    assert concur.witness_state()["armed"]
    assert concur.witness_state()["ring"] >= 1
    # arming twice is a no-op
    assert concur.trace_locks() == 0
    restored = concur.untrace_locks()
    assert restored == n
    assert not isinstance(faults._lock, concur._WitnessLock)


def test_witness_clean_under_serving_and_modelbus(tmp_path):
    """The integration bar: threaded serving + live-weight streaming
    run with every module-level lock witnessed — zero inversions."""
    import numpy as np

    from mxnet_tpu import gluon, modelbus, serving

    n = concur.trace_locks()
    assert n
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize()
    container = serving.ModelContainer()
    container.add_block("wit", net, example_shape=(8,), buckets=(2,))
    server = serving.ModelServer(container, max_wait_ms=1.0).start()
    try:
        bus = modelbus.ModelBus(str(tmp_path / "bus"))
        bus.publish([(k, p.data().asnumpy())
                     for k, p in net.collect_params().items()], step=1)
        watcher = server.watch_bus(bus, poll=0.01)
        errors = []

        def client(tid):
            rng = np.random.RandomState(tid)
            for _ in range(5):
                try:
                    server.predict("wit",
                                   rng.randn(1, 8).astype(np.float32),
                                   timeout=10.0)
                except Exception as e:
                    errors.append(e)

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        deadline = 200
        while watcher.applied_version < 1 and deadline:
            deadline -= 1
            threading.Event().wait(0.01)
        assert not errors, errors[:3]
        assert concur.check_witness(raise_=False) == []
        assert concur.witness_state()["ring"] > 0
    finally:
        server.drain(timeout=10.0)


# --------------------------------------------------- knob + package --

def test_env_opt_out(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CONCUR", "0")
    assert not concur.enabled()
    assert concur.run() == []
    assert concur.trace_locks() == 0
    assert concur.witness_state()["armed"] is False
    monkeypatch.setenv("MXNET_TPU_CONCUR", "1")
    assert concur.enabled()


def test_package_scans_clean():
    """The ratchet: the installed package carries zero concurrency
    findings — new lock cycles, unlocked shared writes or raw tmp-file
    protocols fail here with the site in the message."""
    issues = concur.run_static()
    assert issues == [], [f"[{i.code}] {i.node}: {i.message}"
                          for i in issues]


def test_callable_module_and_error_class():
    from mxnet_tpu import analysis

    assert analysis.concur() == []  # callable, clean package
    # ConcurError realises once (lazily) and carries .issues
    cls = concur.ConcurError
    assert cls is concur.ConcurError and issubclass(cls, Exception)
    err = cls([concur.Issue("error", "lock-order-cycle", "x.py:1",
                            "f", "planted")])
    assert err.issues and err.issues[0].is_error


def test_suppression_marker_must_terminate_line(tmp_path):
    # a marker that does NOT end the line is not a suppression: the
    # same markers that silence the finding in
    # test_shared_state_lock_and_suppression stop working with trailing
    # prose appended
    f = tmp_path / "mid.py"
    f.write_text(SHARED_SRC.replace(
        '    STATS["beats"] = STATS.get("beats", 0) + 1',
        '    STATS["beats"] = STATS.get("beats", 0) + 1'
        '  # concur: atomic (prose)').replace(
        '    STATS["started"] = 1',
        '    STATS["started"] = 1  # concur: atomic (prose)'))
    issues = concur.check_shared_state(root=str(tmp_path),
                                       files=[str(f)])
    assert any(i.code == "unlocked-shared-state" for i in issues), issues


# ----------------------------------------------------- real-fix regressions

def _hammer(write, path, payloads, rounds=25):
    """Two threads write the same final path concurrently; pre-fix the
    pid-only tmp name collided and the loser's os.replace raised
    FileNotFoundError."""
    errors = []

    def worker(payload):
        for _ in range(rounds):
            try:
                write(path, payload)
            except FileNotFoundError as e:  # the PR-16-class bug
                errors.append(e)
    threads = [threading.Thread(target=worker, args=(p,))
               for p in payloads]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    return errors


def test_atomic_write_concurrent_same_path(tmp_path):
    from mxnet_tpu import checkpoint

    path = str(tmp_path / "spec.json")

    def write(p, payload):
        def _w(tmp):
            with open(tmp, "w") as f:
                json.dump(payload, f)
        checkpoint.atomic_write(p, _w)

    errors = _hammer(write, path, [{"v": 1}, {"v": 2}])
    assert errors == []
    with open(path) as f:
        assert json.load(f)["v"] in (1, 2)
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]


def test_elastic_atomic_json_concurrent_same_path(tmp_path):
    from mxnet_tpu.elastic import _atomic_json

    path = str(tmp_path / "heartbeat.json")
    errors = _hammer(_atomic_json, path, [{"rank": 0}, {"rank": 1}])
    assert errors == []
    with open(path) as f:
        assert json.load(f)["rank"] in (0, 1)


def test_worker_write_spec_concurrent_same_path(tmp_path):
    from mxnet_tpu.serving import worker

    errors = _hammer(lambda d, models: worker.write_spec(d, models),
                     str(tmp_path), [[{"name": "a"}], [{"name": "b"}]])
    assert errors == []
    with open(tmp_path / worker.SPEC_FILE) as f:
        assert json.load(f)["models"][0]["name"] in ("a", "b")
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]


# ------------------------------------------------------- mxlint bridge --

@pytest.mark.lint
def test_mxlint_concurrency_rules_fire(tmp_path):
    import mxlint

    bad = tmp_path / "bad.py"
    bad.write_text(
        DEADLOCK_SRC
        + "\nSTATE = {}\n"
        "\n"
        "\n"
        "def spawn():\n"
        "    t = threading.Thread(target=poke)\n"
        "    t.start()\n"
        "    STATE['x'] = 1\n"
        "\n"
        "\n"
        "def poke():\n"
        "    STATE['y'] = 2\n"
        "\n"
        "\n"
        "def dump(path, obj):\n"
        "    import json\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump(obj, f)\n")
    rules = {f.rule for f in mxlint.run([str(bad)], root=str(tmp_path))}
    assert {"lock-order", "shared-state", "torn-file"} <= rules
    # per-rule noqa works through the bridge
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import json\n"
        "\n"
        "\n"
        "def dump(path, obj):\n"
        "    with open(path, 'w') as f:  # noqa: torn-file\n"
        "        json.dump(obj, f)  # noqa: torn-file\n")
    assert [f for f in mxlint.run([str(ok)], root=str(tmp_path))
            if f.rule == "torn-file"] == []


@pytest.mark.lint
def test_mxlint_concurrency_baseline_ratchet(tmp_path):
    """Baseline semantics for the new rules: tolerated legacy findings
    pass, one extra torn-file write fails the gate."""
    import mxlint

    f = tmp_path / "m.py"
    f.write_text("import json\n"
                 "\n"
                 "\n"
                 "def dump(path, obj):\n"
                 "    with open(path, 'w') as fh:\n"
                 "        json.dump(obj, fh)\n")
    base = tmp_path / "base.txt"
    findings = [x for x in mxlint.run([str(f)], root=str(tmp_path))
                if x.rule == "torn-file"]
    assert findings
    base.write_text(f"torn-file m.py {len(findings)}  # legacy writer\n")
    assert mxlint.main([str(f), "--root", str(tmp_path),
                        "--baseline", str(base),
                        "--rule", "torn-file"]) == 0
    f.write_text(f.read_text()
                 + "\n\ndef dump2(path, obj):\n"
                 "    with open(path, 'w') as fh:\n"
                 "        json.dump(obj, fh)\n")
    assert mxlint.main([str(f), "--root", str(tmp_path),
                        "--baseline", str(base),
                        "--rule", "torn-file"]) == 1


@pytest.mark.lint
def test_diagnose_concurrency_section():
    import diagnose

    out = diagnose.check_concur()
    assert out["enabled"] is True
    assert out["graph"]["locks"] >= 10
    assert out["findings"] == []
    assert out["witness"]["armed"] is False
    assert len(out["torn_seams"]) >= 10
