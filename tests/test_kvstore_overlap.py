"""Bucketed async gradient collectives (docs/PERFORMANCE.md):
deterministic bucket assembly, bit-identity with the legacy per-key
path, fingerprint stability, overlap telemetry, and the 2-process A/B
acceptance drill."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.kvstore import buckets

from test_sparse_dist import _needs_multiprocess_cpu


# --------------------------------------------------------- plan assembly ---

def test_bucket_plan_greedy_cap_and_order():
    plan = buckets.BucketPlan(64)  # cap: 16 f32 elements
    plan.register("a", (2, 2), "float32")   # 16B -> bucket 0
    plan.register("b", (8,), "float32")     # 32B -> bucket 0 (48B)
    plan.register("c", (4,), "float32")     # 16B -> bucket 0 (64B, fits)
    plan.register("d", (1,), "float32")     # bucket 0 full -> bucket 1
    assert [b["keys"] for b in plan.buckets] == [["a", "b", "c"], ["d"]]
    assert plan.buckets[0]["nbytes"] == 64
    # assignment is stable under append and a pure function of the
    # registration sequence
    plan2 = buckets.BucketPlan(64)
    for k, s in (("a", (2, 2)), ("b", (8,)), ("c", (4,)), ("d", (1,))):
        plan2.register(k, s, "float32")
    assert [b["keys"] for b in plan2.buckets] == \
        [b["keys"] for b in plan.buckets]


def test_bucket_plan_oversized_single_grad_own_bucket():
    plan = buckets.BucketPlan(64)
    plan.register("small", (4,), "float32")
    plan.register("huge", (1024,), "float32")  # 4KB >> cap
    plan.register("tail", (4,), "float32")
    assert [b["keys"] for b in plan.buckets] == \
        [["small"], ["huge"], ["tail"]]


def test_bucket_plan_dtype_split_and_idempotent_register():
    plan = buckets.BucketPlan(1 << 20)
    plan.register("f", (4,), "float32")
    plan.register("i", (4,), "int32")     # dtype change -> new bucket
    plan.register("g", (4,), "float32")   # and again
    assert len(plan.buckets) == 3
    bid = plan.register("f", (4,), "float32")  # idempotent
    assert bid == 0 and len(plan.order) == 3


def test_bucket_plan_empty_and_single_key():
    plan = buckets.BucketPlan(buckets.DEFAULT_BUCKET_BYTES)
    assert plan.buckets == [] and plan.describe()["keys"] == 0
    plan.register("only", (3, 3), "float32")
    assert [b["keys"] for b in plan.buckets] == [["only"]]


def test_bucket_bytes_env(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_BUCKET_BYTES", raising=False)
    assert buckets.bucket_bytes() == buckets.DEFAULT_BUCKET_BYTES
    monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "1234")
    assert buckets.bucket_bytes() == 1234
    monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "0")
    assert buckets.bucket_bytes() == 0
    monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "junk")
    assert buckets.bucket_bytes() == buckets.DEFAULT_BUCKET_BYTES


# --------------------------------------- forced pipeline vs legacy (1 proc) --

SHAPES = [(4, 4), (8,), (2, 3), (16,), (1,)]


def _drive(kv, steps=3, order="backward"):
    for i, s in enumerate(SHAPES):
        kv.init(i, mx.nd.zeros(s))
    outs = None
    for step in range(steps):
        idxs = range(len(SHAPES))
        if order == "backward":
            idxs = reversed(list(idxs))
        for i in idxs:
            g = mx.nd.array(onp.full(SHAPES[i], 0.25 * (i + 1) + 0.1 * step,
                                     onp.float32))
            kv.push(i, g, priority=-i)
        outs = [mx.nd.zeros(s) for s in SHAPES]
        for i in range(len(SHAPES)):
            kv.pull(i, outs[i])
    kv.barrier()
    return [o.asnumpy() for o in outs]


@pytest.mark.parametrize("cap", ["1", "48", "4096", None])
def test_forced_pipeline_bit_identical_to_legacy(monkeypatch, cap):
    """Every bucket size — per-key (1B cap), mixed partial-fit, one big
    bucket, and the default — produces bit-identical pulls vs the
    legacy path (MXNET_TPU_BUCKET_BYTES=0)."""
    monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")
    if cap is None:
        monkeypatch.delenv("MXNET_TPU_BUCKET_BYTES", raising=False)
    else:
        monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", cap)
    bucketed = _drive(mx.kv.create("dist_sync"))
    monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "0")
    monkeypatch.delenv("MXNET_TPU_BUCKET_FORCE", raising=False)
    legacy = _drive(mx.kv.create("dist_sync"))
    for a, b in zip(bucketed, legacy):
        assert onp.array_equal(a, b), (cap, a, b)


def test_forced_pipeline_update_on_store_bit_identical(monkeypatch):
    def run(force):
        monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1" if force else "0")
        monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "" if force else "0")
        kv = mx.kv.create("dist_sync")
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))
        return _drive(kv)

    for a, b in zip(run(True), run(False)):
        assert onp.array_equal(a, b)


def test_forced_pipeline_dist_async_gather_bit_identical(monkeypatch):
    def run(force):
        monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1" if force else "0")
        monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "" if force else "0")
        kv = mx.kv.create("dist_async")
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        return _drive(kv)

    for a, b in zip(run(True), run(False)):
        assert onp.array_equal(a, b)


def test_bucket_bytes_zero_restores_legacy_exactly(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "0")
    kv = mx.kv.create("dist_sync")
    assert kv._pipeline is None  # the legacy path, not an idle pipeline


def test_pipeline_fuses_fewer_collectives_than_keys(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")
    monkeypatch.delenv("MXNET_TPU_BUCKET_BYTES", raising=False)
    kv = mx.kv.create("dist_sync")
    _drive(kv)
    st = kv._pipeline.stats
    assert st["keys"] == 3 * len(SHAPES)
    assert 0 < st["fused"] < st["keys"]  # the fusion win
    assert st["resolved"] == st["fused"]
    assert kv._pipeline.pending() == {"staged": {}, "inflight": 0}
    desc = kv._pipeline.describe()
    assert desc["overlap_ratio"] is not None
    assert buckets.comm_stats()["fused"] >= st["fused"]


def test_repeat_push_before_pull_drains_bucket(monkeypatch):
    """Legacy semantics: two pushes of one key without a pull are two
    reduction rounds whose aggregates both land in pending."""
    monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")

    def run(force):
        monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "" if force else "0")
        kv = mx.kv.create("dist_sync")
        kv.init(0, mx.nd.zeros((4,)))
        kv.push(0, mx.nd.array([1.0, 2.0, 3.0, 4.0]))
        kv.push(0, mx.nd.array([10.0, 20.0, 30.0, 40.0]))
        out = mx.nd.zeros((4,))
        kv.pull(0, out)
        return out.asnumpy()

    a, b = run(True), run(False)
    assert onp.array_equal(a, b)


def test_partial_bucket_dispatches_at_pull(monkeypatch):
    """Keys never pushed this round must not block resolution — the
    partially-filled bucket dispatches (counted as partial) at the
    flush point."""
    monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")
    monkeypatch.delenv("MXNET_TPU_BUCKET_BYTES", raising=False)
    kv = mx.kv.create("dist_sync")
    for i, s in enumerate(SHAPES):
        kv.init(i, mx.nd.zeros(s))
    kv.push(1, mx.nd.array(onp.ones(SHAPES[1], onp.float32)))
    out = mx.nd.zeros(SHAPES[1])
    kv.pull(1, out)
    assert onp.array_equal(out.asnumpy(), onp.ones(SHAPES[1]))
    assert kv._pipeline.stats["partial"] == 1


def test_fingerprint_deterministic_across_identical_programs(monkeypatch):
    """The pass-2 collective fingerprint is a pure function of the
    (registration, push) sequence at every bucket size — what makes the
    cross-rank check valid under bucketing."""
    for cap in ("1", "48", "4096", str(1 << 22)):
        monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")
        monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", cap)

        def run():
            kv = mx.kv.create("dist_sync")
            if kv._sched is None:
                pytest.skip("distcheck disabled in this environment")
            _drive(kv)
            return kv._sched.fingerprint()

        assert run() == run(), cap


def test_sync_phase_and_overlap_land_in_step_report(monkeypatch):
    """The pipeline's blocked resolve tail is 'sync' time in the PR 9
    step timeline, and the scrape exports the overlap gauge."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import steps as tsteps

    monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")
    monkeypatch.delenv("MXNET_TPU_BUCKET_BYTES", raising=False)
    kv = mx.kv.create("dist_sync")
    for i, s in enumerate(SHAPES):
        kv.init(i, mx.nd.zeros(s))
    tsteps.begin_step(1)
    for i in reversed(range(len(SHAPES))):
        kv.push(i, mx.nd.array(onp.ones(SHAPES[i], onp.float32)))
    for i in range(len(SHAPES)):
        kv.pull(i, mx.nd.zeros(SHAPES[i]))
    rec = tsteps.end_step()
    assert rec is not None and rec["phases"]["sync"] >= 0.0
    flat = str(telemetry.metrics_snapshot())
    assert "mxtpu_kvstore_fused_collectives_total" in flat
    assert "mxtpu_kvstore_overlap_ratio" in flat


def test_bucket_lifecycle_spans_committed(monkeypatch):
    from mxnet_tpu.telemetry import trace

    if not trace.enabled():
        pytest.skip("tracing disabled")
    monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")
    monkeypatch.delenv("MXNET_TPU_BUCKET_BYTES", raising=False)
    before = trace.counts().get("bucket", 0)
    _drive(mx.kv.create("dist_sync"), steps=1)
    assert trace.counts().get("bucket", 0) > before
    spans = [s for s in trace.tail() if s["kind"] == "bucket"]
    assert spans
    tid = spans[-1]["trace"]
    phases = {s["name"] for s in trace.tail()
              if s["trace"] == tid and s["kind"] == "phase"}
    assert {"enqueue", "fuse", "dispatch", "resolve"} <= phases


def test_peer_lost_mid_bucket_carries_census(monkeypatch, tmp_path):
    """An injected kvstore.sync hang while a fused bucket resolves must
    surface PeerLostError with the bucket census attached (the chaos
    phase-11 contract, in-process)."""
    import time

    from mxnet_tpu import faults, watchdog
    from mxnet_tpu.kvstore import PeerLostError

    monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1")
    monkeypatch.delenv("MXNET_TPU_BUCKET_BYTES", raising=False)
    kv = mx.kv.create("dist_sync")
    for i, s in enumerate(SHAPES):
        kv.init(i, mx.nd.zeros(s))
    watchdog.configure({"kvstore.sync": 0.5}, crash_dir=str(tmp_path),
                       interval=0.1)
    faults.configure("kvstore.sync:hang@1:1.5")
    try:
        for i in reversed(range(len(SHAPES))):
            kv.push(i, mx.nd.array(onp.ones(SHAPES[i], onp.float32)))
        with pytest.raises(PeerLostError) as ei:
            kv.pull(0, mx.nd.zeros(SHAPES[0]))
        err = ei.value
        assert err.op == "bucket_reduce"
        assert err.census and err.census["plan"]["buckets"]
        assert "bucket census" in str(err)
    finally:
        faults.reset()
        watchdog.configure(None)
        time.sleep(1.6)  # let the abandoned waiter drain


# ------------------------------------------------------------ trainer side --

def test_trainer_grad_scatter_lever_and_token(monkeypatch):
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    def build():
        mx.random.seed(0)
        net = nn.Dense(4, in_units=8)
        net.initialize(mx.init.Xavier())
        net(mx.nd.zeros((2, 8)))
        return ShardedTrainer(net, gloss.L2Loss(), "sgd",
                              {"learning_rate": 0.1},
                              mesh=DeviceMesh({"dp": 1}))

    tr = build()
    assert tr._grad_scatter is False  # single host: nothing to scatter
    # the lever is part of the compiled step's identity
    tok_on = tr._service_token("step")
    tr._grad_scatter = True
    assert tr._service_token("step") != tok_on
    tr._grad_scatter = False
    monkeypatch.setenv("MXNET_TPU_GRAD_SCATTER", "0")
    assert build()._grad_scatter is False
    # the dp-sharding helper picks the first divisible unsharded dim
    assert tr._dp_sharded_full((), (4, 4)) == (None, None)  # dp=1: no-op


def test_trainer_aot_lower_compile_clean():
    """aot_lower lowers the full step under GSPMD without executing it
    or consuming the RNG stream; the compiled HLO feeds the distcheck
    collective census (the multichip-dryrun ROADMAP 3a stage)."""
    from mxnet_tpu import random as mxrand
    from mxnet_tpu.analysis import distcheck
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    mx.random.seed(0)
    net = nn.Dense(4, in_units=8)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, 8)))
    tr = ShardedTrainer(net, gloss.L2Loss(), "sgd",
                        {"learning_rate": 0.1},
                        mesh=DeviceMesh({"dp": 1}), zero=True)
    mxrand._ensure()
    key_before = onp.asarray(mxrand._state.key)
    lowered = tr.aot_lower(mx.nd.ones((4, 8)), mx.nd.ones((4, 4)))
    compiled = lowered.compile()
    assert tr._t == 0  # nothing executed
    assert onp.array_equal(onp.asarray(mxrand._state.key), key_before)
    sched = distcheck.schedule_from_hlo(compiled.as_text())
    assert isinstance(sched, list)  # dp=1: typically empty, never raises
    # the lowered step still runs afterwards
    loss = tr.step(mx.nd.ones((4, 8)), mx.nd.ones((4, 4)))
    assert onp.isfinite(float(loss.asscalar()))


def test_latency_hiding_flags(monkeypatch):
    from mxnet_tpu.base import maybe_enable_latency_hiding

    monkeypatch.delenv("XLA_FLAGS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("MXTPU_PLATFORM", raising=False)
    assert maybe_enable_latency_hiding() is False  # cpu: never
    monkeypatch.setenv("MXTPU_PLATFORM", "tpu")
    assert maybe_enable_latency_hiding() is True
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" \
        in os.environ["XLA_FLAGS"]
    # idempotent / user setting wins
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_tpu_enable_latency_hiding_scheduler=false")
    assert maybe_enable_latency_hiding() is True
    assert os.environ["XLA_FLAGS"] == \
        "--xla_tpu_enable_latency_hiding_scheduler=false"
    monkeypatch.setenv("MXNET_TPU_LHS", "0")
    assert maybe_enable_latency_hiding() is False


def test_bench_train_cpu_emits_gradcomms_fields(capsys, monkeypatch):
    import json

    monkeypatch.setenv("BENCH_TRAIN_CPU_BATCH", "8")
    monkeypatch.setenv("BENCH_TRAIN_CPU_ITERS", "2")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    import bench

    bench.bench_train_cpu()
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "sync_ms_mean" in line
    assert "overlap_ratio" in line  # null single-host, present always


def test_diagnose_grad_comms_section(monkeypatch):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    import diagnose

    out = diagnose.check_gradcomms()
    assert out["cap_bytes"] == buckets.bucket_bytes()
    assert "stats" in out and "overlap_ratio" in out["stats"]


# ---------------------------------------------------------- perf guard -----

@pytest.mark.perf
def test_single_host_pipeline_overhead_within_noise(monkeypatch):
    """The forced bucket pipeline must not tax a single-host
    push/pull loop beyond noise vs the legacy path (the ISSUE guard
    that single-host step time is unaffected)."""
    import time

    def loop(force):
        monkeypatch.setenv("MXNET_TPU_BUCKET_FORCE", "1" if force else "0")
        monkeypatch.setenv("MXNET_TPU_BUCKET_BYTES", "" if force else "0")
        kv = mx.kv.create("dist_sync")
        for i, s in enumerate(SHAPES):
            kv.init(i, mx.nd.zeros(s))
        grads = [mx.nd.array(onp.ones(s, onp.float32)) for s in SHAPES]
        outs = [mx.nd.zeros(s) for s in SHAPES]
        _ = [kv.push(i, grads[i]) for i in range(len(SHAPES))]  # warm
        _ = [kv.pull(i, outs[i]) for i in range(len(SHAPES))]
        t0 = time.perf_counter()
        for _ in range(30):
            for i in reversed(range(len(SHAPES))):
                kv.push(i, grads[i])
            for i in range(len(SHAPES)):
                kv.pull(i, outs[i])
        return time.perf_counter() - t0

    bucketed, legacy = loop(True), loop(False)
    # generous envelope: CI timing is noisy; catches order-of-magnitude
    # regressions (a sync sneaking into enqueue, per-push concat, ...)
    assert bucketed <= legacy * 2.5 + 0.25, (bucketed, legacy)


# ------------------------------------------------- 2-process acceptance ----

def _run_two(tmp_path, child_src, ok_token, timeout=240):
    """The test_sparse_dist 2-process harness, returning both ranks'
    stdout for parent-side cross-rank assertions."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "overlap_child.py"
    script.write_text(child_src)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TPU_BUCKET_BYTES", None)
    env.pop("MXNET_TPU_BUCKET_FORCE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), port, str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.getcwd()) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("distributed runtime hung in this environment")
    if any(p.returncode != 0 for p in procs):
        joined = "\n".join(outs)
        if "DISTRIBUTED" in joined.upper() or "initialize" in joined:
            pytest.skip(f"jax.distributed unavailable: {joined[-300:]}")
        raise AssertionError(joined[-2000:])
    assert all(ok_token in o for o in outs), outs
    return outs


_OVERLAP_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.kvstore import buckets
    from mxnet_tpu.telemetry import steps

    SHAPES = [(64, 64)] * 24   # 16KB each; one ~128KB bucket holds 8
    STEPS = 4

    def run(bucket_bytes):
        os.environ["MXNET_TPU_BUCKET_BYTES"] = str(bucket_bytes)
        kv = mx.kv.create("dist_sync")
        assert kv.num_workers == 2
        for i, s in enumerate(SHAPES):
            kv.init(i, mx.nd.zeros(s))
        sync_ms, outs = [], None
        for step in range(STEPS + 1):   # round 0 warms compile caches
            steps.begin_step(step + 1)
            for i in reversed(range(len(SHAPES))):
                g = mx.nd.array(np.full(
                    SHAPES[i], (kv.rank + 1) * 0.01 * (i + 1 + step),
                    np.float32))
                kv.push(i, g, priority=-i)
            outs = [mx.nd.zeros(s) for s in SHAPES]
            for i in range(len(SHAPES)):
                kv.pull(i, outs[i], priority=-i)
            rec = steps.end_step()
            if step > 0:
                sync_ms.append(rec["phases"]["sync"])
        kv.barrier()   # includes the cross-rank fingerprint check
        fp = kv._sched.fingerprint() if kv._sched is not None else "off"
        vals = np.concatenate([o.asnumpy().ravel() for o in outs])
        return vals, sum(sync_ms) / len(sync_ms), fp

    legacy_vals, legacy_sync, legacy_fp = run(0)
    bucket_vals, bucket_sync, bucket_fp = run(128 * 1024)
    cs = buckets.comm_stats()
    assert np.array_equal(legacy_vals, bucket_vals), "numerics diverged"
    assert 0 < cs["fused"] < cs["keys"], cs
    assert cs["overlap_ratio"] is not None and cs["overlap_ratio"] > 0.0, cs
    assert bucket_sync < legacy_sync, (bucket_sync, legacy_sync)
    print("OVERLAP_OK", pid, "FP=" + bucket_fp, "LFP=" + legacy_fp,
          "legacy_sync=%.3f" % legacy_sync,
          "bucket_sync=%.3f" % bucket_sync,
          "overlap=" + str(cs["overlap_ratio"]),
          "fused=%d/%d" % (cs["fused"], cs["keys"]))
""")


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_bucketed_overlap_ab_drill(tmp_path):
    """The acceptance drill: 2-process CPU A/B — bucketed vs
    MXNET_TPU_BUCKET_BYTES=0 legacy. Bit-identical pulls, fused
    collective count < per-key count, step_report sync mean strictly
    lower with overlap_ratio > 0, and rank-identical collective
    fingerprints."""
    outs = _run_two(tmp_path, _OVERLAP_CHILD, "OVERLAP_OK")
    fps = set()
    for out in outs:
        line = [ln for ln in out.splitlines() if "OVERLAP_OK" in ln][-1]
        fps.add([t for t in line.split() if t.startswith("FP=")][0])
    assert len(fps) == 1, f"fingerprints diverged across ranks: {outs}"


_COMPRESSED_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address="localhost:" + port,
                               num_processes=2, process_id=pid)
    import numpy as np
    import mxnet_tpu as mx

    SHAPES = [(8, 8), (32,), (4, 4)]

    def run(bucket_bytes):
        os.environ["MXNET_TPU_BUCKET_BYTES"] = str(bucket_bytes)
        kv = mx.kv.create("dist_sync")
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        for i, s in enumerate(SHAPES):
            kv.init(i, mx.nd.zeros(s))
        rs = np.random.RandomState(7)
        for step in range(3):
            for i in reversed(range(len(SHAPES))):
                g = mx.nd.array((rs.rand(*SHAPES[i]) - 0.4).astype(
                    np.float32) * (kv.rank + 1))
                kv.push(i, g)
            outs = [mx.nd.zeros(s) for s in SHAPES]
            for i in range(len(SHAPES)):
                kv.pull(i, outs[i])
        kv.barrier()
        res = {k: np.asarray(v) for k, v in kv._residuals.items()}
        return np.concatenate([o.asnumpy().ravel() for o in outs]), res

    lv, lres = run(0)
    bv, bres = run(1 << 20)
    assert np.array_equal(lv, bv), "compressed numerics diverged"
    for k in lres:
        assert np.array_equal(lres[k], bres[k]), "residuals diverged"
    print("COMPRESS_OK", pid)
""")


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("SKIP_DIST_TESTS") == "1",
                    reason="distributed tests disabled")
@_needs_multiprocess_cpu
def test_two_process_compressed_bucket_fusion(tmp_path):
    """2-bit payloads fused through buckets stay bit-identical to the
    legacy per-key compressed path, error-feedback residuals included."""
    _run_two(tmp_path, _COMPRESSED_CHILD, "COMPRESS_OK")
