"""Distributed-correctness analyzer (analysis.distcheck) tests.

The planted-misconfiguration matrix: every supported parallelism config
(dp x tp, ZeRO, pipeline pp, MoE ep) passes clean, and one planted bug per
pass — bad axis name, divergent collective order, use-after-donate,
churning compile-cache key — is caught with a structured, node/param-named
Issue. Plus the knob (MXNET_TPU_DISTCHECK=0) and the mesh-naming
did-you-mean satellites.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import distcheck
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer


@pytest.fixture(autouse=True)
def _clean_distcheck():
    distcheck.clear_donated()
    distcheck.reset_cache_stats()
    yield
    distcheck.clear_donated()
    distcheck.reset_cache_stats()


def _make_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(4, in_units=32))
    net.initialize(mx.init.Xavier())
    return net


def _first_param(net):
    return next(iter(net.collect_params()))


def _batch(b=8):
    rng = np.random.default_rng(0)
    return (mx.nd.array(rng.normal(size=(b, 16)).astype(np.float32)),
            mx.nd.array(rng.normal(size=(b, 4)).astype(np.float32)))


# ===================================================================== #
# clean-config matrix: every parallelism flavour passes                 #
# ===================================================================== #

def test_clean_dp_tp_trainer_steps():
    """dp x tp with default rules: the auto-run passes and training
    proceeds (distcheck must not break a correct config)."""
    st = ShardedTrainer(_make_net(), gloss.L2Loss(), "sgd",
                        {"learning_rate": 0.05},
                        mesh=DeviceMesh({"dp": 4, "tp": 2}))
    x, y = _batch()
    losses = [float(st.step(x, y).asscalar()) for _ in range(3)]
    assert np.isfinite(losses).all()
    assert distcheck.check_trainer(st) == []  # warnings included


def test_clean_zero_trainer():
    """ZeRO-1: dp-sharded optimizer-state layouts verify clean."""
    st = ShardedTrainer(_make_net(), gloss.L2Loss(), "adam",
                        {"learning_rate": 0.01},
                        mesh=DeviceMesh({"dp": 8}), zero=True)
    x, y = _batch()
    st.step(x, y)
    assert distcheck.check_trainer(st) == []


def test_clean_pipeline_config():
    """GPipe pp config: stacked stage params sharded on pp verify clean."""
    mesh = DeviceMesh({"pp": 4})
    issues = distcheck.check_sharding(
        rules={"stages_weight": ("pp", None, None),
               "stages_bias": ("pp", None)},
        shapes={"stages_weight": (4, 16, 16), "stages_bias": (4, 16)},
        mesh=mesh)
    assert issues == []


def test_clean_moe_ep_config():
    """MoE EP config: stacked expert params sharded on ep verify clean,
    router replicated."""
    mesh = DeviceMesh({"dp": 2, "ep": 4})
    issues = distcheck.check_sharding(
        rules={"experts_w": ("ep", None, None), "router_w": ()},
        shapes={"experts_w": (4, 8, 8), "router_w": (8, 4)},
        mesh=mesh, batch_shape=(16, 8))
    assert issues == []


# ===================================================================== #
# pass 1 — sharding verifier: planted bad axis                          #
# ===================================================================== #

def test_planted_bad_axis_refused_before_compile():
    """A rule naming a nonexistent mesh axis is refused at trainer
    CONSTRUCTION (before placement/compile), param-named, with a
    did-you-mean hint and the valid axis list."""
    net = _make_net()
    pname = _first_param(net)
    with pytest.raises(distcheck.DistCheckError) as ei:
        ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                       mesh=DeviceMesh({"dp": 4, "tp": 2}),
                       rules={pname: ("tpp", None)})
    issues = [i for i in ei.value.issues if i.code == "undefined-axis"]
    assert issues and issues[0].node == pname
    msg = issues[0].message
    assert "did you mean 'tp'" in msg
    assert "valid axes" in msg and "'dp'" in msg


def test_planted_duplicate_axis_and_spec_rank():
    net = _make_net()
    pname = _first_param(net)
    with pytest.raises(distcheck.DistCheckError) as ei:
        ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                       mesh=DeviceMesh({"dp": 4, "tp": 2}),
                       rules={pname: ("tp", "tp")})
    assert any(i.code == "duplicate-axis" and i.node == pname
               for i in ei.value.issues)
    issues = distcheck.check_sharding(
        rules={"w": ("tp", None, None)}, shapes={"w": (32, 16)},
        mesh=DeviceMesh({"dp": 4, "tp": 2}))
    assert [i.code for i in issues] == ["spec-rank"]
    assert issues[0].node == "w"


def test_planted_indivisible_dim():
    issues = distcheck.check_sharding(
        rules={"w": ("tp", None)}, shapes={"w": (33, 16)},
        mesh=DeviceMesh({"dp": 2, "tp": 2}))
    assert [i.code for i in issues] == ["indivisible-dim"]
    assert "33" in issues[0].message and issues[0].node == "w"


def test_batch_indivisible_refused_before_compile():
    st = ShardedTrainer(_make_net(), gloss.L2Loss(), "sgd", {},
                        mesh=DeviceMesh({"dp": 8}))
    with pytest.raises(distcheck.DistCheckError) as ei:
        st.step(mx.nd.ones((12, 16)), mx.nd.ones((12, 4)))
    assert any(i.code == "batch-indivisible" for i in ei.value.issues)
    # the step executable was never built — refused before compile
    assert st._step_fn is None


def test_unknown_param_rule_warns_with_suggestion():
    net = _make_net()
    pname = _first_param(net)
    with pytest.warns(distcheck.DistCheckWarning, match="no known param"):
        ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                       mesh=DeviceMesh({"dp": 8}),
                       rules={pname + "x": ()})


def test_replicated_large_param_warning():
    issues = distcheck.check_sharding(
        rules={"embed": ()}, shapes={"embed": (2048, 1024)},
        mesh=DeviceMesh({"dp": 4, "tp": 2}), large_param_elems=1 << 20)
    assert [i.code for i in issues] == ["replicated-large-param"]
    assert not issues[0].is_error  # advisory, not fatal
    # pure-dp meshes replicate by design: no warning there
    assert distcheck.check_sharding(
        rules={"embed": ()}, shapes={"embed": (2048, 1024)},
        mesh=DeviceMesh({"dp": 8}), large_param_elems=1 << 20) == []


def test_distcheck_env_opt_out(monkeypatch):
    """MXNET_TPU_DISTCHECK=0: the planted bad axis silently replicates
    (the documented lenient mesh.sharding behaviour) instead of raising."""
    monkeypatch.setenv("MXNET_TPU_DISTCHECK", "0")
    net = _make_net()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                        mesh=DeviceMesh({"dp": 4, "tp": 2}),
                        rules={_first_param(net): ("tpp", None)})
    x, y = _batch()
    st.step(x, y)  # no distcheck error, no donation poisoning
    assert distcheck.donated_count() == 0


# ===================================================================== #
# pass 2 — collective-order deadlock detector                           #
# ===================================================================== #

def test_static_collective_schedule_extraction():
    """The dp-gradient shape (sharded in, replicated out) compiles to an
    all-reduce; a pointwise sharded map compiles to none."""
    import jax
    import jax.numpy as jnp

    mesh = DeviceMesh({"dp": 8})
    av = jax.ShapeDtypeStruct((8, 4), "float32")
    reduced = distcheck.collective_schedule(
        lambda x: jnp.sum(x), av,
        in_shardings=(mesh.sharding("dp"),),
        out_shardings=mesh.replicated())
    assert reduced and reduced[0][0] == "all-reduce"
    pointwise = distcheck.collective_schedule(
        lambda x: x * 2, av,
        in_shardings=(mesh.sharding("dp"),),
        out_shardings=mesh.sharding("dp"))
    assert pointwise == []
    assert distcheck.schedule_fingerprint(reduced) \
        != distcheck.schedule_fingerprint(pointwise)


def test_planted_divergent_schedule_names_position():
    """Two ranks whose static schedules diverge at position 1 get a
    collective-order error naming exactly that position."""
    a = [("all-reduce", "f32[8,4]", "[1,8]"), ("all-gather", "f32[4]", "[1,8]")]
    b = [("all-reduce", "f32[8,4]", "[1,8]"), ("all-reduce", "f32[4]", "[1,8]")]
    issues = distcheck.compare_schedules({0: a, 1: b})
    assert len(issues) == 1 and issues[0].code == "collective-order"
    assert issues[0].node == "collective #1"
    assert "rank 1" in issues[0].message
    # identical schedules: clean
    assert distcheck.compare_schedules({0: a, 1: list(a)}) == []


def test_cross_check_schedule_raises_on_divergence():
    """The barrier-time fingerprint cross-check: rank-divergent recorded
    schedules raise CollectiveOrderError naming both fingerprints."""
    r0 = distcheck.ScheduleRecorder()
    r1 = distcheck.ScheduleRecorder()
    r0.note("allreduce", "w0:(4, 4):float32")
    r0.note("allreduce", "w1:(2,):float32")
    r1.note("allreduce", "w1:(2,):float32")   # reversed push order:
    r1.note("allreduce", "w0:(4, 4):float32")  # the classic deadlock
    with pytest.raises(distcheck.CollectiveOrderError) as ei:
        distcheck.cross_check_schedule(
            r0, allgather=lambda w: [w, r1.digest_words()])
    assert "rank 0" in str(ei.value) and "rank 1" in str(ei.value)
    assert ei.value.tail  # recent schedule entries for the post-mortem
    # identical schedules pass
    distcheck.cross_check_schedule(r0, allgather=lambda w: [w, w])


def test_kvstore_records_collective_schedule():
    """The dist kvstore feeds the recorder: push + barrier land in the
    schedule with their keys, and the single-worker barrier stays clean."""
    kv = mx.kv.create("dist_sync")
    if kv._sched is None:
        pytest.skip("distcheck disabled in this environment")
    v = mx.nd.ones((4, 4))
    kv.init("w0", v)
    kv.push("w0", v)
    kv.barrier()
    ops = [op for op, _ in kv._sched.tail]
    assert "allreduce" in ops and "barrier" in ops
    assert any("w0" in d for _, d in kv._sched.tail)
    fp = kv._sched.fingerprint()
    assert fp.startswith(str(kv._sched.count) + ":")


# ===================================================================== #
# pass 3 — donation-safety checker                                      #
# ===================================================================== #

def test_planted_use_after_donate_eager():
    """A stale alias of a donated parameter buffer raises a param-named
    DonatedBufferError at the eager use site."""
    net = _make_net()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd",
                        {"learning_rate": 0.05}, mesh=DeviceMesh({"dp": 8}))
    pname = _first_param(net)
    stale = mx.nd.NDArray(net.collect_params()[pname].data()._data)
    x, y = _batch()
    st.step(x, y)
    assert distcheck.donated_count() >= 1
    with pytest.raises(distcheck.DonatedBufferError) as ei:
        stale * 2
    e = ei.value
    assert e.name == pname and "use-after-donate" in str(e)
    assert "ShardedTrainer.step" in str(e) and "step 1" in str(e)


def test_planted_use_after_donate_in_bulk_segment():
    """The bulking recorder flags use-after-donate at RECORD (trace)
    time, before the stale buffer is wired into a fused segment."""
    from mxnet_tpu import engine

    net = _make_net()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                        mesh=DeviceMesh({"dp": 8}))
    pname = _first_param(net)
    stale = mx.nd.NDArray(net.collect_params()[pname].data()._data)
    x, y = _batch()
    st.step(x, y)
    with engine.bulk(16):
        with pytest.raises(distcheck.DonatedBufferError, match=pname):
            stale + 1


def test_poisoned_lazyref_force_raises():
    """mark_donated poisons a pending LazyRef: forcing it raises the
    named error instead of executing the segment."""
    from mxnet_tpu import engine

    with engine.bulk(16):
        lazy = mx.nd.ones((2, 2)) * 3
        distcheck.mark_donated(lazy, "lazy_param", "test harness", step=7)
        with pytest.raises(distcheck.DonatedBufferError) as ei:
            lazy.asnumpy()
    assert ei.value.name == "lazy_param" and ei.value.step == 7


def test_donation_registry_prunes_with_aliases():
    """Dropped aliases release their registry entries (weakref-pruned):
    poisoning never leaks across steps."""
    net = _make_net()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                        mesh=DeviceMesh({"dp": 8}))
    x, y = _batch()
    for _ in range(4):
        st.step(x, y)
    import gc

    gc.collect()
    assert distcheck.donated_count() == 0  # no live aliases -> no entries


def test_donate_false_tracks_nothing():
    net = _make_net()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                        mesh=DeviceMesh({"dp": 8}), donate=False)
    pname = _first_param(net)
    stale = mx.nd.NDArray(net.collect_params()[pname].data()._data)
    x, y = _batch()
    st.step(x, y)
    np.testing.assert_allclose(stale.asnumpy(), stale.asnumpy())
    assert distcheck.donated_count() == 0


# ===================================================================== #
# pass 4 — recompile-churn detector                                     #
# ===================================================================== #

def test_planted_churning_key_flagged():
    """A CachedOp fed a fresh shape every call compiles every call: the
    churn detector names the site and the drifting key component."""
    from mxnet_tpu.cached_op import CachedOp

    def body(a):
        return a * 2

    co = CachedOp(body)
    for n in range(2, 8):
        co(mx.nd.ones((n, 3)))
    issues = distcheck.check_churn()
    churn = [i for i in issues
             if i.code == "cache-churn" and "CachedOp[" in i.node
             and "body]" in i.node]
    assert churn, issues
    assert "drifting key component" in churn[0].message
    assert not churn[0].is_error  # perf hazard, not fatal
    # ... and the same op at a STABLE shape is not flagged
    distcheck.reset_cache_stats()
    co2 = CachedOp(body)
    for _ in range(8):
        co2(mx.nd.ones((4, 3)))
    assert not [i for i in distcheck.check_churn() if "body]" in i.node]


def test_dispatch_cache_stats_and_counters():
    """Registry jit-cache lookups land in cache_stats, and a recording
    profiler session receives compile_cache counter tracks."""
    from mxnet_tpu import profiler

    distcheck.reset_cache_stats()
    x = mx.nd.ones((3, 3))
    profiler.set_state("run")
    try:
        for p in (1.5, 2.5, 3.5):  # distinct static kwargs: misses
            mx.nd.clip(x, 0.0, p).wait_to_read()
    finally:
        profiler.set_state("stop")
    stats = distcheck.cache_stats()
    site = [(k, v) for k, v in stats.items() if k[0] == "dispatch"]
    assert site, stats
    total = sum(v["hits"] + v["misses"] for _, v in site)
    assert total >= 3
    with profiler._lock:
        cache_events = [e for e in profiler._events
                        if e["name"].startswith("compile_cache.")]
    assert cache_events
    profiler.reset()


def test_cache_tracking_toggle():
    distcheck.track_caches(False)
    try:
        distcheck.reset_cache_stats()
        (mx.nd.ones((2, 2)) * 7).wait_to_read()
        assert distcheck.cache_stats() == {}
    finally:
        distcheck.track_caches(True)


def test_run_entry_point_is_callable_module():
    """analysis.distcheck(...) — the documented orchestrator surface."""
    from mxnet_tpu import analysis

    mesh = DeviceMesh({"dp": 4, "tp": 2})
    with pytest.raises(distcheck.DistCheckError):
        analysis.distcheck(rules={"w": ("nope",)}, shapes={"w": (4, 4)},
                           mesh=mesh)
    issues = analysis.distcheck(rules={"w": ("tp", None)},
                                shapes={"w": (4, 4)}, mesh=mesh,
                                raise_on_error=False)
    assert issues == []


# ===================================================================== #
# mesh-naming satellites                                                #
# ===================================================================== #

def test_mesh_constructor_validates_axis_sizes():
    with pytest.raises(ValueError, match="positive integer"):
        DeviceMesh({"dp": 0})
    with pytest.raises(ValueError, match="positive integer"):
        DeviceMesh({"dp": 2.5})
    with pytest.raises(ValueError, match="non-empty strings"):
        DeviceMesh({None: 2})


def test_mesh_axis_error_suggests():
    mesh = DeviceMesh({"dp": 4, "tp": 2})
    msg = mesh.axis_error("tpp")
    assert "did you mean 'tp'" in msg
    assert "valid axes: ['dp', 'tp']" in msg


def test_resume_reshard_disabled_error_lists_axes(tmp_path):
    """The preempt reshard path: a reshard-disabled topology mismatch
    names the missing axis with a did-you-mean hint + the valid axes."""
    from mxnet_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), prefix="dc", keep=2)
    net = _make_net()
    st = ShardedTrainer(net, gloss.L2Loss(), "sgd", {},
                        mesh=DeviceMesh({"dp": 4, "tp": 2}))
    x, y = _batch()
    st.step(x, y)
    st.save_checkpoint(mgr, 1)
    net2 = _make_net()
    st2 = ShardedTrainer(net2, gloss.L2Loss(), "sgd", {},
                         mesh=DeviceMesh({"dp": 8}))
    with pytest.raises(ValueError) as ei:
        st2.resume(mgr, reshard=False)
    msg = str(ei.value)
    assert "saved axis 'tp' is not an axis of this mesh" in msg
    assert "valid axes: ['dp']" in msg
