"""Fault tolerance: atomic checkpointing, fault injection, kill-and-resume.

The headline contract (ISSUE 2): a training run killed mid-epoch — by an
injected fault or a real SIGKILL — resumes from the CheckpointManager
manifest and reaches BIT-EXACT final parameters versus an uninterrupted
run; a checkpoint truncated on disk is detected by checksum and load falls
back to the previous good epoch.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, faults, gluon
from mxnet_tpu.checkpoint import CheckpointManager, atomic_write, crc32_file
from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends with no armed schedule."""
    faults.reset()
    yield
    faults.reset()


# ------------------------------------------------------------ faults.py ----

def test_retry_decorator_backoff_and_filtering():
    calls = []

    @faults.retry(retries=3, backoff=0.0)
    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return 42

    assert flaky() == 42
    assert len(calls) == 3

    # exhaustion re-raises the last error
    @faults.retry(retries=2, backoff=0.0)
    def always():
        raise OSError("permanent")

    with pytest.raises(OSError, match="permanent"):
        always()

    # non-matching exception types propagate immediately
    attempts = []

    @faults.retry(retries=5, backoff=0.0, retry_on=(OSError,))
    def wrong_type():
        attempts.append(1)
        raise ValueError("not retryable")

    with pytest.raises(ValueError):
        wrong_type()
    assert len(attempts) == 1

    # on_retry observes each failed attempt
    seen = []
    fn = faults.retry(lambda: (_ for _ in ()).throw(OSError("x")),
                      retries=2, backoff=0.0,
                      on_retry=lambda a, e: seen.append(a))
    with pytest.raises(OSError):
        fn()
    assert seen == [1, 2]


def test_fault_schedule_triggers():
    faults.configure("p:raise@2")
    faults.point("p")  # 1st: no fire
    with pytest.raises(faults.InjectedFault):
        faults.point("p")
    faults.point("p")  # 3rd: no fire (single-shot trigger)
    assert faults.stats()["p"] == (3, 1)

    faults.configure("p:raise@2+")
    faults.point("p")
    for _ in range(3):
        with pytest.raises(faults.InjectedFault):
            faults.point("p")

    # list trigger + multiple points in one spec
    faults.configure("a:raise@1,3;b:delay@*:0")
    with pytest.raises(faults.InjectedFault):
        faults.point("a")
    faults.point("a")
    with pytest.raises(faults.InjectedFault):
        faults.point("a")
    faults.point("b")
    assert faults.stats()["b"] == (1, 1)


def test_fault_probabilistic_trigger_is_seeded():
    def fire_pattern(seed):
        faults.configure("p:raise@p0.5", seed=seed)
        pattern = []
        for _ in range(20):
            try:
                faults.point("p")
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        return pattern

    a, b = fire_pattern(3), fire_pattern(3)
    assert a == b, "same seed must replay the same fire pattern"
    assert fire_pattern(4) != a  # and a different seed a different one
    assert sum(a) > 0


def test_fault_env_var_schedule(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FAULTS", "envpt:raise@1")
    # white-box: force the (once-per-process) env read to happen again
    faults._specs.clear()
    faults._counts.clear()
    faults._fired.clear()
    faults._loaded_env = False
    assert faults.active()
    with pytest.raises(faults.InjectedFault):
        faults.point("envpt")


def test_nan_corruption_returns_poisoned_payload():
    faults.configure("p:nan@1")
    x = np.ones((4, 4), np.float32)
    out = faults.point("p", x)
    assert np.isnan(out).any()
    assert not np.isnan(x).any(), "original payload must not be mutated"


# -------------------------------------------------------- checkpoint.py ----

def test_atomic_write_replaces_and_checksums(tmp_path):
    target = tmp_path / "f.bin"
    crc, size = atomic_write(str(target), lambda p: open(p, "wb").write(b"v1"))
    assert target.read_bytes() == b"v1"
    assert size == 2 and crc == crc32_file(str(target))

    # a writer that dies mid-way leaves the OLD content intact
    def bad_writer(p):
        with open(p, "wb") as f:
            f.write(b"torn")
        raise OSError("disk died")

    with pytest.raises(OSError, match="disk died"):
        atomic_write(str(target), bad_writer)
    assert target.read_bytes() == b"v1"
    assert list(tmp_path.iterdir()) == [target], "no tmp litter"


def test_manager_rotation_and_manifest(tmp_path):
    m = CheckpointManager(tmp_path, prefix="ck", keep=2)
    for e in range(1, 5):
        m.save(e, {"params": f"payload-{e}".encode()}, step=e * 10)
    assert m.epochs() == [3, 4]
    assert m.last_good == 4
    assert not (tmp_path / "ck-0001.params").exists()
    assert not (tmp_path / "ck-0002.params").exists()
    # manifest survives a reopen and carries checksums
    m2 = CheckpointManager(tmp_path, prefix="ck", keep=2)
    entry, paths = m2.load()
    assert entry["epoch"] == 4 and entry["step"] == 40
    with open(m2.manifest_path) as f:
        manifest = json.load(f)
    fi = manifest["checkpoints"][-1]["files"]["params"]
    assert fi["crc32"] == crc32_file(paths["params"])


def test_manager_corruption_falls_back_to_previous_good(tmp_path):
    m = CheckpointManager(tmp_path, prefix="ck", keep=5)
    for e in (1, 2, 3):
        m.save(e, {"params": f"payload-{e}".encode()})
    newest = tmp_path / "ck-0003.params"
    newest.write_bytes(b"payload-3"[:4])  # truncated write
    with pytest.warns(UserWarning, match="falling back to epoch 2"):
        entry, paths = m.load()
    assert entry["epoch"] == 2
    assert open(paths["params"], "rb").read() == b"payload-2"

    # everything corrupt -> loud failure, never a silent fresh start
    (tmp_path / "ck-0002.params").write_bytes(b"x")
    (tmp_path / "ck-0001.params").unlink()
    with pytest.raises(ValueError, match="failed checksum"):
        m.load()


def test_manager_tolerates_torn_manifest(tmp_path):
    m = CheckpointManager(tmp_path, prefix="ck")
    m.save(1, {"params": b"p"})
    (tmp_path / "MANIFEST.json").write_text('{"checkpoints": [{"ep')
    with pytest.warns(UserWarning, match="corrupt checkpoint manifest"):
        m2 = CheckpointManager(tmp_path, prefix="ck")
    assert m2.resume() is None  # fresh manifest: nothing vouched for


def test_ckpt_write_fault_leaves_previous_checkpoint(tmp_path):
    m = CheckpointManager(tmp_path, prefix="ck", keep=5)
    m.save(1, {"params": b"good"})
    faults.configure("ckpt.write:raise@1")
    with pytest.raises(faults.InjectedFault):
        m.save(2, {"params": b"never-lands"})
    faults.reset()
    entry, paths = m.load()
    assert entry["epoch"] == 1
    assert open(paths["params"], "rb").read() == b"good"


# ------------------------------------------------------- clear messages ----

def test_load_params_clear_errors(tmp_path):
    from mxnet_tpu import model

    missing = tmp_path / "nope.params"
    with pytest.raises(FileNotFoundError, match=str(missing)):
        model.load_params(str(missing))

    garbage = tmp_path / "bad.params"
    garbage.write_bytes(b"this is not an npz container")
    with pytest.raises(ValueError, match="corrupt params file"):
        model.load_params(str(garbage))

    with pytest.raises(FileNotFoundError, match="symbol file not found"):
        model.load_checkpoint(str(tmp_path / "prefix"), 3)

    (tmp_path / "prefix-symbol.json").write_text("{not json!")
    with pytest.raises(ValueError, match="corrupt symbol file"):
        model.load_checkpoint(str(tmp_path / "prefix"), 3)


def test_trainer_state_clear_errors(tmp_path):
    net, tr = _make_trainer()
    with pytest.raises(FileNotFoundError, match="nope.npz"):
        tr.load_states(str(tmp_path / "nope.npz"))
    bad = tmp_path / "bad.npz"
    bad.write_bytes(b"garbage")
    with pytest.raises(ValueError, match="corrupt trainer state"):
        tr.load_states(str(bad))


# -------------------------------------------------------- trainer guard ----

def _batch(epoch, step):
    rs = np.random.RandomState(1000 * epoch + step)
    x = rs.randn(8, 6).astype(np.float32)
    y = (x @ rs.randn(6, 4) * 0.5).astype(np.float32)
    return mx.nd.array(x), mx.nd.array(y)


def _make_trainer(seed=7, **kw):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(_batch(1, 0)[0])
    kw.setdefault("mesh", DeviceMesh({"dp": 8}))
    return net, ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                               {"learning_rate": 0.05}, **kw)


def _params_of(net):
    return {k: p.data().asnumpy().copy()
            for k, p in net.collect_params().items()}


def test_nan_guard_skips_bad_step_and_recovers():
    net, tr = _make_trainer(max_consecutive_skips=3)
    x, y = _batch(1, 0)
    tr.step(x, y)
    before = _params_of(net)
    opt_before = [[np.asarray(s) for s in per] for per in tr._opt_raws]

    faults.configure("trainer.step:nan@1")  # poison ONE batch
    loss = tr.step(x, y)
    assert not np.isfinite(loss.asscalar())
    assert tr.skipped_steps == 1 and tr.consecutive_skips == 1
    after = _params_of(net)
    for k in before:
        np.testing.assert_array_equal(before[k], after[k]), k
    for pb, pa in zip(opt_before, tr._opt_raws):
        for sb, sa in zip(pb, pa):
            np.testing.assert_array_equal(sb, np.asarray(sa))

    faults.reset()
    tr.step(x, y)  # clean step: streak resets, training continues
    assert tr.consecutive_skips == 0
    assert any(not np.array_equal(before[k], v)
               for k, v in _params_of(net).items())


def test_nan_guard_raises_after_consecutive_skips():
    net, tr = _make_trainer(max_consecutive_skips=3)
    x, y = _batch(1, 0)
    tr.step(x, y)
    faults.configure("trainer.step:nan@1+")  # every batch poisoned
    tr.step(x, y)
    tr.step(x, y)
    with pytest.raises(RuntimeError, match="consecutive steps produced "
                                           "non-finite"):
        tr.step(x, y)
    assert tr.skipped_steps == 3


def test_nan_guard_off_lets_nans_through():
    net, tr = _make_trainer(nan_guard=False)
    x, y = _batch(1, 0)
    faults.configure("trainer.step:nan@1")
    tr.step(x, y)
    assert tr.skipped_steps == 0
    assert any(np.isnan(v).any() for v in _params_of(net).values())


# ---------------------------------------------------- kill-and-resume ------

def _train(trainer, manager, epochs, steps, start_epoch=0):
    for epoch in range(start_epoch + 1, epochs + 1):
        for step in range(steps):
            x, y = _batch(epoch, step)
            trainer.step(x, y)
        trainer.save_checkpoint(manager, epoch)


def test_injected_fault_kill_and_resume_bit_exact(tmp_path):
    epochs, steps = 3, 4

    # ---- uninterrupted reference trajectory
    net_a, tr_a = _make_trainer()
    mgr_a = CheckpointManager(tmp_path / "a", prefix="ft")
    _train(tr_a, mgr_a, epochs, steps)
    ref = _params_of(net_a)

    # ---- interrupted: an injected fault kills epoch 3 mid-flight
    net_b, tr_b = _make_trainer()
    mgr_b = CheckpointManager(tmp_path / "b", prefix="ft")
    faults.configure("trainer.step:raise@11")  # step 3 of epoch 3
    with pytest.raises(faults.InjectedFault):
        _train(tr_b, mgr_b, epochs, steps)
    faults.reset()
    assert mgr_b.last_good == 2  # epochs 1-2 checkpointed before the kill

    # ---- "restart the job": fresh process state, resume from manifest
    net_c, tr_c = _make_trainer(seed=999)  # different init — must not matter
    entry = tr_c.resume(mgr_b)
    assert entry["epoch"] == 2 and entry["step"] == 2 * steps
    _train(tr_c, mgr_b, epochs, steps, start_epoch=entry["epoch"])

    got = _params_of(net_c)
    # gluon auto-prefixes differ between instances: compare positionally
    # (collect_params order is structural)
    assert len(ref) == len(got)
    for (ka, va), (kb, vb) in zip(ref.items(), got.items()):
        np.testing.assert_array_equal(va, vb, err_msg=f"{ka} vs {kb}")


def test_resume_falls_back_past_truncated_states_file(tmp_path):
    epochs, steps = 3, 2
    net, tr = _make_trainer()
    mgr = CheckpointManager(tmp_path, prefix="ft")
    _train(tr, mgr, epochs, steps)

    # truncate the newest states file — simulates dying mid-write on a
    # filesystem without atomic rename (or a torn copy)
    newest = tmp_path / "ft-0003.states"
    newest.write_bytes(newest.read_bytes()[:128])

    net2, tr2 = _make_trainer(seed=999)
    with pytest.warns(UserWarning, match="falling back to epoch 2"):
        entry = tr2.resume(mgr)
    assert entry["epoch"] == 2
    assert tr2._t == 2 * steps


@pytest.mark.skipif(not hasattr(os, "kill"), reason="needs POSIX kill")
def test_sigkill_subprocess_kill_and_resume_bit_exact(tmp_path):
    """The real thing: a child process is SIGKILLed mid-epoch (fault mode
    'kill' — no cleanup, no atexit, exactly a preemption), restarted with
    resume, and must land on bit-exact params vs an uninterrupted child."""
    env_base = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
                "FT_EPOCHS": "3", "FT_STEPS": "4"}
    child = os.path.join(REPO, "tests", "_ft_child.py")

    def run(ckpt_dir, out, extra):
        env = {**env_base, "FT_CKPT_DIR": str(ckpt_dir),
               "FT_OUT": str(out), **extra}
        env.pop("MXNET_TPU_FAULTS", None)
        env.update({k: v for k, v in extra.items()})
        return subprocess.run([sys.executable, child], env=env,
                              capture_output=True, text=True, timeout=240)

    # uninterrupted reference
    ref_out = tmp_path / "ref.npz"
    proc = run(tmp_path / "ref", ref_out, {})
    assert proc.returncode == 0, proc.stderr

    # killed mid-epoch-3 (step 11 of 12): SIGKILL, no exit handlers
    kill_dir = tmp_path / "kill"
    proc = run(kill_dir, tmp_path / "never.npz",
               {"MXNET_TPU_FAULTS": "trainer.step:kill@11"})
    assert proc.returncode == -9, (proc.returncode, proc.stderr)
    assert not (tmp_path / "never.npz").exists()
    manifest = json.loads((kill_dir / "MANIFEST.json").read_text())
    assert manifest["last_good"] == 2

    # restart with resume -> completes, bit-exact vs reference
    res_out = tmp_path / "resumed.npz"
    proc = run(kill_dir, res_out, {"FT_RESUME": "1"})
    assert proc.returncode == 0, proc.stderr
    ref = dict(np.load(ref_out))
    got = dict(np.load(res_out))
    assert ref.keys() == got.keys()
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k]), k


# ------------------------------------------------- estimator integration ---

def test_checkpoint_handler_rotation_and_resume(tmp_path):
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                                   Estimator)

    def toy_net():
        mx.random.seed(3)
        net = gluon.nn.Dense(3)
        net.initialize(mx.init.Xavier())
        net(mx.nd.zeros((4, 5)))
        return net

    rs = np.random.RandomState(0)
    data = [(mx.nd.array(rs.randn(4, 5).astype(np.float32)),
             mx.nd.array(rs.randint(0, 3, 4).astype(np.float32)))
            for _ in range(2)]

    net = toy_net()
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(), context=mx.cpu(),
                    trainer=Trainer(net.collect_params(), "sgd",
                                    {"learning_rate": 0.05}))
    handler = CheckpointHandler(str(tmp_path), model_prefix="m",
                                max_checkpoints=2)
    est.fit(data, epochs=3, event_handlers=[handler])

    manifest = json.loads((tmp_path / "MANIFEST.json").read_text())
    assert [e["epoch"] for e in manifest["checkpoints"]] == [2, 3]
    assert not (tmp_path / "m-0001.params").exists()

    # fresh estimator resumes the newest good checkpoint at train_begin
    net2 = toy_net()
    est2 = Estimator(net2, gloss.SoftmaxCrossEntropyLoss(),
                     context=mx.cpu(),
                     trainer=Trainer(net2.collect_params(), "sgd",
                                     {"learning_rate": 0.05}))
    resumer = CheckpointHandler(str(tmp_path), model_prefix="m",
                                max_checkpoints=2,
                                resume_from_checkpoint=True)
    resumer.train_begin(est2)
    assert resumer.trained_epochs == 3
    for (_, a), (_, b) in zip(net.collect_params().items(),
                              net2.collect_params().items()):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())

    # a truncated newest checkpoint falls back to the previous epoch
    params3 = tmp_path / "m-0003.params"
    params3.write_bytes(params3.read_bytes()[:64])
    net3 = toy_net()
    est3 = Estimator(net3, gloss.SoftmaxCrossEntropyLoss(),
                     context=mx.cpu(),
                     trainer=Trainer(net3.collect_params(), "sgd",
                                     {"learning_rate": 0.05}))
    resumer3 = CheckpointHandler(str(tmp_path), model_prefix="m",
                                 max_checkpoints=2,
                                 resume_from_checkpoint=True)
    with pytest.warns(UserWarning, match="falling back to epoch 2"):
        resumer3.train_begin(est3)
    assert resumer3.trained_epochs == 2


# ----------------------------------------------------------- io / kvstore --

def test_io_decode_fault_surfaces_at_next(tmp_path):
    """A fault raised inside the prefetch producer thread surfaces at
    next(), not as a hang (the deferred-exception contract for data)."""
    from mxnet_tpu import recordio
    from mxnet_tpu.io import ImageRecordIter

    try:
        from PIL import Image
    except ImportError:
        pytest.skip("PIL unavailable")
    import io as _io

    rec_path = str(tmp_path / "d.rec")
    idx_path = str(tmp_path / "d.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rs = np.random.RandomState(0)
    for i in range(8):
        img = Image.fromarray(rs.randint(0, 255, (10, 10, 3), np.uint8))
        buf = _io.BytesIO()
        img.save(buf, format="JPEG")
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), buf.getvalue()))
    rec.close()

    faults.configure("io.decode:raise@2")
    it = ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                         data_shape=(3, 8, 8), batch_size=4,
                         prefetch_buffer=1, preprocess_threads=1)
    it.next()  # batch 1 decodes fine
    with pytest.raises(faults.InjectedFault):
        it.next()
    it.close()


def test_kvstore_push_fault_injection():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((3,)))
    faults.configure("kvstore.push:raise@2")
    kv.push("w", mx.nd.ones((3,)))
    with pytest.raises(faults.InjectedFault):
        kv.push("w", mx.nd.ones((3,)))
    faults.reset()
    out = mx.nd.zeros((3,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(3))
