#!/usr/bin/env python
"""ImageRecordIter end-to-end throughput benchmark.

Measures the host input pipeline's sustained img/s (RecordIO read ->
native OMP JPEG decode+resize -> augment -> normalize -> batch), the
number that must exceed the chip's training consumption rate for
ResNet-50 (reference bar: iter_image_recordio_2.cc's OMP ParseChunk).

Prints ONE JSON line: {"metric": "image_record_iter", "value": img/s,
"unit": "img/s", ...}.

    python benchmark/iter_bench.py --num-images 512 --batch-size 128

``--augment`` benches the STREAMING DATA PLANE instead: the fused
native decode+rand-crop+mirror+color-jitter loop vs the bit-compatible
pure-Python fallback, reporting img/s, img/s/core, and per-thread
scaling (1 -> N threads of the native loop):

    python benchmark/iter_bench.py --augment

Either mode also drops its result JSON into
``$TMPDIR/mxtpu_iter_bench.json`` so ``tools/diagnose.py`` ("Data
Plane" report) can show the host's last measured numbers.
"""
import argparse
import io as _io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

LAST_RESULT_PATH = os.path.join(tempfile.gettempdir(),
                                "mxtpu_iter_bench.json")


def build_rec(path, num_images, src_hw):
    from PIL import Image

    from mxnet_tpu import recordio

    rs = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(num_images):
        arr = rs.randint(0, 255, (src_hw, src_hw, 3), np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=90)
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.getvalue()))
    rec.close()
    return path + ".rec"


def _persist(result):
    """Best-effort: leave the last result where tools/diagnose.py finds
    it (the "Data Plane" report)."""
    try:
        with open(LAST_RESULT_PATH, "w") as f:
            json.dump(dict(result, time=time.time()), f)
    except OSError:
        pass


def _time_epochs(it, epochs):
    """Sustained img/s over `epochs` full passes (first pass pre-warmed
    by the caller)."""
    n = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        it.reset()
        for batch in it:
            batch.data[0].wait_to_read()
            n += batch.data[0].shape[0]
    return n / (time.perf_counter() - t0)


def run_plain(num_images=512, src_size=256, batch_size=128,
              data_shape=(3, 224, 224), epochs=3, threads=None):
    """The classic decode-only bench; returns the result dict."""
    import mxnet_tpu as mx
    from mxnet_tpu import native

    threads = threads or os.cpu_count() or 4
    with tempfile.TemporaryDirectory() as d:
        rec = build_rec(os.path.join(d, "bench"), num_images, src_size)
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, data_shape=tuple(data_shape),
            batch_size=batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True,
            preprocess_threads=threads)
        # warm epoch (native lib build, file cache)
        for batch in it:
            batch.data[0].wait_to_read()
        rate = _time_epochs(it, epochs)
        return {
            "metric": "image_record_iter",
            "value": round(rate, 1),
            "unit": "img/s",
            "native_decode": native.available(),
            "threads": threads,
            "data_shape": list(data_shape),
        }


def run_augment(num_images=256, src_size=256, batch_size=64,
                data_shape=(3, 224, 224), epochs=2, threads=None,
                color_jitter=0.2):
    """The data-plane bench: fused native decode+augment vs the Python
    fallback, with per-thread scaling of the native loop. Returns the
    result dict (one JSON line when run as a script)."""
    import mxnet_tpu as mx
    from mxnet_tpu import native

    threads = threads or os.cpu_count() or 4

    def make(n_threads, prefetch=2):
        return mx.io.ImageRecordIter(
            path_imgrec=rec, data_shape=tuple(data_shape),
            batch_size=batch_size, shuffle=True, rand_crop=True,
            rand_mirror=True, color_jitter=color_jitter, seed=7,
            preprocess_threads=n_threads, prefetch_buffer=prefetch)

    with tempfile.TemporaryDirectory() as d:
        rec = build_rec(os.path.join(d, "bench"), num_images, src_size)
        it = make(threads)
        for batch in it:  # warm: native build, page cache, pools
            batch.data[0].wait_to_read()
        native_rate = _time_epochs(it, epochs)
        it.close()

        # per-thread scaling of the fused native loop (sync iterator so
        # the OMP team size is the only variable)
        scaling = {}
        for t in sorted({1, 2, 4, threads}):
            if t > (os.cpu_count() or 1) and t != threads:
                continue
            ts = make(t, prefetch=0)
            for _ in range(2):  # short warm
                ts.next()
            ts.reset()
            scaling[str(t)] = round(_time_epochs(ts, 1), 1)
            ts.close()

        # bit-compatible pure-Python fallback (PIL threads + numpy
        # augmenter) at the same thread count
        orig = native.decode_augment_batch
        native.decode_augment_batch = lambda *a, **k: None
        try:
            itp = make(threads)
            for batch in itp:
                batch.data[0].wait_to_read()
            python_rate = _time_epochs(itp, 1)
            itp.close()
        finally:
            native.decode_augment_batch = orig

        cores = os.cpu_count() or 1
        line = {
            "metric": "iter_bench_augment",
            "value": round(native_rate, 1),
            "unit": "img/s",
            "img_s_per_core": round(native_rate / cores, 1),
            "python_img_s": round(python_rate, 1),
            "speedup_vs_python": round(native_rate / python_rate, 2)
            if python_rate else None,
            "thread_scaling": scaling,
            "scaling_1_to_4": round(scaling["4"] / scaling["1"], 2)
            if "1" in scaling and "4" in scaling else None,
            "native_augment": native.status()["augment"],
            "threads": threads,
            "cores": cores,
            "data_shape": list(data_shape),
        }
        return line


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-images", type=int, default=None)
    p.add_argument("--src-size", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--data-shape", type=str, default="3,224,224")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--preprocess-threads", type=int, default=None)
    p.add_argument("--augment", action="store_true",
                   help="bench the fused native decode+augment loop vs "
                        "the Python fallback, with per-thread scaling")
    args = p.parse_args()

    shape = tuple(int(d) for d in args.data_shape.split(","))
    if args.augment:
        line = run_augment(num_images=args.num_images or 256,
                           src_size=args.src_size,
                           batch_size=args.batch_size or 64,
                           data_shape=shape, epochs=args.epochs or 2,
                           threads=args.preprocess_threads)
    else:
        line = run_plain(num_images=args.num_images or 512,
                         src_size=args.src_size,
                         batch_size=args.batch_size or 128,
                         data_shape=shape, epochs=args.epochs or 3,
                         threads=args.preprocess_threads)
    _persist(line)
    print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
