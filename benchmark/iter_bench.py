#!/usr/bin/env python
"""ImageRecordIter end-to-end throughput benchmark.

Measures the host input pipeline's sustained img/s (RecordIO read ->
native OMP JPEG decode+resize -> augment -> normalize -> batch), the
number that must exceed the chip's training consumption rate for
ResNet-50 (reference bar: iter_image_recordio_2.cc's OMP ParseChunk).

Prints ONE JSON line: {"metric": "image_record_iter", "value": img/s,
"unit": "img/s", ...}.

    python benchmark/iter_bench.py --num-images 512 --batch-size 128
"""
import argparse
import io as _io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_rec(path, num_images, src_hw):
    from PIL import Image

    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    rs = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(num_images):
        arr = rs.randint(0, 255, (src_hw, src_hw, 3), np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=90)
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.getvalue()))
    rec.close()
    return path + ".rec"


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-images", type=int, default=512)
    p.add_argument("--src-size", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--data-shape", type=str, default="3,224,224")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--preprocess-threads", type=int,
                   default=os.cpu_count() or 4)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import native

    shape = tuple(int(d) for d in args.data_shape.split(","))
    with tempfile.TemporaryDirectory() as d:
        rec = build_rec(os.path.join(d, "bench"), args.num_images,
                        args.src_size)
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, data_shape=shape,
            batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True,
            preprocess_threads=args.preprocess_threads)
        # warm epoch (native lib build, file cache)
        for batch in it:
            batch.data[0].wait_to_read()
        n = 0
        t0 = time.perf_counter()
        for _ in range(args.epochs):
            it.reset()
            for batch in it:
                batch.data[0].wait_to_read()
                n += batch.data[0].shape[0]
        dt = time.perf_counter() - t0
        print(json.dumps({
            "metric": "image_record_iter",
            "value": round(n / dt, 1),
            "unit": "img/s",
            "native_decode": native.available(),
            "threads": args.preprocess_threads,
            "data_shape": list(shape),
        }), flush=True)


if __name__ == "__main__":
    main()
