#!/usr/bin/env python
"""opperf: per-operator micro-benchmark harness over the registry.

Parity target: `benchmark/opperf/opperf.py` — run every (or a chosen
subset of) registered operator with default synthetic inputs, time
forward (and backward where differentiable), and emit results as JSON or
a console table.

Usage:
    python benchmark/opperf.py                      # common op set
    python benchmark/opperf.py --ops dot,softmax    # chosen ops
    python benchmark/opperf.py --all                # whole registry
    python benchmark/opperf.py --output-format json
    python benchmark/opperf.py --dispatch           # bulking microbench

Timing methodology matches the reference's profiler-driven runs: warmup
iterations first (includes XLA compile), then `--runs` timed executions
synchronized via wait_to_read (dispatch+device time per call).

`--dispatch` measures per-op eager dispatch overhead (ns/op) on an
elementwise op chain with engine bulking off (bulk_size=1, today's
per-op jit dispatch) vs on (one fused XLA executable per segment) — the
analogue of the reference's MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN A/B.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.ops import registry

# default input builders per op-shape family; (args, kwargs) given a size
_DEFAULT_SIZE = 1024


def _rand(*shape):
    return mx.nd.array(np.random.rand(*shape).astype(np.float32))


def _inputs_for(op_name, n):
    """Best-effort default inputs for an op; None = not benchmarkable
    with generic inputs."""
    special = {
        "dot": ([_rand(n, n), _rand(n, n)], {}),
        "batch_dot": ([_rand(8, n // 8, n // 8), _rand(8, n // 8, n // 8)],
                      {}),
        "FullyConnected": ([_rand(64, n), _rand(256, n), _rand(256)],
                           {"num_hidden": 256}),
        "Convolution": ([_rand(8, 16, 32, 32), _rand(32, 16, 3, 3),
                         _rand(32)],
                        {"kernel": (3, 3), "num_filter": 32,
                         "pad": (1, 1)}),
        "Pooling": ([_rand(8, 16, 32, 32)],
                    {"kernel": (2, 2), "stride": (2, 2),
                     "pool_type": "max"}),
        "BatchNorm": ([_rand(8, 16, 32, 32), _rand(16), _rand(16),
                       _rand(16), _rand(16)], {}),
        "softmax": ([_rand(64, n)], {}),
        "log_softmax": ([_rand(64, n)], {}),
        "sum": ([_rand(n, n)], {}),
        "mean": ([_rand(n, n)], {}),
        "transpose": ([_rand(n, n)], {}),
        "sgd_update": ([_rand(n, n), _rand(n, n)], {"lr": 0.1}),
        "sgd_mom_update": ([_rand(n, n), _rand(n, n), _rand(n, n)],
                           {"lr": 0.1, "momentum": 0.9}),
        "adam_update": ([_rand(n, n), _rand(n, n), _rand(n, n),
                         _rand(n, n)], {"lr": 0.001}),
    }
    if op_name in special:
        return special[op_name]
    # generic synthesis from the op's reflected schema (ops/schema.py —
    # the dmlc::Parameter layer): the schema names the array inputs, so
    # synthesis no longer re-derives them from raw signature inspection
    op = registry.get(op_name)
    schema = op.schema
    if schema.variadic:
        return [_rand(n, n), _rand(n, n)], {}
    arrays = []
    for pname in schema.inputs:
        if pname in ("key", "training"):
            break
        # scalar-tensor hyper inputs (loss-scale etc.), not matrices
        arrays.append(_rand(1) if pname in ("rescale_grad",)
                      else _rand(n, n))
    if not arrays:
        return None
    return arrays, {}


COMMON_OPS = [
    "elemwise_add", "broadcast_add", "broadcast_mul", "dot", "batch_dot",
    "FullyConnected", "Convolution", "Pooling", "BatchNorm", "softmax",
    "log_softmax", "relu", "sigmoid", "exp", "log", "sum", "mean",
    "transpose", "sgd_update", "sgd_mom_update", "adam_update",
]


def bench_op(op_name, size, runs, warmup, with_backward=True):
    built = _inputs_for(op_name, size)
    if built is None:
        return None
    arrays, kwargs = built
    op = registry.get(op_name)

    def run_fwd():
        out = mx.nd.invoke(op_name, *arrays, **kwargs)
        (out[0] if isinstance(out, tuple) else out).wait_to_read()
        return out

    try:
        for _ in range(warmup):
            run_fwd()
    except Exception as exc:  # op not benchmarkable with generic inputs
        return {"operator": op_name, "error": str(exc)[:80]}
    t0 = time.perf_counter()
    for _ in range(runs):
        run_fwd()
    fwd_ms = (time.perf_counter() - t0) / runs * 1e3

    bwd_ms = None
    if with_backward and op.differentiable:
        try:
            for a in arrays:
                a.attach_grad()
            with mx.autograd.record():
                out = mx.nd.invoke(op_name, *arrays, **kwargs)
                head = out[0] if isinstance(out, tuple) else out
            head.backward()
            t0 = time.perf_counter()
            for _ in range(runs):
                with mx.autograd.record():
                    out = mx.nd.invoke(op_name, *arrays, **kwargs)
                    head = out[0] if isinstance(out, tuple) else out
                head.backward()
                arrays[0].grad.wait_to_read()
            bwd_ms = (time.perf_counter() - t0) / runs * 1e3
        except Exception:
            bwd_ms = None
    entry = {"operator": op_name, "avg_fwd_ms": round(fwd_ms, 4)}
    if bwd_ms is not None:
        entry["avg_fwd_bwd_ms"] = round(bwd_ms, 4)
    return entry


def bench_dispatch(chain_len=16, bulk=16, size=_DEFAULT_SIZE, iters=250,
                   warmup=40, trials=5):
    """Per-op eager dispatch time for a `chain_len`-op elementwise chain,
    bulk_size=1 (per-op executables) vs bulk_size=`bulk` (one fused
    executable per segment). Each chain ends in wait_to_read, so the
    bulked side pays its segment flush inside the timed region; median
    over `trials` interleaved runs defends against scheduler noise."""
    import statistics

    x0 = _rand(size)

    def chain():
        x = x0
        for _ in range(chain_len // 2):
            x = x * 1.0001
            x = x + 0.0001
        x.wait_to_read()

    samples = {1: [], bulk: []}
    for _ in range(trials):
        for bs in (1, bulk):
            with mx.engine.bulk(bs):
                for _ in range(warmup):
                    chain()
                t0 = time.perf_counter()
                for _ in range(iters):
                    chain()
                dt = time.perf_counter() - t0
            samples[bs].append(dt / (iters * chain_len) * 1e9)
    unbulked = statistics.median(samples[1])
    bulked = statistics.median(samples[bulk])
    return {
        "chain_len": chain_len,
        "bulk_size": bulk,
        "tensor_size": size,
        "unbulked_ns_per_op": round(unbulked, 1),
        "bulked_ns_per_op": round(bulked, 1),
        "improvement_pct": round((unbulked - bulked) / unbulked * 100, 1),
    }


def _kernel_cases():
    """(family, builder) shape cases for the kernel autotuner. Builders
    return (args, kwargs) concrete enough to jit both sides; each case
    lands in ONE dispatch-table bucket."""
    import jax.numpy as jnp

    r = np.random.default_rng(0)

    def f32(*shape):
        return jnp.asarray(r.standard_normal(shape, dtype=np.float32))

    # NB: static scalars (scale, thr) ride in kwargs so the jit wrapper
    # below only traces the array positions — they bake into the kernel

    def flash():
        q, k, v = f32(1, 2, 128, 64), f32(1, 2, 128, 64), f32(1, 2, 128, 64)
        return (q, k, v), {"scale": 0.125, "causal": True}

    def opt_sgd():
        n = 65536
        return (f32(n), f32(n), f32(n), jnp.float32(0.05)), \
            {"momentum": 0.9, "wd": 1e-4}

    def opt_adam():
        n = 65536
        return (f32(n), f32(n), f32(n), f32(n), jnp.float32(1e-3)), \
            {"wd": 1e-4}

    def int8_gemm():
        qx = jnp.asarray(r.integers(-127, 128, (128, 256)), dtype=jnp.int8)
        w = jnp.asarray(r.integers(-127, 128, (256, 256)), dtype=jnp.int8)
        sc = jnp.asarray(r.random(256), dtype=jnp.float32) * 0.01
        return (qx, w, sc), {"bias": f32(256), "relu": True}

    def decode():
        q, k, v = f32(2, 2, 64), f32(2, 2, 256, 64), f32(2, 2, 256, 64)
        lens = jnp.asarray([256, 100], dtype=jnp.int32)
        return (q, k, v, lens), {"scale": 0.125}

    def twobit_c():
        n = 65536
        return (f32(n), f32(n) * 0.1), {"thr": 0.5}

    def twobit_d():
        codes = jnp.asarray(r.integers(-4, 5, 65536), dtype=jnp.int8)
        return (codes,), {"thr": 0.5}

    return [("flash_attention", flash), ("opt_sgd", opt_sgd),
            ("opt_adam", opt_adam), ("int8_gemm", int8_gemm),
            ("decode_attention", decode), ("twobit_compress", twobit_c),
            ("twobit_decompress", twobit_d)]


def _time_jitted(fn, args, runs, warmup):
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(runs):
        jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / runs * 1e3


def bench_kernels(runs=10, warmup=3, families=None):
    """The kernel autotuner: time each registry family's Pallas kernel
    against its XLA baseline per shape bucket, record the winner in the
    persisted dispatch table (mxnet_tpu/kernels/table.py). Off-TPU the
    kernel side runs in the Pallas interpreter — rows are stamped
    ``interpret: true`` and honestly lose to XLA (the table then routes
    dispatch to XLA, which IS the tuned decision for this backend)."""
    import jax
    from mxnet_tpu import kernels as klayer
    from mxnet_tpu.kernels import table as ktable

    interp = not klayer.on_tpu()
    t_start = time.time()
    results = []
    for fam, build in _kernel_cases():
        if families and fam not in families:
            continue
        args, kwargs = build()
        e = klayer.entry(fam)
        if not e.supports(*args, **kwargs):
            continue
        bucket = e.bucket(*args, **kwargs)
        kfn = jax.jit(
            lambda *a, _e=e, _kw=kwargs: _e.kernel(*a, interpret=interp,
                                                   **_kw))
        xfn = jax.jit(lambda *a, _e=e, _kw=kwargs: _e.xla(*a, **_kw))
        try:
            k_ms = _time_jitted(kfn, args, runs, warmup)
        except Exception as exc:  # kernel unbuildable here: XLA wins
            row = ktable.record(fam, bucket, "xla", None, None,
                                interpret=interp)
            results.append({"family": fam, "bucket": bucket,
                            "error": str(exc)[:80], **row})
            continue
        x_ms = _time_jitted(xfn, args, runs, warmup)
        winner = "kernel" if k_ms < x_ms else "xla"
        row = ktable.record(fam, bucket, winner, k_ms, x_ms,
                            interpret=interp)
        results.append({"family": fam, "bucket": bucket, **row})
    stamp = {"when": time.time(), "duration_s": round(
        time.time() - t_start, 2), "runs": runs, "interpret": interp,
        "cases": len(results),
        "argv": " ".join(sys.argv[1:]) or "--kernels"}
    ktable.set_opperf_stamp(stamp)
    path = ktable.save()
    return {"table_path": path, "stamp": stamp, "results": results}


def run_benchmark(ops, size=_DEFAULT_SIZE, runs=10, warmup=2):
    results = []
    for name in ops:
        res = bench_op(name, size, runs, warmup)
        if res is not None:
            results.append(res)
    return results


def main():
    parser = argparse.ArgumentParser(description="op micro-benchmarks")
    parser.add_argument("--ops", type=str, default="",
                        help="comma-separated op names (default: common set)")
    parser.add_argument("--all", action="store_true",
                        help="benchmark every registered op")
    parser.add_argument("--size", type=int, default=_DEFAULT_SIZE)
    parser.add_argument("--runs", type=int, default=10)
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--output-format", type=str, default="table",
                        choices=("table", "json"))
    parser.add_argument("--dispatch", action="store_true",
                        help="run the engine-bulking dispatch-overhead "
                             "microbench instead of per-op timings")
    parser.add_argument("--kernels", action="store_true",
                        help="autotune the Pallas kernel layer: time "
                             "kernel vs XLA per (family, shape bucket) "
                             "and persist the winner dispatch table")
    parser.add_argument("--families", type=str, default="",
                        help="comma-separated kernel families for "
                             "--kernels (default: all registered)")
    parser.add_argument("--chain", type=int, default=16,
                        help="op-chain length for --dispatch")
    parser.add_argument("--bulk", type=int, default=16,
                        help="bulk_size for the bulked side of --dispatch")
    args = parser.parse_args()

    if args.kernels:
        fams = [f for f in args.families.split(",") if f] or None
        res = bench_kernels(runs=args.runs, warmup=args.warmup,
                            families=fams)
        if args.output_format == "json":
            print(json.dumps(res, indent=2))
        else:
            where = res["table_path"] or "(memory only — set " \
                "MXNET_TPU_CACHE_DIR to persist)"
            print(f"kernel dispatch table -> {where}")
            print(f"{'Family':<20s} {'Bucket':<34s} {'Kernel ms':>10s} "
                  f"{'XLA ms':>9s} {'Speedup':>8s} {'Winner':>7s}")
            for r in res["results"]:
                k = r.get("kernel_ms")
                x = r.get("xla_ms")
                sp = r.get("speedup")
                tag = r["winner"] + ("*" if r.get("interpret") else "")
                print(f"{r['family']:<20s} {r['bucket']:<34s} "
                      f"{k if k is not None else '-':>10} "
                      f"{x if x is not None else '-':>9} "
                      f"{sp if sp is not None else '-':>8} {tag:>7s}")
            if any(r.get("interpret") for r in res["results"]):
                print("* kernel timed in the Pallas INTERPRETER (no TPU "
                      "here) — not a hardware speed claim")
        return

    if args.dispatch:
        res = bench_dispatch(chain_len=args.chain, bulk=args.bulk,
                             size=args.size)
        if args.output_format == "json":
            print(json.dumps(res, indent=2))
        else:
            print(f"{args.chain}-op elementwise chain, tensor size "
                  f"{args.size}, CPU backend")
            print(f"  bulk_size=1           : "
                  f"{res['unbulked_ns_per_op']:>10.1f} ns/op")
            print(f"  bulk_size={args.bulk:<12d}: "
                  f"{res['bulked_ns_per_op']:>10.1f} ns/op")
            print(f"  dispatch improvement  : "
                  f"{res['improvement_pct']:>10.1f} %")
        return

    if args.ops:
        ops = args.ops.split(",")
    elif args.all:
        ops = registry.list_ops()
    else:
        ops = COMMON_OPS
    results = run_benchmark(ops, args.size, args.runs, args.warmup)
    if args.output_format == "json":
        print(json.dumps(results, indent=2))
    else:
        print(f"{'Operator':<32s} {'Fwd (ms)':>10s} {'Fwd+Bwd (ms)':>14s}")
        for r in results:
            if "error" in r:
                print(f"{r['operator']:<32s} {'SKIP: ' + r['error']}")
            else:
                bwd = r.get("avg_fwd_bwd_ms")
                print(f"{r['operator']:<32s} {r['avg_fwd_ms']:>10.4f} "
                      f"{bwd if bwd is not None else '-':>14}")


if __name__ == "__main__":
    main()
