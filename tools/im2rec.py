#!/usr/bin/env python
"""im2rec: build .lst / .rec image databases from an image folder.

Parity target: `tools/im2rec.py` — `--list` mode walks a directory tree
producing `prefix.lst` (index \\t label \\t relpath), optionally split by
--train-ratio/--test-ratio; record mode packs each listed image into an
IndexedRecordIO `.rec`/`.idx` pair via `recordio.pack_img`.

The reference parallelizes JPEG encoding over worker processes + OpenCV;
here PIL (when available) or raw passthrough does the encode — the
output format is byte-compatible with the reference's RecordIO readers.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402


def list_image(root, recursive, exts):
    """parity: im2rec.py:38 — yield (index, relpath, label)."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and (suffix in exts):
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and (suffix in exts):
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    """parity: im2rec.py:75."""
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def make_list(args):
    """parity: im2rec.py:93 — write train/val/test .lst splits."""
    image_list = list(list_image(args.root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    n = len(image_list)
    chunk_size = (n + args.chunks - 1) // args.chunks
    for i in range(args.chunks):
        chunk = image_list[i * chunk_size:(i + 1) * chunk_size]
        str_chunk = "_%dof%d" % (i, args.chunks) if args.chunks > 1 else ""
        sep = int(chunk_size * args.train_ratio)
        sep_test = int(chunk_size * args.test_ratio)
        if args.train_ratio == 1.0:
            write_list(args.prefix + str_chunk + ".lst", chunk)
        else:
            if args.test_ratio:
                write_list(args.prefix + str_chunk + "_test.lst",
                           chunk[:sep_test])
            if args.train_ratio + args.test_ratio < 1.0:
                write_list(args.prefix + str_chunk + "_val.lst",
                           chunk[sep_test + sep:])
            write_list(args.prefix + str_chunk + "_train.lst",
                       chunk[sep_test:sep_test + sep])


def read_list(path_in):
    """parity: im2rec.py:123."""
    with open(path_in) as fin:
        while True:
            line = fin.readline()
            if not line:
                break
            line = [i.strip() for i in line.strip().split("\t")]
            line_len = len(line)
            if line_len < 3:
                continue
            try:
                item = [int(line[0])] + [line[-1]] + \
                    [float(i) for i in line[1:-1]]
            except ValueError:
                continue
            yield item


def _encode_image(args, fullpath):
    """Read + optionally resize/crop/re-encode one image; returns bytes."""
    if args.pass_through:
        with open(fullpath, "rb") as f:
            return f.read()
    try:
        from PIL import Image
    except ImportError:
        with open(fullpath, "rb") as f:
            return f.read()  # no PIL: pass bytes through
    import io

    img = Image.open(fullpath)
    if args.center_crop:
        w, h = img.size
        s = min(w, h)
        img = img.crop(((w - s) // 2, (h - s) // 2,
                        (w + s) // 2, (h + s) // 2))
    if args.resize:
        w, h = img.size
        if w > h:
            img = img.resize((int(w * args.resize / h), args.resize))
        else:
            img = img.resize((args.resize, int(h * args.resize / w)))
    buf = io.BytesIO()
    fmt = "JPEG" if args.encoding == ".jpg" else "PNG"
    img.convert("RGB").save(buf, format=fmt, quality=args.quality)
    return buf.getvalue()


def make_record(args, lst_path):
    """Pack one .lst into .rec/.idx (parity: im2rec.py read/write workers,
    sequentially)."""
    base = os.path.splitext(lst_path)[0]
    record = recordio.MXIndexedRecordIO(base + ".idx", base + ".rec", "w")
    count = 0
    for item in read_list(lst_path):
        idx, relpath, labels = item[0], item[1], item[2:]
        fullpath = os.path.join(args.root, relpath)
        label = labels[0] if len(labels) == 1 and not args.pack_label \
            else labels
        header = recordio.IRHeader(0, label, idx, 0)
        try:
            payload = _encode_image(args, fullpath)
        except Exception as exc:
            print("imread error trying to load file: %s (%s)"
                  % (fullpath, exc))
            continue
        record.write_idx(idx, recordio.pack(header, payload))
        count += 1
        if count % 1000 == 0:
            print("processed", count, "images")
    record.close()
    print("wrote %d records to %s.rec" % (count, base))


def parse_args():
    parser = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
        description="Create an image list or RecordIO database")
    parser.add_argument("prefix",
                        help="prefix of input/output lst and rec files")
    parser.add_argument("root", help="path to folder containing images")
    cgroup = parser.add_argument_group("Options for creating image lists")
    cgroup.add_argument("--list", action="store_true",
                        help="make a list instead of a record database")
    cgroup.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    cgroup.add_argument("--chunks", type=int, default=1)
    cgroup.add_argument("--train-ratio", type=float, default=1.0)
    cgroup.add_argument("--test-ratio", type=float, default=0)
    cgroup.add_argument("--recursive", action="store_true",
                        help="label by subdirectory")
    cgroup.add_argument("--no-shuffle", dest="shuffle",
                        action="store_false")
    rgroup = parser.add_argument_group("Options for creating database")
    rgroup.add_argument("--pass-through", action="store_true",
                        help="skip transcoding, pack raw bytes")
    rgroup.add_argument("--resize", type=int, default=0)
    rgroup.add_argument("--center-crop", action="store_true")
    rgroup.add_argument("--quality", type=int, default=95)
    rgroup.add_argument("--encoding", type=str, default=".jpg",
                        choices=[".jpg", ".png"])
    rgroup.add_argument("--pack-label", action="store_true",
                        help="pack multi-dimensional labels")
    return parser.parse_args()


def main():
    args = parse_args()
    if args.list:
        make_list(args)
        return
    working_dir = os.path.dirname(args.prefix) or "."
    files = [os.path.join(working_dir, f)
             for f in sorted(os.listdir(working_dir))]
    count = 0
    for f in files:
        if f.startswith(args.prefix) and f.endswith(".lst"):
            count += 1
            make_record(args, f)
    if not count:
        print("did not find and process any .lst files with prefix "
              f"{args.prefix!r}; run with --list first")


if __name__ == "__main__":
    main()
