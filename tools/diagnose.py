#!/usr/bin/env python
"""Diagnose the runtime environment (parity: tools/diagnose.py — platform,
package versions, hardware, environment variables; the script users attach
to bug reports).

    python tools/diagnose.py
"""
import importlib
import os
import platform
import sys
import time


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def check_pip():
    print("------------Pip Info-----------")
    try:
        import pip

        print("Version      :", pip.__version__)
    except ImportError:
        print("No corresponding pip install for current python.")


def check_framework():
    print("---------Framework Info--------")
    try:
        import mxnet_tpu as mx

        print("Version      :", mx.__version__)
        print("Directory    :", os.path.dirname(mx.__file__))
        from mxnet_tpu import runtime

        feats = runtime.Features()
        on = [name for name in feats.keys() if feats.is_enabled(name)]
        print("Features     :", ", ".join(sorted(on)))
    except ImportError as e:
        print("framework import failed:", e)


def check_deps():
    print("--------Dependency Info--------")
    for name in ("jax", "jaxlib", "numpy", "flax", "optax"):
        try:
            mod = importlib.import_module(name)
            print(f"{name:<13}:", getattr(mod, "__version__", "unknown"))
        except ImportError:
            print(f"{name:<13}: not installed")


def check_hardware():
    print("---------Hardware Info---------")
    print("Machine      :", platform.machine())
    print("Platform     :", platform.platform())
    try:
        import jax

        t0 = time.time()
        devices = jax.devices()
        print("Devices      :", devices, f"(probe {time.time() - t0:.2f}s)")
        print("Processes    :", jax.process_count())
    except Exception as e:  # tunnel down, etc.
        print("Device probe failed:", e)


def check_environment():
    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXNET_", "MXTPU_", "JAX_", "XLA_", "TPU_",
                         "DMLC_", "OMP_", "LD_", "PYTHON")):
            print(f"{k}={v}")


def main():
    check_python()
    check_pip()
    check_framework()
    check_deps()
    check_hardware()
    check_environment()


if __name__ == "__main__":
    main()
